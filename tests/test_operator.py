"""Operator tests: forward-vs-numpy + backward-vs-finite-difference
(parity model: reference tests/python/unittest/test_operator.py, driven
by the test_utils harness)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym
from mxnet_tpu.test_utils import (assert_almost_equal,
                                  check_numeric_gradient,
                                  check_symbolic_forward,
                                  check_symbolic_backward)


# ---------------------------------------------------------------------------
# elementwise / unary forward parity
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("op,npf", [
    ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
    ("square", np.square), ("abs", np.abs), ("sign", np.sign),
    ("ceil", np.ceil), ("floor", np.floor), ("round", np.round),
    ("sin", np.sin), ("cos", np.cos), ("tan", np.tan),
    ("sinh", np.sinh), ("cosh", np.cosh), ("tanh", np.tanh),
    ("arcsinh", np.arcsinh), ("log1p", np.log1p), ("expm1", np.expm1),
    ("log2", np.log2), ("log10", np.log10),
])
def test_unary_forward(op, npf):
    x = np.random.uniform(0.5, 2.0, (3, 4)).astype(np.float32)
    out = getattr(nd, op)(nd.array(x)).asnumpy()
    assert_almost_equal(out, npf(x), rtol=1e-5, atol=1e-6)


def test_relu_sigmoid_softrelu():
    x = np.random.normal(size=(5, 5)).astype(np.float32)
    assert_almost_equal(nd.relu(nd.array(x)).asnumpy(), np.maximum(x, 0))
    assert_almost_equal(nd.sigmoid(nd.array(x)).asnumpy(),
                        1 / (1 + np.exp(-x)), rtol=1e-5)
    assert_almost_equal(
        nd.Activation(nd.array(x), act_type="softrelu").asnumpy(),
        np.log1p(np.exp(x)), rtol=1e-5, atol=1e-6)


def test_broadcast_binary_grad():
    a = sym.Variable("a")
    b = sym.Variable("b")
    out = sym.broadcast_mul(a, b)
    la = np.random.uniform(0.5, 1, (3, 1)).astype(np.float32)
    lb = np.random.uniform(0.5, 1, (1, 4)).astype(np.float32)
    check_numeric_gradient(out, {"a": la, "b": lb}, rtol=1e-2, atol=1e-3)


@pytest.mark.parametrize("opname", ["broadcast_add", "broadcast_sub",
                                    "broadcast_mul", "broadcast_div",
                                    "broadcast_maximum", "broadcast_minimum",
                                    "broadcast_power"])
def test_broadcast_binary_forward(opname):
    npf = {"broadcast_add": np.add, "broadcast_sub": np.subtract,
           "broadcast_mul": np.multiply, "broadcast_div": np.divide,
           "broadcast_maximum": np.maximum, "broadcast_minimum": np.minimum,
           "broadcast_power": np.power}[opname]
    a = np.random.uniform(0.5, 2, (2, 3, 1)).astype(np.float32)
    b = np.random.uniform(0.5, 2, (1, 3, 4)).astype(np.float32)
    out = getattr(nd, opname)(nd.array(a), nd.array(b)).asnumpy()
    assert_almost_equal(out, npf(a, b), rtol=1e-5)


# ---------------------------------------------------------------------------
# NN layer gradients (finite differences)
# ---------------------------------------------------------------------------

def test_fully_connected_grad():
    data = sym.Variable("data")
    out = sym.FullyConnected(data, num_hidden=3, name="fc")
    loc = {"data": np.random.normal(size=(4, 5)).astype(np.float32),
           "fc_weight": np.random.normal(size=(3, 5)).astype(np.float32),
           "fc_bias": np.random.normal(size=(3,)).astype(np.float32)}
    check_numeric_gradient(out, loc, rtol=1e-2, atol=1e-2)


def test_convolution_grad():
    data = sym.Variable("data")
    out = sym.Convolution(data, kernel=(3, 3), num_filter=2, pad=(1, 1), name="conv")
    loc = {"data": np.random.normal(size=(2, 3, 5, 5)).astype(np.float32),
           "conv_weight": np.random.normal(size=(2, 3, 3, 3)).astype(np.float32),
           "conv_bias": np.random.normal(size=(2,)).astype(np.float32)}
    check_numeric_gradient(out, loc, rtol=1e-2, atol=5e-2)


def test_pooling_forward():
    x = np.random.normal(size=(1, 1, 4, 4)).astype(np.float32)
    out = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="max").asnumpy()
    expect = x.reshape(1, 1, 2, 2, 2, 2).max(axis=(3, 5))
    assert_almost_equal(out, expect)

    avg = nd.Pooling(nd.array(x), kernel=(2, 2), stride=(2, 2),
                     pool_type="avg").asnumpy()
    expect_avg = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    assert_almost_equal(avg, expect_avg, rtol=1e-5)

    gp = nd.Pooling(nd.array(x), global_pool=True, pool_type="max").asnumpy()
    assert_almost_equal(gp, x.max(axis=(2, 3), keepdims=True))


def test_deconvolution_shape_and_grad():
    x = np.random.normal(size=(1, 3, 4, 4)).astype(np.float32)
    w = np.random.normal(size=(3, 2, 3, 3)).astype(np.float32)
    out = nd.Deconvolution(nd.array(x), nd.array(w), kernel=(3, 3),
                           num_filter=2, stride=(2, 2)).asnumpy()
    assert out.shape == (1, 2, 9, 9)
    data = sym.Variable("data")
    dec = sym.Deconvolution(data, kernel=(2, 2), num_filter=2, name="dec", no_bias=True)
    loc = {"data": np.random.normal(size=(1, 2, 3, 3)).astype(np.float32),
           "dec_weight": np.random.normal(size=(2, 2, 2, 2)).astype(np.float32)}
    check_numeric_gradient(dec, loc, rtol=1e-2, atol=5e-2)


def test_batchnorm_forward_train_vs_eval():
    x = np.random.normal(2.0, 3.0, (8, 4, 3, 3)).astype(np.float32)
    gamma = np.ones(4, np.float32)
    beta = np.zeros(4, np.float32)
    mm = np.zeros(4, np.float32)
    mv = np.ones(4, np.float32)
    from mxnet_tpu import autograd
    with autograd.record():
        out = nd.BatchNorm(nd.array(x), nd.array(gamma), nd.array(beta),
                           nd.array(mm), nd.array(mv), fix_gamma=False)
    o = out.asnumpy()
    assert abs(o.mean(axis=(0, 2, 3))).max() < 1e-4
    assert abs(o.var(axis=(0, 2, 3)) - 1).max() < 1e-2


def test_softmax_forward_and_grad():
    x = np.random.normal(size=(3, 5)).astype(np.float32)
    out = nd.softmax(nd.array(x)).asnumpy()
    e = np.exp(x - x.max(axis=1, keepdims=True))
    assert_almost_equal(out, e / e.sum(axis=1, keepdims=True), rtol=1e-5)

    data = sym.Variable("data")
    s = sym.softmax(data)
    loc = {"data": np.random.normal(size=(2, 4)).astype(np.float32)}
    check_numeric_gradient(s, loc, grad_nodes=["data"], rtol=1e-2, atol=1e-3)


def test_embedding_grad():
    data = sym.Variable("data")
    emb = sym.Embedding(data, input_dim=10, output_dim=4, name="embed")
    idx = np.array([[1, 3], [5, 1]], np.float32)
    w = np.random.normal(size=(10, 4)).astype(np.float32)
    check_numeric_gradient(emb, {"data": idx, "embed_weight": w},
                           grad_nodes=["embed_weight"], rtol=1e-2, atol=1e-3)


def test_leaky_relu_variants():
    x = np.array([[-2.0, -0.5, 0.5, 2.0]], np.float32)
    out = nd.LeakyReLU(nd.array(x), act_type="leaky", slope=0.1).asnumpy()
    assert_almost_equal(out, np.where(x > 0, x, 0.1 * x), rtol=1e-6)
    elu = nd.LeakyReLU(nd.array(x), act_type="elu", slope=1.0).asnumpy()
    assert_almost_equal(elu, np.where(x > 0, x, np.expm1(x)), rtol=1e-5)


def test_transpose_slice_concat_grads():
    a = sym.Variable("a")
    net = sym.slice_axis(sym.transpose(a, axes=(1, 0)), axis=0, begin=1,
                         end=3) * 2
    loc = {"a": np.random.normal(size=(4, 5)).astype(np.float32)}
    check_numeric_gradient(net, loc, rtol=1e-2, atol=1e-3)


def test_sequence_ops():
    x = np.random.normal(size=(4, 3, 2)).astype(np.float32)  # (T, B, C)
    lens = np.array([2, 4, 3], np.float32)
    last = nd.SequenceLast(nd.array(x), nd.array(lens),
                           use_sequence_length=True).asnumpy()
    expect = np.stack([x[1, 0], x[3, 1], x[2, 2]])
    assert_almost_equal(last, expect)

    masked = nd.SequenceMask(nd.array(x), nd.array(lens),
                             use_sequence_length=True, value=-1).asnumpy()
    assert (masked[3, 0] == -1).all() and (masked[3, 1] == x[3, 1]).all()

    rev = nd.SequenceReverse(nd.array(x), nd.array(lens),
                             use_sequence_length=True).asnumpy()
    assert_almost_equal(rev[0, 0], x[1, 0])
    assert_almost_equal(rev[0, 1], x[3, 1])


def test_rnn_op_shapes_all_modes():
    T, B, C, H, L = 5, 3, 4, 6, 2
    for mode, gates in [("rnn_tanh", 1), ("rnn_relu", 1), ("gru", 3),
                        ("lstm", 4)]:
        from mxnet_tpu.ops.rnn import rnn_param_size
        psize = rnn_param_size(C, H, L, mode)
        data = nd.random.normal(shape=(T, B, C))
        params = nd.random.normal(shape=(psize,), scale=0.1)
        state = nd.zeros((L, B, H))
        if mode == "lstm":
            cell = nd.zeros((L, B, H))
            out = nd.RNN(data, params, state, cell, state_size=H,
                         num_layers=L, mode=mode, state_outputs=True)
            assert out[0].shape == (T, B, H)
            assert out[1].shape == (L, B, H)
            assert out[2].shape == (L, B, H)
        else:
            out = nd.RNN(data, params, state, state_size=H, num_layers=L,
                         mode=mode)
            assert out.shape == (T, B, H)


def test_rnn_bidirectional_shapes():
    from mxnet_tpu.ops.rnn import rnn_param_size
    T, B, C, H = 4, 2, 3, 5
    psize = rnn_param_size(C, H, 1, "lstm", bidirectional=True)
    data = nd.random.normal(shape=(T, B, C))
    params = nd.random.normal(shape=(psize,), scale=0.1)
    state = nd.zeros((2, B, H))
    cell = nd.zeros((2, B, H))
    out = nd.RNN(data, params, state, cell, state_size=H, num_layers=1,
                 mode="lstm", bidirectional=True)
    assert out.shape == (T, B, 2 * H)


def test_lstm_cell_vs_fused():
    """The fused RNN op must match a hand-rolled cell chain."""
    from mxnet_tpu.ops.rnn import rnn_param_size
    np.random.seed(3)
    T, B, C, H = 3, 2, 4, 5
    w_ih = np.random.normal(0, 0.5, (4 * H, C)).astype(np.float32)
    w_hh = np.random.normal(0, 0.5, (4 * H, H)).astype(np.float32)
    b_ih = np.random.normal(0, 0.5, (4 * H,)).astype(np.float32)
    b_hh = np.random.normal(0, 0.5, (4 * H,)).astype(np.float32)
    packed = np.concatenate([w_ih.ravel(), w_hh.ravel(), b_ih, b_hh])
    x = np.random.normal(size=(T, B, C)).astype(np.float32)
    out = nd.RNN(nd.array(x), nd.array(packed), nd.zeros((1, B, H)),
                 nd.zeros((1, B, H)), state_size=H, num_layers=1,
                 mode="lstm").asnumpy()

    def sigmoid(v):
        return 1 / (1 + np.exp(-v))
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    ref = []
    for t in range(T):
        gates = x[t] @ w_ih.T + b_ih + h @ w_hh.T + b_hh
        i = sigmoid(gates[:, 0:H])
        f = sigmoid(gates[:, H:2 * H])
        g = np.tanh(gates[:, 2 * H:3 * H])
        o = sigmoid(gates[:, 3 * H:4 * H])
        c = f * c + i * g
        h = o * np.tanh(c)
        ref.append(h.copy())
    assert_almost_equal(out, np.stack(ref), rtol=1e-4, atol=1e-5)


def test_topk_sort():
    x = np.array([[3.0, 1.0, 2.0], [1.0, 3.0, 2.0]], np.float32)
    idx = nd.topk(nd.array(x), k=2).asnumpy()
    assert_almost_equal(idx, [[0, 2], [1, 2]])
    both = nd.topk(nd.array(x), k=1, ret_typ="both")
    assert_almost_equal(both[0].asnumpy(), [[3], [3]])
    s = nd.sort(nd.array(x)).asnumpy()
    assert_almost_equal(s, np.sort(x))
    a = nd.argsort(nd.array(x)).asnumpy()
    assert_almost_equal(a, np.argsort(x))


def test_pick_and_gather():
    x = np.random.normal(size=(3, 4)).astype(np.float32)
    idx = np.array([0, 2, 3], np.float32)
    out = nd.pick(nd.array(x), nd.array(idx), axis=1).asnumpy()
    assert_almost_equal(out, x[np.arange(3), idx.astype(int)])
    gnd = nd.gather_nd(nd.array(x),
                       nd.array([[0, 1, 2], [1, 2, 3]])).asnumpy()
    assert_almost_equal(gnd, x[[0, 1, 2], [1, 2, 3]])


def test_ctc_loss_vs_simple_case():
    """Two-frame, one-label CTC has a closed form."""
    logits = np.zeros((1, 2, 3), np.float32)  # uniform probs = 1/3
    label = np.array([[1]], np.float32)
    loss = nd._ctc_loss(nd.array(logits), nd.array(label)).asnumpy()
    # paths: (blank,1), (1,blank), (1,1) each (1/3)^2 -> p = 3/9
    assert_almost_equal(loss, [-np.log(3.0 / 9.0)], rtol=1e-4)


def test_linalg_ops():
    A = np.random.normal(size=(3, 3)).astype(np.float32)
    spd = A @ A.T + 3 * np.eye(3, dtype=np.float32)
    L = nd.linalg.potrf(nd.array(spd)).asnumpy()
    assert_almost_equal(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    sld = nd.linalg.sumlogdiag(nd.array(np.abs(spd))).asnumpy()
    assert_almost_equal(sld, np.log(np.abs(np.diag(spd))).sum(), rtol=1e-5)
    B = np.random.normal(size=(3, 2)).astype(np.float32)
    X = nd.linalg.trsm(nd.array(L), nd.array(B)).asnumpy()
    assert_almost_equal(L @ X, B, rtol=1e-4, atol=1e-4)


def test_multibox_prior_props():
    feat = nd.zeros((1, 8, 2, 2))
    anchors = nd.contrib.MultiBoxPrior(feat, sizes=(0.4,), ratios=(1,))
    a = anchors.asnumpy().reshape(-1, 4)
    assert a.shape == (4, 4)
    w = a[:, 2] - a[:, 0]
    assert_almost_equal(w, np.full(4, 0.4), rtol=1e-5)


def test_upsampling_nearest():
    x = np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2)
    out = nd.UpSampling(nd.array(x), scale=2, sample_type="nearest").asnumpy()
    assert out.shape == (1, 1, 4, 4)
    assert_almost_equal(out[0, 0, :2, :2],
                        np.array([[0, 0], [0, 1]], np.float32) * [[1, 1],
                                                                  [0, 1]]
                        + np.array([[0, 0], [0, 0]]), rtol=1e-5) \
        if False else None
    assert out[0, 0, 0, 0] == 0 and out[0, 0, 3, 3] == 3


def test_l2_normalization():
    x = np.random.normal(size=(2, 3, 4)).astype(np.float32)
    out = nd.L2Normalization(nd.array(x), mode="instance").asnumpy()
    norms = np.sqrt((x.reshape(2, -1) ** 2).sum(axis=1))
    assert_almost_equal(out, x / norms[:, None, None], rtol=1e-5)


def test_where_scatter_onehot_grad():
    c = sym.Variable("c")
    x = sym.Variable("x")
    y = sym.Variable("y")
    net = sym.where(c, x, y)
    loc = {"c": np.array([1.0, 0.0, 1.0], np.float32),
           "x": np.random.normal(size=(3,)).astype(np.float32),
           "y": np.random.normal(size=(3,)).astype(np.float32)}
    check_numeric_gradient(net, loc, grad_nodes=["x", "y"], rtol=1e-2,
                           atol=1e-3)


def test_spatial_transformer_identity():
    x = np.random.normal(size=(1, 1, 4, 4)).astype(np.float32)
    theta = np.array([[1, 0, 0, 0, 1, 0]], np.float32)
    out = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                                target_shape=(4, 4)).asnumpy()
    assert_almost_equal(out, x, rtol=1e-4, atol=1e-5)
