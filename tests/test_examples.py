"""Examples smoke tests — each example runs end-to-end in a subprocess
(mirrors the reference's nightly test_tutorial.py approach of executing
the shipped example scripts)."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import run_all  # noqa: E402


def _run(rel, extra):
    proc = run_all.run_one(rel, extra)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_train_mnist_example():
    out = _run("image-classification/train_mnist.py",
               ["--synthetic", "--num-epochs", "2", "--network", "mlp"])
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.9


def test_model_parallel_example():
    out = _run("model-parallel/lstm_stages.py", ["--num-stages", "4"])
    assert "PartitionSpec('stage',)" in out


def test_ssd_example():
    out = _run("ssd/train_ssd.py", ["--iters", "2", "--batch-size", "2"])
    assert "detection output" in out


@pytest.mark.slow
def test_all_examples():
    """Full sweep; run explicitly with -m slow (CI nightly analogue)."""
    failures = []
    for rel, extra in run_all.EXAMPLES:
        proc = run_all.run_one(rel, extra)
        if proc.returncode != 0:
            failures.append((rel, proc.stderr[-500:]))
    assert not failures, failures
