"""Examples smoke tests — each example runs end-to-end in a subprocess
(mirrors the reference's nightly test_tutorial.py approach of executing
the shipped example scripts)."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "examples"))
import run_all  # noqa: E402


def _run(rel, extra):
    proc = run_all.run_one(rel, extra)
    assert proc.returncode == 0, proc.stderr[-2000:]
    return proc.stdout


def test_train_mnist_example():
    out = _run("image-classification/train_mnist.py",
               ["--synthetic", "--num-epochs", "2", "--network", "mlp"])
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.9


def test_model_parallel_example():
    out = _run("model-parallel/lstm_stages.py", ["--num-stages", "4"])
    assert "PartitionSpec('stage',)" in out


def test_ssd_example():
    out = _run("ssd/train_ssd.py", ["--iters", "2", "--batch-size", "2"])
    assert "detection output" in out


def test_adversary_example():
    out = _run("adversary/fgsm.py", ["--iters", "80"])
    assert "adversarial accuracy" in out


def test_custom_op_example():
    out = _run("numpy-ops/custom_softmax.py", ["--num-epochs", "6"])
    acc = float(out.strip().splitlines()[-1].split()[-1])
    assert acc > 0.9


def test_recommender_example():
    out = _run("recommenders/matrix_fact.py", ["--num-epochs", "8"])
    assert "rmse" in out


def test_reinforce_example():
    out = _run("reinforcement-learning/reinforce_pole.py",
               ["--episodes", "16", "--batch-episodes", "4",
                "--max-steps", "40"])
    assert "reinforce ok" in out


def test_sgld_example():
    out = _run("bayesian-methods/sgld_regression.py",
               ["--num-epochs", "36", "--burn-in", "18"])
    assert "sgld ok" in out


def test_memcost_example():
    out = _run("memcost/memcost.py", ["--depth", "8", "--hidden", "64"])
    assert "memcost ok" in out


def test_ctc_example():
    out = _run("warpctc/ctc_seq_train.py",
               ["--num-epochs", "30", "--train-size", "256"])
    assert "ctc ok" in out


def test_speech_demo_example():
    out = _run("speech-demo/lstm_acoustic.py",
               ["--num-epochs", "12", "--train-size", "192"])
    assert "speech demo ok" in out


def test_dsd_example():
    out = _run("dsd/dsd.py", ["--epochs-per-phase", "4"])
    assert "dsd ok" in out


def test_adversarial_vae_example():
    out = _run("mxnet_adversarial_vae/avae.py", ["--iters", "400"])
    assert "avae ok" in out


def test_module_tour_example():
    out = _run("module/seq_module.py", ["--num-epochs", "6"])
    assert "module tour ok" in out


def test_python_howto_example():
    out = _run("python-howto/howto.py", ["--num-epochs", "4"])
    assert "howto ok" in out


def test_time_major_example():
    out = _run("rnn-time-major/rnn_cell_demo.py", ["--num-epochs", "4"])
    assert "time-major ok" in out


def test_deepspeech_example():
    out = _run("speech_recognition/deepspeech.py", ["--num-epochs", "24"])
    assert "deepspeech ok" in out


def test_ndsb1_pipeline_example():
    out = _run("kaggle-ndsb1/train_dsb.py", ["--num-epochs", "8"])
    assert "ndsb1 ok" in out


def test_ndsb2_crps_example():
    out = _run("kaggle-ndsb2/train_heart.py", ["--num-epochs", "14"])
    assert "ndsb2 ok" in out


def test_fine_tune_example():
    out = _run("image-classification/fine_tune.py", ["--num-epochs", "6"])
    assert "fine-tune ok" in out


def test_lstm_crf_example():
    out = _run("gluon/lstm_crf/lstm_crf.py", ["--num-epochs", "8"])
    assert "lstm-crf ok" in out


def test_super_resolution_example():
    out = _run("gluon/super_resolution/super_resolution.py",
               ["--num-epochs", "200"])
    assert "super-resolution ok" in out


def test_tree_lstm_example():
    out = _run("gluon/tree_lstm/tree_lstm.py",
               ["--num-epochs", "16", "--train-size", "48",
                "--depth", "2", "--hidden", "12"])
    assert "tree-lstm ok" in out


@pytest.mark.slow
def test_all_examples():
    """Full sweep; run explicitly with -m slow (CI nightly analogue)."""
    failures = []
    for rel, extra in run_all.EXAMPLES:
        proc = run_all.run_one(rel, extra)
        if proc.returncode != 0:
            failures.append((rel, proc.stderr[-500:]))
    assert not failures, failures


def test_predict_cpp_example(tmp_path):
    """The reference's predict-cpp deployment example over our predict C
    ABI: save a checkpoint, compile the C++ driver, run inference."""
    import shutil
    import subprocess as sp
    cxx = shutil.which("g++") or shutil.which("c++")
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    lib_dir = os.path.join(repo, "mxnet_tpu", "_lib")
    if cxx is None or not os.path.exists(
            os.path.join(lib_dir, "libmxtpu_c_api.so")):
        import pytest as _pytest
        _pytest.skip("no C++ compiler or native lib")
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                             name="conv")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.Flatten(net)
    net = mx.sym.FullyConnected(net, num_hidden=5, name="fc")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, label_names=["softmax_label"],
                        context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, 3, 8, 8))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 0)

    src = os.path.join(repo, "examples", "image-classification",
                       "predict-cpp", "image-classification-predict.cc")
    exe = str(tmp_path / "predict")
    sp.run([cxx, "-std=c++17", src, "-o", exe, "-L", lib_dir,
            "-lmxtpu_c_api", "-Wl,-rpath," + lib_dir], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = sp.run([exe, prefix + "-symbol.json", prefix + "-0000.params",
                "1,3,8,8"], capture_output=True, text=True, timeout=300,
               env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PREDICT_OK classes=5" in p.stdout, p.stdout
    psum = float(p.stdout.split("prob_sum=")[1].split()[0])
    assert abs(psum - 1.0) < 1e-3  # softmax over 5 classes, batch 1


def test_symbol_zoo_builds_and_infers():
    """Every symbols/ network builds, shape-infers to (N, classes), and
    the light ones run a real forward (reference benchmark_score nets)."""
    import numpy as np
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "examples", "image-classification"))
    import symbols
    import mxnet_tpu as mx

    cases = {
        "mlp": (28, {}),
        "lenet": (28, {}),
        "alexnet": (224, {}),
        "resnet": (224, {"num_layers": 50}),
        "vgg": (224, {"num_layers": 16}),
        "googlenet": (224, {}),
        "mobilenet": (224, {}),
        "resnext": (224, {"num_layers": 50}),
        "inception-bn": (224, {}),
        "inception-v3": (299, {}),
    }
    for net, (size, kwargs) in cases.items():
        sym = symbols.get_symbol(net, 10, **kwargs)
        shape = (2, 784) if net == "mlp" else (2, 3, size, size)
        _, out_shapes, _ = sym.infer_shape(data=shape)
        assert out_shapes[0] == (2, 10), (net, out_shapes)

    # forward the cheap ones for real
    for net in ("googlenet", "mobilenet"):
        sym = symbols.get_symbol(net, 10)
        mod = mx.mod.Module(sym, label_names=["softmax_label"],
                            context=mx.cpu())
        mod.bind(data_shapes=[("data", (1, 3, 224, 224))],
                 for_training=False)
        mod.init_params(mx.initializer.Xavier())
        from mxnet_tpu.io import DataBatch
        mod.forward(DataBatch(
            data=[mx.nd.array(np.random.rand(1, 3, 224, 224))]),
            is_train=False)
        probs = mod.get_outputs()[0].asnumpy()
        np.testing.assert_allclose(probs.sum(), 1.0, rtol=1e-4)
