"""Test configuration: run on a virtual 8-device CPU mesh.

Mirrors the reference's strategy of exercising distributed code without a
cluster (SURVEY.md §4: tools/launch.py --launcher local). Here the
XLA host-platform device-count flag gives 8 virtual devices so sharding/
collective tests run anywhere; the driver separately dry-runs the
multi-chip path via __graft_entry__.dryrun_multichip.
"""
import os

_TPU_LANE = os.environ.get("MXTPU_TEST_PLATFORM") == "tpu"

if not _TPU_LANE:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not _TPU_LANE:
    # the axon sitecustomize force-selects the TPU platform; tests run on
    # the virtual CPU mesh regardless (the tests/tpu lane lifts this)
    jax.config.update("jax_platforms", "cpu")
# numeric parity tests compare against numpy float32; disable bf16 matmul
jax.config.update("jax_default_matmul_precision", "highest")


import numpy as _np
import pytest as _pytest


@_pytest.fixture(autouse=True)
def _deterministic_seed():
    """Seed all RNG per test: initializer draws use np.random and eager
    random ops use the mx global key — cross-test order must not matter."""
    _np.random.seed(0)
    import mxnet_tpu as _mx
    _mx.random.seed(0)
    yield


def pytest_configure(config):
    config.addinivalue_line("markers", "slow: full example sweeps (nightly)")


def pytest_collection_modifyitems(config, items):
    if config.getoption("-m"):
        return
    skip_slow = _pytest.mark.skip(reason="slow: run with -m slow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip_slow)


_TPU_LANE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "tpu")


def pytest_collection_finish(session):
    session.config._mxtpu_nonlane_collected = sum(
        1 for item in session.items
        if not str(item.fspath).startswith(_TPU_LANE_DIR + os.sep))


def pytest_sessionfinish(session, exitstatus):
    # Tripwire: a run where main-suite tests were collected but ZERO
    # executed is a broken gate, not a green suite (the round-2 tests/tpu
    # conftest bug silently skipped all 301 tests). An all-skip run of the
    # TPU lane alone on a CPU-only host is legitimate, so only tests
    # outside tests/tpu count; --collect-only legitimately runs nothing.
    reporter = session.config.pluginmanager.get_plugin("terminalreporter")
    if reporter is None or exitstatus != 0 or session.config.option.collectonly:
        return
    ran = sum(len(reporter.stats.get(k, ())) for k in ("passed", "failed", "error"))
    nonlane = getattr(session.config, "_mxtpu_nonlane_collected", 0)
    if nonlane > 0 and ran == 0:
        reporter.write_line(
            "TRIPWIRE: %d non-TPU-lane tests collected but none executed — "
            "test gate is broken" % nonlane, red=True)
        session.exitstatus = 1
