"""tools/coreml converter (parity: reference tools/coreml/test/ — build
a net, convert, verify the emitted layer list and weight payloads)."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
sys.path.insert(0, os.path.join(REPO, "tools", "coreml"))

import mxnet_tpu as mx
import converter as cml


def _lenet_checkpoint(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.Convolution(data, kernel=(3, 3), num_filter=4, pad=(1, 1),
                             name="conv1")
    net = mx.sym.BatchNorm(net, fix_gamma=False, name="bn1")
    net = mx.sym.Activation(net, act_type="relu", name="relu1")
    net = mx.sym.Pooling(net, kernel=(2, 2), stride=(2, 2),
                         pool_type="max", name="pool1")
    net = mx.sym.Flatten(net, name="flat")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc1")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, label_names=["softmax_label"], context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, 3, 8, 8))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "lenet")
    mod.save_checkpoint(prefix, 0)
    return prefix


def test_convert_lenet_layers_and_weights(tmp_path):
    prefix = _lenet_checkpoint(tmp_path)
    sym, arg_params, aux_params = mx.model.load_checkpoint(prefix, 0)
    spec = cml.convert(sym, arg_params, aux_params, (1, 3, 8, 8),
                       class_labels=["a", "b", "c"])
    kinds = [l["type"] for l in spec["neuralNetwork"]["layers"]]
    assert kinds == ["convolution", "batchnorm", "activation", "pooling",
                     "flatten", "innerProduct", "softmax"]
    conv = spec["neuralNetwork"]["layers"][0]
    np.testing.assert_allclose(
        cml.decode_weights(conv["weights"]),
        arg_params["conv1_weight"].asnumpy(), rtol=1e-6)
    fc = spec["neuralNetwork"]["layers"][5]
    np.testing.assert_allclose(
        cml.decode_weights(fc["bias"]),
        arg_params["fc1_bias"].asnumpy(), rtol=1e-6)
    bn = spec["neuralNetwork"]["layers"][1]
    np.testing.assert_allclose(
        cml.decode_weights(bn["mean"]),
        aux_params["bn1_moving_mean"].asnumpy(), rtol=1e-6)
    assert spec["description"]["class_labels"] == ["a", "b", "c"]
    # spec JSON round-trip
    out = cml.save_spec(spec, str(tmp_path / "lenet.mlmodel"))
    again = cml.load_spec(out)
    assert again["neuralNetwork"]["layers"][0]["type"] == "convolution"


def test_convert_rejects_unsupported_op(tmp_path):
    data = mx.sym.Variable("data")
    net = mx.sym.SwapAxis(data, dim1=1, dim2=2, name="swap")
    with pytest.raises(ValueError, match="SwapAxis"):
        cml.convert(net, {}, {}, (1, 2, 3))


def test_cli_end_to_end(tmp_path):
    prefix = _lenet_checkpoint(tmp_path)
    out = str(tmp_path / "model.mlmodel")
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "tools", "coreml",
                      "mxnet_coreml_converter.py"),
         "--model-prefix", prefix, "--epoch", "0",
         "--input-shape", "1,3,8,8", "--output-file", out,
         "--class-labels", "x,y,z"],
        capture_output=True, text=True, timeout=300, env=env)
    assert p.returncode == 0, p.stderr
    assert "converted 7 layers" in p.stdout
    spec = json.load(open(out + ".json"))
    assert len(spec["neuralNetwork"]["layers"]) == 7
