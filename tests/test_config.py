"""Runtime config knobs (parity: the reference's MXNET_* env surface,
SURVEY.md §5.6)."""
import logging
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config


def test_knob_registry_covers_reference_surface():
    knobs = config.list_knobs()
    assert len(knobs) >= 30
    # every knob has a disposition + rationale
    for name, (disp, desc, _) in knobs.items():
        assert disp in ("honored", "mapped"), name
        assert desc
    honored = [k for k, v in knobs.items() if v[0] == "honored"]
    assert "MXNET_BACKWARD_DO_MIRROR" in honored
    assert "MXNET_ENGINE_TYPE" in honored


def test_backward_do_mirror_same_gradients(monkeypatch):
    from mxnet_tpu import sym
    rs = np.random.RandomState(0)
    x = rs.uniform(-1, 1, (4, 6)).astype(np.float32)

    def grads():
        data = sym.Variable("data")
        net = sym.FullyConnected(data, num_hidden=3, name="fc")
        net = sym.Activation(net, act_type="tanh")
        ex = net.simple_bind(ctx=mx.cpu(), data=(4, 6))
        ex.arg_dict["data"][:] = x
        ex.arg_dict["fc_weight"][:] = \
            rs.__class__(1).uniform(-0.5, 0.5, (3, 6)).astype(np.float32)
        ex.forward_backward(out_grads=mx.nd.ones((4, 3)))
        return ex.grad_dict["fc_weight"].asnumpy()

    base = grads()
    monkeypatch.setenv("MXNET_BACKWARD_DO_MIRROR", "1")
    remat = grads()
    np.testing.assert_allclose(remat, base, rtol=1e-6)


def test_storage_fallback_logging(monkeypatch, caplog):
    from mxnet_tpu.ndarray import sparse as sp
    monkeypatch.setenv("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", "1")
    config._fallback_logged.clear()
    rsp = sp.row_sparse_array((np.ones((1, 2), np.float32), [0]),
                              shape=(3, 2))
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu"):
        sp.dot(rsp, mx.nd.ones((2, 2)))
    assert any("storage fallback" in r.message for r in caplog.records)


def test_imageiter_threads_default_from_env(monkeypatch, tmp_path):
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    from mxnet_tpu.image import ImageIter
    rec_path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    arr = np.zeros((8, 8, 3), np.uint8)
    buf = _io.BytesIO()
    Image.fromarray(arr).save(buf, format="PNG")
    rec.write(recordio.pack(recordio.IRHeader(0, 0.0, 0, 0), buf.getvalue()))
    rec.close()
    monkeypatch.setenv("MXNET_CPU_WORKER_NTHREADS", "3")
    it = ImageIter(batch_size=1, data_shape=(3, 8, 8), path_imgrec=rec_path)
    assert it._pool is not None
    it2 = ImageIter(batch_size=1, data_shape=(3, 8, 8), path_imgrec=rec_path,
                    preprocess_threads=0)
    assert it2._pool is None
