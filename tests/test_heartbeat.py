"""Heartbeat liveness file semantics (ISSUE 7 satellite): atomic beat
writes (no truncate-in-place window) and stop_heartbeat removing the
worker file instead of leaving it to go stale."""
import os
import time

from mxnet_tpu import heartbeat


def _wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_beat_writes_atomically_and_stop_removes_file(tmp_path):
    root = str(tmp_path)
    heartbeat.start_heartbeat(0, root=root, interval=0.05)
    try:
        path = os.path.join(root, "worker-0")
        assert _wait_for(lambda: os.path.exists(path))
        # the visible file is always COMPLETE: a reader never sees the
        # zero-length truncate window the old in-place write had
        for _ in range(20):
            with open(path) as f:
                content = f.read()
            assert content and float(content) > 0
            time.sleep(0.01)
        assert heartbeat.count_dead(1, root=root, timeout=10) == 0
    finally:
        heartbeat.stop_heartbeat()
    # stop removes the file (and its temp): the worker reads as
    # departed immediately, not alive-until-stale
    assert _wait_for(lambda: not os.path.exists(path))
    assert not os.path.exists(path + ".tmp")
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1


def test_stop_heartbeat_idempotent(tmp_path):
    heartbeat.stop_heartbeat()          # no beat running: no-op
    heartbeat.start_heartbeat(3, root=str(tmp_path), interval=0.05)
    heartbeat.stop_heartbeat()
    heartbeat.stop_heartbeat()          # second stop: still a no-op


def test_count_dead_stale_file_still_counts(tmp_path):
    # a worker that died WITHOUT a clean stop leaves a stale file — the
    # timeout path still catches it
    root = str(tmp_path)
    path = os.path.join(root, "worker-0")
    with open(path, "w") as f:
        f.write(str(time.time() - 100))
    os.utime(path, (time.time() - 100, time.time() - 100))
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1
