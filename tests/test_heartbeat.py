"""Heartbeat liveness semantics.

ISSUE 7 satellite: atomic beat writes (no truncate-in-place window) and
stop_heartbeat removing the worker file instead of leaving it to go
stale. ISSUE 12: staleness judged against the heartbeat directory's OWN
clock (a reader wall clock skewed from the file server must not read
every live peer as dead), leftover ``worker-*.tmp`` files from a writer
that died mid-rename never count as live workers, and the
pre-collective CollectiveGate barrier-file protocol detects dead vs
slow peers with a bounded timeout."""
import os
import threading
import time

import pytest

from mxnet_tpu import faults, heartbeat
from mxnet_tpu.heartbeat import CollectiveGate, DeadWorkerError


def _wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_beat_writes_atomically_and_stop_removes_file(tmp_path):
    root = str(tmp_path)
    heartbeat.start_heartbeat(0, root=root, interval=0.05)
    try:
        path = os.path.join(root, "worker-0")
        assert _wait_for(lambda: os.path.exists(path))
        # the visible file is always COMPLETE: a reader never sees the
        # zero-length truncate window the old in-place write had
        for _ in range(20):
            with open(path) as f:
                content = f.read()
            assert content and float(content) > 0
            time.sleep(0.01)
        assert heartbeat.count_dead(1, root=root, timeout=10) == 0
    finally:
        heartbeat.stop_heartbeat()
    # stop removes the file (and its temp): the worker reads as
    # departed immediately, not alive-until-stale
    assert _wait_for(lambda: not os.path.exists(path))
    assert not os.path.exists(path + ".tmp")
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1


def test_stop_heartbeat_idempotent(tmp_path):
    heartbeat.stop_heartbeat()          # no beat running: no-op
    heartbeat.start_heartbeat(3, root=str(tmp_path), interval=0.05)
    heartbeat.stop_heartbeat()
    heartbeat.stop_heartbeat()          # second stop: still a no-op


def test_count_dead_stale_file_still_counts(tmp_path):
    # a worker that died WITHOUT a clean stop leaves a stale file — the
    # timeout path still catches it
    root = str(tmp_path)
    path = os.path.join(root, "worker-0")
    with open(path, "w") as f:
        f.write(str(time.time() - 100))
    os.utime(path, (time.time() - 100, time.time() - 100))
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1


# ---------------------------------------------------------------------------
# ISSUE 12 satellites: clock-skew tolerance, .tmp hygiene, liveness scan
# ---------------------------------------------------------------------------

def _fresh_worker(root, rank, age=0.0):
    path = os.path.join(root, "worker-%d" % rank)
    with open(path, "w") as f:
        f.write(str(time.time()))
    if age:
        t = time.time() - age
        os.utime(path, (t, t))
    return path


def test_count_dead_ignores_leftover_tmp_files(tmp_path):
    """A writer that died mid-rename leaves ``worker-N.tmp`` — it must
    never read as a live worker (and a dead rank with ONLY a .tmp file
    still counts dead)."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    with open(os.path.join(root, "worker-1.tmp"), "w") as f:
        f.write(str(time.time()))
    assert heartbeat.alive_ranks(root=root, timeout=10) == {0}
    assert heartbeat.count_dead(2, root=root, timeout=10) == 1


def test_staleness_is_clock_skew_tolerant(tmp_path, monkeypatch):
    """Staleness compares worker-file mtimes against a PROBE file's
    mtime in the same directory — the reader's wall clock is never
    consulted, so a reader skewed hours from the file server (NFS /
    GCS-fuse) neither reads live peers as dead nor dead peers as
    forever-live."""
    root = str(tmp_path)
    _fresh_worker(root, 0)            # fresh
    _fresh_worker(root, 1, age=100)   # genuinely stale
    real_time = time.time
    # reader clock skewed far ahead AND far behind: the verdicts of the
    # old now-vs-payload (and now-vs-mtime with a local now) comparison
    # would flip; the probe-based comparison cannot
    for skew in (+3600.0, -3600.0):
        monkeypatch.setattr(time, "time", lambda: real_time() + skew)
        assert heartbeat.count_dead(2, root=root, timeout=10) == 1
        assert heartbeat.alive_ranks(root=root, timeout=10) == {0}
    monkeypatch.setattr(time, "time", real_time)


def test_staleness_uses_mtime_not_payload(tmp_path):
    """The beat payload text is informational only: a file with a
    bogus (skewed-writer) timestamp payload but a fresh mtime is a
    LIVE worker."""
    root = str(tmp_path)
    path = _fresh_worker(root, 0)
    with open(path, "w") as f:
        f.write(str(time.time() - 99999.0))   # skewed payload
    assert heartbeat.count_dead(1, root=root, timeout=10) == 0


def test_stale_ranks_subset(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 2, age=50)
    assert heartbeat.stale_ranks([0, 1, 2], root=root, timeout=10) == [1, 2]
    # no root configured: no verdicts (the surface is inert)
    assert heartbeat.stale_ranks([0, 1], root=None, timeout=10) == []


# ---------------------------------------------------------------------------
# CollectiveGate: the pre-collective barrier-file protocol
# ---------------------------------------------------------------------------

def test_gate_both_members_pass(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)
    out = {}

    def cross(gate, key):
        out[key] = gate.arrive_and_wait()

    t = threading.Thread(target=cross, args=(g1, "r1"))
    t.start()
    cross(g0, "r0")
    t.join(5)
    assert out == {"r0": 1, "r1": 1}
    # a second crossing bumps the generation — same files, rewritten
    t = threading.Thread(target=cross, args=(g1, "r1"))
    t.start()
    cross(g0, "r0")
    t.join(5)
    assert out == {"r0": 2, "r1": 2}


def test_gate_detects_dead_peer(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1, age=100)   # peer's heartbeat is stale
    g0 = CollectiveGate(0, (0, 1), root=root, timeout=10, poll=0.01)
    with pytest.raises(DeadWorkerError) as ei:
        g0.arrive_and_wait()
    assert ei.value.ranks == (1,)
    assert ei.value.channel == "step"
    assert ei.value.generation == 1
    assert not ei.value.timed_out


def test_gate_waits_for_slow_but_live_peer_then_hard_timeout(tmp_path):
    """A missing peer whose heartbeat stays FRESH is slow, not dead —
    the gate keeps waiting, and only the hard cap raises (flagged
    ``timed_out`` so the caller can tell the two apart)."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)            # fresh heartbeat, never arrives
    g0 = CollectiveGate(0, (0, 1), root=root, timeout=10,
                        gate_timeout=0.3, poll=0.01)
    t0 = time.monotonic()
    with pytest.raises(DeadWorkerError) as ei:
        g0.arrive_and_wait()
    assert time.monotonic() - t0 >= 0.25
    assert ei.value.timed_out
    assert ei.value.ranks == (1,)


def test_gate_disabled_without_root_or_peers(tmp_path):
    # no heartbeat dir: crossings are no-ops (still generation-counted)
    g = CollectiveGate(0, (0, 1), root=None)
    assert not g.enabled
    assert g.arrive_and_wait() == 1
    # single member: nothing to guard
    g = CollectiveGate(0, (0,), root=str(tmp_path))
    assert not g.enabled
    assert g.arrive_and_wait() == 1


def test_gate_kv_collective_fault_site_fires_before_arrival(tmp_path):
    """The chaos lane's deterministic kill point: an injected raise at
    ``kv_collective`` fires BEFORE the arrival is published, so peers
    observe an absent arrival — exactly a mid-training death."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    g = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    faults.configure("kv_collective:raise:n=1")
    try:
        with pytest.raises(faults.InjectedFault):
            g.arrive_and_wait()
        assert not os.path.exists(g._member_path(0))
        assert faults.counts()["kv_collective"]["fired"] == 1
    finally:
        faults.clear()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_heartbeat_fault_site_kills_the_beat(tmp_path):
    """``heartbeat:raise`` kills the beat thread: the worker computes
    on but reads as dead — the zombie case the liveness tier must
    treat as a member loss. (The thread dying on the injected raise is
    the point — its unhandled-exception warning is expected.)"""
    root = str(tmp_path)
    faults.configure("heartbeat:raise:first=1000000")
    try:
        heartbeat.start_heartbeat(0, root=root, interval=0.02)
        deadline = time.time() + 5
        while time.time() < deadline \
                and not faults.counts().get("heartbeat", {}).get("fired"):
            time.sleep(0.02)
        assert faults.counts()["heartbeat"]["fired"] >= 1
        # the raise fired before the first write: no live file ever
        assert heartbeat.alive_ranks(root=root, timeout=10) == set()
        assert heartbeat.count_dead(1, root=root, timeout=10) == 1
    finally:
        faults.clear()
        heartbeat.stop_heartbeat()


# ---------------------------------------------------------------------------
# mxlife resource-release regressions: unlink-on-failure for every
# temp+rename site (a failed rename must never leave .tmp artifacts
# on the shared mount — ISSUE 14)
# ---------------------------------------------------------------------------

def test_fs_now_failed_rename_leaves_no_tmp(tmp_path, monkeypatch):
    root = str(tmp_path)

    def _boom(src, dst):
        raise OSError("replace failed")

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    t0 = time.time()
    now = heartbeat._fs_now(root)
    assert now >= t0 - 1.0             # fell back to the local clock
    assert not [n for n in os.listdir(root) if n.endswith(".tmp")]


def test_beat_failed_rename_leaves_no_tmp(tmp_path, monkeypatch):
    root = str(tmp_path)
    real_replace = os.replace
    fails = []

    def _boom(src, dst):
        if dst.endswith("worker-7"):
            fails.append(dst)
            raise OSError("replace failed")
        return real_replace(src, dst)

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    heartbeat.start_heartbeat(7, root=root, interval=0.02)
    try:
        # generous window: the 0.02s beat loop needs two failed beats,
        # but a loaded CI box can stall daemon threads for seconds
        assert _wait_for(lambda: len(fails) >= 2, timeout=20.0)
        # every failed beat cleans its temp — POLL for the absence:
        # fails.append runs inside the patched os.replace, i.e. while
        # the .tmp still exists, so a one-shot listdir can race the
        # beat thread's except-clause unlink
        assert _wait_for(lambda: not [n for n in os.listdir(root)
                                      if n.endswith(".tmp")])
        # the worker file itself never appeared (all renames failed)
        assert not os.path.exists(os.path.join(root, "worker-7"))
    finally:
        heartbeat.stop_heartbeat()


def test_gate_publish_failure_cleans_tmp_and_raises(tmp_path,
                                                    monkeypatch):
    root = str(tmp_path)
    g = CollectiveGate(0, (0, 1), root=root, poll=0.01)

    def _boom(src, dst):
        raise OSError("replace failed")

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    with pytest.raises(OSError):
        g._publish(1)
    # the crossing failed loudly AND left nothing for peers to scan
    assert not [n for n in os.listdir(root) if n.endswith(".tmp")]


# ---------------------------------------------------------------------------
# ISSUE 18 tentpole: gate-wait straggler attribution — per-crossing
# gate_wait spans with causal (channel, generation) ctx, arrival-order
# read back from the gate files, the self-time skew signal, and the
# streak machine behind the structured dist.straggler event
# ---------------------------------------------------------------------------

@pytest.fixture()
def _telemetry():
    from mxnet_tpu import telemetry
    telemetry.enable()
    telemetry.reset()
    yield telemetry
    telemetry.reset()


def _cross_pair(g0, g1, delay1=0.0, self_work=None, n=1):
    """Cross both gates n times from two threads; rank 1 sleeps
    ``delay1`` seconds before each arrival. ``self_work`` optionally
    maps rank -> per-crossing own-work seconds slept WITHOUT a
    matching delay on the other side (the self-time skew case)."""
    def run(gate, delay, work):
        for _ in range(n):
            if work:
                time.sleep(work)
            if delay:
                time.sleep(delay)
            gate.arrive_and_wait()
    sw = self_work or {}
    t = threading.Thread(target=run, args=(g1, delay1, sw.get(1)))
    t.start()
    run(g0, 0.0, sw.get(0))
    t.join(10)


def _gate_wait_spans(telemetry, channel=None):
    return [s for s in telemetry.recent_spans()
            if s["name"] == "gate_wait"
            and (channel is None or s["ctx"].get("channel") == channel)]


def test_gate_wait_span_attributes_last_arriver(tmp_path, _telemetry):
    """Every completed crossing records a gate_wait span whose ctx
    names the channel, generation, the last arriver (read back from
    the gate files' mtimes — the shared filesystem's own clock) and
    its excess over the fleet median arrival."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)
    _cross_pair(g0, g1, delay1=0.08)
    spans = _gate_wait_spans(_telemetry, "step")
    assert len(spans) == 2              # one per rank
    for s in spans:
        c = s["ctx"]
        assert c["generation"] == 1
        assert c["last_rank"] == 1
        assert c["excess_ms"] >= 50
        # arrival order: rank 0 first at rel 0, rank 1 late
        ranks = [r for r, _rel in c["arrivals"]]
        assert ranks == [0, 1]
    # the early rank actually WAITED; the late rank cleared instantly
    by_wait = sorted(spans, key=lambda s: s["ctx"]["wait_ms"])
    assert by_wait[-1]["ctx"]["wait_ms"] >= 50
    cnt = _telemetry.counters()
    assert cnt.get("heartbeat.gate_crossings.step") == 2
    assert cnt.get("heartbeat.gate_wait_ms.step", 0) >= 50


def test_gate_straggler_streak_emits_event(tmp_path, _telemetry):
    """One slow crossing is noise; the SAME rank trailing the fleet
    median by >= the threshold for K consecutive crossings is a
    straggler — a structured dist.straggler event naming it, every
    crossing the streak persists."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)
    assert g0.straggler_k == 3          # default
    _cross_pair(g0, g1, delay1=0.08, n=4)
    evs = [e for e in _telemetry.events()
           if e["kind"] == "dist.straggler"]
    # streak hits K=3 at crossing 3 and persists through 4 — both
    # ranks run the same verdict from the same files
    assert len(evs) == 4
    for e in evs:
        d = e["data"]
        assert d["rank"] == 1
        assert d["channel"] == "step"
        assert d["excess_ms"] >= 50
        assert d["streak"] >= 3
    assert _telemetry.counters().get("dist.straggler") == 4


def test_gate_self_time_skew_names_hidden_straggler(tmp_path,
                                                    _telemetry):
    """A straggler whose slowness a synchronizing collective absorbs
    (peers blocked in the completion await arrive at the next gate
    TOGETHER) is invisible to arrival order — the self-time half of
    the verdict catches it: each rank publishes own-work time (wall
    window minus note_wait-reported waits) in its gate file, and the
    rank whose self-time exceeds the fleet median by the threshold is
    named."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)

    def run0():
        for _ in range(4):
            time.sleep(0.005)           # own work
            time.sleep(0.085)           # blocked on the "collective"
            g0.note_wait(85.0)          # ...reported as WAIT
            g0.arrive_and_wait()

    def run1():
        for _ in range(4):
            time.sleep(0.090)           # all own work
            g1.arrive_and_wait()

    t = threading.Thread(target=run1)
    t.start()
    run0()
    t.join(10)
    evs = [e for e in _telemetry.events()
           if e["kind"] == "dist.straggler"]
    assert evs and all(e["data"]["rank"] == 1 for e in evs)
    # the published self-times ride in the span ctx: rank 1's own-work
    # dominates while the arrivals themselves are near-simultaneous
    with_self = [s for s in _gate_wait_spans(_telemetry, "step")
                 if "self_ms" in s["ctx"]]
    assert with_self
    c = with_self[-1]["ctx"]
    assert c["self_ms"][1] - c["self_ms"][0] >= 50
    assert c["excess_ms"] >= 50


def test_gate_error_crossing_blames_dead_rank_no_streak(tmp_path,
                                                        _telemetry):
    """An aborted crossing (DeadWorkerError) attributes the FULL wait
    to the dead rank — the pre-death spike the fleet view pins on the
    victim — but never feeds the straggler streak (death is not
    slowness)."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1, age=100)     # peer heartbeat stale
    g0 = CollectiveGate(0, (0, 1), root=root, timeout=10, poll=0.01)
    with pytest.raises(DeadWorkerError):
        g0.arrive_and_wait()
    spans = _gate_wait_spans(_telemetry, "step")
    assert len(spans) == 1
    c = spans[0]["ctx"]
    assert c["last_rank"] == 1
    assert c["dead_ranks"] == [1]
    assert c["timed_out"] is False
    assert c["excess_ms"] == pytest.approx(c["wait_ms"])
    assert not [e for e in _telemetry.events()
                if e["kind"] == "dist.straggler"]


def test_gate_stats_and_module_merge(tmp_path, _telemetry):
    """Per-gate stats() feed gate_stats(), the per-channel merge the
    flight sampler folds into its series samples."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    # a channel name unique to this test: the process-global gate
    # registry may still hold gates a prior test's exception pinned
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01,
                        channel="mergetest")
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01,
                        channel="mergetest")
    _cross_pair(g0, g1, delay1=0.06, n=2)
    st = g0.stats()
    assert st["crossings"] == 2
    assert st["last_rank"] == 1
    assert st["wait_ms_total"] >= st["last_wait_ms"] > 0
    merged = heartbeat.gate_stats()
    assert "mergetest" in merged
    # BOTH live gates on the channel merge: totals sum
    assert merged["mergetest"]["crossings"] == 4


def test_gate_attribution_disabled_with_telemetry_off(tmp_path):
    """telemetry.disable() turns the whole attribution path off — no
    spans, no counters, no events — while the barrier protocol itself
    keeps working."""
    from mxnet_tpu import telemetry
    telemetry.reset()
    telemetry.disable()
    try:
        root = str(tmp_path)
        _fresh_worker(root, 0)
        _fresh_worker(root, 1)
        g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
        g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)
        _cross_pair(g0, g1)
    finally:
        telemetry.enable()
    assert not _gate_wait_spans(telemetry)
    assert not telemetry.counters()
    telemetry.reset()
