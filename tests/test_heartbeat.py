"""Heartbeat liveness semantics.

ISSUE 7 satellite: atomic beat writes (no truncate-in-place window) and
stop_heartbeat removing the worker file instead of leaving it to go
stale. ISSUE 12: staleness judged against the heartbeat directory's OWN
clock (a reader wall clock skewed from the file server must not read
every live peer as dead), leftover ``worker-*.tmp`` files from a writer
that died mid-rename never count as live workers, and the
pre-collective CollectiveGate barrier-file protocol detects dead vs
slow peers with a bounded timeout."""
import os
import threading
import time

import pytest

from mxnet_tpu import faults, heartbeat
from mxnet_tpu.heartbeat import CollectiveGate, DeadWorkerError


def _wait_for(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def test_beat_writes_atomically_and_stop_removes_file(tmp_path):
    root = str(tmp_path)
    heartbeat.start_heartbeat(0, root=root, interval=0.05)
    try:
        path = os.path.join(root, "worker-0")
        assert _wait_for(lambda: os.path.exists(path))
        # the visible file is always COMPLETE: a reader never sees the
        # zero-length truncate window the old in-place write had
        for _ in range(20):
            with open(path) as f:
                content = f.read()
            assert content and float(content) > 0
            time.sleep(0.01)
        assert heartbeat.count_dead(1, root=root, timeout=10) == 0
    finally:
        heartbeat.stop_heartbeat()
    # stop removes the file (and its temp): the worker reads as
    # departed immediately, not alive-until-stale
    assert _wait_for(lambda: not os.path.exists(path))
    assert not os.path.exists(path + ".tmp")
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1


def test_stop_heartbeat_idempotent(tmp_path):
    heartbeat.stop_heartbeat()          # no beat running: no-op
    heartbeat.start_heartbeat(3, root=str(tmp_path), interval=0.05)
    heartbeat.stop_heartbeat()
    heartbeat.stop_heartbeat()          # second stop: still a no-op


def test_count_dead_stale_file_still_counts(tmp_path):
    # a worker that died WITHOUT a clean stop leaves a stale file — the
    # timeout path still catches it
    root = str(tmp_path)
    path = os.path.join(root, "worker-0")
    with open(path, "w") as f:
        f.write(str(time.time() - 100))
    os.utime(path, (time.time() - 100, time.time() - 100))
    assert heartbeat.count_dead(1, root=root, timeout=10) == 1


# ---------------------------------------------------------------------------
# ISSUE 12 satellites: clock-skew tolerance, .tmp hygiene, liveness scan
# ---------------------------------------------------------------------------

def _fresh_worker(root, rank, age=0.0):
    path = os.path.join(root, "worker-%d" % rank)
    with open(path, "w") as f:
        f.write(str(time.time()))
    if age:
        t = time.time() - age
        os.utime(path, (t, t))
    return path


def test_count_dead_ignores_leftover_tmp_files(tmp_path):
    """A writer that died mid-rename leaves ``worker-N.tmp`` — it must
    never read as a live worker (and a dead rank with ONLY a .tmp file
    still counts dead)."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    with open(os.path.join(root, "worker-1.tmp"), "w") as f:
        f.write(str(time.time()))
    assert heartbeat.alive_ranks(root=root, timeout=10) == {0}
    assert heartbeat.count_dead(2, root=root, timeout=10) == 1


def test_staleness_is_clock_skew_tolerant(tmp_path, monkeypatch):
    """Staleness compares worker-file mtimes against a PROBE file's
    mtime in the same directory — the reader's wall clock is never
    consulted, so a reader skewed hours from the file server (NFS /
    GCS-fuse) neither reads live peers as dead nor dead peers as
    forever-live."""
    root = str(tmp_path)
    _fresh_worker(root, 0)            # fresh
    _fresh_worker(root, 1, age=100)   # genuinely stale
    real_time = time.time
    # reader clock skewed far ahead AND far behind: the verdicts of the
    # old now-vs-payload (and now-vs-mtime with a local now) comparison
    # would flip; the probe-based comparison cannot
    for skew in (+3600.0, -3600.0):
        monkeypatch.setattr(time, "time", lambda: real_time() + skew)
        assert heartbeat.count_dead(2, root=root, timeout=10) == 1
        assert heartbeat.alive_ranks(root=root, timeout=10) == {0}
    monkeypatch.setattr(time, "time", real_time)


def test_staleness_uses_mtime_not_payload(tmp_path):
    """The beat payload text is informational only: a file with a
    bogus (skewed-writer) timestamp payload but a fresh mtime is a
    LIVE worker."""
    root = str(tmp_path)
    path = _fresh_worker(root, 0)
    with open(path, "w") as f:
        f.write(str(time.time() - 99999.0))   # skewed payload
    assert heartbeat.count_dead(1, root=root, timeout=10) == 0


def test_stale_ranks_subset(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 2, age=50)
    assert heartbeat.stale_ranks([0, 1, 2], root=root, timeout=10) == [1, 2]
    # no root configured: no verdicts (the surface is inert)
    assert heartbeat.stale_ranks([0, 1], root=None, timeout=10) == []


# ---------------------------------------------------------------------------
# CollectiveGate: the pre-collective barrier-file protocol
# ---------------------------------------------------------------------------

def test_gate_both_members_pass(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)
    g0 = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    g1 = CollectiveGate(1, (0, 1), root=root, poll=0.01)
    out = {}

    def cross(gate, key):
        out[key] = gate.arrive_and_wait()

    t = threading.Thread(target=cross, args=(g1, "r1"))
    t.start()
    cross(g0, "r0")
    t.join(5)
    assert out == {"r0": 1, "r1": 1}
    # a second crossing bumps the generation — same files, rewritten
    t = threading.Thread(target=cross, args=(g1, "r1"))
    t.start()
    cross(g0, "r0")
    t.join(5)
    assert out == {"r0": 2, "r1": 2}


def test_gate_detects_dead_peer(tmp_path):
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1, age=100)   # peer's heartbeat is stale
    g0 = CollectiveGate(0, (0, 1), root=root, timeout=10, poll=0.01)
    with pytest.raises(DeadWorkerError) as ei:
        g0.arrive_and_wait()
    assert ei.value.ranks == (1,)
    assert ei.value.channel == "step"
    assert ei.value.generation == 1
    assert not ei.value.timed_out


def test_gate_waits_for_slow_but_live_peer_then_hard_timeout(tmp_path):
    """A missing peer whose heartbeat stays FRESH is slow, not dead —
    the gate keeps waiting, and only the hard cap raises (flagged
    ``timed_out`` so the caller can tell the two apart)."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    _fresh_worker(root, 1)            # fresh heartbeat, never arrives
    g0 = CollectiveGate(0, (0, 1), root=root, timeout=10,
                        gate_timeout=0.3, poll=0.01)
    t0 = time.monotonic()
    with pytest.raises(DeadWorkerError) as ei:
        g0.arrive_and_wait()
    assert time.monotonic() - t0 >= 0.25
    assert ei.value.timed_out
    assert ei.value.ranks == (1,)


def test_gate_disabled_without_root_or_peers(tmp_path):
    # no heartbeat dir: crossings are no-ops (still generation-counted)
    g = CollectiveGate(0, (0, 1), root=None)
    assert not g.enabled
    assert g.arrive_and_wait() == 1
    # single member: nothing to guard
    g = CollectiveGate(0, (0,), root=str(tmp_path))
    assert not g.enabled
    assert g.arrive_and_wait() == 1


def test_gate_kv_collective_fault_site_fires_before_arrival(tmp_path):
    """The chaos lane's deterministic kill point: an injected raise at
    ``kv_collective`` fires BEFORE the arrival is published, so peers
    observe an absent arrival — exactly a mid-training death."""
    root = str(tmp_path)
    _fresh_worker(root, 0)
    g = CollectiveGate(0, (0, 1), root=root, poll=0.01)
    faults.configure("kv_collective:raise:n=1")
    try:
        with pytest.raises(faults.InjectedFault):
            g.arrive_and_wait()
        assert not os.path.exists(g._member_path(0))
        assert faults.counts()["kv_collective"]["fired"] == 1
    finally:
        faults.clear()


@pytest.mark.filterwarnings(
    "ignore::pytest.PytestUnhandledThreadExceptionWarning")
def test_heartbeat_fault_site_kills_the_beat(tmp_path):
    """``heartbeat:raise`` kills the beat thread: the worker computes
    on but reads as dead — the zombie case the liveness tier must
    treat as a member loss. (The thread dying on the injected raise is
    the point — its unhandled-exception warning is expected.)"""
    root = str(tmp_path)
    faults.configure("heartbeat:raise:first=1000000")
    try:
        heartbeat.start_heartbeat(0, root=root, interval=0.02)
        deadline = time.time() + 5
        while time.time() < deadline \
                and not faults.counts().get("heartbeat", {}).get("fired"):
            time.sleep(0.02)
        assert faults.counts()["heartbeat"]["fired"] >= 1
        # the raise fired before the first write: no live file ever
        assert heartbeat.alive_ranks(root=root, timeout=10) == set()
        assert heartbeat.count_dead(1, root=root, timeout=10) == 1
    finally:
        faults.clear()
        heartbeat.stop_heartbeat()


# ---------------------------------------------------------------------------
# mxlife resource-release regressions: unlink-on-failure for every
# temp+rename site (a failed rename must never leave .tmp artifacts
# on the shared mount — ISSUE 14)
# ---------------------------------------------------------------------------

def test_fs_now_failed_rename_leaves_no_tmp(tmp_path, monkeypatch):
    root = str(tmp_path)

    def _boom(src, dst):
        raise OSError("replace failed")

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    t0 = time.time()
    now = heartbeat._fs_now(root)
    assert now >= t0 - 1.0             # fell back to the local clock
    assert not [n for n in os.listdir(root) if n.endswith(".tmp")]


def test_beat_failed_rename_leaves_no_tmp(tmp_path, monkeypatch):
    root = str(tmp_path)
    real_replace = os.replace
    fails = []

    def _boom(src, dst):
        if dst.endswith("worker-7"):
            fails.append(dst)
            raise OSError("replace failed")
        return real_replace(src, dst)

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    heartbeat.start_heartbeat(7, root=root, interval=0.02)
    try:
        # generous window: the 0.02s beat loop needs two failed beats,
        # but a loaded CI box can stall daemon threads for seconds
        assert _wait_for(lambda: len(fails) >= 2, timeout=20.0)
        # every failed beat cleans its temp — POLL for the absence:
        # fails.append runs inside the patched os.replace, i.e. while
        # the .tmp still exists, so a one-shot listdir can race the
        # beat thread's except-clause unlink
        assert _wait_for(lambda: not [n for n in os.listdir(root)
                                      if n.endswith(".tmp")])
        # the worker file itself never appeared (all renames failed)
        assert not os.path.exists(os.path.join(root, "worker-7"))
    finally:
        heartbeat.stop_heartbeat()


def test_gate_publish_failure_cleans_tmp_and_raises(tmp_path,
                                                    monkeypatch):
    root = str(tmp_path)
    g = CollectiveGate(0, (0, 1), root=root, poll=0.01)

    def _boom(src, dst):
        raise OSError("replace failed")

    monkeypatch.setattr(heartbeat.os, "replace", _boom)
    with pytest.raises(OSError):
        g._publish(1)
    # the crossing failed loudly AND left nothing for peers to scan
    assert not [n for n in os.listdir(root) if n.endswith(".tmp")]
