"""Program cards (ISSUE 4): per-program XLA cost/memory introspection
through the executor's instrumented compile wrapper, recompile-cause
diagnosis, the live device-buffer ledger, and enriched OOM errors."""
import gc
import json
import logging

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import executor as _ex
from mxnet_tpu import telemetry
from mxnet_tpu.io import DataBatch, DataDesc


@pytest.fixture(autouse=True)
def _clean_registry():
    """Fresh, enabled registry per test; the once-per-cause recompile
    warning set is cleared so each test sees its own first warning."""
    telemetry.enable()
    telemetry.reset()
    _ex._RECOMPILE_WARNED.clear()
    yield
    telemetry.enable()
    telemetry.reset()
    _ex._RECOMPILE_WARNED.clear()


def _mlp(hidden=32, classes=4):
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=hidden, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=classes, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def _iter(n_batches, batch=32, d=16, classes=4):
    rs = np.random.RandomState(0)
    X = rs.uniform(-1, 1, (batch * n_batches, d)).astype(np.float32)
    Y = rs.randint(0, classes, batch * n_batches).astype(np.float32)
    return mx.io.NDArrayIter(X, Y, batch_size=batch)


def _fit(mod, it, n_epoch=1, **kwargs):
    mod.fit(it, eval_metric=mx.metric.Accuracy(), num_epoch=n_epoch,
            initializer=mx.initializer.Xavier(), optimizer="sgd",
            optimizer_params={"learning_rate": 0.05}, **kwargs)


def _batch(batch=32, d=16, classes=4, seed=0):
    rs = np.random.RandomState(seed)
    return DataBatch(
        [mx.nd.array(rs.uniform(-1, 1, (batch, d)).astype(np.float32))],
        [mx.nd.array(rs.randint(0, classes, batch).astype(np.float32))],
        pad=0)


def _cards(kind=None):
    cards = telemetry.programs().values()
    return [c for c in cards if kind is None or c["kind"] == kind]


# ---------------------------------------------------------------------------
# Card capture: forward / fwd_bwd / train_step with real cost figures
# ---------------------------------------------------------------------------

def test_cards_for_all_entry_points():
    ex = _mlp().simple_bind(ctx=mx.cpu(), grad_req="write", type_dict={},
                            data=(32, 16), softmax_label=(32,))
    ex.forward(is_train=False)
    ex.forward_backward()
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    _fit(mod, _iter(4))

    for kind in ("forward", "fwd_bwd", "train_step"):
        cards = _cards(kind)
        assert cards, "no %s card captured" % kind
        card = cards[0]
        # the CPU backend's cost model yields real nonzero figures
        assert card["flops"] and card["flops"] > 0, card
        assert card["bytes_accessed"] and card["bytes_accessed"] > 0
        assert card["peak_bytes"] and card["peak_bytes"] > 0
        assert card["argument_bytes"] > 0 and card["output_bytes"] > 0
        assert card["compile_ms"] > 0 and card["trace_ms"] >= 0
        assert card["dispatches"] >= 1
        # the abstract input signature names the fed arguments
        paths = [e[0] for e in card["signature"]]
        assert any("data" in p for p in paths), paths

    # the whole-step program donates params/states/acc/aux
    ts = _cards("train_step")[0]
    assert ts["donated"] == [0, 1, 2, 3]
    assert ts["dispatches"] == 4


def test_train_step_card_on_dp_mesh():
    """The 8-device CPU mesh smoke lane's acceptance view: the SPMD
    train-step program cards with nonzero FLOPs and memory figures."""
    import jax
    n = min(8, jax.device_count())
    assert n >= 2, "needs the virtual multi-device CPU mesh"
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(n)])
    _fit(mod, _iter(4))
    assert mod._fused_fallback_reason is None
    cards = _cards("train_step")
    assert cards, telemetry.programs()
    card = cards[0]
    assert card["spmd_devices"] == n
    assert card["flops"] > 0 and card["bytes_accessed"] > 0
    assert card["peak_bytes"] > 0
    assert card["dispatches"] == 4
    # snapshot embeds the same cards (Module.telemetry_snapshot path)
    snap = mod.telemetry_snapshot()
    assert any(c["kind"] == "train_step" and c["spmd_devices"] == n
               for c in snap["programs"].values())


def test_jit_cache_reuse_keeps_one_card():
    """A second fit over the same shapes must reuse the compiled
    program: same card, dispatch count grows, no new compile."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    _fit(mod, _iter(3))
    n_cards = len(_cards("train_step"))
    _fit(mod, _iter(3))
    assert len(_cards("train_step")) == n_cards
    assert _cards("train_step")[0]["dispatches"] == 6
    assert "recompile.train_step" not in telemetry.counters()


# ---------------------------------------------------------------------------
# Recompile-cause diagnosis
# ---------------------------------------------------------------------------

def test_recompile_cause_warning_names_changed_shape(caplog):
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (32, 16))],
             label_shapes=[DataDesc("softmax_label", (32,))],
             for_training=True)
    mod.init_params(initializer=mx.initializer.Xavier())
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.executor"):
        mod.forward(_batch(32), is_train=False)      # first compile
        mod.forward(_batch(16), is_train=False)      # batch-shape flip
        mod.forward(_batch(16), is_train=False)      # cached: no warning
        mod.forward(_batch(32), is_train=False)      # cached: no warning
        mod.forward(_batch(8), is_train=False)       # same cause: warned once
    msgs = [r.message for r in caplog.records if "recompile" in r.message]
    assert len(msgs) == 1, msgs
    # the structured warning names the exact arg and the dimension flip
    assert "data" in msgs[0] and "shape" in msgs[0]
    assert "32" in msgs[0] and "16" in msgs[0]
    # every recompile counted, even the suppressed-warning ones
    assert telemetry.counters().get("recompile.forward") == 2
    # the new card records its causes for snapshot readers
    carded = [c for c in _cards("forward") if c.get("recompile_causes")]
    assert carded and any("shape" in cause
                          for cause in carded[0]["recompile_causes"])


def test_recompile_dtype_flip_named(caplog):
    """A dtype change (not shape) must be named as such."""
    ex = _mlp().simple_bind(ctx=mx.cpu(), grad_req="write", type_dict={},
                            data=(8, 16), softmax_label=(8,))
    ex.forward(is_train=False)
    import jax.numpy as jnp
    ex.arg_dict["data"]._set_data(
        jnp.zeros((8, 16), jnp.float16))             # dtype flip
    with caplog.at_level(logging.WARNING, logger="mxnet_tpu.executor"):
        ex.forward(is_train=False)
    msgs = [r.message for r in caplog.records if "recompile" in r.message]
    assert len(msgs) == 1 and "dtype" in msgs[0], msgs
    assert "float16" in msgs[0]


# ---------------------------------------------------------------------------
# Live device-buffer ledger
# ---------------------------------------------------------------------------

def test_ledger_ndarray_lifecycle():
    key = str(mx.cpu())

    def stats():
        return telemetry.ledger().get(key) or {
            "alive_bytes": 0, "alive_count": 0, "peak_bytes": 0,
            "tracked_total": 0, "tracked_bytes_total": 0}

    # drain cyclic garbage EARLIER tests left alive on this shared
    # context first: the collect below would otherwise reclaim their
    # buffers mid-test and shift the deltas (order-dependent with the
    # native build enabled, which runs more predecessors)
    gc.collect()
    base = stats()
    a = mx.nd.zeros((64, 64))                        # 16 KiB fp32
    after_a = stats()
    assert after_a["alive_bytes"] - base["alive_bytes"] == 64 * 64 * 4
    assert after_a["alive_count"] - base["alive_count"] == 1
    assert after_a["peak_bytes"] >= after_a["alive_bytes"]
    b = mx.nd.ones((32,))
    peak = stats()["peak_bytes"]
    del a
    gc.collect()
    after_del = stats()
    assert after_del["alive_bytes"] - base["alive_bytes"] == 32 * 4
    assert after_del["peak_bytes"] == peak           # high-water stays
    assert after_del["tracked_total"] - base["tracked_total"] == 2
    # the live buffer map backs ledger_top
    top = telemetry.ledger_top(64)
    assert any(t["shape"] == [32] and t["ctx"] == key for t in top)
    del b
    gc.collect()
    assert stats()["alive_bytes"] == base["alive_bytes"]


def test_ledger_shard_put():
    import jax
    from mxnet_tpu.parallel import mesh as _pmesh, spmd as _spmd
    n = min(8, jax.device_count())
    assert n >= 2
    spec = _spmd.dp_spec(_pmesh.mesh_from_contexts(
        [mx.cpu(i) for i in range(n)]))
    key = "mesh(%ddev)" % n
    base = (telemetry.ledger().get(key) or {"alive_bytes": 0})["alive_bytes"]
    out = _spmd.shard_put(np.ones((n * 2, 4), np.float32),
                          spec.data_sharding)
    st = telemetry.ledger()[key]
    assert st["alive_bytes"] - base == n * 2 * 4 * 4
    assert any(t["kind"] == "shard_put" for t in telemetry.ledger_top(64))
    del out
    gc.collect()
    assert telemetry.ledger()[key]["alive_bytes"] == base


def test_ledger_disabled_is_silent_and_consistent():
    """Arrays created while disabled are not charged, and arrays
    created while enabled release correctly even if freed while
    disabled — toggling never corrupts the counters."""
    key = str(mx.cpu())
    a = mx.nd.zeros((16, 16))
    base = telemetry.ledger()[key]["alive_bytes"]
    telemetry.disable()
    b = mx.nd.zeros((128, 128))                      # untracked
    assert telemetry.ledger()[key]["alive_bytes"] == base
    del a                                            # tracked: releases
    gc.collect()
    telemetry.enable()
    assert telemetry.ledger()[key]["alive_bytes"] == base - 16 * 16 * 4
    del b


def test_ledger_release_is_lock_free():
    """The weakref.finalize callback must never take the registry
    lock: cyclic GC (autograd tapes make NDArray cycles) can run it
    synchronously on a thread that already HOLDS the lock — a
    lock-taking finalizer deadlocks the process. The release enqueues
    lock-free and the next ledger operation drains it."""
    key = str(mx.cpu())
    a = mx.nd.zeros((16,))
    base = telemetry.ledger()[key]["alive_bytes"]
    with telemetry._lock:
        del a
        gc.collect()          # finalizer fires while WE hold the lock
    assert telemetry.ledger()[key]["alive_bytes"] == base - 16 * 4


def test_storage_ledger_report():
    from mxnet_tpu.storage import Storage
    a = mx.nd.zeros((8, 8))
    rep = Storage.ledger_report()
    assert str(mx.cpu()) in rep["contexts"]
    assert isinstance(rep["top_buffers"], list)
    json.dumps(rep)                                  # artifact-safe
    del a


# ---------------------------------------------------------------------------
# Enriched OOM errors
# ---------------------------------------------------------------------------

def test_oom_enriched_with_ledger_and_card(monkeypatch):
    ex = _mlp().simple_bind(ctx=mx.cpu(), grad_req="write", type_dict={},
                            data=(8, 16), softmax_label=(8,))
    ex.forward(is_train=False)                       # compile for real
    hog = mx.nd.zeros((512, 512))                    # a nameable suspect

    fake = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                        "to allocate 9123456 bytes.")

    def boom(self, fn, args):
        raise fake

    monkeypatch.setattr(_ex._InstrumentedProgram, "_invoke", boom)
    with pytest.raises(_ex.DeviceMemoryError) as ei:
        ex.forward(is_train=False)
    msg = str(ei.value)
    assert "RESOURCE_EXHAUSTED" in msg               # original text kept
    assert "program memory card" in msg and "peak_bytes" in msg
    assert "live device-buffer ledger" in msg
    assert "top live buffers" in msg and "(512, 512)" in msg
    assert ei.value.__cause__ is fake
    del hog


def test_non_oom_errors_pass_through(monkeypatch):
    ex = _mlp().simple_bind(ctx=mx.cpu(), grad_req="write", type_dict={},
                            data=(8, 16), softmax_label=(8,))
    ex.forward(is_train=False)

    def boom(self, fn, args):
        raise RuntimeError("some unrelated backend failure")

    monkeypatch.setattr(_ex._InstrumentedProgram, "_invoke", boom)
    with pytest.raises(RuntimeError, match="unrelated"):
        ex.forward(is_train=False)


# ---------------------------------------------------------------------------
# Graceful degradation
# ---------------------------------------------------------------------------

def test_cards_degrade_when_analysis_unavailable(monkeypatch):
    """cost_analysis/memory_analysis raising (older jaxlib, platform
    quirks) must yield a card with None figures — and dispatch must
    still work."""
    def no_analysis(compiled):
        raise NotImplementedError("not on this backend")

    monkeypatch.setattr(_ex, "_compiled_cost", no_analysis)
    monkeypatch.setattr(_ex, "_compiled_memory", no_analysis)
    ex = _mlp().simple_bind(ctx=mx.cpu(), grad_req="write", type_dict={},
                            data=(8, 16), softmax_label=(8,))
    outs = ex.forward(is_train=False)
    assert outs and outs[0].shape == (8, 4)
    card = _cards("forward")[0]
    assert card["flops"] is None and card["bytes_accessed"] is None
    assert card["peak_bytes"] is None and card["argument_bytes"] is None
    assert card["dispatches"] == 1
    json.dumps(telemetry.snapshot())


def test_dispatch_survives_aot_compile_failure():
    """lower()/compile() blowing up falls back to the plain jitted
    callable; the card records the fallback, fields stay None."""
    prog = _ex._InstrumentedProgram("forward", lambda x: x * 2.0)

    class _BrokenLower:
        def __init__(self, real):
            self._real = real

        def lower(self, *args):
            raise RuntimeError("AOT not supported here")

        def __call__(self, *args):
            return self._real(*args)

    prog._jitted = _BrokenLower(prog._jitted)
    out = prog(np.ones((3,), np.float32))
    assert float(np.asarray(out).sum()) == 6.0
    card = list(telemetry.programs().values())[0]
    assert "AOT not supported" in card["aot_fallback"]
    assert card["flops"] is None and card["peak_bytes"] is None
    assert card["dispatches"] == 1


# ---------------------------------------------------------------------------
# Online MFU estimate + snapshot serializability
# ---------------------------------------------------------------------------

def test_online_mfu_estimate():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    _fit(mod, _iter(5))
    snap = telemetry.snapshot()
    online = snap["online"]
    assert online["flops_dispatched"] > 0
    assert online["step_time_s"] > 0
    assert online["model_flops_per_s"] > 0
    assert online["mfu"] is None                     # no ceiling known
    telemetry.set_peak_flops(1e12)
    try:
        online = telemetry.snapshot()["online"]
        assert online["peak_flops"] == 1e12
        expected = online["flops_dispatched"] / online["step_time_s"] / 1e12
        assert online["mfu"] == pytest.approx(expected, rel=1e-3)
    finally:
        telemetry.set_peak_flops(None)


def test_snapshot_json_serializable_end_to_end():
    import jax
    n = min(8, jax.device_count())
    mod = mx.mod.Module(_mlp(), context=[mx.cpu(i) for i in range(n)])
    _fit(mod, _iter(3))
    blob = json.dumps(mod.telemetry_snapshot())
    parsed = json.loads(blob)
    assert parsed["programs"] and parsed["online"]["flops_dispatched"] > 0


# ---------------------------------------------------------------------------
# TelemetryLogger programs mode
# ---------------------------------------------------------------------------

def test_telemetry_logger_programs_mode(caplog):
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.telemetry"):
        _fit(mod, _iter(4), batch_end_callback=mx.callback.TelemetryLogger(
            frequent=2, programs=True))
    lines = [r.message for r in caplog.records if "program card" in r.message]
    assert lines, "programs=True logged no cards"
    assert any("train_step" in ln and "compile=" in ln and "flops=" in ln
               for ln in lines)
    # each card logged once
    assert len(lines) == len(set(lines))


# ---------------------------------------------------------------------------
# Lint mirror: no raw jax.jit outside the instrumented wrapper
# ---------------------------------------------------------------------------

def test_no_raw_jit_outside_instrumented_wrapper():
    """Tier-1 mirror of the run_checks.sh lint stage, now driving the
    REAL analyzer (mxnet_tpu.analysis jit-site rule) instead of grep:
    every program must compile through _InstrumentedProgram (program
    card, recompile diagnosis, OOM enrichment — and on the serving
    path, the one-compile-per-bucket accounting). Unlike the old grep,
    this resolves import aliases (`from jax import jit as J`) and
    decorator form, package-wide, against the committed grandfather
    baseline."""
    import os
    from mxnet_tpu.analysis import run
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    report = run([os.path.join(root, "mxnet_tpu")],
                 rules=["jit-site"],
                 baseline=os.path.join(root, "tools",
                                       "mxlint_baseline.json"),
                 root=root)
    assert report.clean, [f.render() for f in report.findings]
