"""On-chip end-to-end smokes: Module.fit convergence + hybridized Gluon.

Parity model: reference tests/python/train (convergence gates) run under
the gpu suite. These exercise the REAL accelerator compile+execute path
end to end: whole-graph XLA program, optimizer updates, metric sync.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.io import NDArrayIter


def _toy_data(n=256, d=16, c=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.normal(0, 2, (c, d)).astype(np.float32)
    y = rng.randint(0, c, n)
    x = ((centers[y] + rng.normal(0, 0.5, (n, d))) / 3.0).astype(np.float32)
    return x, y.astype(np.float32)


def test_module_fit_on_tpu():
    x, y = _toy_data()
    train = NDArrayIter(x, y, batch_size=64, shuffle=True)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")
    mod = mx.mod.Module(net, context=mx.tpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=5)
    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    assert score[0][1] > 0.9, "did not converge on TPU: %s" % score


def test_gluon_hybridize_on_tpu():
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import autograd

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.initializer.Xavier(), ctx=mx.tpu())
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.01})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    x, y = _toy_data(128)
    first = last = None
    for epoch in range(8):
        xb = nd.array(x, ctx=mx.tpu())
        yb = nd.array(y, ctx=mx.tpu())
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out, yb)
        loss.backward()
        trainer.step(x.shape[0])
        cur = float(loss.mean().asnumpy())
        first = cur if first is None else first
        last = cur
    assert last < first, (first, last)
