"""On-chip end-to-end smokes: Module.fit convergence + hybridized Gluon.

Parity model: reference tests/python/train (convergence gates) run under
the gpu suite. These exercise the REAL accelerator compile+execute path
end to end: whole-graph XLA program, optimizer updates, metric sync.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd
from mxnet_tpu.io import NDArrayIter


def _toy_data(n=256, d=16, c=4, seed=0):
    rng = np.random.RandomState(seed)
    centers = rng.normal(0, 2, (c, d)).astype(np.float32)
    y = rng.randint(0, c, n)
    x = ((centers[y] + rng.normal(0, 0.5, (n, d))) / 3.0).astype(np.float32)
    return x, y.astype(np.float32)


def test_module_fit_on_tpu():
    x, y = _toy_data()
    train = NDArrayIter(x, y, batch_size=64, shuffle=True)
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")
    mod = mx.mod.Module(net, context=mx.tpu())
    mod.fit(train, optimizer="sgd",
            optimizer_params={"learning_rate": 0.1, "momentum": 0.9},
            initializer=mx.initializer.Xavier(), num_epoch=5)
    score = mod.score(NDArrayIter(x, y, batch_size=64), "acc")
    assert score[0][1] > 0.9, "did not converge on TPU: %s" % score


def test_gluon_hybridize_on_tpu():
    from mxnet_tpu.gluon import nn
    from mxnet_tpu import autograd

    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(4))
    net.initialize(mx.initializer.Xavier(), ctx=mx.tpu())
    net.hybridize()
    trainer = mx.gluon.Trainer(net.collect_params(), "adam",
                               {"learning_rate": 0.01})
    loss_fn = mx.gluon.loss.SoftmaxCrossEntropyLoss()

    x, y = _toy_data(128)
    first = last = None
    for epoch in range(8):
        xb = nd.array(x, ctx=mx.tpu())
        yb = nd.array(y, ctx=mx.tpu())
        with autograd.record():
            out = net(xb)
            loss = loss_fn(out, yb)
        loss.backward()
        trainer.step(x.shape[0])
        cur = float(loss.mean().asnumpy())
        first = cur if first is None else first
        last = cur
    assert last < first, (first, last)


def test_fused_rnn_time_major_on_tpu():
    """Fused sym.RNN (lax.scan over time) compiles and trains on chip in
    its native TNC layout — the round-5 rnn-time-major path."""
    from mxnet_tpu.rnn import FusedRNNCell
    T, N, V, H = 12, 8, 20, 16
    rng = np.random.RandomState(0)
    # next-token = (token + 1) % V
    starts = rng.randint(0, V, N * 8)
    seqs = (starts[:, None] + np.arange(T + 1)[None, :]) % V
    x = seqs[:, :T].T.astype(np.float32)           # (T, N*8)
    lab = seqs[:, 1:].T.astype(np.float32)

    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    emb = sym.Embedding(data, input_dim=V, output_dim=8, name="emb")
    cell = FusedRNNCell(num_hidden=H, num_layers=1, mode="lstm",
                        prefix="l_")
    out, _ = cell.unroll(T, inputs=emb, layout="TNC",
                         merge_outputs=True)
    pred = sym.Reshape(out, shape=(-1, H))
    pred = sym.FullyConnected(pred, num_hidden=V, name="fc")
    net = sym.SoftmaxOutput(pred, sym.Reshape(label, shape=(-1,)),
                            name="softmax")
    class TM(NDArrayIter):
        def next(self):
            b = super().next()
            return type(b)([b.data[0].T], [b.label[0].T], pad=b.pad)
    tm = TM(x.T.reshape(N * 8, T), lab.T.reshape(N * 8, T), batch_size=N)
    mod = mx.mod.Module(net, context=mx.tpu(),
                        data_names=["data"],
                        label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (T, N))],
             label_shapes=[("softmax_label", (T, N))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="adam",
                       optimizer_params={"learning_rate": 0.02})
    for _ in range(6):
        tm.reset()
        for batch in tm:
            mod.forward_backward(batch)
            mod.update()
    tm.reset()
    correct = total = 0
    for batch in tm:
        mod.forward(batch, is_train=False)
        pred = mod.get_outputs()[0].asnumpy().argmax(axis=1)
        labs = batch.label[0].asnumpy().reshape(-1)
        correct += int((pred == labs).sum())
        total += len(labs)
    assert correct / total > 0.8, (correct, total)


def test_conv_lstm_cell_on_tpu():
    """gluon.contrib Conv2DLSTMCell forward+backward compiles on chip."""
    from mxnet_tpu.gluon.contrib.rnn import Conv2DLSTMCell
    from mxnet_tpu import autograd
    cell = Conv2DLSTMCell(input_shape=(2, 6, 6), hidden_channels=3,
                          i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize(ctx=mx.tpu())
    x = nd.array(np.random.rand(2, 4, 2, 6, 6).astype(np.float32),
                 ctx=mx.tpu())
    with autograd.record():
        out, _ = cell.unroll(4, x, layout="NTC", merge_outputs=True)
        loss = (out * out).sum()
    loss.backward()
    g = cell.i2h_weight.grad()
    assert float((g.asnumpy() ** 2).sum()) > 0
