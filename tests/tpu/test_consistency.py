"""TPU-vs-CPU operator consistency (the reference's second-backend oracle,
tests/python/gpu/test_operator_gpu.py + check_consistency).

Each case binds the same symbol on cpu and tpu contexts and compares
forward outputs AND gradients. TPU matmuls default to bf16-ish passes;
tolerances are set for fp32-highest (conftest of the root suite does not
apply here, so set matmul precision explicitly).
"""
import numpy as np
import pytest

import jax

jax.config.update("jax_default_matmul_precision", "highest")

import mxnet_tpu as mx                    # noqa: E402
from mxnet_tpu import sym                 # noqa: E402
from mxnet_tpu.test_utils import check_consistency  # noqa: E402


def _pair(shape_kwargs):
    return [dict(ctx=mx.cpu(), **shape_kwargs),
            dict(ctx=mx.tpu(), **shape_kwargs)]


def test_fully_connected_consistency():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc")
    check_consistency(net, _pair({"data": (8, 32)}))


def test_convolution_consistency():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="conv")
    check_consistency(net, _pair({"data": (2, 3, 16, 16)}))


def test_pooling_consistency():
    data = sym.Variable("data")
    net = sym.Pooling(data, kernel=(2, 2), stride=(2, 2), pool_type="max")
    check_consistency(net, _pair({"data": (2, 4, 16, 16)}))


def test_batchnorm_consistency():
    data = sym.Variable("data")
    net = sym.BatchNorm(data, fix_gamma=False, name="bn")
    check_consistency(net, _pair({"data": (4, 8, 8, 8)}))


def test_activation_softmax_consistency():
    data = sym.Variable("data")
    net = sym.Activation(data, act_type="tanh")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"))
    check_consistency(net, _pair({"data": (8, 10),
                                  "softmax_label": (8,)}))


def test_elemwise_reduce_consistency():
    a = sym.Variable("a")
    net = sym.sum(sym.broadcast_mul(a, a) + a, axis=1)
    check_consistency(net, _pair({"a": (6, 7)}))


def test_deconv_consistency():
    data = sym.Variable("data")
    net = sym.Deconvolution(data, kernel=(2, 2), stride=(2, 2), num_filter=4,
                            name="deconv")
    check_consistency(net, _pair({"data": (2, 3, 8, 8)}))


def test_dot_transpose_consistency():
    a = sym.Variable("a")
    b = sym.Variable("b")
    net = sym.dot(a, b, transpose_b=True)
    check_consistency(net, _pair({"a": (5, 9), "b": (7, 9)}))
