"""TPU test lane: runs on the REAL chip, skipped on CPU-only runs.

The reference validates its second backend by consistency against the
first (tests/python/gpu/test_operator_gpu.py + test_utils.check_consistency
at python/mxnet/test_utils.py:1267); this lane is the TPU analogue.

Run with:
    MXTPU_TEST_PLATFORM=tpu python -m pytest tests/tpu -q

Under the default test run (`pytest tests/`) the root conftest pins the
cpu platform and everything here skips.
"""
import os

import pytest


def _on_accelerator():
    import jax
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


_TPU_LANE_DIR = os.path.dirname(os.path.abspath(__file__))


def pytest_collection_modifyitems(config, items):
    # pytest hands EVERY conftest the whole session's item list — only mark
    # items that actually live under tests/tpu/, or `pytest tests/` would
    # skip the entire suite (round-2 regression).
    if os.environ.get("MXTPU_SWEEP_SELF") == "1":
        return  # cpu-vs-cpu case-spec debugging (test_op_sweep.SELF_MODE)
    if os.environ.get("MXTPU_TEST_PLATFORM") != "tpu" or not _on_accelerator():
        skip = pytest.mark.skip(
            reason="TPU lane: set MXTPU_TEST_PLATFORM=tpu with a chip attached")
        for item in items:
            if str(item.fspath).startswith(_TPU_LANE_DIR + os.sep):
                item.add_marker(skip)
