"""TPU test lane: runs on the REAL chip, skipped on CPU-only runs.

The reference validates its second backend by consistency against the
first (tests/python/gpu/test_operator_gpu.py + test_utils.check_consistency
at python/mxnet/test_utils.py:1267); this lane is the TPU analogue.

Run with:
    MXTPU_TEST_PLATFORM=tpu python -m pytest tests/tpu -q

Under the default test run (`pytest tests/`) the root conftest pins the
cpu platform and everything here skips.
"""
import os

import pytest


def _on_accelerator():
    import jax
    try:
        return any(d.platform != "cpu" for d in jax.devices())
    except RuntimeError:
        return False


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MXTPU_TEST_PLATFORM") != "tpu" or not _on_accelerator():
        skip = pytest.mark.skip(
            reason="TPU lane: set MXTPU_TEST_PLATFORM=tpu with a chip attached")
        for item in items:
            item.add_marker(skip)
