"""TPU-vs-CPU consistency sweep over the ENTIRE op registry.

The reference validates its second backend by importing the whole unittest
op suite under the gpu context (tests/python/gpu/test_operator_gpu.py);
this is the TPU analogue at registry granularity: every registered op def
either has at least one case here (forward compared CPU-vs-TPU, plus
gradients for differentiable ops) or an entry in SKIP with a written
reason. ``test_registry_fully_covered`` enforces the invariant, so a
newly registered op fails the lane until it is covered or skip-listed.

Run (chip): MXTPU_TEST_PLATFORM=tpu python -m pytest tests/tpu/test_op_sweep.py
Case-spec debugging without a chip: MXTPU_SWEEP_SELF=1 compares cpu-vs-cpu.
"""
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

jax.config.update("jax_default_matmul_precision", "highest")

import mxnet_tpu as mx                              # noqa: E402
from mxnet_tpu.ops.registry import _OPS, get_op     # noqa: E402

SELF_MODE = os.environ.get("MXTPU_SWEEP_SELF") == "1"

# Default tolerance: the cross-backend oracle tolerance (see
# mxnet_tpu/test_utils.py check_consistency — reference fp32 tol 1e-3).
RTOL, ATOL = 1e-3, 1e-4

_rs = np.random.RandomState(0)


def F(shape, lo=-2.0, hi=2.0):
    return _rs.uniform(lo, hi, shape).astype(np.float32)


def P(shape, eps=0.5):  # strictly positive
    return (_rs.uniform(0, 1.5, shape) + eps).astype(np.float32)


def I(shape, hi, lo=0):  # integer indices
    return _rs.randint(lo, hi, shape).astype(np.int32)


def SPD(n):
    a = _rs.uniform(-1, 1, (n, n)).astype(np.float32)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


CASES = {}
SKIP = {
    "Custom": "python-callback op; dispatch is backend-independent "
              "(exercised by tests/test_operator.py on CPU)",
    "_random_gamma": "rejection sampler (while_loop); distribution-level "
                     "checks live in tests/test_random.py",
    "_random_poisson": "rejection/iterative sampler; see tests/test_random.py",
    "_random_negative_binomial": "composed iterative sampler; "
                                 "see tests/test_random.py",
    "_random_generalized_negative_binomial": "composed iterative sampler; "
                                             "see tests/test_random.py",
    "_sample_gamma": "rejection sampler; see tests/test_random.py",
    "_sample_poisson": "rejection sampler; see tests/test_random.py",
    "_sample_multinomial": "search-based sampler; see tests/test_random.py",
    "_shuffle": "random permutation; order is PRNG-path dependent, "
                "distribution checked in tests/test_random.py",
    "_linalg_gelqf": "LQ factors are unique only up to signs across "
                     "backends; reconstruction-level check in "
                     "tests/test_operator_extra3.py",
    "_linalg_syevd": "eigenvector sign/order differs across backends; "
                     "reconstruction-level check in "
                     "tests/test_operator_extra3.py",
}


def case(name, arrays, params=None, grad=True, rtol=None, atol=None,
         train=True, label=None):
    CASES.setdefault(name, []).append({
        "arrays": arrays, "params": params or {}, "grad": grad,
        "rtol": RTOL if rtol is None else rtol,
        "atol": ATOL if atol is None else atol,
        "train": train, "label": label or str(len(CASES.get(name, []))),
    })


# ---------------------------------------------------------------------------
# unary elementwise
# ---------------------------------------------------------------------------
for n in ["sin", "cos", "tan", "sinh", "cosh", "tanh", "erf", "exp",
          "expm1", "sigmoid", "relu", "softsign", "square", "negative",
          "degrees", "radians", "abs", "cbrt", "smooth_l1"]:
    case(n, [F((3, 4))])
for n in ["log", "log10", "log2", "sqrt", "rsqrt", "rcbrt", "reciprocal",
          "gamma", "gammaln"]:
    case(n, [P((3, 4))])
for n in ["arcsin", "arccos", "arctanh"]:
    case(n, [F((3, 4), -0.8, 0.8)])
for n in ["arctan", "arcsinh"]:
    case(n, [F((3, 4))])
case("arccosh", [P((3, 4), eps=1.1)])
case("log1p", [F((3, 4), -0.5, 2.0)])
for n in ["sign", "floor", "ceil", "round", "rint", "fix", "trunc",
          "logical_not"]:
    case(n, [F((3, 4))], grad=False)
case("clip", [F((3, 4))], {"a_min": -0.5, "a_max": 0.5})
case("Cast", [F((3, 4))], {"dtype": "int32"}, grad=False)
case("Cast", [I((3, 4), 5)], {"dtype": "float32"}, grad=False,
     label="int2float")
case("BlockGrad", [F((3, 4))], grad=False)
case("_copy", [F((3, 4))])
case("ones_like", [F((3, 4))], grad=False)
case("zeros_like", [F((3, 4))], grad=False)
case("_identity_with_attr_like_rhs", [F((3, 4)), F((3, 4))])

# ---------------------------------------------------------------------------
# binary / scalar elementwise
# ---------------------------------------------------------------------------
A, B = F((2, 3, 4)), F((2, 1, 4))
for n in ["broadcast_add", "broadcast_sub", "broadcast_mul",
          "broadcast_maximum", "broadcast_minimum", "broadcast_hypot"]:
    case(n, [A, B])
case("broadcast_div", [A, P((2, 1, 4))])
case("broadcast_power", [P((2, 3, 4)), F((2, 1, 4), -1.5, 1.5)])
case("broadcast_mod", [A, P((2, 1, 4))], grad=False)
for n in ["broadcast_equal", "broadcast_not_equal", "broadcast_greater",
          "broadcast_greater_equal", "broadcast_lesser",
          "broadcast_lesser_equal"]:
    case(n, [I((2, 3, 4), 3).astype(np.float32),
             I((2, 1, 4), 3).astype(np.float32)], grad=False)
case("elemwise_add", [F((3, 4)), F((3, 4))])
case("elemwise_sub", [F((3, 4)), F((3, 4))])
case("elemwise_mul", [F((3, 4)), F((3, 4))])
case("elemwise_div", [F((3, 4)), P((3, 4))])
case("add_n", [F((3, 4)), F((3, 4)), F((3, 4))], {})

for n in ["_plus_scalar", "_minus_scalar", "_rminus_scalar", "_mul_scalar",
          "_div_scalar", "_rdiv_scalar", "_maximum_scalar",
          "_minimum_scalar", "_hypot_scalar"]:
    case(n, [P((3, 4))], {"scalar": 0.7})
case("_power_scalar", [P((3, 4))], {"scalar": 1.3})
case("_rpower_scalar", [F((3, 4), -1.5, 1.5)], {"scalar": 1.3})
case("_mod_scalar", [F((3, 4))], {"scalar": 0.7}, grad=False)
case("_rmod_scalar", [P((3, 4))], {"scalar": 0.7}, grad=False)
for n in ["_equal_scalar", "_not_equal_scalar", "_greater_scalar",
          "_greater_equal_scalar", "_lesser_scalar",
          "_lesser_equal_scalar"]:
    case(n, [I((3, 4), 3).astype(np.float32)], {"scalar": 1.0}, grad=False)

# ---------------------------------------------------------------------------
# reductions / sorting / argmax
# ---------------------------------------------------------------------------
for n in ["sum", "mean", "max", "min", "prod"]:
    case(n, [F((2, 3, 4))], {"axis": 1})
    case(n, [F((2, 3, 4))], {"axis": (0, 2), "keepdims": True},
         label="multiaxis")
_nan = F((2, 3, 4))
_nan[0, 1, 2] = np.nan
case("nansum", [_nan], {"axis": 1}, grad=False)
case("nanprod", [_nan], {"axis": 1}, grad=False)
case("norm", [F((3, 4))])
for n in ["argmax", "argmin"]:
    case(n, [F((2, 3, 4))], {"axis": 1}, grad=False)
case("argmax_channel", [F((3, 4))], grad=False)
case("argsort", [F((3, 5))], {"axis": 1}, grad=False)
case("sort", [F((3, 5))], {"axis": 1}, grad=False)
case("topk", [F((3, 5))], {"axis": 1, "k": 2}, grad=False)
case("topk", [F((3, 5))], {"axis": 1, "k": 2, "ret_typ": "value"},
     grad=False, label="values")
case("pick", [F((3, 5)), I((3,), 5).astype(np.float32)], {"axis": 1},
     grad=False)

# ---------------------------------------------------------------------------
# shape / movement / indexing
# ---------------------------------------------------------------------------
case("Reshape", [F((2, 3, 4))], {"shape": (6, -1)})
case("Flatten", [F((2, 3, 4))])
case("expand_dims", [F((3, 4))], {"axis": 1})
case("squeeze", [F((3, 1, 4))], {"axis": 1})
case("transpose", [F((2, 3, 4))], {"axes": (1, 0, 2)})
case("SwapAxis", [F((2, 3, 4))], {"dim1": 0, "dim2": 2})
case("slice", [F((4, 5))], {"begin": (0, 1), "end": (2, 4)})
case("slice_axis", [F((4, 5))], {"axis": 1, "begin": 1, "end": 4})
case("slice_like", [F((4, 5)), F((2, 3))], {})
case("tile", [F((2, 3))], {"reps": (2, 2)})
case("repeat", [F((2, 3))], {"repeats": 2, "axis": 1})
case("reverse", [F((3, 4))], {"axis": 1})
case("broadcast_to", [F((1, 4))], {"shape": (3, 4)})
case("broadcast_axis", [F((2, 1, 4))], {"axis": 1, "size": 3})
case("Pad", [F((2, 2, 3, 3))],
     {"mode": "constant", "pad_width": (0, 0, 0, 0, 1, 1, 2, 2)})
case("Pad", [F((2, 2, 3, 3))],
     {"mode": "edge", "pad_width": (0, 0, 0, 0, 1, 1, 2, 2)}, label="edge")
case("Concat", [F((2, 3)), F((2, 4))], {"num_args": 2, "dim": 1})
case("SliceChannel", [F((2, 6))], {"num_outputs": 2, "axis": 1})
case("stack", [F((2, 3)), F((2, 3))], {"num_args": 2, "axis": 1})
case("one_hot", [I((5,), 4)], {"depth": 4}, grad=False)
case("take", [F((5, 3)), I((4,), 5)], {})
case("batch_take", [F((4, 3)), I((4,), 3)], {})
case("gather_nd", [F((4, 5)), I((2, 3), 4)], {})
case("scatter_nd", [F((3,)), I((1, 3), 4)], {"shape": (4,)})
case("_grad_add_nd", [F((3,)), I((1, 3), 4)], {"shape": (4,)}, grad=False)
case("where", [I((3, 4), 2).astype(np.float32), F((3, 4)), F((3, 4))],
     {})
case("Embedding", [I((2, 3), 10), F((10, 4))],
     {"input_dim": 10, "output_dim": 4})

# ---------------------------------------------------------------------------
# creation ops (no tensor inputs)
# ---------------------------------------------------------------------------
case("_zeros", [], {"shape": (2, 3)}, grad=False)
case("_ones", [], {"shape": (2, 3)}, grad=False)
case("_full", [], {"shape": (2, 3), "value": 1.5}, grad=False)
case("_eye", [], {"N": 4, "M": 5, "k": 1}, grad=False)
case("_arange", [], {"start": 0.0, "stop": 5.0, "step": 0.5}, grad=False)

# ---------------------------------------------------------------------------
# linear algebra
# ---------------------------------------------------------------------------
case("dot", [F((3, 4)), F((4, 5))], {})
case("dot", [F((4, 3)), F((4, 5))], {"transpose_a": True}, label="tA")
case("batch_dot", [F((2, 3, 4)), F((2, 4, 5))], {})
case("_linalg_gemm", [F((3, 4)), F((4, 5)), F((3, 5))],
     {"alpha": 1.5, "beta": 0.5})
case("_linalg_gemm2", [F((3, 4)), F((4, 5))], {"alpha": 2.0})
case("_linalg_syrk", [F((3, 4))], {"alpha": 1.0})
case("_linalg_potrf", [SPD(4)], {}, grad=False)
case("_linalg_potri", [SPD(4)], {}, grad=False, rtol=5e-3, atol=5e-4)
case("_linalg_sumlogdiag", [SPD(4)], {})
_tri = np.linalg.cholesky(SPD(4)).astype(np.float32)
case("_linalg_trmm", [_tri, F((4, 3))], {})
case("_linalg_trsm", [_tri, F((4, 3))], {}, grad=False)
case("FullyConnected", [F((4, 6)), F((5, 6)), F((5,))], {"num_hidden": 5})
case("FullyConnected", [F((2, 3, 4)), F((5, 12))],
     {"num_hidden": 5, "no_bias": True}, label="nobias_flatten")

# ---------------------------------------------------------------------------
# neural-network layers
# ---------------------------------------------------------------------------
for act in ["relu", "sigmoid", "tanh", "softrelu", "softsign"]:
    case("Activation", [F((3, 4))], {"act_type": act}, label=act)
case("LeakyReLU", [F((3, 4))], {"act_type": "leaky", "slope": 0.3},
     label="leaky")
case("LeakyReLU", [F((3, 4))], {"act_type": "elu", "slope": 0.3},
     label="elu")
case("LeakyReLU", [F((2, 3, 4, 4)), P((3,), eps=0.1)],
     {"act_type": "prelu"}, label="prelu")
case("LeakyReLU", [F((3, 4))], {"act_type": "rrelu"}, train=False,
     label="rrelu_eval")
CONV_TOL = dict(rtol=1e-3, atol=1e-3)
case("Convolution", [F((2, 3, 8, 8)), F((4, 3, 3, 3)), F((4,))],
     {"kernel": (3, 3), "num_filter": 4, "pad": (1, 1)}, **CONV_TOL)
case("Convolution", [F((2, 8, 8, 3)), F((4, 3, 3, 3)), F((4,))],
     {"kernel": (3, 3), "num_filter": 4, "pad": (1, 1), "layout": "NHWC"},
     label="nhwc", **CONV_TOL)
case("Convolution", [F((2, 4, 8)), F((6, 2, 3))],
     {"kernel": (3,), "num_filter": 6, "num_group": 2, "no_bias": True},
     label="1d_grouped", **CONV_TOL)
case("Convolution", [F((1, 2, 4, 5, 5)), F((3, 2, 2, 2, 2))],
     {"kernel": (2, 2, 2), "num_filter": 3, "no_bias": True,
      "stride": (1, 2, 2)}, label="3d", **CONV_TOL)
case("Deconvolution", [F((2, 3, 6, 6)), F((3, 4, 2, 2))],
     {"kernel": (2, 2), "num_filter": 4, "stride": (2, 2)}, **CONV_TOL)
case("Pooling", [F((2, 3, 8, 8))],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max"})
case("Pooling", [F((2, 3, 8, 8))],
     {"kernel": (3, 3), "stride": (2, 2), "pool_type": "avg",
      "pooling_convention": "full"}, label="avg_full")
case("Pooling", [F((2, 3, 8, 8))],
     {"kernel": (2, 2), "pool_type": "sum"}, label="sum")
case("Pooling", [F((2, 3, 8, 8))],
     {"global_pool": True, "pool_type": "max", "kernel": (1, 1)},
     label="global")
case("Pooling", [F((2, 8, 8, 3))],
     {"kernel": (2, 2), "stride": (2, 2), "pool_type": "max",
      "layout": "NHWC"}, label="nhwc")
case("BatchNorm",
     [F((2, 3, 4, 4)), P((3,)), F((3,)), F((3,)), P((3,))],
     {"fix_gamma": False}, rtol=1e-3, atol=1e-3)
case("BatchNorm",
     [F((2, 3, 4, 4)), P((3,)), F((3,)), F((3,)), P((3,))],
     {"use_global_stats": True, "fix_gamma": False}, label="globalstats",
     rtol=1e-3, atol=1e-3)
case("BatchNorm",
     [F((2, 4, 4, 3)), P((3,)), F((3,)), F((3,)), P((3,))],
     {"fix_gamma": False, "axis": -1}, label="axis_last",
     rtol=1e-3, atol=1e-3)
case("_contrib_BatchNormAddReLU",
     [F((2, 4, 4, 3)), F((2, 4, 4, 3)), P((3,)), F((3,)), F((3,)),
      P((3,))],
     {"fix_gamma": False, "axis": -1}, rtol=1e-3, atol=1e-3)
case("_contrib_BatchNormAddReLU",
     [F((2, 3, 4, 4)), F((2, 3, 4, 4)), P((3,)), F((3,)), F((3,)),
      P((3,))],
     {"fix_gamma": False}, label="nchw_fallback", rtol=1e-3, atol=1e-3)
case("LRN", [F((2, 6, 4, 4))], {"nsize": 3})
case("L2Normalization", [F((2, 3, 4, 4))], {"mode": "instance"})
case("L2Normalization", [F((2, 3, 4, 4))], {"mode": "channel"},
     label="channel")
case("L2Normalization", [F((2, 3, 4, 4))], {"mode": "spatial"},
     label="spatial")
case("InstanceNorm", [F((2, 3, 4, 4)), P((3,)), F((3,))], {})
case("LayerNorm", [F((2, 3, 4)), P((4,)), F((4,))], {})
case("Dropout", [F((3, 4))], {"p": 0.0})
case("Dropout", [F((64, 64))], {"p": 0.5}, label="p05_train")
case("softmax", [F((3, 4))], {"axis": -1})
case("log_softmax", [F((3, 4))], {"temperature": 2.0})
case("SoftmaxActivation", [F((3, 4))], {})
case("SoftmaxActivation", [F((2, 3, 4, 4))], {"mode": "channel"},
     label="channel")
case("softmax_cross_entropy", [F((4, 5)), I((4,), 5).astype(np.float32)],
     {})
case("SoftmaxOutput", [F((4, 5)), I((4,), 5).astype(np.float32)], {})
case("SoftmaxOutput", [F((4, 5)), I((4,), 5).astype(np.float32)],
     {"use_ignore": True, "ignore_label": 0, "normalization": "valid"},
     label="ignore")
case("LinearRegressionOutput", [F((4, 3)), F((4, 3))], {})
case("MAERegressionOutput", [F((4, 3)), F((4, 3))], {})
case("LogisticRegressionOutput", [F((4, 3)), F((4, 3))], {})
case("MakeLoss", [F((4, 3))], {})
case("make_loss", [F((4, 3))], {})
case("SVMOutput", [F((4, 5)), I((4,), 5).astype(np.float32)], {})
case("UpSampling", [F((2, 3, 4, 4))], {"scale": 2, "sample_type": "nearest"})
case("UpSampling", [F((1, 2, 4, 4)), F((2, 1, 4, 4))],
     {"scale": 2, "sample_type": "bilinear", "num_filter": 2,
      "num_args": 2}, label="bilinear", **CONV_TOL)
_seqlen = np.array([3, 2], dtype=np.float32)
case("SequenceLast", [F((4, 2, 3)), _seqlen], {"use_sequence_length": True})
case("SequenceMask", [F((4, 2, 3)), _seqlen],
     {"use_sequence_length": True, "value": -1.0})
case("SequenceReverse", [F((4, 2, 3)), _seqlen],
     {"use_sequence_length": True})

# fused RNN: parameter vector sized per mode (reference rnn-inl.h layout)
_T, _B, _I, _H = 5, 2, 3, 4


def _rnn_nparams(mode_gates):
    return mode_gates * _H * (_I + _H) + mode_gates * 2 * _H


case("RNN", [F((_T, _B, _I)), F((_rnn_nparams(4),)), F((1, _B, _H)),
             F((1, _B, _H))],
     {"state_size": _H, "num_layers": 1, "mode": "lstm"}, rtol=1e-3,
     atol=1e-3)
case("RNN", [F((_T, _B, _I)), F((_rnn_nparams(3),)), F((1, _B, _H))],
     {"state_size": _H, "num_layers": 1, "mode": "gru"}, label="gru",
     rtol=1e-3, atol=1e-3)
case("RNN", [F((_T, _B, _I)), F((_rnn_nparams(1),)), F((1, _B, _H))],
     {"state_size": _H, "num_layers": 1, "mode": "rnn_tanh"},
     label="rnn_tanh", rtol=1e-3, atol=1e-3)

_ctc_label = np.zeros((2, 3), np.float32)
_ctc_label[0, :2] = [1, 2]
_ctc_label[1, :3] = [2, 1, 2]
case("_ctc_loss", [F((2, 6, 4)), _ctc_label], {}, rtol=1e-3, atol=1e-3)

# spatial ops
_rois = np.array([[0, 0, 0, 6, 6], [0, 2, 2, 7, 7]], np.float32)
case("ROIPooling", [F((1, 2, 8, 8)), _rois],
     {"pooled_size": (2, 2), "spatial_scale": 1.0})
_theta = np.array([[1.0, 0.1, 0.0, -0.1, 1.0, 0.0]], np.float32)
case("SpatialTransformer", [F((1, 2, 6, 6)), _theta],
     {"target_shape": (4, 4), "transform_type": "affine",
      "sampler_type": "bilinear"}, rtol=1e-3, atol=1e-3)
case("GridGenerator", [_theta],
     {"transform_type": "affine", "target_shape": (4, 4)})
case("GridGenerator", [F((1, 2, 4, 4), -0.2, 0.2)],
     {"transform_type": "warp"}, label="warp")
case("BilinearSampler", [F((1, 2, 5, 5)), F((1, 2, 4, 4), -0.9, 0.9)], {},
     rtol=1e-3, atol=1e-3)

# detection / flow / signal / quantization set
case("Correlation", [F((1, 2, 6, 6)), F((1, 2, 6, 6))],
     {"kernel_size": 1, "max_displacement": 1, "pad_size": 1}, **CONV_TOL)
case("_contrib_fft", [F((3, 8))], {})
case("_contrib_ifft", [F((3, 16))], {})
case("_contrib_quantize",
     [F((3, 4)), np.array([-2.0], np.float32), np.array([2.0], np.float32)],
     {}, grad=False)
case("_contrib_dequantize",
     [I((3, 4), 255).astype(np.uint8), np.array([-2.0], np.float32),
      np.array([2.0], np.float32)], {}, grad=False)
case("BatchNorm_v1",
     [F((2, 3, 4, 4)), P((3,)), F((3,)), F((3,)), P((3,))],
     {"fix_gamma": False}, rtol=1e-3, atol=1e-3)
case("IdentityAttachKLSparseReg",
     [F((4, 3), 0.1, 0.9), F((3,), 0.3, 0.7)], {})
case("_contrib_DeformableConvolution",
     [F((1, 2, 6, 6)), F((1, 18, 4, 4), -0.3, 0.3), F((2, 2, 3, 3))],
     {"kernel": (3, 3), "num_filter": 2, "no_bias": True}, **CONV_TOL)
_pp_cls = np.abs(F((1, 4, 3, 3)))  # 2 anchors (scales x ratios) -> 2*A chans
case("_contrib_Proposal",
     [_pp_cls, F((1, 8, 3, 3), -0.2, 0.2),
      np.array([[48.0, 48.0, 1.0]], np.float32)],
     {"rpn_pre_nms_top_n": 20, "rpn_post_nms_top_n": 4, "rpn_min_size": 1,
      "scales": (1.0, 2.0), "ratios": (1.0,)}, grad=False)

case("_contrib_MultiProposal",
     [np.abs(F((2, 4, 3, 3))), F((2, 8, 3, 3), -0.2, 0.2),
      np.array([[48.0, 48.0, 1.0], [48.0, 48.0, 1.0]], np.float32)],
     {"rpn_pre_nms_top_n": 20, "rpn_post_nms_top_n": 4, "rpn_min_size": 1,
      "scales": (1.0, 2.0), "ratios": (1.0,)}, grad=False)
_ps_rois = np.array([[0, 1, 1, 8, 8], [0, 2, 0, 10, 7]], np.float32)
case("_contrib_PSROIPooling",
     [F((1, 8, 12, 12)), _ps_rois],
     {"spatial_scale": 0.8, "output_dim": 2, "pooled_size": 2,
      "group_size": 2})
case("_contrib_DeformablePSROIPooling",
     [F((1, 8, 12, 12)), _ps_rois, F((2, 2, 2, 2), -0.2, 0.2)],
     {"spatial_scale": 0.8, "output_dim": 2, "pooled_size": 2,
      "group_size": 2, "part_size": 2, "sample_per_part": 2,
      "trans_std": 0.1})
case("_contrib_count_sketch",
     [F((3, 8)), I((8,), 6).astype(np.float32),
      np.sign(F((8,))).astype(np.float32)], {"out_dim": 6})
case("reshape_like", [F((3, 4)), F((4, 3))])
case("_slice_assign", [F((4, 4)), F((2, 2))],
     {"begin": (1, 1), "end": (3, 3)})
case("_slice_assign_scalar", [F((4, 4))],
     {"scalar": 0.7, "begin": (0, 2), "end": (4, 4)})
case("Crop", [F((2, 3, 6, 6))],
     {"h_w": (4, 4), "offset": (1, 2), "num_args": 1})
case("_CrossDeviceCopy", [F((3, 4))])

# SSD contrib ops
case("_contrib_MultiBoxPrior", [F((1, 3, 8, 8))],
     {"sizes": (0.5, 0.25), "ratios": (1.0, 2.0)}, grad=False)
_anchors = np.clip(_rs.uniform(0, 1, (1, 8, 4)), 0, 1).astype(np.float32)
_anchors[:, :, 2:] = np.clip(_anchors[:, :, :2] + 0.3, 0, 1)
_mb_label = np.full((1, 2, 6), -1, np.float32)
_mb_label[0, 0] = [1, 0.1, 0.1, 0.5, 0.5, 0]
_mb_label[0, 1] = [0, 0.4, 0.4, 0.9, 0.9, 0]
case("_contrib_MultiBoxTarget",
     [_anchors, _mb_label, F((1, 3, 8))], {}, grad=False)
_cls_prob = np.abs(F((1, 3, 8)))
_cls_prob = _cls_prob / _cls_prob.sum(axis=1, keepdims=True)
case("_contrib_MultiBoxDetection",
     [_cls_prob, F((1, 32)), _anchors], {}, grad=False)

# ---------------------------------------------------------------------------
# optimizer update kernels (mutating; compared on outputs, no autograd)
# ---------------------------------------------------------------------------
_W, _G = F((4, 5)), F((4, 5))
case("sgd_update", [_W, _G], {"lr": 0.1, "wd": 0.01}, grad=False)
case("sgd_mom_update", [_W, _G, F((4, 5))],
     {"lr": 0.1, "momentum": 0.9}, grad=False)
case("mp_sgd_update", [_W.astype(np.float16).astype(np.float32), _G,
                       F((4, 5))], {"lr": 0.1}, grad=False)
case("mp_sgd_mom_update", [_W, _G, F((4, 5)), F((4, 5))],
     {"lr": 0.1, "momentum": 0.9}, grad=False)
case("adam_update", [_W, _G, F((4, 5)), P((4, 5))],
     {"lr": 0.01}, grad=False)
case("rmsprop_update", [_W, _G, P((4, 5))], {"lr": 0.01}, grad=False)
case("rmspropalex_update", [_W, _G, P((4, 5)), F((4, 5)), F((4, 5))],
     {"lr": 0.01}, grad=False)
case("ftrl_update", [_W, _G, F((4, 5)), P((4, 5))], {"lr": 0.1},
     grad=False)

# ---------------------------------------------------------------------------
# random ops with transform-based samplers (threefry bits are
# platform-invariant; float transforms compared at oracle tolerance)
# ---------------------------------------------------------------------------
case("_random_uniform", [], {"shape": (64,), "low": -1.0, "high": 2.0},
     grad=False)
case("_random_normal", [], {"shape": (64,), "loc": 1.0, "scale": 2.0},
     grad=False)
case("_random_exponential", [], {"shape": (64,), "lam": 2.0}, grad=False)
case("_sample_uniform", [np.array([0.0, 1.0], np.float32),
                         np.array([1.0, 4.0], np.float32)],
     {"shape": (8,)}, grad=False)
case("_sample_normal", [np.array([0.0, 1.0], np.float32),
                        np.array([1.0, 2.0], np.float32)],
     {"shape": (8,)}, grad=False)
case("_sample_exponential", [np.array([1.0, 2.0], np.float32)],
     {"shape": (8,)}, grad=False)


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------

def _unique_def_names():
    return sorted({op.name for op in _OPS.values()})


def _backends():
    cpu = jax.devices("cpu")[0]
    if SELF_MODE:
        return cpu, cpu
    acc = [d for d in jax.devices() if d.platform != "cpu"]
    return cpu, acc[0]


def _run_case(op, spec, dev):
    arrays = [jax.device_put(np.asarray(a), dev) for a in spec["arrays"]]
    params = dict(spec["params"])

    def call(*arrs):
        kw = dict(params)
        if op.takes_train:
            kw["_train"] = spec["train"]
        if op.takes_rng:
            kw["_rng"] = jax.random.key(7)
        out = op.fn(*arrs, **kw)
        return out if isinstance(out, tuple) else (out,)

    grad_args = [i for i, a in enumerate(arrays)
                 if spec["grad"] and jnp.issubdtype(a.dtype, jnp.floating)]

    def fwd_and_grad(*arrs):
        outs = call(*arrs)
        if not grad_args:
            return outs, ()

        def loss(*ga):
            full = list(arrs)
            for i, g in zip(grad_args, ga):
                full[i] = g
            os_ = call(*full)
            return sum(jnp.sum(o.astype(jnp.float32)) for o in os_
                       if jnp.issubdtype(o.dtype, jnp.floating))

        grads = jax.grad(loss, argnums=tuple(range(len(grad_args))))(
            *[arrs[i] for i in grad_args])
        return outs, grads

    with jax.default_device(dev):
        outs, grads = jax.jit(fwd_and_grad)(*arrays)
    return ([np.asarray(o) for o in outs], [np.asarray(g) for g in grads])


_ALL_PARAMS = [(name, i) for name in sorted(CASES)
               for i in range(len(CASES[name]))]


@pytest.mark.parametrize(
    "name,idx", _ALL_PARAMS,
    ids=["%s:%s" % (n, CASES[n][i]["label"]) for n, i in _ALL_PARAMS])
def test_op_consistency(name, idx):
    op = get_op(name)
    spec = CASES[name][idx]
    cpu, acc = _backends()
    ref_outs, ref_grads = _run_case(op, spec, cpu)
    got_outs, got_grads = _run_case(op, spec, acc)
    assert len(ref_outs) == len(got_outs)
    for k, (r, g) in enumerate(zip(ref_outs, got_outs)):
        np.testing.assert_allclose(
            g, r, rtol=spec["rtol"], atol=spec["atol"], equal_nan=True,
            err_msg="%s output %d" % (name, k))
    for k, (r, g) in enumerate(zip(ref_grads, got_grads)):
        np.testing.assert_allclose(
            g, r, rtol=spec["rtol"], atol=max(spec["atol"], 1e-4),
            equal_nan=True, err_msg="%s grad %d" % (name, k))


def test_registry_fully_covered():
    """Every registered op def is either swept or skip-listed with a reason."""
    names = set(_unique_def_names())
    covered = set(CASES) | set(SKIP)
    missing = sorted(names - covered)
    assert not missing, "ops with no sweep case and no skip reason: %s" \
        % missing
    stale = sorted((set(CASES) | set(SKIP)) - names)
    assert not stale, "sweep entries for unregistered ops: %s" % stale
    overlap = sorted(set(CASES) & set(SKIP))
    assert not overlap, "ops both swept and skip-listed: %s" % overlap
