"""InferType pass + low-precision symbolic binding.

Parity: reference src/executor/infer_graph_attr_pass.cc (InferType) and
tests/python/train/test_dtype.py (fp16 training). On TPU the native low
precision is bf16, so that is the primary case; fp16 is covered for API
parity.
"""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym


def _lenet():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, name="conv1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Pooling(net, kernel=(2, 2), stride=(2, 2), pool_type="max")
    net = sym.Flatten(net)
    net = sym.FullyConnected(net, num_hidden=10, name="fc1")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")


def test_infer_type_default_fp32():
    net = _lenet()
    arg_types, out_types, aux_types = net.infer_type(data=np.float32)
    assert all(t == np.float32 for t in arg_types)
    assert out_types[0] == np.float32


def test_infer_type_propagates_bf16():
    import jax.numpy as jnp
    net = _lenet()
    bf16 = np.dtype(jnp.bfloat16)
    arg_types, out_types, aux_types = net.infer_type(data=bf16)
    by_name = dict(zip(net.list_arguments(), arg_types))
    assert by_name["conv1_weight"] == bf16
    assert by_name["fc1_weight"] == bf16
    assert out_types[0] == bf16


def test_simple_bind_type_dict_bf16():
    import jax.numpy as jnp
    net = _lenet()
    bf16 = np.dtype(jnp.bfloat16)
    ex = net.simple_bind(ctx=mx.cpu(), type_dict={"data": bf16},
                         data=(2, 1, 8, 8), softmax_label=(2,))
    assert ex.arg_dict["data"].dtype == bf16
    assert ex.arg_dict["conv1_weight"].dtype == bf16
    assert ex.grad_dict["conv1_weight"].dtype == bf16
    ex.arg_dict["data"][:] = np.random.uniform(-1, 1, (2, 1, 8, 8))
    ex.arg_dict["conv1_weight"][:] = \
        np.random.uniform(-0.5, 0.5, ex.arg_dict["conv1_weight"].shape)
    ex.arg_dict["fc1_weight"][:] = \
        np.random.uniform(-0.5, 0.5, ex.arg_dict["fc1_weight"].shape)
    ex.arg_dict["softmax_label"][:] = np.array([1, 3])
    outs = ex.forward(is_train=True)
    assert outs[0].dtype == bf16
    ex.backward()
    g = ex.grad_dict["fc1_weight"].asnumpy()
    assert np.isfinite(g.astype(np.float32)).all()


def test_batchnorm_params_stay_fp32_under_bf16():
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=4, no_bias=True,
                          name="conv")
    net = sym.BatchNorm(net, fix_gamma=False, name="bn")
    net = sym.FullyConnected(sym.Flatten(net), num_hidden=3, name="fc")
    ex = net.simple_bind(ctx=mx.cpu(), type_dict={"data": bf16},
                         data=(2, 2, 6, 6))
    assert ex.arg_dict["conv_weight"].dtype == bf16
    # the cudnn BN rule: scale/shift + moving stats pinned to fp32
    assert ex.arg_dict["bn_gamma"].dtype == np.float32
    assert ex.arg_dict["bn_beta"].dtype == np.float32
    assert ex.aux_dict["bn_moving_mean"].dtype == np.float32
    assert ex.aux_dict["bn_moving_var"].dtype == np.float32


def test_infer_type_fp16_api_parity():
    net = _lenet()
    arg_types, out_types, _ = net.infer_type(data=np.float16)
    by_name = dict(zip(net.list_arguments(), arg_types))
    assert by_name["conv1_weight"] == np.float16
    assert out_types[0] == np.float16


def test_module_fit_bf16_converges():
    """bf16 end-to-end Module.fit on a separable toy problem (the reference
    trains fp16 cifar in tests/python/train/test_dtype.py; this is the
    bf16 TPU-native analogue, small enough for the CPU suite)."""
    import jax.numpy as jnp
    bf16 = np.dtype(jnp.bfloat16)
    rs = np.random.RandomState(0)
    n = 256
    x = rs.uniform(-1, 1, (n, 16)).astype(np.float32)
    w_true = rs.uniform(-1, 1, (16, 2)).astype(np.float32)
    y = (x @ w_true).argmax(axis=1).astype(np.float32)

    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=32, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=2, name="fc2")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax", normalization="batch")

    from mxnet_tpu.io import NDArrayIter, DataDesc
    it = NDArrayIter(x, y, batch_size=32, shuffle=True)
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (32, 16), dtype=bf16)],
             label_shapes=[DataDesc("softmax_label", (32,))])
    mod.init_params(mx.initializer.Xavier())
    assert mod._exec.arg_dict["fc1_weight"].dtype == bf16
    # bf16 weights need fp32 master copies for small-update accumulation —
    # the reference's multi_precision / mp_sgd_update contract
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.5,
                                         "multi_precision": True})
    metric = mx.metric.Accuracy()
    for _ in range(6):
        it.reset()
        metric.reset()
        for batch in it:
            mod.forward_backward(batch)
            mod.update()
            mod.update_metric(metric, batch.label)
    assert metric.get()[1] > 0.8, metric.get()
