"""gluon.contrib suite (parity model: reference
tests/python/unittest/test_gluon_contrib.py — conv RNN cell family
shapes, VariationalDropoutCell mask reuse)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.gluon.contrib import rnn as crnn


@pytest.mark.parametrize("cls,dims,gates", [
    (crnn.Conv1DRNNCell, 1, 1),
    (crnn.Conv1DLSTMCell, 1, 4),
    (crnn.Conv1DGRUCell, 1, 3),
    (crnn.Conv2DRNNCell, 2, 1),
    (crnn.Conv2DLSTMCell, 2, 4),
    (crnn.Conv2DGRUCell, 2, 3),
    (crnn.Conv3DRNNCell, 3, 1),
    (crnn.Conv3DLSTMCell, 3, 4),
    (crnn.Conv3DGRUCell, 3, 3),
])
def test_conv_cell_shapes(cls, dims, gates):
    spatial = (6,) * dims
    cell = cls(input_shape=(3,) + spatial, hidden_channels=4,
               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 5, 3, *spatial).astype(np.float32))
    out, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert out.shape == (2, 5, 4) + spatial
    for s in states:
        assert s.shape == (2, 4) + spatial
    assert cell.i2h_weight.shape[0] == gates * 4
    # h2h conv preserves spatial dims by construction
    assert len(states) == (2 if "LSTM" in cls.__name__ else 1)


def test_conv_cell_even_h2h_kernel_rejected():
    with pytest.raises(ValueError):
        crnn.Conv2DLSTMCell(input_shape=(3, 6, 6), hidden_channels=4,
                            i2h_kernel=3, h2h_kernel=2, i2h_pad=1)


def test_conv_lstm_gradients_flow():
    cell = crnn.Conv2DLSTMCell(input_shape=(2, 5, 5), hidden_channels=3,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 4, 2, 5, 5).astype(np.float32))
    with autograd.record():
        out, _ = cell.unroll(4, x, layout="NTC", merge_outputs=True)
        loss = (out * out).sum()
    loss.backward()
    g = cell.i2h_weight.grad()
    assert float((g.asnumpy() ** 2).sum()) > 0
    g2 = cell.h2h_weight.grad()
    assert float((g2.asnumpy() ** 2).sum()) > 0


def test_variational_dropout_mask_constant_across_steps():
    base = gluon.rnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_outputs=0.5)
    cell.initialize()
    x = mx.nd.ones((4, 16))
    states = cell.begin_state(batch_size=4)
    with autograd.record(train_mode=True):
        o1, states = cell(x, states)
        o2, states = cell(x, states)
    # the SAME output mask applies to both steps: zeros line up
    z1 = o1.asnumpy() == 0.0
    z2 = o2.asnumpy() == 0.0
    assert z1.any()
    np.testing.assert_array_equal(z1, z2)

    # reset() resamples; two sequences almost surely get different masks
    cell.reset()
    with autograd.record(train_mode=True):
        o3, _ = cell(x, cell.begin_state(batch_size=4))
    assert not np.array_equal(z1, o3.asnumpy() == 0.0)


def test_variational_dropout_inference_is_identity():
    base = gluon.rnn.LSTMCell(8)
    cell = crnn.VariationalDropoutCell(base, drop_inputs=0.5,
                                       drop_states=0.5, drop_outputs=0.5)
    cell.initialize()
    x = mx.nd.ones((4, 16))
    o_drop, _ = cell(x, cell.begin_state(batch_size=4))
    cell.reset()
    o_base, _ = base(x, base.begin_state(batch_size=4))
    np.testing.assert_allclose(o_drop.asnumpy(), o_base.asnumpy(),
                               rtol=1e-6)


def test_conv_cell_channels_last_layout():
    """conv_layout='NHWC': channels-last data, gates sliced on the last
    axis, state reported channels-last."""
    cell = crnn.Conv2DLSTMCell(input_shape=(6, 6, 3), hidden_channels=4,
                               i2h_kernel=3, h2h_kernel=3, i2h_pad=1,
                               conv_layout="NHWC")
    cell.initialize()
    x = mx.nd.array(np.random.rand(2, 5, 6, 6, 3).astype(np.float32))
    out, states = cell.unroll(5, x, layout="NTC", merge_outputs=True)
    assert out.shape == (2, 5, 6, 6, 4)
    assert states[0].shape == (2, 6, 6, 4)
    info = cell.state_info(batch_size=2)
    assert info[0]["shape"] == (2, 6, 6, 4)


def test_conv_cell_wrong_rank_input_shape_rejected():
    with pytest.raises(ValueError):
        crnn.Conv2DRNNCell(input_shape=(3, 6, 6, 6), hidden_channels=4,
                           i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
    with pytest.raises(ValueError):
        crnn.Conv2DRNNCell(input_shape=(3, 6), hidden_channels=4,
                           i2h_kernel=3, h2h_kernel=3, i2h_pad=1)
