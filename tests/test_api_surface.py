"""Public-API parity sweep: every top-level public function/class the
reference's python frontend defines must resolve on the matching
mxnet_tpu module, or sit in the explicit skip list with a reason
(the frontend analogue of the op-registry sweep in
tests/test_operator_extra3.py)."""
import ast
import os

import pytest

import mxnet_tpu as mx

REF = "/root/reference/python/mxnet"

SKIP = {
    "gluon/data/dataloader.py": {
        # our process mode ships shm descriptors from accelerator-free
        # forked workers (dataloader._proc_worker/_tree_to_shm); the
        # reference's pickler-patching plumbing has no counterpart
        "rebuild_ndarray": "mp transport is shm descriptors, not pickled "
                           "NDArrays",
        "reduce_ndarray": "same",
        "ConnectionWrapper": "same",
        "Queue": "same",
        "default_mp_batchify_fn": "worker batchify is _numpy_batchify "
                                  "(NDArrays cannot exist in the "
                                  "accelerator-free child)",
        "worker_loop": "worker entry is _proc_worker",
    },
}


def _pairs():
    return {
        "ndarray/ndarray.py": mx.nd, "ndarray/utils.py": mx.nd,
        "ndarray/random.py": mx.nd.random, "symbol/symbol.py": mx.sym,
        "io.py": mx.io, "metric.py": mx.metric,
        "optimizer.py": mx.optimizer, "initializer.py": mx.initializer,
        "autograd.py": mx.autograd, "kvstore.py": mx.kv,
        "callback.py": mx.callback, "monitor.py": mx.monitor,
        "profiler.py": mx.profiler, "recordio.py": mx.recordio,
        "visualization.py": mx.visualization, "random.py": mx.random,
        "test_utils.py": mx.test_utils, "image/image.py": mx.image,
        "module/module.py": mx.mod, "module/base_module.py": mx.mod,
        "gluon/block.py": mx.gluon, "gluon/parameter.py": mx.gluon,
        "gluon/trainer.py": mx.gluon, "gluon/loss.py": mx.gluon.loss,
        "gluon/utils.py": mx.gluon.utils,
        "lr_scheduler.py": mx.lr_scheduler, "rnn/rnn_cell.py": mx.rnn,
        "rnn/io.py": mx.rnn, "model.py": mx.model, "executor.py": mx,
        "context.py": mx, "operator.py": mx.operator,
        "gluon/nn/basic_layers.py": mx.gluon.nn,
        "gluon/nn/conv_layers.py": mx.gluon.nn,
        "gluon/rnn/rnn_cell.py": mx.gluon.rnn,
        "gluon/rnn/rnn_layer.py": mx.gluon.rnn,
        "gluon/data/dataset.py": mx.gluon.data,
        "gluon/data/dataloader.py": mx.gluon.data,
        "gluon/data/sampler.py": mx.gluon.data,
        "gluon/data/vision.py": mx.gluon.data.vision,
        "ndarray/sparse.py": mx.nd.sparse,
        "ndarray/linalg.py": mx.nd.linalg,
    }


@pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
def test_python_frontend_surface_complete():
    missing = {}
    for rel, target in _pairs().items():
        tree = ast.parse(open(os.path.join(REF, rel),
                              errors="replace").read())
        names = [n.name for n in tree.body
                 if isinstance(n, (ast.FunctionDef, ast.ClassDef))
                 and not n.name.startswith("_")]
        skips = SKIP.get(rel, {})
        miss = [n for n in names if not hasattr(target, n)
                and n not in skips]
        if miss:
            missing[rel] = miss
    assert not missing, "reference API names unresolved: %s" % missing


# Names whose arity deliberately diverges (each with the reason). The
# check below only asserts the REQUIRED positional call shape, so these
# are genuine divergences, not default-value differences.
ARITY_SKIP = {
    # reference Executor.__init__(handle, symbol, ctx, grad_req,
    # group2ctx) wraps a C handle produced by MXExecutorBind; ours takes
    # the bound arrays directly. Users construct executors through
    # Symbol.bind/simple_bind on both sides (executor.py docstring).
    ("executor.py", "Executor"),
}


def _ref_required_arity(node):
    """Required positional-arg count of a reference def; for a class, of
    its __init__ minus self. None when there is nothing to check (e.g.
    class without explicit __init__)."""
    if isinstance(node, ast.ClassDef):
        init = next((m for m in node.body
                     if isinstance(m, ast.FunctionDef)
                     and m.name == "__init__"), None)
        if init is None:
            return None
        a = init.args
        drop_self = 1
    else:
        a = node.args
        drop_self = 0
    pos = list(getattr(a, "posonlyargs", [])) + list(a.args)
    required = len(pos) - len(a.defaults) - drop_self
    return max(required, 0)


@pytest.mark.skipif(not os.path.isdir(REF), reason="no reference tree")
def test_python_frontend_signatures_accept_reference_arity():
    """Beyond name resolution: every resolved def must ACCEPT a call
    with the reference's required positional arguments (sig.bind — a
    static check, nothing is invoked). Catches stubs like
    ``def foo(): raise`` that hasattr() cannot (round-3 verdict §weak 6)."""
    import inspect
    bad = {}
    for rel, target in _pairs().items():
        tree = ast.parse(open(os.path.join(REF, rel),
                              errors="replace").read())
        skips = SKIP.get(rel, {})
        for node in tree.body:
            if not isinstance(node, (ast.FunctionDef, ast.ClassDef)):
                continue
            name = node.name
            if (name.startswith("_") or name in skips
                    or (rel, name) in ARITY_SKIP):
                continue
            obj = getattr(target, name, None)
            if obj is None:
                continue  # the completeness test reports these
            if not callable(obj):
                bad.setdefault(rel, []).append("%s: not callable" % name)
                continue
            req = _ref_required_arity(node)
            if req is None:
                continue
            try:
                sig = inspect.signature(obj)
            except (ValueError, TypeError):
                continue  # C-level/builtin signature: nothing to check
            try:
                sig.bind(*([None] * req))
            except TypeError as e:
                bad.setdefault(rel, []).append(
                    "%s: reference requires %d positional args, ours "
                    "rejects them (%s; ours: %s)" % (name, req, e, sig))
    assert not bad, "signature arity mismatches: %s" % bad
