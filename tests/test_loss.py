"""Gluon loss suite — parity with reference tests/python/unittest/test_loss.py."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.gluon import loss as gloss


def _np(x):
    return x.asnumpy()


def test_l2_loss():
    pred = mx.nd.array([[1.0, 2.0], [3.0, 4.0]])
    label = mx.nd.array([[1.5, 2.0], [2.0, 4.0]])
    l = gloss.L2Loss()(pred, label)
    expected = 0.5 * ((np.array([[0.5, 0.0], [1.0, 0.0]]) ** 2).mean(axis=1))
    np.testing.assert_allclose(_np(l), expected, rtol=1e-5)


def test_l1_loss():
    pred = mx.nd.array([[1.0, 2.0]])
    label = mx.nd.array([[2.0, 0.0]])
    l = gloss.L1Loss()(pred, label)
    np.testing.assert_allclose(_np(l), [1.5], rtol=1e-5)


def test_softmax_ce_loss():
    pred = mx.nd.array([[1.0, 2.0, 3.0], [3.0, 2.0, 1.0]])
    label = mx.nd.array([2, 0])
    l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
    logits = _np(pred)
    lse = np.log(np.exp(logits).sum(axis=1))
    expected = lse - logits[np.arange(2), [2, 0]]
    np.testing.assert_allclose(_np(l), expected, rtol=1e-5)


def test_softmax_ce_sparse_vs_dense_label():
    pred = mx.nd.uniform(shape=(4, 5))
    label = mx.nd.array([0, 1, 2, 3])
    onehot = mx.nd.one_hot(label, 5)
    l1 = gloss.SoftmaxCrossEntropyLoss(sparse_label=True)(pred, label)
    l2 = gloss.SoftmaxCrossEntropyLoss(sparse_label=False)(pred, onehot)
    np.testing.assert_allclose(_np(l1), _np(l2), rtol=1e-5)


def test_sigmoid_bce():
    pred = mx.nd.array([[0.5, -0.5]])
    label = mx.nd.array([[1.0, 0.0]])
    l = gloss.SigmoidBinaryCrossEntropyLoss()(pred, label)
    p = 1.0 / (1.0 + np.exp(-np.array([0.5, -0.5])))
    expected = -(np.log(p[0]) + np.log(1 - p[1])) / 2.0
    np.testing.assert_allclose(_np(l), [expected], rtol=1e-5)


def test_kl_div():
    pred = mx.nd.log(mx.nd.array([[0.3, 0.7]]))
    label = mx.nd.array([[0.5, 0.5]])
    l = gloss.KLDivLoss(from_logits=True)(pred, label)
    expected = (0.5 * (np.log(0.5) - np.log(0.3))
                + 0.5 * (np.log(0.5) - np.log(0.7))) / 2.0
    np.testing.assert_allclose(_np(l), [expected], rtol=1e-4)


def test_huber_loss():
    pred = mx.nd.array([[0.0, 3.0]])
    label = mx.nd.array([[0.5, 0.0]])
    l = gloss.HuberLoss(rho=1.0)(pred, label)
    expected = (0.5 * 0.25 + (3.0 - 0.5)) / 2.0
    np.testing.assert_allclose(_np(l), [expected], rtol=1e-5)


def test_hinge_loss():
    pred = mx.nd.array([[0.3], [-2.0]])
    label = mx.nd.array([[1.0], [-1.0]])
    l = gloss.HingeLoss()(pred, label)
    np.testing.assert_allclose(_np(l), [0.7, 0.0], rtol=1e-5, atol=1e-6)


def test_loss_weight_and_sample_weight():
    pred = mx.nd.array([[1.0, 1.0], [1.0, 1.0]])
    label = mx.nd.zeros((2, 2))
    base = _np(gloss.L2Loss()(pred, label))
    weighted = _np(gloss.L2Loss(weight=2.0)(pred, label))
    np.testing.assert_allclose(weighted, 2.0 * base, rtol=1e-6)
    sw = mx.nd.array([[1.0], [0.0]])
    sampled = _np(gloss.L2Loss()(pred, label, sw))
    np.testing.assert_allclose(sampled, base * np.array([1.0, 0.0]), rtol=1e-6)


def test_loss_is_differentiable():
    pred = mx.nd.uniform(shape=(3, 4))
    label = mx.nd.array([0, 1, 2])
    pred.attach_grad()
    with mx.autograd.record():
        l = gloss.SoftmaxCrossEntropyLoss()(pred, label)
        total = l.sum()
    total.backward()
    g = pred.grad.asnumpy()
    assert g.shape == (3, 4)
    assert np.abs(g).sum() > 0
    # rows of softmax-CE grad sum to zero
    np.testing.assert_allclose(g.sum(axis=1), np.zeros(3), atol=1e-5)


def test_ctc_loss_runs():
    pred = mx.nd.uniform(shape=(2, 10, 5))  # (N, T, C) — default layout
    label = mx.nd.array([[1, 2, 3, 0], [2, 2, 0, 0]])
    l = gloss.CTCLoss()(pred, label)
    out = _np(l)
    assert out.shape == (2,)
    assert np.all(out > 0)
