"""C predict API suite (parity model: reference c_predict_api usage in
example/image-classification/predict-cpp and amalgamation)."""
import ctypes
import os
import subprocess
import sys

import numpy as np
import pytest

import mxnet_tpu as mx

REPO = os.path.join(os.path.dirname(__file__), "..")
LIB = os.path.join(REPO, "mxnet_tpu", "_lib", "libmxtpu_predict.so")


def _save_tiny_model(tmp_path):
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data=data, num_hidden=3, name="fc")
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, context=mx.cpu())
    mod.bind(data_shapes=[("data", (2, 4))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 0)
    arg_params, _ = mod.get_params()
    return prefix, arg_params


def _expected(arg_params, x):
    w = arg_params["fc_weight"].asnumpy()
    b = arg_params["fc_bias"].asnumpy()
    logits = x.dot(w.T) + b
    e = np.exp(logits - logits.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


@pytest.mark.skipif(not os.path.exists(LIB), reason="native lib not built")
def test_c_predict_in_process(tmp_path):
    prefix, arg_params = _save_tiny_model(tmp_path)
    with open(prefix + "-symbol.json", "rb") as f:
        sym_json = f.read()
    with open(prefix + "-0000.params", "rb") as f:
        params = f.read()

    L = ctypes.CDLL(LIB)
    L.MXPredCreate.restype = ctypes.c_int
    L.MXGetLastError.restype = ctypes.c_char_p

    handle = ctypes.c_void_p()
    keys = (ctypes.c_char_p * 1)(b"data")
    indptr = (ctypes.c_uint * 2)(0, 2)
    shape = (ctypes.c_uint * 2)(2, 4)
    rc = L.MXPredCreate(sym_json, params, len(params), 1, 0, 1, keys,
                        indptr, shape, ctypes.byref(handle))
    assert rc == 0, L.MXGetLastError()

    x = np.random.RandomState(0).uniform(size=(2, 4)).astype(np.float32)
    buf = (ctypes.c_float * x.size)(*x.ravel())
    assert L.MXPredSetInput(handle, b"data", buf, x.size) == 0, \
        L.MXGetLastError()
    assert L.MXPredForward(handle) == 0, L.MXGetLastError()

    shape_data = ctypes.POINTER(ctypes.c_uint)()
    ndim = ctypes.c_uint()
    assert L.MXPredGetOutputShape(handle, 0, ctypes.byref(shape_data),
                                  ctypes.byref(ndim)) == 0
    out_shape = tuple(shape_data[i] for i in range(ndim.value))
    assert out_shape == (2, 3)

    out = (ctypes.c_float * 6)()
    assert L.MXPredGetOutput(handle, 0, out, 6) == 0, L.MXGetLastError()
    got = np.array(out[:6], np.float32).reshape(2, 3)
    np.testing.assert_allclose(got, _expected(arg_params, x), rtol=1e-4,
                               atol=1e-5)
    assert L.MXPredFree(handle) == 0


@pytest.mark.skipif(not os.path.exists(LIB), reason="native lib not built")
def test_c_predict_standalone_program(tmp_path):
    """Compile and run a real C driver against the library — the
    amalgamation/predict-cpp deployment story, no Python host process."""
    import shutil
    cc = shutil.which("gcc") or shutil.which("cc")
    if cc is None:
        pytest.skip("no C compiler")
    prefix, arg_params = _save_tiny_model(tmp_path)

    driver = tmp_path / "driver.c"
    driver.write_text(r'''
#include <stdio.h>
#include <stdlib.h>
typedef unsigned int mx_uint;
typedef void* PredictorHandle;
extern int MXPredCreate(const char*, const void*, int, int, int, mx_uint,
                        const char**, const mx_uint*, const mx_uint*,
                        PredictorHandle*);
extern int MXPredSetInput(PredictorHandle, const char*, const float*,
                          mx_uint);
extern int MXPredForward(PredictorHandle);
extern int MXPredGetOutput(PredictorHandle, mx_uint, float*, mx_uint);
extern int MXPredFree(PredictorHandle);
extern const char* MXGetLastError();

static char* slurp(const char* path, long* size) {
    FILE* f = fopen(path, "rb");
    if (!f) return NULL;
    fseek(f, 0, SEEK_END); *size = ftell(f); fseek(f, 0, SEEK_SET);
    char* buf = (char*)malloc(*size + 1);
    if (fread(buf, 1, *size, f) != (size_t)*size) { fclose(f); return NULL; }
    buf[*size] = 0; fclose(f);
    return buf;
}

int main(int argc, char** argv) {
    long json_size, param_size;
    char* json = slurp(argv[1], &json_size);
    char* params = slurp(argv[2], &param_size);
    if (!json || !params) { printf("io error\n"); return 2; }
    const char* keys[] = {"data"};
    mx_uint indptr[] = {0, 2};
    mx_uint shape[] = {2, 4};
    PredictorHandle h;
    if (MXPredCreate(json, params, (int)param_size, 1, 0, 1, keys, indptr,
                     shape, &h)) {
        printf("create failed: %s\n", MXGetLastError()); return 1;
    }
    float x[8];
    for (int i = 0; i < 8; ++i) x[i] = 0.1f * (float)i;
    if (MXPredSetInput(h, "data", x, 8)) { printf("set failed\n"); return 1; }
    if (MXPredForward(h)) { printf("fwd failed: %s\n", MXGetLastError());
                            return 1; }
    float out[6];
    if (MXPredGetOutput(h, 0, out, 6)) { printf("out failed\n"); return 1; }
    float rowsum = out[0] + out[1] + out[2];
    printf("PRED_OK %.4f %.4f %.4f rowsum=%.4f\n", out[0], out[1], out[2],
           rowsum);
    MXPredFree(h);
    return 0;
}
''')
    exe = str(tmp_path / "driver")
    subprocess.run([cc, str(driver), "-o", exe,
                    "-L" + os.path.dirname(LIB), "-lmxtpu_predict",
                    "-Wl,-rpath," + os.path.dirname(LIB)], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = os.path.abspath(REPO) + os.pathsep + \
        env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, prefix + "-symbol.json",
                        prefix + "-0000.params"], capture_output=True,
                       text=True, timeout=300, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "PRED_OK" in p.stdout
    rowsum = float(p.stdout.split("rowsum=")[1].split()[0])
    assert abs(rowsum - 1.0) < 1e-3  # softmax row sums to 1
