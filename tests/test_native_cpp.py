"""Drive the C++ unit tests (reference tests/cpp/ analogue) through make,
so `pytest tests/` covers the native layer's own assertions too."""
import os
import shutil
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.skipif(shutil.which("g++") is None or
                    shutil.which("make") is None,
                    reason="no native toolchain")
def test_native_cpp_suite():
    rc = subprocess.run(["make", "-s", "testcpp"], cwd=REPO,
                        capture_output=True, text=True, timeout=300)
    assert rc.returncode == 0, rc.stdout[-2000:] + rc.stderr[-2000:]
    assert "ALL NATIVE TESTS PASSED" in rc.stdout
