"""The persisted AOT executable cache (mxnet_tpu/compile_cache.py).

The zero-cold-start contract (ISSUE 6): a program compiled once is
serialized into a content-addressed on-disk store and a later
``_InstrumentedProgram`` (a fresh process in production; a fresh
wrapper here) DESERIALIZES it instead of invoking XLA — and every way
the store can lie (corrupt blob, stale jax/jaxlib version tag, wrong
backend or mesh topology, mangled container) degrades to a fresh
compile with ONE structured warning and a ``compile_cache.reject``
counter bump, never to a wrong answer or an error.
"""
import glob
import json
import logging
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx  # noqa: F401  (backend pin via conftest)
from mxnet_tpu import compile_cache, telemetry
from mxnet_tpu import executor as _ex


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    d = str(tmp_path / "cc")
    monkeypatch.setenv("MXNET_COMPILE_CACHE", d)
    monkeypatch.delenv("MXNET_CARD_CORPUS", raising=False)
    # per-test single-warning window and a clean counter registry
    compile_cache._WARNED.clear()
    telemetry.enable()
    telemetry.reset()
    yield d
    telemetry.reset()


def _fresh_program(graph_key=None):
    """A new instrumented wrapper over the same tiny fn — each instance
    has an empty in-memory signature cache, so a second instance
    models a fresh process against the shared disk store."""
    def fn(x, y):
        return (x @ y) * 2.0 + jnp.sin(x).sum()
    return _ex._InstrumentedProgram("forward", fn, argnames=("x", "y"),
                                   graph_key=graph_key)


def _args():
    return (jnp.arange(12.0).reshape(3, 4), jnp.ones((4, 2)))


def _cc_counters():
    return {k: v for k, v in telemetry.counters().items()
            if k.startswith("compile_cache.")}


def _entry_files(cache_dir):
    return sorted(glob.glob(os.path.join(cache_dir, "*", "*.mxcc")))


def _span_count(name):
    return telemetry.snapshot()["spans"].get(name, {}).get("count", 0)


# ---------------------------------------------------------------------------
# Enablement
# ---------------------------------------------------------------------------

def test_disabled_by_default(monkeypatch):
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    assert compile_cache.cache_dir() is None
    assert not compile_cache.enabled()
    monkeypatch.setenv("MXNET_COMPILE_CACHE", "0")
    assert not compile_cache.enabled()
    # disabled store/load are clean no-ops
    assert compile_cache.store("0" * 64, object()) == 0
    assert compile_cache.load("0" * 64) is None


def test_disabled_cache_leaves_programs_unaffected(monkeypatch):
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    telemetry.enable()
    telemetry.reset()
    prog = _fresh_program()
    out = prog(*_args())
    assert not _cc_counters()
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_args()[0] @ _args()[1]) * 2.0
        + np.sin(np.asarray(_args()[0])).sum(), rtol=1e-6)


# ---------------------------------------------------------------------------
# Store / load round trip
# ---------------------------------------------------------------------------

def test_cold_store_then_warm_deserialize(cache_dir):
    cold = _fresh_program()
    expect = np.asarray(cold(*_args()))
    cc = _cc_counters()
    assert cc.get("compile_cache.miss") == 1
    assert cc.get("compile_cache.store") == 1
    assert cc.get("compile_cache.bytes_written", 0) > 0
    assert len(_entry_files(cache_dir)) == 1
    compiles_before = _span_count("jit_compile")
    assert compiles_before >= 1

    warm = _fresh_program()
    got = np.asarray(warm(*_args()))
    cc = _cc_counters()
    assert cc.get("compile_cache.hit") == 1
    # the warm build added NO jit_compile span — XLA never ran
    assert _span_count("jit_compile") == compiles_before
    assert _span_count("jit_deserialize") == 1
    np.testing.assert_array_equal(got, expect)
    # the card distinguishes the disk hit from a compile
    cards = [c for c in telemetry.programs().values()
             if c.get("source") == "disk_cache"]
    assert len(cards) == 1
    assert cards[0]["compile_ms"] == 0.0
    assert cards[0]["deserialize_ms"] >= 0.0


def test_quick_key_tier_skips_tracing(cache_dir):
    gk = ["testgraph", "fwd", True]
    cold = _fresh_program(graph_key=gk)
    cold(*_args())
    traces = _span_count("jit_trace")
    assert traces >= 1
    warm = _fresh_program(graph_key=gk)
    warm(*_args())
    # the quick-key index resolved before lower(): no new trace span
    assert _span_count("jit_trace") == traces
    assert _span_count("jit_deserialize") == 1
    assert _cc_counters().get("compile_cache.hit") == 1


def test_signature_change_misses(cache_dir):
    cold = _fresh_program()
    cold(*_args())
    other = _fresh_program()
    other(jnp.ones((5, 4)), jnp.ones((4, 2)))   # different shape
    cc = _cc_counters()
    assert cc.get("compile_cache.miss") == 2
    assert cc.get("compile_cache.store") == 2
    assert cc.get("compile_cache.hit") is None


# ---------------------------------------------------------------------------
# Poisoning: every bad entry falls back to a fresh compile with one
# structured warning and a reject counter bump
# ---------------------------------------------------------------------------

def _poison(cache_dir, mutate):
    """Run a cold build, then corrupt its stored entry via
    ``mutate(meta, blob) -> (meta, blob)``."""
    cold = _fresh_program()
    expect = np.asarray(cold(*_args()))
    [path] = _entry_files(cache_dir)
    meta, blob = compile_cache._read_entry(path)
    meta, blob = mutate(meta, blob)
    compile_cache._write_entry(path, meta, blob)
    return expect


def _warm_after_poison(caplog, expect):
    before = _span_count("jit_compile")
    with caplog.at_level(logging.WARNING,
                         logger="mxnet_tpu.compile_cache"):
        warm = _fresh_program()
        got = np.asarray(warm(*_args()))
    # fell back to a FRESH compile, and the answer stayed right
    assert _span_count("jit_compile") == before + 1
    np.testing.assert_array_equal(got, expect)
    return [r for r in caplog.records
            if "compile_cache: rejected" in r.message]


@pytest.mark.parametrize("case", ["corrupt", "version", "mesh"])
def test_poisoned_entry_rejects_once_and_recompiles(
        cache_dir, caplog, case):
    def mutate(meta, blob):
        if case == "corrupt":
            bad = bytearray(blob)
            bad[len(bad) // 2] ^= 0xFF           # flip a payload byte
            return meta, bytes(bad)
        if case == "version":
            meta["jaxlib"] = "0.0.0-stale"       # stale version tag
            return meta, blob
        meta["devices"] = [["tpu", 0], ["tpu", 1],
                           ["tpu", 2], ["tpu", 3]]   # foreign mesh
        return meta, blob

    expect = _poison(cache_dir, mutate)
    warnings = _warm_after_poison(caplog, expect)
    # EXACTLY one structured warning for the poisoned entry
    assert len(warnings) == 1, [r.message for r in warnings]
    cause = {"corrupt": "corrupt", "version": "version",
             "mesh": "mesh"}[case]
    assert "cause=%s" % cause in warnings[0].message
    cc = _cc_counters()
    assert cc.get("compile_cache.reject") == 1
    assert cc.get("compile_cache.reject.%s" % cause) == 1
    assert cc.get("compile_cache.hit") is None


def test_truncated_container_rejects(cache_dir, caplog):
    cold = _fresh_program()
    expect = np.asarray(cold(*_args()))
    [path] = _entry_files(cache_dir)
    with open(path, "wb") as f:
        f.write(b"garbage, not an entry")
    warnings = _warm_after_poison(caplog, expect)
    assert len(warnings) == 1
    assert _cc_counters().get("compile_cache.reject.corrupt") == 1


def test_reject_warns_only_once_across_retries(cache_dir, caplog):
    expect = _poison(cache_dir, lambda m, b: (dict(m, jaxlib="stale"), b))
    with caplog.at_level(logging.WARNING,
                         logger="mxnet_tpu.compile_cache"):
        for _ in range(3):      # three fresh wrappers trip the same entry
            prog = _fresh_program()
            np.testing.assert_array_equal(np.asarray(prog(*_args())),
                                          expect)
    warnings = [r for r in caplog.records
                if "compile_cache: rejected" in r.message]
    assert len(warnings) == 1, [r.message for r in warnings]
    # ...but every attempt still counted
    assert _cc_counters().get("compile_cache.reject") >= 1


def test_mangled_index_reads_as_miss(cache_dir):
    gk = ["g", 1]
    cold = _fresh_program(graph_key=gk)
    cold(*_args())
    [idx] = glob.glob(os.path.join(cache_dir, "index", "*", "*.json"))
    with open(idx, "w") as f:
        f.write("{not json")
    warm = _fresh_program(graph_key=gk)
    warm(*_args())
    # the content-key tier still resolves the program from disk
    assert _cc_counters().get("compile_cache.hit") == 1


# ---------------------------------------------------------------------------
# Donated programs: excluded by default, opt-in round trip
# ---------------------------------------------------------------------------

def _donating_program():
    def step(w, g):
        return w - 0.1 * g
    return _ex._InstrumentedProgram(
        "train_step", step,
        jit_kwargs={"donate_argnums": (0,)}, argnames=("w", "g"))


def test_donated_programs_not_persisted_by_default(cache_dir,
                                                   monkeypatch):
    """Executing a deserialized input-donating executable intermittently
    corrupts the heap on this jaxlib (see compile_cache.persistable) —
    donated programs must stay OFF the persisted tier unless opted in."""
    monkeypatch.delenv("MXNET_COMPILE_CACHE_DONATED", raising=False)
    assert compile_cache.persistable(()) is True
    assert compile_cache.persistable((0,)) is False
    cold = _donating_program()
    cold(jnp.ones((8, 8)), jnp.ones((8, 8)))
    assert not _cc_counters()                    # no store, no miss
    assert _entry_files(cache_dir) == []
    warm = _donating_program()
    out = warm(jnp.ones((8, 8)), jnp.ones((8, 8)))
    np.testing.assert_allclose(np.asarray(out), 0.9)
    assert not _cc_counters()


def test_donated_program_roundtrip_when_opted_in(cache_dir, monkeypatch):
    monkeypatch.setenv("MXNET_COMPILE_CACHE_DONATED", "1")
    assert compile_cache.persistable((0,)) is True
    cold = _donating_program()
    cold(jnp.ones((8, 8)), jnp.ones((8, 8)))
    assert _cc_counters().get("compile_cache.store") == 1
    warm = _donating_program()
    w = jnp.ones((8, 8))
    out = warm(w, jnp.ones((8, 8)))
    assert _cc_counters().get("compile_cache.hit") == 1
    np.testing.assert_allclose(np.asarray(out), 0.9)
    with pytest.raises(RuntimeError):
        _ = np.asarray(w)       # donated buffer really was consumed


# ---------------------------------------------------------------------------
# Corpus store (append-only JSONL)
# ---------------------------------------------------------------------------

def test_corpus_roundtrip(cache_dir):
    path = compile_cache.corpus_path()
    assert path == os.path.join(cache_dir, "card_corpus.jsonl")
    rec = {"kind": "serving", "max_batch": 16, "rows_hist": {"3": 5}}
    assert compile_cache.corpus_append(rec)
    assert compile_cache.corpus_append({"kind": "other", "x": 1})
    got = compile_cache.corpus_records(kind="serving")
    assert got == [rec]
    assert len(compile_cache.corpus_records()) == 2


def test_corpus_env_override_and_disable(tmp_path, monkeypatch):
    monkeypatch.delenv("MXNET_COMPILE_CACHE", raising=False)
    monkeypatch.setenv("MXNET_CARD_CORPUS", str(tmp_path / "c.jsonl"))
    assert compile_cache.corpus_append({"kind": "serving"})
    assert len(compile_cache.corpus_records()) == 1
    monkeypatch.setenv("MXNET_CARD_CORPUS", "0")
    assert compile_cache.corpus_path() is None
    assert not compile_cache.corpus_append({"kind": "serving"})


def test_corpus_skips_mangled_lines(cache_dir):
    path = compile_cache.corpus_path()
    compile_cache.corpus_append({"kind": "serving", "n": 1})
    with open(path, "a") as f:
        f.write("{truncated mid-append\n")   # a killed run's tail
    compile_cache.corpus_append({"kind": "serving", "n": 2})
    recs = compile_cache.corpus_records(kind="serving")
    assert [r["n"] for r in recs] == [1, 2]


def test_corpus_rejects_unserializable(cache_dir):
    assert not compile_cache.corpus_append({"kind": "x",
                                            "bad": object()})


# ---------------------------------------------------------------------------
# Autotune plan round-trips through the corpus (ISSUE 6 acceptance)
# ---------------------------------------------------------------------------

def test_plan_roundtrips_through_corpus(cache_dir):
    from mxnet_tpu.tuner import plan_serving
    rec = {"kind": "serving", "max_batch": 16,
           "rows_hist": {"3": 50, "10": 30, "16": 5},
           "bucket_ms": {"4": {"total_ms": 40.0, "count": 10},
                         "16": {"total_ms": 160.0, "count": 10}},
           "spans": {"serve_d2h": {"total_ms": 100.0, "count": 10},
                     "serve_batch": {"total_ms": 50.0, "count": 10}}}
    compile_cache.corpus_append(rec)
    plan = plan_serving(compile_cache.corpus_records(kind="serving"))
    assert plan is not None
    compile_cache.corpus_append(plan)
    [stored] = compile_cache.corpus_records(kind="autotune_plan")
    assert stored == plan
    # and the plan recomputed from the re-read corpus is the same plan
    again = plan_serving(compile_cache.corpus_records(kind="serving"))
    assert again == plan


def test_untrusted_cache_dir_disables_tier(tmp_path, monkeypatch):
    """Cache entries are pickles: a group/world-writable cache dir must
    disable the persisted tier (another local user could plant
    deserialization payloads at the predictable path)."""
    d = tmp_path / "shared_cc"
    d.mkdir()
    os.chmod(d, 0o777)
    monkeypatch.setenv("MXNET_COMPILE_CACHE", str(d))
    compile_cache._DIR_TRUST.clear()
    try:
        assert not compile_cache.enabled()
        assert compile_cache.load("0" * 64) is None
        telemetry.enable()
        telemetry.reset()
        prog = _fresh_program()
        prog(*_args())                # still compiles and runs fine
        assert not _cc_counters()
        assert _entry_files(str(d)) == []
    finally:
        compile_cache._DIR_TRUST.clear()


def test_owned_private_dir_stays_trusted(tmp_path, monkeypatch):
    d = tmp_path / "own_cc"
    d.mkdir()
    os.chmod(d, 0o700)
    monkeypatch.setenv("MXNET_COMPILE_CACHE", str(d))
    compile_cache._DIR_TRUST.clear()
    try:
        assert compile_cache.enabled()
    finally:
        compile_cache._DIR_TRUST.clear()
