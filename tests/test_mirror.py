"""MXNET_BACKWARD_DO_MIRROR — segmented rematerialisation.

The mirror knob evaluates the op graph in ~sqrt(N) jax.checkpoint
segments (executor.py eval_graph_mirrored ≙ reference
graph_executor.cc:282-305 mirror policy). These tests pin:
  * gradients and BN aux updates identical to the plain path,
  * recompute genuinely emitted (more matmuls in the lowered program),
  * dropout (an RNG op) reproducing the same mask under recompute.
"""
import os

import numpy as np
import pytest

import mxnet_tpu as mx


def _build(with_bn=True, with_dropout=False):
    data = mx.sym.Variable("data")
    h = data
    for i in range(6):
        h = mx.sym.FullyConnected(h, num_hidden=32, name="fc%d" % i)
        if with_bn:
            h = mx.sym.BatchNorm(h, name="bn%d" % i)
        h = mx.sym.Activation(h, act_type="relu")
        if with_dropout:
            h = mx.sym.Dropout(h, p=0.5, name="do%d" % i)
    h = mx.sym.FullyConnected(h, num_hidden=4, name="head")
    return mx.sym.SoftmaxOutput(h, name="softmax")


def _run(sym, mirror, seed=0):
    os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if mirror else "0"
    try:
        rs = np.random.RandomState(seed)
        exe = sym.simple_bind(ctx=mx.cpu(), grad_req="write",
                              data=(8, 16), softmax_label=(8,))
        for name, arr in exe.arg_dict.items():
            if name not in ("data", "softmax_label"):
                arr[:] = rs.normal(0, 0.1, arr.shape).astype(np.float32)
        exe.arg_dict["data"][:] = rs.normal(size=(8, 16)).astype(np.float32)
        exe.arg_dict["softmax_label"][:] = rs.randint(0, 4, 8).astype(
            np.float32)
        exe.forward_backward()
        grads = {n: g.asnumpy() for n, g in exe.grad_dict.items()
                 if g is not None}
        aux = {n: a.asnumpy() for n, a in exe.aux_dict.items()}
        outs = [o.asnumpy() for o in exe.outputs]
        return outs, grads, aux
    finally:
        os.environ["MXNET_BACKWARD_DO_MIRROR"] = "0"


def test_mirror_matches_plain():
    sym = _build(with_bn=True)
    outs_p, grads_p, aux_p = _run(sym, mirror=False)
    outs_m, grads_m, aux_m = _run(sym, mirror=True)
    for a, b in zip(outs_p, outs_m):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)
    assert set(grads_p) == set(grads_m)
    for n in grads_p:
        np.testing.assert_allclose(grads_p[n], grads_m[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)
    # BN moving stats updated identically through the checkpoint
    assert aux_p and set(aux_p) == set(aux_m)
    for n in aux_p:
        np.testing.assert_allclose(aux_p[n], aux_m[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)


def test_mirror_emits_recompute():
    from mxnet_tpu import random as _random

    def lowered_dots(mirror):
        os.environ["MXNET_BACKWARD_DO_MIRROR"] = "1" if mirror else "0"
        try:
            sym = _build(with_bn=False)
            exe = sym.simple_bind(ctx=mx.cpu(), grad_req="write",
                                  data=(8, 16), softmax_label=(8,))
            gn = tuple(n for n in exe._arg_names
                       if exe._grad_req[n] != "null")
            fn = exe._prog.fwd_bwd_fn(True, gn)
            args = {n: a._data for n, a in
                    zip(exe._arg_names, exe.arg_arrays)}
            aux = {n: a._data for n, a in
                    zip(exe._aux_names, exe.aux_arrays)}
            hg = tuple([None] * exe.output_entries_len())
            low = fn.lower(args, aux, _random.take_key(), hg)
            return low.as_text().count("dot_general")
        finally:
            os.environ["MXNET_BACKWARD_DO_MIRROR"] = "0"

    assert lowered_dots(True) > lowered_dots(False)


def test_mirror_dropout_mask_consistent():
    """The recomputed forward must replay the SAME dropout mask the
    original forward drew, or gradients are silently wrong."""
    sym = _build(with_bn=False, with_dropout=True)
    # grads of a dropout net are only self-consistent if the mask is
    # identical between the saved and recomputed forward: verify the
    # mirrored grads match the plain path run with the SAME rng state
    from mxnet_tpu import random as _random
    _random.seed(42)
    _, grads_p, _ = _run(sym, mirror=False)
    _random.seed(42)
    _, grads_m, _ = _run(sym, mirror=True)
    for n in grads_p:
        np.testing.assert_allclose(grads_p[n], grads_m[n], rtol=1e-5,
                                   atol=1e-6, err_msg=n)
