"""Metric suite — parity with reference tests/python/unittest/test_metric.py."""
import numpy as np

import mxnet_tpu as mx


def test_accuracy():
    pred = mx.nd.array([[0.3, 0.7], [0.0, 1.0], [0.4, 0.6]])
    label = mx.nd.array([0, 1, 1])
    m = mx.metric.Accuracy()
    m.update([label], [pred])
    name, val = m.get()
    assert name == "accuracy"
    assert abs(val - 2.0 / 3.0) < 1e-6


def test_top_k_accuracy():
    pred = mx.nd.array([[0.1, 0.2, 0.7], [0.5, 0.4, 0.1]])
    label = mx.nd.array([1, 1])
    m = mx.metric.TopKAccuracy(top_k=2)
    m.update([label], [pred])
    _, val = m.get()
    assert abs(val - 1.0) < 1e-6
    m = mx.metric.TopKAccuracy(top_k=1)
    m.update([label], [pred])
    _, val = m.get()
    assert abs(val - 0.0) < 1e-6


def test_f1():
    pred = mx.nd.array([[0.3, 0.7], [0.8, 0.2], [0.1, 0.9], [0.6, 0.4]])
    label = mx.nd.array([1, 0, 0, 1])
    m = mx.metric.F1()
    m.update([label], [pred])
    _, val = m.get()
    # tp=1 (idx0), fp=1 (idx2), fn=1 (idx3) -> precision=recall=0.5, f1=0.5
    assert abs(val - 0.5) < 1e-6


def test_regression_metrics():
    pred = mx.nd.array([[1.0], [2.0], [3.0]])
    label = mx.nd.array([[1.5], [2.0], [2.0]])
    mae = mx.metric.MAE()
    mae.update([label], [pred])
    assert abs(mae.get()[1] - (0.5 + 0.0 + 1.0) / 3.0) < 1e-6
    mse = mx.metric.MSE()
    mse.update([label], [pred])
    assert abs(mse.get()[1] - (0.25 + 0.0 + 1.0) / 3.0) < 1e-6
    rmse = mx.metric.RMSE()
    rmse.update([label], [pred])
    assert abs(rmse.get()[1] - np.sqrt((0.25 + 0.0 + 1.0) / 3.0)) < 1e-5


def test_perplexity():
    pred = mx.nd.array([[0.25, 0.75], [0.5, 0.5]])
    label = mx.nd.array([1, 0])
    m = mx.metric.Perplexity(ignore_label=None)
    m.update([label], [pred])
    expected = np.exp(-(np.log(0.75) + np.log(0.5)) / 2.0)
    assert abs(m.get()[1] - expected) < 1e-5


def test_cross_entropy_nll():
    pred = mx.nd.array([[0.2, 0.8], [0.9, 0.1]])
    label = mx.nd.array([1, 0])
    ce = mx.metric.CrossEntropy()
    ce.update([label], [pred])
    expected = -(np.log(0.8) + np.log(0.9)) / 2.0
    assert abs(ce.get()[1] - expected) < 1e-5


def test_custom_and_np():
    def feval(label, pred):
        return float(np.abs(label - pred).mean())
    m = mx.metric.np(feval, name="mymae")
    m.update([mx.nd.array([1.0, 2.0])], [mx.nd.array([1.5, 2.5])])
    name, val = m.get()
    assert "mymae" in name
    assert abs(val - 0.5) < 1e-6


def test_composite():
    m = mx.metric.CompositeEvalMetric([mx.metric.Accuracy(), mx.metric.MAE()])
    pred = mx.nd.array([[0.3, 0.7]])
    label = mx.nd.array([1])
    m.update([label], [pred])
    names, vals = m.get()
    assert len(names) == 2 and len(vals) == 2


def test_create_by_name():
    m = mx.metric.create("acc")
    assert isinstance(m, mx.metric.Accuracy)
    m = mx.metric.create("mse")
    assert isinstance(m, mx.metric.MSE)


def test_reset():
    m = mx.metric.Accuracy()
    m.update([mx.nd.array([1])], [mx.nd.array([[0.3, 0.7]])])
    m.reset()
    assert m.num_inst == 0
