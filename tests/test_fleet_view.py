"""Fleet observability suite (ISSUE 18): the shared-flight-dir
artifact discipline (rank-stamped filenames, rank-local throttle — the
two-writer collision regression), the fleet_view merger (per-rank
summaries, dead-rank naming, straggler blame join), the clock-offset
solver (synthetic known-skew round-trip, bounded by one gate-poll
interval), corrupt-dump degradation (named warning; exit 2 only when
ZERO ranks parse), the merged perfetto trace, and peer-postmortem
gathering for the dead_worker cluster view."""
import json
import os
import subprocess
import sys
import time

import pytest

from mxnet_tpu import flight, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))
import fleet_view   # noqa: E402  (stdlib-only CLI module)
import flight_view  # noqa: E402


@pytest.fixture(autouse=True)
def _clean_registry():
    telemetry.enable()
    telemetry.reset()
    flight.configure(None)
    yield
    flight.configure(None)
    telemetry.enable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Synthetic fleet artifacts
# ---------------------------------------------------------------------------

GATE_POLL_S = 0.05      # CollectiveGate default poll — the solver's
                        # documented error bound


def _dump(directory, rank, reason="dead_worker", ts=None, spans=(),
          events=(), counters=None, extra=None, host=None, pid=4000,
          dead_ranks=()):
    rec = {
        "schema": flight_view.SCHEMA_PREFIX + "1",
        "reason": reason,
        "ts": ts if ts is not None else time.time(),
        "pid": pid + rank,
        "process": {"rank": rank, "num_processes": 2,
                    "dead_ranks": list(dead_ranks),
                    "host": host or ("host%d" % rank), "pid": pid + rank},
        "counters": dict(counters or {}),
        "events": list(events),
        "spans": list(spans),
        "online": {"mfu": 0.1 + rank / 100.0},
    }
    if extra is not None:
        rec["extra"] = extra
    path = os.path.join(directory, "postmortem-r%d-%d-001-%s.json"
                        % (rank, pid + rank, reason))
    with open(path, "w") as f:
        json.dump(rec, f)
    return path


def _gate_span(channel, gen, ts, wait_ms=1.0, last_rank=None,
               excess_ms=0.0):
    ctx = {"channel": channel, "generation": gen,
           "wait_ms": round(wait_ms, 3)}
    if last_rank is not None:
        ctx.update({"last_rank": last_rank,
                    "excess_ms": round(excess_ms, 3)})
    return {"name": "gate_wait", "ts": ts, "dur_ms": wait_ms,
            "tid": 1, "ctx": ctx}


def _skewed_fleet(directory, skew_s, n_gens=6):
    """Two ranks recording the same gate crossings; rank 1's clock
    runs ``skew_s`` ahead. Crossing ENDS are the shared instants: both
    ranks leave within a poll of the last arrival, so each rank's
    (ts + dur) for a generation differs only by clock skew + jitter
    inside one poll interval."""
    base = 1000000.0
    spans0, spans1 = [], []
    for gen in range(1, n_gens + 1):
        end = base + gen * 0.5                      # true shared end
        w0, w1 = 40.0, 2.0                          # rank 0 waited
        # a little sub-poll jitter so the solver has to median it out
        j = (gen % 3) * 0.01
        spans0.append(_gate_span("step", gen, end - w0 / 1e3 + j,
                                 wait_ms=w0, last_rank=1,
                                 excess_ms=35.0))
        spans1.append(_gate_span("step", gen,
                                 end + skew_s - w1 / 1e3,
                                 wait_ms=w1, last_rank=1,
                                 excess_ms=35.0))
    straggler_events = [
        {"ts": base + 2.0 + skew_s, "kind": "dist.straggler", "tid": 1,
         "data": {"rank": 1, "channel": "step", "generation": 4,
                  "excess_ms": 35.0, "wait_ms": 2.0, "streak": 3}}]
    _dump(directory, 0, reason="dead_worker", ts=base + 10,
          spans=spans0,
          counters={"heartbeat.gate_wait_ms.step": 240.0,
                    "heartbeat.gate_crossings.step": n_gens},
          extra={"dead_ranks": [1]})
    _dump(directory, 1, reason="worker_abort", ts=base + 9 + skew_s,
          spans=spans1, events=straggler_events,
          counters={"heartbeat.gate_wait_ms.step": 12.0,
                    "heartbeat.gate_crossings.step": n_gens})


# ---------------------------------------------------------------------------
# Satellite 1: shared-flight-dir collision regression — two ranks, one
# directory, rank-stamped filenames, rank-local throttle
# ---------------------------------------------------------------------------

def test_two_ranks_one_flight_dir_no_collision(tmp_path):
    """Two worker processes sharing MXNET_FLIGHT_DIR dump the SAME
    reason back to back: each rank's artifacts are rank-stamped (no
    overwrite), the 1 s per-reason throttle is rank-LOCAL (rank 1's
    dump is not suppressed by rank 0's), and fleet_view reads both."""
    shared = str(tmp_path)
    prog = (
        "import os, sys\n"
        "from mxnet_tpu import flight\n"
        "flight.configure(%r)\n"
        "p1 = flight.postmortem('collide')\n"
        "p2 = flight.postmortem('collide')\n"   # in-throttle: None
        "assert p1 is not None and p2 is None, (p1, p2)\n"
        "print(os.path.basename(p1))\n" % shared)
    procs = []
    for rank in (0, 1):
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   DMLC_RANK=str(rank), DMLC_NUM_WORKER="2")
        procs.append(subprocess.Popen(
            [sys.executable, "-c", prog], stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True, env=env, cwd=ROOT))
    names = []
    for rank, p in enumerate(procs):
        out, _ = p.communicate(timeout=300)
        assert p.returncode == 0, out[-2000:]
        names.append(out.strip().splitlines()[-1])
    assert names[0].startswith("postmortem-r0-")
    assert names[1].startswith("postmortem-r1-")
    dumps = sorted(f for f in os.listdir(shared) if f.endswith(".json"))
    assert len(dumps) == 2, dumps          # one per rank, zero clobbers
    ranks, warnings = fleet_view.load_fleet(shared)
    assert warnings == []
    assert sorted(ranks) == [0, 1]
    for rank, data in ranks.items():
        rec = data["rec"]
        assert rec["reason"] == "collide"
        assert rec["process"]["rank"] == rank


def test_postmortem_filename_and_series_are_rank_stamped(tmp_path):
    flight.configure(str(tmp_path))
    path = flight.postmortem("unit")
    ident = telemetry.process_identity()
    assert os.path.basename(path) == (
        "postmortem-r%d-%d-001-unit.json"
        % (ident["rank"], os.getpid()))
    # the dump's identity block matches the filename stamp
    rec = flight_view.load_dump(path)
    assert rec["process"]["rank"] == ident["rank"]
    assert rec["process"]["host"] == ident["host"]


# ---------------------------------------------------------------------------
# Satellite 3: clock-offset solver — synthetic known-skew round-trip,
# corrupt-dump degradation
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("skew_s", [1.75, -0.6])
def test_clock_offset_round_trip_within_one_poll(tmp_path, skew_s):
    """Two synthetic rank dumps whose clocks differ by a KNOWN skew:
    the solver recovers it within one gate-poll interval (the crossing
    ends it matches are only that well aligned by construction)."""
    _skewed_fleet(str(tmp_path), skew_s)
    ranks, warnings = fleet_view.load_fleet(str(tmp_path))
    assert warnings == []
    ref, offsets, matched = fleet_view.solve_offsets(ranks)
    assert ref == 0
    assert offsets[0] == 0.0
    assert matched[1] == 6
    assert abs(offsets[1] - skew_s) <= GATE_POLL_S
    # applying the offset lands rank 1's crossings on rank 0's
    # timebase to within the same bound
    c0 = fleet_view.gate_crossings(ranks[0]["rec"])
    c1 = fleet_view.gate_crossings(ranks[1]["rec"])
    for key in c0:
        assert abs((c1[key] - offsets[1]) - c0[key]) <= GATE_POLL_S


def test_fleet_summary_names_dead_and_stragglers(tmp_path):
    _skewed_fleet(str(tmp_path), 1.75)
    ranks, warnings = fleet_view.load_fleet(str(tmp_path))
    summary = fleet_view.summarize(ranks, warnings)
    assert summary["schema"] == fleet_view.FLEET_SCHEMA
    assert summary["n_ranks"] == 2
    # dead: union of the worker_abort reason and the survivor's extra
    assert summary["dead_ranks"] == [1]
    # blame join: rank 0's spans attribute their waits to rank 1;
    # rank 1's dist.straggler verdict corroborates
    top = summary["stragglers"][0]
    assert top["rank"] == 1
    assert top["blamed_crossings"] == 6
    assert top["blamed_wait_ms"] == pytest.approx(240.0)
    assert top["straggler_events"] == 1
    rs = summary["ranks"]["0"]
    assert rs["host"] == "host0"
    assert rs["gate_wait_ms"] == {"step": 240.0}
    assert rs["crossings"] == {"step": 6}
    assert rs["mfu"] == pytest.approx(0.10)


def test_corrupt_dump_degrades_to_named_warning(tmp_path, capsys):
    """A malformed per-rank dump must not take the fleet view down:
    the rank is skipped with a warning NAMING the file, the remaining
    ranks still merge, and the exit code stays 0. Only a fleet with
    zero parseable ranks exits 2."""
    _skewed_fleet(str(tmp_path), 0.5)
    bad = os.path.join(str(tmp_path), "postmortem-r2-9999-001-x.json")
    with open(bad, "w") as f:
        f.write("{\"schema\": \"mxnet_tpu.flight/1\", \"reason\":")
    rc = fleet_view.main(["fleet_view.py", str(tmp_path), "--json"])
    captured = capsys.readouterr()
    assert rc == 0
    summary = json.loads(captured.out)
    assert summary["n_ranks"] == 2          # rank 2 skipped
    assert any("postmortem-r2" in w for w in summary["warnings"])
    assert "postmortem-r2" in captured.err  # named on stderr too


def test_zero_parseable_ranks_exits_2(tmp_path, capsys):
    bad = os.path.join(str(tmp_path), "postmortem-r0-1-001-x.json")
    with open(bad, "w") as f:
        f.write("not json")
    assert fleet_view.main(["fleet_view.py", str(tmp_path)]) == 2
    assert "no parseable rank dumps" in capsys.readouterr().err
    # empty dir: same verdict
    empty = os.path.join(str(tmp_path), "empty")
    os.makedirs(empty)
    assert fleet_view.main(["fleet_view.py", empty]) == 2
    # bad usage
    assert fleet_view.main(["fleet_view.py"]) == 2
    assert fleet_view.main(["fleet_view.py", str(tmp_path),
                            "--bogus"]) == 2


# ---------------------------------------------------------------------------
# Merged trace
# ---------------------------------------------------------------------------

def test_merged_trace_tracks_offsets_and_gate_flows(tmp_path):
    _skewed_fleet(str(tmp_path), 1.75)
    ranks, _ = fleet_view.load_fleet(str(tmp_path))
    trace = fleet_view.merged_trace(ranks)
    evs = trace["traceEvents"]
    # one labelled process track per rank; the dead one is marked
    pnames = {e["pid"]: e["args"]["name"] for e in evs
              if e.get("ph") == "M" and e["name"] == "process_name"}
    assert pnames[0] == "rank 0 (host0)"
    assert pnames[1] == "rank 1 (host1) [dead]"
    # offset correction: matching crossings land within one poll on
    # the merged (reference) timebase
    ends = {}
    for e in evs:
        if e.get("ph") == "X" and e["name"] == "gate_wait":
            gen = e["args"]["generation"]
            ends.setdefault(gen, {})[e["pid"]] = e["ts"] + e["dur"]
    for gen, per_rank in ends.items():
        assert abs(per_rank[0] - per_rank[1]) <= GATE_POLL_S * 1e6
    # cross-rank flow arrows tie each generation's crossings together
    flows = [e for e in evs if e.get("cat") == "gate"]
    assert {e["ph"] for e in flows} == {"s", "f"}
    assert len([e for e in flows if e["ph"] == "s"]) == 6
    # instant markers for the straggler verdict ride on rank 1's track
    marks = [e for e in evs if e.get("ph") == "i"
             and e["name"] == "dist.straggler"]
    assert marks and marks[0]["pid"] == 1


def test_fleet_view_cli_json_and_trace(tmp_path):
    _skewed_fleet(str(tmp_path), 0.8)
    view = os.path.join(ROOT, "tools", "fleet_view.py")
    trace_out = str(tmp_path / "merged.json")
    proc = subprocess.run(
        [sys.executable, view, str(tmp_path), "--json",
         "--trace", trace_out],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=120)
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["dead_ranks"] == [1]
    assert abs(summary["clock"]["offsets_s"]["1"] - 0.8) <= GATE_POLL_S
    with open(trace_out) as f:
        trace = json.load(f)
    assert trace["metadata"]["reference_rank"] == 0
    # the human render mode works on the same dir
    proc = subprocess.run([sys.executable, view, str(tmp_path)],
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True, timeout=120)
    assert proc.returncode == 0
    assert "dead ranks: [1]" in proc.stdout
    assert "straggler ranking" in proc.stdout


# ---------------------------------------------------------------------------
# Tentpole (c): peer-postmortem gathering — the survivor's dead_worker
# dump carries the victim's last seconds
# ---------------------------------------------------------------------------

def test_gather_peer_postmortems_picks_newest_per_peer(tmp_path):
    shared = str(tmp_path)
    _dump(shared, 1, reason="worker_abort", ts=123.0,
          events=[{"ts": 122.9, "kind": "fault.injected", "tid": 1,
                   "data": {"site": "kv_collective"}}])
    # an older dump from the same peer must lose to the newer one
    older = os.path.join(shared, "postmortem-r1-4001-000-early.json")
    with open(older, "w") as f:
        json.dump({"schema": "mxnet_tpu.flight/1", "reason": "early",
                   "ts": 1.0, "counters": {}, "events": [],
                   "spans": []}, f)
    t = time.time()
    os.utime(older, (t - 100, t - 100))
    _dump(shared, 0, reason="dead_worker")     # self: excluded
    peers = flight.gather_peer_postmortems(directory=shared,
                                           exclude_rank=0)
    assert len(peers) == 1
    p = peers[0]
    assert p["rank"] == 1
    assert p["reason"] == "worker_abort"
    assert p["events_tail"][-1]["kind"] == "fault.injected"
    # unreadable dir: degrade to empty, never raise
    assert flight.gather_peer_postmortems(
        directory=os.path.join(shared, "absent")) == []


def test_snapshot_and_series_carry_process_identity():
    snap = telemetry.snapshot()
    ident = telemetry.process_identity()
    assert snap["process"] == ident
    assert set(ident) == {"rank", "num_processes", "dead_ranks",
                          "host", "pid"}
    win = flight.series_window(1)
    assert win["process"] == ident
