"""cpp-package: header-only C++ API over the C ABI (parity: reference
cpp-package/include/mxnet-cpp + example/). Compiles and runs the real
C++ training example."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LIB_DIR = os.path.join(REPO, "mxnet_tpu", "_lib")
LIB = os.path.join(LIB_DIR, "libmxtpu_c_api.so")
HEADER_DIR = os.path.join(REPO, "cpp-package", "include")
EXAMPLE = os.path.join(REPO, "cpp-package", "example", "train_lenet.cpp")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="native lib not built")


def _save_lenet_json(tmp_path):
    from test_c_api import _save_lenet_json as _impl
    return _impl(tmp_path)


def test_cpp_train_example(tmp_path):
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    sys.path.insert(0, os.path.dirname(__file__))
    json_path = _save_lenet_json(tmp_path)
    exe = str(tmp_path / "train_lenet")
    subprocess.run([cxx, "-std=c++17", "-I", HEADER_DIR, EXAMPLE, "-o", exe,
                    "-L", LIB_DIR, "-lmxtpu_c_api",
                    "-Wl,-rpath," + LIB_DIR], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, json_path], capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "CPP_TRAIN_OK" in p.stdout, p.stdout
    acc = float(p.stdout.split("acc=")[1].split()[0])
    assert acc > 0.8, p.stdout
