"""cpp-package: header-only C++ API over the C ABI (parity: reference
cpp-package/include/mxnet-cpp + example/). Compiles and runs the real
C++ training example."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
LIB_DIR = os.path.join(REPO, "mxnet_tpu", "_lib")
LIB = os.path.join(LIB_DIR, "libmxtpu_c_api.so")
HEADER_DIR = os.path.join(REPO, "cpp-package", "include")
EXAMPLE = os.path.join(REPO, "cpp-package", "example", "train_lenet.cpp")

pytestmark = pytest.mark.skipif(not os.path.exists(LIB),
                                reason="native lib not built")


def _save_lenet_json(tmp_path):
    from test_c_api import _save_lenet_json as _impl
    return _impl(tmp_path)


def test_cpp_train_example(tmp_path):
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    sys.path.insert(0, os.path.dirname(__file__))
    json_path = _save_lenet_json(tmp_path)
    exe = str(tmp_path / "train_lenet")
    subprocess.run([cxx, "-std=c++17", "-I", HEADER_DIR, EXAMPLE, "-o", exe,
                    "-L", LIB_DIR, "-lmxtpu_c_api",
                    "-Wl,-rpath," + LIB_DIR], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, json_path], capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "CPP_TRAIN_OK" in p.stdout, p.stdout
    acc = float(p.stdout.split("acc=")[1].split()[0])
    assert acc > 0.8, p.stdout


def test_generated_op_wrappers_build_and_train(tmp_path):
    """The registry-generated C++ op surface (mxnet_cpp_ops.hpp, parity:
    reference OpWrapperGenerator.py output): >=50 wrappers generated,
    and a LeNet defined IN C++ from them trains end-to-end over the C
    ABI — no symbol JSON, no Python objects in the driver."""
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    header = os.path.join(HEADER_DIR, "mxnet_cpp_ops.hpp")
    # drift check: regenerate to a TEMP file and diff against the
    # checked-in header — a stale committed header must FAIL, not be
    # silently repaired in place
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    regen = str(tmp_path / "mxnet_cpp_ops.hpp")
    gen = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "cpp-package", "scripts", "gen_op_hpp.py"),
         "--out", regen],
        capture_output=True, text=True, timeout=300, env=env)
    assert gen.returncode == 0, gen.stderr
    assert open(regen).read() == open(header).read(), \
        "checked-in mxnet_cpp_ops.hpp drifted from the registry — " \
        "rerun cpp-package/scripts/gen_op_hpp.py"
    n_wrappers = sum(1 for line in open(header)
                     if line.startswith("inline Symbol "))
    assert n_wrappers >= 50, n_wrappers

    example = os.path.join(REPO, "cpp-package", "example",
                           "train_lenet_ops.cpp")
    exe = str(tmp_path / "train_lenet_ops")
    subprocess.run([cxx, "-std=c++17", "-I", HEADER_DIR, example, "-o", exe,
                    "-L", LIB_DIR, "-lmxtpu_c_api",
                    "-Wl,-rpath," + LIB_DIR], check=True)
    p = subprocess.run([exe], capture_output=True, text=True, timeout=600,
                       env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "CPP_OPS_TRAIN_OK" in p.stdout, p.stdout
    acc = float(p.stdout.split("acc=")[1].split()[0])
    assert acc > 0.8, p.stdout


def test_cpp_train_full_surface(tmp_path):
    """The cpp-package TRAINING classes (mxnet_cpp_train.hpp, parity:
    reference mxnet-cpp optimizer.h/kvstore.h/io.h/metric.h/
    initializer.h/lr_scheduler.h): every registered optimizer descends
    on a quadratic, then an MLP composed from generated op wrappers
    trains via MXDataIter(CSVIter) -> KVStore::Push/Pull with a
    FactorScheduler'd SGD updater, scored by Accuracy."""
    cxx = shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        pytest.skip("no C++ compiler")
    example = os.path.join(REPO, "cpp-package", "example",
                           "train_mlp_full.cpp")
    exe = str(tmp_path / "train_mlp_full")
    subprocess.run([cxx, "-std=c++17", "-I", HEADER_DIR, example, "-o", exe,
                    "-L", LIB_DIR, "-lmxtpu_c_api",
                    "-Wl,-rpath," + LIB_DIR], check=True)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXNET_TPU_FORCE_CPU"] = "1"
    p = subprocess.run([exe, str(tmp_path)], capture_output=True, text=True,
                       timeout=900, env=env)
    assert p.returncode == 0, p.stdout + p.stderr
    assert "CPP_TRAIN_FULL_OK" in p.stdout, p.stdout + p.stderr
    acc = float(p.stdout.split("CPP_TRAIN_FULL_OK acc=")[1].split()[0])
    assert acc > 0.85, p.stdout
