"""Perl binding suite — builds AI::MXNetTPU (XS over the C ABI) and runs
its t/basic.t including the predictor path against a freshly saved
checkpoint (parity model: reference perl-package/ + test.sh)."""
import os
import shutil
import subprocess
import sys

import pytest

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
PKG = os.path.join(REPO, "perl-package", "AI-MXNetTPU")
LIB = os.path.join(REPO, "mxnet_tpu", "_lib", "libmxtpu_c_api.so")


@pytest.mark.skipif(shutil.which("perl") is None, reason="no perl")
@pytest.mark.skipif(not os.path.exists(LIB), reason="native lib not built")
def test_perl_binding_end_to_end(tmp_path):
    build = subprocess.run(["perl", "build.pl"], cwd=PKG,
                           capture_output=True, text=True, timeout=300)
    assert build.returncode == 0, build.stdout + build.stderr

    # a small softmax model for the predictor leg
    import mxnet_tpu as mx
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=6, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=3, name="fc2")
    net = mx.sym.SoftmaxOutput(net, name="softmax")
    mod = mx.mod.Module(net, label_names=["softmax_label"], context=mx.cpu())
    mod.bind(data_shapes=[("data", (1, 4))], for_training=False)
    mod.init_params(mx.initializer.Xavier())
    prefix = str(tmp_path / "model")
    mod.save_checkpoint(prefix, 0)

    env = dict(os.environ)
    env["MXNET_TPU_FORCE_CPU"] = "1"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["MXTPU_PERL_MODEL_PREFIX"] = prefix
    proc = subprocess.run(["perl", os.path.join("t", "basic.t")], cwd=PKG,
                          capture_output=True, text=True, timeout=600,
                          env=env)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "not ok" not in proc.stdout, proc.stdout
    assert "# skip" not in proc.stdout, proc.stdout  # predictor leg ran
    assert proc.stdout.count("\nok ") >= 7, proc.stdout
