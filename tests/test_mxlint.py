"""mxlint: the AST static-analysis suite (ISSUE 8).

Three layers:

1. **fixture corpus** — every rule fires on its seeded-violation file
   under ``tests/lint_fixtures/`` (exactly the seeded findings, at the
   seeded lines — including the aliased ``from jax import jit as J``
   form the old grep lint missed) and stays silent on the compliant
   twin;
2. **framework** — suppression grammar (justification REQUIRED),
   baseline grandfathering, stale-baseline tolerance + pruning, parse
   errors as findings, JSON shape, CLI exit codes;
3. **tier-1 gate lane** — ``python tools/mxlint.py mxnet_tpu tools
   bench.py`` exits 0 with ZERO unsuppressed findings, and the
   ``--json`` artifact banks next to the bench JSONs
   (``$MXTPU_ARTIFACT_DIR/mxlint.json``, default /tmp/mxtpu_artifacts)
   so the lint trajectory is recorded every round.
"""
import functools
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu.analysis import run, ALL_RULE_IDS
from mxnet_tpu.analysis.core import Baseline, SUPPRESSION_RULE

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
MXLINT = os.path.join(ROOT, "tools", "mxlint.py")


def _fixture(name, rules):
    """Report over one fixture file/dir, no baseline."""
    return run([os.path.join(FIXTURES, name)], rules=rules,
               baseline=Baseline(), root=ROOT)


def _lines(report, rule=None):
    return sorted(f.line for f in report.findings
                  if rule is None or f.rule == rule)


# ---------------------------------------------------------------------------
# Fixture corpus: seeded violation fires, compliant twin is silent
# ---------------------------------------------------------------------------

def test_jit_site_fixture_pair():
    rep = _fixture("jit_site_violation.py", ["jit-site"])
    # 6 seeded: direct call, ALIASED `from jax import jit as J` (the
    # form the grep lint walked past), aliased pjit, pmap, decorator,
    # and the @functools.partial(jax.jit, ...) wrap
    assert _lines(rep) == [11, 15, 19, 23, 26, 31], \
        [f.render() for f in rep.findings]
    assert any("decorator" in f.message for f in rep.findings)
    assert any("functools.partial" in f.message for f in rep.findings)
    ok = _fixture("jit_site_ok.py", ["jit-site"])
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_dispatch_hook_fixture_pair():
    rep = _fixture("dispatch_hook_violation.py", ["dispatch-hook"])
    assert _lines(rep) == [8, 12], [f.render() for f in rep.findings]
    ok = _fixture("dispatch_hook_ok.py", ["dispatch-hook"])
    assert ok.clean, [f.render() for f in ok.findings]


def test_lock_discipline_fixture_pair():
    rep = _fixture("lock_discipline_violation.py", ["lock-discipline"])
    # unlocked global read, finalizer-lock (the PR 4 deadlock class),
    # the read+write halves of the unlocked `self._stats[k] = ...`, and
    # a deferred callback defined under the lock but running without it
    assert _lines(rep) == [14, 18, 28, 28, 43, 53], \
        [f.render() for f in rep.findings]
    assert any("weakref.finalize" in f.message for f in rep.findings)
    ok = _fixture("lock_discipline_ok.py", ["lock-discipline"])
    # Condition alias, _locked-suffix helper, lock-free finalizer,
    # __init__ construction, callback re-acquiring where it runs: all
    # clean with zero suppressions
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_host_sync_fixture_pair():
    rep = _fixture("host_sync_violation.py", ["host-sync"])
    # ...including the standalone marker above a DECORATED def (which
    # arms the decorator's line, not the def's)
    assert _lines(rep) == [9, 10, 11, 18], \
        [f.render() for f in rep.findings]
    msgs = " ".join(f.message for f in rep.findings)
    for form in (".asnumpy()", ".wait_to_read()", "np.asarray"):
        assert form in msgs
    ok = _fixture("host_sync_ok.py", ["host-sync"])
    assert ok.clean, [f.render() for f in ok.findings]
    # the one justified disable in the twin is honoured AND recorded
    assert len(ok.suppressed) == 1
    assert ok.suppressed[0][1]          # justification text rides along


def test_donation_fixture_pair():
    rep = _fixture("donation_violation.py", ["donation-safety"])
    # ...including the use after a donation that happens inside an
    # except handler (handler bodies are in the linear statement order)
    assert _lines(rep) == [13, 19, 26, 36], \
        [f.render() for f in rep.findings]
    assert any("loop" in f.message for f in rep.findings)
    ok = _fixture("donation_ok.py", ["donation-safety"])
    assert ok.clean, [f.render() for f in ok.findings]


def test_trace_purity_fixture_pair():
    rep = _fixture("trace_purity_violation.py", ["trace-purity"])
    # telemetry 2 deep, global mutation 3 deep, self mutation via a
    # local-instance method call, wall clock + global RNG in a
    # jit-decorated kernel
    assert _lines(rep) == [26, 33, 42, 48, 49], \
        [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "telemetry" in msgs[26]
    # the 3-deep chain is printed hop by hop
    assert "call chain" in msgs[33]
    assert "level1" in msgs[33] and "level2" in msgs[33]
    assert "wall clock" in msgs[48]
    assert "RNG" in msgs[49]
    ok = _fixture("trace_purity_ok.py", ["trace-purity"])
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_host_sync_transitive_fixture_pair():
    rep = _fixture("host_sync_chain_violation.py", ["host-sync"])
    # both findings anchor at the SINK lines (the .asnumpy /
    # .wait_to_read), not in the hot function; the recursive
    # drain<->fetch pair (an SCC) terminates and still reports
    assert _lines(rep) == [21, 31], [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "hot_loop" in msgs[21] and "log_metrics" in msgs[21]
    assert "call chain" in msgs[21]
    assert "drain" in msgs[31]
    # sink-line anchors: refactoring an intermediate caller must not
    # invalidate a baseline entry (keyed on rule/path/anchor)
    anchors = {f.line: f.anchor for f in rep.findings}
    assert "asnumpy" in anchors[21]
    assert "wait_to_read" in anchors[31]
    # the dynamic cb(out) call was NOT traversed: no third finding
    ok = _fixture("host_sync_chain_ok.py", ["host-sync"])
    # ref edge to the pool resolver + unreachable epoch helper: clean
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_lockset_fixture_pair():
    rep = _fixture("lockset_violation.py", ["lockset"])
    assert _lines(rep) == [30, 33], [f.render() for f in rep.findings]
    # the finding proposes the exact annotation to add, and the locked
    # evidence comes from the ENTRY lockset of the private helper
    # (called only under the lock — no lexical with in _bump)
    for f in rep.findings:
        assert "# guarded by: self._lock" in f.message
    assert any("_bump" in f.message for f in rep.findings)
    ok = _fixture("lockset_ok.py", ["lockset"])
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_donation_interproc_fixture_pair():
    rep = _fixture("donation_interproc_violation.py",
                   ["donation-safety"])
    # NO markers in the fixture: the wrapper's donated params and the
    # factory's returned donating program are both inferred
    assert _lines(rep) == [16, 16, 22, 37], \
        [f.render() for f in rep.findings]
    msgs = " ".join(f.message for f in rep.findings)
    assert "fused_step" in msgs          # param-propagation inference
    assert "upd" in msgs                 # returns-donating inference
    ok = _fixture("donation_interproc_ok.py", ["donation-safety"])
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_thread_race_fixture_pair():
    rep = _fixture("thread_race_violation.py", ["thread-race"])
    # the attr race (write under a thread root reached THROUGH A REF
    # EDGE — _flush escapes as a value) anchors at the racing write;
    # the finalizer-thread global write is the second finding
    assert _lines(rep) == [31, 43], [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    # both witness chains ride in the message, with the registration
    # site named, and the finding proposes the exact annotation
    assert "registered at" in msgs[31]
    assert "_flush" in msgs[31] and "depth" in msgs[31]
    assert "# guarded by: self._lock" in msgs[31]
    assert "finalizer" in msgs[43]
    assert "main thread" in msgs[43]
    assert "# guarded by: _lock" in msgs[43]
    ok = _fixture("thread_race_ok.py", ["thread-race"])
    # locked+annotated attr, lock-free finalizer pending deque with
    # ONE justified disable (the PR 4 pattern): clean
    assert ok.clean, [f.render() for f in ok.findings]
    assert len(ok.suppressed) == 1 and ok.suppressed[0][1]


def test_collective_discipline_fixture_pair():
    rep = _fixture("collective_violation.py", ["collective-discipline"])
    # ungated _host_allgather from a public entry, step-gate guarding
    # a kv exchange (channel mismatch), rank-divergent psum
    assert _lines(rep) == [30, 34, 37], \
        [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "NO CollectiveGate crossing" in msgs[30]
    assert "channel 'kv'" in msgs[34] and "channel 'step'" in msgs[34]
    assert "DIFFERENT collective sequences" in msgs[37]
    assert "psum" in msgs[37] and "rank" in msgs[37]
    ok = _fixture("collective_ok.py", ["collective-discipline"])
    # lexical crossing, ENTRY-gated private helper, gated call to the
    # marked broadcast primitive, rank-arm with no collectives: clean
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_future_lifecycle_fixture_pair():
    rep = _fixture("future_lifecycle_violation.py", ["future-lifecycle"])
    # strand through risky()'s raise edge, double resolve, return-path
    # strand, and two resolvers skipping the request's entered spans
    assert _lines(rep) == [25, 28, 34, 35, 41], \
        [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "UNRESOLVED" in msgs[25] and "risky" in msgs[25]
    assert "raises ValueError" in msgs[25]       # the witness chain
    assert "SECOND time" in msgs[28]
    assert "returns at line 35" in msgs[35]
    assert "entered scopes" in msgs[41] and "span" in msgs[41]
    ok = _fixture("future_lifecycle_ok.py", ["future-lifecycle"])
    # handler-path resolution, sentinel dequeue, transfer to the
    # resolving shed(), done-guarded late resolve: all clean
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_resource_release_fixture_pair():
    rep = _fixture("resource_release_violation.py", ["resource-release"])
    # bare acquire, never-exited span, jumpable exit, tmp without
    # unlink-on-failure, leaked non-daemon thread, jumpable join
    assert _lines(rep) == [21, 27, 32, 39, 47, 52], \
        [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "with _lock" in msgs[21]
    assert "never exits" in msgs[27]
    assert "must_raise" in msgs[32] and "finally" in msgs[32]
    assert "unlink" in msgs[39]
    assert "non-daemon" in msgs[47]
    assert "join" in msgs[52]
    ok = _fixture("resource_release_ok.py", ["resource-release"])
    # with-lock, finally-release, finally-exit, escape-to-owner,
    # unlink-on-failure, daemon thread, finally-join: all clean
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_torn_state_fixture_pair():
    rep = _fixture("torn_state_violation.py", ["torn-state-on-raise"])
    # bump/unbump and set/clear pairs straddling an unguarded boom()
    assert _lines(rep) == [19, 24], [f.render() for f in rep.findings]
    msgs = {f.line: f.message for f in rep.findings}
    assert "self._depth" in msgs[19] and "boom" in msgs[19]
    assert "raises RuntimeError" in msgs[19]     # the witness chain
    assert "self._busy" in msgs[24]
    ok = _fixture("torn_state_ok.py", ["torn-state-on-raise"])
    # finally-restore, guarded call, init-then-publish idiom, lone
    # mutation: all clean
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_every_rule_has_an_exercised_fixture_pair():
    """Meta-test guarding the NEXT rule family from shipping
    fixture-less: every id in ALL_RULE_IDS declares its fixture pair
    (``fixture_basenames``), every declared fixture exists on disk
    with the violation/compliant twin convention, every fixture file
    in the corpus is declared by some rule, and every fixture is
    actually exercised by a test in this file."""
    from mxnet_tpu.analysis.rules import rule_table
    table = rule_table()
    declared = set()
    for rid in ALL_RULE_IDS:
        rule = table[rid]
        names = getattr(rule, "fixture_basenames", ())
        assert names, "rule %s declares no fixtures" % rid
        assert len(names) % 2 == 0 and any(
            "violation" in n for n in names) and any(
            "ok" in n for n in names), (rid, names)
        for n in names:
            assert os.path.exists(os.path.join(FIXTURES, n)), \
                "rule %s: fixture %s missing" % (rid, n)
        declared.update(names)
    on_disk = {n for n in os.listdir(FIXTURES) if n != "README.md"}
    undeclared = on_disk - declared
    assert not undeclared, \
        "fixtures no rule declares (stale?): %s" % sorted(undeclared)
    with open(os.path.abspath(__file__), encoding="utf-8") as f:
        test_src = f.read()
    unexercised = {n for n in on_disk if n not in test_src}
    assert not unexercised, \
        "fixtures never exercised by a test: %s" % sorted(unexercised)


def test_registry_fixture_pair():
    rep = _fixture("registry_violation", ["registry-consistency"])
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 7, [f.render() for f in rep.findings]
    # one undeclared use per registry kind + the uncovered prefix...
    assert any("'d2h_typo'" in m and "SITES" in m for m in msgs)
    assert any("'bad_code'" in m for m in msgs)
    assert any("'serving.requets'" in m for m in msgs)
    assert any("dynamic counter prefix" in m for m in msgs)
    # ...and one unused declaration per registry kind
    assert any("'kv_push'" in m and "never consulted" in m for m in msgs)
    assert any("'group2ctx'" in m and "never constructed" in m
               for m in msgs)
    assert any("'faults.injected.*'" in m and "dead" in m for m in msgs)
    ok = _fixture("registry_ok", ["registry-consistency"])
    assert ok.clean, [f.render() for f in ok.findings]


# ---------------------------------------------------------------------------
# Framework: suppressions, baseline, parse errors
# ---------------------------------------------------------------------------

def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


_VIOLATION_SRC = "import jax\n\n\ndef f(fn):\n    return jax.jit(fn)%s\n"


def test_suppression_requires_justification(tmp_path):
    # bare disable: the finding STILL reports, plus a grammar finding
    p = _write(tmp_path, "bare.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-site")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE], \
        [f.render() for f in rep.findings]
    assert "justification" in rep.findings[0].message \
        or "justification" in rep.findings[1].message


def test_suppression_with_justification_silences(tmp_path):
    p = _write(tmp_path, "just.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-site -- fixture")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert rep.clean
    assert [(f.rule, j) for f, j in rep.suppressed] == \
        [("jit-site", "fixture")]


def test_standalone_suppression_covers_next_line(tmp_path):
    src = ("import jax\n\n\ndef f(fn):\n"
           "    # mxlint: disable=jit-site -- covers the next line\n"
           "    return jax.jit(fn)\n")
    p = _write(tmp_path, "standalone.py", src)
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert rep.clean and len(rep.suppressed) == 1


def test_unknown_rule_in_suppression_is_flagged(tmp_path):
    p = _write(tmp_path, "typo.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-sight -- typo")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE]
    assert any("unknown rule id" in f.message for f in rep.findings)


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    p = _write(tmp_path, "old.py", _VIOLATION_SRC % "")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert len(rep.findings) == 1
    doc = Baseline.render(rep.findings)
    doc["findings"].append({"rule": "jit-site", "path": "gone.py",
                            "anchor": "jax.jit(deleted_code)"})
    bl_path = _write(tmp_path, "bl.json", json.dumps(doc))
    rep2 = run([p], rules=["jit-site"], baseline=bl_path,
               root=str(tmp_path))
    # grandfathered: clean exit, the finding visible as baselined, and
    # the entry whose code no longer exists WARNS instead of erroring
    assert rep2.clean
    assert len(rep2.baselined) == 1
    assert len(rep2.stale_baseline) == 1
    assert rep2.stale_baseline[0]["path"] == "gone.py"
    assert "stale" in rep2.render_text()


def test_baseline_loader_tolerates_garbage(tmp_path):
    p = _write(tmp_path, "v.py", _VIOLATION_SRC % "")
    bl_path = _write(tmp_path, "bad.json", "{not json")
    rep = run([p], rules=["jit-site"], baseline=bl_path,
              root=str(tmp_path))
    # unreadable baseline: warn and lint WITHOUT it — never a crash
    assert len(rep.findings) == 1
    assert any("unreadable" in w for w in rep.warnings)
    bl2 = _write(tmp_path, "odd.json",
                 json.dumps({"findings": [42, {"rule": "jit-site"}]}))
    rep2 = run([p], rules=["jit-site"], baseline=bl2, root=str(tmp_path))
    assert len(rep2.findings) == 1 and len(rep2.warnings) == 2


def test_parse_error_is_a_finding(tmp_path):
    p = _write(tmp_path, "broken.py", "def f(:\n")
    rep = run([p], baseline=Baseline(), root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["parse-error"]


def test_baseline_never_hides_gate_compromising_rules(tmp_path):
    """Neither --update-baseline nor a hand-edited entry may grandfather
    a bare suppression or a parse error — those mean the gate itself is
    compromised and must keep failing until the code is fixed."""
    bare = _write(tmp_path, "bare.py",
                  _VIOLATION_SRC % "   # mxlint: disable=jit-site")
    broken = _write(tmp_path, "broken.py", "def f(:\n")
    rep = run([bare, broken], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE, "parse-error"]
    # render (what --update-baseline writes) drops both forbidden rules
    doc = Baseline.render(rep.findings)
    assert [e["rule"] for e in doc["findings"]] == ["jit-site"]
    # and even a hand-edited baseline listing them cannot hide them
    doc["findings"].extend(
        {"rule": f.rule, "path": f.path, "anchor": f.anchor}
        for f in rep.findings if f.rule != "jit-site")
    bl_path = _write(tmp_path, "bl.json", json.dumps(doc))
    rep2 = run([bare, broken], rules=["jit-site"], baseline=bl_path,
               root=str(tmp_path))
    assert sorted(f.rule for f in rep2.findings) == \
        [SUPPRESSION_RULE, "parse-error"], \
        [f.render() for f in rep2.findings]


def test_registry_duplicate_declaration_is_flagged(tmp_path):
    """Two SITES declarations in one scan (e.g. a fixture mini-registry
    next to the runtime's) must not silently bind an arbitrary one —
    the duplicate is a finding and uses check against the FIRST."""
    a = _write(tmp_path, "a.py",
               'SITES = ("dispatch",)\n\n\ndef go(fire):\n'
               '    fire("dispatch")\n')
    b = _write(tmp_path, "b.py", 'SITES = ("other",)\n')
    rep = run([a, b], rules=["registry-consistency"], baseline=Baseline(),
              root=str(tmp_path))
    msgs = [f.message for f in rep.findings]
    assert any("duplicate SITES" in m for m in msgs), msgs
    # the legitimate use against the first declaration stays clean
    assert not any("not declared" in m for m in msgs), msgs


def test_json_report_shape(tmp_path):
    p = _write(tmp_path, "v.py", _VIOLATION_SRC % "")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    doc = rep.to_dict()
    assert doc["clean"] is False
    assert doc["counts"] == {"jit-site": 1}
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message", "anchor"}
    json.dumps(doc)                      # JSON-serializable end to end


# ---------------------------------------------------------------------------
# CLI: stable exit codes, JSON artifact, baseline update
# ---------------------------------------------------------------------------

def _cli(args, cwd=ROOT):
    return subprocess.run([sys.executable, MXLINT] + args,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True, timeout=300, cwd=cwd)


def test_cli_exit_codes(tmp_path):
    clean = _write(tmp_path, "clean.py", "x = 1\n")
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    assert _cli(["--baseline", "none", clean]).returncode == 0
    proc = _cli(["--baseline", "none", dirty])
    assert proc.returncode == 1
    assert "jit-site" in proc.stdout
    assert _cli(["--no-such-flag", clean]).returncode == 2
    assert _cli(["--baseline", "none",
                 str(tmp_path / "missing.py")]).returncode == 2
    assert _cli(["--rules", "not-a-rule", clean]).returncode == 2
    assert _cli([]).returncode == 2


def test_cli_json_operand_forms(tmp_path):
    clean = _write(tmp_path, "clean.py", "x = 1\n")
    # '-' means stdout: the report prints, nothing named '-' is linted
    proc = _cli(["--baseline", "none", "--json", "-", clean])
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["clean"] is True
    # with no operand the report also goes to stdout
    proc = _cli(["--baseline", "none", "--json", clean])
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["paths"] == [clean]
    # an ambiguous operand (not '-', not *.json, not an existing lint
    # path) is a usage error, never silently linted or guessed at
    proc = _cli(["--baseline", "none", "--json",
                 str(tmp_path / "report.out"), clean])
    assert proc.returncode == 2
    assert "--json operand" in proc.stderr


def test_cli_list_rules():
    proc = _cli(["--list-rules"])
    assert proc.returncode == 0
    assert proc.stdout.split() == list(ALL_RULE_IDS)


def test_cli_update_baseline_prunes_stale(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    bl = str(tmp_path / "bl.json")
    with open(bl, "w") as f:
        json.dump({"findings": [{"rule": "jit-site", "path": "gone.py",
                                 "anchor": "deleted"}]}, f)
    proc = _cli(["--baseline", bl, "--update-baseline", dirty])
    assert proc.returncode == 0, proc.stderr
    with open(bl) as f:
        doc = json.load(f)
    anchors = [e["anchor"] for e in doc["findings"]]
    assert anchors == ["return jax.jit(fn)"]        # stale entry pruned
    # and the refreshed baseline makes the same run clean
    assert _cli(["--baseline", bl, dirty]).returncode == 0


def test_cli_update_baseline_partial_rules_preserves_others(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    bl = str(tmp_path / "bl.json")
    assert _cli(["--baseline", bl,
                 "--update-baseline", dirty]).returncode == 0
    # a dispatch-hook-only refresh must not wipe the jit-site entry the
    # full gate run depends on
    proc = _cli(["--baseline", bl, "--rules", "dispatch-hook",
                 "--update-baseline", dirty])
    assert proc.returncode == 0, proc.stderr
    with open(bl) as f:
        doc = json.load(f)
    assert [e["rule"] for e in doc["findings"]] == ["jit-site"]
    assert _cli(["--baseline", bl, dirty]).returncode == 0


def test_cli_update_baseline_needs_a_file(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    # '--baseline none' disabled the baseline: nothing to rewrite, and
    # silently clobbering the default committed file would be worse
    proc = _cli(["--baseline", "none", "--update-baseline", dirty])
    assert proc.returncode == 2
    assert "no file to write" in proc.stderr


# ---------------------------------------------------------------------------
# Tier-1 gate lane: the whole runtime lints clean, artifact banked
# ---------------------------------------------------------------------------

@functools.lru_cache(maxsize=1)
def _full_repo_gate_run():
    """ONE timed full-repo CLI run shared by the gate lane and the
    wall-time guard (each full mxflow pass costs ~5s of tier-1 budget;
    both tests assert on the same artifact)."""
    import time as _time
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "mxlint.json")
    t0 = _time.monotonic()
    proc = _cli(["--json", art, "mxnet_tpu", "tools", "bench.py"])
    wall = _time.monotonic() - t0
    return proc, wall, art


def test_mxlint_gate_lane():
    """`run_checks.sh lint` equivalent: zero unsuppressed findings over
    mxnet_tpu/ tools/ bench.py against the committed baseline, with the
    JSON report banked next to the bench artifacts."""
    proc, _, art = _full_repo_gate_run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(art) as f:
        doc = json.load(f)
    assert doc["clean"] is True
    assert doc["findings"] == []
    assert doc["rules"] == list(ALL_RULE_IDS)
    # every honoured suppression carries its justification text, and
    # the committed baseline has no stale entries
    assert doc["suppressed"], "expected justified disables in-tree"
    assert all(s["justification"] for s in doc["suppressed"])
    assert doc["stale_baseline"] == []
    # the grandfathered raw-jit sites are visible, not silently gone
    assert any(b["rule"] == "jit-site" for b in doc["baselined"])


def test_gate_catches_a_seeded_regression(tmp_path):
    """End-to-end negative control: drop an aliased-jit file into a
    copy of the scan set and the gate exits 1 — proving the lane fails
    when someone actually adds a raw compile site."""
    bad = _write(tmp_path, "regression.py",
                 "from jax import jit as J\n\n\ndef f(fn):\n"
                 "    return J(fn)\n")
    proc = _cli(["--baseline",
                 os.path.join(ROOT, "tools", "mxlint_baseline.json"),
                 bad])
    assert proc.returncode == 1
    assert "jit-site" in proc.stdout


@pytest.mark.parametrize("fixture,rule", [
    ("trace_purity_violation.py", "trace-purity"),
    ("host_sync_chain_violation.py", "host-sync"),
    ("lockset_violation.py", "lockset"),
    ("donation_interproc_violation.py", "donation-safety"),
    ("thread_race_violation.py", "thread-race"),
    ("collective_violation.py", "collective-discipline"),
    ("future_lifecycle_violation.py", "future-lifecycle"),
    ("resource_release_violation.py", "resource-release"),
    ("torn_state_violation.py", "torn-state-on-raise"),
])
def test_gate_catches_each_interprocedural_seed(fixture, rule):
    """Negative control per NEW rule: each seeded fixture fails the
    CLI gate against the COMMITTED baseline — the lane cannot go green
    on un-fixed interprocedural violations."""
    proc = _cli(["--baseline",
                 os.path.join(ROOT, "tools", "mxlint_baseline.json"),
                 os.path.join(FIXTURES, fixture)])
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert rule in proc.stdout


# ---------------------------------------------------------------------------
# mxflow: call graph, effect summaries, --changed, wall-time guard
# ---------------------------------------------------------------------------

def _project_of(paths, root):
    from mxnet_tpu.analysis.core import Project, iter_python_files
    proj = Project(root=str(root))
    for p in iter_python_files([str(x) for x in paths]):
        proj.add_file(p)
    return proj


def test_callgraph_resolution(tmp_path):
    """Cross-module (absolute AND relative import), self-type method,
    nested-def and local-instance resolution; dynamic calls counted,
    never edged; SCCs detected."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "util.py").write_text(
        "def helper(x):\n    return x\n\n\n"
        "def ping(x):\n    return pong(x)\n\n\n"
        "def pong(x):\n    return ping(x)\n")
    (pkg / "main.py").write_text(
        "from . import util\n"
        "from pkg.util import helper as H\n\n\n"
        "class Engine:\n"
        "    def run(self, x):\n"
        "        return self._step(x)\n\n"
        "    def _step(self, x):\n"
        "        def inner(y):\n"
        "            return util.helper(y)\n"
        "        return inner(H(x))\n\n\n"
        "def drive(x, cb):\n"
        "    e = Engine()\n"
        "    cb(x)\n"
        "    return e.run(x)\n")
    proj = _project_of([pkg], tmp_path)
    g = proj.callgraph()

    def fi(path, qual):
        got = g._by_key.get(("pkg/%s" % path, qual))
        assert got is not None, (path, qual, sorted(g._by_key))
        return got

    def callee_names(f):
        return sorted(c.qualname for c, _l, _c in g.callees(f))

    # relative-import module alias + nested def + aliased from-import
    assert callee_names(fi("main.py", "Engine._step")) == \
        ["Engine._step.inner", "helper"]
    assert callee_names(fi("main.py", "Engine._step.inner")) == ["helper"]
    # self-type method resolution + local-instance constructor typing
    assert callee_names(fi("main.py", "Engine.run")) == ["Engine._step"]
    drive = fi("main.py", "drive")
    assert "Engine.run" in callee_names(drive)
    # cb(x) through a parameter is DYNAMIC: counted, not edged
    assert g.dynamic_calls.get(drive) == 1
    # the ping<->pong recursion is one SCC of size 2
    sccs = [sorted(f.qualname for f in c) for c in g.sccs()]
    assert ["ping", "pong"] in sccs
    stats = g.stats()
    assert stats["functions"] >= 7 and stats["largest_scc"] == 2
    assert stats["cyclic_sccs"] == 1


def test_summary_facts_and_cache(tmp_path):
    """Direct effect facts of one function, and the content-keyed
    facts cache: a second run over the same text is a cache hit."""
    p = tmp_path / "mod.py"
    # NOTE: _LOG is a module global — mutating a PARAMETER's object is
    # deliberately not a fact (the executor's owned-accumulator
    # pattern, a traced root passing its own dict down to be filled,
    # would drown the signal); globals/closures/self are tracked
    p.write_text(
        "import time\n\n_LOG = []\n\n\n"
        "def effects(out):\n"
        "    t = time.time()\n"
        "    out.wait_to_read()\n"
        "    _LOG.append(t)\n"
        "    return t\n")
    proj = _project_of([p], tmp_path)
    g = proj.callgraph()
    summ = proj.summaries()
    (fi,) = [f for f in g.functions if f.name == "effects"]
    facts = summ.facts_of(fi)
    assert [form for _l, _c, form in facts.syncs] == [".wait_to_read()"]
    assert facts.clock and facts.clock[0][1] == "time.time"
    assert any("_LOG.append" in d for _l, d in facts.mutations)
    # second run, same text: served from the facts cache
    from mxnet_tpu.analysis import summaries as sm
    before = sm.cache_stats()
    proj2 = _project_of([p], tmp_path)
    proj2.summaries()
    after = sm.cache_stats()
    assert after["hits"] == before["hits"] + 1
    assert after["misses"] == before["misses"]


def test_changed_subset_expands_to_reverse_dependents(tmp_path):
    """--changed core semantics: linting only a changed CALLEE pulls
    in its callers (their findings depend on its summary); without
    expansion the caller's finding is filtered out."""
    (tmp_path / "util.py").write_text(
        "import jax\n\n\n"
        "def fused(fn, w, s):\n"
        "    step = jax.jit(fn, donate_argnums=(0, 1))\n"
        "    return step(w, s)\n")
    (tmp_path / "caller.py").write_text(
        "from util import fused\n\n\n"
        "def train(fn, w, s):\n"
        "    out = fused(fn, w, s)\n"
        "    return out, w\n")
    kw = dict(rules=["donation-safety"], baseline=Baseline(),
              root=str(tmp_path))
    full = run([str(tmp_path)], **kw)
    assert [f.path for f in full.findings] == ["caller.py"]
    narrow = run([str(tmp_path)], only=["util.py"], **kw)
    assert narrow.clean and narrow.subset == ["util.py"]
    expanded = run([str(tmp_path)], only=["util.py"],
                   expand_dependents=True, **kw)
    assert [f.path for f in expanded.findings] == ["caller.py"]
    assert expanded.subset == ["caller.py", "util.py"]
    # subset mode never reports stale-baseline noise
    assert expanded.stale_baseline == []


def _dep_proj(tmp_path):
    (tmp_path / "util.py").write_text(
        "def fetch(b):\n"
        "    return b.asnumpy()\n")
    (tmp_path / "hot.py").write_text(
        "from util import fetch\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    (tmp_path / "other.py").write_text(
        "def unrelated():\n"
        "    return 1\n")
    return dict(rules=["host-sync"], baseline=Baseline(),
                root=str(tmp_path),
                dep_cache=str(tmp_path / "dep.json"))


def test_dep_cache_fast_path(tmp_path):
    """A full run banks the dependency skeleton; a later subset run
    with a valid cache parses ONLY the reverse closure — the untouched
    non-dependent file is never read into the project — and still
    finds the chain through the unchanged caller."""
    kw = _dep_proj(tmp_path)
    full = run([str(tmp_path)], **kw)
    assert len(full.findings) == 1 and full.files == 3
    assert os.path.exists(kw["dep_cache"])
    # edit the sink (the pre-commit scenario), lint just the change
    (tmp_path / "util.py").write_text(
        "def fetch(b):\n"
        "    x = 1\n"
        "    return b.asnumpy(), x\n")
    rep = run([str(tmp_path)], only=["util.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"
    assert rep.files == 2                      # util + hot, not other
    assert rep.subset == ["hot.py", "util.py"]
    # the chain finding reflects the EDITED file: sink moved to line 3
    assert [(f.path, f.line) for f in rep.findings] == [("util.py", 3)]


def test_dep_cache_stale_falls_back_and_refreshes(tmp_path):
    """An un-touched file whose hash disagrees with the cache (edited
    behind --changed's back, cache from another branch, ...) forces
    the full parse — which rewrites the cache, so the NEXT subset run
    goes fast again."""
    kw = _dep_proj(tmp_path)
    run([str(tmp_path)], **kw)
    (tmp_path / "other.py").write_text(
        "def unrelated():\n"
        "    return 2\n")                      # changed, NOT in only
    rep = run([str(tmp_path)], only=["util.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "miss:stale"
    assert rep.files == 3                      # full view reparsed
    rep2 = run([str(tmp_path)], only=["util.py"],
               expand_dependents=True, **kw)
    assert rep2.dep_cache == "hit" and rep2.files == 2
    # and no cache at all is its own miss
    os.unlink(kw["dep_cache"])
    rep3 = run([str(tmp_path)], only=["util.py"],
               expand_dependents=True, **kw)
    assert rep3.dep_cache == "miss:absent" and rep3.files == 3


def test_dep_cache_keeps_registry_context(tmp_path):
    """Subset parsing must not orphan registry USES: the files
    declaring SITES/COUNTERS/FUSED_FALLBACK_CODES are always in the
    parse set, so a changed counter_inc call checks against the real
    declarations instead of reporting a phantom undeclared use."""
    (tmp_path / "reg.py").write_text(
        'COUNTERS = ("serving.requests",)\n')
    (tmp_path / "user.py").write_text(
        "from mxnet_tpu import telemetry\n\n\n"
        "def f():\n"
        '    telemetry.counter_inc("serving.requests")\n')
    (tmp_path / "other.py").write_text(
        "def unrelated():\n"
        "    return 1\n")
    kw = dict(rules=["registry-consistency"], baseline=Baseline(),
              root=str(tmp_path),
              dep_cache=str(tmp_path / "dep.json"))
    # prime with ALL rules: the cache is written by runs that build
    # the call graph (a registry-only run never needs it)
    assert run([str(tmp_path)], **dict(kw, rules=None)).clean
    (tmp_path / "user.py").write_text(
        "from mxnet_tpu import telemetry\n\n\n"
        "def f():\n"
        '    telemetry.counter_inc("serving.requests")\n'
        "    return None\n")
    rep = run([str(tmp_path)], only=["user.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"
    assert rep.files == 2                      # user + reg, not other
    assert rep.clean, [f.render() for f in rep.findings]


def test_dep_cache_fast_path_parses_callees(tmp_path):
    """Facts flow CALLEE-ward too: a donation misuse introduced in a
    touched CALLER needs the untouched callee's summary (the donating
    builder) to be detected — the fast path must close the parse set
    over imports, not just reverse dependents."""
    (tmp_path / "util.py").write_text(
        "import jax\n\n\n"
        "def fused(fn, w, s):\n"
        "    step = jax.jit(fn, donate_argnums=(0, 1))\n"
        "    return step(w, s)\n")
    (tmp_path / "caller.py").write_text(
        "from util import fused\n\n\n"
        "def train(fn, w, s):\n"
        "    out = fused(fn, w, s)\n"
        "    return out\n")
    kw = dict(rules=["donation-safety"], baseline=Baseline(),
              root=str(tmp_path),
              dep_cache=str(tmp_path / "dep.json"))
    assert run([str(tmp_path)], **kw).clean    # primes the cache
    # the pre-commit edit: reuse w after it rode a donated position
    (tmp_path / "caller.py").write_text(
        "from util import fused\n\n\n"
        "def train(fn, w, s):\n"
        "    out = fused(fn, w, s)\n"
        "    return out, w\n")
    rep = run([str(tmp_path)], only=["caller.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"
    assert [f.path for f in rep.findings] == ["caller.py"], \
        [f.render() for f in rep.findings]
    assert rep.files == 2                      # caller + util (callee)


def test_changed_keeps_chain_sink_in_untouched_file(tmp_path):
    """Editing only the hot CALLER to reach an existing blocking
    helper must still fail --changed: the sink anchors in the
    untouched helper file, and the finding survives the subset filter
    because its witness chain crosses the touched file — on both the
    dep-cache fast path and the full-parse subset path."""
    kw = _dep_proj(tmp_path)
    run([str(tmp_path)], **kw)                 # primes the cache
    (tmp_path / "hot.py").write_text(          # edit the CALLER only
        "from util import fetch\n\n\n"
        "def loop(batches, log):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        log(fetch(b))\n")
    rep = run([str(tmp_path)], only=["hot.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"
    assert [(f.path, f.line) for f in rep.findings] == [("util.py", 2)]
    assert "hot.py" in rep.findings[0].via
    # chain-bearing findings expose the crossing files in the JSON too
    assert rep.findings[0].to_dict()["via"] == rep.findings[0].via \
        or rep.findings[0].to_dict()["via"] == list(rep.findings[0].via)
    # same answer without the cache (full-parse subset path)
    rep2 = run([str(tmp_path)], only=["hot.py"],
               expand_dependents=True,
               **dict(kw, dep_cache=None))
    assert [(f.path, f.line) for f in rep2.findings] == [("util.py", 2)]


def test_changed_closure_is_audited(tmp_path):
    """--changed reports WHAT it linted: the touched files, the
    reverse-dependent expansion, the parsed set and how many findings
    anchored outside the subset were kept only via chain crossings —
    so a '0 findings' on a partial view is auditable; --json carries
    the closure record verbatim."""
    kw = _dep_proj(tmp_path)
    run([str(tmp_path)], **kw)                 # primes the cache
    (tmp_path / "hot.py").write_text(          # edit the CALLER only
        "from util import fetch\n\n\n"
        "def loop(batches, log):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        log(fetch(b))\n")
    rep = run([str(tmp_path)], only=["hot.py"],
              expand_dependents=True, **kw)
    c = rep.closure
    assert c["touched"] == ["hot.py"]
    assert c["linted"] == ["hot.py"] and c["dependents"] == 0
    assert "util.py" in c["parsed"]            # the callee was parsed
    assert c["via_kept"] == 1                  # sink-elsewhere finding
    assert rep.to_dict()["closure"] == c
    # touching the CALLEE expands to its reverse dependent
    (tmp_path / "util.py").write_text(
        "def fetch(b):\n"
        "    return b.asnumpy()\n")
    rep2 = run([str(tmp_path)], only=["util.py"],
               expand_dependents=True, **kw)
    c2 = rep2.closure
    assert c2["touched"] == ["util.py"]
    assert c2["linted"] == ["hot.py", "util.py"]
    assert c2["dependents"] == 1
    # a full (non-subset) run has no closure record
    assert run([str(tmp_path)], **kw).closure is None


def test_local_shadowing_never_fabricates_a_call_edge(tmp_path):
    """A parameter (or any local binding) named like a module function
    must resolve as DYNAMIC, not as the shadowed module function —
    otherwise correct code fails the gate on a chain that is not a
    real call path."""
    (tmp_path / "shadow.py").write_text(
        "def fetch(b):\n"
        "    return b.asnumpy()\n\n\n"
        "def loop(batches, fetch):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    kw = dict(rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "shadow.py").write_text(   # positive control: no param
        "def fetch(b):\n"
        "    return b.asnumpy()\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    rep = run([str(tmp_path)], **kw)
    assert [(f.path, f.line) for f in rep.findings] == [("shadow.py", 2)]


def test_relative_import_inside_package_init_resolves(tmp_path):
    """`from . import util` inside pkg/__init__.py resolves against
    the package ITSELF (its module name already dropped '__init__'),
    so chains out of package __init__ files are not silently lost."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "util.py").write_text(
        "def fetch(b):\n"
        "    return b.asnumpy()\n")
    (pkg / "__init__.py").write_text(
        "from . import util\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        util.fetch(b)\n")
    rep = run([str(tmp_path)], rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    assert [(f.path, f.line) for f in rep.findings] \
        == [("pkg/util.py", 2)], [f.render() for f in rep.findings]
    assert "pkg/__init__.py" in rep.findings[0].via


def test_nested_def_binding_does_not_shadow_hot_scope(tmp_path):
    """A name bound INSIDE a nested def shadows nothing in the hot
    function's own scope: the outer np.asarray sync must still be
    flagged even when a nested helper has a param named `np`."""
    (tmp_path / "hotnp.py").write_text(
        "import numpy as np\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    def helper(np):\n"
        "        return np\n"
        "    for b in batches:\n"
        "        helper(np.asarray(b))\n")
    rep = run([str(tmp_path)], rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["host-sync"], \
        [f.render() for f in rep.findings]
    (tmp_path / "hotnp.py").write_text(      # compliant twin: the HOT
        "import numpy as np\n\n\n"           # scope itself rebinds np
        "def loop(batches, np):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        np.asarray(b)\n")
    rep = run([str(tmp_path)], rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    assert rep.clean, [f.render() for f in rep.findings]


def test_lockset_method_escaping_as_value_loses_entry_locks(tmp_path):
    """A private method handed somewhere as a VALUE (Timer/Thread
    callback target) can be invoked bare — its locked call-edge
    callers must not credit it with a held-at-entry lockset."""
    (tmp_path / "escape.py").write_text(
        "import threading\n\n\n"
        "class Buf:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self.buf = []\n\n"
        "    def flush(self):\n"
        "        with self._lock:\n"
        "            self.buf.append(0)\n"
        "            self._drain()\n\n"
        "    def start(self):\n"
        "        threading.Timer(1.0, self._drain).start()\n\n"
        "    def _drain(self):\n"
        "        self.buf.append(1)\n")
    rep = run([str(tmp_path)], rules=["lockset"], baseline=Baseline(),
              root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["lockset"], \
        [f.render() for f in rep.findings]
    assert "buf" in rep.findings[0].message


def test_decorator_above_jit_runs_at_def_time(tmp_path):
    """A decorator stacked above @jax.jit evaluates ONCE, at def time,
    in the enclosing scope — it must not become a call edge of the
    traced function (a false 'inside the trace cone' gate failure)."""
    (tmp_path / "deco.py").write_text(
        "import jax\n\n"
        "_CALLS = []\n\n\n"
        "def audit():\n"
        "    def wrap(fn):\n"
        "        _CALLS.append(fn.__name__)\n"
        "        return fn\n"
        "    return wrap\n\n\n"
        "@audit()\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return x + 1\n")
    kw = dict(rules=["trace-purity"], baseline=Baseline(),
              root=str(tmp_path))
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "deco.py").write_text(       # positive control: the
        "import jax\n\n"                     # impurity IN the body
        "_CALLS = []\n\n\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    _CALLS.append(1)\n"
        "    return x + 1\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["trace-purity"]


def test_staticmethod_donation_needs_no_self_shift(tmp_path):
    """@staticmethod params line up with the call args as written: the
    bound-method shift must not move inferred donated positions off by
    one (dropping the real donation, flagging the wrong arg)."""
    (tmp_path / "sm.py").write_text(
        "import jax\n\n\n"
        "class Step:\n"
        "    @staticmethod\n"
        "    def fused(w, s):\n"
        "        prog = jax.jit(lambda a, b: (a, b),\n"
        "                       donate_argnums=(1,))\n"
        "        return prog(w, s)\n\n"
        "    def train(self, w, s):\n"
        "        out = self.fused(w, s)\n"
        "        return out, s\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert len(rep.findings) == 1, [f.render() for f in rep.findings]
    assert "'s'" in rep.findings[0].message
    (tmp_path / "sm.py").write_text(         # compliant twin: reuse w
        "import jax\n\n\n"                   # (position 0, NOT donated)
        "class Step:\n"
        "    @staticmethod\n"
        "    def fused(w, s):\n"
        "        prog = jax.jit(lambda a, b: (a, b),\n"
        "                       donate_argnums=(1,))\n"
        "        return prog(w, s)\n\n"
        "    def train(self, w, s):\n"
        "        out = self.fused(w, s)\n"
        "        return out, w\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert rep.clean, [f.render() for f in rep.findings]


def test_changed_registry_decl_edit_reaches_untouched_users(tmp_path):
    """Registry uses are string-keyed, not call edges: touching only
    the DECLARING file must fall back to the full parse (every use
    site re-checked) and the use-site finding in the untouched file
    must survive the subset filter via its declaring-file `via`."""
    (tmp_path / "reg.py").write_text(
        'COUNTERS = ("serving.requests", "serving.errors")\n')
    (tmp_path / "user.py").write_text(
        "from mxnet_tpu import telemetry\n\n\n"
        "def f():\n"
        '    telemetry.counter_inc("serving.requests")\n'
        '    telemetry.counter_inc("serving.errors")\n')
    kw = dict(rules=["registry-consistency"], baseline=Baseline(),
              root=str(tmp_path),
              dep_cache=str(tmp_path / "dep.json"))
    assert run([str(tmp_path)], **dict(kw, rules=None)).clean
    (tmp_path / "reg.py").write_text(        # drop a declared counter
        'COUNTERS = ("serving.requests",)\n')
    rep = run([str(tmp_path)], only=["reg.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "miss:registry-decl-touched"
    assert [f.path for f in rep.findings] == ["user.py"], \
        [f.render() for f in rep.findings]
    assert "serving.errors" in rep.findings[0].message
    assert "reg.py" in rep.findings[0].via


def test_dep_cache_survives_narrow_runs(tmp_path):
    """A one-off narrow run (fixture test, single file) must not
    clobber the repo-wide skeleton: the cache is keyed on the lint
    path set and only a --changed fallback may overwrite across sets."""
    kw = _dep_proj(tmp_path)
    run([str(tmp_path)], **kw)               # repo-wide prime
    import mxnet_tpu.analysis.core as _core
    doc_before = _core.load_dep_cache(kw["dep_cache"])
    run([str(tmp_path / "other.py")], **kw)  # narrow run, same cache
    doc_after = _core.load_dep_cache(kw["dep_cache"])
    assert doc_after == doc_before           # untouched
    rep = run([str(tmp_path)], only=["util.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"            # still valid
    # a cache from a DIFFERENT path set is a miss, and the --changed
    # fallback rewrites it for its own (canonical) set
    rep2 = run([str(tmp_path / "hot.py"), str(tmp_path / "util.py")],
               only=["util.py"], expand_dependents=True, **kw)
    assert rep2.dep_cache == "miss:paths"
    doc2 = _core.load_dep_cache(kw["dep_cache"])
    assert doc2["paths"] == ["hot.py", "util.py"]


def test_same_named_defs_keep_distinct_facts(tmp_path):
    """Branch-defined same-named defs must not alias the LAST def's
    effect facts: an impurity in the FIRST variant (both are traced —
    each carries its own @jax.jit) must still be flagged."""
    (tmp_path / "variants.py").write_text(
        "import jax\n"
        "import time\n\n\n"
        "def build(flag):\n"
        "    if flag:\n"
        "        @jax.jit\n"
        "        def kernel(x):\n"
        "            return x * time.time()\n"
        "    else:\n"
        "        @jax.jit\n"
        "        def kernel(x):\n"
        "            return x + 1\n"
        "    return kernel\n")
    rep = run([str(tmp_path)], rules=["trace-purity"],
              baseline=Baseline(), root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["trace-purity"], \
        [f.render() for f in rep.findings]
    assert "reads the wall clock" in rep.findings[0].message
    assert rep.findings[0].line == 9           # the FIRST variant's line


def test_shadowed_module_names_are_not_global_effects(tmp_path):
    """A parameter named `random` (or `np`, `time`, ...) makes calls
    through it calls on a runtime object — classifying them as global
    RNG/clock reads fails the gate on correct code."""
    (tmp_path / "shadowed.py").write_text(
        "import jax\n\n\n"
        "def helper(random):\n"
        "    return random.random()\n\n\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return helper(x)\n")
    kw = dict(rules=["trace-purity"], baseline=Baseline(),
              root=str(tmp_path))
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "shadowed.py").write_text(     # positive control
        "import jax\n"
        "import random\n\n\n"
        "def helper(x):\n"
        "    return x * random.random()\n\n\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    return helper(x)\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["trace-purity"]
    assert "draws from the global RNG" in rep.findings[0].message


def test_bound_method_passed_as_value_is_traced(tmp_path):
    """jax.jit(self._kernel): the bound method runs under the tracer —
    it must be a trace-purity root via the same self-type resolution
    the call edges use."""
    (tmp_path / "bound.py").write_text(
        "import jax\n"
        "import time\n\n\n"
        "class K:\n"
        "    def build(self):\n"
        "        return jax.jit(self._kernel)\n\n"
        "    def _kernel(self, x):\n"
        "        return x * time.time()\n")
    rep = run([str(tmp_path)], rules=["trace-purity"],
              baseline=Baseline(), root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["trace-purity"], \
        [f.render() for f in rep.findings]
    assert "reads the wall clock" in rep.findings[0].message


def test_changed_handles_paths_with_spaces(tmp_path, monkeypatch):
    """git -z plumbing: a touched path containing a space must reach
    the linter intact, not be split into fragments that silently match
    nothing (a clean exit on an unlinted violation)."""
    def g(*a):
        return subprocess.run(["git", "-C", str(tmp_path)] + list(a),
                              stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True)
    assert g("init", "-q").returncode == 0
    (tmp_path / "base.py").write_text("x = 1\n")
    g("add", ".")
    assert g("-c", "user.email=t@t", "-c", "user.name=t",
             "commit", "-qm", "seed").returncode == 0
    (tmp_path / "my probe.py").write_text(_VIOLATION_SRC % "")
    (tmp_path / "base.py").write_text("x = 2\n")
    import importlib.util
    spec = importlib.util.spec_from_file_location("_mxlint_cli", MXLINT)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    monkeypatch.setattr(mod, "ROOT", str(tmp_path))
    files, err = mod.changed_files("HEAD")
    assert err is None, err
    assert files == ["base.py", "my probe.py"]


def test_nested_local_store_is_not_a_global_mutation(tmp_path):
    """An outer `global` declaration does not inherit into nested
    defs: a traced kernel's plain local store to a name its ENCLOSING
    function declared global is pure (Python scoping), while the
    kernel declaring `global` itself is the real impurity."""
    (tmp_path / "pure.py").write_text(
        "import jax\n\n"
        "_N = 0\n\n\n"
        "def outer():\n"
        "    global _N\n"
        "    _N = 1\n\n"
        "    def kernel(x):\n"
        "        _N = x + 1\n"
        "        return _N\n\n"
        "    return jax.jit(kernel)\n")
    kw = dict(rules=["trace-purity"], baseline=Baseline(),
              root=str(tmp_path))
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "pure.py").write_text(         # positive control
        "import jax\n\n"
        "_N = 0\n\n\n"
        "def outer():\n"
        "    def kernel(x):\n"
        "        global _N\n"
        "        _N = 2\n"
        "        return x\n\n"
        "    return jax.jit(kernel)\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["trace-purity"]
    assert "writes global '_N'" in rep.findings[0].message


def test_changed_cli_smoke():
    """--changed against the repo's own git state: exits clean either
    way (nothing touched, or the touched subset lints clean) and never
    crashes; --changed-base with a bogus ref is a usage error."""
    # base HEAD, not the default origin/main: on a committed tree this
    # takes the cheap nothing-touched path instead of re-linting the
    # whole branch's worth of files on every tier-1 run
    proc = _cli(["--changed", "--changed-base", "HEAD",
                 "mxnet_tpu", "tools", "bench.py"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "--changed" in proc.stdout or "mxlint" in proc.stdout
    proc = _cli(["--changed", "--changed-base", "no-such-ref-xyz",
                 "mxnet_tpu"])
    assert proc.returncode == 2
    proc = _cli(["--changed", "--update-baseline", "mxnet_tpu"])
    assert proc.returncode == 2
    proc = _cli(["--dep-cache"])
    assert proc.returncode == 2


def test_changed_cli_dep_cache_self_primes(tmp_path):
    """The first --changed run (cache absent) pays the full parse and
    banks the skeleton; the second hits it. '--dep-cache none' opts
    out entirely."""
    cache = str(tmp_path / "dep.json")
    first = _cli(["--changed", "--changed-base", "HEAD",
                  "--dep-cache", cache, "mxnet_tpu", "tools",
                  "bench.py"])
    assert first.returncode == 0, first.stdout + first.stderr
    if "no python files touched" in first.stdout:
        pytest.skip("clean tree: --changed has nothing to lint")
    assert "dep cache miss:absent" in first.stdout
    assert os.path.exists(cache)
    second = _cli(["--changed", "--changed-base", "HEAD",
                   "--dep-cache", cache, "mxnet_tpu", "tools",
                   "bench.py"])
    assert second.returncode == 0, second.stdout + second.stderr
    # a touched registry-DECLARING file legitimately forces the full
    # parse every time (string-keyed uses have no call edges to follow)
    assert ("dep cache hit" in second.stdout
            or "miss:registry-decl-touched" in second.stdout), \
        second.stdout
    off = _cli(["--changed", "--changed-base", "HEAD",
                "--dep-cache", "none", "mxnet_tpu", "tools",
                "bench.py"])
    assert off.returncode == 0
    assert "dep cache off" in off.stdout


def test_chain_finding_baseline_keys_on_sink(tmp_path):
    """Refactoring an INTERMEDIATE caller (rename, line drift) must
    not invalidate a grandfathered chain finding: the baseline keys on
    the sink line only."""
    hot = tmp_path / "hot.py"
    hot.write_text(
        "from sink import fetch\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    sink = tmp_path / "sink.py"
    sink.write_text(
        "def fetch(b):\n"
        "    return b.asnumpy()\n")
    kw = dict(rules=["host-sync"], root=str(tmp_path))
    rep = run([str(tmp_path)], baseline=Baseline(), **kw)
    assert len(rep.findings) == 1
    f = rep.findings[0]
    assert (f.path, f.line) == ("sink.py", 2)       # anchored at the sink
    bl_path = _write(tmp_path, "bl.json",
                     json.dumps(Baseline.render(rep.findings)))
    assert run([str(tmp_path)], baseline=bl_path, **kw).clean
    # refactor the intermediate caller: rename + shift lines
    hot.write_text(
        "from sink import fetch\n\n\n"
        "def renamed_loop(batches, extra):   # mxlint: hot\n"
        "    del extra\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    rep3 = run([str(tmp_path)], baseline=bl_path, **kw)
    assert rep3.clean, [x.render() for x in rep3.findings]
    assert len(rep3.baselined) == 1
    assert rep3.stale_baseline == []


def test_trace_purity_via_includes_registration_file(tmp_path):
    """The file holding the jit/_InstrumentedProgram REGISTRATION call
    is part of the witness: a --changed run touching only that file
    (the newly-introduced `jax.jit(helper)` line) must still surface
    the impurity that anchors in the untouched helper file."""
    (tmp_path / "util.py").write_text(
        "_CACHE = {}\n\n\n"
        "def helper(x):\n"
        "    _CACHE[0] = x\n"
        "    return x\n")
    (tmp_path / "app.py").write_text(
        "import jax\n\n"
        "from util import helper\n\n"
        "prog = jax.jit(helper)\n")
    kw = dict(rules=["trace-purity"], baseline=Baseline(),
              root=str(tmp_path))
    full = run([str(tmp_path)], **kw)
    assert [(f.path, f.line) for f in full.findings] == [("util.py", 5)]
    assert "app.py" in full.findings[0].via
    narrow = run([str(tmp_path)], only=["app.py"],
                 expand_dependents=True, **kw)
    assert [(f.path, f.line) for f in narrow.findings] \
        == [("util.py", 5)], [f.render() for f in narrow.findings]


def test_suppressed_sync_never_hides_another(tmp_path):
    """Every sync site in every reachable sink gets its own finding: a
    justified disable on the FIRST fetch in a helper must not swallow
    the bare fetch on the next line, nor a farther sink function
    behind the suppressed one."""
    (tmp_path / "util.py").write_text(
        "def deeper(b):\n"
        "    return b.wait_to_read()\n\n\n"
        "def fetch(b):\n"
        "    x = b.asnumpy()   # mxlint: disable=host-sync -- "
        "deliberate: admission-path marshalling\n"
        "    b.wait_to_read()\n"
        "    return deeper(b), x\n")
    (tmp_path / "hot.py").write_text(
        "from util import fetch\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    rep = run([str(tmp_path)], rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    assert sorted((f.path, f.line) for f in rep.findings) \
        == [("util.py", 2), ("util.py", 7)], \
        [f.render() for f in rep.findings]
    assert [(f.path, f.line) for f, _ in rep.suppressed] \
        == [("util.py", 6)]


def test_param_annotation_runs_at_def_time(tmp_path):
    """A parameter annotation on a traced def evaluates ONCE, at def
    time, in the enclosing scope — like a stacked decorator it must
    not become a call edge of the traced function."""
    (tmp_path / "anno.py").write_text(
        "import jax\n\n"
        "_SPECS = []\n\n\n"
        "def make_spec():\n"
        "    _SPECS.append(1)\n"
        "    return None\n\n\n"
        "@jax.jit\n"
        "def kernel(x: make_spec()):\n"
        "    return x + 1\n")
    kw = dict(rules=["trace-purity"], baseline=Baseline(),
              root=str(tmp_path))
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "anno.py").write_text(       # positive control: the
        "import jax\n\n"                     # call IN the body
        "_SPECS = []\n\n\n"
        "def make_spec():\n"
        "    _SPECS.append(1)\n"
        "    return None\n\n\n"
        "@jax.jit\n"
        "def kernel(x):\n"
        "    make_spec()\n"
        "    return x + 1\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["trace-purity"]


def test_unbound_base_method_call_keeps_arg_positions(tmp_path):
    """`Base.update(self, w)` super-delegation passes self EXPLICITLY
    as arg 0 — the bound-method shift must not move the inferred
    donated position onto self (false finding) while missing the real
    use-after-donate of w."""
    (tmp_path / "base.py").write_text(
        "import jax\n\n\n"
        "class Base:\n"
        "    def update(self, w):\n"
        "        step = jax.jit(lambda v: v, donate_argnums=(0,))\n"
        "        return step(w)\n")
    (tmp_path / "sub.py").write_text(
        "from base import Base\n\n\n"
        "class Sub(Base):\n"
        "    def __init__(self):\n"
        "        self.count = 0\n\n"
        "    def update(self, w):\n"
        "        y = Base.update(self, w)\n"
        "        self.count += 1\n"
        "        return y, w\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert [(f.path, f.line) for f in rep.findings] == [("sub.py", 11)], \
        [f.render() for f in rep.findings]
    assert "'w'" in rep.findings[0].message
    # the bound form still shifts: self.update-style delegation via an
    # instance consumes the receiver binding
    (tmp_path / "sub.py").write_text(
        "from base import Base\n\n\n"
        "def drive(w):\n"
        "    b = Base()\n"
        "    y = b.update(w)\n"
        "    return y, w\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert [(f.path, f.line) for f in rep.findings] == [("sub.py", 7)], \
        [f.render() for f in rep.findings]


def test_decorator_armed_hot_sink_not_double_counted(tmp_path):
    """A hot caller reaching a sink whose # mxlint: hot marker arms
    the DECORATOR line must produce only the direct finding — the
    transitive skip mirrors _hot_functions' def-or-decorator-line
    check, or the same sync line is reported twice under one baseline
    key."""
    (tmp_path / "m.py").write_text(
        "def wrap(fn):\n"
        "    return fn\n\n\n"
        "# mxlint: hot\n"
        "@wrap\n"
        "def fetch(b):\n"
        "    return b.asnumpy()\n\n\n"
        "def loop(batches):   # mxlint: hot\n"
        "    for b in batches:\n"
        "        fetch(b)\n")
    rep = run([str(tmp_path)], rules=["host-sync"], baseline=Baseline(),
              root=str(tmp_path))
    assert [(f.path, f.line) for f in rep.findings] == [("m.py", 8)], \
        [f.render() for f in rep.findings]


def test_donation_gate_skips_graph_on_donation_free_tree(tmp_path):
    """--rules donation-safety on a tree with no donate_argnums and no
    markers must answer without building the call graph (the cheap
    gate runs BEFORE the interprocedural build)."""
    (tmp_path / "plain.py").write_text(
        "def helper(x):\n"
        "    return x + 1\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert rep.clean
    assert "callgraph" not in rep.timings, rep.timings
    # positive control: one literal donate_argnums anywhere re-enables
    # the interprocedural feed
    (tmp_path / "prog.py").write_text(
        "import jax\n\n\n"
        "def build(fn):\n"
        "    return jax.jit(fn, donate_argnums=(0,))\n")
    rep = run([str(tmp_path)], rules=["donation-safety"],
              baseline=Baseline(), root=str(tmp_path))
    assert "callgraph" in rep.timings, rep.timings


def test_divergence_sees_fallthrough_suffix(tmp_path):
    """`if rank != 0: return` BEFORE a psum diverges too: a
    terminating arm skips the block's suffix, the fallthrough arm
    inherits it — sequence comparison must include both."""
    kw = dict(rules=["collective-discipline"], baseline=Baseline(),
              root=str(tmp_path))
    (tmp_path / "early.py").write_text(
        "from jax import lax\n\n\n"
        "def step(rank, x):\n"
        "    if rank != 0:\n"
        "        return x\n"
        "    return lax.psum(x, 'dp')\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.line for f in rep.findings] == [5], \
        [f.render() for f in rep.findings]
    assert "DIFFERENT collective sequences" in rep.findings[0].message
    (tmp_path / "early.py").write_text(   # rank-invariant control:
        "from jax import lax\n\n\n"       # both arms reach the psum
        "def step(rank, x):\n"
        "    if rank != 0:\n"
        "        x = x * 2\n"
        "    return lax.psum(x, 'dp')\n")
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]


def test_collective_call_site_channel_override(tmp_path):
    """A call-line `# mxsync: collective channel=...` overrides the
    def-line default: the step-gated commit path calling a kv-default
    primitive mismatches without the override and is clean with it."""
    src_tmpl = (
        "class CollectiveGate:\n"
        "    def __init__(self, channel='step'):\n"
        "        self.channel = channel\n\n"
        "    def arrive_and_wait(self):\n"
        "        return 0\n\n\n"
        "def bcast(tree):   # mxsync: collective channel=kv\n"
        "    return tree\n\n\n"
        "def commit(tree):\n"
        "    gate = CollectiveGate(channel='step')\n"
        "    gate.arrive_and_wait()\n"
        "    return bcast(tree)%s\n")
    kw = dict(rules=["collective-discipline"], baseline=Baseline(),
              root=str(tmp_path))
    (tmp_path / "ov.py").write_text(src_tmpl % "")
    rep = run([str(tmp_path)], **kw)
    assert len(rep.findings) == 1, [f.render() for f in rep.findings]
    assert "channel 'kv'" in rep.findings[0].message
    assert "'step'" in rep.findings[0].message
    (tmp_path / "ov.py").write_text(
        src_tmpl % "   # mxsync: collective channel=step")
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]


def test_thread_spawned_from_thread_keeps_its_own_root(tmp_path):
    """A Thread target spawning ANOTHER thread hands the inner target
    to the NEW thread — following that registration edge during root
    propagation would fabricate a cross-root race between two points
    of one sequential spawn chain."""
    (tmp_path / "spawn.py").write_text(
        "import threading\n\n\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._n = 0\n\n"
        "    def start(self):\n"
        "        threading.Thread(target=self._outer).start()\n\n"
        "    def _outer(self):\n"
        "        threading.Thread(target=self._inner).start()\n\n"
        "    def _inner(self):\n"
        "        self._n = 1\n"
        "        self._report()\n\n"
        "    def _report(self):\n"
        "        return self._n\n")
    rep = run([str(tmp_path)], rules=["thread-race"],
              baseline=Baseline(), root=str(tmp_path))
    assert rep.clean, [f.render() for f in rep.findings]


def test_closure_read_of_shadowing_local_is_not_a_global(tmp_path):
    """A nested worker reading an ENCLOSING function's local that
    shadows a module-global name touches the closure variable, not the
    global — Python scoping walks every enclosing frame, so must the
    global-access resolution."""
    kw = dict(rules=["thread-race"], baseline=Baseline(),
              root=str(tmp_path))
    (tmp_path / "closure.py").write_text(
        "import threading\n\n"
        "_buf = []\n\n\n"
        "def start():\n"
        "    _buf = []\n"
        "    def worker():\n"
        "        return len(_buf)\n"
        "    threading.Thread(target=worker).start()\n\n\n"
        "def writeback():\n"
        "    global _buf\n"
        "    _buf = [1]\n")
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "closure.py").write_text(   # positive control: no
        "import threading\n\n"              # shadowing local — the
        "_buf = []\n\n\n"                   # worker reads the global
        "def start():\n"
        "    def worker():\n"
        "        return len(_buf)\n"
        "    threading.Thread(target=worker).start()\n\n\n"
        "def writeback():\n"
        "    global _buf\n"
        "    _buf = [1]\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["thread-race"], \
        [f.render() for f in rep.findings]


def test_function_level_excepthook_registers_one_root(tmp_path):
    """A hook assignment inside a function must register exactly ONE
    thread root (with the function as scope, so the registration ref
    edge is excluded from main propagation) — the whole-tree module
    scan used to see it too, and the two clone roots fabricated a
    cross-root race for code that only ever runs under the hook."""
    (tmp_path / "hook.py").write_text(
        "import sys\n\n\n"
        "def _hook(t, v, tb):\n"
        "    pass\n\n\n"
        "def install():\n"
        "    sys.excepthook = _hook\n")
    from mxnet_tpu.analysis.core import Project, iter_python_files
    proj = Project(root=str(tmp_path))
    for p in iter_python_files([str(tmp_path)]):
        proj.add_file(p)
    tm = proj.threads()
    assert len(tm.roots) == 1, [r.label() for r in tm.roots]
    assert tm.roots[0].kind == "excepthook"


def test_pool_submit_is_a_thread_root(tmp_path):
    """`self._pool.submit(self._resolve, ...)` on a ThreadPoolExecutor
    attr makes _resolve a thread root — the serving resolver-pool
    shape — so its unlocked writes race main-thread reads."""
    (tmp_path / "pool.py").write_text(
        "import threading\n"
        "from concurrent.futures import ThreadPoolExecutor\n\n\n"
        "class Engine:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._pool = ThreadPoolExecutor(2)\n"
        "        self._done = 0\n\n"
        "    def dispatch(self, batch):\n"
        "        self._pool.submit(self._resolve, batch)\n\n"
        "    def _resolve(self, batch):\n"
        "        self._done += 1\n\n"
        "    def done(self):\n"
        "        return self._done\n")
    rep = run([str(tmp_path)], rules=["thread-race"],
              baseline=Baseline(), root=str(tmp_path))
    assert len(rep.findings) == 1, [f.render() for f in rep.findings]
    assert "pool-worker" in rep.findings[0].message
    assert "_resolve" in rep.findings[0].message


def test_lint_wall_time_guard():
    """The full-repo mxflow run stays inside its wall-time budget
    (MXLINT_BUDGET_S, default 60s — ~10x the measured cost, so only a
    pathological blowup of the interprocedural passes trips it), and
    the JSON report carries per-rule timings + call-graph stats."""
    budget = float(os.environ.get("MXLINT_BUDGET_S", "60"))
    proc, wall, art = _full_repo_gate_run()
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert wall < budget, \
        "full mxflow lint took %.1fs (budget %.0fs)" % (wall, budget)
    with open(art) as f:
        doc = json.load(f)
    for rule in ALL_RULE_IDS:
        assert rule in doc["timings"], doc["timings"]
    assert "callgraph" in doc["timings"] and "summaries" in doc["timings"]
    # the mxsync/mxlife models are timed under their own keys (like
    # callgraph/summaries) so rule timings never double-count the builds
    assert "threads" in doc["timings"] and "collectives" in doc["timings"]
    assert "lifecycle" in doc["timings"]
    cg = doc["callgraph"]
    for key in ("functions", "call_edges", "ref_edges", "dynamic_calls",
                "sccs", "cyclic_sccs", "largest_scc", "facts_cache",
                "thread_roots", "thread_rooted_functions",
                "collective_sites", "collective_host_sites",
                "gate_crossings", "lifecycle_future_classes",
                "lifecycle_resolver_functions",
                "lifecycle_simulated_functions", "may_raise_functions"):
        assert key in cg, cg
    assert cg["functions"] > 1000        # the graph really covers the repo
    assert cg["call_edges"] > 500
    # the mxsync models really cover the runtime: the coalescer/
    # sampler/heartbeat/pool roots and the kvstore/spmd collective
    # surface are all discoverable statically
    assert cg["thread_roots"] >= 10, cg
    assert cg["collective_sites"] >= 5, cg
    assert cg["collective_host_sites"] >= 4, cg
    assert cg["gate_crossings"] >= 4, cg
    # ...and the mxlife model: the serving _Request class, its
    # resolver set, and the runtime's real may-raise surface
    assert cg["lifecycle_future_classes"] >= 1, cg
    assert cg["lifecycle_resolver_functions"] >= 2, cg
    assert cg["may_raise_functions"] >= 100, cg


# ---------------------------------------------------------------------------
# mxlife: may_raise summaries, typestate semantics, --explain
# ---------------------------------------------------------------------------

def test_may_raise_propagates_through_unguarded_calls(tmp_path):
    """An unguarded own raise seeds may_raise; it propagates to
    callers through UNGUARDED call sites only — a try with ANY except
    handler swallows (conservative-quiet), while handler bodies and
    finally bodies propagate past their own try."""
    (tmp_path / "m.py").write_text(
        "def origin(x):\n"
        "    raise ValueError(x)\n\n\n"
        "def unguarded(x):\n"
        "    return origin(x)\n\n\n"
        "def guarded(x):\n"
        "    try:\n"
        "        return origin(x)\n"
        "    except Exception:\n"
        "        return None\n\n\n"
        "def in_handler(x):\n"
        "    try:\n"
        "        return 1\n"
        "    except Exception:\n"
        "        return origin(x)\n\n\n"
        "def in_finally(x):\n"
        "    try:\n"
        "        return 1\n"
        "    finally:\n"
        "        origin(x)\n")
    proj = _project_of([tmp_path / "m.py"], tmp_path)
    g = proj.callgraph()
    summ = proj.summaries()
    by = {fi.name: fi for fi in g.functions}
    assert summ.may_raise(by["origin"])
    assert summ.may_raise(by["unguarded"])
    assert not summ.may_raise(by["guarded"])
    assert summ.may_raise(by["in_handler"])
    assert summ.may_raise(by["in_finally"])
    # the witness chain bottoms out at the origin raise
    hops, line, exc = summ.raise_chain(by["unguarded"])
    assert [h.name for h, _l in hops] == ["origin"]
    assert line == 2 and exc == "ValueError"


def test_future_lifecycle_resolving_callee_discharges(tmp_path):
    """Passing an owned request to an in-scan callee that resolves its
    parameter on every path discharges the obligation (the _shed
    pattern) — and the same code WITHOUT the resolving callee is a
    strand."""
    common = (
        "from concurrent.futures import Future\n\n\n"
        "class Req:\n"
        "    def __init__(self):\n"
        "        self.future = Future()\n\n\n"
        "def risky(x):\n"
        "    if x:\n"
        "        raise RuntimeError(x)\n\n\n"
        "def shed(req, exc):\n"
        "    if not req.future.done():\n"
        "        req.future.set_exception(exc)\n\n\n")
    kw = dict(rules=["future-lifecycle"], baseline=Baseline(),
              root=str(tmp_path))
    (tmp_path / "m.py").write_text(
        common
        + "def drive(q, x):\n"
        "    req = q.get()\n"
        "    try:\n"
        "        risky(x)\n"
        "    except Exception as e:\n"
        "        shed(req, e)\n"
        "        return\n"
        "    req.future.set_result(x)\n")
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "m.py").write_text(        # positive control: the
        common                             # handler forgets the request
        + "def drive(q, x):\n"
        "    req = q.get()\n"
        "    try:\n"
        "        risky(x)\n"
        "    except Exception:\n"
        "        return\n"
        "    req.future.set_result(x)\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["future-lifecycle"], \
        [f.render() for f in rep.findings]
    assert "UNRESOLVED" in rep.findings[0].message


def test_future_lifecycle_finally_resolution_is_clean(tmp_path):
    """A finally-guarded resolve covers the raise leg too — the
    linearized try/except/finally walk must see it."""
    (tmp_path / "m.py").write_text(
        "from concurrent.futures import Future\n\n\n"
        "class Req:\n"
        "    def __init__(self):\n"
        "        self.future = Future()\n\n\n"
        "def risky(x):\n"
        "    if x:\n"
        "        raise RuntimeError(x)\n\n\n"
        "def drive(q, x):\n"
        "    req = q.get()\n"
        "    out = None\n"
        "    try:\n"
        "        out = risky(x)\n"
        "    finally:\n"
        "        if not req.future.done():\n"
        "            req.future.set_result(out)\n")
    rep = run([str(tmp_path)], rules=["future-lifecycle"],
              baseline=Baseline(), root=str(tmp_path))
    assert rep.clean, [f.render() for f in rep.findings]


def test_cli_explain(tmp_path):
    """--explain <rule> prints the rule's doc, finding format and its
    fixture pair paths; exit 2 on an unknown rule id."""
    proc = _cli(["--explain", "future-lifecycle"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "future-lifecycle" in proc.stdout
    assert "future_lifecycle_violation.py" in proc.stdout
    assert "future_lifecycle_ok.py" in proc.stdout
    assert "rule, path, line, col, message" in proc.stdout
    proc = _cli(["--explain", "no-such-rule"])
    assert proc.returncode == 2
    assert "unknown rule" in proc.stderr
    # every rule id explains without error (doc + fixtures wired)
    for rid in ALL_RULE_IDS:
        assert _cli(["--explain", rid]).returncode == 0, rid


def test_changed_refinds_lifecycle_strand_through_callee_edit(tmp_path):
    """mxlife rides the --changed machinery: touching only the CALLEE
    whose may_raise summary creates the caller's strand must re-find
    the caller's finding through the reverse-dependent closure, on the
    dep-cache fast path."""
    (tmp_path / "util.py").write_text(
        "def risky(x):\n"
        "    if x:\n"
        "        raise RuntimeError(x)\n")
    (tmp_path / "worker.py").write_text(
        "from concurrent.futures import Future\n"
        "from util import risky\n\n\n"
        "class Req:\n"
        "    def __init__(self):\n"
        "        self.future = Future()\n\n\n"
        "def drive(q, x):\n"
        "    req = q.get()\n"
        "    risky(x)\n"
        "    req.future.set_result(x)\n")
    kw = dict(rules=["future-lifecycle"], baseline=Baseline(),
              root=str(tmp_path),
              dep_cache=str(tmp_path / "dep.json"))
    full = run([str(tmp_path)], **kw)
    assert [(f.path, f.line) for f in full.findings] \
        == [("worker.py", 12)], [f.render() for f in full.findings]
    (tmp_path / "util.py").write_text(       # edit ONLY the callee
        "def risky(x):\n"
        "    x = x + 1\n"
        "    if x:\n"
        "        raise RuntimeError(x)\n")
    rep = run([str(tmp_path)], only=["util.py"],
              expand_dependents=True, **kw)
    assert rep.dep_cache == "hit"
    assert [(f.path, f.line) for f in rep.findings] \
        == [("worker.py", 12)], [f.render() for f in rep.findings]
    # the witness names the EDITED origin raise line
    assert "util.py:4" in rep.findings[0].message


def test_finally_resolution_covers_return_legs(tmp_path):
    """A future resolved in a finally covers a `return` INSIDE the try
    too — the return leg runs the finalbody before exiting, so no
    strand may report (the rule's own recommended fix must not keep
    firing)."""
    (tmp_path / "m.py").write_text(
        "from concurrent.futures import Future\n\n\n"
        "class Req:\n"
        "    def __init__(self):\n"
        "        self.future = Future()\n\n\n"
        "def risky(x):\n"
        "    if x:\n"
        "        raise RuntimeError(x)\n\n\n"
        "def drive(q, x):\n"
        "    req = q.get()\n"
        "    try:\n"
        "        if x:\n"
        "            return 1\n"
        "        risky(x)\n"
        "    finally:\n"
        "        if not req.future.done():\n"
        "            req.future.set_result(x)\n"
        "    return 0\n")
    rep = run([str(tmp_path)], rules=["future-lifecycle"],
              baseline=Baseline(), root=str(tmp_path))
    assert rep.clean, [f.render() for f in rep.findings]


def test_done_guarded_late_resolve_is_not_a_double(tmp_path):
    """A resolve under `if not v.future.done():` AFTER an earlier
    resolve on the same path is the sanctioned idempotent form — the
    R-state is runtime-infeasible on the not-done branch and must not
    report a phantom double-resolve; the truly unguarded second
    resolve still does."""
    common = (
        "from concurrent.futures import Future\n\n\n"
        "class Req:\n"
        "    def __init__(self):\n"
        "        self.future = Future()\n\n\n")
    kw = dict(rules=["future-lifecycle"], baseline=Baseline(),
              root=str(tmp_path))
    (tmp_path / "m.py").write_text(
        common
        + "def drive(q, x, exc):\n"
        "    req = q.get()\n"
        "    req.future.set_result(x)\n"
        "    if not req.future.done():\n"
        "        req.future.set_exception(exc)\n")
    rep = run([str(tmp_path)], **kw)
    assert rep.clean, [f.render() for f in rep.findings]
    (tmp_path / "m.py").write_text(        # positive control: bare
        common
        + "def drive(q, x, exc):\n"
        "    req = q.get()\n"
        "    req.future.set_result(x)\n"
        "    req.future.set_exception(exc)\n")
    rep = run([str(tmp_path)], **kw)
    assert [f.rule for f in rep.findings] == ["future-lifecycle"]
    assert "SECOND time" in rep.findings[0].message
