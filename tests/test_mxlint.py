"""mxlint: the AST static-analysis suite (ISSUE 8).

Three layers:

1. **fixture corpus** — every rule fires on its seeded-violation file
   under ``tests/lint_fixtures/`` (exactly the seeded findings, at the
   seeded lines — including the aliased ``from jax import jit as J``
   form the old grep lint missed) and stays silent on the compliant
   twin;
2. **framework** — suppression grammar (justification REQUIRED),
   baseline grandfathering, stale-baseline tolerance + pruning, parse
   errors as findings, JSON shape, CLI exit codes;
3. **tier-1 gate lane** — ``python tools/mxlint.py mxnet_tpu tools
   bench.py`` exits 0 with ZERO unsuppressed findings, and the
   ``--json`` artifact banks next to the bench JSONs
   (``$MXTPU_ARTIFACT_DIR/mxlint.json``, default /tmp/mxtpu_artifacts)
   so the lint trajectory is recorded every round.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu.analysis import run, ALL_RULE_IDS
from mxnet_tpu.analysis.core import Baseline, SUPPRESSION_RULE

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(ROOT, "tests", "lint_fixtures")
MXLINT = os.path.join(ROOT, "tools", "mxlint.py")


def _fixture(name, rules):
    """Report over one fixture file/dir, no baseline."""
    return run([os.path.join(FIXTURES, name)], rules=rules,
               baseline=Baseline(), root=ROOT)


def _lines(report, rule=None):
    return sorted(f.line for f in report.findings
                  if rule is None or f.rule == rule)


# ---------------------------------------------------------------------------
# Fixture corpus: seeded violation fires, compliant twin is silent
# ---------------------------------------------------------------------------

def test_jit_site_fixture_pair():
    rep = _fixture("jit_site_violation.py", ["jit-site"])
    # 6 seeded: direct call, ALIASED `from jax import jit as J` (the
    # form the grep lint walked past), aliased pjit, pmap, decorator,
    # and the @functools.partial(jax.jit, ...) wrap
    assert _lines(rep) == [11, 15, 19, 23, 26, 31], \
        [f.render() for f in rep.findings]
    assert any("decorator" in f.message for f in rep.findings)
    assert any("functools.partial" in f.message for f in rep.findings)
    ok = _fixture("jit_site_ok.py", ["jit-site"])
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_dispatch_hook_fixture_pair():
    rep = _fixture("dispatch_hook_violation.py", ["dispatch-hook"])
    assert _lines(rep) == [8, 12], [f.render() for f in rep.findings]
    ok = _fixture("dispatch_hook_ok.py", ["dispatch-hook"])
    assert ok.clean, [f.render() for f in ok.findings]


def test_lock_discipline_fixture_pair():
    rep = _fixture("lock_discipline_violation.py", ["lock-discipline"])
    # unlocked global read, finalizer-lock (the PR 4 deadlock class),
    # the read+write halves of the unlocked `self._stats[k] = ...`, and
    # a deferred callback defined under the lock but running without it
    assert _lines(rep) == [14, 18, 28, 28, 43, 53], \
        [f.render() for f in rep.findings]
    assert any("weakref.finalize" in f.message for f in rep.findings)
    ok = _fixture("lock_discipline_ok.py", ["lock-discipline"])
    # Condition alias, _locked-suffix helper, lock-free finalizer,
    # __init__ construction, callback re-acquiring where it runs: all
    # clean with zero suppressions
    assert ok.clean and not ok.suppressed, \
        [f.render() for f in ok.findings]


def test_host_sync_fixture_pair():
    rep = _fixture("host_sync_violation.py", ["host-sync"])
    # ...including the standalone marker above a DECORATED def (which
    # arms the decorator's line, not the def's)
    assert _lines(rep) == [9, 10, 11, 18], \
        [f.render() for f in rep.findings]
    msgs = " ".join(f.message for f in rep.findings)
    for form in (".asnumpy()", ".wait_to_read()", "np.asarray"):
        assert form in msgs
    ok = _fixture("host_sync_ok.py", ["host-sync"])
    assert ok.clean, [f.render() for f in ok.findings]
    # the one justified disable in the twin is honoured AND recorded
    assert len(ok.suppressed) == 1
    assert ok.suppressed[0][1]          # justification text rides along


def test_donation_fixture_pair():
    rep = _fixture("donation_violation.py", ["donation-safety"])
    # ...including the use after a donation that happens inside an
    # except handler (handler bodies are in the linear statement order)
    assert _lines(rep) == [13, 19, 26, 36], \
        [f.render() for f in rep.findings]
    assert any("loop" in f.message for f in rep.findings)
    ok = _fixture("donation_ok.py", ["donation-safety"])
    assert ok.clean, [f.render() for f in ok.findings]


def test_registry_fixture_pair():
    rep = _fixture("registry_violation", ["registry-consistency"])
    msgs = [f.message for f in rep.findings]
    assert len(msgs) == 7, [f.render() for f in rep.findings]
    # one undeclared use per registry kind + the uncovered prefix...
    assert any("'d2h_typo'" in m and "SITES" in m for m in msgs)
    assert any("'bad_code'" in m for m in msgs)
    assert any("'serving.requets'" in m for m in msgs)
    assert any("dynamic counter prefix" in m for m in msgs)
    # ...and one unused declaration per registry kind
    assert any("'kv_push'" in m and "never consulted" in m for m in msgs)
    assert any("'group2ctx'" in m and "never constructed" in m
               for m in msgs)
    assert any("'faults.injected.*'" in m and "dead" in m for m in msgs)
    ok = _fixture("registry_ok", ["registry-consistency"])
    assert ok.clean, [f.render() for f in ok.findings]


# ---------------------------------------------------------------------------
# Framework: suppressions, baseline, parse errors
# ---------------------------------------------------------------------------

def _write(tmp_path, name, text):
    p = tmp_path / name
    p.write_text(text)
    return str(p)


_VIOLATION_SRC = "import jax\n\n\ndef f(fn):\n    return jax.jit(fn)%s\n"


def test_suppression_requires_justification(tmp_path):
    # bare disable: the finding STILL reports, plus a grammar finding
    p = _write(tmp_path, "bare.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-site")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE], \
        [f.render() for f in rep.findings]
    assert "justification" in rep.findings[0].message \
        or "justification" in rep.findings[1].message


def test_suppression_with_justification_silences(tmp_path):
    p = _write(tmp_path, "just.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-site -- fixture")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert rep.clean
    assert [(f.rule, j) for f, j in rep.suppressed] == \
        [("jit-site", "fixture")]


def test_standalone_suppression_covers_next_line(tmp_path):
    src = ("import jax\n\n\ndef f(fn):\n"
           "    # mxlint: disable=jit-site -- covers the next line\n"
           "    return jax.jit(fn)\n")
    p = _write(tmp_path, "standalone.py", src)
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert rep.clean and len(rep.suppressed) == 1


def test_unknown_rule_in_suppression_is_flagged(tmp_path):
    p = _write(tmp_path, "typo.py",
               _VIOLATION_SRC % "   # mxlint: disable=jit-sight -- typo")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE]
    assert any("unknown rule id" in f.message for f in rep.findings)


def test_baseline_grandfathers_and_reports_stale(tmp_path):
    p = _write(tmp_path, "old.py", _VIOLATION_SRC % "")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    assert len(rep.findings) == 1
    doc = Baseline.render(rep.findings)
    doc["findings"].append({"rule": "jit-site", "path": "gone.py",
                            "anchor": "jax.jit(deleted_code)"})
    bl_path = _write(tmp_path, "bl.json", json.dumps(doc))
    rep2 = run([p], rules=["jit-site"], baseline=bl_path,
               root=str(tmp_path))
    # grandfathered: clean exit, the finding visible as baselined, and
    # the entry whose code no longer exists WARNS instead of erroring
    assert rep2.clean
    assert len(rep2.baselined) == 1
    assert len(rep2.stale_baseline) == 1
    assert rep2.stale_baseline[0]["path"] == "gone.py"
    assert "stale" in rep2.render_text()


def test_baseline_loader_tolerates_garbage(tmp_path):
    p = _write(tmp_path, "v.py", _VIOLATION_SRC % "")
    bl_path = _write(tmp_path, "bad.json", "{not json")
    rep = run([p], rules=["jit-site"], baseline=bl_path,
              root=str(tmp_path))
    # unreadable baseline: warn and lint WITHOUT it — never a crash
    assert len(rep.findings) == 1
    assert any("unreadable" in w for w in rep.warnings)
    bl2 = _write(tmp_path, "odd.json",
                 json.dumps({"findings": [42, {"rule": "jit-site"}]}))
    rep2 = run([p], rules=["jit-site"], baseline=bl2, root=str(tmp_path))
    assert len(rep2.findings) == 1 and len(rep2.warnings) == 2


def test_parse_error_is_a_finding(tmp_path):
    p = _write(tmp_path, "broken.py", "def f(:\n")
    rep = run([p], baseline=Baseline(), root=str(tmp_path))
    assert [f.rule for f in rep.findings] == ["parse-error"]


def test_baseline_never_hides_gate_compromising_rules(tmp_path):
    """Neither --update-baseline nor a hand-edited entry may grandfather
    a bare suppression or a parse error — those mean the gate itself is
    compromised and must keep failing until the code is fixed."""
    bare = _write(tmp_path, "bare.py",
                  _VIOLATION_SRC % "   # mxlint: disable=jit-site")
    broken = _write(tmp_path, "broken.py", "def f(:\n")
    rep = run([bare, broken], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    rules = sorted(f.rule for f in rep.findings)
    assert rules == ["jit-site", SUPPRESSION_RULE, "parse-error"]
    # render (what --update-baseline writes) drops both forbidden rules
    doc = Baseline.render(rep.findings)
    assert [e["rule"] for e in doc["findings"]] == ["jit-site"]
    # and even a hand-edited baseline listing them cannot hide them
    doc["findings"].extend(
        {"rule": f.rule, "path": f.path, "anchor": f.anchor}
        for f in rep.findings if f.rule != "jit-site")
    bl_path = _write(tmp_path, "bl.json", json.dumps(doc))
    rep2 = run([bare, broken], rules=["jit-site"], baseline=bl_path,
               root=str(tmp_path))
    assert sorted(f.rule for f in rep2.findings) == \
        [SUPPRESSION_RULE, "parse-error"], \
        [f.render() for f in rep2.findings]


def test_registry_duplicate_declaration_is_flagged(tmp_path):
    """Two SITES declarations in one scan (e.g. a fixture mini-registry
    next to the runtime's) must not silently bind an arbitrary one —
    the duplicate is a finding and uses check against the FIRST."""
    a = _write(tmp_path, "a.py",
               'SITES = ("dispatch",)\n\n\ndef go(fire):\n'
               '    fire("dispatch")\n')
    b = _write(tmp_path, "b.py", 'SITES = ("other",)\n')
    rep = run([a, b], rules=["registry-consistency"], baseline=Baseline(),
              root=str(tmp_path))
    msgs = [f.message for f in rep.findings]
    assert any("duplicate SITES" in m for m in msgs), msgs
    # the legitimate use against the first declaration stays clean
    assert not any("not declared" in m for m in msgs), msgs


def test_json_report_shape(tmp_path):
    p = _write(tmp_path, "v.py", _VIOLATION_SRC % "")
    rep = run([p], rules=["jit-site"], baseline=Baseline(),
              root=str(tmp_path))
    doc = rep.to_dict()
    assert doc["clean"] is False
    assert doc["counts"] == {"jit-site": 1}
    f = doc["findings"][0]
    assert set(f) == {"rule", "path", "line", "col", "message", "anchor"}
    json.dumps(doc)                      # JSON-serializable end to end


# ---------------------------------------------------------------------------
# CLI: stable exit codes, JSON artifact, baseline update
# ---------------------------------------------------------------------------

def _cli(args, cwd=ROOT):
    return subprocess.run([sys.executable, MXLINT] + args,
                          stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                          text=True, timeout=300, cwd=cwd)


def test_cli_exit_codes(tmp_path):
    clean = _write(tmp_path, "clean.py", "x = 1\n")
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    assert _cli(["--baseline", "none", clean]).returncode == 0
    proc = _cli(["--baseline", "none", dirty])
    assert proc.returncode == 1
    assert "jit-site" in proc.stdout
    assert _cli(["--no-such-flag", clean]).returncode == 2
    assert _cli(["--baseline", "none",
                 str(tmp_path / "missing.py")]).returncode == 2
    assert _cli(["--rules", "not-a-rule", clean]).returncode == 2
    assert _cli([]).returncode == 2


def test_cli_json_operand_forms(tmp_path):
    clean = _write(tmp_path, "clean.py", "x = 1\n")
    # '-' means stdout: the report prints, nothing named '-' is linted
    proc = _cli(["--baseline", "none", "--json", "-", clean])
    assert proc.returncode == 0, proc.stderr
    assert json.loads(proc.stdout)["clean"] is True
    # with no operand the report also goes to stdout
    proc = _cli(["--baseline", "none", "--json", clean])
    assert proc.returncode == 0
    assert json.loads(proc.stdout)["paths"] == [clean]
    # an ambiguous operand (not '-', not *.json, not an existing lint
    # path) is a usage error, never silently linted or guessed at
    proc = _cli(["--baseline", "none", "--json",
                 str(tmp_path / "report.out"), clean])
    assert proc.returncode == 2
    assert "--json operand" in proc.stderr


def test_cli_list_rules():
    proc = _cli(["--list-rules"])
    assert proc.returncode == 0
    assert proc.stdout.split() == list(ALL_RULE_IDS)


def test_cli_update_baseline_prunes_stale(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    bl = str(tmp_path / "bl.json")
    with open(bl, "w") as f:
        json.dump({"findings": [{"rule": "jit-site", "path": "gone.py",
                                 "anchor": "deleted"}]}, f)
    proc = _cli(["--baseline", bl, "--update-baseline", dirty])
    assert proc.returncode == 0, proc.stderr
    with open(bl) as f:
        doc = json.load(f)
    anchors = [e["anchor"] for e in doc["findings"]]
    assert anchors == ["return jax.jit(fn)"]        # stale entry pruned
    # and the refreshed baseline makes the same run clean
    assert _cli(["--baseline", bl, dirty]).returncode == 0


def test_cli_update_baseline_partial_rules_preserves_others(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    bl = str(tmp_path / "bl.json")
    assert _cli(["--baseline", bl,
                 "--update-baseline", dirty]).returncode == 0
    # a dispatch-hook-only refresh must not wipe the jit-site entry the
    # full gate run depends on
    proc = _cli(["--baseline", bl, "--rules", "dispatch-hook",
                 "--update-baseline", dirty])
    assert proc.returncode == 0, proc.stderr
    with open(bl) as f:
        doc = json.load(f)
    assert [e["rule"] for e in doc["findings"]] == ["jit-site"]
    assert _cli(["--baseline", bl, dirty]).returncode == 0


def test_cli_update_baseline_needs_a_file(tmp_path):
    dirty = _write(tmp_path, "dirty.py", _VIOLATION_SRC % "")
    # '--baseline none' disabled the baseline: nothing to rewrite, and
    # silently clobbering the default committed file would be worse
    proc = _cli(["--baseline", "none", "--update-baseline", dirty])
    assert proc.returncode == 2
    assert "no file to write" in proc.stderr


# ---------------------------------------------------------------------------
# Tier-1 gate lane: the whole runtime lints clean, artifact banked
# ---------------------------------------------------------------------------

def test_mxlint_gate_lane():
    """`run_checks.sh lint` equivalent: zero unsuppressed findings over
    mxnet_tpu/ tools/ bench.py against the committed baseline, with the
    JSON report banked next to the bench artifacts."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "mxlint.json")
    proc = _cli(["--json", art, "mxnet_tpu", "tools", "bench.py"])
    assert proc.returncode == 0, proc.stdout + proc.stderr
    with open(art) as f:
        doc = json.load(f)
    assert doc["clean"] is True
    assert doc["findings"] == []
    assert doc["rules"] == list(ALL_RULE_IDS)
    # every honoured suppression carries its justification text, and
    # the committed baseline has no stale entries
    assert doc["suppressed"], "expected justified disables in-tree"
    assert all(s["justification"] for s in doc["suppressed"])
    assert doc["stale_baseline"] == []
    # the grandfathered raw-jit sites are visible, not silently gone
    assert any(b["rule"] == "jit-site" for b in doc["baselined"])


def test_gate_catches_a_seeded_regression(tmp_path):
    """End-to-end negative control: drop an aliased-jit file into a
    copy of the scan set and the gate exits 1 — proving the lane fails
    when someone actually adds a raw compile site."""
    bad = _write(tmp_path, "regression.py",
                 "from jax import jit as J\n\n\ndef f(fn):\n"
                 "    return J(fn)\n")
    proc = _cli(["--baseline",
                 os.path.join(ROOT, "tools", "mxlint_baseline.json"),
                 bad])
    assert proc.returncode == 1
    assert "jit-site" in proc.stdout
