"""Tier-1 smoke lane for the continuous-batching decode engine.

Runs ``tools/serve_probe.py --decode-smoke`` as a subprocess and pins
the ISSUE 16 acceptance numbers:

- slot-batched decode is BIT-EXACT (tokens and logits) against
  one-at-a-time decode through the same engine;
- the open-loop skewed-length stream through continuous batching
  sustains >= 2x the tokens/s of wave-synchronized static whole-batch
  decode of the same work;
- ZERO ``jit_compile`` spans anywhere in the timed windows (warmup
  built every prompt-length and slot-count bucket program up front);
- the mp leg: under ``DECODE_PARTITION_RULES`` on the 1x8 CPU mesh the
  KV-cache pool's committed ledger bytes read exactly 1/8 of the same
  pool replicated onto that mesh.

The probe's JSON banks as an artifact (``$MXTPU_ARTIFACT_DIR/
decode_smoke.json``, default /tmp/mxtpu_artifacts) so the decode
trajectory is recorded every round.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_probe(art):
    # the mp leg NEEDS the multi-device mesh: unlike the single-device
    # serving lanes this one keeps (and pins) the forced device count
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_probe.py"),
         "--decode-smoke", "--json-out", art],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, timeout=900, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout[-2000:]
    with open(art) as f:
        return json.loads(f.read())


def test_decode_smoke_lane():
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "decode_smoke.json")
    try:
        out = _run_probe(art)
    except AssertionError:
        out = _run_probe(art)   # one retry under CI timing noise
    assert out["lane"] == "decode_smoke"
    assert out["gates_passed"] is True, out
    # deterministic guards, independent of the timing gate
    assert out["bit_exact"] is True
    assert out["jit_compiles_timed"] == 0, out
    assert out["devices"] >= 8
    assert out["mp"]["ledger_ratio"] == 8.0, out["mp"]
    assert out["mp"]["replicated_kv_bytes"] \
        == 8 * out["mp"]["sharded_kv_bytes"], out["mp"]
    # the steady-state schedule really was continuous: every decode
    # dispatch advanced a full-or-draining pool, so the step count
    # lands at ~tokens/slots, nowhere near static's waves x longest
    c = out["telemetry"]["counters"]
    assert c["decode.tokens"] == out["total_tokens"]
    assert c["decode.steps"] <= out["total_tokens"] // out["slots"] \
        + out["gen_long"], c
    # per-token latency percentiles banked, coordinated-omission-free
    assert out["token_latency_ms"]["p99_ms"] is not None
    # the timing gate proper (retried once above under CI noise)
    assert out["decode_speedup"] >= out["speedup_gate"], out
