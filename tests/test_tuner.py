"""Operator tuner: measured dispatch-level implementation choice
(parity target: reference src/operator/operator_tune.h:37-202 —
measure candidates, cache per signature, MXNET_USE_OPERATOR_TUNING /
MXNET_OUTPUT_TUNING_DATA gates; here the candidates are framework
lowerings/meta-params rather than OMP-vs-serial)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.tuner import OperatorTuner, tuned_choice, tuner


@pytest.fixture(autouse=True)
def _fresh_tuner(monkeypatch):
    monkeypatch.delenv("MXNET_TUNING_CACHE", raising=False)
    monkeypatch.delenv("MXNET_USE_OPERATOR_TUNING", raising=False)
    tuner().clear()
    yield
    tuner().clear()


def _slow_fast_candidates(calls):
    import time
    import jax.numpy as jnp

    def slow():
        calls.append("slow")
        time.sleep(0.05)
        return jnp.zeros(())

    def fast():
        calls.append("fast")
        return jnp.zeros(())

    return [("slow", slow), ("fast", fast)]


def test_choose_picks_faster_and_caches():
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    n = len(calls)
    # second query: cache hit, no re-measurement
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    assert len(calls) == n
    # different signature re-measures
    assert t.choose("op", "k2", _slow_fast_candidates(calls)) == "fast"
    assert len(calls) > n
    recs = t.records()
    assert recs[0][0] == "op" and recs[0][2] == "fast"
    assert set(recs[0][3]) == {"slow", "fast"}


def test_disabled_returns_default(monkeypatch):
    monkeypatch.setenv("MXNET_USE_OPERATOR_TUNING", "0")
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "slow"
    assert calls == []  # nothing measured


def test_single_candidate_short_circuits():
    t = OperatorTuner()
    assert t.choose("op", "k", [("only", lambda: 1 / 0)]) == "only"


def test_failing_candidate_excluded():
    import jax.numpy as jnp
    t = OperatorTuner()

    def broken():
        raise RuntimeError("unsupported here")

    got = t.choose("op", "k", [("broken", broken),
                               ("ok", lambda: jnp.ones(()))])
    assert got == "ok"


def test_persistent_cache_roundtrip(tmp_path, monkeypatch):
    path = str(tmp_path / "tuning.json")
    monkeypatch.setenv("MXNET_TUNING_CACHE", path)
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    with open(path) as f:
        assert json.load(f) == {"op|k": "fast"}
    # a new process-equivalent tuner loads the decision without measuring
    calls2 = []
    t2 = OperatorTuner()
    assert t2.choose("op", "k", _slow_fast_candidates(calls2)) == "fast"
    assert calls2 == []


def test_tuned_choice_under_tracing_never_measures():
    import jax
    import jax.numpy as jnp
    calls = []

    def f(x):
        lab = tuned_choice("op", "k", _slow_fast_candidates(calls),
                           args=(x,))
        assert lab == "slow"  # default candidate: no cache entry
        return x + 1

    jax.jit(f)(jnp.zeros(()))
    assert calls == []  # tracing must not trigger device measurement
    # but a prior eager decision IS visible at trace time
    tuner().choose("op", "k", _slow_fast_candidates(calls))

    def g(x):
        assert tuned_choice("op", "k", _slow_fast_candidates(calls),
                            args=(x,)) == "fast"
        return x + 1

    jax.jit(g)(jnp.zeros(()))


def test_flash_attention_tuned_default_matches_reference():
    """block_q=None goes through the tuner path (default off-TPU) and
    stays numerically identical to an explicit block size."""
    from mxnet_tpu.pallas.flash_attention import flash_attention
    from mxnet_tpu.parallel import attention
    rs = np.random.RandomState(0)
    q = rs.randn(1, 2, 64, 16).astype(np.float32)
    k = rs.randn(1, 2, 64, 16).astype(np.float32)
    v = rs.randn(1, 2, 64, 16).astype(np.float32)
    import jax.numpy as jnp
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True)
    ref = attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                    causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_tuned_impl(monkeypatch):
    """On a 'tpu' backend the ring-attention impl comes from the tuner;
    here the pallas candidate cannot lower (cpu devices), so the tuner
    excludes it and selects the XLA path — exercising candidate-failure
    exclusion end to end."""
    import jax
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"sp": 1})
    rs = np.random.RandomState(0)
    q = rs.randn(1, 2, 16, 8).astype(np.float32)
    k = rs.randn(1, 2, 16, 8).astype(np.float32)
    v = rs.randn(1, 2, 16, 8).astype(np.float32)
    import jax.numpy as jnp
    ref = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, use_pallas=False)

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    recs = [r for r in tuner().records() if r[0] == "ring_attention.impl"]
    assert recs and recs[-1][2] == "xla"


# ---------------------------------------------------------------------------
# Card-corpus serving autotuner (ISSUE 6)
# ---------------------------------------------------------------------------

def _serving_rec(**over):
    rec = {"kind": "serving", "max_batch": 16,
           "rows_hist": {"3": 50, "10": 30, "16": 5},
           "bucket_ms": {"4": {"total_ms": 40.0, "count": 10},
                         "16": {"total_ms": 160.0, "count": 10}},
           "spans": {"serve_d2h": {"total_ms": 100.0, "count": 10},
                     "serve_batch": {"total_ms": 50.0, "count": 10}}}
    rec.update(over)
    return rec


def test_plan_serving_deterministic_and_json_native():
    from mxnet_tpu.tuner import plan_serving
    recs = [_serving_rec()]
    p1, p2 = plan_serving(recs), plan_serving(recs)
    assert p1 == p2
    # JSON-native: the plan round-trips through the JSONL corpus store
    assert json.loads(json.dumps(p1)) == p1
    assert p1["kind"] == "autotune_plan"


def test_plan_serving_picks_observed_boundaries():
    """Traffic at rows 3/10/16 with a linear-ish cost model: the
    optimal bucket tops are exactly the observed row counts (any pow-2
    set pads 3->4 and 10->16)."""
    from mxnet_tpu.tuner import plan_serving
    plan = plan_serving([_serving_rec()])
    assert plan["buckets"] == [3, 10, 16]
    assert plan["max_batch"] == 16
    # max_batch ALWAYS tops the set so every request stays coverable
    assert plan["buckets"][-1] == 16


def test_plan_serving_merges_records_and_clamps():
    from mxnet_tpu.tuner import plan_serving
    # rows above max_batch (stale corpus from a larger engine) clamp out
    recs = [_serving_rec(), _serving_rec(rows_hist={"3": 5, "99": 7})]
    plan = plan_serving(recs, max_batch=16)
    assert plan["buckets"][-1] == 16
    assert all(b <= 16 for b in plan["buckets"])
    assert plan["basis"]["records"] == 2


def test_plan_serving_max_inflight_from_spans():
    from mxnet_tpu.tuner import plan_serving
    # d2h mean 10ms vs batch mean 5ms -> 1 + ceil(2) = 3
    plan = plan_serving([_serving_rec()])
    assert plan["max_inflight"] == 3
    # no span data -> the default
    rec = _serving_rec(spans={})
    assert plan_serving([rec])["max_inflight"] == 2
    assert plan_serving([rec], default_inflight=5)["max_inflight"] == 5


def test_plan_serving_without_measured_ms_uses_linear_prior():
    from mxnet_tpu.tuner import plan_serving
    plan = plan_serving([_serving_rec(bucket_ms={})])
    # rows-histogram-only corpus still plans (linear ms=batch prior)
    assert plan is not None and plan["buckets"][-1] == 16


def test_plan_serving_respects_max_buckets():
    from mxnet_tpu.tuner import plan_serving
    hist = {str(r): 10 for r in range(1, 17)}       # 16 distinct rows
    plan = plan_serving([_serving_rec(rows_hist=hist)], max_buckets=4)
    assert len(plan["buckets"]) <= 4
    assert plan["buckets"][-1] == 16


def test_plan_serving_empty_corpus():
    from mxnet_tpu.tuner import plan_serving
    assert plan_serving([]) is None
    assert plan_serving(None) is None
    assert plan_serving([{"kind": "programs"}]) is None
    # a serving record with no histogram has nothing to plan from
    assert plan_serving([_serving_rec(rows_hist={})]) is None


def test_plan_serving_ignores_garbage_fields():
    from mxnet_tpu.tuner import plan_serving
    rec = _serving_rec(rows_hist={"3": 5, "bad": "x"},
                       bucket_ms={"4": "not-a-dict",
                                  "16": {"total_ms": "nope"}})
    plan = plan_serving([rec])
    assert plan is not None and plan["buckets"][-1] == 16


def test_plan_serving_filters_by_graph_identity():
    """A shared corpus must not plan one model from another model's
    traffic: with ``graph=`` given, only records stamped with the SAME
    fingerprint participate."""
    from mxnet_tpu.tuner import plan_serving
    mine = _serving_rec(graph=["hashA", "NHWC"])
    other = _serving_rec(graph=["hashB", None],
                         rows_hist={"7": 1000})
    unstamped = _serving_rec(rows_hist={"2": 500})   # no graph field
    plan = plan_serving([mine, other, unstamped],
                        graph=["hashA", "NHWC"])
    assert plan["basis"]["records"] == 1
    assert plan["buckets"] == [3, 10, 16]       # mine only
    assert plan["graph"] == ["hashA", "NHWC"]
    # no matching records -> no plan, never a cross-model one
    assert plan_serving([other], graph=["hashA", "NHWC"]) is None
    # without graph, everything still pools (explicit opt-out)
    assert plan_serving([mine, other])["basis"]["records"] == 2
