"""Operator tuner: measured dispatch-level implementation choice
(parity target: reference src/operator/operator_tune.h:37-202 —
measure candidates, cache per signature, MXNET_USE_OPERATOR_TUNING /
MXNET_OUTPUT_TUNING_DATA gates; here the candidates are framework
lowerings/meta-params rather than OMP-vs-serial)."""
import json
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.tuner import OperatorTuner, tuned_choice, tuner


@pytest.fixture(autouse=True)
def _fresh_tuner(monkeypatch):
    monkeypatch.delenv("MXNET_TUNING_CACHE", raising=False)
    monkeypatch.delenv("MXNET_USE_OPERATOR_TUNING", raising=False)
    tuner().clear()
    yield
    tuner().clear()


def _slow_fast_candidates(calls):
    import time
    import jax.numpy as jnp

    def slow():
        calls.append("slow")
        time.sleep(0.05)
        return jnp.zeros(())

    def fast():
        calls.append("fast")
        return jnp.zeros(())

    return [("slow", slow), ("fast", fast)]


def test_choose_picks_faster_and_caches():
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    n = len(calls)
    # second query: cache hit, no re-measurement
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    assert len(calls) == n
    # different signature re-measures
    assert t.choose("op", "k2", _slow_fast_candidates(calls)) == "fast"
    assert len(calls) > n
    recs = t.records()
    assert recs[0][0] == "op" and recs[0][2] == "fast"
    assert set(recs[0][3]) == {"slow", "fast"}


def test_disabled_returns_default(monkeypatch):
    monkeypatch.setenv("MXNET_USE_OPERATOR_TUNING", "0")
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "slow"
    assert calls == []  # nothing measured


def test_single_candidate_short_circuits():
    t = OperatorTuner()
    assert t.choose("op", "k", [("only", lambda: 1 / 0)]) == "only"


def test_failing_candidate_excluded():
    import jax.numpy as jnp
    t = OperatorTuner()

    def broken():
        raise RuntimeError("unsupported here")

    got = t.choose("op", "k", [("broken", broken),
                               ("ok", lambda: jnp.ones(()))])
    assert got == "ok"


def test_persistent_cache_roundtrip(tmp_path, monkeypatch):
    path = str(tmp_path / "tuning.json")
    monkeypatch.setenv("MXNET_TUNING_CACHE", path)
    calls = []
    t = OperatorTuner()
    assert t.choose("op", "k", _slow_fast_candidates(calls)) == "fast"
    with open(path) as f:
        assert json.load(f) == {"op|k": "fast"}
    # a new process-equivalent tuner loads the decision without measuring
    calls2 = []
    t2 = OperatorTuner()
    assert t2.choose("op", "k", _slow_fast_candidates(calls2)) == "fast"
    assert calls2 == []


def test_tuned_choice_under_tracing_never_measures():
    import jax
    import jax.numpy as jnp
    calls = []

    def f(x):
        lab = tuned_choice("op", "k", _slow_fast_candidates(calls),
                           args=(x,))
        assert lab == "slow"  # default candidate: no cache entry
        return x + 1

    jax.jit(f)(jnp.zeros(()))
    assert calls == []  # tracing must not trigger device measurement
    # but a prior eager decision IS visible at trace time
    tuner().choose("op", "k", _slow_fast_candidates(calls))

    def g(x):
        assert tuned_choice("op", "k", _slow_fast_candidates(calls),
                            args=(x,)) == "fast"
        return x + 1

    jax.jit(g)(jnp.zeros(()))


def test_flash_attention_tuned_default_matches_reference():
    """block_q=None goes through the tuner path (default off-TPU) and
    stays numerically identical to an explicit block size."""
    from mxnet_tpu.pallas.flash_attention import flash_attention
    from mxnet_tpu.parallel import attention
    rs = np.random.RandomState(0)
    q = rs.randn(1, 2, 64, 16).astype(np.float32)
    k = rs.randn(1, 2, 64, 16).astype(np.float32)
    v = rs.randn(1, 2, 64, 16).astype(np.float32)
    import jax.numpy as jnp
    out = flash_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                          causal=True)
    ref = attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
                    causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_tuned_impl(monkeypatch):
    """On a 'tpu' backend the ring-attention impl comes from the tuner;
    here the pallas candidate cannot lower (cpu devices), so the tuner
    excludes it and selects the XLA path — exercising candidate-failure
    exclusion end to end."""
    import jax
    from mxnet_tpu import parallel

    mesh = parallel.make_mesh({"sp": 1})
    rs = np.random.RandomState(0)
    q = rs.randn(1, 2, 16, 8).astype(np.float32)
    k = rs.randn(1, 2, 16, 8).astype(np.float32)
    v = rs.randn(1, 2, 16, 8).astype(np.float32)
    import jax.numpy as jnp
    ref = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh, use_pallas=False)

    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    out = parallel.ring_attention(jnp.asarray(q), jnp.asarray(k),
                                  jnp.asarray(v), mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    recs = [r for r in tuner().records() if r[0] == "ring_attention.impl"]
    assert recs and recs[-1][2] == "xla"
