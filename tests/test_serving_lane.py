"""Tier-1 smoke lane for the serving path.

Runs ``tools/serve_probe.py --serve-smoke`` (CPU backend, tiny MLP,
256 one-row requests) as a subprocess and pins the ISSUE 5 acceptance
numbers:

- the micro-batched ``serving.InferenceEngine`` sustains >= 3x the
  throughput of the one-request-at-a-time ``Predictor.forward`` loop at
  max_batch >= 8;
- EXACTLY one compiled program per bucket signature (the probe asserts
  it via ``telemetry.programs()``) and zero compiles inside the timed
  steady-state window;
- request p95 latency lands in the JSON artifact.

The probe's JSON banks as an artifact (``$MXTPU_ARTIFACT_DIR/
serve_smoke.json``, default /tmp/mxtpu_artifacts) so the serving
trajectory is recorded every round even when the TPU tunnel is down.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_probe(art, lane_flag="--serve-smoke"):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)   # single-device lane
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "serve_probe.py"),
         lane_flag, "--json-out", art],
        stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
        text=True, timeout=900, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout[-2000:]
    with open(art) as f:
        return json.loads(f.read())


def test_serve_smoke_lane():
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "serve_smoke.json")
    try:
        out = _run_probe(art)
    except AssertionError:
        out = _run_probe(art)   # one retry under CI timing noise
    assert out["lane"] == "serve_smoke"
    assert out["gates_passed"] is True, out
    assert out["max_batch"] >= 8
    # deterministic guards (no timing): one compile per bucket, none in
    # the steady-state window, and the latency percentiles are banked
    assert out["compiles_per_bucket"] == 1.0, out
    assert out["telemetry"]["jit_compiles"] == 0, out
    assert out["latency_ms"]["p95_ms"] is not None
    assert out["batched_req_s"] > 0 and out["unbatched_req_s"] > 0
    assert out["serve_speedup"] >= 3.0, out


def test_chaos_smoke_lane():
    """The fault-tolerant-serving acceptance lane (ISSUE 7): the
    open-loop ladder at 2x measured capacity with injected dispatch
    faults (delay throttle + probabilistic raises) against the bounded
    admission queue and per-request deadlines. The probe gates: zero
    hung futures, shed counters > 0 at 2x, admitted-request p99 <= the
    configured deadline, and exact injected-fault accounting
    (telemetry counter == registry fire count). This test pins the
    artifact schema and re-asserts the deterministic halves."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "chaos_smoke.json")
    try:
        out = _run_probe(art, "--chaos-smoke")
    except AssertionError:
        out = _run_probe(art, "--chaos-smoke")   # one retry under noise
    assert out["lane"] == "chaos_smoke"
    assert out["gates_passed"] is True, out
    hot = out["offered_loads"]["2.0"]
    # the engine degraded DELIBERATELY: structured sheds, not a hung
    # queue — and admitted requests kept the deadline promise
    assert hot["hung"] == 0, hot
    assert hot["shed_admission"] + hot["shed_deadline"] > 0, hot
    assert hot["admitted_latency_ms"]["p99"] <= out["deadline_ms"], hot
    assert hot["ok"] + hot["shed_deadline"] + hot["failed"] \
        == hot["submitted"], hot
    # exact injection accounting survived the trip through telemetry
    assert hot["faults_fired"] > 0
    assert hot["faults_injected_counter"] == hot["faults_fired"], hot
    assert hot["queued_rows"] <= out["max_queue_rows"], hot
    assert out["stats"]["shed_requests"] > 0


def test_postmortem_smoke_lane():
    """The flight-recorder acceptance lane (ISSUE 10): the chaos ladder
    with an injected TERMINAL dispatch fault (raise:first outlasting
    the retry budget) and the metrics sampler on. The probe gates: a
    postmortem file appears, ``flight_view`` parses it (and rejects a
    corrupted copy non-zero), the dump names the injected fault's site
    and exactly the dying batch's member req_ids, the sampler banked a
    non-empty series window, zero hung futures, and the recorder's
    measured work stays under the <2% overhead guard. This test pins
    the artifact schema, re-asserts the deterministic halves, and runs
    the flight_view CLI over the banked dump itself."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "postmortem_smoke.json")
    try:
        out = _run_probe(art, "--postmortem-smoke")
    except AssertionError:
        out = _run_probe(art, "--postmortem-smoke")  # one retry (noise)
    assert out["lane"] == "postmortem_smoke"
    assert out["gates_passed"] is True, out
    # the injected terminal fault produced a REAL postmortem naming the
    # fault's site and the dying batch's member req_ids
    assert out["failed_requests"] > 0
    assert out["view_summary"]["reason"] == "serving_dispatch_failure"
    assert out["view_summary"]["exception"]["fault_site"] == "dispatch"
    assert sorted(out["view_summary"]["extra"]["req_ids"]) \
        == out["failed_req_ids"], out["view_summary"]
    # the sampler banked a non-empty time-series window with samples
    # shaped like the schema the bench artifacts embed
    win = out["series_window"]
    assert win["n"] > 0 and len(win["samples"]) == win["n"]
    assert {"ts", "dt_ms", "counters", "queue_depth"} \
        <= set(win["samples"][-1])
    # no hung futures, and the flight-recorder work fits the <2% guard
    assert out["hung"] == 0
    assert out["overhead"]["frac"] < out["overhead"]["gate"], out
    # the banked dump parses through the CLI end to end
    pm = out["postmortem_path"]
    assert pm and os.path.exists(pm), pm
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools", "flight_view.py"),
         pm], stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr[-1000:]
    assert "slowest requests" in proc.stdout


def test_warm_smoke_lane():
    """The zero-cold-start acceptance lane (ISSUE 6): two fresh
    processes over one shared compile-cache dir. The probe gates the
    warm leg at zero ``jit_compile`` spans, deserialize hits >= bucket
    count, bit-identical outputs and warm startup <= 25% of cold; this
    test pins the artifact schema and the deterministic halves of the
    gate (the wall-clock ratio gets the usual one retry under CI
    noise)."""
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "warm_smoke.json")
    try:
        out = _run_probe(art, "--warm-smoke")
    except AssertionError:
        out = _run_probe(art, "--warm-smoke")   # one retry under noise
    assert out["lane"] == "warm_smoke"
    assert out["gates_passed"] is True, out
    # the deterministic contract, independent of the timing gate: a
    # warm process serving every bucket never invokes XLA
    assert out["warm"]["jit_compile_spans"] == 0, out
    assert out["warm"]["jit_deserialize_spans"] >= out["n_buckets"], out
    assert out["warm"]["compile_cache"].get(
        "compile_cache.hit", 0) >= out["n_buckets"], out
    assert out["cold"]["compile_cache"].get(
        "compile_cache.store", 0) >= out["n_buckets"], out
    assert out["warm"]["sources"] == ["disk_cache"], out
    # deserialized executables compute the SAME function, bit for bit
    assert out["warm"]["probe_sum"] == out["cold"]["probe_sum"], out
    assert out["warm_vs_cold"] <= out["ratio_gate"], out


def test_recalibrated_warm_gate_math():
    """The in-run warm-gate recalibration (ISSUE 14): gate =
    clamp(1.4 * (1 - compile_share), 0.25, 0.85) from the cold leg's
    own span accounting; unusable accounting degrades to the cap
    (only demand SOME win)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "_serve_probe", os.path.join(ROOT, "tools", "serve_probe.py"))
    sp = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(sp)
    gate = sp._recalibrated_warm_gate
    # compile-dominated box: clamps to the old absolute strength
    p, g = gate({"startup_s": 10.0, "jit_compile_s": 8.0,
                 "jit_trace_s": 1.0})
    assert p == 0.1 and g == sp.WARM_RATIO_FLOOR == 0.25
    # share-throttled box (this one): the gate relaxes to what the
    # box can actually show, with margin
    p, g = gate({"startup_s": 1.335, "jit_compile_s": 0.6057,
                 "jit_trace_s": 0.2191})
    assert 0.35 < p < 0.42 and 0.5 < g < 0.6
    # overhead-only box: caps — a warm leg must still show a real win
    p, g = gate({"startup_s": 10.0, "jit_compile_s": 0.5,
                 "jit_trace_s": 0.0})
    assert g == sp.WARM_RATIO_CAP == 0.85
    # no usable accounting: cap, never a crash
    p, g = gate({"startup_s": 0.0})
    assert p is None and g == sp.WARM_RATIO_CAP
