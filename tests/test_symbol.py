"""Symbol + Executor tests (parity model: test_symbol.py / test_executor.py
/ test_infer_shape.py in the reference suite)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu import sym, nd


def _mlp():
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"), name="softmax")


def test_list_arguments_and_outputs():
    net = _mlp()
    assert net.list_arguments() == ["data", "fc1_weight", "fc1_bias",
                                    "fc2_weight", "fc2_bias", "softmax_label"]
    assert net.list_outputs() == ["softmax_output"]


def test_infer_shape():
    net = _mlp()
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(
        data=(8, 10), softmax_label=(8,))
    assert arg_shapes[1] == (16, 10)   # fc1_weight
    assert arg_shapes[3] == (4, 16)    # fc2_weight
    assert out_shapes == [(8, 4)]
    assert aux_shapes == []


def test_infer_shape_conv_bn():
    data = sym.Variable("data")
    net = sym.Convolution(data, kernel=(3, 3), num_filter=8, pad=(1, 1),
                          name="conv")
    net = sym.BatchNorm(net, name="bn")
    arg_shapes, out_shapes, aux_shapes = net.infer_shape(data=(2, 3, 8, 8))
    assert arg_shapes[1] == (8, 3, 3, 3)
    assert out_shapes[0] == (2, 8, 8, 8)
    assert net.list_auxiliary_states() == ["bn_moving_mean", "bn_moving_var"]
    assert aux_shapes == [(8,), (8,)]


def test_group_and_index():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = a + b
    g = sym.Group([c, a * b])
    assert len(g.list_outputs()) == 2
    first = g[0]
    assert len(first.list_outputs()) == 1


def test_json_roundtrip():
    net = _mlp()
    js = net.tojson()
    net2 = sym.load_json(js)
    assert net2.list_arguments() == net.list_arguments()
    assert net2.list_outputs() == net.list_outputs()
    # still executable after round trip
    ex = net2.simple_bind(ctx=mx.cpu(), data=(2, 6), softmax_label=(2,))
    assert ex.forward()[0].shape == (2, 4)


def test_symbol_arithmetic_exec():
    a = sym.Variable("a")
    b = sym.Variable("b")
    c = 2 * a + b ** 2 - 3
    ex = c.bind(ctx=mx.cpu(), args={"a": nd.array([1.0, 2.0]),
                                    "b": nd.array([3.0, 4.0])})
    np.testing.assert_allclose(ex.forward()[0].asnumpy(), [8.0, 17.0])


def test_executor_backward():
    a = sym.Variable("a")
    loss = sym.MakeLoss((a * a).sum())
    ex = loss.bind(ctx=mx.cpu(), args={"a": nd.array([1.0, 2.0, 3.0])},
                   args_grad={"a": nd.zeros((3,))}, grad_req="write")
    ex.forward(is_train=True)
    ex.backward()
    np.testing.assert_allclose(ex.grad_dict["a"].asnumpy(), [2.0, 4.0, 6.0])


def test_grad_req_add_and_null():
    a = sym.Variable("a")
    b = sym.Variable("b")
    loss = sym.MakeLoss((a * b).sum())
    ag = nd.zeros((2,))
    ex = loss.bind(ctx=mx.cpu(),
                   args={"a": nd.array([1.0, 2.0]), "b": nd.array([3.0, 4.0])},
                   args_grad={"a": ag},
                   grad_req={"a": "add", "b": "null"})
    for _ in range(2):
        ex.forward(is_train=True)
        ex.backward()
    np.testing.assert_allclose(ag.asnumpy(), [6.0, 8.0])


def test_get_internals():
    net = _mlp()
    internals = net.get_internals()
    names = internals.list_outputs()
    assert any("fc1" in n for n in names)
    fc1 = internals["fc1_output"]
    ash, osh, _ = fc1.infer_shape(data=(4, 10))
    assert osh == [(4, 16)]


def test_composition():
    lhs = sym.Variable("lhs")
    net1 = sym.FullyConnected(lhs, num_hidden=8, name="fca")
    data2 = sym.Variable("d2")
    net2 = sym.Activation(data2, act_type="relu")
    composed = net1(lhs=net2, name="composed")
    assert "d2" in composed.list_arguments()


def test_variable_shape_attr():
    v = sym.Variable("x", shape=(2, 3))
    out = sym.Flatten(v)
    _, osh, _ = out.infer_shape()
    assert osh == [(2, 3)]


def test_simple_bind_forward_with_kwargs():
    net = _mlp()
    ex = net.simple_bind(ctx=mx.cpu(), data=(4, 10), softmax_label=(4,))
    for name, arr in ex.arg_dict.items():
        if name not in ("data", "softmax_label"):
            arr[:] = np.random.normal(0, 0.1, arr.shape)
    out = ex.forward(is_train=False, data=np.random.normal(size=(4, 10)))
    probs = out[0].asnumpy()
    np.testing.assert_allclose(probs.sum(axis=1), np.ones(4), rtol=1e-5)


def test_load_json_legacy_formats():
    """Pre-1.0 graph JSON loads: 0.9-era 'attr' key, pre-0.9 'param' key,
    and legacy non-parameter attrs (lr_mult) migrating to __k__ form
    (parity: src/nnvm/legacy_json_util.cc upgrade pass)."""
    import json as _json
    legacy = {
        "nodes": [
            {"op": "null", "name": "data", "inputs": []},
            {"op": "null", "name": "fc_weight", "inputs": [],
             "attr": {"lr_mult": "2.0"}},
            {"op": "null", "name": "fc_bias", "inputs": []},
            {"op": "FullyConnected", "name": "fc",
             # 0.9-era: params under 'attr', with a non-parameter key
             "attr": {"num_hidden": "4", "lr_mult": "0.5"},
             "inputs": [[0, 0, 0], [1, 0, 0], [2, 0, 0]]},
            {"op": "Activation", "name": "act",
             # pre-0.9: params under 'param'
             "param": {"act_type": "relu"},
             "inputs": [[3, 0, 0]]},
        ],
        "arg_nodes": [0, 1, 2],
        "heads": [[4, 0, 0]],
    }
    net = sym.load_json(_json.dumps(legacy))
    assert net.list_arguments() == ["data", "fc_weight", "fc_bias"]
    # the graph binds and runs (unknown attrs did not reach the op)
    ex = net.simple_bind(ctx=mx.cpu(), data=(2, 3))
    ex.arg_dict["data"][:] = np.ones((2, 3), np.float32)
    ex.arg_dict["fc_weight"][:] = np.ones((4, 3), np.float32)
    out = ex.forward()[0].asnumpy()
    np.testing.assert_allclose(out, np.full((2, 4), 3.0))
    # legacy attrs preserved in __k__ form (visible to optimizers)
    attrs = net.attr_dict()
    assert attrs["fc_weight"]["__lr_mult__"] == "2.0"
    assert attrs["fc"]["__lr_mult__"] == "0.5"


def test_json_roundtrip_preserves_signature_only_params():
    """Params that exist only as fn keyword defaults (not registry
    defaults) must survive tojson/load_json — e.g. linalg_trsm's lower."""
    A = sym.Variable("A")
    B = sym.Variable("B")
    s = sym._linalg_trsm(A, B, lower=False) if hasattr(sym, "_linalg_trsm") \
        else sym.linalg.trsm(A, B, lower=False)
    s2 = sym.load_json(s.tojson())
    import numpy as _np
    tri = _np.triu(_np.ones((3, 3), _np.float32)) + 2 * _np.eye(3, dtype=_np.float32)
    rhs = _np.arange(9, dtype=_np.float32).reshape(3, 3)
    outs = []
    for net in (s, s2):
        ex = net.simple_bind(ctx=mx.cpu(), A=(3, 3), B=(3, 3))
        ex.arg_dict["A"][:] = tri
        ex.arg_dict["B"][:] = rhs
        outs.append(ex.forward()[0].asnumpy())
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5)
