"""URI-aware streams (parity: dmlc Stream::Create scheme dispatch —
reference saves/loads through S3/HDFS-capable streams; here file:// and
registered schemes, zero-egress)."""
import io

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import filesystem


def test_nd_save_load_file_uri(tmp_path):
    arrs = {"w": mx.nd.array(np.arange(6).reshape(2, 3))}
    uri = "file://" + str(tmp_path / "x.params")
    mx.nd.save(uri, arrs)
    back = mx.nd.load(uri)
    np.testing.assert_allclose(back["w"].asnumpy(), arrs["w"].asnumpy())


def test_symbol_save_load_file_uri(tmp_path):
    net = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=3,
                                name="fc")
    uri = "file://" + str(tmp_path / "net.json")
    net.save(uri)
    back = mx.sym.load(uri)
    assert back.list_arguments() == net.list_arguments()


def test_recordio_file_uri(tmp_path):
    from mxnet_tpu import recordio
    uri = "file://" + str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(uri, "w")
    rec.write(b"payload")
    rec.close()
    rec = recordio.MXRecordIO(uri, "r")
    assert rec.read() == b"payload"


def test_registered_scheme_roundtrip(tmp_path):
    blobs = {}

    class _MemFile(io.BytesIO):
        def __init__(self, uri, init=b""):
            super().__init__(init)
            self._uri = uri

        def close(self):
            blobs[self._uri] = self.getvalue()
            super().close()

    def opener(uri, mode):
        if "w" in mode:
            return _MemFile(uri)
        if uri not in blobs:
            raise FileNotFoundError(uri)
        return io.BytesIO(blobs[uri])

    filesystem.register_scheme("mem", opener)
    try:
        arrs = [mx.nd.array(np.ones((2, 2)))]
        mx.nd.save("mem://bucket/a", arrs)
        back = mx.nd.load("mem://bucket/a")
        np.testing.assert_allclose(back[0].asnumpy(), 1.0)
    finally:
        filesystem._OPENERS.pop("mem", None)


def test_unknown_scheme_raises():
    with pytest.raises(mx.MXNetError):
        filesystem.open_uri("s3://bucket/key", "rb")


def test_plain_paths_and_windows_drives_are_local():
    assert filesystem.scheme_of("/a/b.params") == ""
    assert filesystem.scheme_of("C://odd") == ""  # single-letter head
    assert filesystem.scheme_of("file:///x") == "file"
    assert filesystem.scheme_of("s3://b/k") == "s3"


def test_indexed_recordio_file_uri(tmp_path):
    from mxnet_tpu import recordio
    idx = "file://" + str(tmp_path / "t.idx")
    rec_uri = "file://" + str(tmp_path / "t.rec")
    w = recordio.MXIndexedRecordIO(idx, rec_uri, "w")
    w.write_idx(7, b"seven")
    w.write_idx(9, b"nine")
    w.close()
    r = recordio.MXIndexedRecordIO(idx, rec_uri, "r")
    assert r.read_idx(9) == b"nine"
    assert r.read_idx(7) == b"seven"
