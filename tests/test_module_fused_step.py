"""Whole-step fused training program (Module.fit / Module.fused_step).

Three load-bearing properties, each pinned:

1. DISPATCH COUNT — the fused inner loop must issue ONE jitted-program
   execution per batch (the PERF.md "Module.fit gap" was pure dispatch
   overhead; the guard catches any regression that sneaks a second
   program back into the loop). The phase-split fallback's count is
   pinned too, so a regression in EITHER path fails loudly.
2. NUMERICAL EQUIVALENCE — fused vs phase-split must be bit-identical
   (params, optimizer state, metric) after N batches on the virtual
   8-device CPU mesh, including bf16-resident weights + fp32 master and
   a grad_req='add' accumulation case. The phase-split path is the
   correctness oracle; fusion may only change WHEN things compute, not
   WHAT.
3. FALLBACK RULES — every non-fusible configuration must still train
   (via the phase-split path) and must say why it fell back.
"""
import contextlib
import os

import numpy as np
import pytest

import mxnet_tpu as mx
import mxnet_tpu.executor as _ex
from mxnet_tpu import nd, sym
from mxnet_tpu.io import DataBatch, DataDesc

import jax
import jax.numpy as jnp


@contextlib.contextmanager
def _pin(value):
    """Pin MXNET_MODULE_FUSED_STEP for the duration (the A/B knob)."""
    old = os.environ.get("MXNET_MODULE_FUSED_STEP")
    os.environ["MXNET_MODULE_FUSED_STEP"] = value
    try:
        yield
    finally:
        if old is None:
            del os.environ["MXNET_MODULE_FUSED_STEP"]
        else:
            os.environ["MXNET_MODULE_FUSED_STEP"] = old


@contextlib.contextmanager
def _count_dispatches(counts):
    _ex.dispatch_hook = \
        lambda kind: counts.__setitem__(kind, counts.get(kind, 0) + 1)
    try:
        yield counts
    finally:
        _ex.dispatch_hook = None


def _mlp(c=4):
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=c, name="fc2")
    return sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                             name="softmax")


def _batches(nbatch, batch=16, d=8, c=4, seed=7):
    rs = np.random.RandomState(seed)
    return [DataBatch(
        data=[nd.array(rs.uniform(-1, 1, (batch, d)).astype(np.float32))],
        label=[nd.array(rs.randint(0, c, batch).astype(np.float32))],
        pad=0) for _ in range(nbatch)]


def _make_module(n_dev=1, bf16=False, grad_req="write", batch=16, d=8):
    ctx = [mx.cpu(i) for i in range(n_dev)] if n_dev > 1 else mx.cpu()
    mod = mx.mod.Module(_mlp(), context=ctx)
    ddtype = np.dtype(jnp.bfloat16) if bf16 else None
    mod.bind(data_shapes=[DataDesc("data", (batch, d), dtype=ddtype)],
             label_shapes=[DataDesc("softmax_label", (batch,))],
             grad_req=grad_req)
    np.random.seed(11)
    mod.init_params(mx.initializer.Xavier())
    # kvstore=None: a kvstore-mediated update is a documented fallback
    # (push/pull is not a pure function of params/grads) — on the mesh
    # the gradient all-reduce rides inside the sharded program instead
    mod.init_optimizer(
        kvstore=None, optimizer="sgd",
        optimizer_params={"learning_rate": 0.05, "momentum": 0.9,
                          "wd": 1e-4, "multi_precision": bf16})
    return mod


def _state_arrays(updater):
    out = []
    for i in sorted(updater.states):
        for leaf in jax.tree_util.tree_leaves(updater.states[i]):
            out.append(np.asarray(leaf._data if hasattr(leaf, "_data")
                                  else leaf))
    return out


def _train(fused, n_dev=1, bf16=False, grad_req="write", nbatch=6):
    with _pin("1" if fused else "0"):
        mod = _make_module(n_dev=n_dev, bf16=bf16, grad_req=grad_req)
        metric = mx.metric.Accuracy()
        for b in _batches(nbatch):
            ran_fused = mod.fused_step(b, eval_metric=metric)
            assert ran_fused == fused, mod._fused_fallback_reason
    params = {n: np.asarray(mod._exec.arg_dict[n]._data)
              for n in mod._param_names}
    grads = {n: np.asarray(g._data)
             for n, g in mod._exec.grad_dict.items() if g is not None}
    return params, _state_arrays(mod._updater), metric.get(), grads


# ---------------------------------------------------------------------------
# 1. dispatch-count regression guard
# ---------------------------------------------------------------------------

def test_fused_fit_dispatch_guard():
    """The fused Module.fit inner loop must stay at <= 2 jitted-program
    dispatches per batch on the CPU backend (it is exactly 1 today:
    train_step; the headroom covers a future second program, nothing
    more)."""
    nbatch = 5
    with _pin("1"):
        mod = _make_module()
        metric = mx.metric.Accuracy()
        batches = _batches(2)
        for b in batches:  # warm: compiles the program
            assert mod.fused_step(b, eval_metric=metric), \
                mod._fused_fallback_reason
        with _count_dispatches({}) as counts:
            for b in _batches(nbatch):
                assert mod.fused_step(b, eval_metric=metric)
    assert mod._fused_fallback_reason is None
    assert sum(counts.values()) <= 2 * nbatch, counts
    assert counts == {"train_step": nbatch}, counts


def test_phase_split_dispatch_pinned():
    """The fallback path's per-batch dispatch count is pinned at exactly
    fwd_bwd + opt_update + metric — a regression in the phase-split
    (oracle) path must be as loud as one in the fused path."""
    nbatch = 5
    with _pin("0"):
        mod = _make_module()
        metric = mx.metric.Accuracy()
        for b in _batches(2):  # warm
            assert not mod.fused_step(b, eval_metric=metric)
        assert mod._fused_fallback_reason == "MXNET_MODULE_FUSED_STEP=0"
        with _count_dispatches({}) as counts:
            for b in _batches(nbatch):
                assert not mod.fused_step(b, eval_metric=metric)
    assert counts == {"fwd_bwd": nbatch, "opt_update": nbatch,
                      "metric": nbatch}, counts


# ---------------------------------------------------------------------------
# 2. numerical equivalence: fused vs phase-split oracle
# ---------------------------------------------------------------------------

def _assert_equal_runs(run_a, run_b):
    params_a, states_a, metric_a, grads_a = run_a
    params_b, states_b, metric_b, grads_b = run_b
    for n in params_a:
        np.testing.assert_array_equal(params_a[n], params_b[n], err_msg=n)
    assert len(states_a) == len(states_b)
    for i, (a, b) in enumerate(zip(states_a, states_b)):
        np.testing.assert_array_equal(a, b, err_msg="state %d" % i)
    assert metric_a == metric_b, (metric_a, metric_b)


def test_equivalence_fp32_mesh():
    """fp32 SGD+momentum+wd on the virtual 8-device mesh: params,
    optimizer state, and metric bit-identical after 6 batches."""
    n_dev = min(8, jax.device_count())
    _assert_equal_runs(_train(True, n_dev=n_dev), _train(False, n_dev=n_dev))


def test_equivalence_bf16_master_mesh():
    """bf16-resident weights + fp32 master (multi_precision) on the
    mesh: the fused program must round exactly like the phase-split
    bf16 executor + mp optimizer chain."""
    n_dev = min(8, jax.device_count())
    _assert_equal_runs(_train(True, n_dev=n_dev, bf16=True),
                       _train(False, n_dev=n_dev, bf16=True))


def test_equivalence_grad_add():
    """grad_req='add': the gradient accumulator is a fused-program
    OUTPUT (it feeds the next step) — its running value must match the
    phase-split accumulation bit for bit, params and states too."""
    fused = _train(True, grad_req="add")
    split = _train(False, grad_req="add")
    _assert_equal_runs(fused, split)
    assert fused[3], "grad_req='add' run must expose accumulators"
    for n in fused[3]:
        np.testing.assert_array_equal(fused[3][n], split[3][n], err_msg=n)


def test_equivalence_through_fit_loop():
    """Same equivalence through the real Module.fit loop (callbacks,
    epoch-end sync, lazily fetched metric) — the loop restructure must
    not change the math either."""
    from mxnet_tpu.io import NDArrayIter
    rs = np.random.RandomState(3)
    x = rs.uniform(-1, 1, (96, 8)).astype(np.float32)
    y = rs.randint(0, 4, 96).astype(np.float32)

    def run(fused):
        with _pin("1" if fused else "0"):
            np.random.seed(5)
            mx.random.seed(5)
            mod = mx.mod.Module(_mlp(), context=mx.cpu())
            metric = mx.metric.Accuracy()
            mod.fit(NDArrayIter(x, y, batch_size=16),
                    eval_metric=metric, num_epoch=2,
                    initializer=mx.initializer.Xavier(),
                    optimizer="sgd",
                    optimizer_params={"learning_rate": 0.05,
                                      "momentum": 0.9})
            assert (mod._fused_fallback_reason is None) == fused
            return ({n: np.asarray(mod._exec.arg_dict[n]._data)
                     for n in mod._param_names}, metric.get())

    params_f, metric_f = run(True)
    params_s, metric_s = run(False)
    for n in params_f:
        np.testing.assert_array_equal(params_f[n], params_s[n], err_msg=n)
    assert metric_f == metric_s


# ---------------------------------------------------------------------------
# 3. fused_step API + fallback rules
# ---------------------------------------------------------------------------

def test_fused_step_accepts_raw_arrays():
    """fused_step(data, label) without a DataBatch — the manual-loop
    spelling from the README."""
    mod = _make_module()
    b = _batches(1)[0]
    before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
    with _pin("1"):
        assert mod.fused_step(b.data[0], b.label[0])
    after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
    assert not np.array_equal(before, after), "step must train"


def test_fused_step_on_non_default_device():
    """A module bound on a NON-default device fed default-device batch
    arrays: the fused step feeds batches as jit arguments (no copy into
    bound storage), so IT must commit them — and a fresh metric
    accumulator — to the module's device, or the program crashes on
    mixed committed inputs where the phase-split path trains fine."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu(1))
    mod.bind(data_shapes=[DataDesc("data", (16, 8))],
             label_shapes=[DataDesc("softmax_label", (16,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(kvstore=None, optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05})
    metric = mx.metric.Accuracy()
    before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
    with _pin("1"):
        for b in _batches(2):
            assert mod.fused_step(b, eval_metric=metric), \
                mod._fused_fallback_reason
    after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
    assert not np.array_equal(before, after), "step must train"
    assert metric.get()[1] >= 0.0


def test_fused_step_fallback_still_trains():
    """A fallback is a slow path, not a no-op: with the knob pinned off
    the step must still run (phase-split) and return False."""
    mod = _make_module()
    metric = mx.metric.Accuracy()
    b = _batches(1)[0]
    before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
    with _pin("0"):
        assert not mod.fused_step(b, eval_metric=metric)
    after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
    assert not np.array_equal(before, after), "fallback step must train"
    assert metric.get()[1] >= 0.0  # metric accumulated eagerly


def test_fallback_reason_monitor():
    mod = _make_module()
    mon = mx.monitor.Monitor(1, pattern=".*weight")
    mod.install_monitor(mon)
    with _pin("1"):
        assert not mod.fused_step(_batches(1)[0])
    assert mod._fused_fallback_reason == "monitor installed"


def test_fused_with_metric_only_label():
    """A label bound for metric use but NOT consumed by the graph (e.g.
    a MakeLoss custom loss) must still fuse — the label simply doesn't
    ride as a program input, and the metric accumulates phase-split on
    the step's outputs instead of crashing the plan build."""
    data = sym.Variable("data")
    net = sym.MakeLoss(sym.mean(sym.square(
        sym.FullyConnected(data, num_hidden=4, name="fc1"))))
    mod = mx.mod.Module(net, context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (16, 8))],
             label_shapes=[DataDesc("softmax_label", (16,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
    with _pin("1"):
        assert mod.fused_step(_batches(1)[0]), mod._fused_fallback_reason
    after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
    assert not np.array_equal(before, after), "step must train"


def test_fallback_unbound_label_shapes():
    """A label-consuming graph bound WITHOUT label shapes must fall back
    (the fused pure-function program cannot feed `softmax_label`), not
    crash — the phase-split path handles this binding fine."""
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[DataDesc("data", (16, 8))], label_shapes=None,
             for_training=True)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    before = np.asarray(mod._exec.arg_dict["fc1_weight"]._data).copy()
    with _pin("1"):
        assert not mod.fused_step(_batches(1)[0],
                                  eval_metric=mx.metric.Accuracy())
    assert "not fed by the fused step" in mod._fused_fallback_reason
    after = np.asarray(mod._exec.arg_dict["fc1_weight"]._data)
    assert not np.array_equal(before, after), "fallback step must train"


def test_fallback_reason_inputs_need_grad():
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    mod.bind(data_shapes=[("data", (16, 8))],
             label_shapes=[("softmax_label", (16,))],
             inputs_need_grad=True)
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd")
    with _pin("1"):
        assert not mod.fused_step(_batches(1)[0])
    assert mod._fused_fallback_reason == "inputs_need_grad"


def test_plan_invalidation_on_new_optimizer():
    """A cached plan is keyed to the optimizer identity: re-initialising
    the optimizer must rebuild the plan, not run the stale program."""
    mod = _make_module()
    with _pin("1"):
        assert mod.fused_step(_batches(1)[0])
        plan1 = mod._fused_plan
        mod.init_optimizer(optimizer="sgd",
                           optimizer_params={"learning_rate": 0.5},
                           force_init=True)
        assert mod.fused_step(_batches(1)[0])
        assert mod._fused_plan is not plan1
        assert mod._fused_plan["optimizer"] is mod._optimizer


def test_plan_rebuild_on_hyper_mutation():
    """Statics baked into the compiled program (momentum, rescale_grad)
    are re-checked per step: mutating them on the live optimizer must
    not silently keep running the stale program."""
    mod = _make_module()
    metric = mx.metric.Accuracy()
    with _pin("1"):
        assert mod.fused_step(_batches(1)[0], eval_metric=metric)
        fn1 = mod._fused_plan["fn"]
        mod._optimizer.rescale_grad = 0.5
        assert mod.fused_step(_batches(1)[0], eval_metric=metric)
        assert mod._fused_plan["fn"] is not fn1


# ---------------------------------------------------------------------------
# 4. BucketingModule: per-bucket fusion, per-bucket fallback
# ---------------------------------------------------------------------------

def _bucket_setup():
    def sym_gen(seq_len):
        # weights must be bucket-key independent (as in a real unrolled
        # RNN): pool over the variable-length axis before the shared FC
        data = sym.Variable("data")
        net = sym.mean(data, axis=1, keepdims=True)
        net = sym.FullyConnected(net, num_hidden=8, name="fc_shared")
        net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                                name="softmax")
        return net, ("data",), ("softmax_label",)

    mod = mx.mod.BucketingModule(sym_gen, default_bucket_key=16,
                                 context=mx.cpu())
    mod.bind(data_shapes=[("data", (4, 16))],
             label_shapes=[("softmax_label", (4,))])
    mod.init_params(mx.initializer.Xavier())
    mod.init_optimizer(optimizer="sgd",
                       optimizer_params={"learning_rate": 0.05,
                                         "momentum": 0.9})

    def batch(key):
        return DataBatch(data=[nd.ones((4, key))], label=[nd.zeros((4,))],
                         bucket_key=key,
                         provide_data=[("data", (4, key))],
                         provide_label=[("softmax_label", (4,))])

    return mod, batch


def test_bucketing_fused_per_bucket():
    """Each bucket compiles and runs its own whole-step program; the
    shared optimizer's update counts stay uniform across buckets."""
    mod, batch = _bucket_setup()
    metric = mx.metric.Accuracy()
    keys = [16, 8, 16, 8, 8, 16]
    with _pin("1"):
        for k in keys[:2]:  # warm both buckets
            assert mod.fused_step(batch(k), eval_metric=metric), \
                mod._fused_fallback_reason
        with _count_dispatches({}) as counts:
            for k in keys:
                assert mod.fused_step(batch(k), eval_metric=metric)
    assert counts == {"train_step": len(keys)}, counts
    opt = mod._curr_module._optimizer
    assert len(set(opt._index_update_count.values())) == 1, \
        "shared optimizer counts must stay uniform across buckets"


def test_bucketing_fallback_is_per_bucket():
    """A bucket that can't fuse falls back for ITS batches only — the
    other bucket keeps its one-dispatch program."""
    mod, batch = _bucket_setup()
    with _pin("1"):
        assert mod.fused_step(batch(16))
        assert mod.fused_step(batch(8))
        # wedge bucket 8 only (a per-bucket monitor tap is the
        # realistic way a single bucket loses fusion eligibility)
        mod._buckets[8]._exec._monitor_callback = lambda *a: None
        with _count_dispatches({}) as counts:
            assert mod.fused_step(batch(16))
            assert mod._fused_fallback_reason is None
            assert not mod.fused_step(batch(8))
            assert mod._fused_fallback_reason == "monitor installed"
            assert mod.fused_step(batch(16))
    assert counts["train_step"] == 2, counts
    assert counts["fwd_bwd"] == 1, counts


def _train_sym(symbol, fused, nbatch=6, batch=16, d=8):
    with _pin("1" if fused else "0"):
        mod = mx.mod.Module(symbol, context=mx.cpu())
        mod.bind(data_shapes=[DataDesc("data", (batch, d))],
                 label_shapes=[DataDesc("softmax_label", (batch,))])
        np.random.seed(11)
        mod.init_params(mx.initializer.Xavier())
        mx.random.seed(13)
        mod.init_optimizer(kvstore=None, optimizer="sgd",
                           optimizer_params={"learning_rate": 0.05,
                                             "momentum": 0.9})
        metric = mx.metric.Accuracy()
        for b in _batches(nbatch):
            assert mod.fused_step(b, eval_metric=metric) == fused, \
                mod._fused_fallback_reason
    params = {n: np.asarray(mod._exec.arg_dict[n]._data)
              for n in mod._param_names}
    aux = {n: np.asarray(a._data)
           for n, a in zip(mod._exec._aux_names, mod._exec.aux_arrays)}
    return params, aux, metric.get()


def test_equivalence_batchnorm_aux():
    """BatchNorm moving mean/var are AUX state — donated and updated
    inside the fused program; their trajectory must match the
    phase-split forward/backward aux write-back exactly."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.BatchNorm(net, name="bn1")
    net = sym.Activation(net, act_type="relu")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    pf, auxf, mf = _train_sym(net, True)
    ps, auxs, ms = _train_sym(net, False)
    assert auxf, "BatchNorm must expose moving-stat aux states"
    for n in pf:
        np.testing.assert_array_equal(pf[n], ps[n], err_msg=n)
    for n in auxf:
        np.testing.assert_array_equal(auxf[n], auxs[n], err_msg=n)
    assert mf == ms


def test_equivalence_dropout_rng():
    """Dropout consumes the executor's step RNG: the fused step must
    advance the SAME key sequence as the phase-split forward/backward,
    one key per batch — masks, and therefore params, bit-identical."""
    data = sym.Variable("data")
    net = sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = sym.Activation(net, act_type="relu")
    net = sym.Dropout(net, p=0.5, name="drop1")
    net = sym.FullyConnected(net, num_hidden=4, name="fc2")
    net = sym.SoftmaxOutput(net, sym.Variable("softmax_label"),
                            name="softmax")
    pf, _, mf = _train_sym(net, True)
    ps, _, ms = _train_sym(net, False)
    for n in pf:
        np.testing.assert_array_equal(pf[n], ps[n], err_msg=n)
    assert mf == ms
