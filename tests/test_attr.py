"""Attribute/name scoping tests (parity: reference
tests/python/unittest/test_attr.py)."""
import mxnet_tpu as mx


def test_attr_basic():
    with mx.AttrScope(group="4", data="great"):
        data = mx.sym.Variable("data", attr={"dtype": "data",
                                             "group": "1",
                                             "force_mirroring": "True"})
        gdata = mx.sym.Variable("data2")
    assert gdata.attr("group") == "4"
    assert data.attr("group") == "1"
    assert data.attr("force_mirroring") == "True"

    data2 = mx.sym.Variable("data3")
    assert data2.attr("group") is None


def test_operator_attr():
    data = mx.sym.Variable("data", attr={"group": "4"})
    with mx.AttrScope(__group__="4", __lr_mult__="1"):
        fc1 = mx.sym.Activation(data, act_type="relu")
    assert fc1.attr("__group__") == "4"
    assert fc1.attr("__lr_mult__") == "1"


def test_attr_nested_scope():
    with mx.AttrScope(x="1", y="a"):
        with mx.AttrScope(y="b", z="2"):
            v = mx.sym.Variable("v")
        w = mx.sym.Variable("w")
    assert v.attr("x") == "1" and v.attr("y") == "b" and v.attr("z") == "2"
    assert w.attr("y") == "a" and w.attr("z") is None


def test_name_manager_auto():
    with mx.name.NameManager():
        data = mx.sym.Variable("data")
        a = mx.sym.FullyConnected(data, num_hidden=2)
        b = mx.sym.FullyConnected(a, num_hidden=2)
    assert a.name == "fullyconnected0"
    assert b.name == "fullyconnected1"


def test_name_prefix():
    data = mx.sym.Variable("data")
    with mx.name.Prefix("mynet_"):
        net = mx.sym.FullyConnected(data, num_hidden=10, name="fc1")
    args = net.list_arguments()
    assert args == ["data", "mynet_fc1_weight", "mynet_fc1_bias"]


def test_attr_dict_includes_scope_attrs():
    data = mx.sym.Variable("data")
    with mx.AttrScope(ctx_group="dev1"):
        fc = mx.sym.FullyConnected(data, num_hidden=4, name="fc1")
    d = fc.attr_dict()
    assert d["fc1"]["ctx_group"] == "dev1"
