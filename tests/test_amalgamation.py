"""Amalgamated predict-only build: one generated .cc -> one .so -> the
standalone ctypes wrapper scores a saved model with NO mxnet_tpu import
on the client side (parity model: reference amalgamation/ +
python/mxnet_predict.py)."""
import importlib.util
import os
import shutil
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
AMAL = os.path.join(REPO, "amalgamation")


@pytest.fixture(scope="module")
def built_lib(tmp_path_factory):
    if shutil.which("g++") is None:
        pytest.skip("no C++ compiler")
    rc = subprocess.run(["make", "-s"], cwd=AMAL, capture_output=True,
                        text=True)
    if rc.returncode != 0:
        pytest.fail("amalgamation build failed:\n%s" % rc.stderr[-2000:])
    lib = os.path.join(AMAL, "libmxnet_predict.so")
    assert os.path.exists(lib)
    return lib


@pytest.fixture(scope="module")
def wrapper(built_lib):
    os.environ["MXNET_PREDICT_LIB"] = built_lib
    spec = importlib.util.spec_from_file_location(
        "mxnet_predict", os.path.join(AMAL, "python", "mxnet_predict.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def tiny_model(tmp_path_factory):
    import mxnet_tpu as mx
    tmp = tmp_path_factory.mktemp("amal_model")
    data = mx.sym.Variable("data")
    fc = mx.sym.FullyConnected(data, name="fc1", num_hidden=3)
    out = mx.sym.SoftmaxOutput(fc, name="softmax")
    mod = mx.mod.Module(out, data_names=["data"],
                        label_names=["softmax_label"])
    mod.bind(data_shapes=[("data", (2, 4))],
             label_shapes=[("softmax_label", (2,))])
    mod.init_params(mx.initializer.Uniform(0.5))
    prefix = str(tmp / "tiny")
    mod.save_checkpoint(prefix, 0)
    arg_params, _ = mod.get_params()
    with open(prefix + "-symbol.json") as f:
        sym_json = f.read()
    with open(prefix + "-0000.params", "rb") as f:
        params = f.read()
    return sym_json, params, {k: v.asnumpy() for k, v in arg_params.items()}


def _softmax(z):
    e = np.exp(z - z.max(axis=1, keepdims=True))
    return e / e.sum(axis=1, keepdims=True)


def test_standalone_predictor(wrapper, tiny_model):
    sym_json, params, arg_params = tiny_model
    x = np.random.RandomState(0).uniform(size=(2, 4)).astype(np.float32)
    pred = wrapper.Predictor(sym_json, params, {"data": (2, 4)})
    pred.forward(data=x)
    got = pred.get_output(0)
    want = _softmax(x @ arg_params["fc1_weight"].T + arg_params["fc1_bias"])
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_partial_out(wrapper, tiny_model):
    """MXPredCreatePartialOut exposes an internal node (pre-softmax)."""
    sym_json, params, arg_params = tiny_model
    x = np.random.RandomState(1).uniform(size=(2, 4)).astype(np.float32)
    pred = wrapper.Predictor(sym_json, params, {"data": (2, 4)},
                             output_names=["fc1"])
    pred.forward(data=x)
    got = pred.get_output(0)
    want = x @ arg_params["fc1_weight"].T + arg_params["fc1_bias"]
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_load_ndarray_file(wrapper, tiny_model):
    _, params, arg_params = tiny_model
    loaded = wrapper.load_ndarray_file(params)
    assert set(loaded) == {"arg:fc1_weight", "arg:fc1_bias"}
    np.testing.assert_allclose(loaded["arg:fc1_weight"],
                               arg_params["fc1_weight"], rtol=1e-6)


def test_amalgamated_file_is_single_unit(built_lib):
    src = os.path.join(AMAL, "mxnet_predict-all.cc")
    assert os.path.exists(src)
    with open(src) as f:
        text = f.read()
    assert '#include "' not in text  # every local include was inlined
    for sym in ("MXPredCreatePartialOut", "MXPredPartialForward",
                "MXNDListCreate", "MXGetLastError"):
        assert sym in text
