"""Pallas kernel tests (interpret mode on the CPU mesh; the same kernels
compile natively on TPU — the bench/driver exercises that path)."""
import numpy as np

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import parallel
from mxnet_tpu.pallas import flash_attention, flash_attention_carry


def _rand_qkv(seed, B=2, H=3, S=24, D=16):
    rng = np.random.RandomState(seed)
    return [jnp.asarray(rng.normal(size=(B, H, S, D)).astype(np.float32))
            for _ in range(3)]


def test_flash_matches_reference():
    q, k, v = _rand_qkv(0)
    for causal in (False, True):
        ref = parallel.attention(q, k, v, causal=causal)
        got = flash_attention(q, k, v, causal)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


def test_flash_uneven_seq():
    # S > block_q and not a multiple of it: exercises the real padding
    # path (block_q=8 so S=19 pads to 24) including padded-row gradients
    q, k, v = _rand_qkv(1, S=19)
    ref = parallel.attention(q, k, v, causal=True)
    got = flash_attention(q, k, v, True, None, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def f_ref(q, k, v):
        return jnp.sum(jnp.sin(parallel.attention(q, k, v, causal=True)))

    def f_got(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, True, None, 8)))

    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(f_got, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gg):
        assert np.all(np.isfinite(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_flash_grads_match_reference():
    q, k, v = _rand_qkv(2, B=1, H=2, S=12, D=8)

    def f_ref(q, k, v):
        return jnp.sum(jnp.sin(parallel.attention(q, k, v, causal=True)))

    def f_got(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, True)))

    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(f_got, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gg):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_carry_chaining_equals_full():
    """Two chained kv blocks with offsets == one full-sequence call — the
    invariant ring attention relies on."""
    B, H, S, D = 1, 2, 16, 8
    q, k, v = _rand_qkv(3, B=B, H=H, S=S, D=D)
    ref = parallel.attention(q, k, v, causal=True)
    qf, kf, vf = [x.reshape(B * H, S, D) for x in (q, k, v)]
    o = jnp.zeros((B * H, S, D), jnp.float32)
    m = jnp.full((B * H, S), -1e30, jnp.float32)
    l = jnp.zeros((B * H, S), jnp.float32)
    half = S // 2
    o, m, l = flash_attention_carry(qf, kf[:, :half], vf[:, :half], o, m, l,
                                    q_offset=0, kv_offset=0, causal=True)
    o, m, l = flash_attention_carry(qf, kf[:, half:], vf[:, half:], o, m, l,
                                    q_offset=0, kv_offset=half, causal=True)
    out = (o / jnp.maximum(l, 1e-30)[..., None]).reshape(B, H, S, D)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_attention_pallas_path():
    """Ring attention with the Pallas local kernel (interpret mode) must
    match the single-chip reference."""
    B, H, S, D = 1, 2, 16, 8
    q, k, v = _rand_qkv(4, B=B, H=H, S=S, D=D)
    mesh = parallel.make_mesh({"sp": 4})
    for causal in (False, True):
        ref = parallel.attention(q, k, v, causal=causal)
        out = parallel.ring_attention(q, k, v, mesh, axis_name="sp",
                                      causal=causal, use_pallas=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)


def test_ring_attention_pallas_grads():
    """Training through the Pallas ring path: the custom ring VJP must
    match autodiff through the single-chip reference."""
    B, H, S, D = 1, 2, 16, 8
    q, k, v = _rand_qkv(5, B=B, H=H, S=S, D=D)
    mesh = parallel.make_mesh({"sp": 4})

    def f_ref(q, k, v):
        return jnp.sum(jnp.sin(parallel.attention(q, k, v, causal=True)))

    def f_ring(q, k, v):
        out = parallel.ring_attention(q, k, v, mesh, axis_name="sp",
                                      causal=True, use_pallas=True)
        return jnp.sum(jnp.sin(out))

    gr = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
    gg = jax.grad(f_ring, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gg):
        assert np.all(np.isfinite(np.asarray(b)))
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-4, atol=1e-4)


def test_flash_backward_pallas_vs_xla():
    """The Pallas flash backward (sequential-grid dk/dv accumulation)
    must match the XLA recompute backward bit-for-tolerance on uneven
    (non-block-multiple) sequence lengths, causal and not."""
    import importlib
    import os
    import jax
    import jax.numpy as jnp
    # the package re-exports the function under the submodule's name, so
    # the module itself must come from importlib
    fa = importlib.import_module("mxnet_tpu.pallas.flash_attention")

    rs = np.random.RandomState(0)
    for causal in (False, True):
        for s_q, s_kv in [(48, 48), (33, 65)] if not causal else [(48, 48)]:
            q = jnp.asarray(rs.randn(1, 2, s_q, 16).astype(np.float32))
            k = jnp.asarray(rs.randn(1, 2, s_kv, 16).astype(np.float32))
            v = jnp.asarray(rs.randn(1, 2, s_kv, 16).astype(np.float32))
            g = jnp.asarray(rs.randn(1, 2, s_q, 16).astype(np.float32))

            def loss(qq, kk, vv):
                return jnp.sum(fa.flash_attention(qq, kk, vv,
                                                  causal, None, 32) * g)

            grads_pallas = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            os.environ["MXTPU_FLASH_BWD"] = "xla"
            try:
                grads_xla = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
            finally:
                del os.environ["MXTPU_FLASH_BWD"]
            for gp, gx, name in zip(grads_pallas, grads_xla,
                                    ("dq", "dk", "dv")):
                np.testing.assert_allclose(
                    np.asarray(gp), np.asarray(gx), rtol=2e-4, atol=2e-4,
                    err_msg="%s causal=%s s=(%d,%d)"
                            % (name, causal, s_q, s_kv))


# ---------------------------------------------------------------------------
# Fused BN-apply + residual-add + ReLU (pallas/fused_bn.py + the
# _contrib_BatchNormAddReLU registry op)
# ---------------------------------------------------------------------------

def test_scale_bias_add_relu_matches_composed():
    import jax.numpy as jnp
    from mxnet_tpu.pallas.fused_bn import scale_bias_add_relu
    rs = np.random.RandomState(0)
    # shapes chosen to hit: single block (105x33), a PARTIAL row block
    # (280 rows > BLOCK_ROWS=256, not a multiple), and a partial column
    # block (600 cols > BLOCK_COLS=512)
    for shape in ((3, 5, 7, 33), (2, 20, 7, 33), (2, 2, 2, 600)):
        c = shape[-1]
        x = jnp.asarray(rs.randn(*shape).astype(np.float32))
        r = jnp.asarray(rs.randn(*shape).astype(np.float32))
        s = jnp.asarray(rs.randn(c).astype(np.float32))
        b = jnp.asarray(rs.randn(c).astype(np.float32))
        got = scale_bias_add_relu(x, s, b, r)
        want = np.maximum(np.asarray(x) * np.asarray(s) + np.asarray(b)
                          + np.asarray(r), 0.0)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6,
                                   atol=1e-6)
        # no-residual form
        got2 = scale_bias_add_relu(x, s, b)
        want2 = np.maximum(np.asarray(x) * np.asarray(s) + np.asarray(b),
                           0.0)
        np.testing.assert_allclose(np.asarray(got2), want2, rtol=1e-6,
                                   atol=1e-6)


def test_scale_bias_add_relu_bf16():
    import jax.numpy as jnp
    from mxnet_tpu.pallas.fused_bn import scale_bias_add_relu
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, 8, 8, 16)).astype(jnp.bfloat16)
    r = jnp.asarray(rs.randn(4, 8, 8, 16)).astype(jnp.bfloat16)
    s = jnp.asarray(rs.randn(16).astype(np.float32))
    b = jnp.asarray(rs.randn(16).astype(np.float32))
    got = scale_bias_add_relu(x, s, b, r)
    assert got.dtype == jnp.bfloat16
    want = np.maximum(
        np.asarray(x, np.float32) * np.asarray(s.astype(jnp.bfloat16),
                                               np.float32)
        + np.asarray(b.astype(jnp.bfloat16), np.float32)
        + np.asarray(r, np.float32), 0.0)
    np.testing.assert_allclose(np.asarray(got, np.float32), want,
                               rtol=2e-2, atol=2e-2)


def test_scale_bias_add_relu_grad():
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.pallas.fused_bn import scale_bias_add_relu
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(2, 3, 3, 9).astype(np.float32))
    r = jnp.asarray(rs.randn(2, 3, 3, 9).astype(np.float32))
    s = jnp.asarray(rs.rand(9).astype(np.float32) + 0.5)
    b = jnp.asarray(rs.randn(9).astype(np.float32))

    def fused(x, s, b, r):
        return jnp.sum(scale_bias_add_relu(x, s, b, r) ** 2)

    def composed(x, s, b, r):
        return jnp.sum(jnp.maximum(x * s + b + r, 0.0) ** 2)

    g1 = jax.grad(fused, argnums=(0, 1, 2, 3))(x, s, b, r)
    g2 = jax.grad(composed, argnums=(0, 1, 2, 3))(x, s, b, r)
    for a, e in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(e), rtol=1e-5,
                                   atol=1e-5)


def test_batch_norm_add_relu_op_matches_bn_chain():
    """_contrib_BatchNormAddReLU == BatchNorm -> +residual -> relu in
    both training and inference mode, channels-last AND channels-first,
    including the moving-stat writeback."""
    rs = np.random.RandomState(3)
    for axis, shape in ((3, (2, 4, 4, 6)), (1, (2, 6, 4, 4))):
        c = shape[axis]
        x = mx.nd.array(rs.randn(*shape).astype(np.float32))
        res = mx.nd.array(rs.randn(*shape).astype(np.float32))
        gamma = mx.nd.array(rs.rand(c).astype(np.float32) + 0.5)
        beta = mx.nd.array(rs.randn(c).astype(np.float32))

        for train in (True, False):
            mean1 = mx.nd.zeros((c,))
            var1 = mx.nd.ones((c,))
            mean2 = mx.nd.zeros((c,))
            var2 = mx.nd.ones((c,))
            from mxnet_tpu import autograd
            with autograd.record(train_mode=train):
                bn = mx.nd.BatchNorm(x, gamma, beta, mean1, var1,
                                     fix_gamma=False, axis=axis)
                want = mx.nd.relu(bn + res)
                got = mx.nd._contrib_BatchNormAddReLU(
                    x, res, gamma, beta, mean2, var2, fix_gamma=False,
                    axis=axis)
            np.testing.assert_allclose(got.asnumpy(), want.asnumpy(),
                                       rtol=1e-5, atol=1e-5)
            # moving stats updated identically
            np.testing.assert_allclose(mean2.asnumpy(), mean1.asnumpy(),
                                       rtol=1e-6)
            np.testing.assert_allclose(var2.asnumpy(), var1.asnumpy(),
                                       rtol=1e-6)


def test_batch_norm_add_relu_symbol_bind():
    """The fused op composes and trains through the symbolic executor."""
    rs = np.random.RandomState(4)
    data = mx.sym.Variable("data")
    res = mx.sym.Variable("res")
    out = mx.sym._contrib_BatchNormAddReLU(data, res, name="bnar",
                                           fix_gamma=False, axis=3)
    out = mx.sym.sum(out)
    ex = out.simple_bind(ctx=mx.cpu(), data=(2, 3, 3, 5), res=(2, 3, 3, 5))
    ex.arg_dict["data"][:] = rs.randn(2, 3, 3, 5).astype(np.float32)
    ex.arg_dict["res"][:] = rs.randn(2, 3, 3, 5).astype(np.float32)
    ex.arg_dict["bnar_gamma"][:] = 1.0
    ex.arg_dict["bnar_beta"][:] = 0.0
    ex.forward(is_train=True)
    ex.backward()
    g = ex.grad_dict["data"].asnumpy()
    assert np.isfinite(g).all() and (g != 0).any()
