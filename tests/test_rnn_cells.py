"""RNN cell suite — parity with reference tests/python/unittest/test_rnn.py
(cell unroll, fused cell, bidirectional, sequential stacks; default
begin_state must bind without explicit batch shapes)."""
import numpy as np

import mxnet_tpu as mx
from mxnet_tpu.rnn import (RNNCell, LSTMCell, GRUCell, FusedRNNCell,
                           SequentialRNNCell, BidirectionalCell, DropoutCell)


def _bind_run(outputs, batch=4, seq=5, feat=6):
    exe = outputs.simple_bind(ctx=mx.current_context(),
                              data=(batch, seq, feat))
    for name, arr in exe.arg_dict.items():
        if name != "data":
            arr[:] = np.random.uniform(-0.1, 0.1, arr.shape)
    exe.arg_dict["data"][:] = np.random.uniform(size=(batch, seq, feat))
    return exe.forward()[0]


def test_lstm_cell_unroll_default_state():
    cell = LSTMCell(num_hidden=8, prefix="l_")
    outputs, states = cell.unroll(5, inputs=mx.sym.Variable("data"),
                                  merge_outputs=True)
    out = _bind_run(outputs)
    assert out.shape == (4, 5, 8)
    assert len(states) == 2


def test_rnn_gru_cells_unroll():
    for cell in (RNNCell(num_hidden=8, prefix="r_"),
                 GRUCell(num_hidden=8, prefix="g_")):
        outputs, _ = cell.unroll(5, inputs=mx.sym.Variable("data"),
                                 merge_outputs=True)
        assert _bind_run(outputs).shape == (4, 5, 8)


def test_fused_cell_unroll_default_state():
    # regression: FusedRNNCell's (layers, 0, H) default state must bind
    cell = FusedRNNCell(num_hidden=8, num_layers=2, mode="lstm",
                        prefix="f_")
    outputs, _ = cell.unroll(5, inputs=mx.sym.Variable("data"),
                             merge_outputs=True)
    assert _bind_run(outputs).shape == (4, 5, 8)


def test_bidirectional_cell_default_state():
    # regression: Bidirectional's concatenated default states must bind
    cell = BidirectionalCell(LSTMCell(num_hidden=8, prefix="lf_"),
                             LSTMCell(num_hidden=8, prefix="rb_"))
    outputs, _ = cell.unroll(5, inputs=mx.sym.Variable("data"),
                             merge_outputs=True)
    assert _bind_run(outputs).shape == (4, 5, 16)


def test_sequential_stack_with_dropout():
    stack = SequentialRNNCell()
    stack.add(LSTMCell(num_hidden=8, prefix="s0_"))
    stack.add(DropoutCell(0.0, prefix="sd_"))
    stack.add(LSTMCell(num_hidden=6, prefix="s1_"))
    outputs, states = stack.unroll(5, inputs=mx.sym.Variable("data"),
                                   merge_outputs=True)
    assert _bind_run(outputs).shape == (4, 5, 6)


def test_cell_explicit_begin_state_matches_zeros():
    cell = LSTMCell(num_hidden=8, prefix="e_")
    data = mx.sym.Variable("data")
    out_default, _ = cell.unroll(3, inputs=data, merge_outputs=True)
    cell2 = LSTMCell(num_hidden=8, prefix="e_", params=cell.params)
    explicit = [mx.sym.Variable("h0"), mx.sym.Variable("c0")]
    out_explicit, _ = cell2.unroll(3, inputs=data,
                                   begin_state=explicit,
                                   merge_outputs=True)
    def fill(exe):
        for name, arr in exe.arg_dict.items():
            # name-deterministic values so both executors agree per-param
            arr[:] = (np.arange(arr.size).reshape(arr.shape) % 7 - 3) * 0.03
    exe1 = out_default.simple_bind(ctx=mx.current_context(), data=(2, 3, 4))
    fill(exe1)
    r1 = exe1.forward()[0].asnumpy()
    exe2 = out_explicit.simple_bind(ctx=mx.current_context(),
                                    data=(2, 3, 4), h0=(2, 8), c0=(2, 8))
    fill(exe2)
    for name in ("h0", "c0"):
        exe2.arg_dict[name][:] = 0
    r2 = exe2.forward()[0].asnumpy()
    np.testing.assert_allclose(r1, r2, rtol=1e-5, atol=1e-6)
