"""Continuous-batching decode engine: slot pool + donated KV cache.

Equivalence methodology: the one thing continuous batching must never
do is change the math. The reference for "slot-batched" is the SAME
engine driven one sequence at a time (decode dispatches at slot bucket
1); the batched leg drives all slots concurrently (bucket S). Token ids
AND logits compare bit-exact — measured to hold on the CPU backend
because the per-row kernels are identical across vmap widths — in fp32
and bf16. An eager (un-jitted) incremental reference rides along for
token-id equality, catching any batching bug the cross-bucket
comparison could mask.
"""
import threading
import time

import numpy as np
import pytest

import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.decode import (DecodeEngine, AttentionDecodeCell,
                              LSTMDecodeCell, DeadlineExceeded,
                              QueueOverflow, CircuitOpen, EngineClosed)

PROMPTS = ([3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7, 9, 3], [2, 7])


def _prompts():
    return [np.array(p, np.int32) for p in PROMPTS]


def _attn_cell(dtype=np.float32, heads=4):
    return AttentionDecodeCell(vocab=29, embed=16, heads=heads,
                               head_dim=8, max_len=48, dtype=dtype)


def _engine(cell, **kw):
    kw.setdefault("slots", 4)
    kw.setdefault("max_prompt_len", 16)
    kw.setdefault("max_new_tokens", 10)
    kw.setdefault("keep_logits", True)
    return DecodeEngine(cell, cell.init_params(1), **kw)


def _serial_then_batched(eng, prompts, **kw):
    """The equivalence harness: one-at-a-time (slot bucket 1) then all
    concurrent (slot bucket N) through the SAME engine and cache pool."""
    serial = [eng.generate(p, **kw) for p in prompts]
    futs = [eng.submit(p, **kw) for p in prompts]
    batched = [f.result(timeout=120) for f in futs]
    return serial, batched


# -- bit-exact equivalence ---------------------------------------------------

@pytest.mark.parametrize("dtype", [np.float32, jnp.bfloat16],
                         ids=["fp32", "bf16"])
def test_slot_batched_bit_exact_attention(dtype):
    """Slot-batched decode is BIT-EXACT against one-at-a-time decode —
    same tokens, same logits bytes — for the KV-cached attention cell,
    in fp32 and bf16."""
    with _engine(_attn_cell(dtype)) as eng:
        serial, batched = _serial_then_batched(eng, _prompts())
    for a, b in zip(serial, batched):
        assert a.tokens == b.tokens
        assert a.logits.dtype == b.logits.dtype
        assert np.array_equal(np.asarray(a.logits, np.float32),
                              np.asarray(b.logits, np.float32))


def test_slot_batched_lstm_tokens_exact():
    """The RNN-shaped cell (hidden/cell state pool): token ids are
    EXACT across slot-bucket widths; the logits are ULP-tight only —
    the (B, E) x (E, 4H) gate matmul specializes per batch width
    (measured: 1-ULP drift at width 4 vs 1), the same
    kernel-specialization reality test_serving.py documents for
    cross-bucket comparisons."""
    cell = LSTMDecodeCell(vocab=23, embed=8, hidden=16, max_len=32)
    with _engine(cell) as eng:
        serial, batched = _serial_then_batched(eng, _prompts())
    for a, b in zip(serial, batched):
        assert a.tokens == b.tokens
        np.testing.assert_allclose(a.logits, b.logits, atol=1e-6)


def test_engine_matches_eager_incremental_reference():
    """The engine's tokens match an UN-JITTED incremental decode using
    the cell's own step math — the cross-implementation check the
    bucket-vs-bucket comparison cannot provide."""
    import jax
    cell = _attn_cell()
    params_np = cell.init_params(1)
    with _engine(cell, slots=2, max_new_tokens=8) as eng:
        got = [eng.generate(p) for p in _prompts()[:2]]
    params = {k: jnp.asarray(v) for k, v in params_np.items()}
    for prompt, res in zip(_prompts()[:2], got):
        state = {n: jnp.zeros(s[1:], d)
                 for n, (s, d) in cell.cache_spec(1).items()}
        # eager prefill: teacher-force the prompt one token at a time
        toks = []
        for i, t in enumerate(prompt):
            state, logits = cell.step(params, state, jnp.int32(t),
                                      jnp.int32(i))
        tok = int(jnp.argmax(logits))
        toks.append(tok)
        pos = len(prompt)
        while len(toks) < 8:
            state, logits = cell.step(params, state, jnp.int32(tok),
                                      jnp.int32(pos))
            tok = int(jnp.argmax(logits))
            toks.append(tok)
            pos += 1
        assert toks == res.tokens
        np.testing.assert_allclose(np.asarray(logits, np.float32),
                                   np.asarray(res.logits[-1], np.float32),
                                   rtol=2e-5, atol=2e-6)


def test_retire_readmit_no_state_bleed():
    """A slot's cache is fully overwritten on re-admission: the same
    prompt decodes bit-identically before and after the slot hosted a
    DIFFERENT longer sequence (stale cache positions past the new
    prompt's length are never attended)."""
    cell = _attn_cell()
    with _engine(cell, slots=1, max_new_tokens=12) as eng:
        probe = np.array([2, 7], np.int32)
        first = eng.generate(probe)
        # occupy the single slot with a longer, different sequence
        eng.generate(np.array([5, 3, 5, 8, 9, 7, 9, 3], np.int32),
                     max_new_tokens=16)
        again = eng.generate(probe)
    assert first.tokens == again.tokens
    assert np.array_equal(first.logits, again.logits)


# -- steady-state compile discipline ----------------------------------------

def test_zero_steady_state_compiles():
    """After warmup every (prompt bucket, slot bucket) program exists:
    live traffic across varying prompt lengths and slot occupancies
    records ZERO jit_compile spans."""
    cell = _attn_cell()
    eng = _engine(cell, max_new_tokens=6)
    try:
        telemetry.reset()      # drop the warmup compiles from the books
        futs = [eng.submit(p) for p in _prompts()]
        [f.result(timeout=120) for f in futs]
        for p in _prompts()[:2]:       # different occupancy mix
            eng.generate(p)
        spans = telemetry.span_stats()
        assert spans.get("jit_compile", {}).get("count", 0) == 0
        assert spans["serve_decode_step"]["count"] == eng.stats()["steps"]
    finally:
        eng.close()


def test_warmup_builds_every_bucket_card():
    cell = _attn_cell()
    with _engine(cell) as eng:
        cards = eng.program_cards()
        prefill = [k for k in cards if k.startswith("decode_prefill")]
        step = [k for k in cards if k.startswith("decode_step")]
        assert len(prefill) == len(eng.prompt_buckets)
        assert len(step) == len(eng.slot_buckets)


# -- ledger interplay --------------------------------------------------------

def test_kv_cache_charged_to_ledger_by_kind():
    """The cache pool is a NAMED by-kind ledger charge: stats() reports
    it, ledger_top() names it (the OOM-postmortem requirement), and the
    per-slot figure divides evenly."""
    cell = _attn_cell()
    with _engine(cell, slots=4) as eng:
        st = eng.stats()
        expect = sum(int(np.prod(s)) * np.dtype(d).itemsize
                     for s, d in cell.cache_spec(4).values())
        assert st["kv_cache_bytes"] == expect
        assert st["kv_cache_bytes_per_slot"] == expect // 4
        # the global per-context ledger carries the charge by kind
        # (>=: every decode engine sharing the context adds to it)
        led = telemetry.ledger().get("mesh(1dev)", {})
        assert led.get("by_kind", {}).get("kv_cache", 0) >= expect
        kinds = {r["kind"] for r in telemetry.ledger_top(64)}
        assert "kv_cache" in kinds


def test_mp_sharded_cache_reads_fraction_of_replicated():
    """The mp leg: under DECODE_PARTITION_RULES on a 1x8 mesh the
    head-sharded cache's committed (per-shard x devices) bytes read
    exactly 1/mp of the same cache replicated onto that mesh."""
    from mxnet_tpu.parallel.ring_attention import DECODE_PARTITION_RULES
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device CPU mesh")
    cell = _attn_cell(heads=8)
    axes = {"dp": 1, "mp": 8}
    ctxs = [mx.context.cpu(i) for i in range(8)]
    with _engine(cell, partition_rules=DECODE_PARTITION_RULES,
                 mesh_axes=axes, contexts=ctxs) as sharded:
        sharded_bytes = sharded.stats()["kv_cache_bytes"]
        assert sharded.generate(np.array([1, 2, 3], np.int32),
                                max_new_tokens=4).tokens
    with _engine(cell, partition_rules=[], mesh_axes=axes,
                 contexts=ctxs) as repl:
        repl_bytes = repl.stats()["kv_cache_bytes"]
    assert repl_bytes == 8 * sharded_bytes


def test_serving_stats_device_bytes_by_kind():
    """InferenceEngine.stats() now carries the ledger's by-kind view of
    its context (model params vs kv_cache on a shared mesh)."""
    from tests.test_serving import _engine as _serving_engine
    _, _, eng = _serving_engine()
    with eng:
        db = eng.stats()["device_bytes"]
    assert set(db) == {"context", "total", "by_kind"}
    assert isinstance(db["by_kind"], dict)


# -- overload control --------------------------------------------------------

def test_deadline_shed_at_slot_saturation():
    """A saturated slot pool sheds queued prompts past their deadline
    (DeadlineExceeded, cause slot_wait) instead of decoding answers
    nobody is waiting for; the survivor completes."""
    cell = _attn_cell()
    with _engine(cell, slots=1, max_new_tokens=48 - 16) as eng:
        long_fut = eng.submit(_prompts()[2], max_new_tokens=30)
        doomed = eng.submit(_prompts()[0], max_new_tokens=2,
                            deadline_ms=1.0)
        with pytest.raises(DeadlineExceeded):
            doomed.result(timeout=60)
        assert long_fut.result(timeout=120).tokens
        st = eng.stats()
        assert st["shed_by_cause"].get("slot_wait") == 1
        assert st["shed_requests"] == 1
        assert st["resolved"] == 1


def test_queue_overflow_sheds_at_admission():
    cell = _attn_cell()
    with _engine(cell, slots=1, max_queue=1) as eng:
        running = eng.submit(_prompts()[2], max_new_tokens=30)
        # wait for admission so the next submit deterministically QUEUES
        deadline = time.monotonic() + 30
        while eng.overload_state()["active_slots"] < 1:
            assert time.monotonic() < deadline, "admission stalled"
            time.sleep(0.001)
        # the queue bound counts sequences WAITING for a slot; fill it
        queued = eng.submit(_prompts()[0], max_new_tokens=2)
        with pytest.raises(QueueOverflow):
            eng.submit(_prompts()[1], max_new_tokens=2)
        assert running.result(timeout=120).tokens
        assert queued.result(timeout=120).tokens
        assert eng.stats()["shed_by_cause"].get("admission", 0) >= 1


def test_mid_decode_deadline_shed():
    """A slotted sequence past its deadline sheds at the step boundary
    and frees the slot. A delaying step proxy makes the timing
    deterministic (CPU steps are too fast to outlast any real
    deadline)."""
    cell = _attn_cell()
    with _engine(cell, slots=2) as eng:
        real = eng._decode_prog

        class _Slow:
            entry = real.entry

            def __call__(self, *a):
                time.sleep(0.01)
                return real(*a)

        eng._decode_prog = _Slow()
        fut = eng.submit(_prompts()[2], max_new_tokens=30,
                         deadline_ms=50.0)
        with pytest.raises(DeadlineExceeded):
            fut.result(timeout=60)
        eng._decode_prog = real
        st = eng.stats()
        assert st["shed_by_cause"].get("decode") == 1
        assert st["active_slots"] == 0
        # the pool keeps serving after the shed
        assert eng.generate(_prompts()[0], max_new_tokens=2).tokens


def test_dispatch_failure_poisons_pool_and_recovers():
    """A terminal decode-dispatch failure fails every in-flight
    sequence (the donated pool is unrecoverable), rebuilds a zeroed
    pool, and the engine keeps serving — with bit-identical results."""
    cell = _attn_cell()
    with _engine(cell, slots=2, retry_budget=0,
                 breaker_threshold=0) as eng:
        before = eng.generate(_prompts()[0], max_new_tokens=4)
        real = eng._decode_prog

        class _Boom:
            entry = real.entry

            def __call__(self, *a):
                raise ValueError("injected: decode backend fell over")

            def build(self, *a):
                return real.build(*a)

        eng._decode_prog = _Boom()
        fut = eng.submit(_prompts()[1], max_new_tokens=4)
        with pytest.raises(mx.MXNetError, match="poisoned"):
            fut.result(timeout=60)
        eng._decode_prog = real
        after = eng.generate(_prompts()[0], max_new_tokens=4)
        st = eng.stats()
    assert before.tokens == after.tokens
    assert np.array_equal(before.logits, after.logits)
    assert st["failed_requests"] == 1
    assert st["dispatch_failures"] == 1


def test_breaker_trips_and_resets():
    cell = _attn_cell()
    with _engine(cell, slots=1, retry_budget=0, breaker_threshold=1,
                 breaker_reset_s=3600.0) as eng:
        real = eng._decode_prog

        class _Boom:
            entry = real.entry

            def __call__(self, *a):
                raise ValueError("injected")

        eng._decode_prog = _Boom()
        with pytest.raises(mx.MXNetError):
            eng.generate(_prompts()[0], max_new_tokens=4)
        eng._decode_prog = real
        with pytest.raises(CircuitOpen):
            eng.submit(_prompts()[0])
        assert eng.stats()["breaker"]["open"]
        eng.reset_breaker()
        assert eng.generate(_prompts()[0], max_new_tokens=2).tokens


# -- lifecycle ---------------------------------------------------------------

def test_close_drains_admitted_sequences():
    """close() resolves every already-submitted sequence (generation
    completes) before returning; later submits raise EngineClosed."""
    cell = _attn_cell()
    eng = _engine(cell)
    futs = [eng.submit(p, max_new_tokens=6) for p in _prompts()]
    eng.close()
    for f in futs:
        assert len(f.result(timeout=1).tokens) == 6
    with pytest.raises(EngineClosed):
        eng.submit(_prompts()[0])
    eng.close()      # idempotent


def test_submit_validation():
    cell = _attn_cell()
    with _engine(cell) as eng:
        with pytest.raises(mx.MXNetError, match="max_prompt_len"):
            eng.submit(np.arange(17, dtype=np.int32))
        with pytest.raises(mx.MXNetError, match="max_len"):
            eng.submit(_prompts()[0], max_new_tokens=48)
        with pytest.raises(mx.MXNetError, match="non-empty"):
            eng.submit(np.zeros((0,), np.int32))
    with pytest.raises(mx.MXNetError, match="overload"):
        _engine(cell, overload="panic")


def test_eos_stops_generation():
    """Generation stops at the default or per-request EOS id."""
    cell = _attn_cell()
    with _engine(cell, max_new_tokens=12) as eng:
        free = eng.generate(_prompts()[1])
        assert len(free.tokens) == 12
        eos = free.tokens[3]
        stopped = eng.generate(_prompts()[1], eos_id=eos)
        assert stopped.tokens == free.tokens[:4]
        assert stopped.tokens[-1] == eos


# -- telemetry ---------------------------------------------------------------

def test_decode_counters_and_flow_spans():
    """The decode.* counters land and the per-token flow spans
    (serve_prefill -> serve_decode_step x N -> serve_detokenize) are
    recorded with causal req ctx."""
    telemetry.reset()
    cell = _attn_cell()
    with _engine(cell) as eng:
        futs = [eng.submit(p, max_new_tokens=5) for p in _prompts()]
        [f.result(timeout=120) for f in futs]
    c = telemetry.counters()
    assert c["decode.requests"] == 4
    assert c["decode.slot_admit"] == 4
    assert c["decode.slot_retire"] == 4
    assert c["decode.resolved"] == 4
    assert c["decode.tokens"] == 20
    assert c["decode.steps"] >= 4
    spans = telemetry.span_stats()
    for name in telemetry.DECODE_SPANS:
        assert spans[name]["count"] >= 4, name
    assert spans["serve_prefill"]["count"] == 4
    assert spans["serve_detokenize"]["count"] == 4


def test_log_decode_line(caplog):
    from mxnet_tpu.callback import TelemetryLogger
    telemetry.reset()
    logger = TelemetryLogger(frequent=1)
    cell = _attn_cell()
    with caplog.at_level("INFO", logger="mxnet_tpu.telemetry"):
        with _engine(cell, telemetry_logger=logger,
                     max_new_tokens=6) as eng:
            [f.result(timeout=120)
             for f in [eng.submit(p) for p in _prompts()]]
    lines = [r.message for r in caplog.records
             if r.message.startswith("decode:")]
    assert lines
    assert "tok/s=" in lines[-1]
    assert "active_slots=" in lines[-1]


def test_overload_state_for_flight_sampler():
    cell = _attn_cell()
    with _engine(cell) as eng:
        ov = eng.overload_state()
    assert {"queued_rows", "active_slots", "slots", "breaker_open",
            "closed"} <= set(ov)
