"""Flight recorder suite (ISSUE 10): causal ids on spans + chrome flow
events, the discrete-event ring, the metrics sampler / time-series ring
/ JSONL export, the OpenMetrics endpoint, crash postmortems (explicit
triggers, excepthook, throttle) and the flight_view CLI."""
import json
import logging
import os
import subprocess
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import flight, telemetry

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "tools"))
import flight_view  # noqa: E402  (stdlib-only CLI module)


@pytest.fixture(autouse=True)
def _clean_registry():
    """Fresh telemetry + inert flight recorder around every test (both
    are process-global)."""
    telemetry.enable()
    telemetry.reset()
    flight.sampler_stop()
    flight.series_clear()
    flight.configure(None)
    yield
    flight.sampler_stop()
    flight.metrics_http_stop()
    flight.series_clear()
    flight.configure(None)
    telemetry.enable()
    telemetry.reset()


# ---------------------------------------------------------------------------
# Causal ids
# ---------------------------------------------------------------------------

def test_causal_scope_stamps_spans_and_nests():
    with telemetry.causal(epoch=1, nbatch=7):
        with telemetry.span("feed"):
            pass
        assert telemetry.current_causal() == {"epoch": 1, "nbatch": 7}
        with telemetry.causal(req_id=3):
            with telemetry.span("inner"):
                pass
    assert telemetry.current_causal() is None
    with telemetry.span("outside"):
        pass
    by_name = {s["name"]: s for s in telemetry.recent_spans()}
    assert by_name["feed"]["ctx"] == {"epoch": 1, "nbatch": 7}
    assert by_name["inner"]["ctx"] == {"req_id": 3}
    assert by_name["outside"]["ctx"] is None


def test_span_explicit_ctx_survives_cross_thread_exit():
    # the serving pattern: entered on the submitting thread, exited on
    # a resolver thread — the explicit ctx must ride, not the exiting
    # thread's ambient scope
    sp = telemetry.span("serve_wait", ctx={"req_id": 42}).__enter__()

    def _closer():
        with telemetry.causal(epoch=9, nbatch=9):
            sp.__exit__(None, None, None)

    t = threading.Thread(target=_closer)
    t.start()
    t.join()
    rec = [s for s in telemetry.recent_spans()
           if s["name"] == "serve_wait"]
    assert rec and rec[-1]["ctx"] == {"req_id": 42}


def test_chrome_flow_events_link_shared_ids():
    with telemetry.causal(epoch=0, nbatch=2):
        with telemetry.span("feed"):
            pass
        with telemetry.span("step"):
            pass
    with telemetry.span("serve_batch", ctx={"req_ids": [5, 6]}):
        pass
    with telemetry.span("serve_request", ctx={"req_id": 5}):
        pass
    evs = telemetry.chrome_events(since_trace_start=False)
    step_flow = [e for e in evs if e.get("cat") == "flow"
                 and e["id"] == "step:0:2"]
    assert [e["ph"] for e in step_flow] == ["s", "f"]
    assert step_flow[-1]["bp"] == "e"
    req_flow = [e for e in evs if e.get("cat") == "flow"
                and e["id"] == "req:5"]
    assert [e["ph"] for e in req_flow] == ["s", "f"]
    # a lone id draws no arrow (req 6 appears in ONE span only)
    assert not [e for e in evs if e.get("cat") == "flow"
                and e["id"] == "req:6"]
    # slices carry the causal ids as args for the perfetto tooltip
    feed = [e for e in evs if e.get("ph") == "X" and e["name"] == "feed"]
    assert feed[0]["args"] == {"epoch": 0, "nbatch": 2}


def test_request_flow_chains_in_pipeline_order():
    # the REAL serving shape: serve_request is entered at submit (same
    # instant as serve_wait) and closes last — by start time it would
    # sort second and the chain would terminate at serve_d2h. The flow
    # must chain wait -> batch -> d2h -> request, with the terminal 'f'
    # bound near the serve_request span's END (the resolution instant).
    req_sp = telemetry.span("serve_request",
                            ctx={"req_id": 9}).__enter__()
    with telemetry.span("serve_wait", ctx={"req_id": 9}):
        time.sleep(0.001)
    with telemetry.span("serve_batch", ctx={"req_ids": [9]}):
        time.sleep(0.001)
    with telemetry.span("serve_d2h", ctx={"req_ids": [9]}):
        time.sleep(0.001)
    time.sleep(0.001)
    req_sp.__exit__(None, None, None)
    evs = telemetry.chrome_events(since_trace_start=False)
    flow = [e for e in evs if e.get("cat") == "flow"
            and e["id"] == "req:9"]
    assert [e["ph"] for e in flow] == ["s", "t", "t", "f"]
    slices = {e["name"]: e for e in evs if e.get("ph") == "X"}
    # the chain's nodes bind in pipeline order: wait, batch, d2h
    # starts, then the request terminus
    assert flow[0]["ts"] == slices["serve_wait"]["ts"]
    assert flow[1]["ts"] == slices["serve_batch"]["ts"]
    assert flow[2]["ts"] == slices["serve_d2h"]["ts"]
    req = slices["serve_request"]
    assert flow[3]["bp"] == "e"
    # terminal node sits inside the serve_request slice, AFTER the d2h
    # slice began — the resolution instant, not the submit instant
    assert req["ts"] <= flow[3]["ts"] <= req["ts"] + req["dur"]
    assert flow[3]["ts"] > slices["serve_d2h"]["ts"]


# ---------------------------------------------------------------------------
# Event ring
# ---------------------------------------------------------------------------

def test_event_ring_records_bounded_and_resets():
    telemetry.record_event("serving.shed", req_id=1, cause="admission")
    evs = telemetry.events()
    assert evs[-1]["kind"] == "serving.shed"
    assert evs[-1]["data"] == {"req_id": 1, "cause": "admission"}
    for i in range(telemetry.EVENT_RING_SIZE + 10):
        telemetry.record_event("tick", i=i)
    assert len(telemetry.events()) == telemetry.EVENT_RING_SIZE
    assert telemetry.events(n=3)[-1]["data"] == {
        "i": telemetry.EVENT_RING_SIZE + 9}
    telemetry.reset()
    assert telemetry.events() == []
    telemetry.disable()
    telemetry.record_event("off")
    telemetry.enable()
    assert telemetry.events() == []


# ---------------------------------------------------------------------------
# Metrics sampler + series ring
# ---------------------------------------------------------------------------

def test_sampler_banks_counter_deltas_and_gauges():
    flight.sampler_start(10)
    assert flight.sampler_running()
    assert flight.sampler_interval_ms() == pytest.approx(10.0)
    time.sleep(0.05)
    telemetry.counter_inc("serving.requests", 4)
    telemetry.counter_inc("serving.resolved", 1)
    time.sleep(0.08)
    flight.sampler_stop()
    assert not flight.sampler_running()
    samples = flight.series()
    assert samples, "sampler banked nothing"
    for s in samples:
        assert {"ts", "dt_ms", "counters", "queue_depth",
                "ledger_bytes", "serving"} <= set(s)
    # the bumps landed as DELTAS in some interval, exactly once
    assert sum(s["counters"].get("serving.requests", 0)
               for s in samples) == 4
    # queue depth gauge derives from the cumulative counters
    assert samples[-1]["queue_depth"] == 3
    # a registry reset mid-window flags the sample instead of emitting
    # garbage negative deltas
    flight.sampler_start(10)
    time.sleep(0.03)
    telemetry.reset()
    time.sleep(0.05)
    flight.sampler_stop()
    flagged = [s for s in flight.series() if s.get("registry_reset")]
    assert flagged and flagged[-1]["counters"] == {}


def test_sampler_interval_zero_means_disabled():
    # MXNET_METRICS_INTERVAL_MS=0 must turn the sampler OFF, not spin
    # it at the 1 ms clamp floor
    assert flight.sampler_start(0) is None
    assert not flight.sampler_running()
    assert flight.sampler_start(-5) is None
    assert not flight.sampler_running()


def test_series_window_and_jsonl_dump(tmp_path):
    flight.sampler_start(10)
    time.sleep(0.06)
    flight.sampler_stop()
    win = flight.series_window(3)
    assert win["n"] == len(win["samples"]) <= 3
    out = str(tmp_path / "series.jsonl")
    text = flight.series_dump(out)
    lines = [json.loads(l) for l in text.splitlines()]
    assert lines == flight.series()
    with open(out) as f:
        assert f.read() == text
    flight.series_clear()
    assert flight.series() == []


# ---------------------------------------------------------------------------
# OpenMetrics endpoint
# ---------------------------------------------------------------------------

def test_openmetrics_endpoint_loopback_scrape():
    telemetry.counter_inc("serving.requests", 7)
    # two ledger contexts: the labeled gauge family must emit its
    # '# TYPE' metadata line exactly ONCE (a duplicate is invalid
    # OpenMetrics and Prometheus rejects the whole scrape).
    # SYNTHETIC ctx keys, not cpu(N): reset() deliberately preserves
    # the ledger's ALIVE map (the buffers are still alive), so real
    # device contexts carry whatever earlier tests still hold live —
    # with the native build enabled that made these exact-value
    # asserts order-dependent
    class _Buf:      # bare object() is not weakref-able
        pass

    holders = [_Buf(), _Buf()]
    telemetry.ledger_track(holders[0], "ledgertest(0)", 64)
    telemetry.ledger_track(holders[1], "ledgertest(1)", 128)
    port = flight.metrics_http_start(0)   # ephemeral, loopback-only
    try:
        body = urllib.request.urlopen(
            "http://127.0.0.1:%d/metrics" % port, timeout=10).read()
        text = body.decode()
        # every sample carries the process identity labels (ISSUE 18):
        # a fleet-scraping Prometheus can slice per rank without
        # relabel rules
        ident = telemetry.process_identity()
        who = 'host="%s",rank="%d"' % (ident["host"], ident["rank"])
        assert "# TYPE mxnet_tpu_serving_requests counter" in text
        assert "mxnet_tpu_serving_requests_total{%s} 7" % who in text
        assert "mxnet_tpu_serving_queue_depth" in text
        assert text.count(
            "# TYPE mxnet_tpu_ledger_alive_bytes gauge") == 1
        assert ('mxnet_tpu_ledger_alive_bytes{ctx="ledgertest(0)",%s}'
                ' 64' % who) in text
        assert ('mxnet_tpu_ledger_alive_bytes{ctx="ledgertest(1)",%s}'
                ' 128' % who) in text
        assert text.rstrip().endswith("# EOF")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                "http://127.0.0.1:%d/secrets" % port, timeout=10)
        # idempotent: a second start reports the same bound port
        assert flight.metrics_http_start(0) == port
    finally:
        flight.metrics_http_stop()


# ---------------------------------------------------------------------------
# Postmortems
# ---------------------------------------------------------------------------

def test_postmortem_schema_and_flight_view_summary(tmp_path):
    flight.configure(str(tmp_path))
    # a synthetic request trajectory in the rings: breakdown material
    with telemetry.span("serve_wait", ctx={"req_id": 11}):
        time.sleep(0.002)
    with telemetry.span("serve_batch", ctx={"req_ids": [11]}):
        time.sleep(0.001)
    with telemetry.span("serve_d2h", ctx={"req_ids": [11]}):
        pass
    with telemetry.span("serve_request", ctx={"req_id": 11}):
        time.sleep(0.004)
    telemetry.record_event("serving.batch", req_ids=[11], bucket=8,
                           rows=1, pad_rows=7)
    from mxnet_tpu.faults import InjectedFault
    path = flight.postmortem("unit_test", exc=InjectedFault("dispatch"),
                             extra={"req_ids": [11]})
    assert path is not None and os.path.exists(path)
    assert flight.last_postmortem() == path
    assert telemetry.counters().get("flight.postmortem") == 1
    rec = flight_view.load_dump(path)
    assert rec["reason"] == "unit_test"
    assert rec["exception"]["type"] == "InjectedFault"
    assert rec["exception"]["fault_site"] == "dispatch"
    assert rec["extra"] == {"req_ids": [11]}
    summary = flight_view.summarize(rec)
    slow = summary["slowest_requests"]
    assert slow and slow[0]["req_id"] == 11
    assert slow[0]["total_ms"] >= slow[0]["wait_ms"] > 0
    assert slow[0]["pad_rows"] == 7 and slow[0]["bucket"] == 8
    # wait/batch/d2h/resolve decompose the total
    assert slow[0]["resolve_ms"] >= 0


def test_postmortem_disabled_and_throttled(tmp_path):
    # no dir configured: triggers are no-ops
    assert flight.postmortem("nothing") is None
    flight.configure(str(tmp_path))
    p1 = flight.postmortem("flap")
    p2 = flight.postmortem("flap")          # inside the 1 s throttle
    p3 = flight.postmortem("flap", force=True)
    assert p1 is not None and p2 is None and p3 is not None
    assert p1 != p3


def test_failed_write_does_not_burn_throttle_slot(tmp_path,
                                                  monkeypatch):
    flight.configure(str(tmp_path))
    calls = {"n": 0}
    real = flight.atomic_write

    def flaky(path, data):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("disk full")
        return real(path, data)

    monkeypatch.setattr(flight, "atomic_write", flaky)
    assert flight.postmortem("glitch") is None       # write failed
    assert telemetry.counters().get("flight.postmortem_fail") == 1
    # the failed attempt must NOT have consumed the 1 s throttle slot:
    # an immediate re-trigger of the same reason dumps for real
    p = flight.postmortem("glitch")
    assert p is not None and os.path.exists(p)


def test_env_autostart_is_guarded():
    """Malformed MXNET_METRICS_* env values (and port 0/conflicts) must
    never break ``import mxnet_tpu`` — the recorder warns and stays
    off, like a bad MXNET_FAULTS spec."""
    env = dict(os.environ, MXNET_METRICS_INTERVAL_MS="abc",
               MXNET_METRICS_PORT="abc", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c",
         "from mxnet_tpu import flight; "
         "assert not flight.sampler_running(); "
         "import mxnet_tpu.flight as f; "
         "assert f._http_server is None; print('OK')"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 0 and "OK" in proc.stdout, proc.stderr
    # the env knob treats 0 as OFF for both sampler and endpoint
    env = dict(os.environ, MXNET_METRICS_INTERVAL_MS="0",
               MXNET_METRICS_PORT="0", JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-c",
         "from mxnet_tpu import flight; "
         "assert not flight.sampler_running(); "
         "import mxnet_tpu.flight as f; "
         "assert f._http_server is None; print('OK')"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        timeout=300, env=env, cwd=ROOT)
    assert proc.returncode == 0 and "OK" in proc.stdout, proc.stderr


def test_thread_excepthook_writes_postmortem(tmp_path):
    flight.configure(str(tmp_path))          # also installs the hooks
    assert flight.installed()

    def _boom():
        raise RuntimeError("coalescer down")

    t = threading.Thread(target=_boom, name="doomed")
    t.start()
    t.join()
    dumps = [f for f in os.listdir(str(tmp_path))
             if "uncaught_thread_exception" in f]
    assert dumps, os.listdir(str(tmp_path))
    rec = flight_view.load_dump(os.path.join(str(tmp_path), dumps[0]))
    assert rec["exception"]["type"] == "RuntimeError"
    assert rec["extra"]["thread"] == "doomed"


def test_divergence_halt_triggers_postmortem(tmp_path):
    from mxnet_tpu.checkpoint import DivergenceError
    from mxnet_tpu.module.base_module import BaseModule
    flight.configure(str(tmp_path))
    with pytest.raises(DivergenceError):
        BaseModule()._handle_divergence("halt", None, 3, 14)
    dumps = [f for f in os.listdir(str(tmp_path))
             if "divergence" in f]
    assert dumps
    rec = flight_view.load_dump(os.path.join(str(tmp_path), dumps[0]))
    assert rec["extra"] == {"epoch": 3, "nbatch": 14, "policy": "halt"}
    # the sentinel event landed in the ring too
    assert any(e["kind"] == "divergence.detected"
               for e in rec["events"])


# ---------------------------------------------------------------------------
# flight_view CLI
# ---------------------------------------------------------------------------

def test_flight_view_cli_renders_and_rejects_garbage(tmp_path):
    flight.configure(str(tmp_path))
    telemetry.record_event("serving.shed", req_id=1, cause="coalesce")
    path = flight.postmortem("cli_test", exc=ValueError("x"))
    view = os.path.join(ROOT, "tools", "flight_view.py")
    proc = subprocess.run([sys.executable, view, path],
                         stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                         text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "flight postmortem: cli_test" in proc.stdout
    assert "event timeline" in proc.stdout
    assert "serving.shed" in proc.stdout
    proc_json = subprocess.run([sys.executable, view, path, "--json"],
                               stdout=subprocess.PIPE, text=True,
                               timeout=60)
    assert proc_json.returncode == 0
    assert json.loads(proc_json.stdout)["reason"] == "cli_test"
    # malformed inputs exit non-zero: truncated JSON, wrong schema,
    # missing file, bad usage
    bad = str(tmp_path / "bad.json")
    with open(bad, "w") as f:
        f.write("{\"schema\": \"mxnet_tpu.flight/1\", \"reason\":")
    for argv in ([view, bad], [view, str(tmp_path / "absent.json")],
                 [view]):
        p = subprocess.run([sys.executable] + argv,
                           stdout=subprocess.PIPE,
                           stderr=subprocess.PIPE, text=True, timeout=60)
        assert p.returncode != 0, argv
    wrong = str(tmp_path / "wrong.json")
    with open(wrong, "w") as f:
        json.dump({"schema": "other/1"}, f)
    with pytest.raises(flight_view.MalformedDump):
        flight_view.load_dump(wrong)


# ---------------------------------------------------------------------------
# TelemetryLogger.log_series
# ---------------------------------------------------------------------------

def test_telemetry_logger_log_series(caplog):
    logger = mx.callback.TelemetryLogger(frequent=1)
    with caplog.at_level(logging.INFO, logger="mxnet_tpu.telemetry"):
        logger.log_series()                  # no sampler: silent no-op
        flight.sampler_start(10)
        telemetry.counter_inc("serving.requests", 20)
        telemetry.counter_inc("serving.shed_requests", 5)
        telemetry.counter_inc("dispatch.serve", 2)
        time.sleep(0.08)
        flight.sampler_stop()
        logger.log_series()
        logger.log_series()                  # nothing new: no line
    lines = [r.message for r in caplog.records
             if r.message.startswith("series:")]
    assert len(lines) == 1, lines
    assert "req/s=" in lines[0] and "shed/s=" in lines[0]
    assert "dispatch/s=" in lines[0]


# ---------------------------------------------------------------------------
# Module.fit integration: step ids on the fit-phase spans
# ---------------------------------------------------------------------------

def _mlp():
    data = mx.sym.Variable("data")
    net = mx.sym.FullyConnected(data, num_hidden=16, name="fc1")
    net = mx.sym.Activation(net, act_type="relu")
    net = mx.sym.FullyConnected(net, num_hidden=4, name="fc2")
    return mx.sym.SoftmaxOutput(net, name="softmax")


def test_fit_stamps_step_ids_and_flows():
    rs = np.random.RandomState(0)
    X = rs.uniform(-1, 1, (32 * 3, 8)).astype(np.float32)
    Y = rs.randint(0, 4, 32 * 3).astype(np.float32)
    mod = mx.mod.Module(_mlp(), context=mx.cpu())
    metric = mx.metric.Accuracy()

    def fit():
        it = mx.io.NDArrayIter(X, Y, batch_size=32)
        mod.fit(it, eval_metric=metric, num_epoch=1,
                initializer=mx.initializer.Xavier(), optimizer="sgd",
                optimizer_params={"learning_rate": 0.05})

    fit()                  # bind + compile outside the asserted window
    telemetry.reset()
    fit()
    spans = [s for s in telemetry.recent_spans()
             if s["ctx"] and s["ctx"].get("nbatch") == 1]
    names = {s["name"] for s in spans}
    assert {"fit_batch", "feed", "step"} <= names, names
    flows = [e for e in telemetry.chrome_events(since_trace_start=False)
             if e.get("cat") == "flow" and e["id"] == "step:0:1"]
    phs = [e["ph"] for e in flows]
    assert phs[0] == "s" and phs[-1] == "f" and len(phs) >= 3
