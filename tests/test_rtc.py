"""Runtime-compiled kernel tests (parity: reference tests/python/gpu/
test_rtc.py — here Pallas/jax source instead of CUDA C)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu.base import MXNetError


def test_rtc_axpy():
    source = """
import jax.numpy as jnp
def axpy(alpha, x, y):
    return y + alpha * x
"""
    module = mx.rtc.PallasModule(source, exports=["axpy"])
    k = module.get_kernel("axpy", "float alpha, const float *x, float *y")
    x = mx.nd.array(np.arange(8, dtype=np.float32))
    y = mx.nd.ones((8,))
    k.launch([2.0, x, y], mx.cpu(0), (1, 1, 1), (8, 1, 1))
    np.testing.assert_allclose(y.asnumpy(), 1 + 2 * np.arange(8))


def test_rtc_multiple_outputs():
    source = """
def swap(a, b):
    return b, a
"""
    module = mx.rtc.PallasModule(source)
    k = module.get_kernel("swap", "float *a, float *b")
    a = mx.nd.zeros((3,))
    b = mx.nd.ones((3,))
    k.launch([a, b], mx.cpu(0))
    assert a.asnumpy().sum() == 3 and b.asnumpy().sum() == 0


def test_rtc_bad_signature():
    module = mx.rtc.PallasModule("def f(x):\n    return x\n")
    with pytest.raises(MXNetError):
        module.get_kernel("f", "widget *x")
    with pytest.raises(MXNetError):
        module.get_kernel("g", "float *x")


def test_rtc_pallas_kernel():
    """A real pallas_call kernel compiled from source at runtime."""
    source = """
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

def _scale_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0

def double(x):
    return pl.pallas_call(
        _scale_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        interpret=True,  # CPU test mesh; compiles natively on TPU
    )(x)
"""
    module = mx.rtc.PallasModule(source, exports=["double"])
    k = module.get_kernel("double", "float *x")
    x = mx.nd.array(np.arange(4, dtype=np.float32))
    k.launch([x], mx.cpu(0))
    np.testing.assert_allclose(x.asnumpy(), 2.0 * np.arange(4))
