"""NDArray tests (parity model: reference tests/python/unittest/test_ndarray.py)."""
import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd


def test_creation():
    a = nd.zeros((3, 4))
    assert a.shape == (3, 4)
    assert a.dtype == np.float32
    assert (a.asnumpy() == 0).all()

    b = nd.ones((2,), dtype="int32")
    assert b.dtype == np.int32

    c = nd.full((2, 2), 7.0)
    assert (c.asnumpy() == 7).all()

    d = nd.array([[1, 2], [3, 4]])
    assert d.shape == (2, 2)

    e = nd.arange(0, 10, 2)
    np.testing.assert_allclose(e.asnumpy(), np.arange(0, 10, 2, dtype=np.float32))


def test_arithmetic():
    a = nd.array([[1.0, 2.0], [3.0, 4.0]])
    b = nd.array([[10.0, 20.0], [30.0, 40.0]])
    np.testing.assert_allclose((a + b).asnumpy(), [[11, 22], [33, 44]])
    np.testing.assert_allclose((b - a).asnumpy(), [[9, 18], [27, 36]])
    np.testing.assert_allclose((a * b).asnumpy(), [[10, 40], [90, 160]])
    np.testing.assert_allclose((b / a).asnumpy(), [[10, 10], [10, 10]])
    np.testing.assert_allclose((a + 1).asnumpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((1 - a).asnumpy(), [[0, -1], [-2, -3]])
    np.testing.assert_allclose((2 * a).asnumpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((2 / a).asnumpy(), 2 / a.asnumpy())
    np.testing.assert_allclose((a ** 2).asnumpy(), [[1, 4], [9, 16]], rtol=1e-5)
    np.testing.assert_allclose((-a).asnumpy(), -a.asnumpy())


def test_inplace():
    a = nd.ones((2, 2))
    aid = id(a)
    a += 1
    assert id(a) == aid
    np.testing.assert_allclose(a.asnumpy(), 2 * np.ones((2, 2)))
    a *= 3
    np.testing.assert_allclose(a.asnumpy(), 6 * np.ones((2, 2)))


def test_broadcast():
    a = nd.ones((2, 1, 3))
    b = nd.ones((1, 4, 3))
    assert (a + b).shape == (2, 4, 3)
    c = nd.ones((2, 1)).broadcast_to((2, 5))
    assert c.shape == (2, 5)


def test_comparisons():
    a = nd.array([1.0, 2.0, 3.0])
    b = nd.array([2.0, 2.0, 2.0])
    np.testing.assert_allclose((a > b).asnumpy(), [0, 0, 1])
    np.testing.assert_allclose((a == b).asnumpy(), [0, 1, 0])
    np.testing.assert_allclose((a <= 2).asnumpy(), [1, 1, 0])


def test_indexing():
    a = nd.array(np.arange(24).reshape(2, 3, 4))
    np.testing.assert_allclose(a[1].asnumpy(), np.arange(12, 24).reshape(3, 4))
    np.testing.assert_allclose(a[0, 1].asnumpy(), [4, 5, 6, 7])
    np.testing.assert_allclose(a[:, 1:3].asnumpy(),
                               np.arange(24).reshape(2, 3, 4)[:, 1:3])
    a[0] = 0
    assert (a.asnumpy()[0] == 0).all()
    a[:] = 5
    assert (a.asnumpy() == 5).all()


def test_reshape_special_codes():
    a = nd.ones((2, 3, 4, 5))
    assert a.reshape((-1,)).shape == (120,)
    assert a.reshape((0, -1)).shape == (2, 60)
    assert a.reshape((0, 0, -1)).shape == (2, 3, 20)
    assert a.reshape((-3, 0, 0)).shape == (6, 4, 5)
    assert a.reshape((0, -4, -1, 1, 0, 0)).shape == (2, 3, 1, 4, 5)
    assert a.reshape((0, 0, -2)).shape == (2, 3, 4, 5)


def test_dtype_cast():
    a = nd.ones((2, 2), dtype="float32")
    b = a.astype("int32")
    assert b.dtype == np.int32
    c = nd.Cast(a, dtype="float16")
    assert c.dtype == np.float16


def test_copy_and_context():
    a = nd.ones((2, 2))
    b = a.copy()
    b += 1
    assert (a.asnumpy() == 1).all()
    c = a.as_in_context(mx.cpu(0))
    assert c.context.device_type == "cpu"
    d = nd.zeros((2, 2))
    a.copyto(d)
    assert (d.asnumpy() == 1).all()


def test_scalar_conversions():
    a = nd.array([3.5])
    assert float(a) == 3.5
    assert a.asscalar() == np.float32(3.5)
    assert len(nd.zeros((5, 2))) == 5


def test_sync_api():
    a = nd.ones((4, 4))
    a.wait_to_read()
    nd.waitall()


def test_reductions():
    x = np.random.uniform(-1, 1, (3, 4, 5)).astype(np.float32)
    a = nd.array(x)
    np.testing.assert_allclose(a.sum().asnumpy(), x.sum().reshape(()), rtol=1e-5)
    np.testing.assert_allclose(nd.sum(a, axis=1).asnumpy(), x.sum(1), rtol=1e-5)
    np.testing.assert_allclose(nd.sum(a, axis=(0, 2)).asnumpy(), x.sum((0, 2)), rtol=1e-4)
    np.testing.assert_allclose(nd.sum(a, axis=1, exclude=True).asnumpy(),
                               x.sum((0, 2)), rtol=1e-4)
    np.testing.assert_allclose(nd.mean(a, axis=0, keepdims=True).asnumpy(),
                               x.mean(0, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(nd.max(a, axis=2).asnumpy(), x.max(2))
    np.testing.assert_allclose(nd.argmax(a, axis=1).asnumpy(), x.argmax(1))
    np.testing.assert_allclose(nd.norm(a).asnumpy(),
                               [np.sqrt((x ** 2).sum())], rtol=1e-5)


def test_dot():
    A = np.random.normal(size=(3, 4)).astype(np.float32)
    B = np.random.normal(size=(4, 5)).astype(np.float32)
    np.testing.assert_allclose(nd.dot(nd.array(A), nd.array(B)).asnumpy(),
                               A @ B, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        nd.dot(nd.array(A), nd.array(B.T), transpose_b=True).asnumpy(),
        A @ B, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(
        nd.dot(nd.array(A.T), nd.array(B), transpose_a=True).asnumpy(),
        A @ B, rtol=1e-4, atol=1e-5)
    bA = np.random.normal(size=(2, 3, 4)).astype(np.float32)
    bB = np.random.normal(size=(2, 4, 5)).astype(np.float32)
    np.testing.assert_allclose(nd.batch_dot(nd.array(bA), nd.array(bB)).asnumpy(),
                               bA @ bB, rtol=1e-4, atol=1e-5)


def test_concat_split_stack():
    a = nd.ones((2, 3))
    b = nd.zeros((2, 3))
    c = nd.Concat(a, b, dim=0)
    assert c.shape == (4, 3)
    parts = nd.SliceChannel(nd.array(np.arange(12).reshape(2, 6)),
                            num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2)
    s = nd.stack(a, b, axis=0)
    assert s.shape == (2, 2, 3)


def test_take_onehot():
    w = nd.array(np.arange(20).reshape(10, 2))
    idx = nd.array([0, 5, 9])
    out = nd.take(w, idx)
    np.testing.assert_allclose(out.asnumpy(),
                               np.arange(20).reshape(10, 2)[[0, 5, 9]])
    oh = nd.one_hot(nd.array([0, 2]), depth=4)
    np.testing.assert_allclose(oh.asnumpy(), [[1, 0, 0, 0], [0, 0, 1, 0]])


def test_where_clip():
    cond = nd.array([1.0, 0.0, 1.0])
    x = nd.array([1.0, 2.0, 3.0])
    y = nd.array([-1.0, -2.0, -3.0])
    np.testing.assert_allclose(nd.where(cond, x, y).asnumpy(), [1, -2, 3])
    np.testing.assert_allclose(nd.clip(nd.array([-2.0, 0.5, 2.0]), 0.0, 1.0).asnumpy(),
                               [0, 0.5, 1])


def test_save_load_roundtrip(tmp_path):
    fname = str(tmp_path / "arrs")
    d = {"w": nd.array([[1.0, 2.0]]), "b": nd.zeros((3,))}
    nd.save(fname, d)
    loaded = nd.load(fname)
    assert set(loaded) == {"w", "b"}
    np.testing.assert_allclose(loaded["w"].asnumpy(), [[1, 2]])

    lst = [nd.ones((2,)), nd.zeros((3,))]
    nd.save(fname, lst)
    loaded = nd.load(fname)
    assert isinstance(loaded, list) and loaded[0].shape == (2,)


def test_random_shapes_and_seed():
    mx.random.seed(42)
    a = nd.random.uniform(shape=(100,))
    mx.random.seed(42)
    b = nd.random.uniform(shape=(100,))
    np.testing.assert_allclose(a.asnumpy(), b.asnumpy())
    n = nd.random.normal(loc=5, scale=0.1, shape=(2000,))
    assert abs(n.asnumpy().mean() - 5) < 0.1


def test_basic_slice_is_write_through_view():
    """Basic axis-0 indexing aliases the parent (reference
    NDArray.__getitem__ via MXNDArraySlice/_at): writes through the view
    mutate the parent; advanced indexing still copies."""
    a = mx.nd.zeros((4, 5))
    s = a[1:3]
    s[:] = 9.0
    assert a.asnumpy()[1:3].sum() == 90
    row = a[0]
    row += 1
    assert a.asnumpy()[0].sum() == 5
    v = a[2]
    v[1] = 7.0
    assert a.asnumpy()[2, 1] == 7
    nested = a[1:3][0]
    nested[:] = 2.0
    assert a.asnumpy()[1].sum() == 10
    # advanced indexing copies (parity: the reference copies there too)
    idx = mx.nd.array(np.array([0, 2], np.float32))
    c = a[idx]
    c[:] = -1.0
    assert a.asnumpy()[0].sum() == 5
