"""IO tests (parity model: reference tests/python/unittest/test_io.py)."""
import os

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, recordio
from mxnet_tpu.base import MXNetError
from mxnet_tpu.io import (NDArrayIter, ResizeIter, PrefetchingIter,
                          ImageRecordIter, CSVIter)


def test_ndarray_iter_basic():
    data = np.arange(40).reshape(10, 4).astype(np.float32)
    label = np.arange(10).astype(np.float32)
    it = NDArrayIter(data, label, batch_size=4)
    batches = list(it)
    assert len(batches) == 3  # 10/4 padded
    assert batches[0].data[0].shape == (4, 4)
    assert batches[2].pad == 2
    it.reset()
    first = next(iter(it))
    np.testing.assert_allclose(first.data[0].asnumpy(), data[:4])


def test_ndarray_iter_discard_and_shuffle():
    data = np.arange(20).reshape(10, 2).astype(np.float32)
    it = NDArrayIter(data, np.zeros(10), batch_size=3,
                     last_batch_handle="discard")
    assert len(list(it)) == 3
    it2 = NDArrayIter(data, np.arange(10), batch_size=5, shuffle=True)
    b = next(iter(it2))
    # shuffled but data/label stay aligned
    d = b.data[0].asnumpy()
    lbl = b.label[0].asnumpy()
    np.testing.assert_allclose(d[:, 0] // 2, lbl)


def test_ndarray_iter_dict_input():
    it = NDArrayIter({"a": np.zeros((6, 2)), "b": np.ones((6, 3))},
                     batch_size=2)
    assert sorted(d.name for d in it.provide_data) == ["a", "b"]


def test_resize_iter():
    it = NDArrayIter(np.zeros((10, 2)), np.zeros(10), batch_size=2)
    r = ResizeIter(it, 8)
    assert len(list(r)) == 8


def test_prefetching_iter():
    it = NDArrayIter(np.arange(24).reshape(12, 2).astype(np.float32),
                     np.zeros(12), batch_size=4)
    p = PrefetchingIter(it)
    batches = list(p)
    assert len(batches) == 3
    p.reset()
    batches2 = list(p)
    assert len(batches2) == 3


def test_prefetching_iter_reset_survives_wedged_backing():
    """reset() must neither hang NOR proceed when the worker is
    blocked INSIDE backing.next() (stalled data source): a
    replacement worker would race the wedged one's in-flight next()
    on the shared backing iterator. It waits reset_join_timeout, then
    raises a diagnosable error; once the source unblocks (the worker
    exits via its closure-captured stop), reset() is re-entrant and
    the next epoch is a full clean pass."""
    import threading
    import time

    release = threading.Event()
    base = NDArrayIter(np.arange(24).reshape(12, 2).astype(np.float32),
                       np.zeros(12), batch_size=4)

    class Wedged:
        """First next() after arming blocks until released."""
        batch_size = 4

        def __init__(self):
            self.armed = False

        @property
        def provide_data(self):
            return base.provide_data

        @property
        def provide_label(self):
            return base.provide_label

        def reset(self):
            base.reset()

        def next(self):
            if self.armed:
                release.wait()
            return base.next()

    w = Wedged()
    p = PrefetchingIter([w], prefetch_depth=1)
    p.next()                      # worker running
    w.armed = True
    p.next()                      # steer the worker into a blocked next()
    time.sleep(0.05)
    w.armed = False               # after the wedge clears, stay clear
    p.reset_join_timeout = 0.3
    t0 = time.monotonic()
    with pytest.raises(MXNetError, match="blocked inside the backing"):
        p.reset()                 # bounded: raises, never hangs/races
    took = time.monotonic() - t0
    assert took < 3.0, took
    release.set()                 # source unblocks; worker sees ITS
    time.sleep(0.2)               # stop (set by the failed reset), dies
    p.reset()                     # re-entrant retry: clean this time
    assert len(list(p)) == 3      # full epoch, nothing stolen
    p.reset()
    assert len(list(p)) == 3


def test_recordio_roundtrip(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"world!!", b"x" * 100]
    for p in payloads:
        w.write(p)
    w.close()
    r = recordio.MXRecordIO(path, "r")
    got = []
    while True:
        s = r.read()
        if s is None:
            break
        got.append(bytes(s))
    assert got == payloads


def test_indexed_recordio(tmp_path):
    path = str(tmp_path / "t.rec")
    idx_path = str(tmp_path / "t.idx")
    w = recordio.MXIndexedRecordIO(idx_path, path, "w")
    for i in range(5):
        w.write_idx(i, b"rec%d" % i)
    w.close()
    r = recordio.MXIndexedRecordIO(idx_path, path, "r")
    assert bytes(r.read_idx(3)) == b"rec3"
    assert bytes(r.read_idx(0)) == b"rec0"


def test_pack_unpack():
    hdr = recordio.IRHeader(0, 2.5, 7, 0)
    s = recordio.pack(hdr, b"payload")
    h2, payload = recordio.unpack(s)
    assert h2.label == 2.5 and h2.id == 7
    assert bytes(payload) == b"payload"


def _write_image_rec(path, n=8, shape=(3, 8, 8)):
    w = recordio.MXRecordIO(path, "w")
    imgs = []
    for i in range(n):
        img = np.random.randint(0, 255, shape, dtype=np.uint8)
        imgs.append(img)
        w.write(recordio.pack(recordio.IRHeader(0, float(i % 4), i, 0),
                              img.tobytes()))
    w.close()
    return imgs


def test_image_record_iter(tmp_path):
    path = str(tmp_path / "imgs.rec")
    imgs = _write_image_rec(path)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8), batch_size=4)
    batch = it.next()
    assert batch.data[0].shape == (4, 3, 8, 8)
    np.testing.assert_allclose(batch.data[0].asnumpy()[0],
                               imgs[0].astype(np.float32))
    np.testing.assert_allclose(batch.label[0].asnumpy(), [0, 1, 2, 3])


def test_image_record_iter_native_normalisation(tmp_path):
    path = str(tmp_path / "imgs.rec")
    imgs = _write_image_rec(path, n=4)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8), batch_size=2,
                         mean_r=10.0, mean_g=20.0, mean_b=30.0, std_r=2.0,
                         std_g=2.0, std_b=2.0)
    batch = it.next()
    expect = (imgs[0].astype(np.float32)
              - np.array([10, 20, 30], np.float32).reshape(3, 1, 1)) / 2.0
    np.testing.assert_allclose(batch.data[0].asnumpy()[0], expect, rtol=1e-5)


def test_csv_iter(tmp_path):
    data_csv = str(tmp_path / "d.csv")
    label_csv = str(tmp_path / "l.csv")
    data = np.random.uniform(size=(10, 3)).astype(np.float32)
    labels = np.arange(10).astype(np.float32)
    np.savetxt(data_csv, data, delimiter=",")
    np.savetxt(label_csv, labels, delimiter=",")
    it = CSVIter(data_csv=data_csv, data_shape=(3,), label_csv=label_csv,
                 batch_size=5)
    b = next(iter(it))
    np.testing.assert_allclose(b.data[0].asnumpy(), data[:5], rtol=1e-5)
    np.testing.assert_allclose(b.label[0].asnumpy(), labels[:5])


def test_mnist_iter_from_idx_files(tmp_path):
    """Write idx-format files and read them back (MNISTIter parity)."""
    import gzip
    import struct
    img_path = str(tmp_path / "train-images-idx3-ubyte")
    lbl_path = str(tmp_path / "train-labels-idx1-ubyte")
    imgs = np.random.randint(0, 255, (20, 28, 28), dtype=np.uint8)
    lbls = np.random.randint(0, 10, 20).astype(np.uint8)
    with open(img_path, "wb") as f:
        f.write(struct.pack(">IIII", 2051, 20, 28, 28))
        f.write(imgs.tobytes())
    with open(lbl_path, "wb") as f:
        f.write(struct.pack(">II", 2049, 20))
        f.write(lbls.tobytes())
    from mxnet_tpu.io import MNISTIter
    it = MNISTIter(image=img_path, label=lbl_path, batch_size=5,
                   shuffle=False)
    b = next(iter(it))
    assert b.data[0].shape == (5, 1, 28, 28)
    np.testing.assert_allclose(b.label[0].asnumpy(), lbls[:5])


def test_iterator_num_parts_sharding():
    """num_parts/part_index shard the data per worker (parity: dmlc
    InputSplit through the reference iterators' kwargs)."""
    x = np.arange(24, dtype=np.float32).reshape(12, 2)
    y = np.arange(12, dtype=np.float32)
    full = mx.io.NDArrayIter(x, y, batch_size=2)
    p0 = mx.io.NDArrayIter(x, y, batch_size=2, num_parts=3, part_index=0)
    p1 = mx.io.NDArrayIter(x, y, batch_size=2, num_parts=3, part_index=1)
    assert p0.num_data == p1.num_data == 4
    seen = []
    for it in (p0, p1):
        for b in it:
            seen.extend(b.label[0].asnumpy().tolist())
    assert sorted(seen) == [0, 1, 3, 4, 6, 7, 9, 10]
    assert full.num_data == 12


def test_sustained_feed_probe_overlaps_decode_with_consumer():
    """The pipeline must DECODE WHILE THE CONSUMER RUNS (reference
    iter_image_recordio_2.cc decode-parallel design): a consumer paced
    at half of measured decode capacity is sustained, with wall-clock
    visibly under the serialized decode+consume sum. Runs the probe in
    a SUBPROCESS (the tools pattern — its module body pins
    jax_platforms=cpu, which must not leak into this session); timing
    thresholds are deliberately loose, this is a concurrency-property
    check, not a perf gate. tools/feed_probe.py is the deployment-
    facing version (point --target-img-s at bench.py's measured rate).
    Retried once: the capacity measurement and the paced phase run at
    different times, so a host-load spike between them can produce one
    spurious miss."""
    import json
    import subprocess
    import sys as _sys
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["MXNET_TPU_FORCE_CPU"] = "1"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [_sys.executable,
           os.path.join(repo, "tools", "feed_probe.py"),
           "--threads", "1", "--images", "96", "--size", "64x64",
           "--batch", "16", "--target-fraction", "0.5"]
    res = None
    for _ in range(2):
        p = subprocess.run(cmd, capture_output=True, text=True,
                           timeout=300, env=env)
        assert p.returncode == 0, p.stderr
        res = json.loads(p.stdout.strip().splitlines()[-1])
        if res["sustained"] and res["overlap_efficiency"] > 0.15:
            break
    assert res["sustained"], res
    assert res["overlap_efficiency"] > 0.15, res
    # core-sizing arithmetic is exactly ceil(target / per-core rate)
    import math
    assert res["cores_needed_for_target"] == int(
        math.ceil(res["target_img_s"] / res["per_core_img_s"])), res


def test_worker_decode_scaling_probe():
    """Process-based decode workers (the multi-core feed-scaling model,
    PERF.md): N workers on disjoint num_parts shards must cover every
    image exactly once and sustain, concurrently, a meaningful fraction
    of the single-process rate even when time-slicing one core (on N
    cores the same machinery multiplies instead). Subprocess for the
    same jax_platforms isolation as the probe above."""
    import json
    import subprocess
    import sys as _sys
    repo = os.path.join(os.path.dirname(__file__), "..")
    env = dict(os.environ)
    env["MXNET_TPU_FORCE_CPU"] = "1"
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    cmd = [_sys.executable,
           os.path.join(repo, "tools", "feed_probe.py"),
           "--workers", "2", "--images", "64", "--size", "64x64",
           "--batch", "16"]
    p = subprocess.run(cmd, capture_output=True, text=True,
                       timeout=600, env=env)
    assert p.returncode == 0, p.stderr
    res = json.loads(p.stdout.strip().splitlines()[-1])
    assert res["workers"] == 2 and len(res["per_worker_img_s"]) == 2, res
    assert res["shard_exact_cover"], res
    # loose: scheduler overhead on a loaded 1-core host can be large,
    # but the two workers' concurrent aggregate must not collapse
    assert res["scaling_efficiency_vs_single"] > 0.3, res


def test_native_im2rec_roundtrip(tmp_path):
    """The native C++ im2rec (src/im2rec.cc, parity: reference
    tools/im2rec.cc): packs a .lst of image files into .rec/.idx in the
    shared wire format, single- and multi-label rows, num_parts
    sharding — and the Python side reads every record back."""
    import subprocess
    repo = os.path.abspath(os.path.join(os.path.dirname(__file__), ".."))
    exe = os.path.join(repo, "tools", "im2rec")
    if not os.path.exists(exe):
        import pytest as _pytest
        _pytest.skip("native im2rec not built (run make)")
    from mxnet_tpu import recordio
    # three fake "images" (arbitrary bytes — im2rec streams encoded
    # bytes through untouched)
    blobs = [os.urandom(100 + 13 * i) for i in range(3)]
    for i, b in enumerate(blobs):
        (tmp_path / ("img%d.jpg" % i)).write_bytes(b)
    lst = tmp_path / "train.lst"
    lst.write_text(
        "0\t1.0\timg0.jpg\n"
        "1\t2.0\t3.0\timg1.jpg\n"       # multi-label row
        "2\t0.0\timg2.jpg\n")
    out = tmp_path / "train"
    p = subprocess.run([exe, str(lst), str(tmp_path), str(out)],
                      capture_output=True, text=True, timeout=120)
    assert p.returncode == 0, p.stdout + p.stderr
    rec = recordio.MXIndexedRecordIO(str(out) + ".idx", str(out) + ".rec",
                                     "r")
    hdr0, s0 = recordio.unpack(rec.read_idx(0))
    assert hdr0.label == 1.0 and s0 == blobs[0]
    hdr1, s1 = recordio.unpack(rec.read_idx(1))
    assert list(hdr1.label) == [2.0, 3.0] and s1 == blobs[1]
    hdr2, s2 = recordio.unpack(rec.read_idx(2))
    assert hdr2.label == 0.0 and s2 == blobs[2]

    # sharded packing covers disjoint rows
    for part in (0, 1):
        op = tmp_path / ("shard%d" % part)
        subprocess.run([exe, str(lst), str(tmp_path), str(op), "2",
                        str(part)], check=True, timeout=120)
    r0 = recordio.MXRecordIO(str(tmp_path / "shard0.rec"), "r")
    r1 = recordio.MXRecordIO(str(tmp_path / "shard1.rec"), "r")
    ids = []
    for r in (r0, r1):
        while True:
            buf = r.read()
            if buf is None:
                break
            ids.append(recordio.unpack(buf)[0].id)
    assert sorted(ids) == [0, 1, 2]


def _write_jpeg_rec(path, n=7, size=(12, 12), gray=False):
    import io as pyio
    from PIL import Image
    w = recordio.MXRecordIO(path, "w")
    for i in range(n):
        arr = np.random.randint(0, 255, size + ((1,) if gray else (3,)),
                                dtype=np.uint8)
        img = Image.fromarray(arr[:, :, 0] if gray else arr,
                              mode="L" if gray else "RGB")
        buf = pyio.BytesIO()
        img.save(buf, format="JPEG")
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.getvalue()))
    w.close()


def test_image_record_iter_jpeg_decode_and_round_batch(tmp_path):
    """Encoded payloads decode via PIL; round_batch wraps + reports pad."""
    path = str(tmp_path / "jpeg.rec")
    _write_jpeg_rec(path, n=7)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=4, rand_crop=True, rand_mirror=True)
    b0 = it.next()
    assert b0.data[0].shape == (4, 3, 8, 8) and b0.pad == 0
    b1 = it.next()   # 3 records left -> wraps 1, pad=1
    assert b1.data[0].shape == (4, 3, 8, 8) and b1.pad == 1
    np.testing.assert_allclose(b1.label[0].asnumpy(), [4, 5, 6, 0])
    import pytest as _pytest
    with _pytest.raises(StopIteration):
        it.next()
    # round_batch=False drops the partial tail instead
    it2 = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                          batch_size=4, rand_crop=True, round_batch=False)
    it2.next()
    with _pytest.raises(StopIteration):
        it2.next()


def test_image_record_iter_grayscale_jpeg(tmp_path):
    path = str(tmp_path / "gray.rec")
    _write_jpeg_rec(path, n=4, gray=True)
    it = ImageRecordIter(path_imgrec=path, data_shape=(1, 8, 8),
                         batch_size=4, mean_r=1.0, std_r=2.0)
    batch = it.next()
    assert batch.data[0].shape == (4, 1, 8, 8)


def test_image_record_iter_smaller_than_batch(tmp_path):
    path = str(tmp_path / "tiny.rec")
    _write_jpeg_rec(path, n=3)
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=8, rand_crop=True)
    batch = it.next()   # wraps repeatedly to fill, pad = 8-3 = 5
    assert batch.data[0].shape == (8, 3, 8, 8) and batch.pad == 5
    np.testing.assert_allclose(batch.label[0].asnumpy(),
                               [0, 1, 2, 0, 1, 2, 0, 1])


def test_image_record_iter_raw_payload_with_magic_prefix(tmp_path):
    """Raw pixels starting with a JPEG signature still raw-decode."""
    path = str(tmp_path / "trap.rec")
    w = recordio.MXRecordIO(path, "w")
    arr = np.random.randint(0, 255, (3, 8, 8), dtype=np.uint8)
    arr.flat[0], arr.flat[1] = 0xFF, 0xD8   # JPEG SOI magic
    w.write(recordio.pack(recordio.IRHeader(0, 5.0, 0, 0), arr.tobytes()))
    w.close()
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=1)
    batch = it.next()
    np.testing.assert_allclose(batch.data[0].asnumpy()[0],
                               arr.astype(np.float32))


def test_image_record_iter_jpeg_bypasses_native_loader(tmp_path):
    """Encoded payloads must never hit the native raw-pixel loader, even
    in its sweet spot (no augmentation, batch divides evenly)."""
    import io as pyio
    from PIL import Image
    path = str(tmp_path / "enc.rec")
    w = recordio.MXRecordIO(path, "w")
    arr = np.full((8, 8, 3), 200, np.uint8)
    for i in range(4):
        buf = pyio.BytesIO()
        Image.fromarray(arr).save(buf, format="JPEG", quality=95)
        w.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                              buf.getvalue()))
    w.close()
    it = ImageRecordIter(path_imgrec=path, data_shape=(3, 8, 8),
                         batch_size=4)
    assert it._native is None
    batch = it.next()
    # decoded pixels, not compressed bytes: a near-uniform 200 plane
    got = batch.data[0].asnumpy()
    assert abs(got.mean() - 200.0) < 5.0 and got.std() < 10.0
