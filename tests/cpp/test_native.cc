// Native-layer unit tests (parity model: reference tests/cpp/ —
// engine/threaded_engine_test.cc dependency-ordering semantics and
// storage/storage_test.cc pooling). Assert-based, no gtest dependency:
// build + run via `make testcpp`.
#include <atomic>
#include <cassert>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <vector>

extern "C" {
void* eng_create(int num_workers, int naive);
void eng_destroy(void* h);
int64_t eng_new_var(void* h);
void eng_delete_var(void* h, int64_t v);
void eng_push(void* h, void (*fn)(void*), void* arg, const int64_t* cvars,
              int n_c, const int64_t* mvars, int n_m, int priority);
void eng_wait_for_var(void* h, int64_t v);
void eng_wait_all(void* h);

void* sto_alloc(size_t nbytes);
void sto_free(void* buf, size_t nbytes);
void sto_direct_free(void* buf, size_t nbytes);
void sto_stats(size_t* allocated, size_t* pooled, size_t* peak);
void sto_release_all();
}

namespace {

struct Cell {
  std::atomic<long> value{0};
};

void increment(void* arg) {
  auto* c = static_cast<Cell*>(arg);
  // non-atomic read-modify-write: only safe if the engine serialises
  // writers on the same mutable var — which is exactly the contract
  long v = c->value.load(std::memory_order_relaxed);
  c->value.store(v + 1, std::memory_order_relaxed);
}

struct ReadCheck {
  Cell* cell;
  long expected;
  std::atomic<int>* failures;
};

void read_check(void* arg) {
  auto* rc = static_cast<ReadCheck*>(arg);
  if (rc->cell->value.load(std::memory_order_relaxed) != rc->expected)
    rc->failures->fetch_add(1);
}

void engine_write_serialisation() {
  void* eng = eng_create(4, 0);
  Cell cell;
  int64_t var = eng_new_var(eng);
  const int N = 2000;
  for (int i = 0; i < N; ++i)
    eng_push(eng, increment, &cell, nullptr, 0, &var, 1, 0);
  eng_wait_for_var(eng, var);
  assert(cell.value.load() == N && "writes on one var must serialise");
  eng_delete_var(eng, var);
  eng_destroy(eng);
  std::puts("ok engine_write_serialisation");
}

void engine_read_after_write() {
  void* eng = eng_create(4, 0);
  Cell cell;
  int64_t var = eng_new_var(eng);
  std::atomic<int> failures{0};
  std::vector<ReadCheck> checks(64);
  for (int round = 0; round < 64; ++round) {
    eng_push(eng, increment, &cell, nullptr, 0, &var, 1, 0);
    checks[round] = {&cell, static_cast<long>(round + 1), &failures};
    // reader lists var as const: must observe the preceding write
    eng_push(eng, read_check, &checks[round], &var, 1, nullptr, 0, 0);
  }
  eng_wait_all(eng);
  assert(failures.load() == 0 && "reader ran before its writer");
  eng_delete_var(eng, var);
  eng_destroy(eng);
  std::puts("ok engine_read_after_write");
}

void engine_naive_mode() {
  void* eng = eng_create(1, /*naive=*/1);
  Cell cell;
  int64_t var = eng_new_var(eng);
  for (int i = 0; i < 100; ++i)
    eng_push(eng, increment, &cell, nullptr, 0, &var, 1, 0);
  // naive mode executes synchronously: value is final without waiting
  assert(cell.value.load() == 100);
  eng_delete_var(eng, var);
  eng_destroy(eng);
  std::puts("ok engine_naive_mode");
}

void storage_pool_reuse() {
  sto_release_all();
  void* a = sto_alloc(1000);
  assert(a != nullptr);
  sto_free(a, 1000);
  void* b = sto_alloc(900);  // same size bucket: must come from the pool
  assert(b == a && "freed buffer should be reused for same-bucket alloc");
  size_t allocated = 0, pooled = 0, peak = 0;
  sto_stats(&allocated, &pooled, &peak);
  assert(peak >= allocated);
  sto_free(b, 900);
  sto_stats(&allocated, &pooled, &peak);
  assert(pooled > 0 && "freed buffer should park in the pool");
  sto_release_all();
  sto_stats(&allocated, &pooled, &peak);
  assert(pooled == 0 && "release_all must drop parked buffers");
  std::puts("ok storage_pool_reuse");
}

void storage_direct_free() {
  void* a = sto_alloc(4096);
  size_t pooled_before = 0;
  sto_stats(nullptr, &pooled_before, nullptr);
  sto_direct_free(a, 4096);
  size_t pooled_after = 0;
  sto_stats(nullptr, &pooled_after, nullptr);
  assert(pooled_after == pooled_before && "direct free bypasses the pool");
  std::puts("ok storage_direct_free");
}

}  // namespace

int main() {
  engine_write_serialisation();
  engine_read_after_write();
  engine_naive_mode();
  storage_pool_reuse();
  storage_direct_free();
  std::puts("ALL NATIVE TESTS PASSED");
  return 0;
}
