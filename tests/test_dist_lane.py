"""Tier-1 dist lane (ISSUE 12): real 2-process ``dist_sync`` on one box.

Runs ``tools/module_fit_probe.py --dist-smoke`` as a subprocess: two
workers wired through ``jax.distributed`` over localhost (gloo CPU
collectives) run the SAME fused donated-buffer train step over a
process-spanning dp mesh. The probe gates:

- leg A: zero ``kvstore_dist`` fallback events, replicas bit-equal
  across ranks, one fused collective step per batch;
- leg B: params equal to a single-process run at the same global batch
  (rtol=1e-5 — the cross-host psum reassociates the batch reduction);
- leg C (chaos): rank 1 killed deterministically by an injected
  ``kv_collective`` fault mid-epoch → rank 0 detects via worker
  liveness, re-meshes over the survivors, resumes from the last atomic
  checkpoint, finishes the run, and the flight postmortem names rank 1
  and the step it died on; every leg under a hard timeout (a hung
  worker is a failure, never a hung lane).

The artifact lands as ``$MXTPU_ARTIFACT_DIR/module_fit_dist_smoke.json``.
"""
import json
import os
import subprocess
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_dist_smoke_lane():
    art_dir = os.environ.get("MXTPU_ARTIFACT_DIR", "/tmp/mxtpu_artifacts")
    os.makedirs(art_dir, exist_ok=True)
    art = os.path.join(art_dir, "module_fit_dist_smoke.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    env.pop("MXNET_FAULTS", None)
    # the probe's own per-leg deadlines fire well inside this cap, so a
    # hang still reports as the probe's "worker hung" SystemExit
    proc = subprocess.run(
        [sys.executable, os.path.join(ROOT, "tools",
                                      "module_fit_probe.py"),
         "--dist-smoke", "--json-out", art],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=780, env=env, cwd=ROOT)
    assert proc.returncode == 0, proc.stdout[-3000:]
    with open(art) as f:
        out = json.loads(f.read())
    assert out["lane"] == "module_fit_dist_smoke"
    assert out["gates_passed"] is True
    # the headline properties, re-asserted from the artifact so a
    # regression shows the numbers, not just a nonzero exit
    assert out["fused"]["kvstore_dist_fallbacks"] == [0, 0]
    assert out["oracle_max_abs_diff"] <= 1e-4
    assert out["chaos"]["survivor"]["elastic"]["elastic.resumed"] == 1
    assert out["chaos"]["postmortem_extra"]["dead_ranks"] == [1]
    # the merged cluster view (ISSUE 18): fleet_view parsed both
    # ranks' artifacts from the shared flight dir, named the killed
    # rank, pinned the fleet-wide gate-wait blame and the
    # dist.straggler verdicts on it, and solved clock offsets from
    # matched gate crossings
    fleet = out["chaos"]["fleet"]
    assert fleet["n_ranks"] >= 2
    assert fleet["dead_ranks"] == [1]
    assert fleet["stragglers"][0]["rank"] == 1
    assert fleet["stragglers"][0]["straggler_events"] > 0
    assert fleet["clock"]["reference_rank"] == 0
    # the survivor's dead_worker dump carries the victim's own last
    # seconds, gathered from the shared dir at recovery time
    peers = out["chaos"]["postmortem_extra"]["peer_postmortems"]
    assert any(p["rank"] == 1 and p["reason"] == "worker_abort"
               for p in peers)
