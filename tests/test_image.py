"""Image pipeline suite — parity with reference tests/python/unittest/test_image.py."""
import io as _io

import numpy as np
import pytest

import mxnet_tpu as mx
from mxnet_tpu import image

PIL = pytest.importorskip("PIL.Image")


def _jpeg_bytes(h=32, w=48):
    # smooth gradient image: JPEG round-trips it near-losslessly (random
    # noise would not), so decode accuracy is checkable
    yy, xx = np.mgrid[0:h, 0:w]
    arr = np.stack([255.0 * yy / h, 255.0 * xx / w,
                    np.full((h, w), 128.0)], axis=2).astype(np.uint8)
    img = PIL.fromarray(arr)
    buf = _io.BytesIO()
    img.save(buf, format="JPEG", quality=95)
    return buf.getvalue(), arr


def test_imdecode():
    data, arr = _jpeg_bytes()
    out = image.imdecode(data)
    assert out.shape == (32, 48, 3)
    # JPEG is lossy; mean error stays small
    assert np.abs(out.asnumpy().astype(np.float32)
                  - arr.astype(np.float32)).mean() < 3


def test_imresize_and_resize_short():
    data, _ = _jpeg_bytes()
    img = image.imdecode(data)
    out = image.imresize(img, 16, 8)
    assert out.shape == (8, 16, 3)
    out = image.resize_short(img, 24)
    assert min(out.shape[:2]) == 24


def test_crops():
    data, _ = _jpeg_bytes()
    img = image.imdecode(data)
    out = image.fixed_crop(img, 4, 4, 20, 16)
    assert out.shape == (16, 20, 3)
    out, _ = image.center_crop(img, (20, 16))
    assert out.shape == (16, 20, 3)
    out, _ = image.random_crop(img, (20, 16))
    assert out.shape == (16, 20, 3)


def test_color_normalize():
    src = mx.nd.ones((4, 4, 3)) * 128.0
    mean = mx.nd.array([128.0, 128.0, 128.0])
    std = mx.nd.array([2.0, 2.0, 2.0])
    out = image.color_normalize(src, mean, std)
    np.testing.assert_allclose(out.asnumpy(), np.zeros((4, 4, 3)), atol=1e-5)


def test_augmenter_list():
    augs = image.CreateAugmenter(data_shape=(3, 24, 24), resize=26,
                                 rand_crop=True, rand_mirror=True,
                                 mean=True, std=True)
    data, _ = _jpeg_bytes(64, 64)
    img = image.imdecode(data).astype("float32")
    for aug in augs:
        img = aug(img)
    out = img.asnumpy() if hasattr(img, "asnumpy") else np.asarray(img)
    assert out.shape[:2] == (24, 24)


def test_imageiter_from_list(tmp_path):
    # write a tiny .rec via recordio + pack, then iterate
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "data.rec")
    idx_path = str(tmp_path / "data.idx")
    record = recordio.MXIndexedRecordIO(idx_path, rec_path, "w")
    for i in range(8):
        data, _ = _jpeg_bytes(40, 40)
        header = recordio.IRHeader(0, float(i % 2), i, 0)
        record.write_idx(i, recordio.pack(header, data))
    record.close()
    it = image.ImageIter(batch_size=4, data_shape=(3, 24, 24),
                         path_imgrec=rec_path, path_imgidx=idx_path)
    batch = next(iter([b for b in [next(it)]]))
    assert batch.data[0].shape == (4, 3, 24, 24)
    assert batch.label[0].shape == (4,)


def test_imageiter_threaded_decode_matches_serial(tmp_path):
    """preprocess_threads decode+augment (parity: the reference's
    multithreaded iter_image_recordio_2 pipeline) must produce the same
    batches as inline decode for deterministic augmenters."""
    import io as _io
    from PIL import Image
    from mxnet_tpu import recordio
    from mxnet_tpu.image import ImageIter

    rs = np.random.RandomState(0)
    rec_path = str(tmp_path / "t.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    for i in range(8):
        arr = rs.randint(0, 255, (40, 40, 3), np.uint8)
        buf = _io.BytesIO()
        Image.fromarray(arr).save(buf, format="PNG")  # lossless
        rec.write(recordio.pack(recordio.IRHeader(0, float(i), i, 0),
                                buf.getvalue()))
    rec.close()

    def collect(threads):
        it = ImageIter(batch_size=4, data_shape=(3, 32, 32),
                       path_imgrec=rec_path, preprocess_threads=threads)
        out = []
        for b in it:
            out.append((b.data[0].asnumpy().copy(),
                        b.label[0].asnumpy().copy()))
        return out

    serial = collect(0)
    threaded = collect(3)
    assert len(serial) == len(threaded) == 2
    for (d0, l0), (d1, l1) in zip(serial, threaded):
        np.testing.assert_allclose(d0, d1)
        np.testing.assert_allclose(l0, l1)


def test_copy_make_border():
    """copyMakeBorder (the opencv-plugin op role, plugin/opencv
    _cvcopyMakeBorder): constant fill and replicate modes."""
    img = mx.nd.array(np.arange(12, dtype=np.uint8).reshape(2, 2, 3))
    out = mx.image.copyMakeBorder(img, 1, 1, 2, 2, type=0, value=7)
    assert out.shape == (4, 6, 3)
    got = out.asnumpy()
    np.testing.assert_array_equal(got[0], np.full((6, 3), 7, np.uint8))
    np.testing.assert_array_equal(got[1:3, 2:4], img.asnumpy())
    rep = mx.image.copyMakeBorder(img, 1, 0, 0, 0, type=1)
    np.testing.assert_array_equal(rep.asnumpy()[0], img.asnumpy()[0])


def test_copy_make_border_modes_and_out():
    img = mx.nd.array(np.arange(12, dtype=np.uint8).reshape(2, 2, 3))
    a = img.asnumpy()
    # reflect / wrap / reflect_101 map to the numpy modes exactly
    for btype, mode in ((2, "symmetric"), (3, "wrap"), (4, "reflect")):
        got = mx.image.copyMakeBorder(img, 1, 1, 1, 1, type=btype)
        want = np.pad(a, ((1, 1), (1, 1), (0, 0)), mode=mode)
        np.testing.assert_array_equal(got.asnumpy(), want)
    # per-channel constant fill
    got = mx.image.copyMakeBorder(img, 1, 0, 0, 0, type=0,
                                  values=[1, 2, 3])
    np.testing.assert_array_equal(got.asnumpy()[0],
                                  np.tile([1, 2, 3], (2, 1)))
    # out= validates shape
    bad = mx.nd.zeros((2, 2, 3), dtype="uint8")
    with pytest.raises(mx.MXNetError):
        mx.image.copyMakeBorder(img, 1, 1, 1, 1, out=bad)
    ok = mx.nd.zeros((4, 4, 3), dtype="uint8")
    ret = mx.image.copyMakeBorder(img, 1, 1, 1, 1, out=ok)
    assert ret is ok and ok.asnumpy()[1, 1, 0] == 0
