"""2-bit gradient compression tests (parity model: reference
tests/nightly/dist_sync_kvstore.py:48-130 compressed push/pull section)."""
import numpy as np
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu.gradient_compression import (
    GradientCompression, quantize_2bit, dequantize_2bit, compressed_psum)


def test_quantize_roundtrip_values():
    thr = 0.5
    g = jnp.asarray([0.7, -0.9, 0.2, -0.1, 0.5, -0.5, 0.0, 3.0],
                    jnp.float32)
    res = jnp.zeros_like(g)
    packed, new_res = quantize_2bit(g, res, thr)
    deq = dequantize_2bit(packed, g.shape, thr)
    expect = np.array([0.5, -0.5, 0.0, 0.0, 0.5, -0.5, 0.0, 0.5])
    np.testing.assert_allclose(np.asarray(deq), expect)
    # residual holds exactly the quantisation error
    np.testing.assert_allclose(np.asarray(new_res),
                               np.asarray(g) - expect, rtol=1e-6)
    # 16 codes per word
    assert packed.dtype == jnp.uint32 and packed.shape == (1,)


def test_error_feedback_preserves_signal():
    """Summed dequantised pushes converge to the true sum over steps —
    the whole point of keeping the residual."""
    thr = 0.5
    rng = np.random.RandomState(0)
    g = jnp.asarray(rng.uniform(-0.2, 0.2, 64).astype(np.float32))
    res = jnp.zeros_like(g)
    total = jnp.zeros_like(g)
    steps = 50
    for _ in range(steps):
        packed, res = quantize_2bit(g, res, thr)
        total = total + dequantize_2bit(packed, g.shape, thr)
    # average transmitted value ~ true gradient
    np.testing.assert_allclose(np.asarray(total) / steps, np.asarray(g),
                               atol=thr / steps + 1e-5)


def test_non_multiple_of_16_sizes():
    thr = 0.25
    g = jnp.asarray(np.random.RandomState(1)
                    .normal(size=(3, 7)).astype(np.float32))
    packed, _ = quantize_2bit(g, jnp.zeros_like(g), thr)
    assert packed.shape == (2,)  # ceil(21/16)
    deq = dequantize_2bit(packed, g.shape, thr)
    assert deq.shape == g.shape
    assert set(np.unique(np.asarray(deq))) <= {0.0, thr, -thr}


def test_kvstore_compressed_push():
    kv = mx.kv.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init(3, mx.nd.zeros((4,)))
    shards = [mx.nd.array([0.9, -0.9, 0.1, 0.0]),
              mx.nd.array([0.6, 0.3, -0.7, 0.0])]
    kv.push(3, shards)
    out = mx.nd.zeros((4,))
    kv.pull(3, out=out)
    # each shard quantised independently then summed
    np.testing.assert_allclose(out.asnumpy(),
                               np.array([1.0, -0.5, -0.5, 0.0]))
    # residuals persist per (key, shard): second identical push sees
    # g+res, e.g. shard B elem1 0.3+0.3=0.6 now crosses the threshold
    kv.push(3, shards)
    kv.pull(3, out=out)
    np.testing.assert_allclose(out.asnumpy(),
                               np.array([1.0, 0.0, -0.5, 0.0]))


def test_compressed_psum_on_mesh():
    import jax
    from mxnet_tpu import parallel
    from jax.sharding import PartitionSpec as P
    mesh = parallel.make_mesh({"dp": 4})
    x = jnp.asarray(np.tile(np.array([0.9, -0.6, 0.1, 0.0],
                                     np.float32), (4, 1)))

    def body(xs):
        local = xs[0]
        res = jnp.zeros_like(local)
        s, new_res = compressed_psum(local, "dp", res, threshold=0.5)
        return s[None], new_res[None]

    fn = parallel.shard_map(body, mesh=mesh, in_specs=(P("dp", None),),
                            out_specs=(P("dp", None), P("dp", None)))
    s, res = fn(x)
    # every device contributed the same quantised value
    np.testing.assert_allclose(np.asarray(s)[0],
                               4 * np.array([0.5, -0.5, 0.0, 0.0]))
    np.testing.assert_allclose(np.asarray(res)[0],
                               np.array([0.4, -0.1, 0.1, 0.0]), rtol=1e-6)
