// Training-side C++ classes over the general C ABI: Optimizer (with
// registry), LRScheduler, EvalMetric, Initializer, DataIter/MXDataIter,
// KVStore.
//
// Parity: reference cpp-package/include/mxnet-cpp/{optimizer.h,
// lr_scheduler.h, metric.h, initializer.h, io.h, kvstore.h} — same
// class surfaces so a reference cpp-package training program ports
// line-for-line. Bodies are independent: fused optimizer steps dispatch
// the SAME registry update ops the Python optimizers use
// (ops/optimizer_ops.py: sgd_update, sgd_mom_update, adam_update,
// rmsprop_update, rmspropalex_update), so C++ and Python training take
// one compiled XLA path; AdaGrad/AdaDelta compose imperative ops like
// the reference's NDArray-arithmetic versions (optimizer.hpp).
//
// Link against mxnet_tpu/_lib/libmxtpu_c_api.so (tests/test_cpp_package.py
// compiles and trains through every class here).
#ifndef MXNET_CPP_TRAIN_HPP_
#define MXNET_CPP_TRAIN_HPP_

#include <algorithm>
#include <cmath>
#include <cstdint>
#include <cstdio>
#include <functional>
#include <map>
#include <memory>
#include <regex>
#include <sstream>
#include <string>
#include <tuple>
#include <utility>
#include <vector>

#include "mxnet_cpp.hpp"

extern "C" {
typedef void* KVStoreHandle;
typedef void* DataIterHandle;
typedef void* DataIterCreator;
typedef void (*MXKVStoreUpdater)(int, NDArrayHandle, NDArrayHandle, void*);
int MXKVStoreCreate(const char*, KVStoreHandle*);
int MXKVStoreFree(KVStoreHandle);
int MXKVStoreInit(KVStoreHandle, mx_uint, const int*, NDArrayHandle*);
int MXKVStorePush(KVStoreHandle, mx_uint, const int*, NDArrayHandle*, int);
int MXKVStorePull(KVStoreHandle, mx_uint, const int*, NDArrayHandle*, int);
int MXKVStoreSetUpdater(KVStoreHandle, MXKVStoreUpdater, void*);
int MXKVStoreGetType(KVStoreHandle, const char**);
int MXKVStoreGetRank(KVStoreHandle, int*);
int MXKVStoreGetGroupSize(KVStoreHandle, int*);
int MXKVStoreBarrier(KVStoreHandle);
int MXKVStoreRunServer(KVStoreHandle,
                       void (*)(int, const char*, void*), void*);
typedef void* ExecutorHandle;
int MXExecutorSetMonitorCallback(ExecutorHandle,
                                 void (*)(const char*, NDArrayHandle, void*),
                                 void*);
int MXListDataIters(mx_uint*, DataIterCreator**);
int MXDataIterGetIterInfo(DataIterCreator, const char**, const char**,
                          mx_uint*, const char***, const char***,
                          const char***);
int MXDataIterCreateIter(DataIterCreator, mx_uint, const char**,
                         const char**, DataIterHandle*);
int MXDataIterFree(DataIterHandle);
int MXDataIterNext(DataIterHandle, int*);
int MXDataIterBeforeFirst(DataIterHandle);
int MXDataIterGetData(DataIterHandle, NDArrayHandle*);
int MXDataIterGetLabel(DataIterHandle, NDArrayHandle*);
int MXDataIterGetPadNum(DataIterHandle, int*);
int MXDataIterGetIndex(DataIterHandle, uint64_t**, uint64_t*);
}

namespace mxnet {
namespace cpp {

// ---------------------------------------------------------------------------
// LR schedulers (reference lr_scheduler.h)
// ---------------------------------------------------------------------------

class LRScheduler {
 public:
  explicit LRScheduler(float base_lr = 0.01f) : base_lr_(base_lr) {}
  virtual ~LRScheduler() = default;
  void SetLR(float lr) { base_lr_ = lr; }
  virtual float GetLR(unsigned num_update) = 0;

 protected:
  float base_lr_;
};

class FactorScheduler : public LRScheduler {
 public:
  explicit FactorScheduler(int step, float factor = 1.0f,
                           float stop_factor_lr = 1e-8f)
      : step_(step), factor_(factor), stop_factor_lr_(stop_factor_lr) {}
  float GetLR(unsigned num_update) override {
    while (num_update > static_cast<unsigned>(count_ + step_)) {
      count_ += step_;
      base_lr_ = std::max(base_lr_ * factor_, stop_factor_lr_);
    }
    return base_lr_;
  }

 private:
  int count_ = 0;
  int step_;
  float factor_;
  float stop_factor_lr_;
};

// ---------------------------------------------------------------------------
// Optimizers (reference optimizer.h) — fused registry update ops
// ---------------------------------------------------------------------------

class Optimizer {
 public:
  explicit Optimizer(unsigned begin_num_update = 0)
      : begin_num_update_(begin_num_update),
        num_update_(begin_num_update) {
    params_["lr"] = "0.01";
    params_["wd"] = "0";
  }
  virtual ~Optimizer() = default;
  virtual std::string GetType() const = 0;

  template <typename T>
  Optimizer* SetParam(const std::string& name, const T& value) {
    std::ostringstream ss;
    ss << value;
    params_[name] = ss.str();
    return this;
  }
  Optimizer* SetLRScheduler(std::unique_ptr<LRScheduler> sched) {
    lr_scheduler_ = std::move(sched);
    lr_scheduler_->SetLR(std::stof(params_["lr"]));
    return this;
  }

  virtual void Update(int index, NDArray weight, NDArray grad) = 0;

  std::string Serialize() const {
    std::ostringstream ss;
    ss << "opt_type=" << GetType();
    for (const auto& kv : params_) ss << "\n" << kv.first << "=" << kv.second;
    return ss.str();
  }

 protected:
  unsigned UpdateCount_(int index) {
    auto it = count_.emplace(index, begin_num_update_).first;
    num_update_ = std::max(num_update_, ++it->second);
    return num_update_;
  }
  float GetLR_(int index) {
    if (lr_scheduler_) return lr_scheduler_->GetLR(num_update_);
    (void)index;
    return std::stof(params_.at("lr"));
  }
  float GetWD_(int index) {
    (void)index;
    return std::stof(params_.at("wd"));
  }
  // registry ops reject unknown kwargs, so forward only the keys the
  // caller actually set (each fused op's schema is a subset of these)
  std::map<std::string, std::string> UpdateParams_(int index) {
    std::map<std::string, std::string> p;
    p["lr"] = std::to_string(GetLR_(index));
    p["wd"] = std::to_string(GetWD_(index));
    for (const char* k : {"rescale_grad", "clip_gradient", "momentum",
                          "beta1", "beta2", "epsilon", "gamma1", "gamma2",
                          "rho"}) {
      auto it = params_.find(k);
      if (it != params_.end()) p[k] = it->second;
    }
    return p;
  }
  virtual void CreateState_(int index, NDArray weight) {
    (void)index;
    (void)weight;
  }
  static NDArray ZerosLike_(const NDArray& w) {
    std::vector<NDArray> out;
    Op("zeros_like").Invoke({w}, &out);
    NDArray::WaitAll();
    return out.at(0);
  }

  std::map<std::string, std::string> params_;
  std::map<int, unsigned> count_;
  unsigned begin_num_update_, num_update_;
  std::unique_ptr<LRScheduler> lr_scheduler_;
};

typedef std::function<Optimizer*()> OptimizerCreator;

class OptimizerRegistry {
 public:
  static Optimizer* Find(const std::string& name) {
    auto it = cmap().find(name);
    if (it == cmap().end())
      throw std::runtime_error("optimizer " + name + " not registered");
    return it->second();
  }
  static int __REGISTER__(const std::string& name, OptimizerCreator c) {
    cmap()[name] = std::move(c);
    return 0;
  }
  OptimizerRegistry() = delete;

 private:
  static std::map<std::string, OptimizerCreator>& cmap() {
    static std::map<std::string, OptimizerCreator> m;
    return m;
  }
};

#define MXNETCPP_REGISTER_OPTIMIZER(Name, OptimizerType)                  \
  static int __make_##OptimizerType##_##Name##__ =                        \
      ::mxnet::cpp::OptimizerRegistry::__REGISTER__(                      \
          #Name, []() { return new OptimizerType(); })

class SGDOptimizer : public Optimizer {
 public:
  explicit SGDOptimizer(unsigned begin_num_update = 0)
      : Optimizer(begin_num_update) {}
  std::string GetType() const override { return "sgd"; }
  void Update(int index, NDArray weight, NDArray grad) override {
    UpdateCount_(index);
    auto p = UpdateParams_(index);
    std::vector<NDArray> out{weight};
    bool mom = params_.count("momentum") &&
               std::stof(params_["momentum"]) != 0.0f;
    if (mom) {
      if (!states_.count(index)) states_[index] = ZerosLike_(weight);
      Op("sgd_mom_update").Invoke({weight, grad, states_[index]}, &out, p);
    } else {
      p.erase("momentum");
      Op("sgd_update").Invoke({weight, grad}, &out, p);
    }
  }

 private:
  std::map<int, NDArray> states_;
};

class AdamOptimizer : public Optimizer {
 public:
  explicit AdamOptimizer(unsigned begin_num_update = 0)
      : Optimizer(begin_num_update) {}
  std::string GetType() const override { return "adam"; }
  void Update(int index, NDArray weight, NDArray grad) override {
    unsigned t = UpdateCount_(index);
    auto p = UpdateParams_(index);
    // bias correction folds into the per-step lr, the reference
    // AdamOptimizer::Update scheme
    float b1 = params_.count("beta1") ? std::stof(params_["beta1"]) : 0.9f;
    float b2 = params_.count("beta2") ? std::stof(params_["beta2"]) : 0.999f;
    float lr = GetLR_(index) *
               std::sqrt(1.0f - std::pow(b2, static_cast<float>(t))) /
               (1.0f - std::pow(b1, static_cast<float>(t)));
    p["lr"] = std::to_string(lr);
    if (!mean_.count(index)) {
      mean_[index] = ZerosLike_(weight);
      var_[index] = ZerosLike_(weight);
    }
    std::vector<NDArray> out{weight};
    Op("adam_update").Invoke({weight, grad, mean_[index], var_[index]},
                             &out, p);
  }

 private:
  std::map<int, NDArray> mean_, var_;
};

class RMSPropOptimizer : public Optimizer {
 public:
  explicit RMSPropOptimizer(unsigned begin_num_update = 0)
      : Optimizer(begin_num_update) {
    params_["gamma1"] = "0.9";
    params_["gamma2"] = "0.9";
    params_["epsilon"] = "1e-8";
  }
  std::string GetType() const override { return "rmsprop"; }
  void Update(int index, NDArray weight, NDArray grad) override {
    UpdateCount_(index);
    auto p = UpdateParams_(index);
    if (!n_.count(index)) {
      n_[index] = ZerosLike_(weight);
      g_[index] = ZerosLike_(weight);
      delta_[index] = ZerosLike_(weight);
    }
    std::vector<NDArray> out{weight};
    // centered variant (the reference dispatches rmspropalex_update)
    Op("rmspropalex_update")
        .Invoke({weight, grad, n_[index], g_[index], delta_[index]}, &out, p);
  }

 private:
  std::map<int, NDArray> n_, g_, delta_;
};

class AdaGradOptimizer : public Optimizer {
 public:
  explicit AdaGradOptimizer(unsigned begin_num_update = 0)
      : Optimizer(begin_num_update) {
    params_["eps"] = "1e-7";
  }
  std::string GetType() const override { return "adagrad"; }
  // composed from imperative ops (no fused kernel in the reference
  // either — optimizer.hpp AdaGradOptimizer::Update is NDArray math):
  //   history += grad^2;  weight -= lr * grad / (sqrt(history) + eps)
  void Update(int index, NDArray weight, NDArray grad) override {
    UpdateCount_(index);
    float lr = GetLR_(index), wd = GetWD_(index);
    float eps = std::stof(params_["eps"]);
    if (!history_.count(index)) history_[index] = ZerosLike_(weight);
    NDArray& hist = history_[index];
    std::vector<NDArray> g2;
    Op("square").Invoke({grad}, &g2);
    std::vector<NDArray> hist_out{hist};
    Op("elemwise_add").Invoke({hist, g2.at(0)}, &hist_out);
    std::vector<NDArray> denom;
    Op("sqrt").Invoke({hist}, &denom);
    std::vector<NDArray> denom_eps;
    Op("_plus_scalar").Invoke({denom.at(0)}, &denom_eps,
                              {{"scalar", std::to_string(eps)}});
    std::vector<NDArray> step;
    Op("elemwise_div").Invoke({grad, denom_eps.at(0)}, &step);
    std::vector<NDArray> scaled;
    Op("_mul_scalar").Invoke({step.at(0)}, &scaled,
                             {{"scalar", std::to_string(-lr)}});
    if (wd != 0.0f) {
      std::vector<NDArray> decay;
      Op("_mul_scalar").Invoke({weight}, &decay,
                               {{"scalar", std::to_string(-lr * wd)}});
      std::vector<NDArray> s2{scaled.at(0)};
      Op("elemwise_add").Invoke({scaled.at(0), decay.at(0)}, &s2);
    }
    std::vector<NDArray> w_out{weight};
    Op("elemwise_add").Invoke({weight, scaled.at(0)}, &w_out);
  }

 private:
  std::map<int, NDArray> history_;
};

class AdaDeltaOptimizer : public Optimizer {
 public:
  explicit AdaDeltaOptimizer(unsigned begin_num_update = 0)
      : Optimizer(begin_num_update) {
    params_["rho"] = "0.90";
    params_["epsilon"] = "1e-5";
  }
  std::string GetType() const override { return "adadelta"; }
  // classic self-tuning rule (no lr factor, like the reference's):
  // acc_g = rho*acc_g + (1-rho)*g^2
  // step  = g * sqrt(acc_delta + eps) / sqrt(acc_g + eps)
  // acc_delta = rho*acc_delta + (1-rho)*step^2;  weight -= step
  void Update(int index, NDArray weight, NDArray grad) override {
    UpdateCount_(index);
    float rho = std::stof(params_["rho"]);
    float eps = std::stof(params_["epsilon"]);
    if (!acc_g_.count(index)) {
      acc_g_[index] = ZerosLike_(weight);
      acc_delta_[index] = ZerosLike_(weight);
    }
    NDArray &ag = acc_g_[index], &ad = acc_delta_[index];
    auto scal = [](const NDArray& a, float s) {
      std::vector<NDArray> o;
      Op("_mul_scalar").Invoke({a}, &o, {{"scalar", std::to_string(s)}});
      return o.at(0);
    };
    auto plus_scal = [](const NDArray& a, float s) {
      std::vector<NDArray> o;
      Op("_plus_scalar").Invoke({a}, &o, {{"scalar", std::to_string(s)}});
      return o.at(0);
    };
    auto unary = [](const char* name, const NDArray& a) {
      std::vector<NDArray> o;
      Op(name).Invoke({a}, &o);
      return o.at(0);
    };
    auto binary = [](const char* name, const NDArray& a, const NDArray& b) {
      std::vector<NDArray> o;
      Op(name).Invoke({a, b}, &o);
      return o.at(0);
    };
    std::vector<NDArray> ag_out{ag};
    Op("elemwise_add")
        .Invoke({scal(ag, rho), scal(unary("square", grad), 1.0f - rho)},
                &ag_out);
    NDArray step = binary(
        "elemwise_mul", grad,
        binary("elemwise_div", unary("sqrt", plus_scal(ad, eps)),
               unary("sqrt", plus_scal(ag, eps))));
    std::vector<NDArray> ad_out{ad};
    Op("elemwise_add")
        .Invoke({scal(ad, rho), scal(unary("square", step), 1.0f - rho)},
                &ad_out);
    std::vector<NDArray> w_out{weight};
    Op("elemwise_add").Invoke({weight, scal(step, -1.0f)}, &w_out);
  }

 private:
  std::map<int, NDArray> acc_g_, acc_delta_;
};

MXNETCPP_REGISTER_OPTIMIZER(sgd, SGDOptimizer);
MXNETCPP_REGISTER_OPTIMIZER(adam, AdamOptimizer);
MXNETCPP_REGISTER_OPTIMIZER(rmsprop, RMSPropOptimizer);
MXNETCPP_REGISTER_OPTIMIZER(adagrad, AdaGradOptimizer);
MXNETCPP_REGISTER_OPTIMIZER(adadelta, AdaDeltaOptimizer);

// ---------------------------------------------------------------------------
// Metrics (reference metric.h)
// ---------------------------------------------------------------------------

class EvalMetric {
 public:
  explicit EvalMetric(const std::string& name, int num = 0)
      : name(name), num(num) {}
  virtual ~EvalMetric() = default;
  virtual void Update(NDArray labels, NDArray preds) = 0;
  void Reset() {
    num_inst = 0;
    sum_metric = 0.0f;
  }
  float Get() const { return num_inst ? sum_metric / num_inst : 0.0f; }
  const std::string& GetName() const { return name; }

 protected:
  std::string name;
  int num;
  float sum_metric = 0.0f;
  int num_inst = 0;
};

class Accuracy : public EvalMetric {
 public:
  Accuracy() : EvalMetric("accuracy") {}
  void Update(NDArray labels, NDArray preds) override {
    NDArray pred_idx = preds.Shape().size() > 1 && preds.Shape()[1] > 1
                           ? preds.ArgmaxChannel()
                           : preds;
    NDArray::WaitAll();
    std::vector<float> p, l;
    pred_idx.SyncCopyToCPU(&p);
    labels.SyncCopyToCPU(&l);
    for (size_t i = 0; i < l.size(); ++i) {
      sum_metric += (p[i] == l[i]) ? 1.0f : 0.0f;
      ++num_inst;
    }
  }
};

class LogLoss : public EvalMetric {
 public:
  LogLoss() : EvalMetric("logloss") {}
  void Update(NDArray labels, NDArray preds) override {
    auto sh = preds.Shape();
    size_t n = sh[0], m = sh.size() > 1 ? sh[1] : 1;
    std::vector<float> p, l;
    preds.SyncCopyToCPU(&p, n * m);
    labels.SyncCopyToCPU(&l, n);
    for (size_t i = 0; i < n; ++i) {
      float q = p[i * m + static_cast<size_t>(l[i])];
      sum_metric += -std::log(std::max(q, 1e-15f));
      ++num_inst;
    }
  }
};

namespace detail {
// shared elementwise-residual reduce for the regression metrics
template <typename F>
inline std::pair<float, size_t> Residual(const NDArray& labels,
                                         const NDArray& preds, F f) {
  std::vector<float> p, l;
  preds.SyncCopyToCPU(&p);
  labels.SyncCopyToCPU(&l);
  float sum = 0;
  for (size_t i = 0; i < p.size(); ++i) sum += f(p[i] - l[i]);
  return {sum, p.size()};
}
}  // namespace detail

class MAE : public EvalMetric {
 public:
  MAE() : EvalMetric("mae") {}
  void Update(NDArray labels, NDArray preds) override {
    auto r = detail::Residual(labels, preds,
                              [](float d) { return std::abs(d); });
    sum_metric += r.first / r.second;
    ++num_inst;
  }
};

class MSE : public EvalMetric {
 public:
  MSE() : EvalMetric("mse") {}
  void Update(NDArray labels, NDArray preds) override {
    auto r = detail::Residual(labels, preds, [](float d) { return d * d; });
    sum_metric += r.first / r.second;
    ++num_inst;
  }
};

class RMSE : public EvalMetric {
 public:
  RMSE() : EvalMetric("rmse") {}
  void Update(NDArray labels, NDArray preds) override {
    auto r = detail::Residual(labels, preds, [](float d) { return d * d; });
    sum_metric += std::sqrt(r.first / r.second);
    ++num_inst;
  }
};

// ---------------------------------------------------------------------------
// Initializers (reference initializer.h) — name-routed, host-side fills
// ---------------------------------------------------------------------------

class Initializer {
 public:
  virtual ~Initializer() = default;
  static bool StringStartWith(const std::string& name,
                              const std::string& s) {
    return name.size() >= s.size() && name.compare(0, s.size(), s) == 0;
  }
  static bool StringEndWith(const std::string& name, const std::string& s) {
    return name.size() >= s.size() &&
           name.compare(name.size() - s.size(), s.size(), s) == 0;
  }
  virtual void operator()(const std::string& name, NDArray* arr) {
    if (StringEndWith(name, "bias") || StringEndWith(name, "beta") ||
        StringEndWith(name, "moving_mean") ||
        StringEndWith(name, "running_mean")) {
      Fill(arr, 0.0f);
    } else if (StringEndWith(name, "gamma") ||
               StringEndWith(name, "moving_var") ||
               StringEndWith(name, "running_var")) {
      Fill(arr, 1.0f);
    } else if (StringEndWith(name, "weight")) {
      InitWeight(arr);
    } else {
      InitDefault(arr);
    }
  }

 protected:
  virtual void InitWeight(NDArray* arr) { InitDefault(arr); }
  virtual void InitDefault(NDArray* arr) { (void)arr; }
  static void Fill(NDArray* arr, float v) {
    std::vector<float> buf(arr->Size(), v);
    arr->SyncCopyFromCPU(buf);
  }
  // deterministic host RNG (keeps examples reproducible without
  // threading a seed through the ABI)
  float NextUniform() {
    seed_ = seed_ * 1103515245u + 12345u;
    return static_cast<float>((seed_ >> 8) & 0xffffff) /
           static_cast<float>(0x1000000);
  }
  float NextGaussian() {
    float u1 = std::max(NextUniform(), 1e-7f), u2 = NextUniform();
    return std::sqrt(-2.0f * std::log(u1)) *
           std::cos(6.2831853f * u2);
  }
  unsigned seed_ = 12345u;
};

class Constant : public Initializer {
 public:
  explicit Constant(float value) : value_(value) {}
  void operator()(const std::string&, NDArray* arr) override {
    Fill(arr, value_);
  }

 private:
  float value_;
};

class Zero : public Constant {
 public:
  Zero() : Constant(0.0f) {}
};

class One : public Constant {
 public:
  One() : Constant(1.0f) {}
};

class Uniform : public Initializer {
 public:
  explicit Uniform(float scale) : Uniform(-scale, scale) {}
  Uniform(float begin, float end) : begin_(begin), end_(end) {}

 protected:
  void InitDefault(NDArray* arr) override {
    std::vector<float> buf(arr->Size());
    for (auto& v : buf) v = begin_ + (end_ - begin_) * NextUniform();
    arr->SyncCopyFromCPU(buf);
  }

 private:
  float begin_, end_;
};

class Normal : public Initializer {
 public:
  Normal(float mu, float sigma) : mu_(mu), sigma_(sigma) {}

 protected:
  void InitDefault(NDArray* arr) override {
    std::vector<float> buf(arr->Size());
    for (auto& v : buf) v = mu_ + sigma_ * NextGaussian();
    arr->SyncCopyFromCPU(buf);
  }

 private:
  float mu_, sigma_;
};

class Xavier : public Initializer {
 public:
  enum RandType { gaussian, uniform };
  enum FactorType { avg, in, out };
  explicit Xavier(RandType rand_type = gaussian,
                  FactorType factor_type = avg, float magnitude = 3.0f)
      : rand_type_(rand_type),
        factor_type_(factor_type),
        magnitude_(magnitude) {}

 protected:
  void InitWeight(NDArray* arr) override { InitDefault(arr); }
  void InitDefault(NDArray* arr) override {
    auto sh = arr->Shape();
    float hw = 1.0f;
    for (size_t i = 2; i < sh.size(); ++i) hw *= sh[i];
    float fan_out = sh.empty() ? 1.0f : sh[0] * hw;
    float fan_in = sh.size() < 2 ? 1.0f : sh[1] * hw;
    float factor = factor_type_ == avg ? (fan_in + fan_out) / 2.0f
                   : factor_type_ == in ? fan_in
                                        : fan_out;
    float scale = std::sqrt(magnitude_ / std::max(factor, 1.0f));
    std::vector<float> buf(arr->Size());
    for (auto& v : buf)
      v = rand_type_ == uniform ? (2.0f * NextUniform() - 1.0f) * scale
                                : NextGaussian() * scale;
    arr->SyncCopyFromCPU(buf);
  }

 private:
  RandType rand_type_;
  FactorType factor_type_;
  float magnitude_;
};

// ---------------------------------------------------------------------------
// Data iterators (reference io.h)
// ---------------------------------------------------------------------------

struct DataBatch {
  NDArray data;
  NDArray label;
  int pad_num;
  std::vector<int> index;
};

class DataIter {
 public:
  virtual ~DataIter() = default;
  virtual void BeforeFirst() = 0;
  virtual bool Next() = 0;
  virtual NDArray GetData() = 0;
  virtual NDArray GetLabel() = 0;
  virtual int GetPadNum() = 0;
  virtual std::vector<int> GetIndex() = 0;
  DataBatch GetDataBatch() {
    return DataBatch{GetData(), GetLabel(), GetPadNum(), GetIndex()};
  }
  void Reset() { BeforeFirst(); }
};

class MXDataIter : public DataIter {
 public:
  explicit MXDataIter(const std::string& type) : type_(type) {
    mx_uint n = 0;
    DataIterCreator* creators = nullptr;
    Check(MXListDataIters(&n, &creators), "ListDataIters");
    for (mx_uint i = 0; i < n; ++i) {
      const char *name, *desc;
      mx_uint argc;
      const char **argv, **types, **descs;
      Check(MXDataIterGetIterInfo(creators[i], &name, &desc, &argc, &argv,
                                  &types, &descs),
            "DataIterGetIterInfo");
      if (type == name) {
        creator_ = creators[i];
        return;
      }
    }
    throw std::runtime_error("data iter " + type + " not registered");
  }

  template <typename T>
  MXDataIter& SetParam(const std::string& name, const T& value) {
    std::ostringstream ss;
    ss << value;
    params_[name] = ss.str();
    return *this;
  }

  MXDataIter& CreateDataIter() {
    std::vector<const char*> keys, vals;
    for (auto& kv : params_) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    DataIterHandle h = nullptr;
    Check(MXDataIterCreateIter(creator_, static_cast<mx_uint>(keys.size()),
                               keys.data(), vals.data(), &h),
          "DataIterCreateIter");
    blob_ = std::shared_ptr<void>(h, [](void* p) {
      if (p != nullptr) MXDataIterFree(p);
    });
    return *this;
  }

  void BeforeFirst() override {
    EnsureCreated_();
    Check(MXDataIterBeforeFirst(blob_.get()), "DataIterBeforeFirst");
  }
  bool Next() override {
    EnsureCreated_();
    int out = 0;
    Check(MXDataIterNext(blob_.get(), &out), "DataIterNext");
    return out != 0;
  }
  NDArray GetData() override {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetData(blob_.get(), &h), "DataIterGetData");
    return NDArray(h);  // CallHandle hands out a new reference
  }
  NDArray GetLabel() override {
    NDArrayHandle h = nullptr;
    Check(MXDataIterGetLabel(blob_.get(), &h), "DataIterGetLabel");
    return NDArray(h);
  }
  int GetPadNum() override {
    int pad = 0;
    Check(MXDataIterGetPadNum(blob_.get(), &pad), "DataIterGetPadNum");
    return pad;
  }
  std::vector<int> GetIndex() override {
    uint64_t* idx = nullptr;
    uint64_t n = 0;
    Check(MXDataIterGetIndex(blob_.get(), &idx, &n), "DataIterGetIndex");
    return std::vector<int>(idx, idx + n);
  }

 private:
  void EnsureCreated_() {
    if (!blob_) CreateDataIter();
  }
  std::string type_;
  DataIterCreator creator_ = nullptr;
  std::map<std::string, std::string> params_;
  std::shared_ptr<void> blob_;
};

// ---------------------------------------------------------------------------
// Monitor (reference monitor.h) — per-output statistics via the
// executor monitor callback
// ---------------------------------------------------------------------------

inline NDArray _default_monitor_func(const NDArray& x) {
  // mean |x| — the reference's default statistic
  std::vector<NDArray> a, s;
  Op("abs").Invoke({x}, &a);
  Op("mean").Invoke({a.at(0)}, &s);
  return s.at(0);
}

class Monitor {
 public:
  typedef std::function<NDArray(const NDArray&)> StatFunc;
  typedef std::tuple<int, std::string, NDArray> Stat;

  explicit Monitor(int interval, std::regex pattern = std::regex(".*"),
                   StatFunc stat_func = _default_monitor_func)
      : interval(interval), pattern(std::move(pattern)),
        stat_func(std::move(stat_func)) {}

  void install(Executor* exe) {
    Check(MXExecutorSetMonitorCallback(exe->handle(),
                                       &Monitor::executor_callback, this),
          "SetMonitorCallback");
    exes.push_back(exe);
  }

  void tic() {
    if (step % interval == 0) {
      activated = true;
      stats.clear();
    }
  }

  std::vector<Stat> toc() {
    std::vector<Stat> out;
    if (activated) {
      activated = false;
      NDArray::WaitAll();
      out.swap(stats);
    }
    ++step;
    return out;
  }

  void toc_print() {
    for (auto& s : toc()) {
      std::vector<float> v;
      std::get<2>(s).SyncCopyToCPU(&v, 1);
      std::printf("Batch %d %s %.6f\n", std::get<0>(s),
                  std::get<1>(s).c_str(), v.at(0));
    }
  }

 protected:
  int interval;
  std::regex pattern;
  StatFunc stat_func;
  std::vector<Executor*> exes;
  int step = 0;
  bool activated = false;
  std::vector<Stat> stats;

  static void executor_callback(const char* name, NDArrayHandle handle,
                                void* monitor_ptr) {
    auto* m = static_cast<Monitor*>(monitor_ptr);
    // callback handles are new references (ABI contract) — owning wrap
    NDArray arr(handle);
    if (m->activated && std::regex_match(name, m->pattern)) {
      m->stats.emplace_back(m->step, name, m->stat_func(arr));
    }
  }
};

// ---------------------------------------------------------------------------
// KVStore (reference kvstore.h) — static singleton facade
// ---------------------------------------------------------------------------

class KVStore {
 public:
  static void SetType(const std::string& type) {
    if (get_handle() != nullptr)
      throw std::runtime_error("KVStore type must be set before first use");
    type_() = type;
  }
  static void Init(int key, const NDArray& val) {
    NDArrayHandle h = val.handle();
    Check(MXKVStoreInit(handle(), 1, &key, &h), "KVStoreInit");
  }
  static void Init(const std::vector<int>& keys,
                   const std::vector<NDArray>& vals) {
    std::vector<NDArrayHandle> hs;
    for (auto& v : vals) hs.push_back(v.handle());
    Check(MXKVStoreInit(handle(), static_cast<mx_uint>(keys.size()),
                        keys.data(), hs.data()),
          "KVStoreInit");
  }
  static void Push(int key, const NDArray& val, int priority = 0) {
    NDArrayHandle h = val.handle();
    Check(MXKVStorePush(handle(), 1, &key, &h, priority), "KVStorePush");
  }
  static void Push(const std::vector<int>& keys,
                   const std::vector<NDArray>& vals, int priority = 0) {
    std::vector<NDArrayHandle> hs;
    for (auto& v : vals) hs.push_back(v.handle());
    Check(MXKVStorePush(handle(), static_cast<mx_uint>(keys.size()),
                        keys.data(), hs.data(), priority),
          "KVStorePush");
  }
  static void Pull(int key, NDArray* out, int priority = 0) {
    NDArrayHandle h = out->handle();
    Check(MXKVStorePull(handle(), 1, &key, &h, priority), "KVStorePull");
  }
  static void Pull(const std::vector<int>& keys, std::vector<NDArray>* outs,
                   int priority = 0) {
    std::vector<NDArrayHandle> hs;
    for (auto& v : *outs) hs.push_back(v.handle());
    Check(MXKVStorePull(handle(), static_cast<mx_uint>(keys.size()),
                        keys.data(), hs.data(), priority),
          "KVStorePull");
  }
  // local=true applies updates worker-side with the given optimizer —
  // the only mode in the SPMD runtime (kvstore.py applies updates in the
  // compiled step; dist modes share the same updater discipline)
  static void SetOptimizer(std::unique_ptr<Optimizer> optimizer,
                           bool local = true) {
    (void)local;
    get_optimizer() = std::move(optimizer);
    Check(MXKVStoreSetUpdater(handle(), &KVStore::Updater, nullptr),
          "KVStoreSetUpdater");
  }
  static std::string GetType() {
    const char* t = nullptr;
    Check(MXKVStoreGetType(handle(), &t), "KVStoreGetType");
    return t != nullptr ? t : "";
  }
  static int GetRank() {
    int r = 0;
    Check(MXKVStoreGetRank(handle(), &r), "KVStoreGetRank");
    return r;
  }
  static int GetNumWorkers() {
    int n = 1;
    Check(MXKVStoreGetGroupSize(handle(), &n), "KVStoreGetGroupSize");
    return n;
  }
  static void Barrier() { Check(MXKVStoreBarrier(handle()), "KVStoreBarrier"); }

 private:
  KVStore() = delete;
  static std::string& type_() {
    static std::string t = "local";
    return t;
  }
  static KVStoreHandle& get_handle() {
    static KVStoreHandle h = nullptr;
    return h;
  }
  static KVStoreHandle handle() {
    KVStoreHandle& h = get_handle();
    if (h == nullptr)
      Check(MXKVStoreCreate(type_().c_str(), &h), "KVStoreCreate");
    return h;
  }
  static std::unique_ptr<Optimizer>& get_optimizer() {
    static std::unique_ptr<Optimizer> opt;
    return opt;
  }
  static void Updater(int key, NDArrayHandle grad, NDArrayHandle weight,
                      void*) {
    // callback handles are NEW references the callback must release
    // (MXKVStoreSetUpdater ownership contract) — the owning NDArray
    // wrappers free them on scope exit
    get_optimizer()->Update(key, NDArray(weight), NDArray(grad));
  }
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_TRAIN_HPP_
