// Header-only C++ training/inference API over the general C ABI.
//
// Parity: reference cpp-package/include/mxnet-cpp/*.hpp — RAII wrappers
// (NDArray/Symbol/Executor/Op) over the flat C API so C++ programs train
// on the same executor path as Python. The reference generated op
// wrappers from the registry; here Op::Invoke dispatches by registry
// name (MXListAllOpNames enumerates them), which keeps this header small
// and always in sync with the registry.
//
// Link against mxnet_tpu/_lib/libmxtpu_c_api.so (see tests/test_cpp_package.py
// for a full compile-and-train example).
#ifndef MXNET_CPP_HPP_
#define MXNET_CPP_HPP_

#include <cstddef>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

extern "C" {
typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* AtomicSymbolCreator;
const char* MXGetLastError();
int MXNDArrayCreateEx(const mx_uint*, mx_uint, int, int, int, int,
                      NDArrayHandle*);
int MXNDArrayFree(NDArrayHandle);
int MXNDArraySyncCopyFromCPU(NDArrayHandle, const void*, size_t);
int MXNDArraySyncCopyToCPU(NDArrayHandle, void*, size_t);
int MXNDArrayGetShape(NDArrayHandle, mx_uint*, const mx_uint**);
int MXNDArrayWaitAll();
int MXListAllOpNames(mx_uint*, const char***);
int NNGetOpHandle(const char*, AtomicSymbolCreator*);
int MXImperativeInvoke(AtomicSymbolCreator, int, NDArrayHandle*, int*,
                       NDArrayHandle**, int, const char**, const char**);
int MXSymbolCreateFromFile(const char*, SymbolHandle*);
int MXSymbolCreateFromJSON(const char*, SymbolHandle*);
int MXSymbolFree(SymbolHandle);
int MXSymbolListArguments(SymbolHandle, mx_uint*, const char***);
int MXSymbolListOutputs(SymbolHandle, mx_uint*, const char***);
int MXSymbolListAuxiliaryStates(SymbolHandle, mx_uint*, const char***);
int MXSymbolInferShape(SymbolHandle, mx_uint, const char**, const mx_uint*,
                       const mx_uint*, mx_uint*, const mx_uint**,
                       const mx_uint***, mx_uint*, const mx_uint**,
                       const mx_uint***, mx_uint*, const mx_uint**,
                       const mx_uint***, int*);
int MXSymbolCreateVariable(const char*, SymbolHandle*);
int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator, mx_uint, const char**,
                               const char**, SymbolHandle*);
int MXSymbolCompose(SymbolHandle, const char*, mx_uint, const char**,
                    SymbolHandle*);
int MXSymbolSaveToJSON(SymbolHandle, const char**);
int MXExecutorBind(SymbolHandle, int, int, mx_uint, NDArrayHandle*,
                   NDArrayHandle*, mx_uint*, mx_uint, NDArrayHandle*,
                   ExecutorHandle*);
int MXExecutorForward(ExecutorHandle, int);
int MXExecutorBackward(ExecutorHandle, mx_uint, NDArrayHandle*);
int MXExecutorOutputs(ExecutorHandle, mx_uint*, NDArrayHandle**);
int MXExecutorFree(ExecutorHandle);
}

namespace mxnet {
namespace cpp {

inline void Check(int rc, const char* what) {
  if (rc != 0) {
    throw std::runtime_error(std::string(what) + ": " + MXGetLastError());
  }
}

struct Context {
  int dev_type;
  int dev_id;
  static Context cpu(int id = 0) { return {1, id}; }
  static Context gpu(int id = 0) { return {2, id}; }  // maps to the TPU
  static Context tpu(int id = 0) { return {2, id}; }
};

class NDArray {
 public:
  NDArray() = default;
  NDArray(const std::vector<mx_uint>& shape, const Context& ctx,
          int dtype = 0) {
    NDArrayHandle h = nullptr;
    Check(MXNDArrayCreateEx(shape.data(),
                            static_cast<mx_uint>(shape.size()),
                            ctx.dev_type, ctx.dev_id, 0, dtype, &h),
          "NDArrayCreate");
    reset(h);
  }
  explicit NDArray(NDArrayHandle h) { reset(h); }

  // non-owning view over a handle whose lifetime someone else manages
  // (kvstore updater callbacks hand out borrowed handles)
  static NDArray Borrow(NDArrayHandle h) {
    NDArray a;
    a.h_ = std::make_shared<Owner>(h, false);
    return a;
  }

  NDArrayHandle handle() const { return h_ ? h_->ptr : nullptr; }

  void SyncCopyFromCPU(const float* data, size_t size) {
    Check(MXNDArraySyncCopyFromCPU(handle(), data, size), "CopyFromCPU");
  }
  void SyncCopyFromCPU(const std::vector<float>& data) {
    SyncCopyFromCPU(data.data(), data.size());
  }
  void SyncCopyToCPU(float* data, size_t size) const {
    Check(MXNDArraySyncCopyToCPU(handle(), data, size), "CopyToCPU");
  }
  void SyncCopyToCPU(std::vector<float>* data, size_t size = 0) const {
    if (size == 0) size = Size();
    data->resize(size);
    SyncCopyToCPU(data->data(), size);
  }
  std::vector<mx_uint> Shape() const {
    mx_uint ndim = 0;
    const mx_uint* pdata = nullptr;
    Check(MXNDArrayGetShape(handle(), &ndim, &pdata), "GetShape");
    return std::vector<mx_uint>(pdata, pdata + ndim);
  }
  // reference mxnet-cpp spelling of the same accessor
  std::vector<mx_uint> GetShape() const { return Shape(); }
  // argmax over axis 1 (the metric helper the reference NDArray carries);
  // defined after Op below
  inline NDArray ArgmaxChannel() const;
  size_t Size() const {
    size_t n = 1;
    for (auto s : Shape()) n *= s;
    return n;
  }
  static void WaitAll() { Check(MXNDArrayWaitAll(), "WaitAll"); }

 private:
  struct Owner {
    NDArrayHandle ptr;
    bool own;
    explicit Owner(NDArrayHandle p, bool o = true) : ptr(p), own(o) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    ~Owner() {
      if (own) MXNDArrayFree(ptr);
    }
  };
  std::shared_ptr<Owner> h_;
  // construct in place: a temporary Owner would free the handle in its
  // destructor the moment it is copied from
  void reset(NDArrayHandle h) { h_ = std::make_shared<Owner>(h); }
};

class Op {
 public:
  explicit Op(const std::string& name) {
    Check(NNGetOpHandle(name.c_str(), &op_), ("op " + name).c_str());
  }
  // Reference cpp-package Operator::Invoke contract: a non-empty
  // *outputs is the in-place form (e.g. sgd_update writing the weight);
  // an empty *outputs lets the op allocate, and the returned handles are
  // adopted into the caller's vector.
  void Invoke(std::vector<NDArray> inputs, std::vector<NDArray>* outputs,
              const std::map<std::string, std::string>& params = {}) const {
    std::vector<NDArrayHandle> in;
    for (auto& a : inputs) in.push_back(a.handle());
    std::vector<NDArrayHandle> out;
    for (auto& a : *outputs) out.push_back(a.handle());
    std::vector<const char*> keys, vals;
    for (auto& kv : params) {
      keys.push_back(kv.first.c_str());
      vals.push_back(kv.second.c_str());
    }
    int n_out = static_cast<int>(out.size());
    NDArrayHandle* out_ptr = out.empty() ? nullptr : out.data();
    Check(MXImperativeInvoke(op_, static_cast<int>(in.size()), in.data(),
                             &n_out, &out_ptr,
                             static_cast<int>(keys.size()), keys.data(),
                             vals.data()),
          "ImperativeInvoke");
    if (outputs->empty()) {   // allocate mode: adopt the new handles
      for (int i = 0; i < n_out; ++i) outputs->emplace_back(out_ptr[i]);
    }
  }

 private:
  AtomicSymbolCreator op_ = nullptr;
};

inline NDArray NDArray::ArgmaxChannel() const {
  std::vector<NDArray> out;
  Op("argmax_channel").Invoke({*this}, &out);
  return out.at(0);
}

class Symbol {
 public:
  Symbol() = default;
  static Symbol Load(const std::string& path) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromFile(path.c_str(), &h), "SymbolLoad");
    return Symbol(h);
  }
  static Symbol LoadJSON(const std::string& json) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateFromJSON(json.c_str(), &h), "SymbolLoadJSON");
    return Symbol(h);
  }
  static Symbol Variable(const std::string& name) {
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateVariable(name.c_str(), &h), "SymbolVariable");
    return Symbol(h);
  }
  // adopt an owned SymbolHandle (used by Operator::CreateSymbol)
  static Symbol FromHandle(SymbolHandle h) { return Symbol(h); }
  SymbolHandle handle() const { return h_ ? h_->ptr : nullptr; }

  std::string ToJSON() const {
    const char* js = nullptr;
    Check(MXSymbolSaveToJSON(handle(), &js), "SymbolToJSON");
    return std::string(js != nullptr ? js : "");
  }

  std::vector<std::string> ListArguments() const {
    return StrList(&MXSymbolListArguments);
  }
  std::vector<std::string> ListOutputs() const {
    return StrList(&MXSymbolListOutputs);
  }
  std::vector<std::string> ListAuxiliaryStates() const {
    return StrList(&MXSymbolListAuxiliaryStates);
  }

  // known: name -> shape; returns arg shapes in ListArguments() order
  std::vector<std::vector<mx_uint>> InferArgShapes(
      const std::map<std::string, std::vector<mx_uint>>& known) const {
    std::vector<const char*> keys;
    std::vector<mx_uint> indptr{0};
    std::vector<mx_uint> data;
    for (auto& kv : known) {
      keys.push_back(kv.first.c_str());
      for (auto v : kv.second) data.push_back(v);
      indptr.push_back(static_cast<mx_uint>(data.size()));
    }
    mx_uint in_n, out_n, aux_n;
    const mx_uint *in_nd, *out_nd, *aux_nd;
    const mx_uint **in_sh, **out_sh, **aux_sh;
    int complete = 0;
    Check(MXSymbolInferShape(handle(),
                             static_cast<mx_uint>(keys.size()), keys.data(),
                             indptr.data(), data.data(), &in_n, &in_nd,
                             &in_sh, &out_n, &out_nd, &out_sh, &aux_n,
                             &aux_nd, &aux_sh, &complete),
          "InferShape");
    if (!complete) throw std::runtime_error("InferShape incomplete");
    std::vector<std::vector<mx_uint>> shapes(in_n);
    for (mx_uint i = 0; i < in_n; ++i)
      shapes[i].assign(in_sh[i], in_sh[i] + in_nd[i]);
    return shapes;
  }

 private:
  explicit Symbol(SymbolHandle h) : h_(std::make_shared<Owner>(h)) {}
  struct Owner {
    SymbolHandle ptr;
    explicit Owner(SymbolHandle p) : ptr(p) {}
    Owner(const Owner&) = delete;
    Owner& operator=(const Owner&) = delete;
    ~Owner() { MXSymbolFree(ptr); }
  };
  std::shared_ptr<Owner> h_;

  template <typename Fn>
  std::vector<std::string> StrList(Fn fn) const {
    mx_uint n = 0;
    const char** arr = nullptr;
    Check(fn(handle(), &n, &arr), "SymbolList");
    std::vector<std::string> out;
    for (mx_uint i = 0; i < n; ++i) out.emplace_back(arr[i]);
    return out;
  }
};

// Symbol-graph composition builder (parity: reference mxnet-cpp
// Operator — the class every generated op wrapper in mxnet_cpp_ops.hpp
// drives): CreateAtomicSymbol with string params, then Compose with the
// named inputs.
class Operator {
 public:
  explicit Operator(const std::string& op_name) : op_name_(op_name) {}

  Operator& SetParam(const std::string& key, const std::string& value) {
    keys_.push_back(key);
    vals_.push_back(value);
    return *this;
  }
  Operator& SetParam(const std::string& key, const char* value) {
    return SetParam(key, std::string(value));
  }
  Operator& SetParam(const std::string& key, bool value) {
    return SetParam(key, std::string(value ? "True" : "False"));
  }
  Operator& SetParam(const std::string& key, int value) {
    return SetParam(key, std::to_string(value));
  }
  Operator& SetParam(const std::string& key, double value) {
    return SetParam(key, std::to_string(value));
  }
  Operator& SetInput(const std::string& name, const Symbol& s) {
    input_keys_.push_back(name);
    inputs_.push_back(s);
    return *this;
  }

  Symbol CreateSymbol(const std::string& name = "") {
    AtomicSymbolCreator op = nullptr;
    Check(NNGetOpHandle(op_name_.c_str(), &op),
          ("op " + op_name_).c_str());
    std::vector<const char*> ks, vs;
    for (auto& k : keys_) ks.push_back(k.c_str());
    for (auto& v : vals_) vs.push_back(v.c_str());
    SymbolHandle h = nullptr;
    Check(MXSymbolCreateAtomicSymbol(op,
                                     static_cast<mx_uint>(ks.size()),
                                     ks.data(), vs.data(), &h),
          "CreateAtomicSymbol");
    std::vector<const char*> ik;
    std::vector<SymbolHandle> ih;
    for (auto& k : input_keys_) ik.push_back(k.c_str());
    for (auto& s : inputs_) ih.push_back(s.handle());
    Check(MXSymbolCompose(h, name.c_str(),
                          static_cast<mx_uint>(ih.size()), ik.data(),
                          ih.data()),
          "SymbolCompose");
    return Symbol::FromHandle(h);
  }

 private:
  std::string op_name_;
  std::vector<std::string> keys_, vals_;
  std::vector<std::string> input_keys_;
  std::vector<Symbol> inputs_;
};

enum OpReqType { kNullOp = 0, kWriteTo = 1 };

class Executor {
 public:
  Executor(const Symbol& sym, const Context& ctx,
           const std::vector<NDArray>& args,
           const std::vector<NDArray>& arg_grads,   // empty handle = null
           const std::vector<mx_uint>& grad_reqs,
           const std::vector<NDArray>& aux = {}) {
    std::vector<NDArrayHandle> a, g, x;
    for (auto& v : args) a.push_back(v.handle());
    for (auto& v : arg_grads) g.push_back(v.handle());
    for (auto& v : aux) x.push_back(v.handle());
    std::vector<mx_uint> reqs = grad_reqs;
    Check(MXExecutorBind(sym.handle(), ctx.dev_type, ctx.dev_id,
                         static_cast<mx_uint>(a.size()), a.data(),
                         g.data(), reqs.data(),
                         static_cast<mx_uint>(x.size()),
                         x.empty() ? nullptr : x.data(), &h_),
          "ExecutorBind");
  }
  ~Executor() {
    if (h_ != nullptr) MXExecutorFree(h_);
  }
  Executor(const Executor&) = delete;
  Executor& operator=(const Executor&) = delete;

  ExecutorHandle handle() const { return h_; }

  void Forward(bool is_train) {
    Check(MXExecutorForward(h_, is_train ? 1 : 0), "Forward");
  }
  void Backward() {
    Check(MXExecutorBackward(h_, 0, nullptr), "Backward");
  }
  std::vector<NDArray> Outputs() const {
    mx_uint n = 0;
    NDArrayHandle* arr = nullptr;
    Check(MXExecutorOutputs(h_, &n, &arr), "Outputs");
    std::vector<NDArray> out;
    for (mx_uint i = 0; i < n; ++i) out.emplace_back(arr[i]);
    return out;
  }

 private:
  ExecutorHandle h_ = nullptr;
};

}  // namespace cpp
}  // namespace mxnet

#endif  // MXNET_CPP_HPP_
