// End-to-end C++ training over the FULL cpp-package training surface:
// MXDataIter(CSVIter) feeds batches, Xavier initialises, the optimizer
// comes from OptimizerRegistry with a FactorScheduler, updates flow
// through KVStore::SetOptimizer/Push/Pull, and Accuracy scores — the
// reference cpp-package example flow (example/mlp_cpu.cpp + io.h +
// kvstore.h + optimizer.h + metric.h + initializer.h) on the TPU
// runtime's C ABI.
//
// Stage 1 sanity-checks every registered optimizer on a tiny quadratic
// before the MLP trains, so a broken update rule fails loudly and
// early.
//
// Build/run: see tests/test_cpp_package.py::test_cpp_train_full_surface.
#include <cstdio>
#include <cstdlib>
#include <map>
#include <memory>
#include <string>
#include <vector>

#include "mxnet_cpp.hpp"
#include "mxnet_cpp_ops.hpp"
#include "mxnet_cpp_train.hpp"

using namespace mxnet::cpp;      // NOLINT
using namespace mxnet::cpp::op;  // NOLINT — generated op wrappers

static unsigned g_seed = 99;
static float frand() {
  g_seed = g_seed * 1103515245u + 12345u;
  return static_cast<float>((g_seed >> 8) & 0xffffff) /
         static_cast<float>(0x1000000);
}

static const int kBatch = 32;
static const int kDim = 64;

// synthetic separable task (same family as train_lenet.cpp): class 1
// iff the left half of the vector is brighter than the right half
static void WriteCSVs(const std::string& dir, int rows) {
  std::string xp = dir + "/x.csv", yp = dir + "/y.csv";
  FILE* fx = std::fopen(xp.c_str(), "w");
  FILE* fy = std::fopen(yp.c_str(), "w");
  for (int r = 0; r < rows; ++r) {
    int label = r % 2;
    for (int i = 0; i < kDim; ++i) {
      float base = frand() * 0.5f;
      if (label == 1 && i < kDim / 2) base += 0.8f;
      if (label == 0 && i >= kDim / 2) base += 0.8f;
      std::fprintf(fx, "%s%.5f", i ? "," : "", base);
    }
    std::fprintf(fx, "\n");
    std::fprintf(fy, "%d\n", label);
  }
  std::fclose(fx);
  std::fclose(fy);
}

// every registered optimizer must descend on f(w) = 0.5*w^2 (grad = w)
static bool OptimizerSanity() {
  const char* names[] = {"sgd", "adam", "rmsprop", "adagrad", "adadelta"};
  for (const char* name : names) {
    std::unique_ptr<Optimizer> opt(OptimizerRegistry::Find(name));
    opt->SetParam("lr", 0.1);
    NDArray w({4}, Context::cpu());
    std::vector<float> init(4, 1.0f);
    w.SyncCopyFromCPU(init);
    std::vector<float> host;
    // 300 steps: enough for AdaDelta's self-tuning step size to ramp
    for (int step = 0; step < 300; ++step) {
      NDArray grad({4}, Context::cpu());
      w.SyncCopyToCPU(&host);
      grad.SyncCopyFromCPU(host);  // grad of 0.5*w^2 is w
      opt->Update(0, w, grad);
    }
    NDArray::WaitAll();
    w.SyncCopyToCPU(&host);
    float v = std::abs(host[0]);
    std::printf("optimizer %s final |w|=%.4f\n", name, v);
    if (v > 0.5f) {
      std::printf("optimizer %s failed to descend\n", name);
      return false;
    }
  }
  return true;
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s scratch_dir\n", argv[0]);
    return 2;
  }
  if (!OptimizerSanity()) return 1;

  std::string dir = argv[1];
  WriteCSVs(dir, 512);

  // MLP composed from the registry-generated op wrappers
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol fc1 = FullyConnected("fc1", data, Symbol::Variable("fc1_weight"),
                              Symbol::Variable("fc1_bias"), true, false, 32);
  Symbol act = Activation("relu1", fc1, "relu");
  Symbol fc2 = FullyConnected("fc2", act, Symbol::Variable("fc2_weight"),
                              Symbol::Variable("fc2_bias"), true, false, 2);
  Symbol net = SoftmaxOutput("softmax", fc2, label, 1.0, -1.0, false,
                             "null", false, false, 0.0, false);

  auto ctx = Context::cpu();
  auto arg_names = net.ListArguments();
  auto shapes = net.InferArgShapes(
      {{"data", {kBatch, kDim}}, {"softmax_label", {kBatch}}});

  Xavier xavier(Xavier::gaussian, Xavier::avg, 2.0f);
  std::vector<NDArray> args, grads;
  std::vector<mx_uint> reqs;
  std::vector<int> param_keys;
  std::vector<NDArray> param_args, param_grads;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    NDArray a(shapes[i], ctx);
    bool is_param =
        arg_names[i] != "data" && arg_names[i] != "softmax_label";
    if (is_param) {
      xavier(arg_names[i], &a);
    } else {
      std::vector<float> buf(a.Size(), 0.0f);
      a.SyncCopyFromCPU(buf);
    }
    args.push_back(a);
    NDArray g(shapes[i], ctx);
    std::vector<float> gz(g.Size(), 0.0f);
    g.SyncCopyFromCPU(gz);
    grads.push_back(g);
    reqs.push_back(is_param ? kWriteTo : kNullOp);
    if (is_param) {
      param_keys.push_back(static_cast<int>(i));
      param_args.push_back(a);
      param_grads.push_back(g);
    }
  }

  // the kvstore owns the update rule: sgd + momentum + factor schedule
  std::unique_ptr<Optimizer> opt(OptimizerRegistry::Find("sgd"));
  opt->SetParam("lr", 0.1)->SetParam("momentum", 0.9)->SetParam("wd", 1e-4);
  opt->SetLRScheduler(std::unique_ptr<LRScheduler>(
      new FactorScheduler(100, 0.9f)));
  KVStore::SetOptimizer(std::move(opt));
  KVStore::Init(param_keys, param_args);
  std::printf("kvstore type=%s rank=%d workers=%d\n",
              KVStore::GetType().c_str(), KVStore::GetRank(),
              KVStore::GetNumWorkers());

  Executor exec(net, ctx, args, grads, reqs);
  Monitor monitor(200);  // per-output |x| stats every 200th batch
  monitor.install(&exec);

  MXDataIter train_iter("CSVIter");
  train_iter.SetParam("data_csv", dir + "/x.csv")
      .SetParam("data_shape", "(64,)")
      .SetParam("label_csv", dir + "/y.csv")
      .SetParam("batch_size", kBatch)
      .CreateDataIter();

  int data_idx = -1, label_idx = -1;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    if (arg_names[i] == "data") data_idx = static_cast<int>(i);
    if (arg_names[i] == "softmax_label") label_idx = static_cast<int>(i);
  }

  Accuracy acc;
  std::vector<float> host;
  for (int epoch = 0; epoch < 6; ++epoch) {
    train_iter.Reset();
    acc.Reset();
    while (train_iter.Next()) {
      DataBatch batch = train_iter.GetDataBatch();
      batch.data.SyncCopyToCPU(&host);
      args[data_idx].SyncCopyFromCPU(host);
      batch.label.SyncCopyToCPU(&host);
      args[label_idx].SyncCopyFromCPU(host);
      monitor.tic();
      exec.Forward(true);
      exec.Backward();
      monitor.toc_print();
      // gradients ride the kvstore; the optimizer applies them in the
      // updater and Pull hands the fresh weights back
      KVStore::Push(param_keys, param_grads);
      KVStore::Pull(param_keys, &param_args);
      acc.Update(args[label_idx], exec.Outputs()[0]);
    }
    std::printf("epoch %d acc=%.4f\n", epoch, acc.Get());
  }
  NDArray::WaitAll();
  if (acc.Get() < 0.85f) {
    std::printf("accuracy too low\n");
    return 1;
  }
  std::printf("CPP_TRAIN_FULL_OK acc=%.4f\n", acc.Get());
  return 0;
}
