// C++ LeNet training with the network DEFINED IN C++ via the generated
// per-op wrappers (mxnet_cpp_ops.hpp) — no symbol JSON involved.
// Parity: reference cpp-package/example/lenet.cpp, which composes the
// graph from generated op functions the same way.
//
// Build (from repo root, after `make`):
//   g++ -std=c++17 -I cpp-package/include train_lenet_ops.cpp \
//       -L mxnet_tpu/_lib -lmxtpu_c_api -Wl,-rpath,mxnet_tpu/_lib
// Run:  PYTHONPATH=. MXNET_TPU_FORCE_CPU=1 ./a.out
#include <cstdio>
#include <map>
#include <string>
#include <vector>

#include "mxnet_cpp_ops.hpp"

using mxnet::cpp::Context;
using mxnet::cpp::Executor;
using mxnet::cpp::NDArray;
using mxnet::cpp::Op;
using mxnet::cpp::Symbol;
namespace op = mxnet::cpp::op;

static unsigned int g_seed = 7;
static float frand() {
  g_seed = g_seed * 1103515245u + 12345u;
  return static_cast<float>((g_seed >> 8) & 0xffffff) /
         static_cast<float>(0x1000000);
}

static const int kBatch = 32;

// synthetic separable task: class 1 iff left half brighter than right
static void MakeBatch(std::vector<float>* x, std::vector<float>* y) {
  x->resize(kBatch * 64);
  y->resize(kBatch);
  for (int b = 0; b < kBatch; ++b) {
    int label = b % 2;
    for (int i = 0; i < 64; ++i) {
      int col = i % 8;
      float base = frand() * 0.5f;
      if (label == 1 && col < 4) base += 0.8f;
      if (label == 0 && col >= 4) base += 0.8f;
      (*x)[b * 64 + i] = base;
    }
    (*y)[b] = static_cast<float>(label);
  }
}

static Symbol BuildLeNet() {
  Symbol data = Symbol::Variable("data");
  Symbol label = Symbol::Variable("softmax_label");
  Symbol conv1 = op::Convolution(
      "conv1", data, Symbol(), Symbol(), /*cudnn_off=*/false,
      /*cudnn_tune=*/"None", /*dilate=*/"(1, 1)", /*kernel=*/"(3, 3)",
      /*layout=*/"None", /*no_bias=*/false, /*num_filter=*/8,
      /*num_group=*/1, /*pad=*/"(1, 1)", /*stride=*/"(1, 1)");
  Symbol act1 = op::Activation("relu1", conv1, "relu");
  Symbol pool1 = op::Pooling(
      "pool1", act1, /*cudnn_off=*/false, /*global_pool=*/false,
      /*kernel=*/"(2, 2)", /*layout=*/"None", /*pad=*/"(0, 0)",
      /*pool_type=*/"max", /*pooling_convention=*/"valid",
      /*stride=*/"(2, 2)");
  Symbol flat = op::Flatten("flat", pool1);
  Symbol fc1 = op::FullyConnected("fc1", flat, Symbol(), Symbol(),
                                  /*flatten=*/true, /*no_bias=*/false,
                                  /*num_hidden=*/16);
  Symbol act2 = op::Activation("relu2", fc1, "relu");
  Symbol fc2 = op::FullyConnected("fc2", act2, Symbol(), Symbol(),
                                  /*flatten=*/true, /*no_bias=*/false,
                                  /*num_hidden=*/2);
  return op::SoftmaxOutput("softmax", fc2, label,
                           /*grad_scale=*/1.0, /*ignore_label=*/-1.0,
                           /*multi_output=*/false,
                           /*normalization=*/"batch");
}

int main() {
  Symbol net = BuildLeNet();

  auto arg_names = net.ListArguments();
  auto shapes = net.InferArgShapes(
      {{"data", {kBatch, 1, 8, 8}}, {"softmax_label", {kBatch}}});

  Context ctx = Context::cpu();
  std::vector<NDArray> args, grads;
  std::vector<mx_uint> reqs;
  int data_idx = -1, label_idx = -1;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    args.emplace_back(shapes[i], ctx);
    bool is_input = arg_names[i] == "data" ||
                    arg_names[i] == "softmax_label";
    if (arg_names[i] == "data") data_idx = static_cast<int>(i);
    if (arg_names[i] == "softmax_label") label_idx = static_cast<int>(i);
    if (is_input) {
      grads.emplace_back();  // null grad
      reqs.push_back(mxnet::cpp::kNullOp);
    } else {
      grads.emplace_back(shapes[i], ctx);
      reqs.push_back(mxnet::cpp::kWriteTo);
      size_t n = args[i].Size();
      std::vector<float> w(n);
      for (auto& v : w) v = (frand() - 0.5f) * 0.35f;
      args[i].SyncCopyFromCPU(w.data(), n);
    }
  }
  if (data_idx < 0 || label_idx < 0) {
    std::printf("FAIL input names\n");
    return 1;
  }

  Executor exec(net, ctx, args, grads, reqs);
  Op sgd("sgd_update");
  std::map<std::string, std::string> sgd_params{{"lr", "0.2"}};

  std::vector<float> x, y;
  for (int step = 0; step < 60; ++step) {
    MakeBatch(&x, &y);
    args[data_idx].SyncCopyFromCPU(x.data(), x.size());
    args[label_idx].SyncCopyFromCPU(y.data(), y.size());
    exec.Forward(true);
    exec.Backward();
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] == mxnet::cpp::kNullOp) continue;
      std::vector<NDArray> out{args[i]};
      sgd.Invoke({args[i], grads[i]}, &out, sgd_params);
    }
  }
  NDArray::WaitAll();

  MakeBatch(&x, &y);
  args[data_idx].SyncCopyFromCPU(x.data(), x.size());
  exec.Forward(false);
  auto outs = exec.Outputs();
  std::vector<float> prob(kBatch * 2);
  outs[0].SyncCopyToCPU(prob.data(), prob.size());
  int correct = 0;
  for (int b = 0; b < kBatch; ++b) {
    int pred = prob[b * 2 + 1] > prob[b * 2] ? 1 : 0;
    if (pred == static_cast<int>(y[b])) correct++;
  }
  std::printf("CPP_OPS_TRAIN_OK acc=%.4f\n",
              static_cast<float>(correct) / kBatch);
  return 0;
}
