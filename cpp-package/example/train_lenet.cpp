// C++ LeNet training example over the header-only API (parity:
// reference cpp-package/example/ — same flow: load symbol, infer
// shapes, init params, bind, train with sgd_update, evaluate).
//
// Build (from repo root, after `make`):
//   g++ -std=c++17 -I cpp-package/include train_lenet.cpp \
//       -L mxnet_tpu/_lib -lmxtpu_c_api -Wl,-rpath,mxnet_tpu/_lib
// Run:  PYTHONPATH=. MXNET_TPU_FORCE_CPU=1 ./a.out lenet-symbol.json
#include <cstdio>
#include <cstring>
#include <map>
#include <string>
#include <vector>

#include "mxnet_cpp.hpp"

using mxnet::cpp::Context;
using mxnet::cpp::Executor;
using mxnet::cpp::NDArray;
using mxnet::cpp::Op;
using mxnet::cpp::Symbol;

static unsigned int g_seed = 7;
static float frand() {
  g_seed = g_seed * 1103515245u + 12345u;
  return static_cast<float>((g_seed >> 8) & 0xffffff) /
         static_cast<float>(0x1000000);
}

static const int kBatch = 32;

// synthetic separable task: class 1 iff left half brighter than right
static void MakeBatch(std::vector<float>* x, std::vector<float>* y) {
  x->resize(kBatch * 64);
  y->resize(kBatch);
  for (int b = 0; b < kBatch; ++b) {
    int label = b % 2;
    for (int i = 0; i < 64; ++i) {
      int col = i % 8;
      float base = frand() * 0.5f;
      if (label == 1 && col < 4) base += 0.8f;
      if (label == 0 && col >= 4) base += 0.8f;
      (*x)[b * 64 + i] = base;
    }
    (*y)[b] = static_cast<float>(label);
  }
}

int main(int argc, char** argv) {
  if (argc < 2) {
    std::fprintf(stderr, "usage: %s lenet-symbol.json\n", argv[0]);
    return 2;
  }
  auto ctx = Context::cpu();
  Symbol net = Symbol::Load(argv[1]);
  auto arg_names = net.ListArguments();
  auto shapes = net.InferArgShapes(
      {{"data", {kBatch, 1, 8, 8}}, {"softmax_label", {kBatch}}});

  std::vector<NDArray> args, grads;
  std::vector<mx_uint> reqs;
  int data_idx = -1, label_idx = -1;
  for (size_t i = 0; i < arg_names.size(); ++i) {
    args.emplace_back(shapes[i], ctx);
    bool is_param = arg_names[i] != "data" &&
                    arg_names[i] != "softmax_label";
    if (arg_names[i] == "data") data_idx = static_cast<int>(i);
    if (arg_names[i] == "softmax_label") label_idx = static_cast<int>(i);
    reqs.push_back(is_param ? mxnet::cpp::kWriteTo : mxnet::cpp::kNullOp);
    if (is_param) {
      grads.emplace_back(shapes[i], ctx);
      size_t n = grads.back().Size();
      std::vector<float> w(n);
      for (auto& v : w) v = (frand() - 0.5f) * 0.35f;
      args.back().SyncCopyFromCPU(w.data(), n);
    } else {
      grads.emplace_back();  // null grad handle
    }
  }

  if (data_idx < 0 || label_idx < 0) {
    std::fprintf(stderr, "symbol must have data/softmax_label inputs\n");
    return 2;
  }

  Executor exec(net, ctx, args, grads, reqs);
  Op sgd("sgd_update");
  std::map<std::string, std::string> sgd_params{{"lr", "0.2"}};

  std::vector<float> x, y;
  for (int step = 0; step < 60; ++step) {
    MakeBatch(&x, &y);
    args[data_idx].SyncCopyFromCPU(x.data(), x.size());
    args[label_idx].SyncCopyFromCPU(y.data(), y.size());
    exec.Forward(true);
    exec.Backward();
    for (size_t i = 0; i < args.size(); ++i) {
      if (reqs[i] != mxnet::cpp::kWriteTo) continue;
      std::vector<NDArray> outs{args[i]};
      sgd.Invoke({args[i], grads[i]}, &outs, sgd_params);
    }
  }
  NDArray::WaitAll();

  MakeBatch(&x, &y);
  args[data_idx].SyncCopyFromCPU(x.data(), x.size());
  exec.Forward(false);
  auto outs = exec.Outputs();
  std::vector<float> prob(kBatch * 2);
  outs[0].SyncCopyToCPU(prob.data(), prob.size());
  int correct = 0;
  for (int b = 0; b < kBatch; ++b) {
    int pred = prob[b * 2 + 1] > prob[b * 2] ? 1 : 0;
    if (pred == static_cast<int>(y[b])) ++correct;
  }
  std::printf("CPP_TRAIN_OK acc=%.4f\n",
              static_cast<float>(correct) / kBatch);
  return 0;
}
