"""Standalone ctypes predictor over the amalgamated predict ABI.

Parity target: reference ``amalgamation/python/mxnet_predict.py`` — a
single-file, dependency-light (numpy + ctypes only, NO mxnet_tpu
import) client of the predict shared library, for deployments that ship
just ``libmxnet_predict.so`` and this file.

Library lookup order: ``MXNET_PREDICT_LIB`` env var, then
``libmxnet_predict.so`` next to this file's package, then the
framework's full build (``mxnet_tpu/_lib/libmxtpu_predict.so``).
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

__all__ = ["Predictor", "load_ndarray_file"]

_mx_uint = ctypes.c_uint
_float_p = ctypes.POINTER(ctypes.c_float)
_uint_p = ctypes.POINTER(_mx_uint)


def _find_lib():
    here = os.path.dirname(os.path.abspath(__file__))
    candidates = [
        os.environ.get("MXNET_PREDICT_LIB") or "",
        os.path.join(here, "..", "libmxnet_predict.so"),
        os.path.join(here, "..", "..", "mxnet_tpu", "_lib",
                     "libmxtpu_predict.so"),
    ]
    for c in candidates:
        if c and os.path.exists(c):
            return os.path.abspath(c)
    raise OSError("libmxnet_predict.so not found; set MXNET_PREDICT_LIB "
                  "or run `make` in amalgamation/")


_lib = None


def _load_lib():
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(_find_lib(), ctypes.RTLD_GLOBAL)
        _lib.MXGetLastError.restype = ctypes.c_char_p
    return _lib


def _check(rc):
    if rc != 0:
        raise RuntimeError(_load_lib().MXGetLastError().decode("utf-8",
                                                               "replace"))


def _c_strs(strings):
    arr = (ctypes.c_char_p * len(strings))()
    arr[:] = [s.encode("utf-8") for s in strings]
    return arr


_DEV = {"cpu": 1, "gpu": 2, "tpu": 2}  # accelerator rides dev_type 2


class Predictor:
    """Run inference from a symbol JSON + param blob, no framework import.

    Parameters
    ----------
    symbol_json : str — graph JSON text (pass file contents)
    param_raw_bytes : bytes — ``.params`` blob as saved by the framework
    input_shapes : dict of input name -> shape tuple
    dev_type, dev_id : device selection (default cpu)
    output_names : optional list of internal node names to expose as
        outputs (reference MXPredCreatePartialOut)
    """

    def __init__(self, symbol_json, param_raw_bytes, input_shapes,
                 dev_type="cpu", dev_id=0, output_names=None):
        lib = _load_lib()
        names = list(input_shapes.keys())
        indptr = [0]
        shape_data = []
        for name in names:
            shape_data.extend(int(d) for d in input_shapes[name])
            indptr.append(len(shape_data))
        handle = ctypes.c_void_p()
        dev = _DEV.get(dev_type, 1) if isinstance(dev_type, str) else dev_type
        args = [symbol_json.encode("utf-8"), param_raw_bytes,
                len(param_raw_bytes), dev, dev_id, len(names),
                _c_strs(names), (_mx_uint * len(indptr))(*indptr),
                (_mx_uint * len(shape_data))(*shape_data)]
        if output_names:
            _check(lib.MXPredCreatePartialOut(
                *args, len(output_names), _c_strs(output_names),
                ctypes.byref(handle)))
        else:
            _check(lib.MXPredCreate(*args, ctypes.byref(handle)))
        self.handle = handle
        self._shapes = {k: tuple(v) for k, v in input_shapes.items()}

    def __del__(self):
        if getattr(self, "handle", None):
            _load_lib().MXPredFree(self.handle)
            self.handle = None

    def forward(self, **kwargs):
        """Set named inputs (numpy arrays) and run the forward pass."""
        lib = _load_lib()
        for name, arr in kwargs.items():
            arr = np.ascontiguousarray(arr, np.float32)
            if name in self._shapes and arr.shape != self._shapes[name]:
                raise ValueError("input %r shape %s != bound %s"
                                 % (name, arr.shape, self._shapes[name]))
            _check(lib.MXPredSetInput(
                self.handle, name.encode("utf-8"),
                arr.ctypes.data_as(_float_p), arr.size))
        _check(lib.MXPredForward(self.handle))

    def get_output(self, index):
        """Fetch output ``index`` as a numpy array."""
        lib = _load_lib()
        sdata = _uint_p()
        ndim = _mx_uint()
        _check(lib.MXPredGetOutputShape(self.handle, index,
                                        ctypes.byref(sdata),
                                        ctypes.byref(ndim)))
        shape = tuple(sdata[i] for i in range(ndim.value))
        out = np.empty(shape, np.float32)
        _check(lib.MXPredGetOutput(self.handle, index,
                                   out.ctypes.data_as(_float_p), out.size))
        return out


def load_ndarray_file(nd_bytes):
    """Load a ``.params``/``nd.save`` blob into {name: numpy array}
    through the library (reference MXNDListCreate/Get/Free)."""
    lib = _load_lib()
    handle = ctypes.c_void_p()
    length = _mx_uint()
    _check(lib.MXNDListCreate(nd_bytes, len(nd_bytes),
                              ctypes.byref(handle), ctypes.byref(length)))
    out = {}
    try:
        for i in range(length.value):
            key = ctypes.c_char_p()
            data = _float_p()
            sdata = _uint_p()
            ndim = _mx_uint()
            _check(lib.MXNDListGet(handle, i, ctypes.byref(key),
                                   ctypes.byref(data), ctypes.byref(sdata),
                                   ctypes.byref(ndim)))
            shape = tuple(sdata[j] for j in range(ndim.value))
            n = int(np.prod(shape)) if shape else 1
            arr = np.array(data[:n], np.float32).reshape(shape)
            out[(key.value or b"").decode("utf-8")] = arr
    finally:
        lib.MXNDListFree(handle)
    return out
