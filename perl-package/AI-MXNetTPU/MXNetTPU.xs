/* XS glue: the mxnet_tpu C ABI -> Perl.
 *
 * Parity: reference perl-package/AI-MXNetCAPI (SWIG-generated wrapper
 * over include/mxnet/c_api.h) — this is the same idea, hand-rolled and
 * minimal: NDArray create/copy/shape/free, imperative op invoke, and
 * the full predict ABI. The high-level OO layer lives in
 * lib/AI/MXNetTPU.pm.
 */
#include "EXTERN.h"
#include "perl.h"
#include "XSUB.h"

typedef unsigned int mx_uint;
typedef float mx_float;
typedef void *NDArrayHandle;
typedef void *OpHandle;
typedef void *PredictorHandle;

extern const char *MXGetLastError(void);
extern int MXGetVersion(int *out);
extern int MXNDArrayCreateEx(const mx_uint *shape, mx_uint ndim,
                             int dev_type, int dev_id, int delay_alloc,
                             int dtype, NDArrayHandle *out);
extern int MXNDArrayFree(NDArrayHandle h);
extern int MXNDArraySyncCopyFromCPU(NDArrayHandle h, const void *data,
                                    size_t size);
extern int MXNDArraySyncCopyToCPU(NDArrayHandle h, void *data, size_t size);
extern int MXNDArrayGetShape(NDArrayHandle h, mx_uint *out_dim,
                             const mx_uint **out_pdata);
extern int NNGetOpHandle(const char *name, OpHandle *out);
extern int MXImperativeInvoke(OpHandle op, int num_inputs,
                              NDArrayHandle *inputs, int *num_outputs,
                              NDArrayHandle **outputs, int num_params,
                              const char **param_keys,
                              const char **param_vals);
extern int MXPredCreate(const char *symbol_json, const void *param_bytes,
                        int param_size, int dev_type, int dev_id,
                        mx_uint num_input, const char **input_keys,
                        const mx_uint *input_shape_indptr,
                        const mx_uint *input_shape_data,
                        PredictorHandle *out);
extern int MXPredSetInput(PredictorHandle h, const char *key,
                          const mx_float *data, mx_uint size);
extern int MXPredForward(PredictorHandle h);
extern int MXPredGetOutputShape(PredictorHandle h, mx_uint index,
                                mx_uint **shape_data, mx_uint *shape_ndim);
extern int MXPredGetOutput(PredictorHandle h, mx_uint index, mx_float *data,
                           mx_uint size);
extern int MXPredFree(PredictorHandle h);

static void croak_mx(const char *what) {
    croak("%s failed: %s", what, MXGetLastError());
}

MODULE = AI::MXNetTPU    PACKAGE = AI::MXNetTPU

PROTOTYPES: DISABLE

int
_version()
  CODE:
    {
        int v = 0;
        if (MXGetVersion(&v) != 0) croak_mx("MXGetVersion");
        RETVAL = v;
    }
  OUTPUT:
    RETVAL

IV
_nd_create(AV *shape_av, AV *data_av)
  CODE:
    {
        mx_uint ndim = (mx_uint)(av_len(shape_av) + 1);
        mx_uint shape[16];
        size_t total = 1, n, i;
        NDArrayHandle h = NULL;
        float *buf;
        if (ndim > 16) croak("ndim > 16");
        for (i = 0; i < ndim; ++i) {
            shape[i] = (mx_uint)SvUV(*av_fetch(shape_av, (I32)i, 0));
            total *= shape[i];
        }
        n = (size_t)(av_len(data_av) + 1);
        if (n != total) croak("data length %zu != shape product %zu",
                              n, total);
        if (MXNDArrayCreateEx(shape, ndim, 1, 0, 0, 0, &h) != 0)
            croak_mx("MXNDArrayCreateEx");
        Newx(buf, total, float);
        for (i = 0; i < total; ++i)
            buf[i] = (float)SvNV(*av_fetch(data_av, (I32)i, 0));
        if (MXNDArraySyncCopyFromCPU(h, buf, total) != 0) {
            Safefree(buf);
            MXNDArrayFree(h);
            croak_mx("MXNDArraySyncCopyFromCPU");
        }
        Safefree(buf);
        RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
_nd_free(IV h)
  CODE:
    MXNDArrayFree(INT2PTR(NDArrayHandle, h));

AV *
_nd_shape(IV h)
  CODE:
    {
        mx_uint ndim = 0, i;
        const mx_uint *pdata = NULL;
        if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim,
                              &pdata) != 0)
            croak_mx("MXNDArrayGetShape");
        RETVAL = newAV();
        sv_2mortal((SV *)RETVAL);
        for (i = 0; i < ndim; ++i)
            av_push(RETVAL, newSVuv(pdata[i]));
    }
  OUTPUT:
    RETVAL

AV *
_nd_to_list(IV h)
  CODE:
    {
        mx_uint ndim = 0, i;
        const mx_uint *pdata = NULL;
        size_t total = 1;
        float *buf;
        if (MXNDArrayGetShape(INT2PTR(NDArrayHandle, h), &ndim,
                              &pdata) != 0)
            croak_mx("MXNDArrayGetShape");
        for (i = 0; i < ndim; ++i) total *= pdata[i];
        Newx(buf, total, float);
        if (MXNDArraySyncCopyToCPU(INT2PTR(NDArrayHandle, h), buf,
                                   total) != 0) {
            Safefree(buf);
            croak_mx("MXNDArraySyncCopyToCPU");
        }
        RETVAL = newAV();
        sv_2mortal((SV *)RETVAL);
        for (i = 0; i < total; ++i)
            av_push(RETVAL, newSVnv(buf[i]));
        Safefree(buf);
    }
  OUTPUT:
    RETVAL

AV *
_op_invoke(const char *op_name, AV *in_av, AV *keys_av, AV *vals_av)
  CODE:
    {
        OpHandle op = NULL;
        NDArrayHandle ins[16];
        NDArrayHandle *outs = NULL;
        int n_in = (int)(av_len(in_av) + 1);
        int n_params = (int)(av_len(keys_av) + 1);
        const char *keys[32];
        const char *vals[32];
        int n_out = 0, i;
        /* op handles are interned per name: NNGetOpHandle allocates a
         * handle that lives forever, so cache it (one per distinct op)
         * instead of leaking one per invocation */
        static HV *op_cache = NULL;
        SV **cached;
        if (n_in > 16) croak("too many inputs");
        if (n_params > 32) croak("too many params");
        if (!op_cache) op_cache = newHV();
        cached = hv_fetch(op_cache, op_name, (I32)strlen(op_name), 0);
        if (cached) {
            op = INT2PTR(OpHandle, SvIV(*cached));
        } else {
            if (NNGetOpHandle(op_name, &op) != 0)
                croak_mx("NNGetOpHandle");
            (void)hv_store(op_cache, op_name, (I32)strlen(op_name),
                           newSViv(PTR2IV(op)), 0);
        }
        for (i = 0; i < n_in; ++i)
            ins[i] = INT2PTR(NDArrayHandle,
                             SvIV(*av_fetch(in_av, (I32)i, 0)));
        for (i = 0; i < n_params; ++i) {
            keys[i] = SvPV_nolen(*av_fetch(keys_av, (I32)i, 0));
            vals[i] = SvPV_nolen(*av_fetch(vals_av, (I32)i, 0));
        }
        if (MXImperativeInvoke(op, n_in, ins, &n_out, &outs, n_params,
                               n_params ? keys : NULL,
                               n_params ? vals : NULL) != 0)
            croak_mx("MXImperativeInvoke");
        RETVAL = newAV();
        sv_2mortal((SV *)RETVAL);
        for (i = 0; i < n_out; ++i)
            av_push(RETVAL, newSViv(PTR2IV(outs[i])));
    }
  OUTPUT:
    RETVAL

IV
_pred_create(SV *symbol_json, SV *param_bytes, AV *input_keys_av, AV *shapes_av)
  CODE:
    {
        STRLEN jlen, plen;
        const char *json = SvPV(symbol_json, jlen);
        const char *params = SvPV(param_bytes, plen);
        mx_uint num_input = (mx_uint)(av_len(input_keys_av) + 1);
        const char *keys[8];
        mx_uint indptr[9];
        mx_uint shape_data[64];
        mx_uint pos = 0, i, j;
        PredictorHandle h = NULL;
        if (num_input > 8) croak("too many inputs");
        indptr[0] = 0;
        for (i = 0; i < num_input; ++i) {
            AV *shape_av;
            I32 sdim;
            SV **slot = av_fetch(shapes_av, (I32)i, 0);
            keys[i] = SvPV_nolen(*av_fetch(input_keys_av, (I32)i, 0));
            if (!slot || !SvROK(*slot)) croak("shapes must be arrayrefs");
            shape_av = (AV *)SvRV(*slot);
            sdim = av_len(shape_av) + 1;
            if (sdim <= 0) croak("input %u has an empty shape", i);
            for (j = 0; j < (mx_uint)sdim; ++j) {
                if (pos >= 64) croak("shape data overflow");
                shape_data[pos++] =
                    (mx_uint)SvUV(*av_fetch(shape_av, (I32)j, 0));
            }
            indptr[i + 1] = pos;
        }
        if (MXPredCreate(json, params, (int)plen, 1, 0, num_input, keys,
                         indptr, shape_data, &h) != 0)
            croak_mx("MXPredCreate");
        RETVAL = PTR2IV(h);
    }
  OUTPUT:
    RETVAL

void
_pred_set_input(IV h, const char *key, AV *data_av)
  CODE:
    {
        size_t n = (size_t)(av_len(data_av) + 1), i;
        float *buf;
        Newx(buf, n, float);
        for (i = 0; i < n; ++i)
            buf[i] = (float)SvNV(*av_fetch(data_av, (I32)i, 0));
        if (MXPredSetInput(INT2PTR(PredictorHandle, h), key, buf,
                           (mx_uint)n) != 0) {
            Safefree(buf);
            croak_mx("MXPredSetInput");
        }
        Safefree(buf);
    }

void
_pred_forward(IV h)
  CODE:
    if (MXPredForward(INT2PTR(PredictorHandle, h)) != 0)
        croak_mx("MXPredForward");

AV *
_pred_get_output(IV h, unsigned int index)
  CODE:
    {
        mx_uint *shape_data = NULL;
        mx_uint ndim = 0, i;
        size_t total = 1;
        float *buf;
        if (MXPredGetOutputShape(INT2PTR(PredictorHandle, h), index,
                                 &shape_data, &ndim) != 0)
            croak_mx("MXPredGetOutputShape");
        for (i = 0; i < ndim; ++i) total *= shape_data[i];
        Newx(buf, total, float);
        if (MXPredGetOutput(INT2PTR(PredictorHandle, h), index, buf,
                            (mx_uint)total) != 0) {
            Safefree(buf);
            croak_mx("MXPredGetOutput");
        }
        RETVAL = newAV();
        sv_2mortal((SV *)RETVAL);
        for (i = 0; i < total; ++i)
            av_push(RETVAL, newSVnv(buf[i]));
        Safefree(buf);
    }
  OUTPUT:
    RETVAL

void
_pred_free(IV h)
  CODE:
    MXPredFree(INT2PTR(PredictorHandle, h));
