#!/usr/bin/perl
# NDArray + imperative-op + predictor round trip (parity model:
# reference perl-package/AI-MXNet/t/ test files).
use strict;
use warnings;
use FindBin;
use File::Spec;
use lib File::Spec->catdir($FindBin::Bin, '..', 'lib');
use lib File::Spec->catdir($FindBin::Bin, '..', 'blib', 'arch');
use Test::More tests => 8;

use_ok('AI::MXNetTPU');

ok(AI::MXNetTPU::version() >= 1200, 'MXGetVersion answers');

my $a = AI::MXNetTPU::NDArray->new([1, 2, 3, 4, 5, 6], [2, 3]);
is_deeply($a->shape, [2, 3], 'shape round trip');

my $b = AI::MXNetTPU::NDArray->new([10, 20, 30, 40, 50, 60], [2, 3]);
my $c = $a + $b;
is_deeply($c->aslist, [11, 22, 33, 44, 55, 66], 'elemwise_add');

my $d = $a * $b;
is_deeply($d->aslist, [10, 40, 90, 160, 250, 360], 'elemwise_mul');

my $e = $a->invoke('sum', axis => 1, keepdims => 0);
is_deeply($e->aslist, [6, 15], 'op with params (sum axis=1)');

# matmul: (2,3) x (3,2)
my $m = AI::MXNetTPU::NDArray->new([1, 0, 0, 1, 1, 1], [3, 2]);
my $prod = $a->dot($m);
is_deeply($prod->aslist, [4, 5, 10, 11], 'dot');

# predictor over a saved checkpoint (written by the python harness into
# $ENV{MXTPU_PERL_MODEL_PREFIX})
SKIP: {
    my $prefix = $ENV{MXTPU_PERL_MODEL_PREFIX};
    skip 'no model prefix provided', 1 unless $prefix;
    my $pred = AI::MXNetTPU::Predictor->new(
        symbol_file => "$prefix-symbol.json",
        param_file  => "$prefix-0000.params",
        inputs      => [['data', [1, 4]]]);
    $pred->set_input('data', [0.5, -0.25, 1.0, 2.0]);
    $pred->forward;
    my $probs = $pred->get_output(0);
    my $sum = 0; $sum += $_ for @$probs;
    ok(abs($sum - 1.0) < 1e-3, 'predictor softmax sums to 1');
}
