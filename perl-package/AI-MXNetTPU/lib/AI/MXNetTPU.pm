package AI::MXNetTPU;
# Minimal Perl binding over the mxnet_tpu C ABI.
#
# Parity: reference perl-package/AI-MXNet (high-level OO API over the
# SWIG AI-MXNetCAPI layer). This package keeps the same shape at small
# scale: AI::MXNetTPU::NDArray with operator overloading routed through
# MXImperativeInvoke, and AI::MXNetTPU::Predictor over the predict ABI
# for checkpoint inference. Build with build.pl (xsubpp + g++ against
# mxnet_tpu/_lib/libmxtpu_c_api.so).
use strict;
use warnings;
use DynaLoader;

our $VERSION = '0.01';
our @ISA = ('DynaLoader');

sub dl_load_flags { 0x01 }   # RTLD_GLOBAL for the embedded CPython

__PACKAGE__->bootstrap($VERSION);

sub version { return _version(); }

package AI::MXNetTPU::NDArray;
use strict;
use warnings;
use overload
    '+' => \&_add,
    '-' => \&_sub,
    '*' => \&_mul,
    '""' => \&_str;

sub new {
    my ($class, $data, $shape) = @_;
    my $h = AI::MXNetTPU::_nd_create($shape, $data);
    return bless { handle => $h, owned => 1 }, $class;
}

sub _wrap {
    my ($class, $h) = @_;
    return bless { handle => $h, owned => 1 }, $class;
}

sub shape    { my $s = AI::MXNetTPU::_nd_shape($_[0]{handle}); return $s; }
sub aslist   { return AI::MXNetTPU::_nd_to_list($_[0]{handle}); }

sub _invoke_all {
    my ($op, $ins, $keys, $vals) = @_;
    my $outs = AI::MXNetTPU::_op_invoke(
        $op, [map { $_->{handle} } @$ins], $keys, $vals);
    return map { AI::MXNetTPU::NDArray->_wrap($_) } @$outs;
}

sub _invoke1 {
    my ($op, @ins) = @_;
    my @out = _invoke_all($op, \@ins, [], []);
    die "$op returned " . scalar(@out) . " outputs, expected 1"
        unless @out == 1;
    return $out[0];
}

sub _add { return _invoke1('elemwise_add', $_[0], $_[1]); }
sub _sub {
    my ($a, $b, $swap) = @_;
    return $swap ? _invoke1('elemwise_sub', $b, $a)
                 : _invoke1('elemwise_sub', $a, $b);
}
sub _mul { return _invoke1('elemwise_mul', $_[0], $_[1]); }

sub dot  { return _invoke1('dot', $_[0], $_[1]); }
sub exp_ { return _invoke1('exp', $_[0]); }

sub invoke {
    # every output comes back wrapped (and so freed); scalar context
    # yields the first output, list context all of them
    my ($self, $op, %params) = @_;
    my @k = keys %params;
    my @v = map { "$params{$_}" } @k;
    my @out = _invoke_all($op, [$self], \@k, \@v);
    return wantarray ? @out : $out[0];
}

sub _str {
    my $self = shift;
    my $shape = $self->shape;
    return sprintf("<NDArray %s>", join('x', @$shape));
}

sub DESTROY {
    my $self = shift;
    AI::MXNetTPU::_nd_free($self->{handle})
        if $self->{owned} && $self->{handle};
}

package AI::MXNetTPU::Predictor;
use strict;
use warnings;

sub new {
    my ($class, %args) = @_;
    open(my $jf, '<', $args{symbol_file})
        or die "cannot open $args{symbol_file}: $!";
    local $/; my $json = <$jf>; close $jf;
    open(my $pf, '<:raw', $args{param_file})
        or die "cannot open $args{param_file}: $!";
    my $params = <$pf>; close $pf;
    my @keys   = map { $_->[0] } @{ $args{inputs} };
    my @shapes = map { $_->[1] } @{ $args{inputs} };
    my $h = AI::MXNetTPU::_pred_create($json, $params, \@keys, \@shapes);
    return bless { handle => $h }, $class;
}

sub set_input {
    my ($self, $key, $data) = @_;
    AI::MXNetTPU::_pred_set_input($self->{handle}, $key, $data);
}

sub forward { AI::MXNetTPU::_pred_forward($_[0]{handle}); }

sub get_output {
    my ($self, $index) = @_;
    return AI::MXNetTPU::_pred_get_output($self->{handle}, $index // 0);
}

sub DESTROY {
    my $self = shift;
    AI::MXNetTPU::_pred_free($self->{handle}) if $self->{handle};
}

1;
__END__

=head1 NAME

AI::MXNetTPU - Perl interface to the mxnet_tpu framework's C ABI

=head1 SYNOPSIS

    use AI::MXNetTPU;
    my $a = AI::MXNetTPU::NDArray->new([1, 2, 3, 4], [2, 2]);
    my $b = AI::MXNetTPU::NDArray->new([5, 6, 7, 8], [2, 2]);
    my $c = $a + $b;                 # MXImperativeInvoke('elemwise_add')
    print join(',', @{ $c->aslist }), "\n";

    my $pred = AI::MXNetTPU::Predictor->new(
        symbol_file => 'model-symbol.json',
        param_file  => 'model-0000.params',
        inputs      => [['data', [1, 3, 8, 8]]]);
    $pred->set_input('data', \@pixels);
    $pred->forward;
    my $probs = $pred->get_output(0);

=cut
