#!/usr/bin/perl
# Build AI::MXNetTPU: xsubpp -> C -> shared object next to the .pm.
#
# Usage: perl build.pl        (requires `make` to have produced
#                              mxnet_tpu/_lib/libmxtpu_c_api.so first)
use strict;
use warnings;
use Config;
use File::Spec;
use File::Path qw(make_path);
use FindBin;

my $root = File::Spec->rel2abs(File::Spec->catdir($FindBin::Bin,
                                                  '..', '..'));
my $lib_dir = File::Spec->catdir($root, 'mxnet_tpu', '_lib');
my $so = File::Spec->catfile($lib_dir, 'libmxtpu_c_api.so');
die "native library not built: $so (run `make` at the repo root)\n"
    unless -e $so;

my $xs = File::Spec->catfile($FindBin::Bin, 'MXNetTPU.xs');
my $c = File::Spec->catfile($FindBin::Bin, 'MXNetTPU.c');
my $auto = File::Spec->catdir($FindBin::Bin, 'blib', 'arch', 'auto',
                              'AI', 'MXNetTPU');
make_path($auto);
my $out = File::Spec->catfile($auto, "MXNetTPU.$Config{dlext}");

require ExtUtils::ParseXS;
my $typemap = $INC{'ExtUtils/ParseXS.pm'};
$typemap =~ s/ParseXS\.pm$/typemap/;
die "cannot locate xsubpp typemap\n" unless -e $typemap;

system("xsubpp", "-typemap", $typemap, "-output", $c, $xs) == 0
    or die "xsubpp failed\n";

require ExtUtils::Embed;
# ccopts() returns the flag string when called in non-void context
my $ccflags = ExtUtils::Embed::ccopts();
chomp $ccflags;
die "empty ccopts from ExtUtils::Embed\n" unless $ccflags;
my $cmd = "cc -shared -fPIC $ccflags -o '$out' '$c' " .
          "-L'$lib_dir' -lmxtpu_c_api -Wl,-rpath,'$lib_dir'";
print "$cmd\n";
system($cmd) == 0 or die "cc failed\n";
print "built $out\n";
