#include "embed_common.h"

#include <mutex>

thread_local std::string mxtpu_last_error;

PyGILState_STATE MXTPUEnsurePython() {
  // check-then-init must be synchronized: two threads making their first
  // API call concurrently would otherwise both run Py_InitializeEx
  // (undefined behaviour). call_once serialises exactly the init.
  static std::once_flag once;
  std::call_once(once, []() {
    if (!Py_IsInitialized()) {
      Py_InitializeEx(0);
      // Py_InitializeEx leaves the GIL held by this thread; release it
      // so PyGILState_Ensure below behaves uniformly.
      PyEval_SaveThread();
    }
  });
  return PyGILState_Ensure();
}

void MXTPUCaptureError() {
  PyObject *type = nullptr, *value = nullptr, *trace = nullptr;
  PyErr_Fetch(&type, &value, &trace);
  PyErr_NormalizeException(&type, &value, &trace);
  mxtpu_last_error = "unknown python error";
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c != nullptr) mxtpu_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(trace);
}

extern "C" const char* MXGetLastError() { return mxtpu_last_error.c_str(); }
