// Native im2rec: pack an image list into RecordIO (parity: reference
// tools/im2rec.cc — same .lst input, same IRHeader/record wire format
// as mxnet_tpu/recordio.py and src/recordio.cc).
//
// Divergence (documented): the reference decodes + optionally resizes/
// re-encodes through OpenCV; this environment has no native image
// codec, so the packer streams the ENCODED bytes through untouched
// (the reference's behaviour at resize=0, quality=default). Decode-time
// augmentation lives in the Python pipeline (mxnet_tpu/image).
//
// Usage:
//   im2rec <prefix.lst> <image-root> <out-prefix> [num_parts part_index]
//
// .lst format (reference im2rec.py): index \t label(s...) \t relpath
// Multi-label rows use the flag=len(labels) wire form with float32
// labels prepended to the payload.
//
// Writes out-prefix.rec and out-prefix.idx (tab-separated key\toffset).
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fstream>
#include <sstream>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

#pragma pack(push, 1)
struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};
#pragma pack(pop)

struct ListEntry {
  uint64_t index;
  std::vector<float> labels;
  std::string path;
};

bool ParseListLine(const std::string& line, ListEntry* e) {
  // index \t label... \t path  (path is the LAST field; labels between)
  std::vector<std::string> fields;
  std::stringstream ss(line);
  std::string f;
  while (std::getline(ss, f, '\t')) fields.push_back(f);
  if (fields.size() < 3) return false;
  e->index = std::strtoull(fields[0].c_str(), nullptr, 10);
  e->labels.clear();
  for (size_t i = 1; i + 1 < fields.size(); ++i)
    e->labels.push_back(std::strtof(fields[i].c_str(), nullptr));
  e->path = fields.back();
  return true;
}

class RecWriter {
 public:
  RecWriter(const std::string& rec_path, const std::string& idx_path)
      : rec_(std::fopen(rec_path.c_str(), "wb")),
        idx_(std::fopen(idx_path.c_str(), "w")) {}
  ~RecWriter() {
    if (rec_ != nullptr) std::fclose(rec_);
    if (idx_ != nullptr) std::fclose(idx_);
  }
  bool ok() const { return rec_ != nullptr && idx_ != nullptr; }

  bool Write(const ListEntry& e, const std::string& payload) {
    long pos = std::ftell(rec_);
    IRHeader hdr{};
    hdr.id = e.index;
    hdr.id2 = 0;
    std::string body;
    if (e.labels.size() == 1) {
      hdr.flag = 0;
      hdr.label = e.labels[0];
      body = payload;
    } else {  // multi-label: flag = count, labels prepended as float32
      hdr.flag = static_cast<uint32_t>(e.labels.size());
      hdr.label = 0.0f;
      body.assign(reinterpret_cast<const char*>(e.labels.data()),
                  e.labels.size() * sizeof(float));
      body += payload;
    }
    uint32_t len =
        static_cast<uint32_t>(sizeof(IRHeader) + body.size()) & kLenMask;
    if (std::fwrite(&kMagic, 4, 1, rec_) != 1) return false;
    if (std::fwrite(&len, 4, 1, rec_) != 1) return false;
    if (std::fwrite(&hdr, sizeof(IRHeader), 1, rec_) != 1) return false;
    if (!body.empty() &&
        std::fwrite(body.data(), body.size(), 1, rec_) != 1)
      return false;
    uint32_t pad = (4 - (sizeof(IRHeader) + body.size()) % 4) % 4;
    static const char zeros[4] = {0, 0, 0, 0};
    if (pad != 0 && std::fwrite(zeros, pad, 1, rec_) != 1) return false;
    std::fprintf(idx_, "%llu\t%ld\n",
                 static_cast<unsigned long long>(e.index), pos);
    return true;
  }

 private:
  FILE* rec_;
  FILE* idx_;
};

}  // namespace

int main(int argc, char** argv) {
  if (argc < 4) {
    std::fprintf(stderr,
                 "usage: %s <list.lst> <image-root> <out-prefix> "
                 "[num_parts part_index]\n",
                 argv[0]);
    return 2;
  }
  std::string lst = argv[1], root = argv[2], prefix = argv[3];
  if (argc == 5) {
    std::fprintf(stderr,
                 "im2rec: num_parts given without part_index\n");
    return 2;
  }
  int num_parts = argc > 5 ? std::atoi(argv[4]) : 1;
  int part_index = argc > 5 ? std::atoi(argv[5]) : 0;
  if (!root.empty() && root.back() != '/') root += '/';

  std::ifstream in(lst);
  if (!in) {
    std::fprintf(stderr, "im2rec: cannot open list %s\n", lst.c_str());
    return 1;
  }
  RecWriter w(prefix + ".rec", prefix + ".idx");
  if (!w.ok()) {
    std::fprintf(stderr, "im2rec: cannot open output %s.rec/.idx\n",
                 prefix.c_str());
    return 1;
  }
  std::string line;
  long row = 0, written = 0, missing = 0;
  while (std::getline(in, line)) {
    if (line.empty()) continue;
    long this_row = row++;
    if (num_parts > 1 && this_row % num_parts != part_index) continue;
    ListEntry e;
    if (!ParseListLine(line, &e)) {
      std::fprintf(stderr, "im2rec: bad list line %ld\n", this_row);
      continue;
    }
    std::ifstream img(root + e.path, std::ios::binary);
    if (!img) {
      std::fprintf(stderr, "im2rec: missing image %s\n",
                   (root + e.path).c_str());
      ++missing;
      continue;
    }
    std::ostringstream buf;
    buf << img.rdbuf();
    if (!w.Write(e, buf.str())) {
      std::fprintf(stderr, "im2rec: write failed at row %ld\n", this_row);
      return 1;
    }
    ++written;
  }
  std::printf("im2rec: wrote %ld records (%ld missing) -> %s.rec\n",
              written, missing, prefix.c_str());
  return missing == 0 ? 0 : 1;
}
