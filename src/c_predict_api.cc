// C predict API — standalone inference ABI.
//
// TPU-native re-design of the reference's predict-only C API
// (include/mxnet/c_predict_api.h, src/c_api/c_predict_api.cc, consumed
// by amalgamation/ mobile builds and example/image-classification/
// predict-cpp). The reference linked a full C++ inference engine; here
// the library EMBEDS CPython and drives the framework's own XLA
// executor through mxnet_tpu/c_predict.py — one inference stack, one
// ABI. Works both from a standalone C program (initializes the
// interpreter; set PYTHONPATH to the package) and from inside an
// existing Python process (uses PyGILState).
//
// Exported surface mirrors the reference's names and call shapes:
//   MXPredCreate, MXPredSetInput, MXPredForward, MXPredGetOutputShape,
//   MXPredGetOutput, MXPredFree, MXGetLastError.

#include <Python.h>

#include "embed_common.h"

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

typedef unsigned int mx_uint;
typedef void* PredictorHandle;

namespace {

struct Pred {
  PyObject* obj;                 // mxnet_tpu.predictor.Predictor
  std::vector<mx_uint> shape_buf;  // backing for MXPredGetOutputShape
};

// The embedded interpreter is never finalized: predictor handles may
// outlive any one call, and XLA client teardown at interpreter shutdown
// is not safe from an arbitrary unload point.
PyGILState_STATE EnsurePython() { return MXTPUEnsurePython(); }

PyObject* HelperModule() {
  static PyObject* mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxnet_tpu.c_predict");
  }
  return mod;
}

void CaptureError() { MXTPUCaptureError(); }

}  // namespace

extern "C" {

// MXGetLastError is exported by embed_common.cc

int MXPredCreatePartialOut(const char* symbol_json_str,
                           const void* param_bytes, int param_size,
                           int dev_type, int dev_id,
                           mx_uint num_input_nodes, const char** input_keys,
                           const mx_uint* input_shape_indptr,
                           const mx_uint* input_shape_data,
                           mx_uint num_output_nodes,
                           const char** output_keys, PredictorHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  int rc = -1;
  PyObject* mod = HelperModule();
  if (mod == nullptr) {
    CaptureError();
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* names = PyList_New(num_input_nodes);
  PyObject* shapes = PyList_New(num_input_nodes);
  for (mx_uint i = 0; i < num_input_nodes; ++i) {
    PyList_SetItem(names, i, PyUnicode_FromString(input_keys[i]));
    mx_uint lo = input_shape_indptr[i], hi = input_shape_indptr[i + 1];
    PyObject* shp = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(shp, j - lo, PyLong_FromLong(input_shape_data[j]));
    PyList_SetItem(shapes, i, shp);
  }
  PyObject* outputs = Py_None;
  Py_INCREF(Py_None);
  if (num_output_nodes > 0) {
    Py_DECREF(Py_None);
    outputs = PyList_New(num_output_nodes);
    for (mx_uint i = 0; i < num_output_nodes; ++i)
      PyList_SetItem(outputs, i, PyUnicode_FromString(output_keys[i]));
  }
  PyObject* params = PyBytes_FromStringAndSize(
      static_cast<const char*>(param_bytes), param_size);
  PyObject* pred = PyObject_CallMethod(
      mod, "create", "sOiiOOO", symbol_json_str, params, dev_type, dev_id,
      names, shapes, outputs);
  Py_DECREF(params);
  Py_DECREF(names);
  Py_DECREF(shapes);
  Py_DECREF(outputs);
  if (pred == nullptr) {
    CaptureError();
  } else {
    Pred* p = new Pred();
    p->obj = pred;
    *out = p;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXPredCreate(const char* symbol_json_str, const void* param_bytes,
                 int param_size, int dev_type, int dev_id,
                 mx_uint num_input_nodes, const char** input_keys,
                 const mx_uint* input_shape_indptr,
                 const mx_uint* input_shape_data, PredictorHandle* out) {
  return MXPredCreatePartialOut(symbol_json_str, param_bytes, param_size,
                                dev_type, dev_id, num_input_nodes,
                                input_keys, input_shape_indptr,
                                input_shape_data, 0, nullptr, out);
}

int MXPredSetInput(PredictorHandle handle, const char* key,
                   const float* data, mx_uint size) {
  Pred* p = static_cast<Pred*>(handle);
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = PyObject_CallMethod(
      HelperModule(), "set_input", "OsLI", p->obj, key,
      static_cast<long long>(reinterpret_cast<intptr_t>(data)), size);
  int rc = r != nullptr ? 0 : (CaptureError(), -1);
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXPredForward(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = PyObject_CallMethod(HelperModule(), "forward", "O", p->obj);
  int rc = r != nullptr ? 0 : (CaptureError(), -1);
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXPredGetOutputShape(PredictorHandle handle, mx_uint index,
                         mx_uint** shape_data, mx_uint* shape_ndim) {
  Pred* p = static_cast<Pred*>(handle);
  PyGILState_STATE gil = EnsurePython();
  PyObject* shp = PyObject_CallMethod(HelperModule(), "output_shape", "OI",
                                      p->obj, index);
  if (shp == nullptr) {
    CaptureError();
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyList_Size(shp);
  p->shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    p->shape_buf[i] =
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(shp, i)));
  Py_DECREF(shp);
  *shape_data = p->shape_buf.data();
  *shape_ndim = static_cast<mx_uint>(n);
  PyGILState_Release(gil);
  return 0;
}

int MXPredGetOutput(PredictorHandle handle, mx_uint index, float* data,
                    mx_uint size) {
  Pred* p = static_cast<Pred*>(handle);
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = PyObject_CallMethod(
      HelperModule(), "copy_output", "OILI", p->obj, index,
      static_cast<long long>(reinterpret_cast<intptr_t>(data)), size);
  int rc = r != nullptr ? 0 : (CaptureError(), -1);
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

// The reference steps the graph one engine op at a time
// (c_predict_api.cc PartialForward). Here the whole forward is ONE XLA
// program — the minimal faithful mapping is a single step: step 0 runs
// the program, *step_left reports 0 afterwards.
int MXPredPartialForward(PredictorHandle handle, int step, int* step_left) {
  if (step_left != nullptr) *step_left = 0;
  if (step > 0) return 0;  // whole program already ran at step 0
  return MXPredForward(handle);
}

int MXPredFree(PredictorHandle handle) {
  Pred* p = static_cast<Pred*>(handle);
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(p->obj);
  PyGILState_Release(gil);
  delete p;
  return 0;
}

// -- NDArray-list access over a saved blob (MXNDList*) ----------------------
// Handle owns the helper-module list; every pointer handed out (name,
// data, shape) is backed by objects stored in that list, valid until
// MXNDListFree.

typedef void* NDListHandle;

int MXNDListCreate(const char* nd_file_bytes, int nd_file_size,
                   NDListHandle* out, mx_uint* out_length) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* mod = HelperModule();
  if (mod == nullptr) {
    CaptureError();
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* blob = PyBytes_FromStringAndSize(nd_file_bytes, nd_file_size);
  PyObject* lst = PyObject_CallMethod(mod, "ndlist_create", "O", blob);
  Py_DECREF(blob);
  if (lst == nullptr) {
    CaptureError();
    PyGILState_Release(gil);
    return -1;
  }
  *out = lst;
  *out_length = static_cast<mx_uint>(PyList_Size(lst));
  PyGILState_Release(gil);
  return 0;
}

int MXNDListGet(NDListHandle handle, mx_uint index, const char** out_key,
                const float** out_data, const mx_uint** out_shape,
                mx_uint* out_ndim) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* entry = PyObject_CallMethod(
      HelperModule(), "ndlist_entry", "OI",
      static_cast<PyObject*>(handle), index);
  if (entry == nullptr) {
    CaptureError();
    PyGILState_Release(gil);
    return -1;
  }
  // (name_bytes, data_addr, shape_addr, ndim); the bytes/array objects
  // live in the handle's list, so the raw pointers outlive `entry`
  *out_key = PyBytes_AsString(PyTuple_GetItem(entry, 0));
  *out_data = reinterpret_cast<const float*>(
      PyLong_AsLongLong(PyTuple_GetItem(entry, 1)));
  *out_shape = reinterpret_cast<const mx_uint*>(
      PyLong_AsLongLong(PyTuple_GetItem(entry, 2)));
  *out_ndim = static_cast<mx_uint>(
      PyLong_AsLong(PyTuple_GetItem(entry, 3)));
  Py_DECREF(entry);
  PyGILState_Release(gil);
  return 0;
}

int MXNDListFree(NDListHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

}  // extern "C"
