// Shared CPython-embedding machinery for the C ABI libraries
// (c_api.cc, c_predict_api.cc). One definition of the error buffer and
// MXGetLastError lives in embed_common.cc; when several of these
// libraries are loaded into one process the dynamic linker unifies the
// globals, so errors raised through one library are readable through
// another (the reference ships one libmxnet.so — this keeps the split
// build observably equivalent).
#ifndef MXTPU_EMBED_COMMON_H_
#define MXTPU_EMBED_COMMON_H_

#include <Python.h>

#include <string>

// thread-local last error; written by CaptureError, read by MXGetLastError
extern thread_local std::string mxtpu_last_error;

// Bring the interpreter up (thread-safe, at-most-once) and take the GIL.
PyGILState_STATE MXTPUEnsurePython();

// Capture the pending Python exception into mxtpu_last_error.
void MXTPUCaptureError();

extern "C" const char* MXGetLastError();

#endif  // MXTPU_EMBED_COMMON_H_
