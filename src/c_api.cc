// General C API — the language-binding ABI.
//
// TPU-native re-design of the reference's src/c_api/{c_api.cc,
// c_api_ndarray.cc,c_api_symbolic.cc,c_api_executor.cc} slice of the
// 159-function MXNET_DLL surface (include/mxnet/c_api.h) that powers
// cpp-package/scala/R/perl frontends. Same design as c_predict_api.cc:
// the library embeds CPython and drives the framework's own executor
// through mxnet_tpu/c_api_impl.py, so a C driver trains/infers on the
// exact XLA path Python users run. Handles are owned PyObject* of the
// framework objects.
//
// Exported surface (reference names and call shapes):
//   MXGetLastError, MXNDArrayCreate/CreateEx/Free,
//   MXNDArraySyncCopyFromCPU/SyncCopyToCPU, MXNDArrayGetShape/GetDType,
//   MXNDArrayWaitToRead/WaitToWrite/WaitAll, MXNDArraySave/Load,
//   MXListAllOpNames, NNGetOpHandle, MXImperativeInvoke,
//   MXSymbolCreateFromJSON/CreateFromFile/Free,
//   MXSymbolListArguments/ListOutputs/ListAuxiliaryStates,
//   MXSymbolInferShape, MXExecutorBind/Forward/Backward/Outputs/Free.
// Round-4 tranche (reference c_api.h:359-1269): runtime knobs +
// profiler, NDArray slice/at/reshape/context/grad/raw-bytes, the full
// MXSymbol attr/compose/atomic surface, MXExecutorSimpleBind/BackwardEx,
// MXDataIter*, MXKVStore* (incl. C-callback updater), MXRecordIO*,
// MXAutograd*, CachedOp — each backed by mxnet_tpu/c_api_impl.py and
// exercised from tests/test_c_api.py via ctypes.

#include <Python.h>

#include <cstdarg>
#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "embed_common.h"

typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* AtomicSymbolCreator;

namespace {

// String/shape buffers whose pointers we hand out must stay alive until
// the next API call on the same thread (the reference uses thread-local
// return buffers, c_api.h "callee keeps ownership").
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char*> g_str_ptrs;
thread_local std::vector<mx_uint> g_shape_buf;
thread_local std::vector<std::vector<mx_uint>> g_shape_store;
thread_local std::vector<const mx_uint*> g_shape_ptrs;
thread_local std::vector<mx_uint> g_ndim_buf;
thread_local std::vector<void*> g_handle_buf;

PyGILState_STATE EnsurePython() { return MXTPUEnsurePython(); }

PyObject* Impl() {
  static PyObject* mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxnet_tpu.c_api_impl");
  }
  return mod;
}

void CaptureError() { MXTPUCaptureError(); }

// Call impl helper `name` with pre-built args tuple; returns new ref or
// nullptr with g_last_error set.
PyObject* CallImpl(const char* name, PyObject* args) {
  PyObject* mod = Impl();
  if (mod == nullptr) {
    CaptureError();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(mod, name);
  if (fn == nullptr) {
    CaptureError();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) CaptureError();
  return res;
}

PyObject* StrList(const char** arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyUnicode_FromString(arr[i] ? arr[i] : ""));
  return lst;
}

PyObject* HandleList(NDArrayHandle* arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject* o = arr && arr[i] ? static_cast<PyObject*>(arr[i]) : Py_None;
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

// Store a python list of str into thread-local storage; out gets char**.
int ReturnStrList(PyObject* lst, mx_uint* out_size, const char*** out_array) {
  Py_ssize_t n = PyList_Size(lst);
  g_str_store.clear();
  g_str_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
    g_str_store.emplace_back(c ? c : "");
  }
  for (auto& s : g_str_store) g_str_ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(n);
  *out_array = g_str_ptrs.data();
  return 0;
}

int ReturnHandleList(PyObject* lst, mx_uint* out_size,
                     NDArrayHandle** out_array) {
  Py_ssize_t n = PyList_Size(lst);
  g_handle_buf.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(lst, i);
    Py_INCREF(o);  // handle owns a reference; freed by MXNDArrayFree
    g_handle_buf.push_back(o);
  }
  *out_size = static_cast<mx_uint>(n);
  *out_array = g_handle_buf.data();
  return 0;
}

}  // namespace

extern "C" {

// MXGetLastError is exported by embed_common.cc

// ---- NDArray --------------------------------------------------------------

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* shp = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oiiii)", shp, dev_type, dev_id,
                                 delay_alloc, dtype);
  Py_DECREF(shp);
  PyObject* nd = CallImpl("ndarray_create", args);
  int rc = -1;
  if (nd != nullptr) {
    *out = nd;  // transfer ownership to the handle
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

int MXNDArrayFree(NDArrayHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue(
      "(OLn)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(data)),
      static_cast<Py_ssize_t>(size));
  PyObject* r = CallImpl("ndarray_sync_copy_from", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue(
      "(OLn)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(data)),
      static_cast<Py_ssize_t>(size));
  PyObject* r = CallImpl("ndarray_sync_copy_to", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* shp = CallImpl("ndarray_shape", args);
  if (shp == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyList_Size(shp);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    g_shape_buf[i] =
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(shp, i)));
  Py_DECREF(shp);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = g_shape_buf.data();
  PyGILState_Release(gil);
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("ndarray_dtype", args);
  int rc = -1;
  if (r != nullptr) {
    *out_dtype = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("ndarray_wait", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return MXNDArrayWaitToRead(handle);
}

int MXNDArrayWaitAll() {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("wait_all", PyTuple_New(0));
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySave(const char* fname, mx_uint num_args, NDArrayHandle* args,
                  const char** keys) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* nds = HandleList(args, num_args);
  PyObject* ks = keys != nullptr ? StrList(keys, num_args) : PyList_New(0);
  PyObject* a = Py_BuildValue("(sOO)", fname, nds, ks);
  Py_DECREF(nds);
  Py_DECREF(ks);
  PyObject* r = CallImpl("ndarray_save", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(s)", fname);
  PyObject* r = CallImpl("ndarray_load", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* nds = PyTuple_GetItem(r, 0);
  PyObject* names = PyTuple_GetItem(r, 1);
  ReturnHandleList(nds, out_size, out_arr);
  ReturnStrList(names, out_name_size, out_names);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- operators ------------------------------------------------------------

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("op_names", PyTuple_New(0));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnStrList(r, out_size, out_array);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// Op handles are name strings validated against the registry (the
// reference hands out nnvm::Op* and errors on unknown names).
int NNGetOpHandle(const char* name, AtomicSymbolCreator* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("op_exists", Py_BuildValue("(s)", name));
  int rc = -1;
  if (r != nullptr) {
    if (PyObject_IsTrue(r)) {
      *out = new std::string(name);  // leaked by design: handles live forever
      rc = 0;
    } else {
      mxtpu_last_error = std::string("operator not registered: ") + name;
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  PyGILState_STATE gil = EnsurePython();
  std::string* name = static_cast<std::string*>(creator);
  PyObject* ins = HandleList(inputs, num_inputs);
  PyObject* keys = StrList(param_keys, num_params);
  PyObject* vals = StrList(param_vals, num_params);
  PyObject* outs;
  if (*num_outputs > 0 && *outputs != nullptr) {
    outs = HandleList(*outputs, *num_outputs);
  } else {
    outs = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* a = Py_BuildValue("(sOOOO)", name->c_str(), ins, keys, vals,
                              outs);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  Py_DECREF(outs);
  PyObject* r = CallImpl("imperative_invoke", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  if (*num_outputs <= 0 || *outputs == nullptr) {
    mx_uint n = 0;
    ReturnHandleList(r, &n, outputs);
    *num_outputs = static_cast<int>(n);
  }
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- symbols --------------------------------------------------------------

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("symbol_from_json", Py_BuildValue("(s)", json));
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("symbol_from_file", Py_BuildValue("(s)", fname));
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolFree(SymbolHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

static int SymStrList(SymbolHandle sym, const char* fn, mx_uint* out_size,
                      const char*** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl(fn, Py_BuildValue("(O)",
                                           static_cast<PyObject*>(sym)));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnStrList(r, out_size, out_array);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array) {
  return SymStrList(sym, "symbol_arguments", out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array) {
  return SymStrList(sym, "symbol_outputs", out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array) {
  return SymStrList(sym, "symbol_aux", out_size, out_array);
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args, const char** keys,
                       const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data, mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data, mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* names = StrList(keys, num_args);
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* s = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(s, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shapes, i, s);
  }
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(sym), names,
                              shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  PyObject* r = CallImpl("symbol_infer_shape", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  // unpack three shape-list groups into thread-local storage
  g_shape_store.clear();
  g_shape_ptrs.clear();
  g_ndim_buf.clear();
  mx_uint sizes[3];
  size_t offsets[4] = {0, 0, 0, 0};
  for (int grp = 0; grp < 3; ++grp) {
    PyObject* lst = PyTuple_GetItem(r, grp);
    Py_ssize_t n = PyList_Size(lst);
    sizes[grp] = static_cast<mx_uint>(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* s = PyList_GetItem(lst, i);
      Py_ssize_t nd = PyList_Size(s);
      std::vector<mx_uint> v(nd);
      for (Py_ssize_t j = 0; j < nd; ++j)
        v[j] = static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(s, j)));
      g_shape_store.push_back(std::move(v));
      g_ndim_buf.push_back(static_cast<mx_uint>(nd));
    }
    offsets[grp + 1] = g_shape_store.size();
  }
  for (auto& v : g_shape_store) g_shape_ptrs.push_back(v.data());
  *in_shape_size = sizes[0];
  *in_shape_ndim = g_ndim_buf.data() + offsets[0];
  *in_shape_data = g_shape_ptrs.data() + offsets[0];
  *out_shape_size = sizes[1];
  *out_shape_ndim = g_ndim_buf.data() + offsets[1];
  *out_shape_data = g_shape_ptrs.data() + offsets[1];
  *aux_shape_size = sizes[2];
  *aux_shape_ndim = g_ndim_buf.data() + offsets[2];
  *aux_shape_data = g_shape_ptrs.data() + offsets[2];
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- executor ---------------------------------------------------------------

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id, mx_uint len,
                   NDArrayHandle* in_args, NDArrayHandle* arg_grad_store,
                   mx_uint* grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle* aux_states, ExecutorHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = HandleList(in_args, len);
  PyObject* grads = HandleList(arg_grad_store, len);
  PyObject* reqs = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i)
    PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(grad_req_type[i]));
  PyObject* aux = HandleList(aux_states, aux_states_len);
  PyObject* a = Py_BuildValue("(OiiOOOO)", static_cast<PyObject*>(sym),
                              dev_type, dev_id, args, grads, reqs, aux);
  Py_DECREF(args);
  Py_DECREF(grads);
  Py_DECREF(reqs);
  Py_DECREF(aux);
  PyObject* r = CallImpl("executor_bind", a);
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle),
                              is_train);
  PyObject* r = CallImpl("executor_forward", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle* head_grads) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* hg = HandleList(head_grads, len);
  PyObject* a = Py_BuildValue("(OO)", static_cast<PyObject*>(handle), hg);
  Py_DECREF(hg);
  PyObject* r = CallImpl("executor_backward", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("executor_outputs", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnHandleList(r, out_size, out);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXExecutorFree(ExecutorHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

}  // extern "C"

// ===========================================================================
// Round-4 tranche
// ===========================================================================

typedef void* KVStoreHandle;
typedef void* DataIterHandle;
typedef void* DataIterCreator;
typedef void* RecordIOHandle;
typedef void* CachedOpHandle;
typedef void (*MXKVStoreUpdater)(int, NDArrayHandle, NDArrayHandle, void*);
typedef void (*MXKVStoreStrUpdater)(const char*, NDArrayHandle, NDArrayHandle,
                                    void*);
typedef void (*MXKVStoreServerController)(int, const char*, void*);

namespace {

// extra thread-local return stores (one call may hand out several lists)
thread_local std::string g_ret_str;
thread_local std::string g_ret_str2;
thread_local std::vector<std::string> g_info_store;
thread_local std::vector<const char*> g_info_ptrs[3];
thread_local std::vector<int> g_int_buf;
thread_local std::vector<uint64_t> g_u64_buf;
thread_local std::string g_rec_buf;
thread_local std::vector<void*> g_handle_buf2;


// call an impl fn; ignore result
int CallVoid(const char* name, PyObject* args) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl(name, args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

// call an impl fn; transfer the new python object to a handle
int CallHandle(const char* name, PyObject* args, void** out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl(name, args);
  int rc = -1;
  if (r != nullptr) {
    if (r == Py_None) {  // e.g. get_children of a leaf -> NULL handle
      Py_DECREF(r);
      *out = nullptr;
    } else {
      *out = r;
    }
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}


// Variadic forms: acquire the GIL BEFORE building the args tuple.
// ctypes/other FFI callers invoke these functions WITHOUT the GIL, so a
// Py_BuildValue evaluated in the caller's argument list would touch the
// interpreter unlocked (the round-4 segfault).
int CallIntV(const char* name, int* out, const char* fmt, ...) {
  PyGILState_STATE gil = EnsurePython();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* r = CallImpl(name, args);
  int rc = -1;
  if (r != nullptr) {
    *out = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int CallVoidV(const char* name, const char* fmt, ...) {
  PyGILState_STATE gil = EnsurePython();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* r = CallImpl(name, args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int CallHandleV(const char* name, void** out, const char* fmt, ...) {
  PyGILState_STATE gil = EnsurePython();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* r = CallImpl(name, args);
  int rc = -1;
  if (r != nullptr) {
    if (r == Py_None) {
      Py_DECREF(r);
      *out = nullptr;
    } else {
      *out = r;
    }
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int CallStrV(const char* name, const char** out, const char* fmt, ...) {
  PyGILState_STATE gil = EnsurePython();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* r = CallImpl(name, args);
  int rc = -1;
  if (r != nullptr) {
    const char* c = PyUnicode_AsUTF8(r);
    g_ret_str = c ? c : "";
    *out = g_ret_str.c_str();
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int StrSuccessPairV(const char* fn, const char** out, int* success,
                    const char* fmt, ...) {
  PyGILState_STATE gil = EnsurePython();
  va_list ap;
  va_start(ap, fmt);
  PyObject* args = Py_VaBuildValue(fmt, ap);
  va_end(ap);
  PyObject* r = CallImpl(fn, args);
  int rc = -1;
  if (r != nullptr) {
    const char* c = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
    g_ret_str2 = c ? c : "";
    *out = g_ret_str2.c_str();
    *success = PyObject_IsTrue(PyTuple_GetItem(r, 1));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

PyObject* IntList(const int* arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromLong(arr ? arr[i] : 0));
  return lst;
}

PyObject* UIntList(const mx_uint* arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyLong_FromUnsignedLong(arr ? arr[i] : 0));
  return lst;
}

// shape groups packed as (names, list-of-shape-lists) from ind_ptr layout
PyObject* ShapeLists(mx_uint num_args, const mx_uint* ind_ptr,
                     const mx_uint* shape_data) {
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint lo = ind_ptr[i], hi = ind_ptr[i + 1];
    PyObject* s = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(s, j - lo, PyLong_FromUnsignedLong(shape_data[j]));
    PyList_SetItem(shapes, i, s);
  }
  return shapes;
}

// unpack (name, description, names[], types[], descs[]) info tuples —
// shared by MXSymbolGetAtomicSymbolInfo and MXDataIterGetIterInfo; the
// two string scalars land in g_ret_str/g_ret_str2, the three lists in
// g_info_store with per-group pointer arrays in g_info_ptrs
void UnpackInfoGroups(PyObject* r, const char** name,
                      const char** description, mx_uint* num_args,
                      const char*** arg_names, const char*** arg_type_infos,
                      const char*** arg_descriptions) {
  g_info_store.clear();
  const char* c0 = PyUnicode_AsUTF8(PyTuple_GetItem(r, 0));
  const char* c1 = PyUnicode_AsUTF8(PyTuple_GetItem(r, 1));
  g_ret_str = c0 ? c0 : "";
  g_ret_str2 = c1 ? c1 : "";
  size_t counts[3];
  for (int grp = 0; grp < 3; ++grp) {
    PyObject* lst = PyTuple_GetItem(r, 2 + grp);
    Py_ssize_t cnt = PyList_Size(lst);
    counts[grp] = static_cast<size_t>(cnt);
    for (Py_ssize_t i = 0; i < cnt; ++i) {
      const char* c = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
      g_info_store.emplace_back(c ? c : "");
    }
  }
  size_t off = 0;
  for (int grp = 0; grp < 3; ++grp) {
    g_info_ptrs[grp].clear();
    for (size_t i = 0; i < counts[grp]; ++i)
      g_info_ptrs[grp].push_back(g_info_store[off + i].c_str());
    off += counts[grp];
  }
  *name = g_ret_str.c_str();
  *description = g_ret_str2.c_str();
  *num_args = static_cast<mx_uint>(counts[0]);
  *arg_names = g_info_ptrs[0].data();
  *arg_type_infos = g_info_ptrs[1].data();
  *arg_descriptions = g_info_ptrs[2].data();
}

// unpack the 3-group shape tuple exactly like MXSymbolInferShape does
int UnpackShapeGroups(PyObject* r, mx_uint* in_shape_size,
                      const mx_uint** in_shape_ndim,
                      const mx_uint*** in_shape_data, mx_uint* out_shape_size,
                      const mx_uint** out_shape_ndim,
                      const mx_uint*** out_shape_data, mx_uint* aux_shape_size,
                      const mx_uint** aux_shape_ndim,
                      const mx_uint*** aux_shape_data, int* complete) {
  g_shape_store.clear();
  g_shape_ptrs.clear();
  g_ndim_buf.clear();
  mx_uint sizes[3];
  size_t offsets[4] = {0, 0, 0, 0};
  for (int grp = 0; grp < 3; ++grp) {
    PyObject* lst = PyTuple_GetItem(r, grp);
    Py_ssize_t n = PyList_Size(lst);
    sizes[grp] = static_cast<mx_uint>(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* s = PyList_GetItem(lst, i);
      Py_ssize_t nd = PyList_Size(s);
      std::vector<mx_uint> v(nd);
      for (Py_ssize_t j = 0; j < nd; ++j)
        v[j] = static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(s, j)));
      g_shape_store.push_back(std::move(v));
      g_ndim_buf.push_back(static_cast<mx_uint>(nd));
    }
    offsets[grp + 1] = g_shape_store.size();
  }
  for (auto& v : g_shape_store) g_shape_ptrs.push_back(v.data());
  *in_shape_size = sizes[0];
  *in_shape_ndim = g_ndim_buf.data() + offsets[0];
  *in_shape_data = g_shape_ptrs.data() + offsets[0];
  *out_shape_size = sizes[1];
  *out_shape_ndim = g_ndim_buf.data() + offsets[1];
  *out_shape_data = g_shape_ptrs.data() + offsets[1];
  *aux_shape_size = sizes[2];
  *aux_shape_ndim = g_ndim_buf.data() + offsets[2];
  *aux_shape_data = g_shape_ptrs.data() + offsets[2];
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  return 0;
}

}  // namespace

extern "C" {

// ---- runtime knobs --------------------------------------------------------

int MXGetVersion(int* out) { return CallIntV("version", out, "()"); }

int MXRandomSeed(int seed) {
  return CallVoidV("random_seed", "(i)", seed);
}

int MXNotifyShutdown() {
  return CallVoidV("notify_shutdown", "()");
}

int MXSetNumOMPThreads(int thread_num) {
  return CallVoidV("set_num_omp_threads", "(i)", thread_num);
}

int MXEngineSetBulkSize(int bulk_size, int* prev_bulk_size) {
  return CallIntV("engine_set_bulk_size", prev_bulk_size, "(i)", bulk_size);
}

int MXSetProfilerConfig(int mode, const char* filename) {
  return CallVoidV("profiler_set_config", "(is)", mode, filename);
}

int MXSetProfilerState(int state) {
  return CallVoidV("profiler_set_state", "(i)", state);
}

int MXDumpProfile() { return CallVoidV("profiler_dump", "()"); }

// ---- NDArray extras -------------------------------------------------------

int MXNDArrayCreateNone(NDArrayHandle* out) {
  return CallHandleV("ndarray_create_none", out, "()");
}

int MXNDArraySlice(NDArrayHandle handle, mx_uint begin, mx_uint end,
                   NDArrayHandle* out) {
  return CallHandleV("ndarray_slice", out, "(OII)",
                     static_cast<PyObject*>(handle), begin, end);
}

int MXNDArrayAt(NDArrayHandle handle, mx_uint idx, NDArrayHandle* out) {
  return CallHandleV("ndarray_at", out, "(OI)",
                     static_cast<PyObject*>(handle), idx);
}

int MXNDArrayReshape(NDArrayHandle handle, int ndim, int* dims,
                     NDArrayHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* d = IntList(dims, ndim);
  PyObject* a = Py_BuildValue("(OO)", static_cast<PyObject*>(handle), d);
  Py_DECREF(d);
  PyGILState_Release(gil);
  return CallHandle("ndarray_reshape", a, out);
}

int MXNDArrayGetContext(NDArrayHandle handle, int* out_dev_type,
                        int* out_dev_id) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("ndarray_get_context",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    *out_dev_type = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
    *out_dev_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetStorageType(NDArrayHandle handle, int* out_storage_type) {
  return CallIntV("ndarray_storage_type", out_storage_type, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXNDArrayGetGrad(NDArrayHandle handle, NDArrayHandle* out) {
  return CallHandleV("ndarray_get_grad", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXNDArrayDetach(NDArrayHandle handle, NDArrayHandle* out) {
  return CallHandleV("ndarray_detach", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXNDArraySetGradState(NDArrayHandle handle, int state) {
  return CallVoidV("ndarray_set_grad_state", "(Oi)",
                   static_cast<PyObject*>(handle), state);
}

int MXNDArrayGetGradState(NDArrayHandle handle, int* out) {
  return CallIntV("ndarray_get_grad_state", out, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXNDArraySyncCopyFromNDArray(NDArrayHandle dst, const NDArrayHandle src,
                                 const int i) {
  return CallVoidV("ndarray_sync_copy_from_ndarray", "(OOi)",
                   static_cast<PyObject*>(dst),
                   static_cast<PyObject*>(src), i);
}

int MXNDArraySaveRawBytes(NDArrayHandle handle, size_t* out_size,
                          const char** out_buf) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("ndarray_save_raw_bytes",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    char* buf = nullptr;
    Py_ssize_t n = 0;
    if (PyBytes_AsStringAndSize(r, &buf, &n) == 0) {
      g_rec_buf.assign(buf, n);
      *out_size = static_cast<size_t>(n);
      *out_buf = g_rec_buf.data();
      rc = 0;
    } else {
      CaptureError();
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayLoadFromRawBytes(const void* buf, size_t size,
                              NDArrayHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* b = PyBytes_FromStringAndSize(static_cast<const char*>(buf),
                                          static_cast<Py_ssize_t>(size));
  PyObject* a = Py_BuildValue("(O)", b);
  Py_DECREF(b);
  PyGILState_Release(gil);
  return CallHandle("ndarray_load_from_raw_bytes", a, out);
}

// ---- symbol surface -------------------------------------------------------

int MXSymbolCreateVariable(const char* name, SymbolHandle* out) {
  return CallHandleV("symbol_create_variable", out, "(s)", name);
}

int MXSymbolCreateGroup(mx_uint num_symbols, SymbolHandle* symbols,
                        SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* lst = HandleList(symbols, num_symbols);
  PyObject* a = Py_BuildValue("(O)", lst);
  Py_DECREF(lst);
  PyGILState_Release(gil);
  return CallHandle("symbol_create_group", a, out);
}

int MXSymbolSaveToFile(SymbolHandle symbol, const char* fname) {
  return CallVoidV("symbol_save_to_file", "(Os)",
                   static_cast<PyObject*>(symbol), fname);
}

int MXSymbolSaveToJSON(SymbolHandle symbol, const char** out_json) {
  return CallStrV("symbol_to_json", out_json, "(O)",
                  static_cast<PyObject*>(symbol));
}

int MXSymbolCopy(SymbolHandle symbol, SymbolHandle* out) {
  return CallHandleV("symbol_copy", out, "(O)",
                     static_cast<PyObject*>(symbol));
}

int MXSymbolPrint(SymbolHandle symbol, const char** out_str) {
  return CallStrV("symbol_print", out_str, "(O)",
                  static_cast<PyObject*>(symbol));
}


int MXSymbolGetName(SymbolHandle symbol, const char** out, int* success) {
  return StrSuccessPairV("symbol_get_name", out, success, "(O)",
                         static_cast<PyObject*>(symbol));
}

int MXSymbolGetAttr(SymbolHandle symbol, const char* key, const char** out,
                    int* success) {
  return StrSuccessPairV("symbol_get_attr", out, success, "(Os)",
                         static_cast<PyObject*>(symbol), key);
}

int MXSymbolSetAttr(SymbolHandle symbol, const char* key, const char* value) {
  return CallVoidV("symbol_set_attr", "(Oss)",
                   static_cast<PyObject*>(symbol), key, value);
}

static int SymAttrList(const char* fn, SymbolHandle symbol, mx_uint* out_size,
                       const char*** out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl(fn, Py_BuildValue("(O)",
                                           static_cast<PyObject*>(symbol)));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  mx_uint n = 0;
  ReturnStrList(r, &n, out);
  *out_size = n / 2;  // reference counts PAIRS here
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolListAttr(SymbolHandle symbol, mx_uint* out_size,
                     const char*** out) {
  return SymAttrList("symbol_list_attr", symbol, out_size, out);
}

int MXSymbolListAttrShallow(SymbolHandle symbol, mx_uint* out_size,
                            const char*** out) {
  return SymAttrList("symbol_list_attr_shallow", symbol, out_size, out);
}

int MXSymbolGetInternals(SymbolHandle symbol, SymbolHandle* out) {
  return CallHandleV("symbol_get_internals", out, "(O)",
                     static_cast<PyObject*>(symbol));
}

int MXSymbolGetChildren(SymbolHandle symbol, SymbolHandle* out) {
  return CallHandleV("symbol_get_children", out, "(O)",
                     static_cast<PyObject*>(symbol));
}

int MXSymbolGetOutput(SymbolHandle symbol, mx_uint index, SymbolHandle* out) {
  return CallHandleV("symbol_get_output", out, "(OI)",
                     static_cast<PyObject*>(symbol), index);
}

int MXSymbolCompose(SymbolHandle sym, const char* name, mx_uint num_args,
                    const char** keys, SymbolHandle* args) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = keys != nullptr ? StrList(keys, num_args) : PyList_New(0);
  PyObject* as = HandleList(args, num_args);
  PyObject* a = Py_BuildValue("(OsOO)", static_cast<PyObject*>(sym),
                              name != nullptr ? name : "", ks, as);
  Py_DECREF(ks);
  Py_DECREF(as);
  PyGILState_Release(gil);
  return CallVoid("symbol_compose", a);
}

int MXSymbolCreateAtomicSymbol(AtomicSymbolCreator creator, mx_uint num_param,
                               const char** keys, const char** vals,
                               SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  std::string* name = static_cast<std::string*>(creator);
  PyObject* ks = StrList(keys, num_param);
  PyObject* vs = StrList(vals, num_param);
  PyObject* a = Py_BuildValue("(sOO)", name->c_str(), ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallHandle("symbol_create_atomic", a, out);
}

int MXSymbolListAtomicSymbolCreators(mx_uint* out_size,
                                     AtomicSymbolCreator** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("op_names", PyTuple_New(0));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyList_Size(r);
  // dedicated static storage: callers cache this array across later API
  // calls (the reference returns a stable registry vector), so it must
  // not share a buffer with any other return path
  static std::vector<void*> creators;
  creators.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    creators.push_back(new std::string(c ? c : ""));  // leaked handles
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(n);
  *out_array = creators.data();
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolGetAtomicSymbolName(AtomicSymbolCreator creator,
                                const char** name) {
  *name = static_cast<std::string*>(creator)->c_str();
  return 0;
}

int MXSymbolGetAtomicSymbolInfo(AtomicSymbolCreator creator,
                                const char** name, const char** description,
                                mx_uint* num_args, const char*** arg_names,
                                const char*** arg_type_infos,
                                const char*** arg_descriptions,
                                const char** key_var_num_args,
                                const char** return_type) {
  PyGILState_STATE gil = EnsurePython();
  std::string* op = static_cast<std::string*>(creator);
  PyObject* r = CallImpl("op_info", Py_BuildValue("(s)", op->c_str()));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  const char* c5 = PyUnicode_AsUTF8(PyTuple_GetItem(r, 5));
  g_rec_buf = c5 ? c5 : "";
  UnpackInfoGroups(r, name, description, num_args, arg_names,
                   arg_type_infos, arg_descriptions);
  Py_DECREF(r);
  *key_var_num_args = g_rec_buf.c_str();
  if (return_type != nullptr) *return_type = "";
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolInferShapePartial(
    SymbolHandle sym, mx_uint num_args, const char** keys,
    const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
    mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
    const mx_uint*** in_shape_data, mx_uint* out_shape_size,
    const mx_uint** out_shape_ndim, const mx_uint*** out_shape_data,
    mx_uint* aux_shape_size, const mx_uint** aux_shape_ndim,
    const mx_uint*** aux_shape_data, int* complete) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* names = StrList(keys, num_args);
  PyObject* shapes = ShapeLists(num_args, arg_ind_ptr, arg_shape_data);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(sym), names,
                              shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  PyObject* r = CallImpl("symbol_infer_shape_partial", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  UnpackShapeGroups(r, in_shape_size, in_shape_ndim, in_shape_data,
                    out_shape_size, out_shape_ndim, out_shape_data,
                    aux_shape_size, aux_shape_ndim, aux_shape_data, complete);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolInferType(SymbolHandle sym, mx_uint num_args, const char** keys,
                      const int* arg_type_data, mx_uint* in_type_size,
                      const int** in_type_data, mx_uint* out_type_size,
                      const int** out_type_data, mx_uint* aux_type_size,
                      const int** aux_type_data, int* complete) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* names = StrList(keys, num_args);
  PyObject* codes = IntList(arg_type_data, num_args);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(sym), names,
                              codes);
  Py_DECREF(names);
  Py_DECREF(codes);
  PyObject* r = CallImpl("symbol_infer_type", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  g_int_buf.clear();
  mx_uint sizes[3];
  size_t offsets[4] = {0, 0, 0, 0};
  for (int grp = 0; grp < 3; ++grp) {
    PyObject* lst = PyTuple_GetItem(r, grp);
    Py_ssize_t n = PyList_Size(lst);
    sizes[grp] = static_cast<mx_uint>(n);
    for (Py_ssize_t i = 0; i < n; ++i)
      g_int_buf.push_back(
          static_cast<int>(PyLong_AsLong(PyList_GetItem(lst, i))));
    offsets[grp + 1] = g_int_buf.size();
  }
  *in_type_size = sizes[0];
  *in_type_data = g_int_buf.data() + offsets[0];
  *out_type_size = sizes[1];
  *out_type_data = g_int_buf.data() + offsets[1];
  *aux_type_size = sizes[2];
  *aux_type_data = g_int_buf.data() + offsets[2];
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- executor extras ------------------------------------------------------

int MXExecutorPrint(ExecutorHandle handle, const char** out_str) {
  return CallStrV("executor_print", out_str, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXExecutorBackwardEx(ExecutorHandle handle, mx_uint len,
                         NDArrayHandle* head_grads, int is_train) {
  (void)is_train;  // our backward derives mode from the recorded program
  return MXExecutorBackward(handle, len, head_grads);
}

int MXExecutorSimpleBind(
    SymbolHandle symbol_handle, int dev_type, int dev_id,
    const mx_uint num_g2c_keys, const char** g2c_keys,
    const int* g2c_dev_types, const int* g2c_dev_ids,
    const mx_uint provided_grad_req_list_len,
    const char** provided_grad_req_names,
    const char** provided_grad_req_types,
    const mx_uint num_provided_arg_shapes,
    const char** provided_arg_shape_names,
    const mx_uint* provided_arg_shape_data,
    const mx_uint* provided_arg_shape_idx,
    const mx_uint num_provided_arg_dtypes,
    const char** provided_arg_dtype_names, const int* provided_arg_dtypes,
    const mx_uint num_provided_arg_stypes,
    const char** provided_arg_stype_names, const int* provided_arg_stypes,
    const mx_uint num_shared_arg_names, const char** shared_arg_name_list,
    int* shared_buffer_len, const char** shared_buffer_name_list,
    NDArrayHandle* shared_buffer_handle_list,
    const char*** updated_shared_buffer_name_list,
    NDArrayHandle** updated_shared_buffer_handle_list, mx_uint* num_in_args,
    NDArrayHandle** in_args, NDArrayHandle** arg_grads,
    mx_uint* num_aux_states, NDArrayHandle** aux_states,
    ExecutorHandle shared_exec_handle, ExecutorHandle* out) {
  // shared buffer / shared exec are allocator-reuse hints in the
  // reference (c_api_executor.cc); PJRT owns allocation here, so they
  // are accepted and passed through unchanged.
  (void)num_shared_arg_names;
  (void)shared_arg_name_list;
  (void)shared_exec_handle;
  PyGILState_STATE gil = EnsurePython();
  PyObject* g2ck = StrList(g2c_keys, num_g2c_keys);
  PyObject* g2ct = IntList(g2c_dev_types, num_g2c_keys);
  PyObject* g2ci = IntList(g2c_dev_ids, num_g2c_keys);
  PyObject* reqn = StrList(provided_grad_req_names,
                           provided_grad_req_names != nullptr
                               ? provided_grad_req_list_len : 0);
  // reference convention: a GLOBAL grad_req arrives as list_len==0 with
  // a single-element types array (python/mxnet/symbol.py simple_bind)
  mx_uint n_req_types = provided_grad_req_list_len;
  if (provided_grad_req_names == nullptr && provided_grad_req_list_len == 0
      && provided_grad_req_types != nullptr)
    n_req_types = 1;
  PyObject* reqt = StrList(provided_grad_req_types, n_req_types);
  PyObject* shn = StrList(provided_arg_shape_names, num_provided_arg_shapes);
  PyObject* shs = ShapeLists(num_provided_arg_shapes, provided_arg_shape_idx,
                             provided_arg_shape_data);
  PyObject* dtn = StrList(provided_arg_dtype_names, num_provided_arg_dtypes);
  PyObject* dtc = IntList(provided_arg_dtypes, num_provided_arg_dtypes);
  PyObject* stn = StrList(provided_arg_stype_names, num_provided_arg_stypes);
  PyObject* stc = IntList(provided_arg_stypes, num_provided_arg_stypes);
  PyObject* a = Py_BuildValue(
      "(OiiOOOOOOOOOOO)", static_cast<PyObject*>(symbol_handle), dev_type,
      dev_id, g2ck, g2ct, g2ci, reqn, reqt, shn, shs, dtn, dtc, stn, stc);
  Py_DECREF(g2ck); Py_DECREF(g2ct); Py_DECREF(g2ci);
  Py_DECREF(reqn); Py_DECREF(reqt); Py_DECREF(shn); Py_DECREF(shs);
  Py_DECREF(dtn); Py_DECREF(dtc); Py_DECREF(stn); Py_DECREF(stc);
  PyObject* r = CallImpl("executor_simple_bind", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* ex = PyTuple_GetItem(r, 0);
  Py_INCREF(ex);
  *out = ex;
  mx_uint n_in = 0, n_aux = 0;
  // in_args and arg_grads must live in SEPARATE buffers (two pointers
  // handed out simultaneously); ReturnHandleList uses one — inline here
  {
    PyObject* ins = PyTuple_GetItem(r, 1);
    PyObject* grads = PyTuple_GetItem(r, 2);
    Py_ssize_t n = PyList_Size(ins);
    g_handle_buf.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* o = PyList_GetItem(ins, i);
      Py_INCREF(o);
      g_handle_buf.push_back(o);
    }
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* o = PyList_GetItem(grads, i);
      if (o == Py_None) {
        g_handle_buf.push_back(nullptr);
      } else {
        Py_INCREF(o);
        g_handle_buf.push_back(o);
      }
    }
    n_in = static_cast<mx_uint>(n);
    *in_args = g_handle_buf.data();
    *arg_grads = g_handle_buf.data() + n;
  }
  {
    PyObject* aux = PyTuple_GetItem(r, 3);
    Py_ssize_t n = PyList_Size(aux);
    g_handle_buf2.clear();
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* o = PyList_GetItem(aux, i);
      Py_INCREF(o);
      g_handle_buf2.push_back(o);
    }
    n_aux = static_cast<mx_uint>(n);
    *aux_states = g_handle_buf2.data();
  }
  *num_in_args = n_in;
  *num_aux_states = n_aux;
  if (updated_shared_buffer_name_list != nullptr)
    *updated_shared_buffer_name_list = shared_buffer_name_list;
  if (updated_shared_buffer_handle_list != nullptr)
    *updated_shared_buffer_handle_list = shared_buffer_handle_list;
  if (shared_buffer_len != nullptr && *shared_buffer_len < 0)
    *shared_buffer_len = 0;
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- CachedOp -------------------------------------------------------------

int MXCreateCachedOp(SymbolHandle handle, CachedOpHandle* out) {
  return CallHandleV("cached_op_create", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXFreeCachedOp(CachedOpHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXInvokeCachedOp(CachedOpHandle handle, int num_inputs,
                     NDArrayHandle* inputs, int* num_outputs,
                     NDArrayHandle** outputs) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ins = HandleList(inputs, num_inputs);
  PyObject* a = Py_BuildValue("(OO)", static_cast<PyObject*>(handle), ins);
  Py_DECREF(ins);
  PyObject* r = CallImpl("cached_op_invoke", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  mx_uint n = 0;
  ReturnHandleList(r, &n, outputs);
  *num_outputs = static_cast<int>(n);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- autograd -------------------------------------------------------------

int MXAutogradSetIsRecording(int is_recording, int* prev) {
  return CallIntV("autograd_set_recording", prev, "(i)", is_recording);
}

int MXAutogradSetIsTraining(int is_training, int* prev) {
  return CallIntV("autograd_set_training", prev, "(i)", is_training);
}

int MXAutogradIsRecording(bool* curr) {
  int v = 0;
  int rc = CallIntV("autograd_is_recording", &v, "()");
  *curr = v != 0;
  return rc;
}

int MXAutogradIsTraining(bool* curr) {
  int v = 0;
  int rc = CallIntV("autograd_is_training", &v, "()");
  *curr = v != 0;
  return rc;
}

int MXAutogradMarkVariables(mx_uint num_var, NDArrayHandle* var_handles,
                            mx_uint* reqs_array,
                            NDArrayHandle* grad_handles) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* vars = HandleList(var_handles, num_var);
  PyObject* reqs = UIntList(reqs_array, num_var);
  PyObject* grads = HandleList(grad_handles, num_var);
  PyObject* a = Py_BuildValue("(OOO)", vars, reqs, grads);
  Py_DECREF(vars);
  Py_DECREF(reqs);
  Py_DECREF(grads);
  PyGILState_Release(gil);
  return CallVoid("autograd_mark_variables", a);
}

int MXAutogradBackward(mx_uint num_output, NDArrayHandle* output_handles,
                       NDArrayHandle* ograd_handles, int retain_graph) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* outs = HandleList(output_handles, num_output);
  PyObject* ogs = ograd_handles != nullptr
                      ? HandleList(ograd_handles, num_output) : PyList_New(0);
  PyObject* a = Py_BuildValue("(OOii)", outs, ogs, retain_graph, 1);
  Py_DECREF(outs);
  Py_DECREF(ogs);
  PyGILState_Release(gil);
  return CallVoid("autograd_backward", a);
}

int MXAutogradComputeGradient(mx_uint num_output,
                              NDArrayHandle* output_handles) {
  return MXAutogradBackward(num_output, output_handles, nullptr, 0);
}

int MXAutogradBackwardEx(mx_uint num_output, NDArrayHandle* output_handles,
                         NDArrayHandle* ograd_handles, mx_uint num_variables,
                         NDArrayHandle* var_handles, int retain_graph,
                         int create_graph, int is_train,
                         NDArrayHandle** grad_handles, int** grad_stypes) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* outs = HandleList(output_handles, num_output);
  PyObject* ogs = ograd_handles != nullptr
                      ? HandleList(ograd_handles, num_output) : PyList_New(0);
  PyObject* vars = HandleList(var_handles, num_variables);
  PyObject* a = Py_BuildValue("(OOOiii)", outs, ogs, vars, retain_graph,
                              create_graph, is_train);
  Py_DECREF(outs);
  Py_DECREF(ogs);
  Py_DECREF(vars);
  PyObject* r = CallImpl("autograd_backward_ex", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  if (num_variables > 0 && grad_handles != nullptr) {
    mx_uint n = 0;
    ReturnHandleList(PyTuple_GetItem(r, 0), &n, grad_handles);
    PyObject* st = PyTuple_GetItem(r, 1);
    g_int_buf.clear();
    for (Py_ssize_t i = 0; i < PyList_Size(st); ++i)
      g_int_buf.push_back(
          static_cast<int>(PyLong_AsLong(PyList_GetItem(st, i))));
    if (grad_stypes != nullptr) *grad_stypes = g_int_buf.data();
  }
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXAutogradGetSymbol(NDArrayHandle handle, SymbolHandle* out) {
  return CallHandleV("autograd_get_symbol", out, "(O)",
                     static_cast<PyObject*>(handle));
}

// ---- data iterators -------------------------------------------------------

int MXListDataIters(mx_uint* out_size, DataIterCreator** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("list_data_iters", PyTuple_New(0));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  static std::vector<void*> creators;  // leaked name handles, like ops
  creators.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(r, i));
    creators.push_back(new std::string(c ? c : ""));
  }
  Py_DECREF(r);
  *out_size = static_cast<mx_uint>(creators.size());
  *out_array = creators.data();
  PyGILState_Release(gil);
  return 0;
}

int MXDataIterGetIterInfo(DataIterCreator creator, const char** name,
                          const char** description, mx_uint* num_args,
                          const char*** arg_names,
                          const char*** arg_type_infos,
                          const char*** arg_descriptions) {
  PyGILState_STATE gil = EnsurePython();
  std::string* n = static_cast<std::string*>(creator);
  PyObject* r = CallImpl("data_iter_info", Py_BuildValue("(s)", n->c_str()));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  UnpackInfoGroups(r, name, description, num_args, arg_names,
                   arg_type_infos, arg_descriptions);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXDataIterCreateIter(DataIterCreator handle, mx_uint num_param,
                         const char** keys, const char** vals,
                         DataIterHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  std::string* n = static_cast<std::string*>(handle);
  PyObject* ks = StrList(keys, num_param);
  PyObject* vs = StrList(vals, num_param);
  PyObject* a = Py_BuildValue("(sOO)", n->c_str(), ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallHandle("data_iter_create", a, out);
}

int MXDataIterFree(DataIterHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXDataIterNext(DataIterHandle handle, int* out) {
  return CallIntV("data_iter_next", out, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXDataIterBeforeFirst(DataIterHandle handle) {
  return CallVoidV("data_iter_before_first", "(O)",
                   static_cast<PyObject*>(handle));
}

int MXDataIterGetData(DataIterHandle handle, NDArrayHandle* out) {
  return CallHandleV("data_iter_get_data", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXDataIterGetLabel(DataIterHandle handle, NDArrayHandle* out) {
  return CallHandleV("data_iter_get_label", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXDataIterGetPadNum(DataIterHandle handle, int* pad) {
  return CallIntV("data_iter_get_pad", pad, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXDataIterGetIndex(DataIterHandle handle, uint64_t** out_index,
                       uint64_t* out_size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("data_iter_get_index",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  g_u64_buf.clear();
  for (Py_ssize_t i = 0; i < PyList_Size(r); ++i)
    g_u64_buf.push_back(static_cast<uint64_t>(
        PyLong_AsUnsignedLongLong(PyList_GetItem(r, i))));
  Py_DECREF(r);
  *out_index = g_u64_buf.data();
  *out_size = static_cast<uint64_t>(g_u64_buf.size());
  PyGILState_Release(gil);
  return 0;
}

// ---- kvstore --------------------------------------------------------------

int MXInitPSEnv(mx_uint num_vars, const char** keys, const char** vals) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = StrList(keys, num_vars);
  PyObject* vs = StrList(vals, num_vars);
  PyObject* a = Py_BuildValue("(OO)", ks, vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("init_ps_env", a);
}

int MXKVStoreCreate(const char* type, KVStoreHandle* out) {
  return CallHandleV("kvstore_create", out, "(s)", type);
}

int MXKVStoreFree(KVStoreHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

static PyObject* KVKeys(const int* keys, mx_uint num) {
  return IntList(keys, num);
}

static PyObject* KVKeysEx(const char** keys, mx_uint num) {
  return StrList(keys, num);
}

int MXKVStoreInit(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeys(keys, num);
  PyObject* vs = HandleList(vals, num);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(handle), ks,
                              vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_init", a);
}

int MXKVStoreInitEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeysEx(keys, num);
  PyObject* vs = HandleList(vals, num);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(handle), ks,
                              vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_init", a);
}

static int KVPush(KVStoreHandle handle, PyObject* ks, mx_uint num,
                  NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* vs = HandleList(vals, num);
  PyObject* a = Py_BuildValue("(OOOi)", static_cast<PyObject*>(handle), ks,
                              vs, priority);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_push", a);
}

int MXKVStorePush(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeys(keys, num);
  PyGILState_Release(gil);
  return KVPush(handle, ks, num, vals, priority);
}

int MXKVStorePushEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeysEx(keys, num);
  PyGILState_Release(gil);
  return KVPush(handle, ks, num, vals, priority);
}

static int KVPull(KVStoreHandle handle, PyObject* ks, mx_uint num,
                  NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* vs = HandleList(vals, num);
  PyObject* a = Py_BuildValue("(OOOi)", static_cast<PyObject*>(handle), ks,
                              vs, priority);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_pull", a);
}

int MXKVStorePull(KVStoreHandle handle, mx_uint num, const int* keys,
                  NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeys(keys, num);
  PyGILState_Release(gil);
  return KVPull(handle, ks, num, vals, priority);
}

int MXKVStorePullEx(KVStoreHandle handle, mx_uint num, const char** keys,
                    NDArrayHandle* vals, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeysEx(keys, num);
  PyGILState_Release(gil);
  return KVPull(handle, ks, num, vals, priority);
}

static int KVPullRsp(KVStoreHandle handle, PyObject* ks, mx_uint num,
                     NDArrayHandle* vals, const NDArrayHandle* row_ids,
                     int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* vs = HandleList(vals, num);
  PyObject* rs = HandleList(const_cast<NDArrayHandle*>(row_ids), num);
  PyObject* a = Py_BuildValue("(OOOOi)", static_cast<PyObject*>(handle), ks,
                              vs, rs, priority);
  Py_DECREF(ks);
  Py_DECREF(vs);
  Py_DECREF(rs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_pull_row_sparse", a);
}

int MXKVStorePullRowSparse(KVStoreHandle handle, mx_uint num, const int* keys,
                           NDArrayHandle* vals, const NDArrayHandle* row_ids,
                           int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeys(keys, num);
  PyGILState_Release(gil);
  return KVPullRsp(handle, ks, num, vals, row_ids, priority);
}

int MXKVStorePullRowSparseEx(KVStoreHandle handle, mx_uint num,
                             const char** keys, NDArrayHandle* vals,
                             const NDArrayHandle* row_ids, int priority) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = KVKeysEx(keys, num);
  PyGILState_Release(gil);
  return KVPullRsp(handle, ks, num, vals, row_ids, priority);
}

int MXKVStoreSetGradientCompression(KVStoreHandle handle, mx_uint num_params,
                                    const char** keys, const char** vals) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* ks = StrList(keys, num_params);
  PyObject* vs = StrList(vals, num_params);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(handle), ks,
                              vs);
  Py_DECREF(ks);
  Py_DECREF(vs);
  PyGILState_Release(gil);
  return CallVoid("kvstore_set_gradient_compression", a);
}

int MXKVStoreSetUpdater(KVStoreHandle handle, MXKVStoreUpdater updater,
                        void* updater_handle) {
  return CallVoidV(
      "kvstore_set_updater", "(OLL)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(updater)),
      static_cast<long long>(reinterpret_cast<intptr_t>(updater_handle)));
}

int MXKVStoreSetUpdaterEx(KVStoreHandle handle, MXKVStoreUpdater updater,
                          MXKVStoreStrUpdater str_updater,
                          void* updater_handle) {
  return CallVoidV(
      "kvstore_set_updater", "(OLLL)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(updater)),
      static_cast<long long>(reinterpret_cast<intptr_t>(updater_handle)),
      static_cast<long long>(reinterpret_cast<intptr_t>(str_updater)));
}

int MXKVStoreGetType(KVStoreHandle handle, const char** type) {
  return CallStrV("kvstore_get_type", type, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXKVStoreGetRank(KVStoreHandle handle, int* ret) {
  return CallIntV("kvstore_get_rank", ret, "(O)",
                  static_cast<PyObject*>(handle));
}

int MXKVStoreGetGroupSize(KVStoreHandle handle, int* ret) {
  return CallIntV("kvstore_get_group_size", ret, "(O)",
                  static_cast<PyObject*>(handle));
}

// role queries: the SPMD runtime has workers only (kvstore_server.py is
// the documented role-absorber); env overrides keep launcher parity
int MXKVStoreIsWorkerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (role == nullptr || std::string(role) == "worker") ? 1 : 0;
  return 0;
}

int MXKVStoreIsServerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (role != nullptr && std::string(role) == "server") ? 1 : 0;
  return 0;
}

int MXKVStoreIsSchedulerNode(int* ret) {
  const char* role = getenv("DMLC_ROLE");
  *ret = (role != nullptr && std::string(role) == "scheduler") ? 1 : 0;
  return 0;
}

int MXKVStoreBarrier(KVStoreHandle handle) {
  return CallVoidV("kvstore_barrier", "(O)",
                   static_cast<PyObject*>(handle));
}

int MXKVStoreSetBarrierBeforeExit(KVStoreHandle handle,
                                  const int barrier_before_exit) {
  (void)handle;
  (void)barrier_before_exit;  // process teardown is jax.distributed's
  return 0;
}

int MXKVStoreRunServer(KVStoreHandle handle,
                       MXKVStoreServerController controller,
                       void* controller_handle) {
  return CallVoidV(
      "kvstore_run_server", "(OLL)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(controller)),
      static_cast<long long>(
          reinterpret_cast<intptr_t>(controller_handle)));
}

int MXKVStoreSendCommmandToServers(KVStoreHandle handle, int cmd_id,
                                   const char* cmd_body) {
  return CallVoidV("kvstore_send_command", "(Ois)",
                   static_cast<PyObject*>(handle), cmd_id, cmd_body);
}

int MXKVStoreGetNumDeadNode(KVStoreHandle handle, const int node_id,
                            int* number, const int timeout_sec) {
  return CallIntV("kvstore_num_dead_node", number, "(Oii)",
                  static_cast<PyObject*>(handle), node_id, timeout_sec);
}

// ---- recordio -------------------------------------------------------------

int MXRecordIOWriterCreate(const char* uri, RecordIOHandle* out) {
  return CallHandleV("recordio_writer_create", out, "(s)", uri);
}

int MXRecordIOReaderCreate(const char* uri, RecordIOHandle* out) {
  return CallHandleV("recordio_reader_create", out, "(s)", uri);
}

static int RecordIOFree(RecordIOHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("recordio_close", a);
  Py_XDECREF(r);
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return r != nullptr ? 0 : -1;
}

int MXRecordIOWriterFree(RecordIOHandle handle) {
  return RecordIOFree(handle);
}

int MXRecordIOReaderFree(RecordIOHandle handle) {
  return RecordIOFree(handle);
}

int MXRecordIOWriterWriteRecord(RecordIOHandle handle, const char* buf,
                                size_t size) {
  return CallVoidV(
      "recordio_write_record", "(OLn)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(buf)),
      static_cast<Py_ssize_t>(size));
}

int MXRecordIOWriterTell(RecordIOHandle handle, size_t* pos) {
  // full 64-bit position: .rec files routinely exceed 2 GB
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("recordio_tell",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    *pos = static_cast<size_t>(PyLong_AsUnsignedLongLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXRecordIOReaderTell(RecordIOHandle handle, size_t* pos) {
  return MXRecordIOWriterTell(handle, pos);
}

int MXRecordIOReaderSeek(RecordIOHandle handle, size_t pos) {
  return CallVoidV("recordio_seek", "(On)",
                   static_cast<PyObject*>(handle),
                   static_cast<Py_ssize_t>(pos));
}

int MXRecordIOReaderReadRecord(RecordIOHandle handle, char const** buf,
                               size_t* size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("recordio_read_record",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    if (r == Py_None) {  // EOF
      *buf = nullptr;
      *size = 0;
      rc = 0;
    } else {
      char* data = nullptr;
      Py_ssize_t n = 0;
      if (PyBytes_AsStringAndSize(r, &data, &n) == 0) {
        g_rec_buf.assign(data, n);
        *buf = g_rec_buf.data();
        *size = static_cast<size_t>(n);
        rc = 0;
      } else {
        CaptureError();
      }
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}


// ---- sparse NDArray -------------------------------------------------------

int MXNDArrayCreateSparseEx(int storage_type, const mx_uint* shape,
                            mx_uint ndim, int dev_type, int dev_id,
                            int delay_alloc, int dtype, mx_uint num_aux,
                            int* aux_type, mx_uint* aux_ndims,
                            const mx_uint* aux_shape, NDArrayHandle* out) {
  (void)delay_alloc;
  (void)aux_ndims;
  (void)aux_shape;
  PyGILState_STATE gil = EnsurePython();
  PyObject* shp = UIntList(shape, ndim);
  PyObject* at = IntList(aux_type, num_aux);
  PyObject* a = Py_BuildValue("(iOiiiO)", storage_type, shp, dev_type,
                              dev_id, dtype, at);
  Py_DECREF(shp);
  Py_DECREF(at);
  PyGILState_Release(gil);
  return CallHandle("ndarray_create_sparse", a, out);
}

int MXNDArrayGetAuxType(NDArrayHandle handle, mx_uint i, int* out_type) {
  return CallIntV("ndarray_get_aux_type", out_type, "(OI)",
                  static_cast<PyObject*>(handle), i);
}

int MXNDArrayGetAuxNDArray(NDArrayHandle handle, mx_uint i,
                           NDArrayHandle* out) {
  return CallHandleV("ndarray_get_aux_ndarray", out, "(OI)",
                     static_cast<PyObject*>(handle), i);
}

int MXNDArrayGetDataNDArray(NDArrayHandle handle, NDArrayHandle* out) {
  return CallHandleV("ndarray_get_data_ndarray", out, "(O)",
                     static_cast<PyObject*>(handle));
}

int MXNDArraySyncCheckFormat(NDArrayHandle handle, const bool full_check) {
  return CallVoidV("ndarray_sync_check_format", "(Oi)",
                   static_cast<PyObject*>(handle), full_check ? 1 : 0);
}

// READ-ONLY host view (documented divergence: PJRT owns device memory,
// so this is a synced host copy, alive until the next call on this
// thread — the reference returns the live device pointer)
int MXNDArrayGetData(NDArrayHandle handle, void** out_pdata) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("ndarray_get_data_ptr",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    // r is a numpy array; keep it alive in a thread-local slot and
    // expose its buffer
    static thread_local PyObject* keep = nullptr;
    PyObject* old = keep;
    keep = r;
    Py_XDECREF(old);
    Py_buffer view;
    if (PyObject_GetBuffer(r, &view, PyBUF_SIMPLE) == 0) {
      *out_pdata = view.buf;
      PyBuffer_Release(&view);  // numpy keeps the memory; r stays alive
      rc = 0;
    } else {
      CaptureError();
    }
  }
  PyGILState_Release(gil);
  return rc;
}

// ---- legacy function API --------------------------------------------------

typedef void* FunctionHandle;

int MXListFunctions(mx_uint* out_size, FunctionHandle** out_array) {
  // functions ARE the ops under the legacy convention
  return MXSymbolListAtomicSymbolCreators(
      out_size, reinterpret_cast<AtomicSymbolCreator**>(out_array));
}

int MXGetFunction(const char* name, FunctionHandle* out) {
  return NNGetOpHandle(name,
                       reinterpret_cast<AtomicSymbolCreator*>(out));
}

int MXFuncGetInfo(FunctionHandle fun, const char** name,
                  const char** description, mx_uint* num_args,
                  const char*** arg_names, const char*** arg_type_infos,
                  const char*** arg_descriptions,
                  const char** return_type) {
  PyGILState_STATE gil = EnsurePython();
  std::string* op = static_cast<std::string*>(fun);
  PyObject* r = CallImpl("func_info", Py_BuildValue("(s)", op->c_str()));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  UnpackInfoGroups(r, name, description, num_args, arg_names,
                   arg_type_infos, arg_descriptions);
  Py_DECREF(r);
  if (return_type != nullptr) *return_type = "";
  PyGILState_Release(gil);
  return 0;
}

int MXFuncDescribe(FunctionHandle fun, mx_uint* num_use_vars,
                   mx_uint* num_scalars, mx_uint* num_mutate_vars,
                   int* type_mask) {
  PyGILState_STATE gil = EnsurePython();
  std::string* op = static_cast<std::string*>(fun);
  PyObject* r = CallImpl("func_describe",
                         Py_BuildValue("(s)", op->c_str()));
  int rc = -1;
  if (r != nullptr) {
    *num_use_vars =
        static_cast<mx_uint>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
    *num_scalars =
        static_cast<mx_uint>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
    *num_mutate_vars =
        static_cast<mx_uint>(PyLong_AsLong(PyTuple_GetItem(r, 2)));
    *type_mask = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 3)));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

static int FuncInvokeImpl(FunctionHandle fun, NDArrayHandle* use_vars,
                          float* scalar_args, NDArrayHandle* mutate_vars,
                          int num_params, char** param_keys,
                          char** param_vals);

int MXFuncInvokeEx(FunctionHandle fun, NDArrayHandle* use_vars,
                   float* scalar_args, NDArrayHandle* mutate_vars,
                   int num_params, char** param_keys, char** param_vals) {
  return FuncInvokeImpl(fun, use_vars, scalar_args, mutate_vars,
                        num_params, param_keys, param_vals);
}

int MXFuncInvoke(FunctionHandle fun, NDArrayHandle* use_vars,
                 float* scalar_args, NDArrayHandle* mutate_vars) {
  return FuncInvokeImpl(fun, use_vars, scalar_args, mutate_vars, 0,
                        nullptr, nullptr);
}

static int FuncInvokeImpl(FunctionHandle fun, NDArrayHandle* use_vars,
                          float* scalar_args, NDArrayHandle* mutate_vars,
                          int num_params, char** param_keys,
                          char** param_vals) {
  PyGILState_STATE gil = EnsurePython();
  std::string* op = static_cast<std::string*>(fun);
  // arity comes from func_describe
  PyObject* d = CallImpl("func_describe",
                         Py_BuildValue("(s)", op->c_str()));
  if (d == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  long n_use = PyLong_AsLong(PyTuple_GetItem(d, 0));
  long n_scalar = PyLong_AsLong(PyTuple_GetItem(d, 1));
  long n_mut = PyLong_AsLong(PyTuple_GetItem(d, 2));
  Py_DECREF(d);
  PyObject* uses = HandleList(use_vars, static_cast<mx_uint>(n_use));
  PyObject* scalars = PyList_New(n_scalar);
  for (long i = 0; i < n_scalar; ++i)
    PyList_SetItem(scalars, i,
                   PyFloat_FromDouble(scalar_args ? scalar_args[i] : 0.0));
  PyObject* muts = HandleList(mutate_vars, static_cast<mx_uint>(n_mut));
  PyObject* ek = StrList(const_cast<const char**>(param_keys),
                         param_keys != nullptr ? num_params : 0);
  PyObject* ev = StrList(const_cast<const char**>(param_vals),
                         param_vals != nullptr ? num_params : 0);
  PyObject* a = Py_BuildValue("(sOOOOO)", op->c_str(), uses, scalars, muts,
                              ek, ev);
  Py_DECREF(uses);
  Py_DECREF(scalars);
  Py_DECREF(muts);
  Py_DECREF(ek);
  Py_DECREF(ev);
  PyObject* r = CallImpl("func_invoke", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

// ---- executor bind with device map ---------------------------------------

static int ExecutorBindMapped(SymbolHandle sym, int dev_type, int dev_id,
                              mx_uint num_map_keys, const char** map_keys,
                              const int* map_dev_types,
                              const int* map_dev_ids, mx_uint len,
                              NDArrayHandle* in_args,
                              NDArrayHandle* arg_grad_store,
                              mx_uint* grad_req_type, mx_uint aux_states_len,
                              NDArrayHandle* aux_states,
                              ExecutorHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* mk = StrList(map_keys, num_map_keys);
  PyObject* mt = IntList(map_dev_types, num_map_keys);
  PyObject* mi = IntList(map_dev_ids, num_map_keys);
  PyObject* args = HandleList(in_args, len);
  PyObject* grads = HandleList(arg_grad_store, len);
  PyObject* reqs = UIntList(grad_req_type, len);
  PyObject* aux = HandleList(aux_states, aux_states_len);
  PyObject* a = Py_BuildValue("(OiiOOOOOOO)", static_cast<PyObject*>(sym),
                              dev_type, dev_id, mk, mt, mi, args, grads,
                              reqs, aux);
  Py_DECREF(mk); Py_DECREF(mt); Py_DECREF(mi);
  Py_DECREF(args); Py_DECREF(grads); Py_DECREF(reqs); Py_DECREF(aux);
  PyGILState_Release(gil);
  return CallHandle("executor_bind_x", a, out);
}

int MXExecutorBindX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                    mx_uint num_map_keys, const char** map_keys,
                    const int* map_dev_types, const int* map_dev_ids,
                    mx_uint len, NDArrayHandle* in_args,
                    NDArrayHandle* arg_grad_store, mx_uint* grad_req_type,
                    mx_uint aux_states_len, NDArrayHandle* aux_states,
                    ExecutorHandle* out) {
  return ExecutorBindMapped(symbol_handle, dev_type, dev_id, num_map_keys,
                            map_keys, map_dev_types, map_dev_ids, len,
                            in_args, arg_grad_store, grad_req_type,
                            aux_states_len, aux_states, out);
}

int MXExecutorBindEX(SymbolHandle symbol_handle, int dev_type, int dev_id,
                     mx_uint num_map_keys, const char** map_keys,
                     const int* map_dev_types, const int* map_dev_ids,
                     mx_uint len, NDArrayHandle* in_args,
                     NDArrayHandle* arg_grad_store, mx_uint* grad_req_type,
                     mx_uint aux_states_len, NDArrayHandle* aux_states,
                     ExecutorHandle shared_exec, ExecutorHandle* out) {
  (void)shared_exec;  // allocator-reuse hint; PJRT owns allocation
  return ExecutorBindMapped(symbol_handle, dev_type, dev_id, num_map_keys,
                            map_keys, map_dev_types, map_dev_ids, len,
                            in_args, arg_grad_store, grad_req_type,
                            aux_states_len, aux_states, out);
}

typedef void (*ExecutorMonitorCallback)(const char*, NDArrayHandle, void*);

int MXExecutorSetMonitorCallback(ExecutorHandle handle,
                                 ExecutorMonitorCallback callback,
                                 void* callback_handle) {
  return CallVoidV(
      "executor_set_monitor_callback", "(OLLi)",
      static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(callback)),
      static_cast<long long>(reinterpret_cast<intptr_t>(callback_handle)),
      0);
}

// ---- Ex invoke variants ---------------------------------------------------

int MXImperativeInvokeEx(AtomicSymbolCreator creator, int num_inputs,
                         NDArrayHandle* inputs, int* num_outputs,
                         NDArrayHandle** outputs, int num_params,
                         const char** param_keys, const char** param_vals,
                         const int** out_stypes) {
  int rc = MXImperativeInvoke(creator, num_inputs, inputs, num_outputs,
                              outputs, num_params, param_keys, param_vals);
  if (rc != 0) return rc;
  PyGILState_STATE gil = EnsurePython();
  g_int_buf.clear();
  for (int i = 0; i < *num_outputs; ++i) {
    PyObject* r = CallImpl(
        "ndarray_storage_type",
        Py_BuildValue("(O)", static_cast<PyObject*>((*outputs)[i])));
    g_int_buf.push_back(r != nullptr
                            ? static_cast<int>(PyLong_AsLong(r)) : 0);
    Py_XDECREF(r);
  }
  if (out_stypes != nullptr) *out_stypes = g_int_buf.data();
  PyGILState_Release(gil);
  return 0;
}

int MXInvokeCachedOpEx(CachedOpHandle handle, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, const int** out_stypes) {
  int rc = MXInvokeCachedOp(handle, num_inputs, inputs, num_outputs,
                            outputs);
  if (rc != 0) return rc;
  PyGILState_STATE gil = EnsurePython();
  g_int_buf.clear();
  for (int i = 0; i < *num_outputs; ++i) {
    PyObject* r = CallImpl(
        "ndarray_storage_type",
        Py_BuildValue("(O)", static_cast<PyObject*>((*outputs)[i])));
    g_int_buf.push_back(r != nullptr
                            ? static_cast<int>(PyLong_AsLong(r)) : 0);
    Py_XDECREF(r);
  }
  if (out_stypes != nullptr) *out_stypes = g_int_buf.data();
  PyGILState_Release(gil);
  return 0;
}

// ---- RTC (PallasModule-backed; the reference compiles CUDA C here —
// documented divergence, PARITY.md) ----------------------------------------

typedef void* RtcHandle;
typedef void* CudaModuleHandle;
typedef void* CudaKernelHandle;

int MXRtcCreate(char* name, mx_uint num_input, mx_uint num_output,
                char** input_names, char** output_names,
                NDArrayHandle* inputs, NDArrayHandle* outputs,
                char* kernel, RtcHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* in_names = StrList(const_cast<const char**>(input_names),
                               num_input);
  PyObject* out_names = StrList(const_cast<const char**>(output_names),
                                num_output);
  PyObject* ins = HandleList(inputs, num_input);
  PyObject* outs = HandleList(outputs, num_output);
  PyObject* a = Py_BuildValue("(sOOOOs)", name, in_names, out_names, ins,
                              outs, kernel);
  Py_DECREF(in_names);
  Py_DECREF(out_names);
  Py_DECREF(ins);
  Py_DECREF(outs);
  PyGILState_Release(gil);
  return CallHandle("rtc_create", a, out);
}

int MXRtcPush(RtcHandle handle, mx_uint num_input, mx_uint num_output,
              NDArrayHandle* inputs, NDArrayHandle* outputs,
              mx_uint gridDimX, mx_uint gridDimY, mx_uint gridDimZ,
              mx_uint blockDimX, mx_uint blockDimY, mx_uint blockDimZ) {
  (void)gridDimX; (void)gridDimY; (void)gridDimZ;
  (void)blockDimX; (void)blockDimY; (void)blockDimZ;  // XLA schedules
  PyGILState_STATE gil = EnsurePython();
  PyObject* ins = HandleList(inputs, num_input);
  PyObject* outs = HandleList(outputs, num_output);
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(handle),
                              ins, outs);
  Py_DECREF(ins);
  Py_DECREF(outs);
  PyGILState_Release(gil);
  return CallVoid("rtc_push", a);
}

int MXRtcFree(RtcHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXRtcCudaModuleCreate(const char* source, int num_options,
                          const char** options, int num_exports,
                          const char** exports, CudaModuleHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* opts = StrList(options, num_options);
  PyObject* exps = StrList(exports, num_exports);
  PyObject* a = Py_BuildValue("(sOO)", source, opts, exps);
  Py_DECREF(opts);
  Py_DECREF(exps);
  PyGILState_Release(gil);
  return CallHandle("rtc_module_create", a, out);
}

int MXRtcCudaModuleFree(CudaModuleHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXRtcCudaKernelCreate(CudaModuleHandle handle, const char* name,
                          int num_args, int* is_ndarray, int* is_const,
                          int* arg_types, CudaKernelHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* nds = IntList(is_ndarray, num_args);
  PyObject* consts = IntList(is_const, num_args);
  PyObject* types = IntList(arg_types, num_args);
  PyObject* a = Py_BuildValue("(OsOOO)", static_cast<PyObject*>(handle),
                              name, nds, consts, types);
  Py_DECREF(nds);
  Py_DECREF(consts);
  Py_DECREF(types);
  PyGILState_Release(gil);
  return CallHandle("rtc_kernel_create", a, out);
}

int MXRtcCudaKernelFree(CudaKernelHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXRtcCudaKernelCall(CudaKernelHandle handle, int dev_id, void** args,
                        mx_uint grid_dim_x, mx_uint grid_dim_y,
                        mx_uint grid_dim_z, mx_uint block_dim_x,
                        mx_uint block_dim_y, mx_uint block_dim_z,
                        mx_uint shared_mem) {
  (void)shared_mem;
  PyGILState_STATE gil = EnsurePython();
  // the tuple handle is (kernel, is_ndarray, dtype_codes); its second
  // element tells how many args the call takes
  PyObject* tup = static_cast<PyObject*>(handle);
  Py_ssize_t n_args = PyList_Size(PyTuple_GetItem(tup, 1));
  PyObject* addrs = PyList_New(n_args);
  for (Py_ssize_t i = 0; i < n_args; ++i)
    PyList_SetItem(addrs, i,
                   PyLong_FromLongLong(static_cast<long long>(
                       reinterpret_cast<intptr_t>(args[i]))));
  PyObject* a = Py_BuildValue("(OiOIIIIII)", tup, dev_id, addrs,
                              grid_dim_x, grid_dim_y, grid_dim_z,
                              block_dim_x, block_dim_y, block_dim_z);
  Py_DECREF(addrs);
  PyGILState_Release(gil);
  return CallVoid("rtc_kernel_call", a);
}

// ---- custom ops (documented divergence) -----------------------------------

// The reference's C callback protocol (MXCallbackList with per-op
// forward/backward/infer function pointers) exists to run custom code
// inside its C++ engine. Here custom operators are a PYTHON surface
// (mxnet_tpu.operator CustomOp/CustomOpProp) running under the same
// executor as every other op; the C entry points report that clearly
// instead of half-implementing an engine that does not exist.
int MXCustomOpRegister(const char* op_type, void* creator) {
  (void)creator;
  mxtpu_last_error =
      std::string("MXCustomOpRegister: C-callback custom ops are not "
                  "supported on the TPU backend; register op '") +
      (op_type ? op_type : "?") +
      "' through the Python CustomOp API (mxnet_tpu.operator.register) "
      "— see PARITY.md 'known deliberate divergences'";
  return -1;
}

int MXCustomFunctionRecord(int num_inputs, NDArrayHandle* inputs,
                           int num_outputs, NDArrayHandle* outputs,
                           void* callbacks) {
  (void)num_inputs; (void)inputs; (void)num_outputs; (void)outputs;
  (void)callbacks;
  mxtpu_last_error =
      "MXCustomFunctionRecord: C-callback autograd functions are not "
      "supported on the TPU backend; use autograd.Function in Python "
      "(mxnet_tpu.autograd) — see PARITY.md";
  return -1;
}

// ---- shared-memory transport ----------------------------------------------

int MXNDArrayGetSharedMemHandle(NDArrayHandle handle, int* shared_pid,
                                int* shared_id) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("ndarray_get_shared_mem_handle",
                         Py_BuildValue("(O)",
                                       static_cast<PyObject*>(handle)));
  int rc = -1;
  if (r != nullptr) {
    *shared_pid = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 0)));
    *shared_id = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(r, 1)));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayCreateFromSharedMem(int shared_pid, int shared_id,
                                 const mx_uint* shape, mx_uint ndim,
                                 int dtype, NDArrayHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* shp = UIntList(shape, ndim);
  PyObject* a = Py_BuildValue("(iiOi)", shared_pid, shared_id, shp, dtype);
  Py_DECREF(shp);
  PyGILState_Release(gil);
  return CallHandle("ndarray_create_from_shared_mem", a, out);
}


int MXSymbolGrad(SymbolHandle sym, mx_uint num_wrt, const char** wrt,
                 SymbolHandle* out) {
  (void)sym; (void)num_wrt; (void)wrt; (void)out;
  // the reference's own implementation is LOG(FATAL) << "not
  // implemented" (c_api_symbolic.cc:564-568); same contract here
  mxtpu_last_error = "MXSymbolGrad: not implemented (the reference "
                     "raises the same; use executor backward or "
                     "MXAutogradBackward)";
  return -1;
}

}  // extern "C"
