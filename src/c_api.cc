// General C API — the language-binding ABI.
//
// TPU-native re-design of the reference's src/c_api/{c_api.cc,
// c_api_ndarray.cc,c_api_symbolic.cc,c_api_executor.cc} slice of the
// 159-function MXNET_DLL surface (include/mxnet/c_api.h) that powers
// cpp-package/scala/R/perl frontends. Same design as c_predict_api.cc:
// the library embeds CPython and drives the framework's own executor
// through mxnet_tpu/c_api_impl.py, so a C driver trains/infers on the
// exact XLA path Python users run. Handles are owned PyObject* of the
// framework objects.
//
// Exported surface (reference names and call shapes):
//   MXGetLastError, MXNDArrayCreate/CreateEx/Free,
//   MXNDArraySyncCopyFromCPU/SyncCopyToCPU, MXNDArrayGetShape/GetDType,
//   MXNDArrayWaitToRead/WaitToWrite/WaitAll, MXNDArraySave/Load,
//   MXListAllOpNames, NNGetOpHandle, MXImperativeInvoke,
//   MXSymbolCreateFromJSON/CreateFromFile/Free,
//   MXSymbolListArguments/ListOutputs/ListAuxiliaryStates,
//   MXSymbolInferShape, MXExecutorBind/Forward/Backward/Outputs/Free.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>
#include <vector>

#include "embed_common.h"

typedef unsigned int mx_uint;
typedef void* NDArrayHandle;
typedef void* SymbolHandle;
typedef void* ExecutorHandle;
typedef void* AtomicSymbolCreator;

namespace {

// String/shape buffers whose pointers we hand out must stay alive until
// the next API call on the same thread (the reference uses thread-local
// return buffers, c_api.h "callee keeps ownership").
thread_local std::vector<std::string> g_str_store;
thread_local std::vector<const char*> g_str_ptrs;
thread_local std::vector<mx_uint> g_shape_buf;
thread_local std::vector<std::vector<mx_uint>> g_shape_store;
thread_local std::vector<const mx_uint*> g_shape_ptrs;
thread_local std::vector<mx_uint> g_ndim_buf;
thread_local std::vector<void*> g_handle_buf;

PyGILState_STATE EnsurePython() { return MXTPUEnsurePython(); }

PyObject* Impl() {
  static PyObject* mod = nullptr;
  if (mod == nullptr) {
    mod = PyImport_ImportModule("mxnet_tpu.c_api_impl");
  }
  return mod;
}

void CaptureError() { MXTPUCaptureError(); }

// Call impl helper `name` with pre-built args tuple; returns new ref or
// nullptr with g_last_error set.
PyObject* CallImpl(const char* name, PyObject* args) {
  PyObject* mod = Impl();
  if (mod == nullptr) {
    CaptureError();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(mod, name);
  if (fn == nullptr) {
    CaptureError();
    Py_XDECREF(args);
    return nullptr;
  }
  PyObject* res = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  Py_XDECREF(args);
  if (res == nullptr) CaptureError();
  return res;
}

PyObject* StrList(const char** arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i)
    PyList_SetItem(lst, i, PyUnicode_FromString(arr[i] ? arr[i] : ""));
  return lst;
}

PyObject* HandleList(NDArrayHandle* arr, mx_uint n) {
  PyObject* lst = PyList_New(n);
  for (mx_uint i = 0; i < n; ++i) {
    PyObject* o = arr && arr[i] ? static_cast<PyObject*>(arr[i]) : Py_None;
    Py_INCREF(o);
    PyList_SetItem(lst, i, o);
  }
  return lst;
}

// Store a python list of str into thread-local storage; out gets char**.
int ReturnStrList(PyObject* lst, mx_uint* out_size, const char*** out_array) {
  Py_ssize_t n = PyList_Size(lst);
  g_str_store.clear();
  g_str_ptrs.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    const char* c = PyUnicode_AsUTF8(PyList_GetItem(lst, i));
    g_str_store.emplace_back(c ? c : "");
  }
  for (auto& s : g_str_store) g_str_ptrs.push_back(s.c_str());
  *out_size = static_cast<mx_uint>(n);
  *out_array = g_str_ptrs.data();
  return 0;
}

int ReturnHandleList(PyObject* lst, mx_uint* out_size,
                     NDArrayHandle** out_array) {
  Py_ssize_t n = PyList_Size(lst);
  g_handle_buf.clear();
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GetItem(lst, i);
    Py_INCREF(o);  // handle owns a reference; freed by MXNDArrayFree
    g_handle_buf.push_back(o);
  }
  *out_size = static_cast<mx_uint>(n);
  *out_array = g_handle_buf.data();
  return 0;
}

}  // namespace

extern "C" {

// MXGetLastError is exported by embed_common.cc

// ---- NDArray --------------------------------------------------------------

int MXNDArrayCreateEx(const mx_uint* shape, mx_uint ndim, int dev_type,
                      int dev_id, int delay_alloc, int dtype,
                      NDArrayHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* shp = PyList_New(ndim);
  for (mx_uint i = 0; i < ndim; ++i)
    PyList_SetItem(shp, i, PyLong_FromUnsignedLong(shape[i]));
  PyObject* args = Py_BuildValue("(Oiiii)", shp, dev_type, dev_id,
                                 delay_alloc, dtype);
  Py_DECREF(shp);
  PyObject* nd = CallImpl("ndarray_create", args);
  int rc = -1;
  if (nd != nullptr) {
    *out = nd;  // transfer ownership to the handle
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayCreate(const mx_uint* shape, mx_uint ndim, int dev_type,
                    int dev_id, int delay_alloc, NDArrayHandle* out) {
  return MXNDArrayCreateEx(shape, ndim, dev_type, dev_id, delay_alloc, 0,
                           out);
}

int MXNDArrayFree(NDArrayHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

int MXNDArraySyncCopyFromCPU(NDArrayHandle handle, const void* data,
                             size_t size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue(
      "(OLn)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(data)),
      static_cast<Py_ssize_t>(size));
  PyObject* r = CallImpl("ndarray_sync_copy_from", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySyncCopyToCPU(NDArrayHandle handle, void* data, size_t size) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue(
      "(OLn)", static_cast<PyObject*>(handle),
      static_cast<long long>(reinterpret_cast<intptr_t>(data)),
      static_cast<Py_ssize_t>(size));
  PyObject* r = CallImpl("ndarray_sync_copy_to", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayGetShape(NDArrayHandle handle, mx_uint* out_dim,
                      const mx_uint** out_pdata) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* shp = CallImpl("ndarray_shape", args);
  if (shp == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  Py_ssize_t n = PyList_Size(shp);
  g_shape_buf.resize(n);
  for (Py_ssize_t i = 0; i < n; ++i)
    g_shape_buf[i] =
        static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(shp, i)));
  Py_DECREF(shp);
  *out_dim = static_cast<mx_uint>(n);
  *out_pdata = g_shape_buf.data();
  PyGILState_Release(gil);
  return 0;
}

int MXNDArrayGetDType(NDArrayHandle handle, int* out_dtype) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("ndarray_dtype", args);
  int rc = -1;
  if (r != nullptr) {
    *out_dtype = static_cast<int>(PyLong_AsLong(r));
    Py_DECREF(r);
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayWaitToRead(NDArrayHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("ndarray_wait", args);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayWaitToWrite(NDArrayHandle handle) {
  return MXNDArrayWaitToRead(handle);
}

int MXNDArrayWaitAll() {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("wait_all", PyTuple_New(0));
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArraySave(const char* fname, mx_uint num_args, NDArrayHandle* args,
                  const char** keys) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* nds = HandleList(args, num_args);
  PyObject* ks = keys != nullptr ? StrList(keys, num_args) : PyList_New(0);
  PyObject* a = Py_BuildValue("(sOO)", fname, nds, ks);
  Py_DECREF(nds);
  Py_DECREF(ks);
  PyObject* r = CallImpl("ndarray_save", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXNDArrayLoad(const char* fname, mx_uint* out_size,
                  NDArrayHandle** out_arr, mx_uint* out_name_size,
                  const char*** out_names) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(s)", fname);
  PyObject* r = CallImpl("ndarray_load", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  PyObject* nds = PyTuple_GetItem(r, 0);
  PyObject* names = PyTuple_GetItem(r, 1);
  ReturnHandleList(nds, out_size, out_arr);
  ReturnStrList(names, out_name_size, out_names);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- operators ------------------------------------------------------------

int MXListAllOpNames(mx_uint* out_size, const char*** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("op_names", PyTuple_New(0));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnStrList(r, out_size, out_array);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// Op handles are name strings validated against the registry (the
// reference hands out nnvm::Op* and errors on unknown names).
int NNGetOpHandle(const char* name, AtomicSymbolCreator* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("op_exists", Py_BuildValue("(s)", name));
  int rc = -1;
  if (r != nullptr) {
    if (PyObject_IsTrue(r)) {
      *out = new std::string(name);  // leaked by design: handles live forever
      rc = 0;
    } else {
      mxtpu_last_error = std::string("operator not registered: ") + name;
    }
    Py_DECREF(r);
  }
  PyGILState_Release(gil);
  return rc;
}

int MXImperativeInvoke(AtomicSymbolCreator creator, int num_inputs,
                       NDArrayHandle* inputs, int* num_outputs,
                       NDArrayHandle** outputs, int num_params,
                       const char** param_keys, const char** param_vals) {
  PyGILState_STATE gil = EnsurePython();
  std::string* name = static_cast<std::string*>(creator);
  PyObject* ins = HandleList(inputs, num_inputs);
  PyObject* keys = StrList(param_keys, num_params);
  PyObject* vals = StrList(param_vals, num_params);
  PyObject* outs;
  if (*num_outputs > 0 && *outputs != nullptr) {
    outs = HandleList(*outputs, *num_outputs);
  } else {
    outs = Py_None;
    Py_INCREF(Py_None);
  }
  PyObject* a = Py_BuildValue("(sOOOO)", name->c_str(), ins, keys, vals,
                              outs);
  Py_DECREF(ins);
  Py_DECREF(keys);
  Py_DECREF(vals);
  Py_DECREF(outs);
  PyObject* r = CallImpl("imperative_invoke", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  if (*num_outputs <= 0 || *outputs == nullptr) {
    mx_uint n = 0;
    ReturnHandleList(r, &n, outputs);
    *num_outputs = static_cast<int>(n);
  }
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- symbols --------------------------------------------------------------

int MXSymbolCreateFromJSON(const char* json, SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("symbol_from_json", Py_BuildValue("(s)", json));
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolCreateFromFile(const char* fname, SymbolHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl("symbol_from_file", Py_BuildValue("(s)", fname));
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXSymbolFree(SymbolHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

static int SymStrList(SymbolHandle sym, const char* fn, mx_uint* out_size,
                      const char*** out_array) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* r = CallImpl(fn, Py_BuildValue("(O)",
                                           static_cast<PyObject*>(sym)));
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnStrList(r, out_size, out_array);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXSymbolListArguments(SymbolHandle sym, mx_uint* out_size,
                          const char*** out_array) {
  return SymStrList(sym, "symbol_arguments", out_size, out_array);
}

int MXSymbolListOutputs(SymbolHandle sym, mx_uint* out_size,
                        const char*** out_array) {
  return SymStrList(sym, "symbol_outputs", out_size, out_array);
}

int MXSymbolListAuxiliaryStates(SymbolHandle sym, mx_uint* out_size,
                                const char*** out_array) {
  return SymStrList(sym, "symbol_aux", out_size, out_array);
}

int MXSymbolInferShape(SymbolHandle sym, mx_uint num_args, const char** keys,
                       const mx_uint* arg_ind_ptr, const mx_uint* arg_shape_data,
                       mx_uint* in_shape_size, const mx_uint** in_shape_ndim,
                       const mx_uint*** in_shape_data, mx_uint* out_shape_size,
                       const mx_uint** out_shape_ndim,
                       const mx_uint*** out_shape_data, mx_uint* aux_shape_size,
                       const mx_uint** aux_shape_ndim,
                       const mx_uint*** aux_shape_data, int* complete) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* names = StrList(keys, num_args);
  PyObject* shapes = PyList_New(num_args);
  for (mx_uint i = 0; i < num_args; ++i) {
    mx_uint lo = arg_ind_ptr[i], hi = arg_ind_ptr[i + 1];
    PyObject* s = PyList_New(hi - lo);
    for (mx_uint j = lo; j < hi; ++j)
      PyList_SetItem(s, j - lo, PyLong_FromUnsignedLong(arg_shape_data[j]));
    PyList_SetItem(shapes, i, s);
  }
  PyObject* a = Py_BuildValue("(OOO)", static_cast<PyObject*>(sym), names,
                              shapes);
  Py_DECREF(names);
  Py_DECREF(shapes);
  PyObject* r = CallImpl("symbol_infer_shape", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  // unpack three shape-list groups into thread-local storage
  g_shape_store.clear();
  g_shape_ptrs.clear();
  g_ndim_buf.clear();
  mx_uint sizes[3];
  size_t offsets[4] = {0, 0, 0, 0};
  for (int grp = 0; grp < 3; ++grp) {
    PyObject* lst = PyTuple_GetItem(r, grp);
    Py_ssize_t n = PyList_Size(lst);
    sizes[grp] = static_cast<mx_uint>(n);
    for (Py_ssize_t i = 0; i < n; ++i) {
      PyObject* s = PyList_GetItem(lst, i);
      Py_ssize_t nd = PyList_Size(s);
      std::vector<mx_uint> v(nd);
      for (Py_ssize_t j = 0; j < nd; ++j)
        v[j] = static_cast<mx_uint>(PyLong_AsLong(PyList_GetItem(s, j)));
      g_shape_store.push_back(std::move(v));
      g_ndim_buf.push_back(static_cast<mx_uint>(nd));
    }
    offsets[grp + 1] = g_shape_store.size();
  }
  for (auto& v : g_shape_store) g_shape_ptrs.push_back(v.data());
  *in_shape_size = sizes[0];
  *in_shape_ndim = g_ndim_buf.data() + offsets[0];
  *in_shape_data = g_shape_ptrs.data() + offsets[0];
  *out_shape_size = sizes[1];
  *out_shape_ndim = g_ndim_buf.data() + offsets[1];
  *out_shape_data = g_shape_ptrs.data() + offsets[1];
  *aux_shape_size = sizes[2];
  *aux_shape_ndim = g_ndim_buf.data() + offsets[2];
  *aux_shape_data = g_shape_ptrs.data() + offsets[2];
  *complete = PyObject_IsTrue(PyTuple_GetItem(r, 3));
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

// ---- executor ---------------------------------------------------------------

int MXExecutorBind(SymbolHandle sym, int dev_type, int dev_id, mx_uint len,
                   NDArrayHandle* in_args, NDArrayHandle* arg_grad_store,
                   mx_uint* grad_req_type, mx_uint aux_states_len,
                   NDArrayHandle* aux_states, ExecutorHandle* out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* args = HandleList(in_args, len);
  PyObject* grads = HandleList(arg_grad_store, len);
  PyObject* reqs = PyList_New(len);
  for (mx_uint i = 0; i < len; ++i)
    PyList_SetItem(reqs, i, PyLong_FromUnsignedLong(grad_req_type[i]));
  PyObject* aux = HandleList(aux_states, aux_states_len);
  PyObject* a = Py_BuildValue("(OiiOOOO)", static_cast<PyObject*>(sym),
                              dev_type, dev_id, args, grads, reqs, aux);
  Py_DECREF(args);
  Py_DECREF(grads);
  Py_DECREF(reqs);
  Py_DECREF(aux);
  PyObject* r = CallImpl("executor_bind", a);
  int rc = -1;
  if (r != nullptr) {
    *out = r;
    rc = 0;
  }
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorForward(ExecutorHandle handle, int is_train) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(Oi)", static_cast<PyObject*>(handle),
                              is_train);
  PyObject* r = CallImpl("executor_forward", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorBackward(ExecutorHandle handle, mx_uint len,
                       NDArrayHandle* head_grads) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* hg = HandleList(head_grads, len);
  PyObject* a = Py_BuildValue("(OO)", static_cast<PyObject*>(handle), hg);
  Py_DECREF(hg);
  PyObject* r = CallImpl("executor_backward", a);
  int rc = r != nullptr ? 0 : -1;
  Py_XDECREF(r);
  PyGILState_Release(gil);
  return rc;
}

int MXExecutorOutputs(ExecutorHandle handle, mx_uint* out_size,
                      NDArrayHandle** out) {
  PyGILState_STATE gil = EnsurePython();
  PyObject* a = Py_BuildValue("(O)", static_cast<PyObject*>(handle));
  PyObject* r = CallImpl("executor_outputs", a);
  if (r == nullptr) {
    PyGILState_Release(gil);
    return -1;
  }
  ReturnHandleList(r, out_size, out);
  Py_DECREF(r);
  PyGILState_Release(gil);
  return 0;
}

int MXExecutorFree(ExecutorHandle handle) {
  PyGILState_STATE gil = EnsurePython();
  Py_XDECREF(static_cast<PyObject*>(handle));
  PyGILState_Release(gil);
  return 0;
}

}  // extern "C"
