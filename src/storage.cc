// Native host-side storage manager: size-bucketed pooled allocator.
//
// TPU-native re-design of the reference's storage layer
// (src/storage/storage.cc dispatch + pooled_storage_manager.h:48-132's
// GPUPooledStorageManager: a free-list over cudaMalloc keyed by rounded
// size, with an environment-controlled reserve). On TPU the device (HBM)
// side is owned by the PJRT allocator, so the native pool manages the
// HOST staging side: batch-assembly and IO buffers that are written by
// C++/Python producers and then DMA'd to the device. Buckets are
// power-of-two from 4 KB; freed buffers park in the pool until the pooled
// total exceeds MXNET_HOST_MEM_POOL_MB (then they release to the OS),
// mirroring MXNET_GPU_MEM_POOL_RESERVE's role.
//
// C ABI (ctypes-bound in mxnet_tpu/storage.py; pure-Python fallback
// exists, the library is optional):
//   sto_alloc / sto_free / sto_direct_free / sto_stats / sto_release_all

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <map>
#include <mutex>

namespace {

struct Pool {
  std::mutex mu;
  // rounded bucket size -> parked buffer (multimap: many per bucket)
  std::multimap<size_t, void*> free_list;
  size_t allocated_bytes = 0;  // currently handed out
  size_t pooled_bytes = 0;     // parked in the free list
  size_t peak_bytes = 0;       // high-water mark of handed-out bytes
  size_t pool_limit;

  Pool() {
    const char* env = std::getenv("MXNET_HOST_MEM_POOL_MB");
    long mb = env ? std::atol(env) : 1024;
    pool_limit = static_cast<size_t>(mb < 0 ? 0 : mb) << 20;
  }

  static size_t RoundSize(size_t nbytes) {
    size_t b = 4096;
    while (b < nbytes) b <<= 1;
    return b;
  }
};

Pool* pool() {
  static Pool* p = new Pool();  // leaked intentionally: outlive atexit
  return p;
}

}  // namespace

extern "C" {

void* sto_alloc(size_t nbytes) {
  Pool* p = pool();
  size_t bucket = Pool::RoundSize(nbytes);
  {
    std::lock_guard<std::mutex> lk(p->mu);
    auto it = p->free_list.find(bucket);
    if (it != p->free_list.end()) {
      void* buf = it->second;
      p->free_list.erase(it);
      p->pooled_bytes -= bucket;
      p->allocated_bytes += bucket;
      if (p->allocated_bytes > p->peak_bytes)
        p->peak_bytes = p->allocated_bytes;
      return buf;
    }
  }
  void* buf = std::aligned_alloc(64, bucket);
  if (buf == nullptr) return nullptr;
  std::lock_guard<std::mutex> lk(p->mu);
  p->allocated_bytes += bucket;
  if (p->allocated_bytes > p->peak_bytes) p->peak_bytes = p->allocated_bytes;
  return buf;
}

// Return a buffer to the pool (or the OS once the pool is over its limit).
void sto_free(void* buf, size_t nbytes) {
  if (buf == nullptr) return;
  Pool* p = pool();
  size_t bucket = Pool::RoundSize(nbytes);
  std::lock_guard<std::mutex> lk(p->mu);
  p->allocated_bytes -= bucket;
  if (p->pooled_bytes + bucket > p->pool_limit) {
    std::free(buf);
    return;
  }
  p->free_list.emplace(bucket, buf);
  p->pooled_bytes += bucket;
}

// Bypass the pool (parity: Storage::DirectFree).
void sto_direct_free(void* buf, size_t nbytes) {
  if (buf == nullptr) return;
  Pool* p = pool();
  std::lock_guard<std::mutex> lk(p->mu);
  p->allocated_bytes -= Pool::RoundSize(nbytes);
  std::free(buf);
}

void sto_stats(size_t* allocated, size_t* pooled, size_t* peak) {
  Pool* p = pool();
  std::lock_guard<std::mutex> lk(p->mu);
  if (allocated) *allocated = p->allocated_bytes;
  if (pooled) *pooled = p->pooled_bytes;
  if (peak) *peak = p->peak_bytes;
}

// Drop every parked buffer (parity: ReleaseAll on shutdown/OOM retry).
void sto_release_all() {
  Pool* p = pool();
  std::lock_guard<std::mutex> lk(p->mu);
  for (auto& kv : p->free_list) std::free(kv.second);
  p->free_list.clear();
  p->pooled_bytes = 0;
}

}  // extern "C"
