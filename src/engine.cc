// Native dependency engine for host-side async work.
//
// TPU-native re-design of the reference's threaded dataflow engine
// (src/engine/threaded_engine.h:66-217 ThreadedVar/OprBlock;
// threaded_engine_perdevice.cc worker pools). On TPU the *device* stream
// is scheduled by PJRT/XLA, so this engine schedules the HOST side of the
// runtime: RecordIO prefetch, image augmentation, async checkpoint
// writes, metric flushes — anything the reference pushed as CPU engine
// ops. Semantics match the reference: every op declares read (const) and
// write (mutable) variable sets; per-variable versioned queues grant
// concurrent readers / exclusive writers in push order; WaitForVar blocks
// until all prior writers of that var completed; WaitForAll drains.
//
// Exposed as a flat C ABI (parity: the engine slice of
// include/mxnet/c_api.h) consumed by mxnet_tpu/engine.py over ctypes.

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <deque>
#include <functional>
#include <mutex>
#include <queue>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

using Callback = void (*)(void*);

struct Opr;

// Per-variable access queue: readers run concurrently, writers are
// exclusive and ordered (reference ThreadedVar / VersionedVarBlock).
struct Var {
  struct Pending {
    Opr* op;
    bool write;
  };
  std::deque<Pending> queue;
  int active_readers = 0;
  bool active_writer = false;
};

struct Opr {
  Callback fn;
  void* arg;
  std::vector<int64_t> const_vars;
  std::vector<int64_t> mutable_vars;
  std::atomic<int> wait{0};
  int priority = 0;
};

class Engine {
 public:
  explicit Engine(int num_workers, bool naive)
      : naive_(naive) {
    if (naive_) return;
    if (num_workers < 1) num_workers = 1;
    for (int i = 0; i < num_workers; ++i) {
      workers_.emplace_back([this] { WorkerLoop(); });
    }
  }

  ~Engine() {
    WaitAll();
    {
      std::unique_lock<std::mutex> lk(mu_);
      shutdown_ = true;
      cv_.notify_all();
    }
    for (auto& t : workers_) t.join();
  }

  int64_t NewVar() {
    std::unique_lock<std::mutex> lk(mu_);
    int64_t id = next_var_++;
    vars_.emplace(id, Var{});
    return id;
  }

  void DeleteVar(int64_t id) {
    // Deletion is itself ordered: drop the var once all pending ops on it
    // completed (reference Engine::DeleteVariable pushes a delete op).
    std::unique_lock<std::mutex> lk(mu_);
    auto it = vars_.find(id);
    if (it != vars_.end() && it->second.queue.empty() &&
        it->second.active_readers == 0 && !it->second.active_writer) {
      vars_.erase(it);
    } else if (it != vars_.end()) {
      doomed_vars_.push_back(id);
    }
  }

  void Push(Callback fn, void* arg, const int64_t* cvars, int n_c,
            const int64_t* mvars, int n_m, int priority) {
    if (naive_) {
      fn(arg);  // reference NaiveEngine: run synchronously in caller
      return;
    }
    Opr* op = new Opr;
    op->fn = fn;
    op->arg = arg;
    op->const_vars.assign(cvars, cvars + n_c);
    op->mutable_vars.assign(mvars, mvars + n_m);
    op->priority = priority;
    // +1 sentinel so the op cannot fire while we are still enqueueing it
    op->wait.store(1 + n_c + n_m, std::memory_order_relaxed);
    {
      std::unique_lock<std::mutex> lk(mu_);
      ++outstanding_;
      for (int64_t v : op->const_vars) EnqueueAccess(v, op, /*write=*/false);
      for (int64_t v : op->mutable_vars) EnqueueAccess(v, op, /*write=*/true);
    }
    Satisfy(op, 1);  // drop sentinel
  }

  void WaitForVar(int64_t var) {
    // Equivalent to pushing a read op and blocking on it
    // (reference ThreadedEngine::WaitForVar, threaded_engine.cc:356).
    std::mutex m;
    std::condition_variable done_cv;
    bool done = false;
    struct Ctx { std::mutex* m; std::condition_variable* cv; bool* done; };
    Ctx ctx{&m, &done_cv, &done};
    auto cb = [](void* p) {
      Ctx* c = static_cast<Ctx*>(p);
      std::unique_lock<std::mutex> lk(*c->m);
      *c->done = true;
      c->cv->notify_all();
    };
    Push(cb, &ctx, &var, 1, nullptr, 0, /*priority=*/1);
    std::unique_lock<std::mutex> lk(m);
    done_cv.wait(lk, [&] { return done; });
  }

  void WaitAll() {
    std::unique_lock<std::mutex> lk(mu_);
    all_done_cv_.wait(lk, [this] { return outstanding_ == 0; });
  }

 private:
  // mu_ held.
  void EnqueueAccess(int64_t vid, Opr* op, bool write) {
    Var& v = vars_[vid];
    v.queue.push_back({op, write});
    GrantLocked(vid);
  }

  // mu_ held. Advance the var's queue, granting permitted accessors.
  // Collect ops whose wait hits zero into ready_ for dispatch.
  void GrantLocked(int64_t vid) {
    Var& v = vars_[vid];
    while (!v.queue.empty()) {
      Var::Pending front = v.queue.front();
      if (front.write) {
        if (v.active_readers == 0 && !v.active_writer) {
          v.active_writer = true;
          v.queue.pop_front();
          SatisfyLocked(front.op, 1);
        }
        break;  // writer is exclusive; later accessors wait
      }
      if (v.active_writer) break;
      ++v.active_readers;
      v.queue.pop_front();
      SatisfyLocked(front.op, 1);
      // loop: grant consecutive readers
    }
  }

  // mu_ held: move op to ready queue when its wait count drains.
  void SatisfyLocked(Opr* op, int n) {
    if (op->wait.fetch_sub(n, std::memory_order_acq_rel) == n) {
      ready_.push_back(op);
      cv_.notify_one();
    }
  }

  void Satisfy(Opr* op, int n) {
    std::unique_lock<std::mutex> lk(mu_);
    SatisfyLocked(op, n);
  }

  void WorkerLoop() {
    for (;;) {
      Opr* op = nullptr;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_.wait(lk, [this] { return shutdown_ || !ready_.empty(); });
        if (shutdown_ && ready_.empty()) return;
        // priority: scan a small window for a high-priority op
        // (reference keeps a separate priority queue for CPU ops)
        size_t pick = 0;
        for (size_t i = 0; i < ready_.size() && i < 8; ++i) {
          if (ready_[i]->priority > ready_[pick]->priority) pick = i;
        }
        op = ready_[pick];
        ready_.erase(ready_.begin() + pick);
      }
      op->fn(op->arg);
      Complete(op);
    }
  }

  void Complete(Opr* op) {
    std::unique_lock<std::mutex> lk(mu_);
    for (int64_t vid : op->const_vars) {
      auto it = vars_.find(vid);
      if (it == vars_.end()) continue;
      --it->second.active_readers;
      GrantLocked(vid);
    }
    for (int64_t vid : op->mutable_vars) {
      auto it = vars_.find(vid);
      if (it == vars_.end()) continue;
      it->second.active_writer = false;
      GrantLocked(vid);
    }
    ReapDoomedLocked();
    delete op;
    if (--outstanding_ == 0) all_done_cv_.notify_all();
  }

  // mu_ held: erase vars whose deletion was deferred until quiescent.
  void ReapDoomedLocked() {
    for (auto it = doomed_vars_.begin(); it != doomed_vars_.end();) {
      auto vit = vars_.find(*it);
      if (vit == vars_.end() || (vit->second.queue.empty() &&
                                 vit->second.active_readers == 0 &&
                                 !vit->second.active_writer)) {
        if (vit != vars_.end()) vars_.erase(vit);
        it = doomed_vars_.erase(it);
      } else {
        ++it;
      }
    }
  }

  bool naive_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::condition_variable all_done_cv_;
  std::vector<std::thread> workers_;
  std::unordered_map<int64_t, Var> vars_;
  std::vector<int64_t> doomed_vars_;
  std::vector<Opr*> ready_;
  int64_t next_var_ = 1;
  int64_t outstanding_ = 0;
  bool shutdown_ = false;
};

}  // namespace

extern "C" {

void* eng_create(int num_workers, int naive) {
  return new Engine(num_workers, naive != 0);
}

void eng_destroy(void* h) { delete static_cast<Engine*>(h); }

int64_t eng_new_var(void* h) { return static_cast<Engine*>(h)->NewVar(); }

void eng_delete_var(void* h, int64_t v) {
  static_cast<Engine*>(h)->DeleteVar(v);
}

void eng_push(void* h, void (*fn)(void*), void* arg, const int64_t* cvars,
              int n_c, const int64_t* mvars, int n_m, int priority) {
  static_cast<Engine*>(h)->Push(fn, arg, cvars, n_c, mvars, n_m, priority);
}

void eng_wait_for_var(void* h, int64_t v) {
  static_cast<Engine*>(h)->WaitForVar(v);
}

void eng_wait_all(void* h) { static_cast<Engine*>(h)->WaitAll(); }

}  // extern "C"
