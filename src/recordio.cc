// Native RecordIO reader + threaded batch loader.
//
// TPU-native equivalent of the reference's C++ IO pipeline
// (src/io/iter_image_recordio_2.cc: multithreaded decode feeding a
// prefetch queue, over dmlc-core RecordIO). The binary format matches
// recordio.py (and dmlc): per record a LE uint32 magic 0xced7230a, a
// uint32 whose low 29 bits are the payload length, payload, 4-byte
// padding. Payload = IRHeader{uint32 flag; float label; uint64 id,id2}
// + raw uint8 CHW image tensor.
//
// Exposed as a flat C ABI consumed via ctypes (mxnet_tpu/_native.py);
// the Python fallback path implements identical semantics.

#include <algorithm>
#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <queue>
#include <random>
#include <thread>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct IRHeader {
  uint32_t flag;
  float label;
  uint64_t id;
  uint64_t id2;
};

struct Record {
  float label;
  std::vector<uint8_t> payload;  // image bytes (after header)
};

struct Batch {
  std::vector<float> data;    // batch*C*H*W normalised floats
  std::vector<float> label;   // batch
};

class RecordFile {
 public:
  bool Load(const char* path) {
    FILE* f = std::fopen(path, "rb");
    if (!f) return false;
    for (;;) {
      uint32_t magic = 0, lrec = 0;
      if (std::fread(&magic, 4, 1, f) != 1) break;
      if (magic != kMagic) { std::fclose(f); return false; }
      if (std::fread(&lrec, 4, 1, f) != 1) { std::fclose(f); return false; }
      uint32_t len = lrec & kLenMask;
      std::vector<uint8_t> buf(len);
      if (len && std::fread(buf.data(), 1, len, f) != len) {
        std::fclose(f);
        return false;
      }
      uint32_t pad = (4 - len % 4) % 4;
      if (pad) std::fseek(f, pad, SEEK_CUR);
      if (len < sizeof(IRHeader)) continue;
      IRHeader hdr;
      std::memcpy(&hdr, buf.data(), sizeof(IRHeader));
      Record rec;
      rec.label = hdr.label;
      rec.payload.assign(buf.begin() + sizeof(IRHeader), buf.end());
      records_.push_back(std::move(rec));
    }
    std::fclose(f);
    return true;
  }

  const std::vector<Record>& records() const { return records_; }

 private:
  std::vector<Record> records_;
};

// Threaded batch assembler: worker threads build batches ahead of the
// consumer (the reference's PrefetcherIter double-buffering).
class BatchLoader {
 public:
  BatchLoader(RecordFile* file, int batch, int c, int h, int w, int threads,
              bool shuffle, uint64_t seed, float scale, const float* mean,
              const float* std)
      : file_(file), batch_(batch), c_(c), h_(h), w_(w),
        shuffle_(shuffle), rng_(seed), scale_(scale), stop_(false),
        epoch_pos_(0) {
    std::memcpy(mean_, mean, sizeof(float) * 3);
    std::memcpy(std_, std, sizeof(float) * 3);
    order_.resize(file_->records().size());
    for (size_t i = 0; i < order_.size(); ++i) order_[i] = i;
    Reshuffle();
    n_batches_ = order_.size() / batch_;
    int nthreads = threads > 0 ? threads : 2;
    for (int i = 0; i < nthreads; ++i)
      workers_.emplace_back([this] { WorkLoop(); });
  }

  ~BatchLoader() {
    {
      std::lock_guard<std::mutex> lk(mu_);
      stop_ = true;
    }
    cv_work_.notify_all();
    cv_out_.notify_all();
    for (auto& t : workers_) t.join();
  }

  size_t num_batches() const { return n_batches_; }

  // Blocks until the next in-order batch is ready; returns false at epoch
  // end. Caller provides float[batch*c*h*w] and float[batch].
  bool Next(float* data_out, float* label_out) {
    std::unique_lock<std::mutex> lk(mu_);
    if (next_out_ >= n_batches_) return false;
    size_t want = next_out_;
    cv_out_.wait(lk, [&] { return stop_ || done_.count(want); });
    if (stop_ && !done_.count(want)) return false;
    Batch b = std::move(done_[want]);
    done_.erase(want);
    ++next_out_;
    cv_work_.notify_all();
    lk.unlock();
    std::memcpy(data_out, b.data.data(), b.data.size() * sizeof(float));
    std::memcpy(label_out, b.label.data(), b.label.size() * sizeof(float));
    return true;
  }

  void Reset() {
    std::unique_lock<std::mutex> lk(mu_);
    next_build_ = 0;
    next_out_ = 0;
    done_.clear();
    Reshuffle();
    cv_work_.notify_all();
  }

 private:
  void Reshuffle() {
    if (shuffle_) {
      std::shuffle(order_.begin(), order_.end(), rng_);
    }
  }

  void WorkLoop() {
    const size_t elems = static_cast<size_t>(c_) * h_ * w_;
    for (;;) {
      size_t idx;
      {
        std::unique_lock<std::mutex> lk(mu_);
        cv_work_.wait(lk, [&] {
          return stop_ ||
                 (next_build_ < n_batches_ &&
                  done_.size() + building_ < kMaxPrefetch);
        });
        if (stop_) return;
        if (next_build_ >= n_batches_) {
          cv_work_.wait(lk, [&] { return stop_ || next_build_ < n_batches_; });
          if (stop_) return;
        }
        idx = next_build_++;
        ++building_;
      }
      Batch b;
      b.data.resize(static_cast<size_t>(batch_) * elems);
      b.label.resize(batch_);
      const auto& recs = file_->records();
      for (int i = 0; i < batch_; ++i) {
        size_t ri = order_[idx * batch_ + i];
        const Record& r = recs[ri];
        b.label[i] = r.label;
        float* dst = b.data.data() + static_cast<size_t>(i) * elems;
        size_t n = r.payload.size() < elems ? r.payload.size() : elems;
        for (size_t ch = 0; ch < static_cast<size_t>(c_); ++ch) {
          const float m = mean_[ch % 3];
          const float s = std_[ch % 3];
          const size_t plane = static_cast<size_t>(h_) * w_;
          for (size_t px = 0; px < plane; ++px) {
            size_t off = ch * plane + px;
            float v = off < n ? static_cast<float>(r.payload[off]) : 0.f;
            dst[off] = (v * scale_ - m) / s;
          }
        }
      }
      {
        std::lock_guard<std::mutex> lk(mu_);
        done_[idx] = std::move(b);
        --building_;
      }
      cv_out_.notify_all();
      cv_work_.notify_all();
    }
  }

  static constexpr size_t kMaxPrefetch = 8;

  RecordFile* file_;
  int batch_, c_, h_, w_;
  bool shuffle_;
  std::mt19937_64 rng_;
  float scale_;
  float mean_[3], std_[3];
  std::vector<size_t> order_;
  size_t n_batches_ = 0;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_work_, cv_out_;
  std::map<size_t, Batch> done_;
  size_t next_build_ = 0;
  size_t next_out_ = 0;
  size_t building_ = 0;
  bool stop_;
  size_t epoch_pos_;
};

}  // namespace

extern "C" {

void* rio_open(const char* path) {
  auto* f = new RecordFile();
  if (!f->Load(path)) {
    delete f;
    return nullptr;
  }
  return f;
}

long rio_num_records(void* handle) {
  return static_cast<long>(static_cast<RecordFile*>(handle)->records().size());
}

long rio_record_size(void* handle, long i) {
  return static_cast<long>(
      static_cast<RecordFile*>(handle)->records()[i].payload.size());
}

float rio_record_label(void* handle, long i) {
  return static_cast<RecordFile*>(handle)->records()[i].label;
}

void rio_record_copy(void* handle, long i, uint8_t* out) {
  const auto& p = static_cast<RecordFile*>(handle)->records()[i].payload;
  std::memcpy(out, p.data(), p.size());
}

void rio_close(void* handle) { delete static_cast<RecordFile*>(handle); }

void* loader_create(void* file_handle, int batch, int c, int h, int w,
                    int threads, int shuffle, uint64_t seed, float scale,
                    const float* mean, const float* stdv) {
  return new BatchLoader(static_cast<RecordFile*>(file_handle), batch, c, h,
                         w, threads, shuffle != 0, seed, scale, mean, stdv);
}

long loader_num_batches(void* handle) {
  return static_cast<long>(static_cast<BatchLoader*>(handle)->num_batches());
}

int loader_next(void* handle, float* data_out, float* label_out) {
  return static_cast<BatchLoader*>(handle)->Next(data_out, label_out) ? 1 : 0;
}

void loader_reset(void* handle) { static_cast<BatchLoader*>(handle)->Reset(); }

void loader_destroy(void* handle) { delete static_cast<BatchLoader*>(handle); }

}  // extern "C"
