"""Parameter-shape inference for weight-bearing ops.

Parity: the reference's per-op ``FInferShape`` functors (e.g.
``fully_connected-inl.h`` infers weight=(num_hidden, in_units) from data).
Only ops with learnable inputs need hooks here — everything else gets its
output shape from ``jax.eval_shape`` over the op function, which is the
TPU-native replacement for hand-written inference code.

Each hook: ``(input_shapes, params) -> {input_index: shape}`` filling in
shapes for inputs whose shape is still unknown. input_shapes[0] (data) is
always known by the time the executor calls these (forward topo order).
"""
from __future__ import annotations

import numpy as np

from .common import as_tuple, channels_last
from .registry import get_op


def _fc(shapes, params):
    data = shapes[0]
    num_hidden = int(params.get("num_hidden", 0))
    flatten = params.get("flatten", True)
    in_units = int(np.prod(data[1:])) if flatten else data[-1]
    out = {1: (num_hidden, in_units)}
    if not params.get("no_bias", False):
        out[2] = (num_hidden,)
    return out


def _conv(shapes, params):
    data = shapes[0]
    kernel = as_tuple(params.get("kernel")) or ()
    num_filter = int(params.get("num_filter", 0))
    num_group = int(params.get("num_group", 1))
    if channels_last(params.get("layout"), len(kernel)):
        # channels-last: OHWI weight
        out = {1: (num_filter,) + kernel + (data[-1] // num_group,)}
    else:
        out = {1: (num_filter, data[1] // num_group) + kernel}
    if not params.get("no_bias", False):
        out[2] = (num_filter,)
    return out


def _deconv(shapes, params):
    data = shapes[0]
    kernel = as_tuple(params.get("kernel")) or ()
    num_filter = int(params.get("num_filter", 0))
    num_group = int(params.get("num_group", 1))
    out = {1: (data[1], num_filter // num_group) + kernel}
    if not params.get("no_bias", True):
        out[2] = (num_filter,)
    return out


def _bn(shapes, params):
    c = shapes[0][int(params.get("axis", 1)) % len(shapes[0])]
    return {1: (c,), 2: (c,), 3: (c,), 4: (c,)}


def _bn_add_relu(shapes, params):
    c = shapes[0][int(params.get("axis", 1)) % len(shapes[0])]
    # input 1 is the residual (same shape as data); 2-5 are BN params
    return {1: tuple(shapes[0]), 2: (c,), 3: (c,), 4: (c,), 5: (c,)}


def _instance_norm(shapes, params):
    c = shapes[0][1]
    return {1: (c,), 2: (c,)}


def _layer_norm(shapes, params):
    c = shapes[0][int(params.get("axis", -1)) % len(shapes[0])]
    return {1: (c,), 2: (c,)}


def _embedding(shapes, params):
    return {1: (int(params["input_dim"]), int(params["output_dim"]))}


def _leaky_relu(shapes, params):
    if params.get("act_type", "leaky") == "prelu":
        return {1: (shapes[0][1],)}
    return {}


def _upsampling(shapes, params):
    if params.get("sample_type") == "bilinear":
        scale = int(params.get("scale", 1))
        kernel = 2 * scale - scale % 2
        c = shapes[0][1]
        return {1: (c, 1, kernel, kernel)}
    return {}


def _softmax_output(shapes, params):
    """Label shape from data (reference softmax_output-inl.h InferShape):
    (N,) default, (N, d2, ...) with multi_output."""
    data = shapes[0]
    if params.get("multi_output", False):
        return {1: (data[0],) + tuple(data[2:])}
    return {1: (data[0],)}


def _regression_output(shapes, params):
    return {1: tuple(shapes[0])}


def install():
    get_op("SoftmaxOutput").param_shape_infer = _softmax_output
    get_op("LinearRegressionOutput").param_shape_infer = _regression_output
    get_op("MAERegressionOutput").param_shape_infer = _regression_output
    get_op("LogisticRegressionOutput").param_shape_infer = _regression_output
    get_op("FullyConnected").param_shape_infer = _fc
    get_op("Convolution").param_shape_infer = _conv
    get_op("Deconvolution").param_shape_infer = _deconv
    get_op("BatchNorm").param_shape_infer = _bn
    get_op("BatchNorm_v1").param_shape_infer = _bn
    get_op("_contrib_BatchNormAddReLU").param_shape_infer = _bn_add_relu
    get_op("InstanceNorm").param_shape_infer = _instance_norm
    get_op("LayerNorm").param_shape_infer = _layer_norm
    get_op("Embedding").param_shape_infer = _embedding
    get_op("LeakyReLU").param_shape_infer = _leaky_relu
    get_op("UpSampling").param_shape_infer = _upsampling
