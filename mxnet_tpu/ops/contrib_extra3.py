"""Final op-registry gap closure (round-3 sweep vs the reference registry).

Parity targets:
- _contrib_PSROIPooling            reference src/operator/contrib/psroi_pooling.cc
- _contrib_DeformablePSROIPooling  contrib/deformable_psroi_pooling.cc
- _contrib_MultiProposal           contrib/multi_proposal.cc
- _contrib_count_sketch            contrib/count_sketch.cc
- _contrib_SparseEmbedding         src/operator/tensor/indexing_op.cc
- _linalg_gelqf / _linalg_syevd    src/operator/tensor/la_op.cc:483-601
- reshape_like                     tensor/elemwise_unary_op.cc
- _slice_assign / _slice_assign_scalar  tensor/matrix_op.cc:313-360
- _scatter_set_nd                  tensor/indexing_op.cc:550
- Crop                             src/operator/crop.cc (legacy)
- Convolution_v1 / Pooling_v1 / CuDNNBatchNorm  legacy/cudnn aliases
- _CrossDeviceCopy                 src/operator/cross_device_copy.cc

TPU-first notes: PSROIPooling reduces each bin with two batched einsum
contractions (W then H) against dynamic interval masks — MXU matmuls
instead of the reference's per-output scalar loops; the position-
sensitive channel map is static per (ctop, ph, pw) and becomes one
gather. DeformablePSROIPooling vectorises the sample grid and reuses
the bilinear gather from DeformableConvolution. count_sketch is a
matmul against a one-hot scatter matrix (hash is data-independent).
Gradients fall out of jax.vjp — no hand-written backward kernels.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .common import as_tuple
from .registry import register, get_op, alias
from .contrib_extra import _bilinear_gather


# ---------------------------------------------------------------------------
# PSROIPooling (R-FCN position-sensitive ROI pooling)
# ---------------------------------------------------------------------------

def _ps_channel_index(output_dim, pooled, group):
    """Static (output_dim, P, P) channel map c = (ctop*G + gh)*G + gw."""
    ph = np.arange(pooled)
    gh = np.clip((ph * group) // pooled, 0, group - 1)
    c = (np.arange(output_dim)[:, None, None] * group +
         gh[None, :, None]) * group + gh[None, None, :]
    return c.astype(np.int32)


@register("_contrib_PSROIPooling", nin=2, jit=True,
          arg_names=["data", "rois"],
          defaults={"spatial_scale": 1.0, "output_dim": 0, "pooled_size": 0,
                    "group_size": 0},
          aliases=("_contrib_psroipooling",))
def psroi_pooling(data, rois, spatial_scale=1.0, output_dim=0,
                  pooled_size=0, group_size=0):
    """Position-sensitive ROI average pooling (reference
    psroi_pooling.cu PSROIPoolForwardKernel): each output bin averages
    its own channel group over the bin's [start, end) extent; ROI coords
    are rounded then scaled; empty bins emit 0."""
    P = int(pooled_size)
    G = int(group_size) or P
    od = int(output_dim)
    N, C, H, W = data.shape
    if C != od * G * G:
        raise MXNetError("PSROIPooling: channels %d != output_dim*group^2"
                         % C)
    f32 = jnp.float32
    batch = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1]).astype(f32) * spatial_scale
    y1 = jnp.round(rois[:, 2]).astype(f32) * spatial_scale
    x2 = (jnp.round(rois[:, 3]) + 1.0).astype(f32) * spatial_scale
    y2 = (jnp.round(rois[:, 4]) + 1.0).astype(f32) * spatial_scale
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h = rh / P                                   # (R,)
    bin_w = rw / P

    ph = jnp.arange(P, dtype=f32)
    hstart = jnp.clip(jnp.floor(ph[None] * bin_h[:, None] + y1[:, None]),
                      0, H)                          # (R, P)
    hend = jnp.clip(jnp.ceil((ph[None] + 1) * bin_h[:, None] + y1[:, None]),
                    0, H)
    wstart = jnp.clip(jnp.floor(ph[None] * bin_w[:, None] + x1[:, None]),
                      0, W)
    wend = jnp.clip(jnp.ceil((ph[None] + 1) * bin_w[:, None] + x1[:, None]),
                    0, W)

    hs = jnp.arange(H, dtype=f32)
    ws = jnp.arange(W, dtype=f32)
    mask_h = ((hs[None, None] >= hstart[..., None]) &
              (hs[None, None] < hend[..., None])).astype(f32)   # (R, P, H)
    mask_w = ((ws[None, None] >= wstart[..., None]) &
              (ws[None, None] < wend[..., None])).astype(f32)   # (R, P, W)

    sel = data[batch].astype(f32)                    # (R, C, H, W)
    # reduce W then H on the MXU
    t = jnp.einsum("rchw,rqw->rchq", sel, mask_w)
    t = jnp.einsum("rchq,rph->rcpq", t, mask_h)      # (R, C, P, P)

    cidx = jnp.asarray(_ps_channel_index(od, P, G))  # (od, P, P)
    pi = jnp.arange(P)
    out = t[:, cidx, pi[None, :, None], pi[None, None, :]]  # (R, od, P, P)

    area = ((hend - hstart)[:, None, :, None] *
            (wend - wstart)[:, None, None, :])       # (R, 1, P, P)
    out = jnp.where(area > 0, out / jnp.maximum(area, 1.0), 0.0)
    return out.astype(data.dtype)


@register("_contrib_DeformablePSROIPooling", nin=3, jit=True, nout=2,
          arg_names=["data", "rois", "trans"],
          defaults={"spatial_scale": 1.0, "output_dim": 0, "group_size": 0,
                    "pooled_size": 0, "part_size": 0, "sample_per_part": 1,
                    "trans_std": 0.0, "no_trans": False})
def deformable_psroi_pooling(data, rois, trans, spatial_scale=1.0,
                             output_dim=0, group_size=0, pooled_size=0,
                             part_size=0, sample_per_part=1, trans_std=0.0,
                             no_trans=False):
    """Deformable position-sensitive ROI pooling (reference
    deformable_psroi_pooling.cu): each bin averages sample_per_part^2
    bilinear samples, offset by the (trans_std-scaled) transform of its
    part cell. Returns (output, sample_count) like the reference."""
    P = int(pooled_size)
    G = int(group_size) or P
    od = int(output_dim)
    ps = int(part_size) or P
    sp = int(sample_per_part)
    N, C, H, W = data.shape
    f32 = jnp.float32
    R = rois.shape[0]

    batch = rois[:, 0].astype(jnp.int32)
    x1 = jnp.round(rois[:, 1]).astype(f32) * spatial_scale - 0.5
    y1 = jnp.round(rois[:, 2]).astype(f32) * spatial_scale - 0.5
    x2 = (jnp.round(rois[:, 3]) + 1.0).astype(f32) * spatial_scale - 0.5
    y2 = (jnp.round(rois[:, 4]) + 1.0).astype(f32) * spatial_scale - 0.5
    rw = jnp.maximum(x2 - x1, 0.1)
    rh = jnp.maximum(y2 - y1, 0.1)
    bin_h = rh / P
    bin_w = rw / P
    sub_h = bin_h / sp
    sub_w = bin_w / sp

    # part cell of each pooled index and class of each ctop — static maps
    ph_idx = np.arange(P)
    part_cell = np.floor(ph_idx / P * ps).astype(np.int32)       # (P,)
    if no_trans:
        n_cls = 1
        cls_of = np.zeros(od, np.int32)
    else:
        n_cls = int(trans.shape[1]) // 2
        cls_of = (np.arange(od) // max(od // n_cls, 1)).astype(np.int32)
        tr = trans.astype(f32).reshape(R, n_cls, 2, ps, ps)
        # offsets at each bin's part cell: (R, n_cls, P, P)
        tx = tr[:, :, 0][:, :, part_cell][:, :, :, part_cell] * trans_std
        ty = tr[:, :, 1][:, :, part_cell][:, :, :, part_cell] * trans_std

    ph_f = jnp.asarray(ph_idx, f32)
    ih = jnp.arange(sp, dtype=f32)
    r1 = (slice(None), None, None, None, None)
    # bin origins: h varies over axis 1 (ph), w over axis 2 (pw)
    bh = ph_f[None, :, None, None, None] * bin_h[r1] + y1[r1]
    bw = ph_f[None, None, :, None, None] * bin_w[r1] + x1[r1]
    sh = ih[None, None, None, :, None] * sub_h[r1]     # sample row offset
    sw = ih[None, None, None, None, :] * sub_w[r1]

    cidx = jnp.asarray(_ps_channel_index(od, P, G))               # (od,P,P)
    sel = data[batch].astype(f32)                                 # (R,C,H,W)

    def one_roi(img, hc, wc, ok):
        # img (C, H, W); hc/wc/ok (P, P, sp, sp)
        vals = _bilinear_gather(img, hc, wc) * ok.astype(f32)
        return vals.sum((-1, -2))                                 # (C, P, P)

    outs = jnp.zeros((R, od, P, P), f32)
    counts = jnp.zeros((R, od, P, P), f32)
    pi = jnp.arange(P)
    for cls in range(n_cls):
        if no_trans:
            oy = ox = jnp.zeros((R, 1, 1, 1, 1), f32)
        else:
            oy = (ty[:, cls] * rh[:, None, None])[..., None, None]
            ox = (tx[:, cls] * rw[:, None, None])[..., None, None]
        hh = jnp.broadcast_to(bh + oy + sh, (R, P, P, sp, sp))
        ww = jnp.broadcast_to(bw + ox + sw, (R, P, P, sp, sp))
        ok = ((ww > -0.5) & (ww < W - 0.5) &
              (hh > -0.5) & (hh < H - 0.5))
        hc = jnp.clip(hh, 0.0, H - 1.0)
        wc = jnp.clip(ww, 0.0, W - 1.0)
        summed = jax.vmap(one_roi)(sel, hc, wc, ok)               # (R,C,P,P)
        cnt = ok.astype(f32).sum((-1, -2))                        # (R, P, P)
        picked = summed[:, cidx, pi[None, :, None], pi[None, None, :]]
        mask = jnp.asarray(cls_of == cls)[None, :, None, None]
        outs = jnp.where(mask, picked, outs)
        counts = jnp.where(mask, cnt[:, None], counts)
    out = jnp.where(counts > 0, outs / jnp.maximum(counts, 1.0), 0.0)
    return out.astype(data.dtype), counts.astype(data.dtype)


get_op("_contrib_DeformablePSROIPooling").visible_outputs = 1


# ---------------------------------------------------------------------------
# MultiProposal — batched RPN proposal generation
# ---------------------------------------------------------------------------

@register("_contrib_MultiProposal", nin=3, jit=True,
          arg_names=["cls_prob", "bbox_pred", "im_info"], nout=2,
          defaults={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                    "threshold": 0.7, "rpn_min_size": 16,
                    "scales": (4.0, 8.0, 16.0, 32.0),
                    "ratios": (0.5, 1.0, 2.0), "feature_stride": 16,
                    "output_score": False, "iou_loss": False},
          no_grad=True, aliases=("MultiProposal",))
def multi_proposal(cls_prob, bbox_pred, im_info, **params):
    """Batched Proposal (reference contrib/multi_proposal.cc): the
    single-image RPN applied per image, batch index written into
    rois[:, 0]. Output (B*post_nms, 5) rois + scores."""
    from .contrib_extra import proposal
    B = cls_prob.shape[0]
    rois_all, scores_all = [], []
    for i in range(B):
        rois, scores = proposal(cls_prob[i:i + 1], bbox_pred[i:i + 1],
                                im_info[i:i + 1], **params)
        rois = rois.at[:, 0].set(float(i))
        rois_all.append(rois)
        scores_all.append(scores)
    return jnp.concatenate(rois_all, 0), jnp.concatenate(scores_all, 0)


get_op("_contrib_MultiProposal").visible_outputs = 1


# ---------------------------------------------------------------------------
# count_sketch (compact bilinear pooling)
# ---------------------------------------------------------------------------

@register("_contrib_count_sketch", nin=3, jit=True,
          arg_names=["data", "h", "s"],
          defaults={"out_dim": 0, "processing_batch_size": 32})
def count_sketch(data, h, s, out_dim=0, processing_batch_size=32):
    """Count-sketch projection (reference contrib/count_sketch.cu
    sketch_forward_kernel): out[..., h[j]] += s[j] * data[..., j].
    Expressed as one matmul against the static one-hot scatter matrix —
    MXU-native, and the transpose in the backward falls out of jax.vjp.
    processing_batch_size (a GPU grid knob) is accepted and ignored."""
    od = int(out_dim)
    in_dim = data.shape[-1]
    scatter = (h.astype(jnp.int32)[:, None] ==
               jnp.arange(od)[None, :]).astype(data.dtype)        # (in, od)
    scatter = scatter * s.astype(data.dtype)[:, None]
    return (data.reshape(-1, in_dim) @ scatter) \
        .reshape(data.shape[:-1] + (od,))


# ---------------------------------------------------------------------------
# linalg: LQ factorization + symmetric eigendecomposition
# ---------------------------------------------------------------------------

@register("_linalg_gelqf", nout=2, aliases=("linalg_gelqf",),
          arg_names=["A"])
def linalg_gelqf(A):
    """LQ factorization A = L * Q with Q row-orthonormal, L lower
    triangular (reference la_op.cc:483-541 — LAPACK gelqf+orglq).
    A (..., x, y) with x <= y; Q (..., x, y), L (..., x, x)."""
    q1, r1 = jnp.linalg.qr(jnp.swapaxes(A, -1, -2), mode="reduced")
    return jnp.swapaxes(q1, -1, -2), jnp.swapaxes(r1, -1, -2)


@register("_linalg_syevd", nout=2, aliases=("linalg_syevd",),
          arg_names=["A"])
def linalg_syevd(A):
    """Symmetric eigendecomposition A = U^T * diag(L) * U, rows of U are
    the eigenvectors, L ascending (reference la_op.cc syevd)."""
    w, v = jnp.linalg.eigh(A)
    return jnp.swapaxes(v, -1, -2), w


# ---------------------------------------------------------------------------
# reshape_like, slice-assign internals, scatter_set_nd
# ---------------------------------------------------------------------------

@register("reshape_like", nin=2, arg_names=["lhs", "rhs"])
def reshape_like(lhs, rhs):
    """Reshape lhs to rhs's shape (reference elemwise_unary_op.cc)."""
    return lhs.reshape(rhs.shape)


def _slice_tuple(shape, begin, end, step):
    begin = as_tuple(begin)
    end = as_tuple(end)
    step = as_tuple(step) if step else (1,) * len(begin)
    idx = []
    for d, (b, e) in enumerate(zip(begin, end)):
        st = step[d] if d < len(step) and step[d] is not None else 1
        idx.append(slice(b if b is not None else None,
                         e if e is not None else None, st))
    return tuple(idx)


@register("_slice_assign", nin=2, arg_names=["lhs", "rhs"],
          defaults={"begin": (), "end": (), "step": ()},
          aliases=("_crop_assign",))
def slice_assign(lhs, rhs, begin=(), end=(), step=()):
    """lhs with lhs[begin:end:step] = rhs (reference matrix_op.cc:313,
    the op behind sliced NDArray writes)."""
    return lhs.at[_slice_tuple(lhs.shape, begin, end, step)].set(rhs)


@register("_slice_assign_scalar", nin=1, arg_names=["data"],
          defaults={"scalar": 0.0, "begin": (), "end": (), "step": ()},
          aliases=("_crop_assign_scalar",))
def slice_assign_scalar(data, scalar=0.0, begin=(), end=(), step=()):
    return data.at[_slice_tuple(data.shape, begin, end, step)].set(
        jnp.asarray(scalar, data.dtype))


# `_scatter_set_nd` (indexing_op.cc:550) is deliberately NOT registered:
# it is the reference's internal write-through op for `x[idx] = v`, whose
# semantics require reading the shared output buffer — here NDArray
# advanced-index assignment lowers directly to jnp `.at[].set`.


# ---------------------------------------------------------------------------
# Crop (legacy) — crop spatial dims to h_w / crop_like at offset or center
# ---------------------------------------------------------------------------

@register("Crop", nin=-1, jit=True,
          defaults={"num_args": 1, "offset": (0, 0), "h_w": (0, 0),
                    "center_crop": False})
def crop_op(*inputs, num_args=1, offset=(0, 0), h_w=(0, 0),
            center_crop=False):
    """Legacy Crop (reference crop-inl.h): crop (N, C, H, W) to h_w (or
    to the spatial shape of the second input) at `offset` (y, x), or
    centered when center_crop=True."""
    data = inputs[0]
    H, W = data.shape[2], data.shape[3]
    if int(num_args) == 2 or len(inputs) == 2:
        th, tw = inputs[1].shape[2], inputs[1].shape[3]
    else:
        th, tw = as_tuple(h_w)
    if center_crop:
        oy, ox = (H - th) // 2, (W - tw) // 2
    else:
        oy, ox = as_tuple(offset)
    if oy + th > H or ox + tw > W:
        raise MXNetError("Crop: crop window exceeds input extent")
    return data[:, :, oy:oy + th, ox:ox + tw]


# ---------------------------------------------------------------------------
# Cross-device copy + legacy/cudnn aliases
# ---------------------------------------------------------------------------

@register("_CrossDeviceCopy", aliases=("_copyto",))
def cross_device_copy(data):
    """Identity at the graph level (reference cross_device_copy.cc) —
    device placement is explicit in the executor (group2ctx commits
    storage to the consumer device), so the node carries no compute."""
    return data


# Legacy v1 layers share the modern kernels: the reference keeps both
# registrations for old graph JSON; the compute contract is identical.
alias("Convolution", "Convolution_v1")
alias("Pooling", "Pooling_v1")
alias("BatchNorm", "CuDNNBatchNorm")
alias("Embedding", "_contrib_SparseEmbedding")
alias("_ctc_loss", "_contrib_CTCLoss")
