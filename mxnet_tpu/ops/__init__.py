"""Operator library: importing this package registers every op.

The registry (ops/registry.py) is the single source from which the
``mx.nd.*`` and ``mx.sym.*`` namespaces are generated, mirroring how the
reference generates Python functions from its C++ NNVM registry.
"""
from .registry import OpDef, register, get_op, list_ops, alias  # noqa: F401
from . import elemwise    # noqa: F401
from . import reduce      # noqa: F401
from . import tensor      # noqa: F401
from . import nn          # noqa: F401
from . import random_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import linalg      # noqa: F401
from . import rnn         # noqa: F401
from . import ctc         # noqa: F401
from . import contrib     # noqa: F401
from . import contrib_extra  # noqa: F401
from . import contrib_extra3  # noqa: F401
from . import spatial     # noqa: F401

from . import shape_infer as _shape_infer  # noqa: E402
_shape_infer.install()

# dynamic output counts
from .registry import get_op as _g  # noqa: E402
_g("topk").visible_outputs = lambda p: 2 if p.get("ret_typ") == "both" else 1
