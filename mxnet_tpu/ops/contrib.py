"""Contrib operators: SSD multibox family, ROIPooling, proposal ops.

Parity: reference ``src/operator/contrib/multibox_prior.cc``,
``multibox_target.cc``, ``multibox_detection.cc`` (the SSD-VGG16 baseline
workload, SURVEY.md BASELINE config 4) and ``src/operator/roi_pooling.cc``.
TPU-native design: all static-shape vectorised jax — anchor matching is a
masked argmax instead of the reference's sequential bipartite loop, and
NMS is a fixed-trip-count lax.fori_loop over score-sorted candidates
(compiler-friendly; no dynamic shapes).
"""
from __future__ import annotations

import ast

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .common import as_tuple
from .registry import register


def _parse_floats(v, default=()):
    if v is None:
        return tuple(default)
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if isinstance(v, (int, float)):
        return (float(v),)
    return tuple(float(x) for x in v)


@register("_contrib_MultiBoxPrior", nin=1,
          defaults={"sizes": (1.0,), "ratios": (1.0,), "clip": False,
                    "steps": (-1.0, -1.0), "offsets": (0.5, 0.5)},
          no_grad=True, aliases=("MultiBoxPrior", "_contrib_multibox_prior"))
def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5)):
    """Generate anchor boxes per feature-map cell (reference
    multibox_prior.cc). Output (1, H*W*(S+R-1), 4) as cx-style corners."""
    sizes = _parse_floats(sizes, (1.0,))
    ratios = _parse_floats(ratios, (1.0,))
    offsets = _parse_floats(offsets, (0.5, 0.5))
    steps = _parse_floats(steps, (-1.0, -1.0))
    H, W = data.shape[2], data.shape[3]
    step_y = steps[0] if steps[0] > 0 else 1.0 / H
    step_x = steps[1] if steps[1] > 0 else 1.0 / W
    cy = (jnp.arange(H) + offsets[0]) * step_y
    cx = (jnp.arange(W) + offsets[1]) * step_x
    cy, cx = jnp.meshgrid(cy, cx, indexing="ij")
    # anchor list: (sizes[0], ratios[0]), (sizes[i>0], ratios[0]),
    # (sizes[0], ratios[j>0]) — reference ordering
    whs = []
    for k, s in enumerate(sizes):
        whs.append((s * np.sqrt(ratios[0]), s / np.sqrt(ratios[0])))
    for r in ratios[1:]:
        whs.append((sizes[0] * np.sqrt(r), sizes[0] / np.sqrt(r)))
    whs = jnp.asarray(whs)  # (A, 2) w,h
    A = whs.shape[0]
    cxy = jnp.stack([cx, cy], axis=-1).reshape(H * W, 1, 2)
    half = whs.reshape(1, A, 2) / 2.0
    mins = cxy - half
    maxs = cxy + half
    out = jnp.concatenate([mins, maxs], axis=-1).reshape(1, H * W * A, 4)
    if clip:
        out = jnp.clip(out, 0.0, 1.0)
    return out


def _iou(anchors, gt):
    """anchors (N,4) corners; gt (M,4) corners -> (N,M)"""
    ax1, ay1, ax2, ay2 = [anchors[:, i] for i in range(4)]
    gx1, gy1, gx2, gy2 = [gt[:, i] for i in range(4)]
    ix1 = jnp.maximum(ax1[:, None], gx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], gy1[None, :])
    ix2 = jnp.minimum(ax2[:, None], gx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], gy2[None, :])
    iw = jnp.maximum(ix2 - ix1, 0.0)
    ih = jnp.maximum(iy2 - iy1, 0.0)
    inter = iw * ih
    area_a = jnp.maximum((ax2 - ax1) * (ay2 - ay1), 0.0)
    area_g = jnp.maximum((gx2 - gx1) * (gy2 - gy1), 0.0)
    union = area_a[:, None] + area_g[None, :] - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _encode_boxes(anchors, gt, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    gw = jnp.maximum(gt[:, 2] - gt[:, 0], 1e-8)
    gh = jnp.maximum(gt[:, 3] - gt[:, 1], 1e-8)
    gcx = (gt[:, 0] + gt[:, 2]) / 2
    gcy = (gt[:, 1] + gt[:, 3]) / 2
    tx = (gcx - acx) / jnp.maximum(aw, 1e-8) / variances[0]
    ty = (gcy - acy) / jnp.maximum(ah, 1e-8) / variances[1]
    tw = jnp.log(gw / jnp.maximum(aw, 1e-8)) / variances[2]
    th = jnp.log(gh / jnp.maximum(ah, 1e-8)) / variances[3]
    return jnp.stack([tx, ty, tw, th], axis=-1)


@register("_contrib_MultiBoxTarget", nin=3,
          arg_names=["anchor", "label", "cls_pred"], nout=3,
          defaults={"overlap_threshold": 0.5, "ignore_label": -1.0,
                    "negative_mining_ratio": -1.0,
                    "negative_mining_thresh": 0.5,
                    "minimum_negative_samples": 0,
                    "variances": (0.1, 0.1, 0.2, 0.2)},
          no_grad=True,
          aliases=("MultiBoxTarget", "_contrib_multibox_target"))
def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """Assign training targets to anchors (reference multibox_target.cc).

    anchor (1, N, 4); label (B, M, 5) [cls, x1, y1, x2, y2] padded with
    cls=-1; cls_pred (B, C, N). Returns (loc_target (B, N*4),
    loc_mask (B, N*4), cls_target (B, N)).
    """
    variances = _parse_floats(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]

    def per_sample(lab, scores):
        valid = lab[:, 0] >= 0                          # (M,)
        iou = _iou(anchors, lab[:, 1:5])                # (N, M)
        iou = jnp.where(valid[None, :], iou, -1.0)
        best_gt = jnp.argmax(iou, axis=1)               # (N,)
        best_iou = jnp.max(iou, axis=1)
        # force-match: each gt claims its best anchor
        best_anchor = jnp.argmax(iou, axis=0)           # (M,)
        # .max accumulates: padded gts share argmax 0 and must not
        # overwrite a real gt's forced match
        forced = jnp.zeros((N,), bool).at[best_anchor].max(valid)
        pos = forced | (best_iou >= overlap_threshold)
        matched_gt = lab[best_gt]                       # (N, 5)
        cls_t = jnp.where(pos, matched_gt[:, 0] + 1.0, 0.0)
        loc_t = _encode_boxes(anchors, matched_gt[:, 1:5],
                              variances) * pos[:, None]
        mask = jnp.tile(pos[:, None], (1, 4)).astype(jnp.float32)
        if negative_mining_ratio > 0:
            # hard-negative mining by background confidence
            max_pos = jnp.sum(pos)
            n_neg = jnp.maximum(max_pos * negative_mining_ratio,
                                minimum_negative_samples).astype(jnp.int32)
            bg_score = scores[0]                        # (N,) bg confidence
            neg_cand = (~pos) & (best_iou < negative_mining_thresh)
            hardness = jnp.where(neg_cand, -bg_score, -jnp.inf)
            order = jnp.argsort(-hardness)
            rank = jnp.zeros((N,), jnp.int32).at[order].set(jnp.arange(N))
            keep_neg = neg_cand & (rank < n_neg)
            cls_t = jnp.where(pos, cls_t,
                              jnp.where(keep_neg, 0.0, ignore_label))
        return loc_t.reshape(-1), mask.reshape(-1), cls_t

    loc_t, loc_m, cls_t = jax.vmap(per_sample)(label, cls_pred)
    return loc_t, loc_m, cls_t


def _decode_boxes(anchors, deltas, variances):
    aw = anchors[:, 2] - anchors[:, 0]
    ah = anchors[:, 3] - anchors[:, 1]
    acx = (anchors[:, 0] + anchors[:, 2]) / 2
    acy = (anchors[:, 1] + anchors[:, 3]) / 2
    cx = deltas[:, 0] * variances[0] * aw + acx
    cy = deltas[:, 1] * variances[1] * ah + acy
    w = jnp.exp(jnp.clip(deltas[:, 2] * variances[2], -10, 10)) * aw
    h = jnp.exp(jnp.clip(deltas[:, 3] * variances[3], -10, 10)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2],
                     axis=-1)


@register("_contrib_MultiBoxDetection", nin=3,
          arg_names=["cls_prob", "loc_pred", "anchor"],
          defaults={"clip": True, "threshold": 0.01, "background_id": 0,
                    "nms_threshold": 0.5, "force_suppress": False,
                    "variances": (0.1, 0.1, 0.2, 0.2), "nms_topk": -1},
          no_grad=True,
          aliases=("MultiBoxDetection", "_contrib_multibox_detection"))
def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False, variances=(0.1, 0.1, 0.2, 0.2),
                       nms_topk=-1):
    """Decode predictions + NMS (reference multibox_detection.cc).

    cls_prob (B, C, N), loc_pred (B, N*4), anchor (1, N, 4) ->
    (B, N, 6) rows [cls_id, score, x1, y1, x2, y2], suppressed rows
    cls_id=-1.
    """
    variances = _parse_floats(variances, (0.1, 0.1, 0.2, 0.2))
    anchors = anchor.reshape(-1, 4)
    N = anchors.shape[0]

    def per_sample(scores, deltas):
        boxes = _decode_boxes(anchors, deltas.reshape(-1, 4), variances)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        fg = jnp.concatenate([scores[:background_id],
                              scores[background_id + 1:]], axis=0) \
            if scores.shape[0] > 1 else scores
        cls_id = jnp.argmax(fg, axis=0).astype(jnp.float32)  # (N,)
        score = jnp.max(fg, axis=0)
        keep = score > threshold
        cls_id = jnp.where(keep, cls_id, -1.0)
        order = jnp.argsort(-score)
        cls_s = cls_id[order]
        score_s = score[order]
        boxes_s = boxes[order]
        topk = nms_topk if nms_topk and nms_topk > 0 else N
        iou = _iou(boxes_s, boxes_s)

        def body(i, alive):
            cur_alive = alive[i] & (cls_s[i] >= 0) & (i < topk)
            same = (cls_s == cls_s[i]) | force_suppress
            sup = (iou[i] > nms_threshold) & same & \
                (jnp.arange(N) > i) & cur_alive
            return alive & ~sup

        alive = jax.lax.fori_loop(0, min(N, topk), body, jnp.ones((N,), bool))
        cls_out = jnp.where(alive, cls_s, -1.0)
        return jnp.concatenate([cls_out[:, None], score_s[:, None], boxes_s],
                               axis=-1)

    return jax.vmap(per_sample)(cls_prob, loc_pred)


@register("ROIPooling", nin=2, arg_names=["data", "rois"],
          defaults={"pooled_size": (), "spatial_scale": 1.0})
def roi_pooling(data, rois, pooled_size=(), spatial_scale=1.0):
    """Max-pool regions of interest (reference src/operator/roi_pooling.cc).

    data (B, C, H, W); rois (R, 5) [batch_idx, x1, y1, x2, y2] in image
    coords. Static-shape design: each output cell max-pools over the full
    feature map with a membership mask (vectorised; no dynamic slicing).
    """
    ph, pw = as_tuple(pooled_size, 2)
    B, C, H, W = data.shape

    ys = jnp.arange(H, dtype=jnp.float32)
    xs = jnp.arange(W, dtype=jnp.float32)

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * spatial_scale)
        y1 = jnp.round(roi[2] * spatial_scale)
        x2 = jnp.round(roi[3] * spatial_scale)
        y2 = jnp.round(roi[4] * spatial_scale)
        rh = jnp.maximum(y2 - y1 + 1, 1.0)
        rw = jnp.maximum(x2 - x1 + 1, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw
        fmap = data[b]                                   # (C, H, W)

        iy = jnp.arange(ph, dtype=jnp.float32)
        ix = jnp.arange(pw, dtype=jnp.float32)
        y_lo = jnp.floor(y1 + iy * bin_h)
        y_hi = jnp.ceil(y1 + (iy + 1) * bin_h)
        x_lo = jnp.floor(x1 + ix * bin_w)
        x_hi = jnp.ceil(x1 + (ix + 1) * bin_w)
        ymask = (ys[None, :] >= y_lo[:, None]) & (ys[None, :] < y_hi[:, None])
        xmask = (xs[None, :] >= x_lo[:, None]) & (xs[None, :] < x_hi[:, None])
        m = ymask[:, None, :, None] & xmask[None, :, None, :]  # (ph,pw,H,W)
        vals = jnp.where(m[None], fmap[:, None, None, :, :], -jnp.inf)
        out = jnp.max(vals, axis=(-2, -1))               # (C, ph, pw)
        return jnp.where(jnp.isfinite(out), out, 0.0)

    return jax.vmap(one_roi)(rois)
