"""Fused multi-layer RNN operator (RNN/LSTM/GRU).

Parity: reference ``src/operator/rnn-inl.h`` (native) /
``cudnn_rnn-inl.h`` (fused cuDNN path) behind the single ``RNN`` op.
TPU-native design: one ``lax.scan`` over time per layer+direction — the
per-step matmuls batch onto the MXU and XLA pipelines the scan; this is
the TPU replacement for cuDNN's fused kernels (SURVEY.md §5.7).

Packed parameter layout (this framework's convention, produced by
``gluon/rnn/rnn_layer.py`` and consumed here): for each layer, for each
direction: W_ih (G*H, in), W_hh (G*H, H), b_ih (G*H,), b_hh (G*H,), all
flattened and concatenated in order. Gate order: LSTM i,f,c,o; GRU r,z,n.

Data layout TNC (seq, batch, feature), states (layers*dirs, batch, H) —
matching the reference RNN op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .registry import register

_GATES = {"rnn_relu": 1, "rnn_tanh": 1, "lstm": 4, "gru": 3}


def rnn_param_size(input_size, state_size, num_layers, mode,
                   bidirectional=False):
    """Total packed parameter count (used by gluon and shape inference)."""
    g = _GATES[mode]
    dirs = 2 if bidirectional else 1
    size = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else state_size * dirs
        for _ in range(dirs):
            size += g * state_size * (in_sz + state_size + 2)
    return size


def _unpack(parameters, input_size, state_size, num_layers, mode, dirs):
    g = _GATES[mode]
    H = state_size
    out = []
    off = 0
    for layer in range(num_layers):
        in_sz = input_size if layer == 0 else H * dirs
        layer_params = []
        for _ in range(dirs):
            w_ih = parameters[off:off + g * H * in_sz].reshape(g * H, in_sz)
            off += g * H * in_sz
            w_hh = parameters[off:off + g * H * H].reshape(g * H, H)
            off += g * H * H
            b_ih = parameters[off:off + g * H]
            off += g * H
            b_hh = parameters[off:off + g * H]
            off += g * H
            layer_params.append((w_ih, w_hh, b_ih, b_hh))
        out.append(layer_params)
    return out


def _cell_step(mode, H):
    if mode == "lstm":
        def step(carry, gates):
            h, c = carry
            i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
            f = jax.nn.sigmoid(gates[:, 1 * H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c)
        return step
    if mode == "gru":
        return None  # handled specially (n gate needs r * (Whh h + bhh))
    act = jnp.tanh if mode == "rnn_tanh" else (lambda x: jnp.maximum(x, 0))

    def step(carry, gates):
        (h,) = carry
        return (act(gates),)
    return step


def _run_layer(x, h0, c0, w_ih, w_hh, b_ih, b_hh, mode, H, reverse=False):
    """x: (T, N, in) -> outputs (T, N, H), final states."""
    if reverse:
        x = jnp.flip(x, axis=0)
    xg = jnp.einsum("tni,gi->tng", x, w_ih) + b_ih  # precompute input gates

    if mode == "gru":
        def step(carry, xg_t):
            (h,) = carry
            hg = jnp.dot(h, w_hh.T) + b_hh
            r = jax.nn.sigmoid(xg_t[:, 0 * H:1 * H] + hg[:, 0 * H:1 * H])
            z = jax.nn.sigmoid(xg_t[:, 1 * H:2 * H] + hg[:, 1 * H:2 * H])
            n = jnp.tanh(xg_t[:, 2 * H:3 * H] + r * hg[:, 2 * H:3 * H])
            h = (1 - z) * n + z * h
            return (h,), h
        carry, ys = jax.lax.scan(step, (h0,), xg)
        final = (carry[0], None)
    elif mode == "lstm":
        def step(carry, xg_t):
            h, c = carry
            gates = xg_t + jnp.dot(h, w_hh.T) + b_hh
            i = jax.nn.sigmoid(gates[:, 0 * H:1 * H])
            f = jax.nn.sigmoid(gates[:, 1 * H:2 * H])
            g = jnp.tanh(gates[:, 2 * H:3 * H])
            o = jax.nn.sigmoid(gates[:, 3 * H:4 * H])
            c = f * c + i * g
            h = o * jnp.tanh(c)
            return (h, c), h
        carry, ys = jax.lax.scan(step, (h0, c0), xg)
        final = carry
    else:
        act = jnp.tanh if mode == "rnn_tanh" else (lambda v: jnp.maximum(v, 0))

        def step(carry, xg_t):
            (h,) = carry
            h = act(xg_t + jnp.dot(h, w_hh.T) + b_hh)
            return (h,), h
        carry, ys = jax.lax.scan(step, (h0,), xg)
        final = (carry[0], None)
    if reverse:
        ys = jnp.flip(ys, axis=0)
    return ys, final


@register("RNN", nin=4, jit=True, arg_names=["data", "parameters", "state", "state_cell"],
          nout=3,
          defaults={"state_size": 0, "num_layers": 1, "mode": "lstm",
                    "bidirectional": False, "p": 0.0, "state_outputs": False,
                    "lstm_state_clip_min": None, "lstm_state_clip_max": None})
def rnn(data, parameters, state, state_cell=None, state_size=0, num_layers=1,
        mode="lstm", bidirectional=False, p=0.0, state_outputs=False,
        lstm_state_clip_min=None, lstm_state_clip_max=None, _train=False,
        _rng=None):
    """Fused RNN (see module docstring for layout/parity notes)."""
    if mode not in _GATES:
        raise MXNetError("unknown RNN mode %r" % mode)
    T, N, input_size = data.shape
    H = int(state_size)
    dirs = 2 if bidirectional else 1
    layers = _unpack(parameters, input_size, H, int(num_layers), mode, dirs)

    x = data
    finals_h = []
    finals_c = []
    for li, layer_params in enumerate(layers):
        outs = []
        for d in range(dirs):
            w_ih, w_hh, b_ih, b_hh = layer_params[d]
            idx = li * dirs + d
            h0 = state[idx]
            c0 = state_cell[idx] if (mode == "lstm" and state_cell is not None) \
                else jnp.zeros_like(h0)
            ys, (hT, cT) = _run_layer(x, h0, c0, w_ih, w_hh, b_ih, b_hh,
                                      mode, H, reverse=(d == 1))
            outs.append(ys)
            finals_h.append(hT)
            if mode == "lstm":
                finals_c.append(cT)
        x = outs[0] if dirs == 1 else jnp.concatenate(outs, axis=-1)
        if _train and p > 0 and li < len(layers) - 1 and _rng is not None:
            keep = jax.random.bernoulli(
                jax.random.fold_in(_rng, li), 1.0 - p, x.shape)
            x = jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))

    h_out = jnp.stack(finals_h, axis=0)
    c_out = jnp.stack(finals_c, axis=0) if finals_c else jnp.zeros_like(h_out)
    return x, h_out, c_out


from .registry import get_op as _get_op
_rnn_op = _get_op("RNN")


def _rnn_visible(params):
    if not params.get("state_outputs", False):
        return 1
    return 3 if params.get("mode", "lstm") == "lstm" else 2


_rnn_op.visible_outputs = _rnn_visible


def _rnn_shape_infer(shapes, params):
    T, N, input_size = shapes[0]
    H = int(params.get("state_size", 0))
    L = int(params.get("num_layers", 1))
    mode = params.get("mode", "lstm")
    dirs = 2 if params.get("bidirectional", False) else 1
    total = rnn_param_size(input_size, H, L, mode, dirs == 2)
    out = {1: (total,), 2: (L * dirs, N, H)}
    if mode == "lstm":
        out[3] = (L * dirs, N, H)
    return out


_rnn_op.param_shape_infer = _rnn_shape_infer
