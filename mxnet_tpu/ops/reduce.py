"""Reduction / ordering / broadcasting operators.

Parity: reference ``src/operator/tensor/broadcast_reduce_op_value.cc``,
``broadcast_reduce_op_index.cc``, ``ordering_op.cc``. MXNet reduce
semantics: ``axis`` may be int/tuple/None, plus ``keepdims`` and
``exclude`` (reduce over the complement).
"""
from __future__ import annotations

import jax.numpy as jnp

from .common import reduce_axes, as_axis
from .registry import register


def _reduce(fn, identity_empty=None):
    def op(data, axis=None, keepdims=False, exclude=False):
        axes = reduce_axes(axis, data.ndim, exclude)
        if axes == ():
            return data if not keepdims else data
        return fn(data, axis=axes, keepdims=bool(keepdims))
    return op


register("sum", defaults={"axis": None, "keepdims": False, "exclude": False},
         aliases=("sum_axis",))(_reduce(jnp.sum))
register("mean", defaults={"axis": None, "keepdims": False, "exclude": False})(_reduce(jnp.mean))
register("prod", defaults={"axis": None, "keepdims": False, "exclude": False})(_reduce(jnp.prod))
register("nansum", defaults={"axis": None, "keepdims": False, "exclude": False})(_reduce(jnp.nansum))
register("nanprod", defaults={"axis": None, "keepdims": False, "exclude": False})(_reduce(jnp.nanprod))
register("max", defaults={"axis": None, "keepdims": False, "exclude": False},
         aliases=("max_axis",))(_reduce(jnp.max))
register("min", defaults={"axis": None, "keepdims": False, "exclude": False},
         aliases=("min_axis",))(_reduce(jnp.min))


@register("_square_sum", defaults={"axis": None, "keepdims": False,
                                   "exclude": False})
def _square_sum(data, axis=None, keepdims=False, exclude=False):
    """Sum of squares — row_sparse-only in the reference (the lazy-update
    optimizer norm reduction, square_sum-inl.h: LOG(FATAL) "nothing to
    fallback on" for dense input). Sparse inputs are intercepted by the
    storage dispatch (ndarray/sparse.py:square_sum) before this body
    runs; reaching it means a dense input, which the reference rejects
    too."""
    from ..base import MXNetError
    raise MXNetError("_square_sum: only row_sparse input is supported "
                     "(reference square_sum-inl.h has no dense kernel)")


@register("norm")
def norm(data):
    """L2 norm over all elements (reference 0.12 norm reduces everything)."""
    return jnp.sqrt(jnp.sum(jnp.square(data))).reshape((1,))


@register("argmax", defaults={"axis": None, "keepdims": False}, no_grad=True)
def argmax(data, axis=None, keepdims=False):
    axis = as_axis(axis)
    out = jnp.argmax(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)  # reference returns real_t indices


@register("argmin", defaults={"axis": None, "keepdims": False}, no_grad=True)
def argmin(data, axis=None, keepdims=False):
    axis = as_axis(axis)
    out = jnp.argmin(data, axis=axis, keepdims=bool(keepdims))
    return out.astype(jnp.float32)


@register("argmax_channel", no_grad=True)
def argmax_channel(data):
    """argmax over axis 1 (reference broadcast_reduce_op_index.cc)."""
    return jnp.argmax(data, axis=1).astype(jnp.float32)


@register("pick", nin=2, arg_names=["data", "index"],
          defaults={"axis": -1, "keepdims": False})
def pick(data, index, axis=-1, keepdims=False):
    """Pick elements along axis by index (reference broadcast_reduce_op_index.cc)."""
    axis = int(axis) % data.ndim
    idx = index.astype(jnp.int32)
    if idx.ndim == data.ndim:
        idx = jnp.squeeze(idx, axis=axis)
    out = jnp.take_along_axis(data, jnp.expand_dims(idx, axis=axis), axis=axis)
    if not keepdims:
        out = jnp.squeeze(out, axis=axis)
    return out


@register("broadcast_to", defaults={"shape": ()})
def broadcast_to(data, shape=()):
    from .common import as_tuple
    shape = as_tuple(shape)
    tgt = tuple(s if s != 0 else d for s, d in zip(shape, data.shape))
    return jnp.broadcast_to(data, tgt)


@register("broadcast_axis", defaults={"axis": (), "size": ()},
          aliases=("broadcast_axes",))
def broadcast_axis(data, axis=(), size=()):
    from .common import as_tuple
    axes = as_tuple(axis) or ()
    sizes = as_tuple(size) or ()
    tgt = list(data.shape)
    for a, s in zip(axes, sizes):
        tgt[a % data.ndim] = s
    return jnp.broadcast_to(data, tuple(tgt))


# ---------------------------------------------------------------------------
# Ordering ops (reference src/operator/tensor/ordering_op.cc)
# ---------------------------------------------------------------------------

@register("sort", defaults={"axis": -1, "is_ascend": True}, no_grad=True)
def sort(data, axis=-1, is_ascend=True):
    axis = as_axis(axis)
    out = jnp.sort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis if axis is not None else 0)
    return out


@register("argsort", defaults={"axis": -1, "is_ascend": True, "dtype": "float32"},
          no_grad=True)
def argsort(data, axis=-1, is_ascend=True, dtype="float32"):
    from .common import mx_dtype
    axis = as_axis(axis)
    out = jnp.argsort(data, axis=axis)
    if not is_ascend:
        out = jnp.flip(out, axis=axis if axis is not None else 0)
    return out.astype(mx_dtype(dtype))


@register("topk", nout=1,
          defaults={"axis": -1, "k": 1, "ret_typ": "indices", "is_ascend": False,
                    "dtype": "float32"}, no_grad=True)
def topk(data, axis=-1, k=1, ret_typ="indices", is_ascend=False, dtype="float32"):
    """Top-k along axis (reference ordering_op.cc TopK).

    ret_typ: "value" | "indices" | "mask" | "both".
    """
    from .common import mx_dtype
    axis = -1 if axis is None else int(axis) % data.ndim
    k = int(k) if int(k) > 0 else data.shape[axis]
    sign = 1 if is_ascend else -1
    order = jnp.argsort(sign * data, axis=axis, stable=True)
    idx = jnp.take(order, jnp.arange(k), axis=axis)
    if ret_typ == "indices":
        return idx.astype(mx_dtype(dtype))
    vals = jnp.take_along_axis(data, idx, axis=axis)
    if ret_typ == "value":
        return vals
    if ret_typ == "both":
        return vals, idx.astype(mx_dtype(dtype))
    if ret_typ == "mask":
        mask = jnp.zeros(data.shape, dtype=data.dtype)
        ones = jnp.ones(idx.shape, dtype=data.dtype)
        return _put_along_axis(mask, idx, ones, axis)
    raise ValueError("unknown ret_typ %r" % ret_typ)


def _put_along_axis(arr, idx, vals, axis):
    return jnp.put_along_axis(arr, idx, vals, axis=axis, inplace=False)
