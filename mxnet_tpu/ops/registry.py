"""Operator registry — the single source of truth for the op surface.

TPU-native re-design of the reference's NNVM op registry
(``nnvm::Op`` + attr functors ``FCompute``/``FInferShape``, see reference
``include/mxnet/op_attr_types.h:45-264`` and SURVEY.md §2.1). In the
reference every op carries a C++ shape/type/storage-inference functor and
per-backend kernels; here every op is ONE pure JAX function — XLA is the
backend, shape/dtype inference falls out of ``jax.eval_shape``, and
gradients fall out of ``jax.vjp``. The Python ``mx.nd.*`` / ``mx.sym.*``
namespaces are code-generated from this registry exactly like the
reference generates them from the C op registry
(``python/mxnet/ndarray/register.py:142-168``).
"""
from __future__ import annotations

import functools
import inspect

from ..base import MXNetError

__all__ = ["OpDef", "register", "get_op", "list_ops", "alias"]

_OPS = {}


class OpDef:
    """One operator.

    Parameters
    ----------
    name : canonical MXNet op name (e.g. ``"FullyConnected"``).
    fn : pure function ``fn(*jax_arrays, **params) -> array | tuple``.
    nin : number of tensor inputs; -1 = variadic (first arg is a list).
    nout : number of outputs (static).
    arg_names : names of the tensor inputs, in order (for symbol binding
        and kwargs-style calls, e.g. ``data/weight/bias``).
    mutate : indices of inputs mutated in place by the imperative wrapper
        (optimizer update ops — reference ``optimizer_op.cc:39-299``).
    no_grad : op is non-differentiable; tape records zero-grad.
    """

    def __init__(self, name, fn, nin=1, nout=1, arg_names=None, defaults=None,
                 mutate=(), no_grad=False, doc=None, jit=False):
        self.name = name
        self.fn = fn
        self.nin = nin
        self.nout = nout
        self.arg_names = list(arg_names) if arg_names is not None else (
            ["data"] if nin in (1, -1) else ["lhs", "rhs"] if nin == 2 else
            ["arg%d" % i for i in range(max(nin, 0))])
        self.defaults = dict(defaults or {})
        self.mutate = tuple(mutate)
        self.no_grad = no_grad
        # Composite ops (scan-heavy RNN/CTC, conv, per-step optimizer
        # updates) re-trace their whole Python body on every eager call;
        # jit=True caches one compiled program per (static-params, avals)
        # signature — the eager analogue of the reference's cached engine
        # ops (graph_executor.cc InitCachedOps). Off by default: ops fed
        # varying shapes (image augmenters) would thrash the cache.
        self.jit_cache = jit
        self._jit_fns = {}
        self.doc = doc or (fn.__doc__ if fn is not None else None)
        # Execution-context needs, discovered from the signature: ops that
        # behave differently at train time declare a `_train` kwarg, random
        # ops a `_rng` kwarg (see ops/common.py).
        try:
            params = inspect.signature(fn).parameters
            self.takes_train = "_train" in params
            self.takes_rng = "_rng" in params
        except (TypeError, ValueError):
            self.takes_train = self.takes_rng = False
        # How many outputs user code sees (reference: num_visible_outputs —
        # e.g. BatchNorm computes 3 but exposes 1).
        self.visible_outputs = None
        # Indices of inputs that are auxiliary states (reference: aux states
        # like BatchNorm moving_mean/var — not arguments, never differentiated).
        self.aux_inputs = ()
        # Optional hook(raw_inputs, raw_outputs, params) -> {input_idx: new
        # raw value}; models reference ops that mutate aux states in place.
        self.stateful_update = None
        # Optional hook(input_shapes, params) -> {input_idx: shape} filling
        # learnable-input shapes (reference FInferShape; see ops/shape_infer.py).
        self.param_shape_infer = None
        # Optional hook(input_dtypes, params) -> {input_idx: dtype} for ops
        # whose learnable inputs do NOT follow the data dtype (reference
        # FInferType; e.g. BatchNorm pins scale/shift/moving stats to fp32
        # under low-precision data, the cudnn_batch_norm behaviour).
        self.param_dtype_infer = None

    def __repr__(self):
        return "OpDef(%s)" % self.name

    def accepted_params(self):
        """Names this op accepts as keyword params — derived from the fn
        signature (registry defaults alone miss params that exist only as
        fn keyword defaults). None means the fn takes **kwargs (accept
        anything)."""
        cached = getattr(self, "_accepted_params", False)
        if cached is not False:
            return cached
        keys = set(self.defaults) | {"num_args", "num_outputs"}
        try:
            sig = inspect.signature(self.fn)
            for p in sig.parameters.values():
                if p.kind == inspect.Parameter.VAR_KEYWORD:
                    self._accepted_params = None
                    return None
                if p.kind in (inspect.Parameter.KEYWORD_ONLY,
                              inspect.Parameter.POSITIONAL_OR_KEYWORD) \
                        and p.default is not inspect.Parameter.empty:
                    keys.add(p.name)
        except (TypeError, ValueError):
            pass
        keys -= set(self.arg_names)
        keys -= {"_train", "_rng"}
        self._accepted_params = keys
        return keys

    def apply(self, arrays, params):
        """Run the op on raw jax arrays. Returns a tuple of outputs."""
        out = self.fn(*arrays, **params)
        return out if isinstance(out, tuple) else (out,)

    def jitted(self, params):
        """Return (jitted_fn, dynamic_params) for this op.

        ``jitted_fn(arrays_tuple, dynamic_params_dict)`` runs the cached
        compiled program; hashable params are baked in as statics,
        array-valued ones (the rng key) stay traced operands.
        """
        import jax
        static, dynamic = [], {}
        for k, v in params.items():
            if isinstance(v, (list, tuple)):
                v = tuple(v)
            try:
                hash(v)
                static.append((k, v))
            except TypeError:
                dynamic[k] = v
        key = (tuple(sorted(static)), tuple(sorted(dynamic)))
        fn = self._jit_fns.get(key)
        if fn is None:
            static_params = dict(static)
            op_fn = self.fn

            def _pure(arrs, dyn):
                out = op_fn(*arrs, **static_params, **dyn)
                return out if isinstance(out, tuple) else (out,)

            fn = jax.jit(_pure)
            self._jit_fns[key] = fn
        return fn, dynamic


def register(name, nin=1, nout=1, arg_names=None, defaults=None, mutate=(),
             no_grad=False, aliases=(), jit=False):
    """Decorator registering a pure-jax function as an operator."""

    def _reg(fn):
        op = OpDef(name, fn, nin=nin, nout=nout, arg_names=arg_names,
                   defaults=defaults, mutate=mutate, no_grad=no_grad,
                   jit=jit)
        if name in _OPS:
            raise MXNetError("op %r already registered" % name)
        _OPS[name] = op
        for a in aliases:
            _OPS[a] = op
        return fn

    return _reg


def alias(existing, *names):
    op = get_op(existing)
    for n in names:
        _OPS[n] = op


def get_op(name):
    if name not in _OPS:
        raise MXNetError("operator %r is not registered" % (name,))
    return _OPS[name]


def list_ops():
    return sorted(_OPS)


def canonical_params(op, kwargs):
    """Merge defaults, normalise unhashable values for cache keys."""
    params = dict(op.defaults)
    params.update(kwargs)
    return params


@functools.lru_cache(maxsize=None)
def _noop():  # placeholder keeping functools imported for future caching
    return None
