"""Elementwise operators: binary broadcast, scalar, unary, comparisons.

Parity: reference ``src/operator/tensor/elemwise_binary_broadcast_op_*.cc``,
``elemwise_binary_op_*.cc``, ``elemwise_binary_scalar_op_*.cc``,
``elemwise_unary_op.cc`` (the ~40 unary math ops listed there) and
``elemwise_sum.cc`` (add_n). On TPU these all lower to single VPU-fused
XLA HLOs — no hand kernels needed; XLA fuses chains of these into
neighbouring MXU ops.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register, alias

# ---------------------------------------------------------------------------
# Binary broadcast ops (reference: NNVM "broadcast_*" family)
# ---------------------------------------------------------------------------

_BINARY = {
    "broadcast_add": jnp.add,
    "broadcast_sub": jnp.subtract,
    "broadcast_mul": jnp.multiply,
    "broadcast_div": jnp.divide,
    "broadcast_mod": jnp.mod,
    "broadcast_power": jnp.power,
    "broadcast_maximum": jnp.maximum,
    "broadcast_minimum": jnp.minimum,
    "broadcast_hypot": jnp.hypot,
}
_BINARY_ALIASES = {
    "broadcast_add": ("broadcast_plus",),
    "broadcast_sub": ("broadcast_minus",),
}

_CMP = {
    "broadcast_equal": jnp.equal,
    "broadcast_not_equal": jnp.not_equal,
    "broadcast_greater": jnp.greater,
    "broadcast_greater_equal": jnp.greater_equal,
    "broadcast_lesser": jnp.less,
    "broadcast_lesser_equal": jnp.less_equal,
}


def _make_binary(fn, cast_bool):
    def op(lhs, rhs):
        out = fn(lhs, rhs)
        if cast_bool:
            out = out.astype(lhs.dtype)
        return out
    return op


for _name, _fn in _BINARY.items():
    register(_name, nin=2, aliases=_BINARY_ALIASES.get(_name, ()))(_make_binary(_fn, False))
for _name, _fn in _CMP.items():
    # reference comparison ops return same-dtype 0/1 tensors, not bool
    register(_name, nin=2, no_grad=True)(_make_binary(_fn, True))

# elemwise_* are the no-broadcast variants; identical on XLA
for _ew, _bc in [("elemwise_add", jnp.add), ("elemwise_sub", jnp.subtract),
                 ("elemwise_mul", jnp.multiply), ("elemwise_div", jnp.divide)]:
    register(_ew, nin=2)(_make_binary(_bc, False))
alias("elemwise_add", "_add", "_plus", "_Plus")
alias("elemwise_sub", "_sub", "_minus", "_Minus")
alias("elemwise_mul", "_mul", "_Mul")
alias("elemwise_div", "_div", "_Div")


@register("add_n", nin=-1, arg_names=["args"], aliases=("ElementWiseSum", "_sum"))
def add_n(*args):
    """Sum of N tensors (reference src/operator/tensor/elemwise_sum.cc)."""
    out = args[0]
    for a in args[1:]:
        out = out + a
    return out


# ---------------------------------------------------------------------------
# Scalar ops (reference: "_plus_scalar" family backing NDArray operators)
# ---------------------------------------------------------------------------

def _scalar_op(name, fn, reverse=False, cast=False, aliases=()):
    def op(data, scalar=1.0):
        s = jnp.asarray(scalar, dtype=data.dtype)
        out = fn(s, data) if reverse else fn(data, s)
        if cast:
            out = out.astype(data.dtype)
        return out
    register(name, nin=1, defaults={"scalar": 1.0}, no_grad=cast, aliases=aliases)(op)


_scalar_op("_plus_scalar", jnp.add, aliases=("_PlusScalar",))
_scalar_op("_minus_scalar", jnp.subtract, aliases=("_MinusScalar",))
_scalar_op("_rminus_scalar", jnp.subtract, reverse=True, aliases=("_RMinusScalar",))
_scalar_op("_mul_scalar", jnp.multiply, aliases=("_MulScalar",))
_scalar_op("_div_scalar", jnp.divide, aliases=("_DivScalar",))
_scalar_op("_rdiv_scalar", jnp.divide, reverse=True, aliases=("_RDivScalar",))
_scalar_op("_mod_scalar", jnp.mod)
_scalar_op("_rmod_scalar", jnp.mod, reverse=True)
_scalar_op("_power_scalar", jnp.power, aliases=("_PowerScalar",))
_scalar_op("_rpower_scalar", jnp.power, reverse=True, aliases=("_RPowerScalar",))
_scalar_op("_maximum_scalar", jnp.maximum, aliases=("_MaximumScalar",))
_scalar_op("_minimum_scalar", jnp.minimum, aliases=("_MinimumScalar",))
_scalar_op("_hypot_scalar", jnp.hypot)
_scalar_op("_equal_scalar", jnp.equal, cast=True)
_scalar_op("_not_equal_scalar", jnp.not_equal, cast=True)
_scalar_op("_greater_scalar", jnp.greater, cast=True)
_scalar_op("_greater_equal_scalar", jnp.greater_equal, cast=True)
_scalar_op("_lesser_scalar", jnp.less, cast=True)
_scalar_op("_lesser_equal_scalar", jnp.less_equal, cast=True)


# ---------------------------------------------------------------------------
# Unary math ops (reference: elemwise_unary_op.cc)
# ---------------------------------------------------------------------------

_UNARY = {
    "abs": jnp.abs, "sign": jnp.sign, "round": jnp.round, "rint": jnp.rint,
    "ceil": jnp.ceil, "floor": jnp.floor, "trunc": jnp.trunc,
    "fix": jnp.trunc, "square": jnp.square, "sqrt": jnp.sqrt,
    "rsqrt": lambda x: jax.lax.rsqrt(x),
    "cbrt": jnp.cbrt, "rcbrt": lambda x: 1.0 / jnp.cbrt(x),
    "exp": jnp.exp, "log": jnp.log, "log10": jnp.log10, "log2": jnp.log2,
    "log1p": jnp.log1p, "expm1": jnp.expm1,
    "sin": jnp.sin, "cos": jnp.cos, "tan": jnp.tan,
    "arcsin": jnp.arcsin, "arccos": jnp.arccos, "arctan": jnp.arctan,
    "degrees": jnp.degrees, "radians": jnp.radians,
    "sinh": jnp.sinh, "cosh": jnp.cosh, "tanh": jnp.tanh,
    "arcsinh": jnp.arcsinh, "arccosh": jnp.arccosh, "arctanh": jnp.arctanh,
    "gamma": lambda x: jnp.exp(jax.scipy.special.gammaln(x)),
    "gammaln": jax.scipy.special.gammaln,
    "erf": jax.scipy.special.erf,
    "negative": jnp.negative,
    "reciprocal": lambda x: 1.0 / x,
    "logical_not": lambda x: (x == 0).astype(x.dtype),
}
_UNARY_NO_GRAD = {"sign", "round", "rint", "ceil", "floor", "trunc", "fix",
                  "logical_not"}
_UNARY_ALIASES = {"abs": ("_abs",), "negative": ("_negative",)}

for _name, _fn in _UNARY.items():
    register(_name, nin=1, no_grad=_name in _UNARY_NO_GRAD,
             aliases=_UNARY_ALIASES.get(_name, ()))(_fn)


@register("relu")
def relu(data):
    """Rectified linear unit (reference elemwise_unary_op.cc "relu")."""
    return jnp.maximum(data, 0)


@register("sigmoid")
def sigmoid(data):
    return jax.nn.sigmoid(data)


@register("softsign")
def softsign(data):
    return data / (1 + jnp.abs(data))


@register("clip", defaults={"a_min": 0.0, "a_max": 1.0})
def clip(data, a_min=0.0, a_max=1.0):
    return jnp.clip(data, a_min, a_max)


@register("_copy", aliases=("identity",))
def _copy(data):
    return data


@register("BlockGrad", aliases=("stop_gradient",))
def block_grad(data):
    """Stop gradient flow (reference elemwise_unary_op.cc BlockGrad)."""
    return jax.lax.stop_gradient(data)


@register("make_loss")
def make_loss_op(data):
    return data


@register("_identity_with_attr_like_rhs", nin=2)
def _identity_with_attr_like_rhs(lhs, rhs):
    return lhs


@register("Cast", defaults={"dtype": "float32"}, aliases=("cast",))
def cast(data, dtype="float32"):
    from .common import mx_dtype
    return data.astype(mx_dtype(dtype))


@register("zeros_like")
def zeros_like(data):
    return jnp.zeros_like(data)


@register("ones_like")
def ones_like(data):
    return jnp.ones_like(data)


@register("smooth_l1", defaults={"scalar": 1.0})
def smooth_l1(data, scalar=1.0):
    """Smooth L1 (reference elemwise_binary_scalar_op_extended.cc; used by SSD).

    f(x) = 0.5 (sigma x)^2 if |x| < 1/sigma^2 else |x| - 0.5/sigma^2
    """
    sigma2 = scalar * scalar
    absx = jnp.abs(data)
    return jnp.where(absx < 1.0 / sigma2, 0.5 * sigma2 * data * data,
                     absx - 0.5 / sigma2)
