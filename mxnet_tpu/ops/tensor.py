"""Shape-manipulation and indexing operators.

Parity: reference ``src/operator/tensor/matrix_op.cc`` (Reshape w/ special
codes, transpose, slice, Concat, tile, repeat, reverse, …),
``indexing_op.cc`` (take, batch_take, one_hot, Embedding, gather_nd),
``src/operator/slice_channel.cc``, ``src/operator/pad.cc``,
``src/operator/swapaxis.cc``, ``src/operator/crop.cc``.
"""
from __future__ import annotations

import ast

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .common import as_tuple, as_axis, mx_dtype
from .registry import register


def infer_reshape(src_shape, target, reverse=False):
    """Implement MXNet Reshape's special codes (reference matrix_op.cc ReshapeShape).

    0 = copy dim; -1 = infer; -2 = copy rest; -3 = merge next two;
    -4 = split (followed by two dims, one may be -1).
    """
    if isinstance(target, str):
        target = ast.literal_eval(target)
    target = list(int(x) for x in target)
    src = list(src_shape)
    if reverse:
        src = src[::-1]
        target = target[::-1]
        # -4's two factor dims also reverse; handle by simple swap after parse
    out = []
    i = 0  # position in src
    j = 0
    while j < len(target):
        t = target[j]
        if t > 0:
            out.append(t)
            i += 1
        elif t == 0:
            if i >= len(src):
                raise MXNetError("reshape: 0 with no corresponding src dim")
            out.append(src[i])
            i += 1
        elif t == -1:
            out.append(-1)
            i += 1
        elif t == -2:
            out.extend(src[i:])
            i = len(src)
        elif t == -3:
            if i + 1 >= len(src):
                raise MXNetError("reshape: -3 needs two src dims")
            out.append(src[i] * src[i + 1])
            i += 2
        elif t == -4:
            d1, d2 = target[j + 1], target[j + 2]
            j += 2
            cur = src[i]
            if d1 == -1:
                d1 = cur // d2
            if d2 == -1:
                d2 = cur // d1
            out.extend([d1, d2])
            i += 1
        else:
            raise MXNetError("reshape: invalid code %d" % t)
        j += 1
    if out.count(-1) > 1:
        raise MXNetError("reshape: more than one -1")
    known = int(np.prod([d for d in out if d != -1], dtype=np.int64)) if out else 1
    total = int(np.prod(src_shape, dtype=np.int64))
    if -1 in out:
        out[out.index(-1)] = total // max(known, 1)
    if reverse:
        out = out[::-1]
    return tuple(out)


@register("Reshape", defaults={"shape": (), "reverse": False}, aliases=("reshape",))
def reshape(data, shape=(), reverse=False, **ignored):
    return jnp.reshape(data, infer_reshape(data.shape, shape, reverse))


@register("Flatten", aliases=("flatten",))
def flatten(data):
    return jnp.reshape(data, (data.shape[0], -1))


@register("transpose", defaults={"axes": ()})
def transpose(data, axes=()):
    axes = as_tuple(axes)
    return jnp.transpose(data, axes if axes else None)


@register("expand_dims", defaults={"axis": 0})
def expand_dims(data, axis=0):
    return jnp.expand_dims(data, int(axis))


@register("squeeze", defaults={"axis": None})
def squeeze(data, axis=None):
    return jnp.squeeze(data, as_axis(axis))


@register("slice", defaults={"begin": (), "end": (), "step": ()},
          aliases=("crop",))
def slice_op(data, begin=(), end=(), step=()):
    """Slice with per-axis begin/end/step; None entries mean full range
    (reference matrix_op.cc Slice)."""
    def _parse(v):
        if isinstance(v, str):
            v = ast.literal_eval(v)
        if v is None:
            return ()
        return tuple(v) if isinstance(v, (list, tuple)) else (v,)
    begin, end, step = _parse(begin), _parse(end), _parse(step)
    idx = []
    for ax in range(data.ndim):
        b = begin[ax] if ax < len(begin) else None
        e = end[ax] if ax < len(end) else None
        s = step[ax] if ax < len(step) and step[ax] is not None else 1
        idx.append(slice(b, e, s))
    return data[tuple(idx)]


@register("slice_axis", defaults={"axis": 0, "begin": 0, "end": None})
def slice_axis(data, axis=0, begin=0, end=None):
    axis = int(axis) % data.ndim
    idx = [slice(None)] * data.ndim
    idx[axis] = slice(int(begin), None if end in (None, "None") else int(end))
    return data[tuple(idx)]


@register("slice_like", nin=2, arg_names=["data", "shape_like"],
          defaults={"axes": ()})
def slice_like(data, shape_like, axes=()):
    axes = as_tuple(axes) or tuple(range(data.ndim))
    idx = [slice(None)] * data.ndim
    for ax in axes:
        idx[ax % data.ndim] = slice(0, shape_like.shape[ax % data.ndim])
    return data[tuple(idx)]


@register("Concat", nin=-1, defaults={"dim": 1}, aliases=("concat",))
def concat(*args, dim=1, num_args=None):
    return jnp.concatenate(args, axis=int(dim))


@register("stack", nin=-1, defaults={"axis": 0})
def stack(*args, axis=0, num_args=None):
    return jnp.stack(args, axis=int(axis))


@register("SliceChannel", nout=-1,
          defaults={"num_outputs": 1, "axis": 1, "squeeze_axis": False},
          aliases=("split",))
def slice_channel(data, num_outputs=1, axis=1, squeeze_axis=False):
    """Split along axis into num_outputs parts (reference slice_channel.cc)."""
    parts = jnp.split(data, int(num_outputs), axis=int(axis))
    if squeeze_axis:
        parts = [jnp.squeeze(p, axis=int(axis)) for p in parts]
    return tuple(parts) if len(parts) > 1 else parts[0]


@register("tile", defaults={"reps": ()})
def tile(data, reps=()):
    return jnp.tile(data, as_tuple(reps))


@register("repeat", defaults={"repeats": 1, "axis": None})
def repeat(data, repeats=1, axis=None):
    return jnp.repeat(data, int(repeats), axis=as_axis(axis))


@register("reverse", defaults={"axis": ()}, aliases=("flip",))
def reverse(data, axis=()):
    return jnp.flip(data, as_tuple(axis))


@register("SwapAxis", defaults={"dim1": 0, "dim2": 0}, aliases=("swapaxes",))
def swapaxes(data, dim1=0, dim2=0):
    return jnp.swapaxes(data, int(dim1), int(dim2))


@register("Pad", defaults={"mode": "constant", "pad_width": (), "constant_value": 0.0},
          aliases=("pad",))
def pad(data, mode="constant", pad_width=(), constant_value=0.0):
    """Pad 4-D/5-D input (reference src/operator/pad.cc); pad_width comes in
    flattened (before, after) pairs per axis."""
    pw = as_tuple(pad_width)
    pairs = [(pw[2 * i], pw[2 * i + 1]) for i in range(len(pw) // 2)]
    while len(pairs) < data.ndim:
        pairs.append((0, 0))
    if mode == "constant":
        return jnp.pad(data, pairs, constant_values=constant_value)
    return jnp.pad(data, pairs, mode={"edge": "edge", "reflect": "reflect"}[mode])


@register("where", nin=3, arg_names=["condition", "x", "y"])
def where(condition, x, y):
    """(reference control_flow_op.cc where): condition either matches
    x/y's shape elementwise, or is a 1-D batch vector selecting whole
    rows (csr-condition form of the reference)."""
    cond = condition.astype(bool)
    if cond.ndim == 1 and x.ndim > 1 and cond.shape[0] == x.shape[0]:
        cond = cond.reshape((-1,) + (1,) * (x.ndim - 1))
    return jnp.where(cond, x, y)


# ---------------------------------------------------------------------------
# Indexing ops (reference src/operator/tensor/indexing_op.cc)
# ---------------------------------------------------------------------------

@register("take", nin=2, arg_names=["a", "indices"],
          defaults={"axis": 0, "mode": "clip"})
def take(a, indices, axis=0, mode="clip"):
    idx = indices.astype(jnp.int32)
    n = a.shape[int(axis)]
    if mode == "wrap":
        idx = jnp.mod(idx, n)
    else:
        idx = jnp.clip(idx, 0, n - 1)
    return jnp.take(a, idx, axis=int(axis))


@register("batch_take", nin=2, arg_names=["a", "indices"])
def batch_take(a, indices):
    idx = jnp.clip(indices.astype(jnp.int32), 0, a.shape[1] - 1)
    return jnp.take_along_axis(a, idx.reshape(-1, 1), axis=1).reshape(idx.shape)


@register("one_hot", defaults={"depth": 1, "on_value": 1.0, "off_value": 0.0,
                               "dtype": "float32"}, no_grad=True)
def one_hot(indices, depth=1, on_value=1.0, off_value=0.0, dtype="float32"):
    d = mx_dtype(dtype)
    oh = jax.nn.one_hot(indices.astype(jnp.int32), int(depth), dtype=d)
    return oh * jnp.asarray(on_value, d) + (1 - oh) * jnp.asarray(off_value, d)


@register("Embedding", nin=2, arg_names=["data", "weight"],
          defaults={"input_dim": 0, "output_dim": 0, "dtype": "float32",
                    "sparse_grad": False})
def embedding(data, weight, input_dim=0, output_dim=0, dtype="float32",
              sparse_grad=False):
    """Embedding lookup (reference indexing_op.cc Embedding). On TPU this is
    a gather that XLA lowers efficiently; sharded variants live in
    mxnet_tpu.parallel."""
    idx = data.astype(jnp.int32)
    return jnp.take(weight, idx, axis=0)


@register("gather_nd", nin=2, arg_names=["data", "indices"])
def gather_nd(data, indices):
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    return data[tuple(idx[i] for i in range(m))]


@register("scatter_nd", nin=2, arg_names=["data", "indices"],
          defaults={"shape": ()})
def scatter_nd(data, indices, shape=()):
    shape = as_tuple(shape)
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].set(data)


@register("_grad_add_nd", nin=2, arg_names=["data", "indices"],
          defaults={"shape": ()})
def _scatter_nd_acc(data, indices, shape=()):
    shape = as_tuple(shape)
    idx = indices.astype(jnp.int32)
    m = idx.shape[0]
    out = jnp.zeros(shape, dtype=data.dtype)
    return out.at[tuple(idx[i] for i in range(m))].add(data)


# ---------------------------------------------------------------------------
# Creation ops (nin=0; reference tensor/init_op.cc)
# ---------------------------------------------------------------------------

def _creation_params(shape, dtype):
    return as_tuple(shape) or (), mx_dtype(dtype) or jnp.float32


@register("_zeros", nin=0, defaults={"shape": (), "dtype": "float32"}, no_grad=True)
def _zeros(shape=(), dtype="float32", ctx=None):
    shape, dtype = _creation_params(shape, dtype)
    return jnp.zeros(shape, dtype)


@register("_ones", nin=0, defaults={"shape": (), "dtype": "float32"}, no_grad=True)
def _ones(shape=(), dtype="float32", ctx=None):
    shape, dtype = _creation_params(shape, dtype)
    return jnp.ones(shape, dtype)


@register("_full", nin=0, defaults={"shape": (), "dtype": "float32", "value": 0.0},
          no_grad=True)
def _full(shape=(), dtype="float32", value=0.0, ctx=None):
    shape, dtype = _creation_params(shape, dtype)
    return jnp.full(shape, value, dtype)


@register("_arange", nin=0,
          defaults={"start": 0, "stop": None, "step": 1.0, "repeat": 1,
                    "dtype": "float32"}, no_grad=True)
def _arange(start=0, stop=None, step=1.0, repeat=1, dtype="float32", ctx=None,
            infer_range=False):
    out = jnp.arange(start, None if stop in (None, "None") else stop, step,
                     dtype=mx_dtype(dtype))
    if int(repeat) > 1:
        out = jnp.repeat(out, int(repeat))
    return out


@register("_eye", nin=0, defaults={"N": 0, "M": 0, "k": 0, "dtype": "float32"},
          no_grad=True)
def _eye(N=0, M=0, k=0, dtype="float32", ctx=None):
    M = int(M) or int(N)
    return jnp.eye(int(N), M, int(k), dtype=mx_dtype(dtype))


# ---------------------------------------------------------------------------
# Matrix products (reference tensor/dot.cc)
# ---------------------------------------------------------------------------

@register("dot", nin=2, defaults={"transpose_a": False, "transpose_b": False})
def dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    """Generalised dot (reference dot.cc): contracts last axis of lhs with
    first axis of rhs. Lowers straight onto the MXU."""
    a = lhs.T if transpose_a else lhs
    b = rhs.T if transpose_b else rhs
    if a.ndim == 1 and b.ndim == 1:
        return jnp.dot(a, b).reshape((1,))
    return jnp.tensordot(a, b, axes=1)


@register("batch_dot", nin=2, defaults={"transpose_a": False, "transpose_b": False})
def batch_dot(lhs, rhs, transpose_a=False, transpose_b=False, forward_stype=None):
    a = jnp.swapaxes(lhs, -1, -2) if transpose_a else lhs
    b = jnp.swapaxes(rhs, -1, -2) if transpose_b else rhs
    return jnp.matmul(a, b)
