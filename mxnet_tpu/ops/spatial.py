"""Spatial transform operators.

Parity: reference ``src/operator/grid_generator.cc``,
``bilinear_sampler.cc``, ``spatial_transformer.cc`` (+ cudnn paths).
Bilinear interpolation is a gather+lerp — VPU-bound, XLA fuses it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..base import MXNetError
from .common import as_tuple
from .registry import register


def _bilinear_sample(data, grid_x, grid_y):
    """data (C, H, W); grid_x/grid_y (Ho, Wo) in [-1, 1] -> (C, Ho, Wo)."""
    C, H, W = data.shape
    x = (grid_x + 1) * (W - 1) / 2
    y = (grid_y + 1) * (H - 1) / 2
    x0 = jnp.floor(x)
    y0 = jnp.floor(y)
    x1 = x0 + 1
    y1 = y0 + 1
    wx1 = x - x0
    wy1 = y - y0
    wx0 = 1 - wx1
    wy0 = 1 - wy1

    def gather(yy, xx):
        inb = (xx >= 0) & (xx <= W - 1) & (yy >= 0) & (yy <= H - 1)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        vals = data[:, yi, xi]            # (C, Ho, Wo)
        return jnp.where(inb[None], vals, 0.0)

    return (gather(y0, x0) * (wy0 * wx0)[None]
            + gather(y0, x1) * (wy0 * wx1)[None]
            + gather(y1, x0) * (wy1 * wx0)[None]
            + gather(y1, x1) * (wy1 * wx1)[None])


@register("BilinearSampler", nin=2, arg_names=["data", "grid"])
def bilinear_sampler(data, grid):
    """(reference bilinear_sampler.cc) data (B,C,H,W); grid (B,2,Ho,Wo)
    normalised to [-1,1]."""
    def one(d, g):
        return _bilinear_sample(d, g[0], g[1])
    return jax.vmap(one)(data, grid)


@register("GridGenerator", defaults={"transform_type": "affine",
                                     "target_shape": ()})
def grid_generator(data, transform_type="affine", target_shape=()):
    """(reference grid_generator.cc) affine: data (B, 6) -> grid
    (B, 2, H, W); warp: data (B, 2, H, W) flow -> grid."""
    if transform_type == "affine":
        H, W = as_tuple(target_shape, 2)
        ys = jnp.linspace(-1, 1, H)
        xs = jnp.linspace(-1, 1, W)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        ones = jnp.ones_like(gx)
        base = jnp.stack([gx, gy, ones], axis=0).reshape(3, -1)  # (3, H*W)

        def one(theta):
            m = theta.reshape(2, 3)
            out = m @ base                                # (2, H*W)
            return out.reshape(2, H, W)
        return jax.vmap(one)(data)
    if transform_type == "warp":
        B, _, H, W = data.shape
        ys = jnp.arange(H, dtype=data.dtype)
        xs = jnp.arange(W, dtype=data.dtype)
        gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
        x = (data[:, 0] + gx) * 2 / jnp.maximum(W - 1, 1) - 1
        y = (data[:, 1] + gy) * 2 / jnp.maximum(H - 1, 1) - 1
        return jnp.stack([x, y], axis=1)
    raise MXNetError("unknown transform_type %r" % transform_type)


@register("SpatialTransformer", nin=2, arg_names=["data", "loc"],
          defaults={"target_shape": (), "transform_type": "affine",
                    "sampler_type": "bilinear", "cudnn_off": False})
def spatial_transformer(data, loc, target_shape=(), transform_type="affine",
                        sampler_type="bilinear", cudnn_off=False):
    """(reference spatial_transformer.cc) — affine grid + bilinear sample."""
    grid = grid_generator(loc, transform_type="affine",
                          target_shape=target_shape)
    return bilinear_sampler(data, grid)
