"""CTC loss operator.

Parity: reference ``src/operator/contrib/ctc_loss-inl.h`` (vendored
warp-ctc kernels). TPU-native design: the alpha recursion runs as a
``lax.scan`` over time with the batch and extended-label dimensions
vectorised — a static-shape log-domain dynamic program XLA maps onto the
VPU. Blank label is index 0 ('first', the gluon default); label padding
is any value < 1 when label_lengths is not given.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .registry import register

_NEG_INF = -1e30


@register("_ctc_loss", nin=-1, jit=True, arg_names=["data", "label"],
          aliases=("ctc_loss", "_contrib_ctc_loss"))
def ctc_loss(data, label, data_lengths=None, label_lengths=None):
    """data: (N, T, C) unnormalised activations; label: (N, L) with classes
    in [1, C); returns per-sample negative log likelihood (N,)."""
    N, T, C = data.shape
    L = label.shape[1]
    logp = jax.nn.log_softmax(data, axis=-1)  # (N, T, C)

    lab = label.astype(jnp.int32)
    if label_lengths is None:
        lab_len = jnp.sum((lab > 0).astype(jnp.int32), axis=1)
    else:
        lab_len = label_lengths.astype(jnp.int32)
    if data_lengths is None:
        seq_len = jnp.full((N,), T, jnp.int32)
    else:
        seq_len = data_lengths.astype(jnp.int32)

    # extended label sequence with interleaved blanks: length S = 2L+1
    S = 2 * L + 1
    ext = jnp.zeros((N, S), jnp.int32)
    ext = ext.at[:, 1::2].set(lab)                      # blanks at even pos
    ext_len = 2 * lab_len + 1

    pos = jnp.arange(S)
    # can skip from s-2 when ext[s] != blank and ext[s] != ext[s-2]
    ext_prev2 = jnp.concatenate(
        [jnp.zeros((N, 2), jnp.int32), ext[:, :-2]], axis=1)
    can_skip = (ext != 0) & (ext != ext_prev2) & (pos >= 2)[None, :]

    # alpha_0
    alpha0 = jnp.full((N, S), _NEG_INF)
    alpha0 = alpha0.at[:, 0].set(logp[:, 0, 0])
    first_lab = jnp.take_along_axis(logp[:, 0, :], ext[:, 1:2], axis=1)[:, 0]
    alpha0 = alpha0.at[:, 1].set(jnp.where(lab_len > 0, first_lab, _NEG_INF))

    batch_idx = jnp.arange(N)[:, None]

    def step(alpha, t):
        lp_t = logp[:, t, :]                       # (N, C)
        emit = lp_t[batch_idx, ext]                # (N, S)
        shift1 = jnp.concatenate(
            [jnp.full((N, 1), _NEG_INF), alpha[:, :-1]], axis=1)
        shift2 = jnp.concatenate(
            [jnp.full((N, 2), _NEG_INF), alpha[:, :-2]], axis=1)
        shift2 = jnp.where(can_skip, shift2, _NEG_INF)
        merged = jnp.logaddexp(jnp.logaddexp(alpha, shift1), shift2)
        new_alpha = merged + emit
        # freeze past each sample's sequence end
        active = (t < seq_len)[:, None]
        new_alpha = jnp.where(active, new_alpha, alpha)
        return new_alpha, None

    alpha, _ = jax.lax.scan(step, alpha0, jnp.arange(1, T))

    last = jnp.take_along_axis(alpha, (ext_len - 1)[:, None], axis=1)[:, 0]
    last2 = jnp.take_along_axis(
        alpha, jnp.maximum(ext_len - 2, 0)[:, None], axis=1)[:, 0]
    ll = jnp.logaddexp(last, jnp.where(lab_len > 0, last2, _NEG_INF))
    return -ll
