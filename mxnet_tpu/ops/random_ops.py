"""Random sampling operators.

Parity: reference ``src/operator/random/sample_op.cc`` (_random_* drawing a
tensor from one distribution) and ``multisample_op.cc`` (sample_* drawing
per-element from tensor-parameterised distributions). The reference uses
per-device PRNG Resource pools; here each call gets a fresh key from the
execution context (`_rng`, see ops/common.py) so the same ops are usable
both eagerly and inside jitted graphs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .common import as_tuple, mx_dtype
from .registry import register


def _shape_dtype(shape, dtype):
    return as_tuple(shape) or (), mx_dtype(dtype) or jnp.float32


@register("_random_uniform", nin=0,
          defaults={"low": 0.0, "high": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("uniform", "random_uniform"))
def random_uniform(low=0.0, high=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    return jax.random.uniform(_rng, shape, dtype=dtype, minval=low, maxval=high)


@register("_random_normal", nin=0,
          defaults={"loc": 0.0, "scale": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("normal", "random_normal"))
def random_normal(loc=0.0, scale=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    return loc + scale * jax.random.normal(_rng, shape, dtype=dtype)


@register("_random_gamma", nin=0,
          defaults={"alpha": 1.0, "beta": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("random_gamma",))
def random_gamma(alpha=1.0, beta=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    return jax.random.gamma(_rng, alpha, shape, dtype=dtype) * beta


@register("_random_exponential", nin=0,
          defaults={"lam": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("random_exponential",))
def random_exponential(lam=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    return jax.random.exponential(_rng, shape, dtype=dtype) / lam


@register("_random_poisson", nin=0,
          defaults={"lam": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("random_poisson",))
def random_poisson(lam=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    return jax.random.poisson(_rng, lam, shape).astype(dtype)


@register("_random_negative_binomial", nin=0,
          defaults={"k": 1, "p": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("random_negative_binomial",))
def random_negative_binomial(k=1, p=1.0, shape=(), dtype="float32", ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    k1, k2 = jax.random.split(_rng)
    lam = jax.random.gamma(k1, float(k), shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(dtype)


@register("_random_generalized_negative_binomial", nin=0,
          defaults={"mu": 1.0, "alpha": 1.0, "shape": (), "dtype": "float32"},
          no_grad=True, aliases=("random_generalized_negative_binomial",))
def random_gen_neg_binomial(mu=1.0, alpha=1.0, shape=(), dtype="float32",
                            ctx=None, _rng=None):
    shape, dtype = _shape_dtype(shape, dtype)
    k1, k2 = jax.random.split(_rng)
    r = 1.0 / alpha
    p = r / (r + mu)
    lam = jax.random.gamma(k1, r, shape) * (1 - p) / p
    return jax.random.poisson(k2, lam, shape).astype(dtype)


# -- sample_* family: tensor-parameterised, one draw per parameter element --

def _multisample(draw):
    def op(*params, shape=(), dtype=None, _rng=None):
        shape = as_tuple(shape) or ()
        p0 = params[0]
        out_shape = p0.shape + shape
        expanded = [p.reshape(p.shape + (1,) * len(shape)) for p in params]
        return draw(_rng, expanded, out_shape,
                    mx_dtype(dtype) or jnp.result_type(p0))
    return op


register("_sample_uniform", nin=2, arg_names=["low", "high"],
         defaults={"shape": (), "dtype": None}, no_grad=True,
         aliases=("sample_uniform",))(
    _multisample(lambda k, p, s, d: p[0] + (p[1] - p[0]) * jax.random.uniform(k, s, dtype=d)))
register("_sample_normal", nin=2, arg_names=["mu", "sigma"],
         defaults={"shape": (), "dtype": None}, no_grad=True,
         aliases=("sample_normal",))(
    _multisample(lambda k, p, s, d: p[0] + p[1] * jax.random.normal(k, s, dtype=d)))
register("_sample_gamma", nin=2, arg_names=["alpha", "beta"],
         defaults={"shape": (), "dtype": None}, no_grad=True,
         aliases=("sample_gamma",))(
    _multisample(lambda k, p, s, d: jax.random.gamma(k, jnp.broadcast_to(p[0], s), s).astype(d) * p[1]))
register("_sample_exponential", nin=1, arg_names=["lam"],
         defaults={"shape": (), "dtype": None}, no_grad=True,
         aliases=("sample_exponential",))(
    _multisample(lambda k, p, s, d: jax.random.exponential(k, s, dtype=d) / p[0]))
register("_sample_poisson", nin=1, arg_names=["lam"],
         defaults={"shape": (), "dtype": None}, no_grad=True,
         aliases=("sample_poisson",))(
    _multisample(lambda k, p, s, d: jax.random.poisson(k, jnp.broadcast_to(p[0], s), s).astype(d)))


@register("_sample_multinomial", nin=1, arg_names=["data"],
          defaults={"shape": (), "get_prob": False, "dtype": "int32"},
          no_grad=True, aliases=("sample_multinomial",))
def sample_multinomial(data, shape=(), get_prob=False, dtype="int32", _rng=None):
    """Categorical sampling (reference random/multisample_op.cc multinomial)."""
    shape = as_tuple(shape) or ()
    n = 1
    for s in shape:
        n *= s
    logits = jnp.log(jnp.maximum(data, 1e-30))
    batch = data.shape[:-1]
    idx = jax.random.categorical(_rng, logits, axis=-1,
                                 shape=(n,) + batch)
    idx = jnp.moveaxis(idx, 0, -1).reshape(batch + shape)
    out = idx.astype(mx_dtype(dtype))
    if get_prob:
        logp = jnp.log(jnp.maximum(data, 1e-30))
        picked = jnp.take_along_axis(
            logp.reshape(batch + (1,) * max(len(shape), 1) + (data.shape[-1],)),
            idx.reshape(batch + shape[:max(len(shape), 1)] + (1,)).astype(jnp.int32)
            if shape else idx.reshape(batch + (1, 1)).astype(jnp.int32)[..., 0, :],
            axis=-1)
        return out, picked.reshape(out.shape)
    return out


@register("_shuffle", no_grad=True, aliases=("shuffle",))
def shuffle(data, _rng=None):
    return jax.random.permutation(_rng, data, axis=0)
