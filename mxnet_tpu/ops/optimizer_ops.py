"""Fused optimizer update operators.

Parity: reference ``src/operator/optimizer_op.cc:39-299`` — sgd_update,
sgd_mom_update, mp_sgd_* (fp16 weights with fp32 master copy), adam_update,
rmsprop_update, rmspropalex_update, ftrl_update. Each op returns the new
weight (plus new state tensors); the imperative wrapper writes them back
into the input NDArrays (declared via ``mutate``), mirroring the
reference's in-place kernels. Under jit the whole update fuses into one
HBM-bandwidth-bound elementwise kernel per parameter — the same reason the
reference fused these into single CUDA kernels.
"""
from __future__ import annotations

import jax.numpy as jnp

from .registry import register


def _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient):
    grad = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        grad = jnp.clip(grad, -clip_gradient, clip_gradient)
    return grad + wd * weight


@register("sgd_update", nin=2, arg_names=["weight", "grad"],
          defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                    "clip_gradient": -1.0},
          mutate=(0,))
def sgd_update(weight, grad, lr=0.01, wd=0.0, rescale_grad=1.0,
               clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient)
    return weight - lr * g


@register("sgd_mom_update", nin=3, arg_names=["weight", "grad", "mom"],
          defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                    "rescale_grad": 1.0, "clip_gradient": -1.0},
          mutate=(0, 2), nout=2)
def sgd_mom_update(weight, grad, mom, lr=0.01, momentum=0.0, wd=0.0,
                   rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient)
    mom = momentum * mom - lr * g
    return weight + mom, mom


@register("mp_sgd_update", nin=3, arg_names=["weight", "grad", "weight32"],
          defaults={"lr": 0.01, "wd": 0.0, "rescale_grad": 1.0,
                    "clip_gradient": -1.0},
          mutate=(0, 2), nout=2)
def mp_sgd_update(weight, grad, weight32, lr=0.01, wd=0.0, rescale_grad=1.0,
                  clip_gradient=-1.0):
    """Multi-precision SGD: bf16/fp16 weight, fp32 master copy
    (reference optimizer_op.cc MP_SGD)."""
    g = _apply_wd_clip(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                       clip_gradient)
    w32 = weight32 - lr * g
    return w32.astype(weight.dtype), w32


@register("mp_sgd_mom_update", nin=4,
          arg_names=["weight", "grad", "mom", "weight32"],
          defaults={"lr": 0.01, "momentum": 0.0, "wd": 0.0,
                    "rescale_grad": 1.0, "clip_gradient": -1.0},
          mutate=(0, 2, 3), nout=3)
def mp_sgd_mom_update(weight, grad, mom, weight32, lr=0.01, momentum=0.0,
                      wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight32, grad.astype(jnp.float32), wd, rescale_grad,
                       clip_gradient)
    mom = momentum * mom - lr * g
    w32 = weight32 + mom
    return w32.astype(weight.dtype), mom, w32


@register("adam_update", nin=4, arg_names=["weight", "grad", "mean", "var"],
          defaults={"lr": 0.001, "beta1": 0.9, "beta2": 0.999, "epsilon": 1e-8,
                    "wd": 0.0, "rescale_grad": 1.0, "clip_gradient": -1.0},
          mutate=(0, 2, 3), nout=3)
def adam_update(weight, grad, mean, var, lr=0.001, beta1=0.9, beta2=0.999,
                epsilon=1e-8, wd=0.0, rescale_grad=1.0, clip_gradient=-1.0):
    g = _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient)
    mean = beta1 * mean + (1 - beta1) * g
    var = beta2 * var + (1 - beta2) * jnp.square(g)
    w = weight - lr * mean / (jnp.sqrt(var) + epsilon)
    return w, mean, var


@register("rmsprop_update", nin=3, arg_names=["weight", "grad", "n"],
          defaults={"lr": 0.001, "gamma1": 0.95, "epsilon": 1e-8, "wd": 0.0,
                    "rescale_grad": 1.0, "clip_gradient": -1.0,
                    "clip_weights": -1.0},
          mutate=(0, 2), nout=2)
def rmsprop_update(weight, grad, n, lr=0.001, gamma1=0.95, epsilon=1e-8,
                   wd=0.0, rescale_grad=1.0, clip_gradient=-1.0,
                   clip_weights=-1.0):
    g = _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient)
    n = gamma1 * n + (1 - gamma1) * jnp.square(g)
    w = weight - lr * g / jnp.sqrt(n + epsilon)
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n


@register("rmspropalex_update", nin=5,
          arg_names=["weight", "grad", "n", "g", "delta"],
          defaults={"lr": 0.001, "gamma1": 0.95, "gamma2": 0.9,
                    "epsilon": 1e-8, "wd": 0.0, "rescale_grad": 1.0,
                    "clip_gradient": -1.0, "clip_weights": -1.0},
          mutate=(0, 2, 3, 4), nout=4)
def rmspropalex_update(weight, grad, n, g, delta, lr=0.001, gamma1=0.95,
                       gamma2=0.9, epsilon=1e-8, wd=0.0, rescale_grad=1.0,
                       clip_gradient=-1.0, clip_weights=-1.0):
    """RMSProp (Graves 2013 variant) — reference optimizer_op.cc."""
    gr = _apply_wd_clip(weight, grad, wd, rescale_grad, clip_gradient)
    n = gamma1 * n + (1 - gamma1) * jnp.square(gr)
    g = gamma1 * g + (1 - gamma1) * gr
    delta = gamma2 * delta - lr * gr / jnp.sqrt(n - jnp.square(g) + epsilon)
    w = weight + delta
    if clip_weights is not None and clip_weights > 0:
        w = jnp.clip(w, -clip_weights, clip_weights)
    return w, n, g, delta


@register("ftrl_update", nin=4, arg_names=["weight", "grad", "z", "n"],
          defaults={"lr": 0.1, "lamda1": 0.01, "beta": 1.0, "wd": 0.0,
                    "rescale_grad": 1.0, "clip_gradient": -1.0},
          mutate=(0, 2, 3), nout=3)
def ftrl_update(weight, grad, z, n, lr=0.1, lamda1=0.01, beta=1.0, wd=0.0,
                rescale_grad=1.0, clip_gradient=-1.0):
    g = grad * rescale_grad
    if clip_gradient is not None and clip_gradient >= 0:
        g = jnp.clip(g, -clip_gradient, clip_gradient)
    new_n = n + jnp.square(g)
    sigma = (jnp.sqrt(new_n) - jnp.sqrt(n)) / lr
    z = z + g - sigma * weight
    w = jnp.where(
        jnp.abs(z) <= lamda1,
        jnp.zeros_like(weight),
        -(z - jnp.sign(z) * lamda1) / ((beta + jnp.sqrt(new_n)) / lr + wd))
    return w, z, new_n
