"""Neural-network operators.

Parity: reference ``src/operator/`` legacy layer ops (fully_connected-inl.h,
convolution-inl.h + cudnn_convolution, pooling-inl.h, batch_norm.cc,
activation-inl.h, leaky_relu-inl.h, dropout-inl.h, lrn-inl.h,
l2_normalization-inl.h, instance_norm-inl.h, upsampling-inl.h,
softmax_output-inl.h, regression_output-inl.h, make_loss-inl.h) and
``src/operator/nn/softmax-inl.h``.

TPU-first notes: convs/matmuls map directly onto the MXU via
``lax.conv_general_dilated`` / ``jnp.dot`` — XLA picks layouts and fuses
the elementwise epilogues (bias, activation, BN scale) into them, which
is what the reference needed cuDNN fused kernels for. Ops that behave
differently in train vs inference (BatchNorm, Dropout) take a ``_train``
flag injected by the execution layer; random ops take ``_rng``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .common import as_tuple, channels_last, mx_dtype
from .registry import register, get_op


# ---------------------------------------------------------------------------
# FullyConnected
# ---------------------------------------------------------------------------

@register("FullyConnected", nin=3, arg_names=["data", "weight", "bias"],
          defaults={"num_hidden": 0, "no_bias": False, "flatten": True})
def fully_connected(data, weight, bias=None, num_hidden=0, no_bias=False,
                    flatten=True):
    """y = x W^T + b (reference fully_connected-inl.h:69-114, linalg_gemm).

    Weight layout (num_hidden, in_units) matches the reference exactly.
    """
    if flatten and data.ndim > 2:
        data = data.reshape(data.shape[0], -1)
    out = jnp.dot(data, weight.T)
    if not no_bias and bias is not None:
        out = out + bias
    return out


# ---------------------------------------------------------------------------
# Convolution / Deconvolution
# ---------------------------------------------------------------------------

def _s2d_applicable(data, kernel, stride, dilate, pad, num_group, is_cl,
                    ndim):
    """The ResNet/VGG stem pattern a TPU hates: channels-last 7x7/s2 conv
    with tiny input depth (C=3 wastes 125/128 MXU input lanes).
    MXNET_CONV_S2D_STEM=0 disables the rewrite (the PERF.md A/B knob);
    read at trace time, so flipping it requires a fresh jit cache."""
    from ..base import get_env
    return (ndim == 2 and is_cl and tuple(kernel) == (7, 7)
            and tuple(stride) == (2, 2) and tuple(pad) == (3, 3)
            and tuple(dilate) == (1, 1) and int(num_group) == 1
            and data.shape[-1] <= 4
            and data.shape[1] % 2 == 0 and data.shape[2] % 2 == 0
            and bool(get_env("MXNET_CONV_S2D_STEM", 1, int)))


def _conv_s2d_7x7s2(data, weight):
    """Space-to-depth rewrite of the 7x7/s2 stem conv (the MLPerf trick;
    PERF.md 'next levers'). Exactly equivalent: pad the kernel to 8x8
    (one leading zero row/col), fold 2x2 input blocks into channels
    (C -> 4C, making the MXU's input-lane dimension useful), and run a
    4x4/s1 conv with the correspondingly folded weights. Pure reshapes +
    one conv — XLA folds the weight transform at compile time, and the
    backward falls out of jax.vjp through the linear ops."""
    N, H, W, C = data.shape
    O = weight.shape[0]
    # kernel 7->8 with a LEADING zero (index shift dy -> dy+1), then
    # split each spatial 8 into (4 taps x 2 phases)
    w8 = jnp.pad(weight, ((0, 0), (1, 0), (1, 0), (0, 0)))
    w4 = w8.reshape(O, 4, 2, 4, 2, C).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(O, 4, 4, 4 * C)
    # space-to-depth: (N,H,W,C) -> (N,H/2,W/2,4C), channel=(by*2+bx)*C+c
    y = data.reshape(N, H // 2, 2, W // 2, 2, C).transpose(0, 1, 3, 2, 4, 5) \
        .reshape(N, H // 2, W // 2, 4 * C)
    # original pad 3/s2 maps to asymmetric (2,1)/s1 on the folded grid
    return jax.lax.conv_general_dilated(
        y, w4, window_strides=(1, 1), padding=[(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "OHWI", "NHWC"))


def _conv_nd(data, weight, bias, kernel, stride, dilate, pad, num_group,
             no_bias, transposed=False, adj=None, target_shape=None,
             layout=None):
    ndim = len(kernel)
    stride = stride or (1,) * ndim
    dilate = dilate or (1,) * ndim
    pad = pad or (0,) * ndim
    spatial = "DHW"[3 - ndim:]
    is_cl = channels_last(layout, ndim)
    # Channels-first: NC+spatial data, OIHW weight (deconv: IOHW in the
    # reference; we keep OIHW at this layer and Deconvolution adapts).
    # Channels-last (the MXU-native layout — channels land in the lane
    # dimension with no relayout): N+spatial+C data, O+spatial+I weight.
    lhs_spec = ("N" + spatial + "C") if is_cl else ("NC" + spatial)
    rhs_spec = ("O" + spatial + "I") if is_cl else ("OI" + spatial)
    if not transposed:
        if _s2d_applicable(data, kernel, stride, dilate, pad, num_group,
                           is_cl, ndim):
            out = _conv_s2d_7x7s2(data, weight)
        else:
            out = jax.lax.conv_general_dilated(
                data, weight, window_strides=stride,
                padding=[(p, p) for p in pad],
                rhs_dilation=dilate,
                dimension_numbers=(lhs_spec, rhs_spec, lhs_spec),
                feature_group_count=int(num_group))
        if not no_bias and bias is not None:
            out = out + (bias if is_cl
                         else bias.reshape((1, -1) + (1,) * ndim))
        return out
    if is_cl:
        raise MXNetError("Deconvolution supports channels-first layouts only")
    # transposed conv = lhs-dilated conv with the flipped kernel.
    # weight arrives in the reference Deconvolution layout
    # (in_channels, num_filter/g, *kernel); the dilated conv needs
    # (num_filter, in_channels/g, *kernel) OIHW.
    adj = adj or (0,) * ndim
    g = int(num_group)
    k_eff = [(k - 1) * d + 1 for k, d in zip(kernel, dilate)]
    padding = [(ke - 1 - p, ke - 1 - p + a)
               for ke, p, a in zip(k_eff, pad, adj)]
    w = jnp.flip(weight, axis=tuple(range(2, 2 + ndim)))
    c_in = w.shape[0]
    f_per_g = w.shape[1]
    wspatial = w.shape[2:]
    w = w.reshape((g, c_in // g, f_per_g) + wspatial)
    w = jnp.swapaxes(w, 1, 2)                    # (g, F/g, C_in/g, ...)
    w = w.reshape((g * f_per_g, c_in // g) + wspatial)
    dn_t = jax.lax.conv_dimension_numbers(
        data.shape, w.shape, (lhs_spec, "OI" + spatial, lhs_spec))
    out = jax.lax.conv_general_dilated(
        data, w, window_strides=(1,) * ndim, padding=padding,
        rhs_dilation=dilate, lhs_dilation=stride,
        dimension_numbers=dn_t, feature_group_count=g)
    if not no_bias and bias is not None:
        out = out + bias.reshape((1, -1) + (1,) * ndim)
    return out


@register("Convolution", nin=3, jit=True, arg_names=["data", "weight", "bias"],
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "num_filter": 0, "num_group": 1, "no_bias": False,
                    "workspace": 1024, "cudnn_tune": None, "cudnn_off": False,
                    "layout": None})
def convolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                pad=(), num_filter=0, num_group=1, no_bias=False,
                workspace=1024, cudnn_tune=None, cudnn_off=False, layout=None):
    """N-D convolution (reference convolution-inl.h). layout=None means the
    reference NCHW/NCDHW; NWC/NHWC/NDHWC run channels-last — the MXU-native
    layout (weight is then (num_filter, *kernel, in_channels/g), matching
    the reference's NHWC cuDNN convention).

    workspace/cudnn_* knobs are accepted for API parity and ignored — XLA
    owns algorithm choice and scratch on TPU.
    """
    kernel = as_tuple(kernel)
    ndim = len(kernel)
    return _conv_nd(data, weight, bias, kernel, as_tuple(stride, ndim),
                    as_tuple(dilate, ndim), as_tuple(pad, ndim), num_group,
                    no_bias, layout=layout)


@register("Deconvolution", nin=3, jit=True, arg_names=["data", "weight", "bias"],
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "adj": (), "target_shape": (), "num_filter": 0,
                    "num_group": 1, "no_bias": True, "workspace": 512,
                    "cudnn_tune": None, "cudnn_off": False, "layout": None})
def deconvolution(data, weight, bias=None, kernel=(), stride=(), dilate=(),
                  pad=(), adj=(), target_shape=(), num_filter=0, num_group=1,
                  no_bias=True, workspace=512, cudnn_tune=None,
                  cudnn_off=False, layout=None):
    """Transposed convolution (reference deconvolution-inl.h). Weight layout
    (in_channels, num_filter/g, *kernel) as in the reference."""
    kernel = as_tuple(kernel)
    ndim = len(kernel)
    return _conv_nd(data, weight, bias, kernel,
                    as_tuple(stride, ndim), as_tuple(dilate, ndim),
                    as_tuple(pad, ndim), num_group, no_bias, transposed=True,
                    adj=as_tuple(adj, ndim) if adj else None)


# ---------------------------------------------------------------------------
# Pooling
# ---------------------------------------------------------------------------

@register("Pooling", defaults={"kernel": (), "pool_type": "max", "stride": (),
                               "pad": (), "global_pool": False,
                               "pooling_convention": "valid", "cudnn_off": False,
                               "layout": None})
def pooling(data, kernel=(), pool_type="max", stride=(), pad=(),
            global_pool=False, pooling_convention="valid", cudnn_off=False,
            layout=None):
    """Max/avg/sum pooling (reference pooling-inl.h). layout=None means the
    reference NC+spatial; channels-last layouts window over the middle dims.

    'full' convention (ceil division of output size) is implemented by
    right-padding up to what ceil needs, matching reference behaviour.
    """
    ndim = data.ndim - 2
    is_cl = channels_last(layout, ndim)
    sp0 = 1 if is_cl else 2  # first spatial dim index
    if global_pool:
        axes = tuple(range(sp0, sp0 + ndim))
        if pool_type == "max":
            out = jnp.max(data, axis=axes, keepdims=True)
        elif pool_type in ("avg", "sum"):
            out = jnp.sum(data, axis=axes, keepdims=True)
            if pool_type == "avg":
                out = out / np.prod([data.shape[a] for a in axes])
        else:
            raise MXNetError("bad pool_type %r" % pool_type)
        return out
    kernel = as_tuple(kernel, ndim)
    stride = as_tuple(stride, ndim) or (1,) * ndim
    pad = as_tuple(pad, ndim) or (0,) * ndim

    pads = []
    for i in range(ndim):
        lo = hi = pad[i]
        if pooling_convention == "full":
            size = data.shape[sp0 + i] + 2 * pad[i]
            rem = (size - kernel[i]) % stride[i]
            if rem:
                hi += stride[i] - rem
        pads.append((lo, hi))
    if is_cl:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        padding = [(0, 0)] + pads + [(0, 0)]
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        padding = [(0, 0), (0, 0)] + pads
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(data.dtype, jnp.floating) else jnp.iinfo(data.dtype).min
        return jax.lax.reduce_window(data, init, jax.lax.max, window, strides, padding)
    if pool_type in ("avg", "sum"):
        out = jax.lax.reduce_window(data, 0.0, jax.lax.add, window, strides, padding)
        if pool_type == "avg":
            # reference avg pooling counts padded cells in the divisor only
            # when pad>0 was explicit; MXNet divides by full kernel size.
            out = out / np.prod(kernel)
        return out
    raise MXNetError("bad pool_type %r" % pool_type)


# ---------------------------------------------------------------------------
# Activations
# ---------------------------------------------------------------------------

@register("Activation", defaults={"act_type": "relu"})
def activation(data, act_type="relu"):
    """(reference activation-inl.h; act types relu/sigmoid/tanh/softrelu/softsign)"""
    if act_type == "relu":
        return jnp.maximum(data, 0)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(data)
    if act_type == "tanh":
        return jnp.tanh(data)
    if act_type == "softrelu":
        return jax.nn.softplus(data)
    if act_type == "softsign":
        return data / (1 + jnp.abs(data))
    raise MXNetError("unknown act_type %r" % act_type)


@register("LeakyReLU", nin=2, arg_names=["data", "gamma"],
          defaults={"act_type": "leaky", "slope": 0.25, "lower_bound": 0.125,
                    "upper_bound": 0.334})
def leaky_relu(data, gamma=None, act_type="leaky", slope=0.25,
               lower_bound=0.125, upper_bound=0.334, _train=False, _rng=None):
    """(reference leaky_relu-inl.h: leaky/prelu/elu/rrelu)"""
    if act_type == "leaky":
        return jnp.where(data >= 0, data, slope * data)
    if act_type == "elu":
        return jnp.where(data >= 0, data, slope * jnp.expm1(data))
    if act_type == "prelu":
        g = gamma.reshape((1, -1) + (1,) * (data.ndim - 2)) if gamma.ndim == 1 else gamma
        return jnp.where(data >= 0, data, g * data)
    if act_type == "rrelu":
        if _train and _rng is not None:
            s = jax.random.uniform(_rng, data.shape, dtype=data.dtype,
                                   minval=lower_bound, maxval=upper_bound)
        else:
            s = (lower_bound + upper_bound) / 2.0
        return jnp.where(data >= 0, data, s * data)
    raise MXNetError("unknown act_type %r" % act_type)


# ---------------------------------------------------------------------------
# Normalisation
# ---------------------------------------------------------------------------

@register("BatchNorm", nin=5, jit=True,
          arg_names=["data", "gamma", "beta", "moving_mean", "moving_var"],
          nout=3,
          defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                    "use_global_stats": False, "output_mean_var": False,
                    "axis": 1, "cudnn_off": False})
def batch_norm(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
               momentum=0.9, fix_gamma=True, use_global_stats=False,
               output_mean_var=False, axis=1, cudnn_off=False, _train=False):
    """Batch normalisation (reference batch_norm.cc / cudnn_batch_norm-inl.h).

    Returns (out, mean, var): in training mode mean/var are the batch
    statistics the executor uses to update the moving aux states
    (moving = momentum*moving + (1-momentum)*batch, as the reference kernel
    does in-place); in inference mode they echo the moving stats.
    """
    axis = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]

    g = jnp.ones_like(gamma) if fix_gamma else gamma
    mean, var = _bn_stats(data, moving_mean, moving_var, red, _train,
                          use_global_stats)
    if data.dtype in (jnp.bfloat16, jnp.float16):
        # scale/offset in fp32, one fused multiply-add over the activations
        # in their own dtype (no fp32 upcast of the big tensor).
        inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
        s = inv * g.astype(jnp.float32)
        b = beta.astype(jnp.float32) - mean.astype(jnp.float32) * s
        out = data * s.astype(data.dtype).reshape(shape) \
            + b.astype(data.dtype).reshape(shape)
    else:
        inv = jax.lax.rsqrt(var + eps)
        out = (data - mean.reshape(shape)) * (inv * g).reshape(shape) \
            + beta.reshape(shape)
    return out, mean, var


def _bn_stats(data, moving_mean, moving_var, red, _train,
              use_global_stats):
    """Shared BN statistics: batch mean/var in training mode (fp32
    accumulation for half dtypes — the reference's cudnn BN behaviour),
    stop-gradiented moving stats otherwise. One source of truth for
    BatchNorm and the fused _contrib_BatchNormAddReLU."""
    if _train and not use_global_stats:
        stat_in = data.astype(jnp.float32) \
            if data.dtype in (jnp.bfloat16, jnp.float16) else data
        mean = jnp.mean(stat_in, axis=red).astype(moving_mean.dtype)
        var = jnp.var(stat_in, axis=red).astype(moving_var.dtype)
        return mean, var
    return (jax.lax.stop_gradient(moving_mean),
            jax.lax.stop_gradient(moving_var))


def _make_bn_stateful_update(mean_idx, var_idx):
    """Moving-stat update the reference BatchNorm kernel does in place;
    parameterized by the aux-input positions (BN: 3/4, fused: 4/5)."""

    def update(raw_inputs, raw_outputs, params):
        if not params.get("_train") or params.get("use_global_stats"):
            return {}
        momentum = params.get("momentum", 0.9)
        _, mean, var = raw_outputs[:3]
        new_mean = momentum * raw_inputs[mean_idx] + (1 - momentum) * mean
        new_var = momentum * raw_inputs[var_idx] + (1 - momentum) * var
        return {mean_idx: new_mean, var_idx: new_var}

    return update


_bn_stateful_update = _make_bn_stateful_update(3, 4)


def _make_bn_param_dtypes(first_param_idx):
    """gamma/beta/moving stats stay fp32 under bf16/fp16 data (reference
    cudnn_batch_norm-inl.h keeps scale/bias/saved stats in fp32)."""
    idxs = tuple(range(first_param_idx, first_param_idx + 4))

    def infer(in_types, params):
        return {i: np.float32 for i in idxs}

    return infer


_bn_param_dtypes = _make_bn_param_dtypes(1)


_bn = get_op("BatchNorm")
_bn.visible_outputs = 1
_bn.aux_inputs = (3, 4)
_bn.stateful_update = _bn_stateful_update


@register("_contrib_BatchNormAddReLU", nin=6, jit=True,
          arg_names=["data", "addend", "gamma", "beta", "moving_mean",
                     "moving_var"],
          nout=3,
          defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                    "use_global_stats": False, "axis": 1,
                    "cudnn_off": False})
def batch_norm_add_relu(data, addend, gamma, beta, moving_mean, moving_var,
                        eps=1e-3, momentum=0.9, fix_gamma=True,
                        use_global_stats=False, axis=1, cudnn_off=False,
                        _train=False):
    """Fused BN + residual-add + ReLU — the ResNet block tail as one op
    (contrib extension; the reference's cudnn era added the equivalent
    BNAddRelu fusion for the same reason). Statistics follow BatchNorm
    exactly; the apply+add+relu runs as ONE device pass (Pallas kernel
    mxnet_tpu/pallas/fused_bn.py) when the channel axis is last — the
    MXU-native layout — and as the composed XLA chain otherwise.

    Returns (out, mean, var) with the same aux/moving-stat contract as
    BatchNorm (the executor updates moving stats from outputs 1/2).
    """
    axis = int(axis) % data.ndim
    red = tuple(i for i in range(data.ndim) if i != axis)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]

    g = jnp.ones_like(gamma) if fix_gamma else gamma
    mean, var = _bn_stats(data, moving_mean, moving_var, red, _train,
                          use_global_stats)
    # folded apply coefficients, fp32 (same folding as batch_norm above)
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    s = inv * g.astype(jnp.float32)
    b = beta.astype(jnp.float32) - mean.astype(jnp.float32) * s
    if axis == data.ndim - 1:
        from ..pallas.fused_bn import scale_bias_add_relu
        out = scale_bias_add_relu(data, s, b, addend)
    else:
        out = jnp.maximum(
            data * s.astype(data.dtype).reshape(shape)
            + b.astype(data.dtype).reshape(shape) + addend,
            jnp.zeros((), data.dtype))
    return out, mean, var


_bnar = get_op("_contrib_BatchNormAddReLU")
_bnar.visible_outputs = 1
_bnar.aux_inputs = (4, 5)
_bnar.stateful_update = _make_bn_stateful_update(4, 5)
_bnar.param_dtype_infer = _make_bn_param_dtypes(2)
_bn.param_dtype_infer = _bn_param_dtypes


@register("LRN", defaults={"alpha": 1e-4, "beta": 0.75, "knorm": 2.0, "nsize": 5})
def lrn(data, alpha=1e-4, beta=0.75, knorm=2.0, nsize=5):
    """Local response norm across channels (reference lrn-inl.h)."""
    nsize = int(nsize)
    sq = jnp.square(data)
    # sum over a window of nsize channels centred at each channel
    pad = nsize // 2
    sq_p = jnp.pad(sq, [(0, 0), (pad, pad)] + [(0, 0)] * (data.ndim - 2))
    win = sum(sq_p[:, i:i + data.shape[1]] for i in range(nsize))
    return data * jnp.power(knorm + alpha * win / nsize, -beta)


@register("L2Normalization", defaults={"eps": 1e-10, "mode": "instance"})
def l2_normalization(data, eps=1e-10, mode="instance"):
    """(reference l2_normalization-inl.h; modes instance/channel/spatial)"""
    if mode == "instance":
        axes = tuple(range(1, data.ndim))
    elif mode == "channel":
        axes = (1,)
    elif mode == "spatial":
        axes = tuple(range(2, data.ndim))
    else:
        raise MXNetError("unknown mode %r" % mode)
    norm = jnp.sqrt(jnp.sum(jnp.square(data), axis=axes, keepdims=True) + eps)
    return data / norm


@register("InstanceNorm", nin=3, arg_names=["data", "gamma", "beta"],
          defaults={"eps": 1e-3})
def instance_norm(data, gamma, beta, eps=1e-3):
    axes = tuple(range(2, data.ndim))
    mean = jnp.mean(data, axis=axes, keepdims=True)
    var = jnp.var(data, axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (data.ndim - 2)
    return (data - mean) * jax.lax.rsqrt(var + eps) * gamma.reshape(shape) \
        + beta.reshape(shape)


@register("LayerNorm", nin=3, arg_names=["data", "gamma", "beta"],
          defaults={"axis": -1, "eps": 1e-5, "output_mean_var": False})
def layer_norm(data, gamma, beta, axis=-1, eps=1e-5, output_mean_var=False):
    """Layer normalisation (new-framework addition; needed for attention)."""
    axis = int(axis) % data.ndim
    mean = jnp.mean(data, axis=axis, keepdims=True)
    var = jnp.var(data, axis=axis, keepdims=True)
    out = (data - mean) * jax.lax.rsqrt(var + eps)
    shape = [1] * data.ndim
    shape[axis] = data.shape[axis]
    return out * gamma.reshape(shape) + beta.reshape(shape)


# ---------------------------------------------------------------------------
# Dropout
# ---------------------------------------------------------------------------

@register("Dropout", defaults={"p": 0.5, "mode": "training", "axes": ()})
def dropout(data, p=0.5, mode="training", axes=(), _train=False, _rng=None):
    """(reference dropout-inl.h). Scales by 1/(1-p) at train time."""
    if (not _train and mode != "always") or p <= 0 or _rng is None:
        return data
    shape = data.shape
    axes = as_tuple(axes) or ()
    if axes:
        shape = tuple(1 if i in axes else s for i, s in enumerate(shape))
    keep = jax.random.bernoulli(_rng, 1.0 - p, shape)
    return jnp.where(keep, data / (1.0 - p), jnp.zeros_like(data))


# ---------------------------------------------------------------------------
# Softmax family
# ---------------------------------------------------------------------------

@register("softmax", defaults={"axis": -1, "temperature": None})
def softmax(data, axis=-1, temperature=None):
    """(reference src/operator/nn/softmax-inl.h)"""
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.softmax(data, axis=int(axis))


@register("log_softmax", defaults={"axis": -1, "temperature": None})
def log_softmax(data, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        data = data / temperature
    return jax.nn.log_softmax(data, axis=int(axis))


@register("SoftmaxActivation", defaults={"mode": "instance"})
def softmax_activation(data, mode="instance"):
    if mode == "channel":
        return jax.nn.softmax(data, axis=1)
    return jax.nn.softmax(data.reshape(data.shape[0], -1), axis=-1).reshape(data.shape)


@register("softmax_cross_entropy", nin=2, arg_names=["data", "label"])
def softmax_cross_entropy(data, label):
    """(reference src/operator/loss_binary_op.cc): scalar summed CE."""
    logp = jax.nn.log_softmax(data, axis=-1)
    picked = jnp.take_along_axis(logp, label.astype(jnp.int32)[:, None], axis=-1)
    return -jnp.sum(picked).reshape((1,))


def _softmax_out_grad(prob, label, grad_scale, ignore_label, use_ignore,
                      normalization, multi_output):
    """Shared SoftmaxOutput backward: prob - one_hot(label)."""
    if multi_output:
        # prob: (n, k, d1...), label: (n, d1...)
        oh = jax.nn.one_hot(label.astype(jnp.int32), prob.shape[1],
                            dtype=prob.dtype, axis=1)
    else:
        oh = jax.nn.one_hot(label.astype(jnp.int32), prob.shape[-1],
                            dtype=prob.dtype)
    grad = prob - oh
    valid = None
    if use_ignore:
        mask = (label.astype(jnp.int32) != int(ignore_label))
        if multi_output:
            grad = grad * mask[:, None].astype(prob.dtype)
        else:
            grad = grad * mask.reshape(mask.shape + (1,) * (grad.ndim - mask.ndim)).astype(prob.dtype)
        valid = jnp.maximum(jnp.sum(mask.astype(prob.dtype)), 1.0)
    if normalization == "valid" and valid is not None:
        grad = grad / valid
    elif normalization == "batch":
        grad = grad / prob.shape[0]
    return grad * grad_scale


@register("SoftmaxOutput", nin=2, arg_names=["data", "label"],
          defaults={"grad_scale": 1.0, "ignore_label": -1.0, "multi_output": False,
                    "use_ignore": False, "preserve_shape": False,
                    "normalization": "null", "out_grad": False,
                    "smooth_alpha": 0.0},
          aliases=("Softmax",))
def softmax_output(data, label, grad_scale=1.0, ignore_label=-1.0,
                   multi_output=False, use_ignore=False, preserve_shape=False,
                   normalization="null", out_grad=False, smooth_alpha=0.0):
    """Softmax with implicit cross-entropy gradient
    (reference softmax_output-inl.h). Forward = softmax(data); backward
    ignores the incoming head gradient (it is a loss layer) and emits
    (p - onehot(label)) * grad_scale, exactly as the reference kernel.
    Implemented with jax.custom_vjp since the gradient is not the vjp of
    the forward function.
    """
    axis = 1 if multi_output else -1
    # the reference's InferShape rejects a label that is not data minus
    # the class axis; without this check a bad label broadcasts into a
    # wrong-shaped cotangent and dies as a bare assertion inside vjp
    expected = ((data.shape[0],) + tuple(data.shape[2:]) if multi_output
                else tuple(data.shape[:-1]))
    if tuple(label.shape) != expected:
        flat = (data.shape[0],
                int(np.prod(data.shape[2:])) if data.ndim > 2 else 1)
        if multi_output and tuple(label.shape) == flat:
            # the reference's InferShape actually assigns the label the
            # FLATTENED Shape2(n, prod(rest)) form — accept and reshape
            label = label.reshape(expected)
        else:
            raise MXNetError(
                "SoftmaxOutput: label shape %s is inconsistent with data "
                "shape %s (expected label %s)"
                % (tuple(label.shape), tuple(data.shape), expected))

    @jax.custom_vjp
    def _fwd(d, l):
        return jax.nn.softmax(d, axis=axis)

    def _fwd_fwd(d, l):
        p = jax.nn.softmax(d, axis=axis)
        return p, (p, l)

    def _fwd_bwd(res, g):
        p, l = res
        grad = _softmax_out_grad(p, l, grad_scale, ignore_label, use_ignore,
                                 normalization, multi_output)
        return grad.astype(p.dtype), jnp.zeros_like(l)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data, label)


def _regression_output(transform, grad_fn):
    def op(data, label, grad_scale=1.0):
        @jax.custom_vjp
        def _fwd(d, l):
            return transform(d)

        def _fwd_fwd(d, l):
            out = transform(d)
            return out, (out, l)

        def _fwd_bwd(res, g):
            out, l = res
            grad = grad_fn(out, l.reshape(out.shape)) * grad_scale
            return grad.astype(out.dtype), jnp.zeros_like(l)

        _fwd.defvjp(_fwd_fwd, _fwd_bwd)
        return _fwd(data, label)
    return op


register("LinearRegressionOutput", nin=2, arg_names=["data", "label"],
         defaults={"grad_scale": 1.0})(
    _regression_output(lambda d: d, lambda o, l: o - l))
register("MAERegressionOutput", nin=2, arg_names=["data", "label"],
         defaults={"grad_scale": 1.0})(
    _regression_output(lambda d: d, lambda o, l: jnp.sign(o - l)))
register("LogisticRegressionOutput", nin=2, arg_names=["data", "label"],
         defaults={"grad_scale": 1.0})(
    _regression_output(jax.nn.sigmoid, lambda o, l: o - l))


@register("MakeLoss", defaults={"grad_scale": 1.0, "valid_thresh": 0.0,
                                "normalization": "null"})
def make_loss(data, grad_scale=1.0, valid_thresh=0.0, normalization="null"):
    """(reference make_loss-inl.h): forward identity, backward = grad_scale."""
    @jax.custom_vjp
    def _fwd(d):
        return d

    def _fwd_fwd(d):
        return d, d

    def _fwd_bwd(d, g):
        scale = grad_scale
        if normalization == "batch":
            scale = scale / d.shape[0]
        elif normalization == "valid":
            valid = jnp.maximum(jnp.sum((d > valid_thresh).astype(d.dtype)), 1.0)
            return ((jnp.ones_like(d) * scale) / valid,)
        return (jnp.full_like(d, scale),)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data)


@register("SVMOutput", nin=2, arg_names=["data", "label"],
          defaults={"margin": 1.0, "regularization_coefficient": 1.0,
                    "use_linear": False})
def svm_output(data, label, margin=1.0, regularization_coefficient=1.0,
               use_linear=False):
    """(reference svm_output-inl.h). Forward identity; backward hinge-loss grad."""
    @jax.custom_vjp
    def _fwd(d, l):
        return d

    def _fwd_fwd(d, l):
        return d, (d, l)

    def _fwd_bwd(res, g):
        d, l = res
        oh = jax.nn.one_hot(l.astype(jnp.int32), d.shape[-1], dtype=d.dtype)
        score_y = jnp.sum(d * oh, axis=-1, keepdims=True)
        if use_linear:
            viol = ((d - score_y + margin) > 0).astype(d.dtype) * (1 - oh)
            grad = viol - oh * jnp.sum(viol, axis=-1, keepdims=True)
        else:
            dist = jnp.maximum(d - score_y + margin, 0) * (1 - oh)
            grad = 2 * dist - oh * jnp.sum(2 * dist, axis=-1, keepdims=True)
        return (grad * regularization_coefficient).astype(d.dtype), jnp.zeros_like(l)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data, label)


# ---------------------------------------------------------------------------
# UpSampling
# ---------------------------------------------------------------------------

@register("UpSampling", nin=-1,
          defaults={"scale": 1, "sample_type": "nearest", "num_filter": 0,
                    "multi_input_mode": "concat", "num_args": 1, "workspace": 512})
def upsampling(*args, scale=1, sample_type="nearest", num_filter=0,
               multi_input_mode="concat", num_args=1, workspace=512):
    """(reference upsampling-inl.h). nearest mode; bilinear mode uses the
    deconvolution path like the reference."""
    scale = int(scale)
    if sample_type == "nearest":
        outs = []
        for d in args:
            o = jnp.repeat(jnp.repeat(d, scale, axis=2), scale, axis=3)
            outs.append(o)
        if len(outs) == 1:
            return outs[0]
        h = max(o.shape[2] for o in outs)
        outs = [o if o.shape[2] == h else
                jnp.repeat(jnp.repeat(o, h // o.shape[2], axis=2),
                           h // o.shape[3], axis=3) for o in outs]
        if multi_input_mode == "sum":
            return sum(outs)
        return jnp.concatenate(outs, axis=1)
    if sample_type == "bilinear":
        data, weight = args
        kernel = 2 * scale - scale % 2
        pad = int(np.ceil((scale - 1) / 2.0))
        return _conv_nd(data, weight, None,
                        (kernel, kernel), (scale, scale), None, (pad, pad),
                        num_group=data.shape[1], no_bias=True, transposed=True)
    raise MXNetError("unknown sample_type %r" % sample_type)


# ---------------------------------------------------------------------------
# Sequence ops (reference src/operator/sequence_*.cc)
# ---------------------------------------------------------------------------

@register("SequenceLast", nin=2, arg_names=["data", "sequence_length"],
          defaults={"use_sequence_length": False, "axis": 0})
def sequence_last(data, sequence_length=None, use_sequence_length=False, axis=0):
    axis = int(axis)
    if not use_sequence_length or sequence_length is None:
        return jnp.take(data, data.shape[axis] - 1, axis=axis)
    idx = (sequence_length.astype(jnp.int32) - 1)
    batch = jnp.arange(data.shape[1 - axis])
    if axis == 0:
        return data[idx, batch]
    return data[batch, idx]


@register("SequenceMask", nin=2, arg_names=["data", "sequence_length"],
          defaults={"use_sequence_length": False, "value": 0.0, "axis": 0})
def sequence_mask(data, sequence_length=None, use_sequence_length=False,
                  value=0.0, axis=0):
    if not use_sequence_length or sequence_length is None:
        return data
    axis = int(axis)
    T = data.shape[axis]
    steps = jnp.arange(T)
    mask = steps[:, None] < sequence_length.astype(jnp.int32)[None, :]  # (T, B)
    if axis == 1:
        mask = mask.T
    mask = mask.reshape(mask.shape + (1,) * (data.ndim - 2))
    return jnp.where(mask, data, jnp.asarray(value, data.dtype))


@register("SequenceReverse", nin=2, arg_names=["data", "sequence_length"],
          defaults={"use_sequence_length": False, "axis": 0})
def sequence_reverse(data, sequence_length=None, use_sequence_length=False, axis=0):
    if not use_sequence_length or sequence_length is None:
        return jnp.flip(data, axis=0)
    T = data.shape[0]
    steps = jnp.arange(T)[:, None]
    lens = sequence_length.astype(jnp.int32)[None, :]
    src = jnp.where(steps < lens, lens - 1 - steps, steps)  # (T, B)
    return jnp.take_along_axis(
        data, src.reshape(src.shape + (1,) * (data.ndim - 2)), axis=0)
