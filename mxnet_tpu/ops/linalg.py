"""Linear-algebra operators (``linalg_*`` namespace).

Parity: reference ``src/operator/tensor/la_op.cc`` (LAPACK-backed
potrf/potri/trmm/trsm/gemm/gemm2/sumlogdiag via ``c_lapack_api.h``).
XLA provides native TPU lowerings for all of these.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import jax.scipy.linalg as jsl

from .registry import register


def _t(x, transpose):
    return jnp.swapaxes(x, -1, -2) if transpose else x


@register("_linalg_gemm", nin=3, arg_names=["A", "B", "C"],
          defaults={"transpose_a": False, "transpose_b": False, "alpha": 1.0,
                    "beta": 1.0}, aliases=("linalg_gemm",))
def linalg_gemm(A, B, C, transpose_a=False, transpose_b=False, alpha=1.0,
                beta=1.0, axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b)) + beta * C


@register("_linalg_gemm2", nin=2, arg_names=["A", "B"],
          defaults={"transpose_a": False, "transpose_b": False, "alpha": 1.0},
          aliases=("linalg_gemm2",))
def linalg_gemm2(A, B, transpose_a=False, transpose_b=False, alpha=1.0,
                 axis=-2):
    return alpha * jnp.matmul(_t(A, transpose_a), _t(B, transpose_b))


@register("_linalg_potrf", aliases=("linalg_potrf",))
def linalg_potrf(A):
    """Cholesky factor L with zeroed upper triangle (reference la_op.cc potrf)."""
    return jnp.linalg.cholesky(A)


@register("_linalg_potri", aliases=("linalg_potri",))
def linalg_potri(A):
    """Inverse of A A^T given its Cholesky factor A=L (reference potri)."""
    eye = jnp.broadcast_to(jnp.eye(A.shape[-1], dtype=A.dtype), A.shape)
    linv = jsl.solve_triangular(A, eye, lower=True)
    return jnp.matmul(jnp.swapaxes(linv, -1, -2), linv)


@register("_linalg_trmm", nin=2, arg_names=["A", "B"],
          defaults={"transpose": False, "rightside": False, "alpha": 1.0},
          aliases=("linalg_trmm",))
def linalg_trmm(A, B, transpose=False, rightside=False, alpha=1.0, lower=True):
    a = _t(A, transpose)
    out = jnp.matmul(B, a) if rightside else jnp.matmul(a, B)
    return alpha * out


@register("_linalg_trsm", nin=2, arg_names=["A", "B"],
          defaults={"transpose": False, "rightside": False, "alpha": 1.0},
          aliases=("linalg_trsm",))
def linalg_trsm(A, B, transpose=False, rightside=False, alpha=1.0, lower=True):
    if rightside:
        # solve X A = alpha B  ->  A^T X^T = alpha B^T
        out = jsl.solve_triangular(_t(A, not transpose), _t(alpha * B, True),
                                   lower=(lower != transpose))
        return _t(out, True)
    return jsl.solve_triangular(_t(A, transpose), alpha * B,
                                lower=(lower != transpose))


@register("_linalg_sumlogdiag", aliases=("linalg_sumlogdiag",))
def linalg_sumlogdiag(A):
    diag = jnp.diagonal(A, axis1=-2, axis2=-1)
    return jnp.sum(jnp.log(diag), axis=-1)


@register("_linalg_syrk", defaults={"transpose": False, "alpha": 1.0},
          aliases=("linalg_syrk",))
def linalg_syrk(A, transpose=False, alpha=1.0):
    a = _t(A, transpose)
    return alpha * jnp.matmul(a, jnp.swapaxes(a, -1, -2))
