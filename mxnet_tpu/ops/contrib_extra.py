"""Remaining reference operators: detection/flow/signal/quantization.

Parity targets:
- Proposal            reference src/operator/contrib/proposal.cc
- DeformableConvolution  contrib/deformable_convolution.cc
- Correlation         src/operator/correlation.cc
- fft / ifft          contrib/fft.cc, contrib/ifft.cc
- quantize/dequantize contrib/quantize.cc, contrib/dequantize.cc
- BatchNorm_v1        src/operator/batch_norm_v1.cc
- IdentityAttachKLSparseReg  src/operator/identity_attach_KL_sparse_reg.cc

TPU-first notes: everything is expressed as dense, statically-shaped jnp
programs. Correlation unrolls the (small) displacement grid into batched
elementwise+window-sum passes instead of the reference's 7-deep scalar
loop nest; DeformableConvolution builds the bilinear-sampled column
tensor with vectorized gathers and reduces with one einsum on the MXU;
Proposal's greedy NMS is a lax.fori_loop with an O(n) vectorized
suppression per step (sequentiality is inherent to greedy NMS).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..base import MXNetError
from .common import as_tuple
from .registry import register, get_op


# ---------------------------------------------------------------------------
# Correlation (FlowNet)
# ---------------------------------------------------------------------------

@register("Correlation", nin=2, jit=True, arg_names=["data1", "data2"],
          defaults={"kernel_size": 1, "max_displacement": 1, "stride1": 1,
                    "stride2": 1, "pad_size": 0, "is_multiply": True})
def correlation(data1, data2, kernel_size=1, max_displacement=1, stride1=1,
                stride2=1, pad_size=0, is_multiply=True):
    """Patch cross-correlation between two feature maps (reference
    src/operator/correlation-inl.h; oracle semantics in the reference's
    tests/python/unittest/test_operator.py correlation_forward).

    Output (N, D*D, top_h, top_w) where D = 2*(max_displacement//stride2)+1;
    each channel is the kernel-window correlation at one displacement,
    normalised by kernel_size^2 * C.
    """
    k = int(kernel_size)
    md = int(max_displacement)
    s1, s2, pad = int(stride1), int(stride2), int(pad_size)
    B, C, H, W = data1.shape
    ph, pw = H + 2 * pad, W + 2 * pad
    kr = (k - 1) // 2
    bs = md + kr
    # ceil division, like the reference's InferShape (correlation-inl.h:101)
    th = -((ph - 2 * bs) // -s1)
    tw = -((pw - 2 * bs) // -s1)
    if th <= 0 or tw <= 0:
        raise MXNetError("Correlation output would be empty")
    r = md // s2
    D = 2 * r + 1
    # window origin for output (i, j) is y1 = i*s1 + md (window spans k);
    # ceil shapes (and even kernel sizes, whose border uses (k-1)//2) can
    # read past the pad_size padding — extend with zeros to cover the
    # full displaced-window extent
    eh = (th - 1) * s1 + k
    ew = (tw - 1) * s1 + k
    xh = max(0, 2 * md + eh - ph)
    xw = max(0, 2 * md + ew - pw)
    t1 = jnp.pad(data1, ((0, 0), (0, 0), (pad, pad + xh), (pad, pad + xw)))
    t2 = jnp.pad(data2, ((0, 0), (0, 0), (pad, pad + xh), (pad, pad + xw)))
    a = t1[:, :, md:md + eh, md:md + ew]
    outs = []
    for p in range(D):        # displacement rows (y)
        for o in range(D):    # displacement cols (x)
            dy = (p - r) * s2
            dx = (o - r) * s2
            b = t2[:, :, md + dy:md + dy + eh, md + dx:md + dx + ew]
            prod = a * b if is_multiply else jnp.abs(a - b)
            chan = jnp.sum(prod, axis=1)          # (B, eh, ew)
            win = jax.lax.reduce_window(
                chan, 0.0, jax.lax.add, (1, k, k), (1, s1, s1),
                [(0, 0), (0, 0), (0, 0)])
            outs.append(win)
    out = jnp.stack(outs, axis=1)                 # (B, D*D, th, tw)
    return out / float(k * k * C)


# ---------------------------------------------------------------------------
# fft / ifft (reference contrib/fft.cc — complex interleaved last axis)
# ---------------------------------------------------------------------------

@register("_contrib_fft", jit=True, defaults={"compute_size": 128},
          aliases=("fft", "_contrib_Fft"))
def fft(data, compute_size=128):
    """FFT along the last axis; output interleaves (real, imag) pairs so
    the last dim doubles (reference contrib/fft-inl.h cuFFT layout).
    compute_size (batching granularity knob) is accepted and ignored —
    XLA owns scheduling."""
    c = jnp.fft.fft(data.astype(jnp.float32), axis=-1)
    out = jnp.stack([c.real, c.imag], axis=-1)
    return out.reshape(data.shape[:-1] + (2 * data.shape[-1],)) \
        .astype(data.dtype)


@register("_contrib_ifft", jit=True, defaults={"compute_size": 128},
          aliases=("ifft", "_contrib_Ifft"))
def ifft(data, compute_size=128):
    """Inverse FFT of the interleaved layout; UNNORMALISED like the
    reference's cuFFT path (out = n * np.fft.ifft(...).real — see the
    reference gpu test check_ifft dividing by n before comparing)."""
    d = data.shape[-1] // 2
    x = data.astype(jnp.float32).reshape(data.shape[:-1] + (d, 2))
    c = jax.lax.complex(x[..., 0], x[..., 1])
    out = jnp.fft.ifft(c, axis=-1).real * d
    return out.astype(data.dtype)


# ---------------------------------------------------------------------------
# quantize / dequantize (reference contrib/quantize.cc — min/max affine)
# ---------------------------------------------------------------------------

@register("_contrib_quantize", nin=3,
          arg_names=["data", "min_range", "max_range"], nout=3,
          defaults={"out_type": "uint8"}, no_grad=True,
          aliases=("quantize",))
def quantize(data, min_range, max_range, out_type="uint8"):
    """Affine-quantize fp32 to uint8 over [min_range, max_range]
    (reference quantize-inl.h: out = (in - min) * 255/(max-min) + 0.5).
    Returns (quantized, min_range, max_range)."""
    if out_type != "uint8":
        raise MXNetError("only uint8 quantization is supported (reference "
                         "quantize-inl.h supports the same)")
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    scale = 255.0 / (hi - lo)
    q = jnp.clip((data - lo) * scale + 0.5, 0, 255).astype(jnp.uint8)
    return q, lo.reshape((1,)), hi.reshape((1,))


@register("_contrib_dequantize", nin=3,
          arg_names=["data", "min_range", "max_range"],
          defaults={"out_type": "float32"}, no_grad=True,
          aliases=("dequantize",))
def dequantize(data, min_range, max_range, out_type="float32"):
    """Inverse affine map uint8 -> fp32 (reference dequantize-inl.h:
    out = in * (max-min)/255 + min)."""
    lo = min_range.reshape(())
    hi = max_range.reshape(())
    return (data.astype(jnp.float32) * ((hi - lo) / 255.0) + lo) \
        .astype(jnp.float32)


# ---------------------------------------------------------------------------
# BatchNorm_v1 (legacy kernel, reference batch_norm_v1.cc)
# ---------------------------------------------------------------------------

@register("BatchNorm_v1", nin=5, jit=True,
          arg_names=["data", "gamma", "beta", "moving_mean", "moving_var"],
          nout=3,
          defaults={"eps": 1e-3, "momentum": 0.9, "fix_gamma": True,
                    "use_global_stats": False, "output_mean_var": False})
def batch_norm_v1(data, gamma, beta, moving_mean, moving_var, eps=1e-3,
                  momentum=0.9, fix_gamma=True, use_global_stats=False,
                  output_mean_var=False, _train=False):
    """Legacy BatchNorm (reference batch_norm_v1-inl.h): channel axis
    fixed at 1, otherwise the same normalisation as BatchNorm. Shares the
    modern kernel — on TPU there is one good way to normalise."""
    bn = get_op("BatchNorm")
    return bn.fn(data, gamma, beta, moving_mean, moving_var, eps=eps,
                 momentum=momentum, fix_gamma=fix_gamma,
                 use_global_stats=use_global_stats,
                 output_mean_var=output_mean_var, axis=1, _train=_train)


# ---------------------------------------------------------------------------
# IdentityAttachKLSparseReg (reference identity_attach_KL_sparse_reg.cc)
# ---------------------------------------------------------------------------

@register("IdentityAttachKLSparseReg", nin=2,
          arg_names=["data", "moving_avg"], jit=True,
          defaults={"sparseness_target": 0.1, "penalty": 0.001,
                    "momentum": 0.9})
def identity_attach_kl_sparse_reg(data, moving_avg, sparseness_target=0.1,
                                  penalty=0.001, momentum=0.9, _train=False):
    """Forward identity; backward adds the KL-sparsity penalty gradient
    penalty * (-t/rho + (1-t)/(1-rho)) where rho is the momentum-updated
    per-feature moving average of the activation over the batch
    (reference identity_attach_KL_sparse_reg-inl.h Backward)."""
    t = float(sparseness_target)
    pen = float(penalty)
    mom = float(momentum)

    @jax.custom_vjp
    def _fwd(d, mov):
        return d

    def _fwd_fwd(d, mov):
        return d, (d, mov)

    def _fwd_bwd(res, g):
        d, mov = res
        d2 = d.reshape(d.shape[0], -1)
        avg = jnp.mean(d2, axis=0)
        mov_new = mom * mov + (1 - mom) * avg  # the backward-time update
        reg = pen * (-t / mov_new + (1 - t) / (1 - mov_new))
        grad = g + reg.reshape((1,) + d.shape[1:]).astype(d.dtype)
        return grad, jnp.zeros_like(mov)

    _fwd.defvjp(_fwd_fwd, _fwd_bwd)
    return _fwd(data, moving_avg)


def _klreg_stateful(raw_inputs, raw_outputs, params):
    """Moving-average update the reference does during Backward; running
    it from the (train-mode) forward keeps the aux contract functional."""
    if not params.get("_train"):
        return {}
    mom = params.get("momentum", 0.9)
    d2 = raw_inputs[0].reshape(raw_inputs[0].shape[0], -1)
    avg = jnp.mean(d2, axis=0)
    return {1: mom * raw_inputs[1] + (1 - mom) * avg}


def _klreg_shapes(shapes, params):
    data = shapes[0]
    return {1: (int(np.prod(data[1:])),)}


_klreg = get_op("IdentityAttachKLSparseReg")
_klreg.visible_outputs = 1
_klreg.aux_inputs = (1,)
_klreg.stateful_update = _klreg_stateful
_klreg.param_shape_infer = _klreg_shapes


# ---------------------------------------------------------------------------
# DeformableConvolution (reference contrib/deformable_convolution.cc)
# ---------------------------------------------------------------------------

def _bilinear_gather(img, ys, xs):
    """img (C, H, W); ys/xs (...) fractional; zero padding outside."""
    C, H, W = img.shape
    y0 = jnp.floor(ys)
    x0 = jnp.floor(xs)
    wy1 = ys - y0
    wx1 = xs - x0
    vals = 0.0
    for dy, wy in ((0, 1 - wy1), (1, wy1)):
        for dx, wx in ((0, 1 - wx1), (1, wx1)):
            yy = y0.astype(jnp.int32) + dy
            xx = x0.astype(jnp.int32) + dx
            ok = (yy >= 0) & (yy < H) & (xx >= 0) & (xx < W)
            yc = jnp.clip(yy, 0, H - 1)
            xc = jnp.clip(xx, 0, W - 1)
            v = img[:, yc, xc]                       # (C, ...)
            vals = vals + v * (wy * wx * ok.astype(img.dtype))
    return vals


@register("_contrib_DeformableConvolution", nin=4, jit=True,
          arg_names=["data", "offset", "weight", "bias"],
          defaults={"kernel": (), "stride": (), "dilate": (), "pad": (),
                    "num_filter": 0, "num_group": 1,
                    "num_deformable_group": 1, "workspace": 1024,
                    "no_bias": False, "layout": None},
          aliases=("DeformableConvolution",))
def deformable_convolution(data, offset, weight, bias=None, kernel=(),
                           stride=(), dilate=(), pad=(), num_filter=0,
                           num_group=1, num_deformable_group=1,
                           workspace=1024, no_bias=False, layout=None):
    """2-D deformable convolution (reference deformable_convolution-inl.h
    + deformable_im2col.h): sampling positions are the regular conv taps
    plus learned per-position offsets, bilinearly interpolated. offset
    has 2*num_deformable_group*kh*kw channels ordered (dg, tap, (y, x)).

    The sampled column tensor reduces with one einsum (MXU path) instead
    of the reference's im2col+gemm loop.
    """
    kh, kw = as_tuple(kernel, 2)
    sh, sw = as_tuple(stride, 2) or (1, 1)
    dh, dw = as_tuple(dilate, 2) or (1, 1)
    ph, pw = as_tuple(pad, 2) or (0, 0)
    B, C, H, W = data.shape
    F = int(num_filter)
    g = int(num_group)
    dg = int(num_deformable_group)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1

    # base sampling grid: (K, Ho, Wo) per kernel tap, K = kh*kw
    oy = jnp.arange(Ho) * sh - ph
    ox = jnp.arange(Wo) * sw - pw
    ty = jnp.arange(kh) * dh
    tx = jnp.arange(kw) * dw
    base_y = (oy[None, :, None] + ty.repeat(kw)[:, None, None])  # (K,Ho,1)
    base_x = (ox[None, None, :] + jnp.tile(tx, kh)[:, None, None])

    off = offset.reshape(B, dg, kh * kw, 2, Ho, Wo)
    ys = base_y + off[:, :, :, 0]                    # (B, dg, K, Ho, Wo)
    xs = base_x + off[:, :, :, 1]

    dpg = C // dg   # data channels per deformable group

    def one_image(img, ys_i, xs_i):
        # img (C,H,W); ys_i/xs_i (dg, K, Ho, Wo)
        def per_dg(img_g, y_g, x_g):
            return _bilinear_gather(img_g, y_g, x_g)  # (dpg, K, Ho, Wo)
        cols = jax.vmap(per_dg)(img.reshape(dg, dpg, H, W), ys_i, xs_i)
        return cols.reshape(C, kh * kw, Ho, Wo)

    cols = jax.vmap(one_image)(data, ys, xs)          # (B, C, K, Ho, Wo)
    # grouped reduction: weight (F, C/g, kh, kw)
    cols = cols.reshape(B, g, C // g, kh * kw, Ho, Wo)
    wr = weight.reshape(g, F // g, C // g, kh * kw)
    out = jnp.einsum("bgckhw,gfck->bgfhw", cols, wr,
                     preferred_element_type=jnp.float32)
    out = out.reshape(B, F, Ho, Wo).astype(data.dtype)
    if not no_bias and bias is not None:
        out = out + bias.reshape(1, -1, 1, 1)
    return out


# ---------------------------------------------------------------------------
# Proposal (RPN, reference contrib/proposal.cc)
# ---------------------------------------------------------------------------

def _generate_anchors(base_size, ratios, scales):
    """Faster-R-CNN anchor enumeration (reference proposal-inl.h
    GenerateAnchors/_Transform; ratio-major, scale-minor order)."""
    w = h = float(base_size)
    x_ctr = 0.5 * (w - 1.0)
    y_ctr = 0.5 * (h - 1.0)
    size = w * h
    anchors = []
    for ratio in ratios:
        size_ratio = np.floor(size / ratio)
        new_w = np.floor(np.sqrt(size_ratio) + 0.5)
        new_h = np.floor(new_w * ratio + 0.5)
        for scale in scales:
            sw = new_w * scale
            sh = new_h * scale
            anchors.append([x_ctr - 0.5 * (sw - 1), y_ctr - 0.5 * (sh - 1),
                            x_ctr + 0.5 * (sw - 1), y_ctr + 0.5 * (sh - 1)])
    return np.array(anchors, np.float32)


@register("_contrib_Proposal", nin=3, jit=True,
          arg_names=["cls_prob", "bbox_pred", "im_info"], nout=2,
          defaults={"rpn_pre_nms_top_n": 6000, "rpn_post_nms_top_n": 300,
                    "threshold": 0.7, "rpn_min_size": 16,
                    "scales": (4.0, 8.0, 16.0, 32.0),
                    "ratios": (0.5, 1.0, 2.0), "feature_stride": 16,
                    "output_score": False, "iou_loss": False},
          no_grad=True, aliases=("Proposal", "_contrib_proposal"))
def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4.0, 8.0, 16.0, 32.0), ratios=(0.5, 1.0, 2.0),
             feature_stride=16, output_score=False, iou_loss=False):
    """RPN proposal generation (reference contrib/proposal.cc Forward):
    enumerate shifted anchors, apply bbox deltas, clip to image, filter
    small boxes, keep top pre_nms by score, greedy NMS, emit post_nms
    rois (batch index 0 prepended). Batch size 1, like the reference CPU
    op. Backward is zero (no_grad), matching the reference."""
    if iou_loss:
        raise MXNetError("iou_loss=True is not supported")
    B, A2, Hf, Wf = cls_prob.shape
    A = A2 // 2
    anchors = jnp.asarray(_generate_anchors(feature_stride, ratios, scales))
    # shifted anchors in (h, w, A) index order -> row index h*(W*A)+w*A+a
    sx = jnp.broadcast_to((jnp.arange(Wf) * feature_stride)[None, :],
                          (Hf, Wf))
    sy = jnp.broadcast_to((jnp.arange(Hf) * feature_stride)[:, None],
                          (Hf, Wf))
    shifts = jnp.stack([sx, sy, sx, sy], axis=-1)     # (H, W, 4)
    boxes = anchors[None, None] + shifts[:, :, None, :]  # (H, W, A, 4)
    boxes = boxes.reshape(-1, 4).astype(jnp.float32)

    scores = cls_prob[0, A:].transpose(1, 2, 0).reshape(-1)   # fg scores
    deltas = bbox_pred[0].reshape(A, 4, Hf, Wf).transpose(2, 3, 0, 1) \
        .reshape(-1, 4)

    im_h = im_info[0, 0]
    im_w = im_info[0, 1]
    im_scale = im_info[0, 2]

    # bbox transform (reference BBoxTransformInv)
    bw = boxes[:, 2] - boxes[:, 0] + 1.0
    bh = boxes[:, 3] - boxes[:, 1] + 1.0
    cx = boxes[:, 0] + 0.5 * (bw - 1.0)
    cy = boxes[:, 1] + 0.5 * (bh - 1.0)
    pcx = deltas[:, 0] * bw + cx
    pcy = deltas[:, 1] * bh + cy
    pw_ = jnp.exp(deltas[:, 2]) * bw
    ph_ = jnp.exp(deltas[:, 3]) * bh
    x1 = jnp.clip(pcx - 0.5 * (pw_ - 1.0), 0.0, im_w - 1.0)
    y1 = jnp.clip(pcy - 0.5 * (ph_ - 1.0), 0.0, im_h - 1.0)
    x2 = jnp.clip(pcx + 0.5 * (pw_ - 1.0), 0.0, im_w - 1.0)
    y2 = jnp.clip(pcy + 0.5 * (ph_ - 1.0), 0.0, im_h - 1.0)

    # out-of-image anchors (beyond the real feature extent) score -1
    real_h = (im_h / feature_stride).astype(jnp.int32)
    real_w = (im_w / feature_stride).astype(jnp.int32)
    hw_idx = jnp.arange(Hf * Wf * A)
    h_idx = hw_idx // (Wf * A)
    w_idx = (hw_idx // A) % Wf
    scores = jnp.where((h_idx >= real_h) | (w_idx >= real_w), -1.0, scores)

    # FilterBox: too-small boxes get enlarged and score -1
    min_size = rpn_min_size * im_scale
    iw = x2 - x1 + 1.0
    ih = y2 - y1 + 1.0
    small = (iw < min_size) | (ih < min_size)
    x1 = jnp.where(small, x1 - min_size / 2, x1)
    y1 = jnp.where(small, y1 - min_size / 2, y1)
    x2 = jnp.where(small, x2 + min_size / 2, x2)
    y2 = jnp.where(small, y2 + min_size / 2, y2)
    scores = jnp.where(small, -1.0, scores)

    # order by score, take top pre_nms
    n_pre = min(int(rpn_pre_nms_top_n), scores.shape[0])
    order = jnp.argsort(-scores)[:n_pre]
    dx1, dy1, dx2, dy2 = x1[order], y1[order], x2[order], y2[order]
    dsc = scores[order]

    # greedy NMS (reference NonMaximumSuppression)
    n_post = int(rpn_post_nms_top_n)
    areas = (dx2 - dx1 + 1.0) * (dy2 - dy1 + 1.0)

    def body(i, state):
        suppressed, keep, out_size = state
        take = (~suppressed[i]) & (out_size < n_post)
        keep = jnp.where(take, keep.at[out_size].set(i), keep)
        xx1 = jnp.maximum(dx1[i], dx1)
        yy1 = jnp.maximum(dy1[i], dy1)
        xx2 = jnp.minimum(dx2[i], dx2)
        yy2 = jnp.minimum(dy2[i], dy2)
        inter = jnp.maximum(0.0, xx2 - xx1 + 1.0) * \
            jnp.maximum(0.0, yy2 - yy1 + 1.0)
        iou = inter / (areas[i] + areas - inter)
        newly = (iou > threshold) & (jnp.arange(n_pre) > i)
        suppressed = jnp.where(take, suppressed | newly, suppressed)
        return suppressed, keep, out_size + take.astype(jnp.int32)

    suppressed0 = jnp.zeros(n_pre, bool)
    keep0 = jnp.zeros(n_post, jnp.int32)
    _, keep, out_size = jax.lax.fori_loop(
        0, n_pre, body, (suppressed0, keep0, jnp.int32(0)))

    # pad by cycling kept entries (reference: keep[i % out_size])
    out_size = jnp.maximum(out_size, 1)
    idx = keep[jnp.mod(jnp.arange(n_post), out_size)]
    rois = jnp.stack([jnp.zeros(n_post, jnp.float32), dx1[idx], dy1[idx],
                      dx2[idx], dy2[idx]], axis=1)
    out_scores = dsc[idx].reshape(-1, 1)
    return rois, out_scores


_prop = get_op("_contrib_Proposal")
_prop.visible_outputs = 1  # scores are the optional second output

# BatchNorm_v1 shares the modern BatchNorm's executor contracts
from . import nn as _nn  # noqa: E402

_bnv1 = get_op("BatchNorm_v1")
_bnv1.visible_outputs = 1
_bnv1.aux_inputs = (3, 4)
_bnv1.stateful_update = _nn._bn_stateful_update
_bnv1.param_dtype_infer = _nn._bn_param_dtypes


def _deform_conv_shapes(shapes, params):
    data = shapes[0]
    kernel = as_tuple(params.get("kernel")) or ()
    num_filter = int(params.get("num_filter", 0))
    num_group = int(params.get("num_group", 1))
    out = {2: (num_filter, data[1] // num_group) + kernel}
    if not params.get("no_bias", False):
        out[3] = (num_filter,)
    return out


get_op("_contrib_DeformableConvolution").param_shape_infer = \
    _deform_conv_shapes
