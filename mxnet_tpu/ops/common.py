"""Shared helpers for operator implementations.

Handles the reference's kwargs conventions (tuples serialized as strings
via the C API — reference parses them in dmlc::Parameter; we accept both
Python tuples and their string forms), plus the execution-context
plumbing JAX needs that the reference kept implicit in global state:
train/predict mode (reference: ``Imperative::is_training``) and PRNG
(reference: per-device ``Resource`` kRandom pools,
``include/mxnet/resource.h:37-185``).
"""
from __future__ import annotations

import ast
import threading

import jax
import numpy as np

from ..base import MXNetError

_DTYPE_MAP = {
    "float32": np.float32, "float64": np.float64, "float16": np.float16,
    "bfloat16": jax.numpy.bfloat16, "uint8": np.uint8, "int8": np.int8,
    "int32": np.int32, "int64": np.int64, "bool": np.bool_,
}


def mx_dtype(dtype):
    """Normalise an MXNet dtype spec (string or np dtype) to a numpy/jnp dtype."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        if dtype not in _DTYPE_MAP:
            raise MXNetError("unknown dtype %r" % dtype)
        return _DTYPE_MAP[dtype]
    return dtype


def channels_last(layout, ndim):
    """True for NWC/NHWC/NDHWC-style layouts; layout=None means the
    reference default (channels-first, NC+spatial). Validates the string
    so a bad layout fails here, not as a wrong shape downstream."""
    if not layout:
        return False
    layout = str(layout).upper()
    if layout not in ("NCW", "NWC", "NCHW", "NHWC", "NCDHW", "NDHWC"):
        raise MXNetError("unsupported layout %r" % layout)
    if len(layout) != ndim + 2:
        raise MXNetError("layout %r does not match %dD kernel"
                         % (layout, ndim))
    return layout.endswith("C")


def as_tuple(v, ndim=None, name="param"):
    """Parse kernel/stride/pad style params: tuple, int, or '(2, 2)' string."""
    if v is None:
        return None
    if isinstance(v, str):
        v = ast.literal_eval(v)
    if v == () or v == []:
        return None
    if isinstance(v, int):
        v = (v,) * (ndim or 1)
    v = tuple(int(x) for x in v)
    if ndim is not None and len(v) == 1 < ndim:
        v = v * ndim
    if ndim is not None and len(v) != ndim:
        raise MXNetError("%s must have %d elements, got %r" % (name, ndim, v))
    return v


def as_axis(axis):
    """Normalise reduce-style axis params: None, int, tuple, or string forms."""
    if axis is None or axis == "()" or axis == ():
        return None
    if isinstance(axis, str):
        axis = ast.literal_eval(axis)
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    return int(axis)


def reduce_axes(axis, ndim, exclude=False):
    """Resolve MXNet reduce semantics (axis + exclude) to a concrete axis tuple."""
    axis = as_axis(axis)
    if axis is None:
        axes = tuple(range(ndim))
        return () if exclude else axes
    if isinstance(axis, int):
        axis = (axis,)
    axes = tuple(sorted(a % ndim for a in axis))
    if exclude:
        axes = tuple(a for a in range(ndim) if a not in axes)
    return axes


# ---------------------------------------------------------------------------
# Execution context: train mode + PRNG threading.
# ---------------------------------------------------------------------------

class _ExecState(threading.local):
    def __init__(self):
        self.train_mode = False
        self.rng_provider = None   # callable () -> jax PRNG key, set by executor/trace
        self.recording = False
        self.aux_collector = None  # list collecting (ndarray, traced_value)
        #   aux updates during graph capture (gluon _CachedOp)
        self.graph_capturing = False  # inside a _CachedOp trace: child
        #   hybridized blocks must inline rather than nest their own jit


_STATE = _ExecState()


def state():
    return _STATE


def is_train():
    return _STATE.train_mode


def take_rng():
    """Get a PRNG key for a random op in the current execution context.

    Inside a traced graph the executor installs a fold_in-based provider so
    the key is a traced value; in eager mode we split the global seed state
    (mxnet_tpu.random).
    """
    if _STATE.rng_provider is not None:
        return _STATE.rng_provider()
    from .. import random as _random
    return _random.take_key()   # mxlint: disable=trace-purity -- eager-only: a traced graph installs rng_provider (rng_scope) and returns above


class rng_scope:
    """Install an RNG provider (used by executor/CachedOp when tracing)."""

    def __init__(self, key):
        self._key = key
        self._count = 0
        self._old = None

    def _provide(self):
        k = jax.random.fold_in(self._key, self._count)
        self._count += 1
        return k

    def __enter__(self):
        self._old = _STATE.rng_provider
        _STATE.rng_provider = self._provide
        return self

    def __exit__(self, *exc):
        _STATE.rng_provider = self._old


class train_scope:
    def __init__(self, mode=True):
        self._mode = mode
        self._old = None

    def __enter__(self):
        self._old = _STATE.train_mode
        _STATE.train_mode = self._mode
        return self

    def __exit__(self, *exc):
        _STATE.train_mode = self._old
