"""Automatic naming for the symbolic API.

Parity: reference ``python/mxnet/name.py`` (NameManager / Prefix). The
reference keeps a thread-global ``NameManager.current`` whose ``get``
either honours a user-supplied name or counts per hint ("fullyconnected0",
"fullyconnected1", ...); ``Prefix`` prepends a string — Gluon uses that to
namespace parameters. Same contract here; scoping is per-thread so
multi-threaded graph construction (e.g. data-loader workers building
augmentation graphs) cannot interleave counters.
"""
from __future__ import annotations

import threading

__all__ = ["NameManager", "Prefix"]


class _Current(threading.local):
    def __init__(self):
        self.value = None


class _ScopedMeta(type):
    """Metaclass giving the class a thread-local ``current`` slot with a
    lazily created per-thread default (assignment supported)."""

    @property
    def current(cls):
        cur = cls._current.value
        if cur is None:
            cur = cls._default()
            cls._current.value = cur
        return cur

    @current.setter
    def current(cls, value):
        cls._current.value = value


class NameManager(metaclass=_ScopedMeta):
    """Scoped automatic namer (``with NameManager(): ...``)."""

    _current = _Current()

    @classmethod
    def _default(cls):
        return NameManager()

    def __init__(self):
        self._counter = {}
        self._old_manager = None

    def get(self, name, hint):
        """Return ``name`` if given, else ``hint%d`` with a per-scope count."""
        if name:
            return name
        hint = hint.lower()
        idx = self._counter.get(hint, 0)
        self._counter[hint] = idx + 1
        return "%s%d" % (hint, idx)

    def __enter__(self):
        self._old_manager = NameManager.current
        NameManager._current.value = self
        return self

    def __exit__(self, ptype, value, trace):
        assert self._old_manager is not None
        NameManager._current.value = self._old_manager


class Prefix(NameManager):
    """Name manager that attaches a prefix to every generated name.

    Example::

        data = mx.sym.Variable('data')
        with mx.name.Prefix('mynet_'):
            net = mx.sym.FullyConnected(data, num_hidden=10, name='fc1')
        net.list_arguments()   # ['data', 'mynet_fc1_weight', ...]
    """

    def __init__(self, prefix):
        super().__init__()
        self._prefix = prefix

    def get(self, name, hint):
        name = super().get(name, hint)
        return self._prefix + name
