"""KVStore — the data-parallel parameter-synchronisation facade.

Parity: reference ``include/mxnet/kvstore.h`` + ``python/mxnet/kvstore.py``
with backends ``local``/``device``/``nccl``/``dist_*`` (SURVEY.md §5.8).

TPU-native design: there are no parameter-server processes. Within a
process, push/pull over device shards reduces via XLA (the reference's
CommDevice/NCCL reduce+broadcast ≙ one ``jnp`` tree-sum that XLA turns
into an ICI all-reduce when inputs live on a mesh). Multi-host SPMD
training doesn't go through this object at all — it uses
``mxnet_tpu.parallel`` pjit shardings, keeping this API as the
compatibility surface that `Module.fit`/`Trainer.step` expect:

* ``local``/``device``/``nccl`` — in-process aggregation (identical
  semantics; on TPU they share one implementation because PJRT owns
  transfers).
* ``dist_sync``/``dist_async``/``dist_sync_device`` — same aggregation,
  plus rank/num_workers from the JAX distributed runtime when
  initialised; server-side async application is documented as
  sync-equivalent (SURVEY.md §2.3: exact async SGD is impossible in SPMD).
"""
from __future__ import annotations

import os
import pickle

from .base import MXNetError
from .ndarray.ndarray import NDArray, zeros as _zeros

__all__ = ["KVStore", "create"]


class KVStore:
    """(parity: kvstore.KVStore)"""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._compression = None

    # -- identity ----------------------------------------------------------
    @property
    def rank(self):
        if self.type.startswith("dist"):
            try:
                import jax
                return jax.process_index()
            except Exception:
                return int(os.environ.get("DMLC_RANK", 0))
        return 0

    @property
    def num_workers(self):
        if self.type.startswith("dist"):
            try:
                import jax
                return jax.process_count()
            except Exception:
                return int(os.environ.get("DMLC_NUM_WORKER", 1))
        return 1

    # -- core ops ----------------------------------------------------------
    def init(self, key, value):
        """(parity: kvstore.init) one key or lists of keys/values."""
        keys, values = _key_value(key, value)
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0):
        """Aggregate gradients (parity: kvstore.push). A list value is the
        per-device shard list; reduction = sum, as CommDevice does. A list
        of KEYS is one batched push: in dist mode all their cross-process
        reductions run as a single jitted collective."""
        keys, values = _key_value(key, value, allow_list_value=True)
        merged_list = []
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, (list, tuple)):
                vlist = [vlist]
            if self._compression is not None:
                # worker-side quantise each device shard, server-side
                # dequantise-aggregate (reference kCompressedPushPull)
                vlist = [self._compress_shard(k, i, v)
                         for i, v in enumerate(vlist)]
            from .ndarray import sparse as _sp
            from .ndarray.ndarray import _wrap
            if all(isinstance(v, _sp.RowSparseNDArray) for v in vlist):
                # sparse gradients aggregate without densifying
                # (reference kRowSparsePushPull)
                merged = _sp.add_n(list(vlist)) if len(vlist) > 1 \
                    else vlist[0]
            else:
                # mixed sparse/dense shards fall back to a dense sum
                # (the reference's storage-fallback path) — summing via
                # the dense views keeps every contribution
                dense = [_wrap(v._data, v.context)
                         if isinstance(v, _sp.BaseSparseNDArray) else v
                         for v in vlist]
                merged = dense[0]
                if len(dense) > 1:
                    merged = dense[0].copy()
                    for v in dense[1:]:
                        merged += v
            merged_list.append(merged)
        merged_list = self._global_reduce_batch(merged_list)
        for k, merged in zip(keys, merged_list):
            if self._updater is not None:
                if k not in self._store:
                    raise MXNetError("push: key %r was not init()ed" % k)
                self._updater(_int_key(k), merged, self._store[k])
            else:
                self._store[k] = merged.copy()

    # one reduction device per process: the first local device of each,
    # a consistent choice on every rank
    @staticmethod
    def _proc_mesh():
        import jax
        import numpy as np
        from jax.sharding import Mesh
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        devs = [by_proc[i] for i in sorted(by_proc)]
        return Mesh(np.array(devs), ("proc",))

    def _global_reduce_batch(self, merged_list):
        """dist_*: sum every locally-merged value across worker processes
        in ONE jitted XLA computation (parity: the ps-lite server
        aggregating every worker's push, kvstore_dist_server.h:261-312
        sync mode). Each process's contribution stays on device: the
        values are assembled into global arrays sharded over a one-
        device-per-process mesh and a single compiled program sums them
        with the collective riding ICI/DCN — no device→host→device round
        trip, no per-key dispatch (the round-1 host allgather did both).

        Collective discipline: every worker must push the same keys in
        the same order (true for SPMD training loops — each process runs
        the same program). ``dist_async`` is emulated synchronously under
        the same rule; true per-arrival async application needs a server
        process, which this all-reduce design intentionally has none of
        (SURVEY.md §2.3 "Async SGD").

        Row-sparse gradients reduce via their dense view (shapes must
        match across processes) plus a row-indicator vector, so the
        result keeps the UNION of rows any worker touched — a pushed row
        whose global sum is exactly zero still reaches the optimizer
        (reference dist-server semantics: every pushed row is updated).
        """
        if not self.type.startswith("dist") or not merged_list:
            return merged_list
        import jax
        if jax.process_count() <= 1:
            return merged_list
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        from .ndarray import sparse as _sp
        from .ndarray.ndarray import _wrap

        mesh = self._proc_mesh()
        nproc = mesh.devices.size
        local_dev = next(d for d in mesh.devices.flat
                         if d.process_index == jax.process_index())
        shard = NamedSharding(mesh, P("proc"))
        repl = NamedSharding(mesh, P())

        # flatten: dense view per value (+ row indicator for row_sparse)
        flat = []          # jax arrays to reduce
        recipe = []        # (kind, ctx, extra) per merged value
        for m in merged_list:
            if isinstance(m, _sp.RowSparseNDArray):
                dense = m.tostype("default")
                ind = jnp.zeros((m.shape[0],), jnp.float32)
                if m._rsp_indices is not None and m._rsp_indices.size:
                    ind = ind.at[m._rsp_indices].set(1.0)
                flat.append(dense._data)
                flat.append(ind)
                recipe.append(("row_sparse", m.context, None))
            elif isinstance(m, _sp.BaseSparseNDArray):
                flat.append(m.tostype("default")._data)
                recipe.append(("csr", m.context, None))
            else:
                flat.append(m._data)
                recipe.append(("dense", m.context, None))

        garrs = []
        for a in flat:
            local = jax.device_put(a, local_dev)
            garrs.append(jax.make_array_from_single_device_arrays(
                (nproc,) + tuple(a.shape), shard, [local[None]]))

        sig = tuple((tuple(a.shape), str(a.dtype)) for a in flat)
        cache = getattr(self, "_reduce_cache", None)
        if cache is None:
            cache = self._reduce_cache = {}
        fn = cache.get(sig)
        if fn is None:
            fn = cache[sig] = jax.jit(
                lambda ts: [t.sum(axis=0) for t in ts],
                out_shardings=repl)
        outs = fn(garrs)
        # replicated outputs: read this process's addressable copy
        outs = [o.addressable_data(0) for o in outs]

        result = []
        i = 0
        for kind, ctx, _ in recipe:
            if kind == "row_sparse":
                dense, ind = outs[i], outs[i + 1]
                i += 2
                rows = np.flatnonzero(np.asarray(ind) > 0).astype(np.int64)
                result.append(self._rows_to_rsp(dense, rows, ctx))
            elif kind == "csr":
                result.append(_sp.cast_storage(
                    _wrap(jnp.asarray(outs[i]), ctx), "csr"))
                i += 1
            else:
                result.append(_wrap(jnp.asarray(outs[i]), ctx))
                i += 1
        return result

    @staticmethod
    def _rows_to_rsp(dense, rows, ctx):
        """Build a RowSparseNDArray holding exactly ``rows`` (the cross-
        worker union), including rows whose summed value is zero."""
        import jax.numpy as jnp
        from .ndarray import sparse as _sp
        dense = jnp.asarray(dense)
        rows_j = jnp.asarray(rows, jnp.int64)
        data = jnp.take(dense, rows_j.astype(jnp.int32), axis=0) \
            if rows_j.size else jnp.zeros((0,) + dense.shape[1:], dense.dtype)
        return _sp.RowSparseNDArray(data, rows_j, dense.shape, ctx)

    def barrier(self):
        """Block until every worker reaches this point (parity:
        KVStore::Barrier via ps-lite Postoffice)."""
        if self.type.startswith("dist"):
            import jax
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                multihost_utils.sync_global_devices("kvstore_barrier")

    def pull(self, key, out=None, priority=0, row_ids=None):
        """Broadcast current value into out arrays (parity: kvstore.pull)."""
        if out is None:
            raise MXNetError("pull requires out=")
        keys, outs = _key_value(key, out, allow_list_value=True)
        for k, olist in zip(keys, outs):
            if k not in self._store:
                raise MXNetError("pull: key %r was not init()ed" % k)
            src = self._store[k]
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            for o in olist:
                src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (parity: kvstore.row_sparse_pull —
        reference kvstore_dist.h:430-496). On TPU this is the sharded-
        embedding gather path; here rows are materialised via retain."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _key_value(key, out, allow_list_value=True)
        if not isinstance(row_ids, (list, tuple)):
            row_ids = [row_ids] * len(keys)
        from .ndarray import sparse as _sp
        for k, olist, rids in zip(keys, outs, row_ids):
            src = self._store[k]
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            for o in olist:
                if isinstance(src, _sp.RowSparseNDArray):
                    picked = src.retain(rids)
                else:
                    picked = _sp.cast_storage(src, "row_sparse").retain(rids)
                o._set_data(picked._data)
                if isinstance(o, _sp.RowSparseNDArray):
                    o._rsp_data = picked._rsp_data
                    o._rsp_indices = picked._rsp_indices

    # -- optimizer plumbing ------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run the optimizer inside the kvstore (parity: update_on_kvstore;
        reference sends a pickled optimizer to the server —
        kvstore_dist.h:102; here it stays in-process)."""
        from . import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit quantised pushes (parity: reference
        gradient_compression.cc; kwargs {'type': '2bit', 'threshold': t}).
        Each device shard is quantised with its own error-feedback
        residual before aggregation — over ICI the raw all-reduce is
        already fast, but this matches the reference's wire semantics and
        is the payload reducer for DCN-spanning pushes."""
        from .gradient_compression import GradientCompression
        params = dict(compression_params or {})
        ctype = params.pop("type", "2bit")
        self._compression_params = compression_params
        self._compression = GradientCompression(type=ctype, **params)

    def _compress_shard(self, key, shard_idx, v):
        """Round-trip one shard through the 2-bit wire format."""
        from .ndarray.ndarray import NDArray, _wrap
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(v, BaseSparseNDArray):
            # reference kvstore_dist.h rejects compression for sparse
            # storage rather than silently densifying
            raise MXNetError(
                "gradient compression is not supported for sparse "
                "gradients (reference parity); push dense or disable "
                "set_gradient_compression")
        raw = v._data if isinstance(v, NDArray) else v
        packed = self._compression.compress((key, shard_idx), raw)
        deq = self._compression.decompress(packed, raw.shape, raw.dtype)
        return _wrap(deq) if isinstance(v, NDArray) else deq

    # -- sync / lifecycle --------------------------------------------------
    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "wb") as f:
            f.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _int_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value, allow_list_value=False):
    if isinstance(key, (str, int)):
        return [key], [value]
    keys = list(key)
    values = list(value)
    if len(values) != len(keys):
        if allow_list_value and len(values) % len(keys) == 0:
            # grouped: values for each key are interleaved per device
            n = len(values) // len(keys)
            values = [values[i * n:(i + 1) * n] for i in range(len(keys))]
        else:
            raise MXNetError("key/value length mismatch")
    return keys, values


def create(name="local"):
    """(parity: mx.kvstore.create / kvstore.cc:38 factory)"""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "nccl", "local_allreduce_cpu",
             "local_allreduce_device", "dist", "dist_sync", "dist_async",
             "dist_sync_device", "dist_device_sync")
    if name not in valid:
        raise MXNetError("unknown kvstore type %r (valid: %s)" % (name, valid))
    return KVStore(name)
