"""KVStore — the data-parallel parameter-synchronisation facade.

Parity: reference ``include/mxnet/kvstore.h`` + ``python/mxnet/kvstore.py``
with backends ``local``/``device``/``nccl``/``dist_*`` (SURVEY.md §5.8).

TPU-native design: there are no parameter-server processes. Within a
process, push/pull over device shards reduces via XLA (the reference's
CommDevice/NCCL reduce+broadcast ≙ one ``jnp`` tree-sum that XLA turns
into an ICI all-reduce when inputs live on a mesh). Multi-host SPMD
training doesn't go through this object at all — it uses
``mxnet_tpu.parallel`` pjit shardings, keeping this API as the
compatibility surface that `Module.fit`/`Trainer.step` expect:

* ``local``/``device``/``nccl`` — in-process aggregation (identical
  semantics; on TPU they share one implementation because PJRT owns
  transfers).
* ``dist_sync``/``dist_async``/``dist_sync_device`` — same aggregation,
  plus rank/num_workers from the JAX distributed runtime when
  initialised; server-side async application is documented as
  sync-equivalent (SURVEY.md §2.3: exact async SGD is impossible in SPMD).
"""
from __future__ import annotations

import os
import pickle

from .base import MXNetError
from .ndarray.ndarray import NDArray, zeros as _zeros
from . import telemetry
from . import faults

__all__ = ["KVStore", "create"]


class KVStore:
    """(parity: kvstore.KVStore)"""

    def __init__(self, kv_type="local"):
        self.type = kv_type
        self._store = {}
        self._updater = None
        self._optimizer = None
        self._compression_params = None
        self._compression = None
        # bytes this process contributed to the last dist push's wire
        # payload (0 for non-dist stores); _raw is the uncompressed
        # equivalent, so wire/raw is the live compression ratio
        self.wire_bytes_last_push = 0
        self.wire_bytes_last_push_raw = 0
        # elastic membership: None = every launched rank; a tuple after
        # _remesh() dropped dead members
        self._live_ranks = None
        self._gate = None
        if kv_type.startswith("dist"):
            # liveness surface (parity: ps-lite scheduler heartbeats
            # behind get_num_dead_node, kvstore.h:338)
            from . import heartbeat
            heartbeat.start_heartbeat(self.rank)

    # -- identity ----------------------------------------------------------
    @property
    def rank(self):
        if self.type.startswith("dist"):
            try:
                import jax
                return jax.process_index()
            except Exception:
                return int(os.environ.get("DMLC_RANK", 0))
        return 0

    @property
    def num_workers(self):
        if self.type.startswith("dist"):
            if self._live_ranks is not None:
                return len(self._live_ranks)
            try:
                import jax
                return jax.process_count()
            except Exception:
                return int(os.environ.get("DMLC_NUM_WORKER", 1))
        return 1

    @property
    def live_ranks(self):
        """Current worker membership: every launched rank until
        :meth:`_remesh` drops dead members."""
        if self._live_ranks is not None:
            return self._live_ranks
        return tuple(range(self.num_workers))

    @property
    def fused_step_subsumable(self):
        """True when a single-program SPMD train step may SUBSUME this
        store's gradient reduction: the in-process aggregation types
        (``local``/``device``/``nccl`` — on TPU one implementation,
        because the dp Module compiles ONE mesh-sharded program whose
        gradients come out of the step already all-reduced over ICI, so
        the software push/pull is an identity round-trip). ``dist_*``
        sync stores are subsumed the same way on a PROCESS-SPANNING
        mesh (:attr:`fused_dist_step`); gradient compression changes
        the pushed values and must keep the explicit wire path."""
        return not self.type.startswith("dist") and self._compression is None

    @property
    def fused_dist_step(self):
        """True when the fused donated-buffer train step may span
        worker processes for this store: the synchronous ``dist_*``
        types, uncompressed. The SAME one-program step then jits over a
        process-spanning dp mesh and XLA inserts the cross-host
        gradient psum INSIDE the step — no software push/pull, the wire
        is the compiled collective. ``dist_async`` keeps the explicit
        path (its server-side async application is emulated over the
        wire; SURVEY.md §2.3), and compression keeps it because the
        2-bit/fp16 payload transform is part of the wire protocol."""
        return (self.type.startswith("dist")
                and self.type != "dist_async"
                and self._compression is None)

    def _remesh(self, live_ranks):
        """Adopt the surviving membership after a member loss: the
        worker count, the pre-collective gate and the compiled exchange
        programs all rebuild against the new (smaller) process set."""
        self._live_ranks = tuple(sorted(int(r) for r in live_ranks))
        self._gate = None
        self._reduce_cache = {}

    def _collective_gate(self):
        """The pre-collective liveness gate for the explicit dist wire
        (lazy; rebuilt on remesh). See heartbeat.CollectiveGate."""
        if self._gate is None:
            from . import heartbeat
            self._gate = heartbeat.CollectiveGate(
                self.rank, self.live_ranks, channel="kv")
        return self._gate

    def _host_allgather(self, arr):
        """Gather one small host array from every LIVE process
        (``(n_live,) + arr.shape``, rank-major). The stock
        ``multihost_utils.process_allgather`` enumerates every LAUNCHED
        process — after an elastic re-mesh it would hang forever
        against the dead members, exactly where the liveness gate just
        promised nothing can hang — so the exchange runs over the
        live-filtered ``_proc_mesh`` instead."""
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P
        a = np.asarray(arr)
        mesh = self._proc_mesh()
        if mesh.devices.size <= 1:
            return a[None]
        local_dev = next(d for d in mesh.devices.flat
                         if d.process_index == jax.process_index())
        local = jax.device_put(a, local_dev)
        garr = jax.make_array_from_single_device_arrays(
            (mesh.devices.size,) + a.shape,
            NamedSharding(mesh, P("proc")), [local[None]])
        cache = getattr(self, "_reduce_cache", None)
        if cache is None:
            cache = self._reduce_cache = {}
        key = ("host_allgather", a.shape, str(a.dtype),
               tuple(d.id for d in mesh.devices.flat))
        fn = cache.get(key)
        if fn is None:
            fn = cache[key] = jax.jit(   # mxlint: disable=jit-site -- a bytes-sized host-metadata replication (the live-membership allgather), same component-kernel class as the grandfathered _global_reduce_batch exchange; a program card per tiny gather signature would be noise
                lambda x: x, out_shardings=NamedSharding(mesh, P()))
        return np.asarray(fn(garr).addressable_data(0))

    # -- core ops ----------------------------------------------------------
    def init(self, key, value):
        """(parity: kvstore.init) one key or lists of keys/values. In a
        multi-process dist store, rank 0's value seeds EVERY worker
        (parity: the ps-lite server is initialised once and workers
        pull — without this, rank-dependent initialisation would
        silently train divergent replicas). One host broadcast per key,
        init-time only; ``MXNET_KVSTORE_DIST_BROADCAST_INIT=0`` opts
        out. Like every dist operation, init must be called by all
        workers symmetrically."""
        keys, values = _key_value(key, value)
        broadcast = None
        if self.type.startswith("dist") and len(self.live_ranks) > 1 \
                and os.environ.get("MXNET_KVSTORE_DIST_BROADCAST_INIT",
                                   "1") != "0":
            try:
                import jax
                from . import dist as _dist
                # never after a member loss: the broadcast spans EVERY
                # launched process and a dead one would hang it (the
                # survivors' values are already consistent — they came
                # from the same checkpoint restore)
                if jax.process_count() > 1 and not _dist.dead_ranks():
                    from .parallel.spmd import broadcast_from_zero
                    broadcast = broadcast_from_zero
            except Exception:
                broadcast = None
        if broadcast is not None \
                and any(k not in self._store for k in keys):
            # liveness gate BEFORE this call's init broadcast(s)
            # (mxsync's collective-discipline check drove this): the
            # broadcast spans every launched process, so a worker that
            # died — even undetected, with dead_ranks() still empty —
            # would hang it forever; the gate turns that into
            # DeadWorkerError. Per init CALL with new keys, so params
            # created later (a second fit, post-recovery keys) are
            # protected too; every worker calls init symmetrically, so
            # the crossing is symmetric
            self._collective_gate().arrive_and_wait()
        for k, v in zip(keys, values):
            if k in self._store:
                continue
            if broadcast is not None and isinstance(v, NDArray) \
                    and getattr(v, "stype", "default") == "default":
                import jax.numpy as jnp
                from .ndarray.ndarray import _wrap
                synced = broadcast(v.asnumpy())
                self._store[k] = _wrap(
                    jnp.asarray(synced).astype(v._data.dtype), v.context)
                continue
            self._store[k] = v.copy() if isinstance(v, NDArray) else v

    def push(self, key, value, priority=0):
        """Aggregate gradients (parity: kvstore.push). A list value is the
        per-device shard list; reduction = sum, as CommDevice does. A list
        of KEYS is one batched push: in dist mode all their cross-process
        reductions run as a single jitted collective."""
        # chaos site: a raise is a lost push (dist wire failure); "nan"
        # corrupts the pushed gradients in place — the divergence
        # sentinel downstream is what should catch it
        if faults.active() and faults.fire("kv_push") == "nan":
            flat = value if isinstance(value, (list, tuple)) else [value]
            for v in flat:
                for x in (v if isinstance(v, (list, tuple)) else [v]):
                    if isinstance(x, NDArray):
                        x[:] = faults.poison([x.asnumpy()])[0]
        with telemetry.span("kv_push"):
            self._push_impl(key, value)
        telemetry.counter_inc("kvstore.push")
        if self.wire_bytes_last_push:
            telemetry.counter_inc("kvstore.wire_bytes",
                                  self.wire_bytes_last_push)
            # dist wire accounting: one batched push = one cross-process
            # collective; raw is the uncompressed-equivalent payload so
            # wire/raw reads off the live compression ratio
            telemetry.counter_inc("kvstore.dist.collectives")
            telemetry.counter_inc("kvstore.dist.wire_bytes",
                                  self.wire_bytes_last_push)
            telemetry.counter_inc("kvstore.dist.wire_bytes_raw",
                                  self.wire_bytes_last_push_raw)

    def _push_impl(self, key, value):
        keys, values = _key_value(key, value, allow_list_value=True)
        merged_list = []
        for k, vlist in zip(keys, values):
            if not isinstance(vlist, (list, tuple)):
                vlist = [vlist]
            if self._compression is not None and not self.type.startswith("dist"):
                # single-process stores quantise each device shard
                # (observable quantisation semantics without a wire); in
                # dist mode the WIRE carries the packed payload instead —
                # local device-shard merging stays full precision, like
                # the reference's Comm-reduce-then-compressed-push
                # (kvstore_dist.h:357-390)
                vlist = [self._compress_shard(k, i, v)
                         for i, v in enumerate(vlist)]
            elif self._compression is not None:
                for v in vlist:
                    self._reject_sparse_compression(v)
            from .ndarray import sparse as _sp
            from .ndarray.ndarray import _wrap
            if all(isinstance(v, _sp.RowSparseNDArray) for v in vlist):
                # sparse gradients aggregate without densifying
                # (reference kRowSparsePushPull)
                merged = _sp.add_n(list(vlist)) if len(vlist) > 1 \
                    else vlist[0]
            else:
                # mixed sparse/dense shards fall back to a dense sum
                # (the reference's storage-fallback path) — summing via
                # the dense views keeps every contribution
                if any(isinstance(v, _sp.BaseSparseNDArray) for v in vlist):
                    from .config import storage_fallback_log
                    storage_fallback_log(
                        "kvstore push of [%s] shards" % ", ".join(
                            getattr(v, "stype", "default") for v in vlist))
                dense = [_wrap(v._data, v.context)
                         if isinstance(v, _sp.BaseSparseNDArray) else v
                         for v in vlist]
                merged = dense[0]
                if len(dense) > 1:
                    merged = dense[0].copy()
                    for v in dense[1:]:
                        merged += v
            merged_list.append(merged)
        merged_list = self._global_reduce_batch(keys, merged_list)
        if self._updater is not None:
            for k in keys:
                if k not in self._store:
                    raise MXNetError("push: key %r was not init()ed" % k)
            if hasattr(self._updater, "update_batch"):
                # whole key list in one fused dispatch (FusedUpdater)
                self._updater.update_batch(
                    [_int_key(k) for k in keys], merged_list,
                    [self._store[k] for k in keys])
            else:
                for k, merged in zip(keys, merged_list):
                    self._updater(_int_key(k), merged, self._store[k])
        else:
            for k, merged in zip(keys, merged_list):
                self._store[k] = merged.copy()

    # one reduction device per LIVE process: the first local device of
    # each, a consistent choice on every rank (after an elastic remesh
    # the dead processes' devices must not enter the exchange mesh)
    def _proc_mesh(self):
        import jax
        import numpy as np
        from jax.sharding import Mesh
        by_proc = {}
        for d in jax.devices():
            by_proc.setdefault(d.process_index, d)
        live = set(self.live_ranks)
        devs = [by_proc[i] for i in sorted(by_proc) if i in live]
        return Mesh(np.array(devs), ("proc",))

    @staticmethod
    def _row_bucket(n):
        """Pad row counts to power-of-two buckets so the exchange program
        recompiles O(log R) times, not per count."""
        return max(8, 1 << (max(int(n), 1) - 1).bit_length())

    def _global_reduce_batch(self, keys, merged_list):
        """dist_*: sum every locally-merged value across worker processes
        in ONE jitted XLA computation (parity: the ps-lite server
        aggregating every worker's push, kvstore_dist_server.h:261-312
        sync mode). Each process's contribution stays on device: the
        values are assembled into global arrays sharded over a one-
        device-per-process mesh and a single compiled program runs with
        the collective riding ICI/DCN.

        Wire payloads (what actually crosses the link; accumulated in
        ``self.wire_bytes_last_push`` for observability):
        - dense, no compression: the fp32 value, summed by the collective;
        - dense + 2-bit compression: each process sends its PACKED uint32
          codes (16x smaller) and every process dequantise-sums the
          gathered payloads — the reference's worker-quantise ->
          server-dequantise-aggregate (kCompressedPushPull), so the wire
          shrinks ~16x, not just the math (the round-2 version quantised
          then shipped uncompressed floats);
        - row_sparse: only TOUCHED rows travel — (indices, rows) padded to
          the bucketed global max count, all-gathered, union-reduced;
          O(nnz-rows) traffic like the reference's kRowSparsePushPull
          (kvstore_dist.h:430-496), not O(full embedding) (round-2). The
          result keeps the UNION of rows any worker touched, so a pushed
          row whose global sum is exactly zero still reaches the
          optimizer (reference dist-server semantics).

        Collective discipline: every worker must push the same keys in
        the same order (true for SPMD training loops). ``dist_async`` is
        emulated synchronously under the same rule (SURVEY.md §2.3).
        """
        self.wire_bytes_last_push = 0
        self.wire_bytes_last_push_raw = 0
        if not self.type.startswith("dist") or not merged_list:
            return merged_list
        import jax
        from .ndarray import sparse as _sp
        from .ndarray.ndarray import _wrap
        if jax.process_count() <= 1 or len(self.live_ranks) <= 1:
            if self._compression is not None:
                # one worker: quantisation semantics still apply (the
                # reference worker would quantise toward its server)
                merged_list = [
                    m if isinstance(m, _sp.BaseSparseNDArray)
                    else self._compress_shard(k, "dist", m)
                    for k, m in zip(keys, merged_list)]
            return merged_list
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        # liveness gate BEFORE the first collective (the discipline
        # check's allgather is itself one): a dead peer raises
        # DeadWorkerError here instead of hanging the exchange
        self._collective_gate().arrive_and_wait()
        self._assert_push_discipline(keys, merged_list)

        mesh = self._proc_mesh()
        nproc = mesh.devices.size
        local_dev = next(d for d in mesh.devices.flat
                         if d.process_index == jax.process_index())
        shard = NamedSharding(mesh, P("proc"))
        repl = NamedSharding(mesh, P())

        # row_sparse values need a common padded row count: one small
        # host allgather of the local counts, bucketed
        rsp_positions = [i for i, m in enumerate(merged_list)
                         if isinstance(m, _sp.RowSparseNDArray)]
        pads = {}
        if rsp_positions:
            local_counts = np.array(
                [int(merged_list[i]._rsp_indices.shape[0])
                 for i in rsp_positions], np.int32)
            all_counts = self._host_allgather(local_counts)
            for j, i in enumerate(rsp_positions):
                pads[i] = self._row_bucket(int(all_counts[:, j].max()))

        flat = []          # local payload arrays
        recipe = []        # one entry per merged value
        comp_saved = []    # bytes saved per compressed entry
        for i, (k, m) in enumerate(zip(keys, merged_list)):
            if isinstance(m, _sp.RowSparseNDArray):
                pcount = pads[i]
                nloc = int(m._rsp_indices.shape[0])
                idx = jnp.full((pcount,), -1, jnp.int32)
                idx = idx.at[:nloc].set(
                    m._rsp_indices.astype(jnp.int32)) if nloc else idx
                vals = jnp.zeros((pcount,) + tuple(m.shape[1:]),
                                 m._rsp_data.dtype)
                vals = vals.at[:nloc].set(m._rsp_data) if nloc else vals
                flat.append(idx)
                flat.append(vals)
                recipe.append(("row_sparse", m.context, m.shape))
            elif isinstance(m, _sp.BaseSparseNDArray):
                flat.append(m.tostype("default")._data)
                recipe.append(("csr_dense_sum", m.context, None))
            elif self._compression is not None:
                packed = self._compression.compress(("dist", k), m._data)
                flat.append(packed)
                recipe.append(("compressed", m.context,
                               (tuple(m.shape), str(m.dtype))))
                # the transform's saving: full-precision fp32 payload
                # minus what actually travels
                comp_saved.append(
                    int(m._data.size) * np.dtype(np.float32).itemsize
                    - int(packed.size) * packed.dtype.itemsize)
            else:
                flat.append(m._data)
                recipe.append(("dense_sum", m.context, None))

        self.wire_bytes_last_push = int(sum(a.size * a.dtype.itemsize
                                            for a in flat))
        # uncompressed equivalent: what the same payloads would have
        # cost without the compression transform (sparse entries
        # already ARE the reduced payload — raw == wire for them)
        self.wire_bytes_last_push_raw = (self.wire_bytes_last_push
                                         + sum(comp_saved))

        garrs = []
        for a in flat:
            local = jax.device_put(a, local_dev)
            garrs.append(jax.make_array_from_single_device_arrays(
                (nproc,) + tuple(a.shape), shard, [local[None]]))

        # one jitted program per (kinds, shapes, dtypes) signature
        ops = []           # parallel to flat: "sum" | "gather" | (shape,)
        for kind, _, extra in recipe:
            if kind == "row_sparse":
                ops.append("gather")
                ops.append("gather")
            elif kind == "compressed":
                ops.append(("dequant_sum", extra[0]))
            else:
                ops.append("sum")
        thr = self._compression.threshold if self._compression else None
        ctype = self._compression.type if self._compression else None
        sig = (tuple((tuple(a.shape), str(a.dtype)) for a in flat),
               tuple(str(o) for o in ops), thr, ctype)
        cache = getattr(self, "_reduce_cache", None)
        if cache is None:
            cache = self._reduce_cache = {}
        fn = cache.get(sig)
        if fn is None:
            from .gradient_compression import dequantize_2bit

            def _run(ts, _ops=tuple(ops), _thr=thr, _ctype=ctype):
                outs = []
                for t, op in zip(ts, _ops):
                    if op == "sum":
                        outs.append(t.sum(axis=0))
                    elif op == "gather":
                        outs.append(t)   # replication IS the all-gather
                    elif _ctype == "fp16":
                        # fp16 wire: dequantise is a widening cast; sum
                        # in fp32 like the reference server would
                        outs.append(t.astype(jnp.float32).sum(axis=0))
                    else:
                        shape = op[1]
                        deq = jax.vmap(lambda p: dequantize_2bit(
                            p, shape, _thr))(t)
                        outs.append(deq.sum(axis=0))
                return outs

            fn = cache[sig] = jax.jit(_run, out_shardings=repl)
        outs = fn(garrs)
        # replicated outputs: read this process's addressable copy
        outs = [o.addressable_data(0) for o in outs]

        result = []
        i = 0
        for kind, ctx, extra in recipe:
            if kind == "row_sparse":
                idx_all = np.asarray(outs[i]).reshape(-1)
                vals_all = jnp.asarray(outs[i + 1]).reshape(
                    (-1,) + tuple(extra[1:]))
                i += 2
                valid = idx_all >= 0
                uniq, inv = np.unique(idx_all[valid], return_inverse=True)
                if uniq.size:
                    summed = jax.ops.segment_sum(
                        vals_all[jnp.asarray(np.flatnonzero(valid))],
                        jnp.asarray(inv), num_segments=len(uniq))
                else:
                    summed = jnp.zeros((0,) + tuple(extra[1:]),
                                       vals_all.dtype)
                result.append(_sp.RowSparseNDArray(
                    summed, jnp.asarray(uniq.astype(np.int64)), extra, ctx))
            elif kind == "csr_dense_sum":
                result.append(_sp.cast_storage(
                    _wrap(jnp.asarray(outs[i]), ctx), "csr"))
                i += 1
            elif kind == "compressed":
                result.append(_wrap(
                    jnp.asarray(outs[i]).astype(extra[1]), ctx))
                i += 1
            else:
                result.append(_wrap(jnp.asarray(outs[i]), ctx))
                i += 1
        return result

    def _assert_push_discipline(self, keys, merged_list):
        """Guard the SPMD collective discipline: every worker must push
        the same (keys, storage types, shapes, dtypes) in the same order
        — a mismatch would deadlock the batched collective or silently
        mis-sum values. The reference's server tolerated arbitrary
        arrival (kvstore_dist_server.h:173-310); SPMD cannot, so we fail
        LOUDLY instead: hash the local push signature, allgather the
        hashes (16 bytes/worker on the host), compare. Disable with
        MXNET_KVSTORE_CHECK_PUSH=0 if the per-push host round-trip ever
        matters (it is one tiny collective per batched push) — the flag
        MUST be set uniformly on every worker: the guard's allgather is
        itself a collective, so a worker that skips it while others run
        it desynchronises the group exactly like the mismatch it
        guards against."""
        if os.environ.get("MXNET_KVSTORE_CHECK_PUSH", "1") == "0":
            return
        import hashlib
        import numpy as np
        desc = repr([(str(k), getattr(m, "stype", "default"),
                      tuple(m.shape), str(m.dtype))
                     for k, m in zip(keys, merged_list)])
        # int32 words: jax x64 is off, so int64 payloads would be
        # silently truncated in the gather and never compare equal
        h = np.frombuffer(hashlib.sha256(desc.encode()).digest()[:16],
                          dtype=np.int32).copy()
        all_h = np.asarray(self._host_allgather(h))
        if not (all_h == all_h[0]).all():
            raise MXNetError(
                "kvstore dist push discipline violated: workers pushed "
                "different (keys, storage types, shapes, dtypes) in this "
                "batched push. Every worker must push the same keys in "
                "the same order (SPMD collective requirement; the "
                "reference's parameter server tolerated arbitrary "
                "arrival, this backend cannot). Local push signature: "
                + desc)

    def barrier(self):
        """Block until every LIVE worker reaches this point (parity:
        KVStore::Barrier via ps-lite Postoffice). Liveness-gated like
        every collective — a dead peer raises instead of hanging — and
        the rendezvous itself is a live-mesh gather (the stock
        ``sync_global_devices`` spans every launched process and would
        hang against members a previous re-mesh dropped)."""
        if self.type.startswith("dist"):
            import jax
            import numpy as np
            if jax.process_count() > 1 and len(self.live_ranks) > 1:
                self._collective_gate().arrive_and_wait()
                self._host_allgather(np.zeros((1,), np.int32))

    def pull(self, key, out=None, priority=0, row_ids=None):
        """Broadcast current value into out arrays (parity: kvstore.pull)."""
        if out is None:
            raise MXNetError("pull requires out=")
        telemetry.counter_inc("kvstore.pull")
        with telemetry.span("kv_pull"):
            keys, outs = _key_value(key, out, allow_list_value=True)
            for k, olist in zip(keys, outs):
                if k not in self._store:
                    raise MXNetError("pull: key %r was not init()ed" % k)
                src = self._store[k]
                if not isinstance(olist, (list, tuple)):
                    olist = [olist]
                for o in olist:
                    src.copyto(o)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (parity: kvstore.row_sparse_pull —
        reference kvstore_dist.h:430-496). On TPU this is the sharded-
        embedding gather path; here rows are materialised via retain."""
        if out is None or row_ids is None:
            raise MXNetError("row_sparse_pull requires out= and row_ids=")
        keys, outs = _key_value(key, out, allow_list_value=True)
        if not isinstance(row_ids, (list, tuple)):
            row_ids = [row_ids] * len(keys)
        from .ndarray import sparse as _sp
        for k, olist, rids in zip(keys, outs, row_ids):
            src = self._store[k]
            if not isinstance(olist, (list, tuple)):
                olist = [olist]
            for o in olist:
                if isinstance(src, _sp.RowSparseNDArray):
                    picked = src.retain(rids)
                else:
                    picked = _sp.cast_storage(src, "row_sparse").retain(rids)
                o._set_data(picked._data)
                if isinstance(o, _sp.RowSparseNDArray):
                    o._rsp_data = picked._rsp_data
                    o._rsp_indices = picked._rsp_indices

    # -- optimizer plumbing ------------------------------------------------
    def set_optimizer(self, optimizer):
        """Run the optimizer inside the kvstore (parity: update_on_kvstore;
        reference sends a pickled optimizer to the server —
        kvstore_dist.h:102; here it stays in-process)."""
        from . import optimizer as opt
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def _set_updater(self, updater):
        self._updater = updater

    def set_gradient_compression(self, compression_params):
        """Enable 2-bit quantised pushes (parity: reference
        gradient_compression.cc; kwargs {'type': '2bit', 'threshold': t}).
        Each device shard is quantised with its own error-feedback
        residual before aggregation — over ICI the raw all-reduce is
        already fast, but this matches the reference's wire semantics and
        is the payload reducer for DCN-spanning pushes."""
        from .gradient_compression import GradientCompression
        params = dict(compression_params or {})
        ctype = params.pop("type", "2bit")
        self._compression_params = compression_params
        self._compression = GradientCompression(type=ctype, **params)

    @staticmethod
    def _reject_sparse_compression(v):
        from .ndarray.sparse import BaseSparseNDArray
        if isinstance(v, BaseSparseNDArray):
            # reference kvstore_dist.h rejects compression for sparse
            # storage rather than silently densifying
            raise MXNetError(
                "gradient compression is not supported for sparse "
                "gradients (reference parity); push dense or disable "
                "set_gradient_compression")

    def _compress_shard(self, key, shard_idx, v):
        """Round-trip one shard through the 2-bit wire format."""
        from .ndarray.ndarray import NDArray, _wrap
        self._reject_sparse_compression(v)
        raw = v._data if isinstance(v, NDArray) else v
        packed = self._compression.compress((key, shard_idx), raw)
        deq = self._compression.decompress(packed, raw.shape, raw.dtype)
        return _wrap(deq) if isinstance(v, NDArray) else deq

    def num_dead_node(self, node_id=0, timeout=None):
        """Count CURRENT members with stale/missing heartbeats (parity:
        KVStore::get_num_dead_node, kvstore.h:338). Unlike the
        reference this is not visibility-only: the pre-collective gate
        turns a dead peer into ``DeadWorkerError`` instead of a hung
        collective, and ``Module.fit`` re-meshes over the survivors.
        node_id is accepted for API parity; the heartbeat dir covers
        all workers. Members dropped by a previous re-mesh no longer
        count."""
        if not self.type.startswith("dist"):
            return 0
        from . import heartbeat
        return len(heartbeat.stale_ranks(self.live_ranks,
                                         timeout=timeout))

    # -- sync / lifecycle --------------------------------------------------
    def send_command_to_servers(self, head, body):
        pass

    def save_optimizer_states(self, fname, dump_optimizer=False):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        from .checkpoint import atomic_write
        atomic_write(fname, self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        if self._updater is None:
            raise MXNetError("no optimizer set on kvstore")
        with open(fname, "rb") as f:
            self._updater.set_states(f.read())


def _int_key(k):
    try:
        return int(k)
    except (TypeError, ValueError):
        return k


def _key_value(key, value, allow_list_value=False):
    if isinstance(key, (str, int)):
        return [key], [value]
    keys = list(key)
    values = list(value)
    if len(values) != len(keys):
        if allow_list_value and len(values) % len(keys) == 0:
            # grouped: values for each key are interleaved per device
            n = len(values) // len(keys)
            values = [values[i * n:(i + 1) * n] for i in range(len(keys))]
        else:
            raise MXNetError("key/value length mismatch")
    return keys, values


def create(name="local"):
    """(parity: mx.kvstore.create / kvstore.cc:38 factory)"""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    valid = ("local", "device", "nccl", "local_allreduce_cpu",
             "local_allreduce_device", "dist", "dist_sync", "dist_async",
             "dist_sync_device", "dist_device_sync")
    if name not in valid:
        raise MXNetError("unknown kvstore type %r (valid: %s)" % (name, valid))
    return KVStore(name)
