"""Experimental contrib namespace (parity: python/mxnet/contrib/)."""
from . import autograd
from . import tensorboard
from ..ndarray import contrib as ndarray
from ..symbol import contrib as symbol
