"""TensorBoard integration (parity: python/mxnet/contrib/tensorboard.py).

``LogMetricsCallback`` streams eval metrics as scalar summaries. Uses the
``tensorboardX``/``tensorboard`` SummaryWriter when importable; otherwise
falls back to a plain JSONL event log in ``logging_dir`` so training
telemetry is never silently dropped (the baked-in environment ships no
tensorboard).
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


class _JsonlWriter:
    """Minimal stand-in for SummaryWriter: one JSON line per scalar."""

    def __init__(self, logdir):
        os.makedirs(logdir, exist_ok=True)
        self._f = open(os.path.join(logdir, "events.jsonl"), "a")

    def add_scalar(self, tag, value, global_step=None):
        self._f.write(json.dumps({
            "wall_time": time.time(), "tag": tag,
            "value": float(value), "step": global_step}) + "\n")
        self._f.flush()

    def close(self):
        self._f.close()


def _make_writer(logging_dir):
    try:
        from tensorboardX import SummaryWriter  # type: ignore
        return SummaryWriter(logging_dir)
    except ImportError:
        pass
    try:
        from torch.utils.tensorboard import SummaryWriter  # type: ignore
        return SummaryWriter(logging_dir)
    except ImportError:
        return _JsonlWriter(logging_dir)


class LogMetricsCallback:
    """Log metrics periodically in TensorBoard (epoch/batch callback).

    Example::

        logging_dir = 'logs/'
        lc = mx.contrib.tensorboard.LogMetricsCallback(logging_dir)
        mod.fit(train, eval_metric='acc', batch_end_callback=lc)
    """

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self.summary_writer = _make_writer(logging_dir)

    def __call__(self, param):
        """Callback to log training speed and metrics in TensorBoard."""
        if param.eval_metric is None:
            return
        self.step += 1
        name_value = param.eval_metric.get_name_value()
        for name, value in name_value:
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self.summary_writer.add_scalar(name, value, self.step)
