"""Contrib (preview) autograd API.

Parity: reference ``python/mxnet/contrib/autograd.py`` — the older spelling
of the autograd surface (train_section/test_section, compute_gradient)
kept for code written against it; delegates to the first-class
``mxnet_tpu.autograd`` tape.
"""
from __future__ import annotations

import functools

from .. import autograd as _ag
from ..imperative import set_training

__all__ = ["set_is_training", "train_section", "test_section",
           "mark_variables", "backward", "compute_gradient", "grad_and_loss",
           "grad", "TrainingStateScope"]


def set_is_training(is_train):
    """Set status to training/not training and recording accordingly.

    Returns the previous training status.
    """
    prev = _ag.set_recording(is_train)
    set_training(is_train)
    return prev


class TrainingStateScope:
    """Scope for managing training state (``with train_section(): ...``)."""

    def __init__(self, enter_state):
        self._enter_state = enter_state
        self._prev = None

    def __enter__(self):
        self._prev = set_is_training(self._enter_state)
        return self

    def __exit__(self, ptype, value, trace):
        if self._prev != self._enter_state:
            set_is_training(self._prev)


def train_section():
    """Scope with gradients recorded (reference contrib.autograd)."""
    return TrainingStateScope(True)


def test_section():
    """Scope with training disabled inside a train_section."""
    return TrainingStateScope(False)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Mark NDArrays as variables for gradient computation."""
    return _ag.mark_variables(variables, gradients, grad_reqs)


def backward(outputs, out_grads=None, retain_graph=False):
    """Compute gradients of outputs w.r.t. marked variables."""
    return _ag.backward(outputs, out_grads, retain_graph)


def compute_gradient(outputs):
    """Deprecated alias of :func:`backward`."""
    return backward(outputs)


def grad_and_loss(func, argnum=None):
    """Return a function that computes both gradient of arguments and loss."""
    @functools.wraps(func)
    def wrapped(*args):
        variables = list(args)
        if argnum is not None:
            argnums = [argnum] if isinstance(argnum, int) else argnum
            variables = [args[i] for i in argnums]
        from ..ndarray import zeros_like
        grads = [zeros_like(x) for x in variables]
        mark_variables(variables, grads)
        with train_section():
            outputs = func(*args)
        backward(list(outputs) if isinstance(outputs, (list, tuple))
                 else [outputs])
        return grads, outputs
    return wrapped


def grad(func, argnum=None):
    """Gradient-only version of :func:`grad_and_loss`."""
    grad_with_loss_func = grad_and_loss(func, argnum)

    @functools.wraps(grad_with_loss_func)
    def wrapped(*args):
        return grad_with_loss_func(*args)[0]
    return wrapped
