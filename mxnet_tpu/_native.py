"""ctypes bindings for the native runtime library (src/recordio.cc).

Parity note: the reference binds its C++ core through a 159-function C
API (include/mxnet/c_api.h). Here only the host-runtime pieces that stay
native (RecordIO scan + threaded batch assembly) cross a C boundary; the
compute path is JAX/XLA and needs no ABI. Builds with `make`; every
consumer has a pure-Python fallback, so the library is optional.
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

_LIB = None
_TRIED = False


def lib():
    """Load (once) and return the native library, or None."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    path = os.path.join(os.path.dirname(__file__), "_lib", "libmxtpu_io.so")
    if not os.path.exists(path):
        return None
    try:
        L = ctypes.CDLL(path)
    except OSError:
        return None
    L.rio_open.restype = ctypes.c_void_p
    L.rio_open.argtypes = [ctypes.c_char_p]
    L.rio_num_records.restype = ctypes.c_long
    L.rio_num_records.argtypes = [ctypes.c_void_p]
    L.rio_record_size.restype = ctypes.c_long
    L.rio_record_size.argtypes = [ctypes.c_void_p, ctypes.c_long]
    L.rio_record_label.restype = ctypes.c_float
    L.rio_record_label.argtypes = [ctypes.c_void_p, ctypes.c_long]
    L.rio_record_copy.argtypes = [ctypes.c_void_p, ctypes.c_long,
                                  ctypes.POINTER(ctypes.c_uint8)]
    L.rio_close.argtypes = [ctypes.c_void_p]
    L.loader_create.restype = ctypes.c_void_p
    L.loader_create.argtypes = [ctypes.c_void_p, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, ctypes.c_int, ctypes.c_int,
                                ctypes.c_int, ctypes.c_uint64,
                                ctypes.c_float,
                                ctypes.POINTER(ctypes.c_float),
                                ctypes.POINTER(ctypes.c_float)]
    L.loader_num_batches.restype = ctypes.c_long
    L.loader_num_batches.argtypes = [ctypes.c_void_p]
    L.loader_next.restype = ctypes.c_int
    L.loader_next.argtypes = [ctypes.c_void_p,
                              ctypes.POINTER(ctypes.c_float),
                              ctypes.POINTER(ctypes.c_float)]
    L.loader_reset.argtypes = [ctypes.c_void_p]
    L.loader_destroy.argtypes = [ctypes.c_void_p]
    _LIB = L
    return _LIB


class NativeRecordLoader:
    """Threaded native batch loader over a RecordIO file."""

    def __init__(self, path, batch_size, data_shape, num_threads=4,
                 shuffle=False, seed=0, scale=1.0, mean=(0, 0, 0),
                 std=(1, 1, 1)):
        L = lib()
        if L is None:
            raise RuntimeError("native library not built (run `make`)")
        self._L = L
        self._file = L.rio_open(path.encode())
        if not self._file:
            raise RuntimeError("cannot open RecordIO file %r" % path)
        c, h, w = data_shape
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        mean_a = (ctypes.c_float * 3)(*[float(m) for m in mean])
        std_a = (ctypes.c_float * 3)(*[float(s) for s in std])
        self._loader = L.loader_create(self._file, batch_size, c, h, w,
                                       num_threads, int(shuffle), seed,
                                       float(scale), mean_a, std_a)
        self.num_batches = L.loader_num_batches(self._loader)

    def next(self):
        c, h, w = self.data_shape
        data = np.empty((self.batch_size, c, h, w), np.float32)
        label = np.empty((self.batch_size,), np.float32)
        ok = self._L.loader_next(
            self._loader,
            data.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            label.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        if not ok:
            raise StopIteration
        return data, label

    def reset(self):
        self._L.loader_reset(self._loader)

    def __del__(self):
        try:
            if getattr(self, "_loader", None):
                self._L.loader_destroy(self._loader)
            if getattr(self, "_file", None):
                self._L.rio_close(self._file)
        except Exception:
            pass
