"""Default image-tensor layout for Gluon conv/pool/norm layers.

TPU-first extension with no reference counterpart: the reference is NCHW
throughout (convolution-inl.h layouts); on TPU the MXU-native layout is
channels-last (channel dim lands in the lane dimension, no relayout
copies). Rather than thread a ``layout=`` argument through every model-zoo
constructor, the Gluon layers resolve their default layout here — models
built under ``set_default_layout("NHWC")`` (or with
``MXTPU_IMAGE_LAYOUT=NHWC`` in the environment) run channels-last end to
end. Explicit per-layer ``layout=``/``axis=`` arguments always win.

The op-level API is unchanged: ``layout=None`` on Convolution/Pooling
still means the reference's NC+spatial.
"""
from __future__ import annotations

import os

from .base import MXNetError

_CHANNELS_LAST = {1: "NWC", 2: "NHWC", 3: "NDHWC"}
_CHANNELS_FIRST = {1: "NCW", 2: "NCHW", 3: "NCDHW"}

_default = None


def set_default_layout(layout):
    """Set the process default: "NHWC"/"NWC"/"NDHWC" (channels-last),
    "NCHW"/"NCW"/"NCDHW" (channels-first), or None (reference default)."""
    global _default
    if layout is not None:
        layout = str(layout).upper()
        if layout not in list(_CHANNELS_LAST.values()) + \
                list(_CHANNELS_FIRST.values()):
            raise MXNetError("unknown layout %r" % layout)
    _default = layout


# env path goes through the validating setter so typos raise instead of
# silently picking a layout
if os.environ.get("MXTPU_IMAGE_LAYOUT"):
    set_default_layout(os.environ["MXTPU_IMAGE_LAYOUT"])


def get_default_layout():
    return _default


def default_is_channels_last():
    return bool(_default) and _default.endswith("C") and _default != "NC"


def resolve(layout, ndim):
    """Layer-construction helper: explicit layout wins; otherwise the
    process default (adapted to ndim); otherwise None (reference NC+spatial)."""
    if layout is not None:
        return str(layout).upper()
    if _default is None:
        return None
    table = _CHANNELS_LAST if _default.endswith("C") and _default != "NC" \
        else _CHANNELS_FIRST
    return table.get(ndim)


def channel_axis(layout, ndim):
    """Channel axis index for a resolved layout (None -> reference's 1)."""
    if layout is None:
        return 1
    return len(layout) - 1 if str(layout).upper().endswith("C") else 1
