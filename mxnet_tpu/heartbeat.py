"""Worker liveness heartbeats.

Parity: the reference's only failure-visibility surface is
``KVStore::get_num_dead_node(node_id, timeout)`` backed by ps-lite
scheduler heartbeats (include/mxnet/kvstore.h:338, SURVEY.md §5.3). The
SPMD design has no scheduler process, so liveness rides a shared
filesystem: each worker's daemon thread touches
``{MXTPU_HEARTBEAT_DIR}/worker-{rank}`` every ``interval`` seconds and
any process can count peers whose file is stale. ``tools/launch.py``
provisions the directory for local/ssh jobs (a pod slice shares NFS/GCS
fuse mounts the same way).

Like the reference, this is VISIBILITY only — a dead worker still hangs
collectives; recovery is checkpoint-restart (SURVEY.md §5.3/5.4).
"""
from __future__ import annotations

import os
import threading
import time

__all__ = ["start_heartbeat", "stop_heartbeat", "count_dead"]

ENV_DIR = "MXTPU_HEARTBEAT_DIR"
DEFAULT_INTERVAL = 1.0

_state = {"thread": None, "stop": None, "path": None}


def _path(root, rank):
    return os.path.join(root, "worker-%d" % int(rank))


def start_heartbeat(rank, root=None, interval=DEFAULT_INTERVAL):
    """Start (idempotently) the daemon heartbeat for this process."""
    root = root or os.environ.get(ENV_DIR)
    if not root or _state["thread"] is not None:
        return
    os.makedirs(root, exist_ok=True)
    path = _path(root, rank)
    stop = threading.Event()

    def beat():
        # ATOMIC beat: write temp + rename. The old open(path, "w")
        # truncated in place, so a concurrent count_dead() could stat
        # the file mid-rewrite and read a zero-length/zero-mtime worker
        # as dead — on shared filesystems (NFS/GCS fuse, exactly where
        # this runs) the truncate→write window is milliseconds wide.
        tmp = path + ".tmp"
        while not stop.is_set():
            try:
                with open(tmp, "w") as f:
                    f.write(str(time.time()))
                os.replace(tmp, path)
            except OSError:
                pass
            stop.wait(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name="mxtpu-heartbeat-%d" % int(rank))
    t.start()
    _state["thread"] = t
    _state["stop"] = stop
    _state["path"] = path


def stop_heartbeat():
    """Stop the beat AND remove this worker's file: a cleanly-stopped
    worker must read as departed immediately, not linger as a stale
    file that counts dead for ``timeout`` seconds first."""
    if _state["stop"] is None:
        return
    _state["stop"].set()
    thread, path = _state["thread"], _state["path"]
    _state["thread"] = None
    _state["stop"] = None
    _state["path"] = None
    if thread is not None:
        # the beat loop wakes immediately on the event; join so a
        # final in-flight rename cannot resurrect the file after the
        # removal below
        thread.join(timeout=5.0)
    if path is not None:
        for p in (path, path + ".tmp"):
            try:
                os.remove(p)
            except OSError:
                pass


def count_dead(num_workers, root=None, timeout=None):
    """Number of workers whose heartbeat is missing or older than
    ``timeout`` seconds (parity: get_num_dead_node)."""
    root = root or os.environ.get(ENV_DIR)
    if not root:
        return 0
    timeout = float(timeout if timeout is not None
                    else os.environ.get("MXTPU_HEARTBEAT_TIMEOUT", 10.0))
    now = time.time()
    dead = 0
    for rank in range(int(num_workers)):
        path = _path(root, rank)
        try:
            if now - os.path.getmtime(path) > timeout:
                dead += 1
        except OSError:
            dead += 1
    return dead
