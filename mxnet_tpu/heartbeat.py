"""Worker liveness: heartbeats + the pre-collective gate.

Parity: the reference's only failure-visibility surface is
``KVStore::get_num_dead_node(node_id, timeout)`` backed by ps-lite
scheduler heartbeats (include/mxnet/kvstore.h:338, SURVEY.md §5.3). The
SPMD design has no scheduler process, so liveness rides a shared
filesystem: each worker's daemon thread touches
``{MXTPU_HEARTBEAT_DIR}/worker-{rank}`` every ``interval`` seconds and
any process can count peers whose file is stale. ``tools/launch.py``
provisions the directory for local/ssh jobs (a pod slice shares NFS/GCS
fuse mounts the same way).

Beyond visibility (the reference stopped there — "a dead worker still
hangs collectives"), this module is the LIVENESS substrate of elastic
training: :class:`CollectiveGate` is a bounded-timeout barrier-file
protocol every worker crosses BEFORE entering a cross-process
collective. A peer that never arrives and whose heartbeat has gone
stale raises :class:`DeadWorkerError` naming the dead ranks — the
survivors abort the step they never entered (nothing hangs), re-mesh
over the live membership and resume from the last atomic checkpoint
(``Module.fit`` elastic path). Two failure-injection sites ride here:
``kv_collective`` (fired at every gate crossing — the chaos lane's
deterministic rank kill) and ``heartbeat`` (fired per beat — a raise
kills the beat thread, simulating a zombie worker that computes but
reads as dead).

Staleness is judged against the FILESYSTEM's clock, not the reader's:
ages compare a worker file's mtime to the mtime of a probe file the
reader just wrote into the same directory. On NFS/GCS-fuse — exactly
where this runs — a reader wall clock skewed from the file server
would otherwise read every live peer as dead (or a dead one as
forever-live). The beat payload's ``time.time()`` text is
informational only.
"""
from __future__ import annotations

import os
import re
import threading
import time
import weakref

from .base import MXNetError

__all__ = ["start_heartbeat", "stop_heartbeat", "count_dead",
           "alive_ranks", "stale_ranks", "CollectiveGate",
           "DeadWorkerError", "gate_stats"]

ENV_DIR = "MXTPU_HEARTBEAT_DIR"
ENV_INTERVAL = "MXTPU_HEARTBEAT_INTERVAL"
ENV_TIMEOUT = "MXTPU_HEARTBEAT_TIMEOUT"
ENV_GATE_TIMEOUT = "MXTPU_GATE_TIMEOUT"
ENV_STRAGGLER_MS = "MXTPU_STRAGGLER_MS"
ENV_STRAGGLER_K = "MXTPU_STRAGGLER_K"
DEFAULT_INTERVAL = 1.0
DEFAULT_TIMEOUT = 10.0
# a peer missing from the gate whose heartbeat stays FRESH is slow
# (compiling, GC pause), not dead — wait for it up to this hard cap
DEFAULT_GATE_TIMEOUT = 300.0
# straggler verdict: the last arriver is a straggler when its arrival
# trails the fleet median by >= this many ms for K consecutive
# crossings of the same channel (one slow step is noise; a streak is
# a rank the planner should act on)
DEFAULT_STRAGGLER_MS = 50.0
DEFAULT_STRAGGLER_K = 3

# every live gate, so the flight sampler can fold per-channel wait
# series into its samples without threading gate handles through the
# fit loop (weak: a gate dies with its owner, the registry must not
# pin re-meshed gates alive)
_gates_lock = threading.Lock()
_gates = weakref.WeakSet()      # guarded by: _gates_lock

_WORKER_RE = re.compile(r"^worker-(\d+)$")

_state = {"thread": None, "stop": None, "path": None}


class DeadWorkerError(MXNetError):
    """A cross-process collective was aborted before entry: peer
    worker(s) are missing from the gate and their heartbeats are stale
    (``ranks``), or the gate's hard timeout expired (``timed_out`` with
    the still-missing ranks). ``channel``/``generation`` locate the
    collective; ``epoch``/``nbatch`` are stamped by the fit loop where
    known."""

    def __init__(self, ranks, channel=None, generation=None,
                 timed_out=False, evidence=None):
        self.ranks = tuple(sorted(int(r) for r in ranks))
        self.channel = channel
        self.generation = generation
        self.timed_out = timed_out
        self.evidence = dict(evidence or {})
        self.epoch = None
        self.nbatch = None
        what = ("gate timeout waiting for worker(s) %s (heartbeats still "
                "fresh — raising anyway after the hard cap)"
                if timed_out else
                "worker(s) %s are dead (missing from the gate, heartbeat "
                "stale)")
        ev = ""
        if self.evidence:
            ev = " evidence: " + ", ".join(
                "rank %s: %s" % (r, e)
                for r, e in sorted(self.evidence.items()))
        super().__init__(
            ("collective aborted before entry: " + what +
             " [channel=%r generation=%s].%s Surviving workers should "
             "re-mesh and resume from the last checkpoint.")
            % (list(self.ranks), channel, generation, ev))


def _path(root, rank):
    return os.path.join(root, "worker-%d" % int(rank))


def _interval(interval):
    if interval is not None:
        return float(interval)
    return float(os.environ.get(ENV_INTERVAL, DEFAULT_INTERVAL))


def _timeout(timeout):
    if timeout is not None:
        return float(timeout)
    return float(os.environ.get(ENV_TIMEOUT, DEFAULT_TIMEOUT))


def _unlink_quiet(path):
    """Best-effort removal of a temp artifact on a failure path — the
    shared unlink-on-failure half of the write-tmp/fsync/rename
    protocol (mxlife resource-release: a failed rename must not leave
    ``.tmp`` litter on the shared mount peers scan forever)."""
    try:
        os.unlink(path)
    except OSError:
        pass


def _fs_now(root):
    """The shared directory's OWN notion of "now": the mtime of a probe
    file this process just wrote there. Comparing worker-file mtimes
    against this (instead of the reader's ``time.time()``) makes
    staleness immune to wall-clock skew between the reader and the
    file server. Falls back to the local clock when the directory
    is unwritable."""
    probe = os.path.join(root, ".clock-probe-%d" % os.getpid())
    tmp = probe + ".tmp"
    try:
        with open(tmp, "w") as f:
            f.write("probe")
        os.replace(tmp, probe)
        return os.path.getmtime(probe)
    except OSError:
        # a failed rename must not leave the probe's .tmp behind on
        # the shared mount — leftover artifacts are exactly what the
        # scanner has to defend against (mxlife resource-release)
        _unlink_quiet(tmp)
        return time.time()


def start_heartbeat(rank, root=None, interval=None):
    """Start (idempotently) the daemon heartbeat for this process."""
    root = root or os.environ.get(ENV_DIR)
    if not root or _state["thread"] is not None:
        return
    os.makedirs(root, exist_ok=True)
    path = _path(root, rank)
    stop = threading.Event()
    interval = _interval(interval)

    def beat():
        # ATOMIC beat: write temp + rename. The old open(path, "w")
        # truncated in place, so a concurrent staleness read could stat
        # the file mid-rewrite and read a zero-length/zero-mtime worker
        # as dead — on shared filesystems (NFS/GCS fuse, exactly where
        # this runs) the truncate→write window is milliseconds wide.
        from . import faults
        tmp = path + ".tmp"
        while not stop.is_set():
            # chaos site: a raise kills THIS thread — the worker keeps
            # computing but its file goes stale, the zombie the liveness
            # tier must treat as dead; delay= stretches the beat gap
            faults.fire("heartbeat")
            try:
                with open(tmp, "w") as f:
                    f.write(str(time.time()))
                os.replace(tmp, path)
            except OSError:
                # a beat that failed between create and rename must
                # not leave its .tmp behind: a worker that then DIES
                # would leak the artifact onto the shared mount
                # forever (stop_heartbeat only cleans a clean stop)
                _unlink_quiet(tmp)
            stop.wait(interval)

    t = threading.Thread(target=beat, daemon=True,
                         name="mxtpu-heartbeat-%d" % int(rank))
    t.start()
    _state["thread"] = t
    _state["stop"] = stop
    _state["path"] = path


def stop_heartbeat():
    """Stop the beat AND remove this worker's file: a cleanly-stopped
    worker must read as departed immediately, not linger as a stale
    file that counts dead for ``timeout`` seconds first."""
    if _state["stop"] is None:
        return
    _state["stop"].set()
    thread, path = _state["thread"], _state["path"]
    _state["thread"] = None
    _state["stop"] = None
    _state["path"] = None
    if thread is not None:
        # the beat loop wakes immediately on the event; join so a
        # final in-flight rename cannot resurrect the file after the
        # removal below
        thread.join(timeout=5.0)
    if path is not None:
        for p in (path, path + ".tmp"):
            try:
                os.remove(p)
            except OSError:
                pass


def _scan(root, timeout):
    """ONE pass over the heartbeat directory: ``(alive_set, ages)``
    with a single probe write (every caller needing both freshness and
    evidence ages shares it — per-poll double probe writes would be
    sustained metadata churn on exactly the NFS/GCS mounts this
    targets). Scans exact ``worker-<N>`` names — a leftover
    ``worker-N.tmp`` from a writer that died mid-rename (or any other
    stray file) is ignored — and judges freshness against the
    directory's own clock (see :func:`_fs_now`)."""
    try:
        names = os.listdir(root)
    except OSError:
        return set(), {}
    now = _fs_now(root)
    alive, ages = set(), {}
    for name in names:
        m = _WORKER_RE.match(name)
        if not m:
            continue
        try:
            age = now - os.path.getmtime(os.path.join(root, name))
        except OSError:
            continue
        ages[int(m.group(1))] = age
        if age <= timeout:
            alive.add(int(m.group(1)))
    return alive, ages


def alive_ranks(root=None, timeout=None):
    """Set of worker ranks with a FRESH heartbeat file (see
    :func:`_scan` for the clock and ``.tmp`` discipline)."""
    root = root or os.environ.get(ENV_DIR)
    if not root:
        return set()
    return _scan(root, _timeout(timeout))[0]


def stale_ranks(ranks, root=None, timeout=None):
    """The subset of ``ranks`` whose heartbeat file is missing or
    stale (same clock discipline as :func:`alive_ranks`)."""
    root = root or os.environ.get(ENV_DIR)
    if not root:
        return []
    alive = alive_ranks(root=root, timeout=timeout)
    return [int(r) for r in ranks if int(r) not in alive]


def count_dead(num_workers, root=None, timeout=None):
    """Number of workers in ``range(num_workers)`` whose heartbeat is
    missing or older than ``timeout`` seconds (parity:
    get_num_dead_node). Staleness is judged against the heartbeat
    directory's own clock and leftover ``*.tmp`` artifacts never count
    as live workers."""
    root = root or os.environ.get(ENV_DIR)
    if not root:
        return 0
    return len(stale_ranks(range(int(num_workers)), root=root,
                           timeout=timeout))


class CollectiveGate:
    """Bounded-timeout barrier-file protocol crossed BEFORE every
    cross-process collective.

    Each member owns ONE file per channel
    (``gate-<channel>-<members>/rank-<r>``) holding its latest
    generation number, rewritten atomically each crossing — no per-step
    file accumulation. ``arrive_and_wait()`` bumps the local
    generation, publishes it, and polls until every peer's published
    generation reaches it (a peer racing ahead has necessarily passed
    this generation). A peer that has not arrived is judged by its
    HEARTBEAT: stale → :class:`DeadWorkerError` naming the dead ranks
    (the caller aborts the step it never entered — nothing hangs);
    fresh → keep waiting (slow ≠ dead) up to the hard cap
    (``MXTPU_GATE_TIMEOUT``), then raise with ``timed_out=True``.

    The directory name embeds the member set, so a re-meshed group
    (after a member loss) opens a fresh namespace and the dead peer's
    old generation file cannot satisfy a new-generation wait.

    The ``kv_collective`` fault site fires at every crossing BEFORE the
    arrival is published: an injected raise kills this worker at a
    deterministic collective index and its peers observe exactly what a
    real mid-training death looks like — an absent arrival and a
    heartbeat going stale.
    """

    def __init__(self, rank, members, root=None, channel="step",
                 timeout=None, gate_timeout=None, poll=0.05):
        self.rank = int(rank)
        self.members = tuple(sorted(int(m) for m in members))
        self.channel = str(channel)
        self.root = root or os.environ.get(ENV_DIR)
        self.timeout = _timeout(timeout)
        self.gate_timeout = float(
            gate_timeout if gate_timeout is not None
            else os.environ.get(ENV_GATE_TIMEOUT, DEFAULT_GATE_TIMEOUT))
        self.poll = float(poll)
        # the gate's mutable state is shared the moment a gate object
        # is reachable from more than one thread (an elastic-recovery
        # watcher reading .generation while the fit thread crosses):
        # guard it explicitly instead of relying on today's single-
        # threaded use (mxsync annotation satellite, ISSUE 13)
        self._lock = threading.Lock()
        self.generation = 0     # guarded by: self._lock
        # ranks whose heartbeat this gate has EVER observed: a missing
        # file is only evidence of death for a peer we once saw — a
        # slow joiner (still importing jax while we cross the first
        # gate) has no file yet and must not read as dead
        self._seen = set()      # guarded by: self._lock
        self.straggler_ms = float(os.environ.get(
            ENV_STRAGGLER_MS, DEFAULT_STRAGGLER_MS))
        self.straggler_k = max(1, int(os.environ.get(
            ENV_STRAGGLER_K, DEFAULT_STRAGGLER_K)))
        # consecutive-crossing count for the CURRENT worst rank only —
        # a different rank arriving last resets the streak (the verdict
        # is "one rank is persistently slow", not "steps are slow")
        self._streak = [None, 0]        # guarded by: self._lock
        self._stats = {                 # guarded by: self._lock
            "crossings": 0, "wait_ms_total": 0.0, "last_wait_ms": 0.0,
            "last_rank": None, "last_excess_ms": 0.0, "stragglers": 0,
        }
        # step-time skew bookkeeping: wall time between crossings minus
        # the waits the caller reported (note_wait) = this rank's OWN
        # work, published in the gate file so every rank can compare
        # self-times fleet-wide. A straggler whose slowness hides
        # behind a synchronizing collective (peers absorb it in their
        # completion await, arriving at the next gate together) is
        # invisible to arrival order but NOT to self-time.
        self._ext_wait_ms = 0.0         # guarded by: self._lock
        self._last_return = None        # guarded by: self._lock
        self._dir = None
        if self.root:
            tag = "-".join(str(m) for m in self.members)
            self._dir = os.path.join(
                self.root, "gate-%s-%s" % (self.channel, tag))
        with _gates_lock:
            _gates.add(self)

    @property
    def enabled(self):
        """The file protocol needs the shared heartbeat directory and a
        peer to guard against; otherwise crossings are (fault-site
        consults followed by) no-ops."""
        return self._dir is not None and len(self.members) > 1

    def _member_path(self, rank):
        return os.path.join(self._dir, "rank-%d" % int(rank))

    def note_wait(self, ms):
        """Report time this rank spent WAITING between crossings (the
        fit loop calls this with its collective-completion await). The
        reported waits are subtracted from the inter-crossing wall time
        so the self-time published at the next arrival reflects this
        rank's OWN work only — a rank stalled waiting on a slow peer
        must not itself read as slow."""
        with self._lock:
            self._ext_wait_ms += max(0.0, float(ms))

    def _take_self_ms(self):
        """Self-time for the crossing about to be published: wall time
        since the previous crossing returned, minus the waits the
        caller reported via :meth:`note_wait`. ``None`` on the first
        crossing (no window yet). Resets the window."""
        now = time.monotonic()
        with self._lock:
            last, ext = self._last_return, self._ext_wait_ms
            self._ext_wait_ms = 0.0
        if last is None:
            return None
        return max(0.0, (now - last) * 1e3 - ext)

    def _publish(self, gen, self_ms=None):
        os.makedirs(self._dir, exist_ok=True)
        path = self._member_path(self.rank)
        tmp = path + ".tmp"
        try:
            with open(tmp, "w") as f:
                # "<gen> <local wall time> <self_ms>": the generation
                # is the protocol; the timestamp is the informational
                # half of the arrival record (arrival ORDER is judged
                # by file mtimes — the shared filesystem's own clock,
                # the only one comparable across hosts; see _fs_now);
                # self_ms is this rank's own-work time since its last
                # crossing ("-" on the first), the fleet-comparable
                # step-time-skew signal every peer reads back
                f.write("%d %.6f %s"
                        % (int(gen), time.time(),
                           "-" if self_ms is None else "%.3f" % self_ms))
            os.replace(tmp, path)
        except BaseException:
            # gate-publish failure is fatal to the crossing (the
            # caller raises), but the .tmp must not linger on the
            # shared mount — peers scan this directory forever
            _unlink_quiet(tmp)
            raise

    def _peer_gen(self, rank):
        try:
            with open(self._member_path(rank)) as f:
                head = f.read().split()
                return int(head[0]) if head else 0
        except (OSError, ValueError):
            return -1

    def _arrivals(self, gen):
        """Arrival record for generation ``gen``, read back from the
        gate files every rank just published: ``[(rank, mtime,
        self_ms)]`` where ``mtime`` is the arrival time on the shared
        filesystem's clock (cross-host comparable — the same clock
        staleness is judged by) and ``self_ms`` is the own-work time
        that rank published with its arrival (None when absent). A
        member whose file already shows a LATER generation raced
        ahead — it certainly arrived at ``gen`` before us, but its
        mtime/self-time now reflect the later publish, so it carries
        ``mtime=None`` and is excluded from timing verdicts."""
        out = []
        for m in self.members:
            path = self._member_path(m)
            try:
                with open(path) as f:
                    head = f.read().split()
                g = int(head[0]) if head else 0
                mt = os.path.getmtime(path)
            except (OSError, ValueError):
                continue
            self_ms = None
            if len(head) > 2 and head[2] != "-":
                try:
                    self_ms = float(head[2])
                except ValueError:
                    pass
            if g == gen:
                out.append((int(m), mt, self_ms))
            elif g > gen:
                out.append((int(m), None, None))
        return out

    def _record_crossing(self, gen, t0_ns, error=None):
        """Attribute one finished (or aborted) crossing: a
        ``gate_wait`` span whose ctx names who arrived last and by how
        much, per-channel wait/crossing counters, running stats for the
        flight sampler, and the streak machine behind the structured
        ``dist.straggler`` event. Attribution must never take down a
        step the barrier itself completed — any surprise here is
        swallowed after stamping the stats.

        TWO skew signals feed one verdict, because a straggler can hide
        either way: (a) arrival-order excess — the last arrival's mtime
        vs the fleet's lower-median arrival (catches slow input/compute
        BEFORE the gate); (b) self-time excess — the max published
        self-time vs its lower median (catches slowness a synchronizing
        collective absorbed: peers blocked in the completion await
        arrive at the next gate TOGETHER, so arrival order reads ~0
        skew while the straggler's own-work time is the step's whole
        budget). The verdict takes whichever signal shows the larger
        excess."""
        from . import telemetry
        with self._lock:
            # close the self-time window at the crossing's end, enabled
            # or not: the next publish measures from here
            self._last_return = time.monotonic()
        if not telemetry.enabled():
            return
        t1_ns = time.perf_counter_ns()
        wait_ms = (t1_ns - t0_ns) / 1e6
        last_rank, excess_ms, order = None, 0.0, []
        try:
            arrivals = self._arrivals(gen)
            timed = sorted((mt, r) for r, mt, _s in arrivals
                           if mt is not None)
            if timed:
                first_mt = timed[0][0]
                order = [[r, round((mt - first_mt) * 1e3, 3)]
                         for mt, r in timed]
                last_mt, last_rank = timed[-1]
                # lower median of the (sorted) arrival times: with 2
                # ranks the excess is simply last-vs-first; with more,
                # one early outlier cannot inflate the verdict
                mid = timed[(len(timed) - 1) // 2][0]
                excess_ms = max(0.0, (last_mt - mid) * 1e3)
            selfs = sorted((s, r) for r, mt, s in arrivals
                           if mt is not None and s is not None)
            self_map = {r: round(s, 3) for s, r in selfs}
            if len(selfs) > 1:
                slow_ms, slow_rank = selfs[-1]
                skew_ms = max(
                    0.0, slow_ms - selfs[(len(selfs) - 1) // 2][0])
                if skew_ms > excess_ms:
                    last_rank, excess_ms = slow_rank, skew_ms
            if error is not None:
                # the crossing never completed: the wait is the dead
                # rank's fault in full — this is the pre-death spike
                # fleet_view pins on the victim
                last_rank = int(error.ranks[0])
                excess_ms = wait_ms
            ctx = dict(telemetry.current_causal() or {})
            ctx.update({"channel": self.channel, "generation": gen,
                        "wait_ms": round(wait_ms, 3)})
            if last_rank is not None:
                ctx["last_rank"] = last_rank
                ctx["excess_ms"] = round(excess_ms, 3)
            if order:
                ctx["arrivals"] = order
            if self_map:
                ctx["self_ms"] = self_map
            if error is not None:
                ctx["dead_ranks"] = list(error.ranks)
                ctx["timed_out"] = bool(error.timed_out)
            telemetry.record_span("gate_wait", t0_ns, t1_ns, ctx)
            telemetry.counter_inc(
                "heartbeat.gate_crossings.%s" % self.channel)
            telemetry.counter_inc(
                "heartbeat.gate_wait_ms.%s" % self.channel,
                round(wait_ms, 3))
            emit = 0
            with self._lock:
                st = self._stats
                st["crossings"] += 1
                st["wait_ms_total"] += wait_ms
                st["last_wait_ms"] = wait_ms
                st["last_rank"] = last_rank
                st["last_excess_ms"] = excess_ms
                if error is None and last_rank is not None \
                        and excess_ms >= self.straggler_ms:
                    if self._streak[0] != last_rank:
                        self._streak = [last_rank, 0]
                    self._streak[1] += 1
                    if self._streak[1] >= self.straggler_k:
                        emit = self._streak[1]
                        st["stragglers"] += 1
                else:
                    self._streak = [None, 0]
            if emit:
                telemetry.record_event(
                    "dist.straggler", rank=last_rank,
                    channel=self.channel, generation=gen,
                    excess_ms=round(excess_ms, 3),
                    wait_ms=round(wait_ms, 3), streak=emit)
                telemetry.counter_inc("dist.straggler")
        except Exception:
            pass

    def stats(self):
        """Point-in-time copy of this gate's crossing stats (the
        flight sampler's per-channel series source)."""
        with self._lock:
            return dict(self._stats)

    def arrive_and_wait(self):
        """Cross the gate for the next collective. Returns the
        generation entered; raises :class:`DeadWorkerError` instead of
        letting the caller enter a collective a dead peer can never
        join."""
        from . import faults
        # the chaos kill point: BEFORE publishing the arrival, so a
        # killed worker is missing from this generation on every peer
        faults.fire("kv_collective")
        with self._lock:
            self.generation += 1
            gen = self.generation
        if not self.enabled:
            return gen
        self._publish(gen, self_ms=self._take_self_ms())
        t0_ns = time.perf_counter_ns()
        deadline = time.monotonic() + self.gate_timeout
        peers = [m for m in self.members if m != self.rank]
        # liveness verdicts need a directory scan + probe write — keep
        # those to a few per second even while the arrival files poll
        # fast (a slow-but-live peer can keep us here for minutes)
        liveness_every = max(self.poll, 0.25)
        next_liveness = time.monotonic()
        while True:
            missing = [p for p in peers if self._peer_gen(p) < gen]
            if not missing:
                self._record_crossing(gen, t0_ns)
                return gen
            if time.monotonic() >= next_liveness:
                next_liveness = time.monotonic() + liveness_every
                dead = self._dead_among(missing)
                if dead:
                    err = DeadWorkerError([r for r, _ in dead],
                                          channel=self.channel,
                                          generation=gen,
                                          evidence=dict(dead))
                    self._record_crossing(gen, t0_ns, error=err)
                    raise err
            if time.monotonic() > deadline:
                err = DeadWorkerError(missing, channel=self.channel,
                                      generation=gen, timed_out=True)
                self._record_crossing(gen, t0_ns, error=err)
                raise err
            time.sleep(self.poll)

    def _dead_among(self, ranks):
        """``[(rank, evidence), ...]`` for the subset of ``ranks`` with
        EVIDENCE of death: a stale existing heartbeat file (beats
        stopped), or no file for a peer this gate has seen before
        (crashed-and-cleaned or departed). A never-seen peer with no
        file is a slow joiner — startup skew under load is not death;
        the hard ``gate_timeout`` bounds how long we extend that
        benefit of the doubt. The evidence string (file age vs the
        directory clock) rides in the error: a false-positive report
        must be diagnosable from one log line."""
        alive, ages = _scan(self.root, self.timeout)
        with self._lock:
            self._seen |= alive
            seen = set(self._seen)
        dead = []
        for r in ranks:
            if int(r) in alive:
                continue
            age = ages.get(int(r))
            if age is not None:
                if age > self.timeout:
                    dead.append((int(r),
                                 "heartbeat file %.2fs stale (timeout "
                                 "%.2fs)" % (age, self.timeout)))
                # a fresh-but-not-alive age cannot happen from one
                # scan; kept for clarity: fresh means not dead
            elif int(r) in seen:
                dead.append((int(r), "heartbeat file removed after "
                                     "being seen alive"))
        return dead


def gate_stats():
    """Per-channel crossing stats over every live gate in this
    process — what the flight sampler folds into its series samples
    (``gate.<channel>.*`` keys) and what the fleet summary reads off a
    rank's dump. Two gates on one channel (a re-mesh in flight) merge
    by summing totals and keeping the most-recently-crossed gate's
    ``last_*`` verdicts."""
    with _gates_lock:
        gates = list(_gates)
    out = {}
    for g in gates:
        s = g.stats()
        if not s["crossings"]:
            continue
        cur = out.get(g.channel)
        if cur is None:
            out[g.channel] = s
        else:
            keep_last = s if s["crossings"] >= cur["crossings"] else cur
            merged = {
                "crossings": cur["crossings"] + s["crossings"],
                "wait_ms_total": (cur["wait_ms_total"]
                                  + s["wait_ms_total"]),
                "stragglers": cur["stragglers"] + s["stragglers"],
                "last_wait_ms": keep_last["last_wait_ms"],
                "last_rank": keep_last["last_rank"],
                "last_excess_ms": keep_last["last_excess_ms"],
            }
            out[g.channel] = merged
    return out
