"""Weight initializers.

Parity: reference ``python/mxnet/initializer.py`` (registry + Xavier/MSRA/
Uniform/Normal/Orthogonal/Bilinear/LSTMBias/Load/Mixed and the name-based
default rules for bias/gamma/beta/moving stats).
"""
from __future__ import annotations

import json
import re

import numpy as np

from .base import registry_create, MXNetError

__all__ = ["InitDesc", "Initializer", "Uniform", "Normal", "Zero", "One",
           "Constant", "Orthogonal", "Xavier", "MSRAPrelu", "Bilinear",
           "LSTMBias", "Load", "Mixed", "register"]

register, _alias, create, _get = registry_create("initializer")
init_registry = {"register": register, "create": create}


class InitDesc(str):
    """Name + attrs describing a parameter (parity: initializer.InitDesc)."""

    def __new__(cls, name, attrs=None, global_init=None):
        obj = super().__new__(cls, name)
        obj.attrs = attrs or {}
        obj.global_init = global_init
        return obj


class Initializer:
    """Base initializer; callable on (InitDesc/str, NDArray)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs
        self._verbose = False
        self._print_func = None

    def set_verbosity(self, verbose=False, print_func=None):
        self._verbose = verbose
        self._print_func = print_func
        return self

    def dumps(self):
        return json.dumps([self.__class__.__name__.lower(), self._kwargs])

    def __call__(self, desc, arr):
        if not isinstance(desc, str):
            raise TypeError("first argument must be a name string/InitDesc")
        if isinstance(desc, InitDesc) and desc.attrs.get("__init__"):
            spec = desc.attrs["__init__"]
            try:
                cls_name, kwargs = json.loads(spec)
            except (ValueError, TypeError):
                # plain registry name (e.g. Variable(init='zeros'))
                cls_name, kwargs = spec, {}
            create(cls_name, **kwargs)._init_weight(desc, arr)
            return
        # name-based dispatch (parity with reference rules)
        if desc.endswith("weight") or desc.endswith("parameters"):
            # fused RNN blobs ("*_parameters") initialise as weights —
            # the FusedRNN initializer unpacks them per gate
            self._init_weight(desc, arr)
        elif desc.endswith("bias"):
            self._init_bias(desc, arr)
        elif desc.endswith("gamma"):
            self._init_gamma(desc, arr)
        elif desc.endswith("beta"):
            self._init_beta(desc, arr)
        elif desc.endswith("moving_mean") or desc.endswith("running_mean"):
            self._init_zero(desc, arr)
        elif desc.endswith("moving_var") or desc.endswith("running_var"):
            self._init_one(desc, arr)
        elif desc.endswith("moving_inv_var") or desc.endswith("moving_avg"):
            self._init_zero(desc, arr)
        else:
            self._init_default(desc, arr)

    # -- fill helpers ------------------------------------------------------
    def _set(self, arr, value):
        arr[:] = value

    def _init_zero(self, _, arr):
        self._set(arr, np.zeros(arr.shape, dtype=np.float32))

    def _init_one(self, _, arr):
        self._set(arr, np.ones(arr.shape, dtype=np.float32))

    def _init_bias(self, _, arr):
        self._init_zero(_, arr)

    def _init_gamma(self, _, arr):
        self._init_one(_, arr)

    def _init_beta(self, _, arr):
        self._init_zero(_, arr)

    def _init_weight(self, name, arr):
        raise NotImplementedError

    def _init_default(self, name, arr):
        raise MXNetError(
            "Unknown initialization pattern for %s; name a parameter "
            "*_weight/*_bias/... or use a Mixed initializer" % name)


@register
class Uniform(Initializer):
    def __init__(self, scale=0.07):
        super().__init__(scale=scale)
        self.scale = scale

    def _init_weight(self, _, arr):
        self._set(arr, np.random.uniform(-self.scale, self.scale, arr.shape)
                  .astype(np.float32))


@register
class Normal(Initializer):
    def __init__(self, sigma=0.01):
        super().__init__(sigma=sigma)
        self.sigma = sigma

    def _init_weight(self, _, arr):
        self._set(arr, np.random.normal(0, self.sigma, arr.shape)
                  .astype(np.float32))


@register
class Zero(Initializer):
    def _init_weight(self, _, arr):
        self._init_zero(_, arr)


_alias("zeros", Zero)


@register
class One(Initializer):
    def _init_weight(self, _, arr):
        self._init_one(_, arr)


_alias("ones", One)


@register
class Constant(Initializer):
    def __init__(self, value=0.0):
        super().__init__(value=value)
        self.value = value

    def _init_weight(self, _, arr):
        self._set(arr, np.full(arr.shape, self.value, dtype=np.float32))


@register
class Orthogonal(Initializer):
    def __init__(self, scale=1.414, rand_type="uniform"):
        super().__init__(scale=scale, rand_type=rand_type)
        self.scale = scale
        self.rand_type = rand_type

    def _init_weight(self, _, arr):
        nout = arr.shape[0]
        nin = int(np.prod(arr.shape[1:]))
        if self.rand_type == "uniform":
            tmp = np.random.uniform(-1.0, 1.0, (nout, nin))
        else:
            tmp = np.random.normal(0.0, 1.0, (nout, nin))
        u, _, v = np.linalg.svd(tmp, full_matrices=False)
        q = u if u.shape == (nout, nin) else v
        self._set(arr, (self.scale * q).reshape(arr.shape).astype(np.float32))


@register
class Xavier(Initializer):
    """(parity: initializer.Xavier — the default for conv/FC nets)"""

    def __init__(self, rnd_type="uniform", factor_type="avg", magnitude=3):
        super().__init__(rnd_type=rnd_type, factor_type=factor_type,
                         magnitude=magnitude)
        self.rnd_type = rnd_type
        self.factor_type = factor_type
        self.magnitude = float(magnitude)

    def _init_weight(self, name, arr):
        shape = arr.shape
        hw_scale = float(np.prod(shape[2:])) if len(shape) > 2 else 1.0
        fan_in = shape[1] * hw_scale if len(shape) >= 2 else shape[0]
        fan_out = shape[0] * hw_scale
        if self.factor_type == "avg":
            factor = (fan_in + fan_out) / 2.0
        elif self.factor_type == "in":
            factor = fan_in
        elif self.factor_type == "out":
            factor = fan_out
        else:
            raise MXNetError("invalid factor_type %r" % self.factor_type)
        scale = np.sqrt(self.magnitude / factor)
        if self.rnd_type == "uniform":
            w = np.random.uniform(-scale, scale, shape)
        elif self.rnd_type == "gaussian":
            w = np.random.normal(0, scale, shape)
        else:
            raise MXNetError("invalid rnd_type %r" % self.rnd_type)
        self._set(arr, w.astype(np.float32))


@register
class MSRAPrelu(Xavier):
    def __init__(self, factor_type="avg", slope=0.25):
        magnitude = 2.0 / (1 + slope ** 2)
        super().__init__("gaussian", factor_type, magnitude)
        self._kwargs = {"factor_type": factor_type, "slope": slope}


@register
class Bilinear(Initializer):
    """Bilinear upsampling kernel (for UpSampling deconv weights)."""

    def _init_weight(self, _, arr):
        shape = arr.shape
        weight = np.zeros(int(np.prod(shape)), dtype=np.float32)
        f = np.ceil(shape[3] / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        for i in range(int(np.prod(shape))):
            x = i % shape[3]
            y = (i // shape[3]) % shape[2]
            weight[i] = (1 - abs(x / f - c)) * (1 - abs(y / f - c))
        self._set(arr, weight.reshape(shape))


@register
class LSTMBias(Initializer):
    """Forget-gate bias = forget_bias, others 0 (parity: LSTMBias)."""

    def __init__(self, forget_bias=1.0):
        super().__init__(forget_bias=forget_bias)
        self.forget_bias = forget_bias

    def _init_weight(self, name, arr):
        b = np.zeros(arr.shape, dtype=np.float32)
        num_hidden = arr.shape[0] // 4
        b[num_hidden:2 * num_hidden] = self.forget_bias  # i, f, c, o layout
        self._set(arr, b)

    _init_bias = _init_weight


@register
class Load(Initializer):
    """Init from a .params file or dict of arrays, fall back to
    default_init (parity: initializer.Load, which accepts both —
    reference initializer.py:303-306)."""

    def __init__(self, param, default_init=None, verbose=False):
        super().__init__()
        if isinstance(param, str):
            from .ndarray import load as _nd_load
            param = _nd_load(param)
        self.param = {k[4:] if k.startswith(("arg:", "aux:")) else k: v
                      for k, v in param.items()}
        self.default_init = default_init

    def __call__(self, name, arr):
        if name in self.param:
            if tuple(self.param[name].shape) != tuple(arr.shape):
                raise MXNetError("Load: shape mismatch for %s" % name)
            arr[:] = self.param[name].asnumpy() if hasattr(self.param[name],
                                                           "asnumpy") \
                else self.param[name]
        else:
            if self.default_init is None:
                raise MXNetError("Load: no init for %s" % name)
            self.default_init(name, arr)


@register
class Mixed(Initializer):
    """Regex-pattern dispatch to sub-initializers (parity: Mixed)."""

    def __init__(self, patterns, initializers):
        super().__init__()
        if len(patterns) != len(initializers):
            raise MXNetError("Mixed: patterns/initializers length mismatch")
        self.map = [(re.compile(p), init) for p, init in
                    zip(patterns, initializers)]

    def __call__(self, name, arr):
        for prog, init in self.map:
            if prog.match(name):
                init(name, arr)
                return
        raise MXNetError("Mixed: no pattern matches %r; add a '.*' catch-all"
                         % name)


@register
class FusedRNN(Initializer):
    """Initialize a fused RNN parameter blob by unpacking it, applying an
    inner initializer per unfused array, and repacking (parity:
    initializer.FusedRNN — including the LSTM forget-gate bias)."""

    def __init__(self, init, num_hidden, num_layers, mode,
                 bidirectional=False, forget_bias=1.0):
        if isinstance(init, str):
            klass, kwargs = json.loads(init)
            init = create(klass, **kwargs)
        super().__init__(init=init.dumps() if init is not None else None,
                         num_hidden=num_hidden, num_layers=num_layers,
                         mode=mode, bidirectional=bidirectional,
                         forget_bias=forget_bias)
        self._init = init
        self._num_hidden = num_hidden
        self._num_layers = num_layers
        self._mode = mode
        self._bidirectional = bidirectional
        self._forget_bias = forget_bias

    def _init_weight(self, desc, arr):
        from .rnn import rnn_cell
        cell = rnn_cell.FusedRNNCell(
            self._num_hidden, self._num_layers, self._mode,
            self._bidirectional, forget_bias=self._forget_bias, prefix="")
        args = cell.unpack_weights({"parameters": arr})
        for name in args:
            if self._mode == "lstm" and name.endswith("_f_bias"):
                args[name][:] = self._forget_bias
            elif self._init is not None:
                self._init(InitDesc(name), args[name])
        arr[:] = cell.pack_weights(args)["parameters"]
