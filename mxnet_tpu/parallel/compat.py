"""JAX version compatibility shims for the parallel package.

``shard_map`` has moved twice upstream: it started life as
``jax.experimental.shard_map.shard_map`` (with a ``check_rep`` kwarg),
and newer JAX releases promote it to ``jax.shard_map`` (renaming the
kwarg to ``check_vma``). Every per-device collective program in this
package (ring/ulysses attention, MoE dispatch, the GPipe schedule) uses
the ONE wrapper below so the call sites are written against the modern
``jax.shard_map`` surface and keep working on the older installed
jaxlib without per-module try/except drift.
"""
from __future__ import annotations

import jax

__all__ = ["shard_map"]


if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _experimental_shard_map

    def shard_map(f, mesh=None, in_specs=None, out_specs=None,
                  check_vma=True, **kwargs):
        """Modern ``jax.shard_map`` signature on the experimental
        implementation: ``check_vma`` maps onto the old ``check_rep``
        (same meaning — verify the per-device values claimed replicated
        really are)."""
        return _experimental_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_rep=check_vma, **kwargs)
