"""Ring attention: sequence-parallel exact attention over a device ring.

The long-context pillar (new-framework extension beyond the 2017
reference, which predates attention — SURVEY.md §5.7). Design follows the
blockwise/ring formulation (Liu et al., "Ring Attention with Blockwise
Transformers"): Q stays put, K/V blocks rotate around the 'sp' mesh axis
via ``ppermute`` while each device maintains an online-softmax
accumulator (running max m, denominator l, numerator o). Communication
is neighbour-to-neighbour so it rides ICI; compute of block t overlaps
the transfer of block t+1 (XLA schedules the ppermute async).

``attention`` is the single-chip reference implementation used for
correctness tests and as the local block kernel.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from .compat import shard_map

__all__ = ["attention", "decode_attention", "ring_attention",
           "PARTITION_RULES", "DECODE_PARTITION_RULES"]

# The ring layout as a partition-rule set: sequence parallelism shards
# ACTIVATIONS (q/k/v along S over ``sp``); the projection parameters
# feeding it stay fully replicated — each device runs the full
# projection on its sequence slice. An explicit everything-replicates
# rule (rather than relying on the UNMATCHED default) makes the layout
# a statement the error policy can enforce.
PARTITION_RULES = [
    (r".*", P()),
]

# The autoregressive-decode layout (mxnet_tpu/decode.py): the KV cache
# is just another rule-sharded leaf. Heads shard over ``mp`` — the
# ulysses head-major convention — so the (S, H, T, D) cache pool, the
# head-major q/k/v producers (E, H, D) and the head-major output
# consumer (H, D, E) all split on the same axis and single-token decode
# needs no resharding: each device attends its own heads and only the
# output projection's psum crosses ``mp``.
DECODE_PARTITION_RULES = [
    (r"cache/(k|v)$", P(None, "mp", None, None)),
    (r"w(q|k|v)$", P(None, "mp", None)),
    (r"wo$", P("mp", None, None)),
    (r".*", P()),
]


def attention(q, k, v, causal=False, scale=None, q_offset=0, kv_offset=0):
    """Plain scaled-dot-product attention, (B, H, S, D) layout.

    ``q_offset``/``kv_offset`` give the global sequence positions of the
    local blocks (used by ring attention's causal masking).
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        qpos = jnp.arange(q.shape[2]) + q_offset
        kpos = jnp.arange(k.shape[2]) + kv_offset
        mask = qpos[:, None] >= kpos[None, :]
        scores = jnp.where(mask, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # rows that are fully masked produce NaN from softmax(-inf); zero them
    if causal:
        probs = jnp.where(jnp.isfinite(scores).any(-1, keepdims=True), probs,
                          0.0)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def decode_attention(q, k_cache, v_cache, length, scale=None):
    """Single-query attention against a KV cache: one sequence, one new
    token. ``q`` is (H, D); ``k_cache``/``v_cache`` are (H, T, D) with
    positions ``[0, length)`` valid (the current token's k/v already
    written at ``length - 1``); everything at or past ``length`` is
    masked out. Returns (H, D). The decode engine vmaps this over the
    gathered active slots.
    """
    d = q.shape[-1]
    scale = scale if scale is not None else 1.0 / math.sqrt(d)
    scores = jnp.einsum("hd,htd->ht", q, k_cache) * scale
    pos = jnp.arange(k_cache.shape[1])
    scores = jnp.where(pos[None, :] < length, scores, -jnp.inf)
    probs = jax.nn.softmax(scores, axis=-1)
    # a fully masked row (length == 0, never live in practice) would
    # produce NaN from softmax(-inf); zero it like ``attention`` does
    probs = jnp.where(jnp.isfinite(scores).any(-1, keepdims=True), probs,
                      0.0)
    return jnp.einsum("ht,htd->hd", probs, v_cache)


def _ring_attention_local(q, k, v, axis_name, causal, scale, use_pallas):
    """Per-device body under shard_map: q/k/v are the local sequence blocks
    (B, H, S_local, D)."""
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    q_pos = my * S + jnp.arange(S)                      # global q positions

    perm = [(i, (i + 1) % n) for i in range(n)]

    if use_pallas:
        return _ring_flash_local(q, k, v, axis_name, causal, scale)

    def step(t, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - t) % n                              # owner of this block
        k_pos = src * S + jnp.arange(S)
        scores = jnp.einsum("bhqd,bhkd->bhqk", q, k_blk) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            scores = jnp.where(mask, scores, -1e30)
        blk_max = jnp.max(scores, axis=-1)              # (B,H,S)
        new_m = jnp.maximum(m, blk_max)
        correction = jnp.exp(m - new_m)
        p = jnp.exp(scores - new_m[..., None])
        l_new = l * correction + jnp.sum(p, axis=-1)
        o_new = o * correction[..., None] + \
            jnp.einsum("bhqk,bhkd->bhqd", p, v_blk)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o_new, new_m, l_new, k_next, v_next)

    # derive accumulators from q so they carry the same shard_map
    # device-varying type as the loop outputs
    o0 = jnp.zeros_like(q)
    m0 = jnp.full_like(q[..., 0], -1e30)
    l0 = jnp.zeros_like(q[..., 0])
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    return o / jnp.maximum(l, 1e-30)[..., None]


def _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale):
    """Pallas-kernel ring forward. Returns (out, lse) with lse (BH, S) —
    the residual the ring backward needs."""
    from ..pallas import flash_attention_carry
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]
    qf = q.reshape(B * H, S, D)

    def step(t, carry):
        o, m, l, k_blk, v_blk = carry
        src = (my - t) % n                              # owner of this block
        o, m, l = flash_attention_carry(
            qf, k_blk.reshape(B * H, S, D), v_blk.reshape(B * H, S, D),
            o, m, l, q_offset=my * S, kv_offset=src * S,
            causal=causal, scale=scale)
        k_next = lax.ppermute(k_blk, axis_name, perm)
        v_next = lax.ppermute(v_blk, axis_name, perm)
        return (o, m, l, k_next, v_next)

    o0 = jnp.zeros((B * H, S, D), jnp.float32)
    m0 = jnp.full((B * H, S), -1e30, jnp.float32)
    l0 = jnp.zeros((B * H, S), jnp.float32)
    o, m, l, _, _ = lax.fori_loop(0, n, step, (o0, m0, l0, k, v))
    l = jnp.maximum(l, 1e-30)
    out = (o / l[..., None]).astype(q.dtype).reshape(B, H, S, D)
    lse = m + jnp.log(l)
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _ring_flash_local(q, k, v, axis_name, causal, scale):
    out, _ = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    return out


def _ring_flash_fwd(q, k, v, axis_name, causal, scale):
    out, lse = _ring_flash_fwd_impl(q, k, v, axis_name, causal, scale)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis_name, causal, scale, res, g):
    """Ring backward: K/V rotate again, and the dK/dV accumulators travel
    WITH their blocks so each returns home after n hops carrying every
    device's contribution."""
    q, k, v, out, lse = res
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)
    B, H, S, D = q.shape
    scale = scale if scale is not None else 1.0 / math.sqrt(D)
    perm = [(i, (i + 1) % n) for i in range(n)]

    BH = B * H
    qf = q.reshape(BH, S, D).astype(jnp.float32)
    gf = g.reshape(BH, S, D).astype(jnp.float32)
    of = out.reshape(BH, S, D).astype(jnp.float32)
    delta = jnp.sum(gf * of, axis=-1)                   # (BH, S)
    q_pos = my * S + jnp.arange(S)

    def step(t, carry):
        dq, dk, dv, k_blk, v_blk = carry
        src = (my - t) % n
        kf = k_blk.reshape(BH, S, D).astype(jnp.float32)
        vf = v_blk.reshape(BH, S, D).astype(jnp.float32)
        k_pos = src * S + jnp.arange(S)
        s = jnp.einsum("bqd,bkd->bqk", qf, kf) * scale
        if causal:
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, -1e30)
        p = jnp.exp(s - lse[..., None])                 # (BH, S, S)
        dp = jnp.einsum("bqd,bkd->bqk", gf, vf)
        ds = p * (dp - delta[..., None]) * scale
        dq = dq + jnp.einsum("bqk,bkd->bqd", ds, kf)
        dk_c = jnp.einsum("bqk,bqd->bkd", ds, qf).reshape(B, H, S, D)
        dv_c = jnp.einsum("bqk,bqd->bkd", p, gf).reshape(B, H, S, D)
        dk, dv = dk + dk_c, dv + dv_c
        # rotate block + its accumulated grad together
        k_blk = lax.ppermute(k_blk, axis_name, perm)
        v_blk = lax.ppermute(v_blk, axis_name, perm)
        dk = lax.ppermute(dk, axis_name, perm)
        dv = lax.ppermute(dv, axis_name, perm)
        return (dq, dk, dv, k_blk, v_blk)

    dq0 = jnp.zeros((BH, S, D), jnp.float32)
    z0 = jnp.zeros((B, H, S, D), jnp.float32)
    dq, dk, dv, _, _ = lax.fori_loop(0, n, step, (dq0, z0, z0, k, v))
    return (dq.reshape(B, H, S, D).astype(q.dtype), dk.astype(k.dtype),
            dv.astype(v.dtype))


_ring_flash_local.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_attention(q, k, v, mesh, axis_name="sp", batch_axis_name=None,
                   causal=False, scale=None, use_pallas=None):
    """Sequence-parallel attention: q/k/v (B, H, S, D) sharded along S over
    ``axis_name`` (and optionally along B over ``batch_axis_name``).
    Returns the attention output with the same sharding.

    Accepts NDArrays or jax arrays; runs under shard_map on ``mesh``.
    ``use_pallas`` selects the Pallas flash kernel for the local block
    compute (default: on real TPU backends only — interpret mode inside a
    shard_map loop is needlessly slow on the CPU test mesh).
    """
    from ..ndarray.ndarray import NDArray, _wrap
    wrap_out = isinstance(q, NDArray)
    raw = [x._data if isinstance(x, NDArray) else x for x in (q, k, v)]

    spec = P(batch_axis_name, None, axis_name, None)
    # inputs committed to one device (NDArrays) must be laid out over the
    # mesh before shard_map will accept them
    raw = [jax.device_put(x, NamedSharding(mesh, spec)) for x in raw]

    def build(flag):
        return shard_map(
            functools.partial(_ring_attention_local, axis_name=axis_name,
                              causal=causal, scale=scale, use_pallas=flag),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            # pallas_call outputs carry no varying-mesh-axes annotation
            check_vma=not flag)

    if use_pallas is None:
        if jax.default_backend() != "tpu":
            use_pallas = False
        else:
            # operator tuner decides pallas-vs-XLA per signature: the
            # flash kernel wins at long local blocks, plain XLA at short
            # ones where the grid overhead dominates (tuner.py ≙
            # reference operator_tune.h)
            from ..tuner import tuned_choice

            def mk(flag):
                def thunk():
                    return build(flag)(*[jnp.zeros_like(x) for x in raw])
                return thunk

            key = "q%s_kv%d_%s_c%d_sp%d" % (
                "x".join(map(str, raw[0].shape)), raw[1].shape[2],
                raw[0].dtype.name, int(causal),
                dict(zip(mesh.axis_names, mesh.devices.shape))[axis_name])
            label = tuned_choice("ring_attention.impl", key,
                                 [("pallas", mk(True)), ("xla", mk(False))],
                                 args=raw)
            use_pallas = label == "pallas"

    out = build(use_pallas)(*raw)
    return _wrap(out) if wrap_out else out
