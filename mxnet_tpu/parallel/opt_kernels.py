"""Pure optimizer kernels for the SPMD sharded train step.

Each kernel is (init_fn, update_fn):
  init_fn(param) -> state tuple of arrays (possibly empty)
  update_fn(param, grad, state, t, hyper) -> (new_param, new_state)
with ``t`` the 1-based update count and ``hyper`` a dict of (traced)
scalars. The update math reuses the fused update ops
(ops/optimizer_ops.py — parity with reference optimizer_op.cc:39-299),
so the eager `mx.optimizer` classes and the jitted SPMD path share one
implementation of each rule. All state arrays are created with
``zeros_like`` so GSPMD gives them the parameter's sharding.
"""
from __future__ import annotations

import jax.numpy as jnp

from ..base import MXNetError
from ..ops import optimizer_ops as _O

__all__ = ["get_kernel", "hyper_from_optimizer"]


def _clip(g, c):
    return jnp.clip(g, -c, c) if (c is not None and c > 0) else g


def _sgd_init(p):
    return (jnp.zeros_like(p),)


def _sgd_update(p, g, s, t, h):
    if h.get("momentum_static", 0.0):
        w, m = _O.sgd_mom_update(p, g, s[0], lr=h["lr"],
                                 momentum=h["momentum"], wd=h["wd"],
                                 rescale_grad=h["rescale_grad"],
                                 clip_gradient=h["clip_gradient"])
        return w, (m,)
    w = _O.sgd_update(p, g, lr=h["lr"], wd=h["wd"],
                      rescale_grad=h["rescale_grad"],
                      clip_gradient=h["clip_gradient"])
    return w, s


def _nag_update(p, g, s, t, h):
    # Nesterov momentum (reference optimizer.py NAG.update_impl)
    grad = _clip(g * h["rescale_grad"], h["clip_gradient"]) + h["wd"] * p
    m = h["momentum"] * s[0] + grad
    w = p - h["lr"] * (grad + h["momentum"] * m)
    return w, (m,)


def _adam_init(p):
    return (jnp.zeros_like(p), jnp.zeros_like(p))


def _adam_update(p, g, s, t, h):
    # bias-corrected lr, as mx.optimizer.Adam folds into lr before the
    # fused op (reference optimizer.py Adam.update)
    coef1 = 1.0 - h["beta1"] ** t
    coef2 = 1.0 - h["beta2"] ** t
    lr_t = h["lr"] * jnp.sqrt(coef2) / coef1
    w, mean, var = _O.adam_update(
        p, g, s[0], s[1], lr=lr_t, beta1=h["beta1"], beta2=h["beta2"],
        epsilon=h["epsilon"], wd=h["wd"], rescale_grad=h["rescale_grad"],
        clip_gradient=h["clip_gradient"])
    return w, (mean, var)


def _rmsprop_update(p, g, s, t, h):
    w, n = _O.rmsprop_update(p, g, s[0], lr=h["lr"], gamma1=h["gamma1"],
                             epsilon=h["epsilon"], wd=h["wd"],
                             rescale_grad=h["rescale_grad"],
                             clip_gradient=h["clip_gradient"])
    return w, (n,)


def _adagrad_init(p):
    return (jnp.zeros_like(p),)


def _adagrad_update(p, g, s, t, h):
    # reference optimizer.py AdaGrad:763-805 dense branch: wd stays OUT
    # of the history accumulator; eps inside the sqrt
    grad = _clip(g * h["rescale_grad"], h["clip_gradient"])
    hist = s[0] + jnp.square(grad)
    w = p - h["lr"] * (grad / jnp.sqrt(hist + h["epsilon"]) + h["wd"] * p)
    return w, (hist,)


def _adadelta_update(p, g, s, t, h):
    # reference optimizer.py AdaDelta: wd applies to the weight directly,
    # not through the accumulators
    grad = _clip(g * h["rescale_grad"], h["clip_gradient"])
    acc_g = h["rho"] * s[0] + (1.0 - h["rho"]) * jnp.square(grad)
    delta = jnp.sqrt((s[1] + h["epsilon"]) / (acc_g + h["epsilon"])) * grad
    acc_d = h["rho"] * s[1] + (1.0 - h["rho"]) * jnp.square(delta)
    return p - delta - h["wd"] * p, (acc_g, acc_d)


def _ftrl_update(p, g, s, t, h):
    w, z, n = _O.ftrl_update(p, g, s[0], s[1], lr=h["lr"],
                             lamda1=h["lamda1"], beta=h["beta"], wd=h["wd"],
                             rescale_grad=h["rescale_grad"],
                             clip_gradient=h["clip_gradient"])
    return w, (z, n)


_KERNELS = {
    "sgd": (_sgd_init, _sgd_update),
    "nag": (_sgd_init, _nag_update),
    "adam": (_adam_init, _adam_update),
    "rmsprop": (_sgd_init, _rmsprop_update),
    "adagrad": (_adagrad_init, _adagrad_update),
    "adadelta": (_adam_init, _adadelta_update),
    "ftrl": (_adam_init, _ftrl_update),
}


def get_kernel(name):
    name = name.lower()
    if name not in _KERNELS:
        raise MXNetError(
            "no SPMD kernel for optimizer %r (have: %s)"
            % (name, ", ".join(sorted(_KERNELS))))
    return _KERNELS[name]


_COMMON = ("lr", "wd", "rescale_grad", "clip_gradient")


def hyper_from_optimizer(optimizer):
    """(kernel_name, hyper dict) from an mx.optimizer.Optimizer instance."""
    from .. import optimizer as opt
    h = {
        "lr": float(optimizer._get_lr(0)),
        "wd": float(optimizer._get_wd(0)),
        "rescale_grad": float(optimizer.rescale_grad),
        "clip_gradient": float(optimizer.clip_gradient
                               if optimizer.clip_gradient is not None
                               else -1.0),
    }
    if isinstance(optimizer, opt.NAG):
        h["momentum"] = float(optimizer.momentum)
        return "nag", h
    if isinstance(optimizer, opt.SGD):
        h["momentum"] = float(optimizer.momentum)
        h["momentum_static"] = float(optimizer.momentum)
        return "sgd", h
    if isinstance(optimizer, opt.Adam):
        h.update(beta1=float(optimizer.beta1), beta2=float(optimizer.beta2),
                 epsilon=float(optimizer.epsilon))
        return "adam", h
    if isinstance(optimizer, opt.RMSProp):
        h.update(gamma1=float(optimizer.gamma1),
                 epsilon=float(optimizer.epsilon))
        return "rmsprop", h
    if isinstance(optimizer, opt.AdaGrad):
        h.update(epsilon=float(optimizer.float_stable_eps
                               if hasattr(optimizer, "float_stable_eps")
                               else getattr(optimizer, "epsilon", 1e-7)))
        return "adagrad", h
    if isinstance(optimizer, opt.AdaDelta):
        h.update(rho=float(optimizer.rho), epsilon=float(optimizer.epsilon))
        return "adadelta", h
    if isinstance(optimizer, opt.Ftrl):
        h.update(lamda1=float(optimizer.lamda1), beta=float(optimizer.beta))
        return "ftrl", h
    raise MXNetError("no SPMD kernel mapping for optimizer %s"
                     % type(optimizer).__name__)
