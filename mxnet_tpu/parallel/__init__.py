"""Distributed execution over TPU meshes.

This package is the TPU-native replacement for the reference's entire
distributed stack (SURVEY.md §5.8): KVStore comm trees, NCCL, and the
ps-lite parameter server all become sharding annotations on ONE compiled
program — XLA GSPMD inserts the ICI/DCN collectives (psum/all_gather/
reduce_scatter) where the shardings require them.

Components:
- mesh:        device-mesh construction helpers
- collectives: named wrappers over XLA collectives (the "comm backend")
- spmd:        sharded train-step compiler (dp/tp batch+param sharding)
- ring_attention: sequence-parallel blockwise attention over ppermute
"""
from .compat import shard_map
from .mesh import make_mesh, default_mesh, mesh_from_contexts, barrier
from .collectives import (all_reduce, all_gather, reduce_scatter, ppermute,
                          all_to_all)
from .spmd import (SPMDTrainer, shard_params_rule, DataParallelSpec,
                   dp_spec, rule_spec, check_batch_divisible, shard_put,
                   DP_AXIS, MP_AXIS)
from .partition import (PartitionRules, UNMATCHED_REPLICATE,
                        UNMATCHED_ERROR, partition_summary)
from .ring_attention import ring_attention, attention
from .ulysses import ulysses_attention
from .moe import moe_ffn
from .pipeline import pipeline_apply
